import os, sys, json
os.environ['BENCH_CHILD'] = 'tpu'
sys.argv = ['bench.py']
import bench
r = bench._bench_stacked_lstm(32, 128, 10, 2)
print(json.dumps(r))
