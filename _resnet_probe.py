"""Isolate resnet slowness: time fwd-only vs train, raw-jax NHWC vs NCHW conv."""
import time
import numpy as np, jax, jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]; print(dev.platform, dev.device_kind)
B = 64

def timeit(name, f, *a, iters=5):
    out = f(*a); jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters): out = f(*a)
    # force real sync through the relay with a scalar pull
    s = float(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32))
    dt = (time.time()-t0)/iters
    print("%s: %.1f ms" % (name, dt*1e3))
    return dt

# raw conv stack bf16, NCHW vs NHWC: 10 convs 3x3 c256 on 56x56
x_nchw = jnp.asarray(np.random.randn(B,256,56,56).astype('float32')).astype(jnp.bfloat16)
w = jnp.asarray(np.random.randn(256,256,3,3).astype('float32')).astype(jnp.bfloat16)
@jax.jit
def conv_nchw(x, w):
    for _ in range(10):
        x = lax.conv_general_dilated(x, w, (1,1), [(1,1),(1,1)],
                                     dimension_numbers=('NCHW','OIHW','NCHW'))
    return x
timeit('10x conv NCHW bf16', conv_nchw, x_nchw, w)

x_nhwc = jnp.asarray(np.random.randn(B,56,56,256).astype('float32')).astype(jnp.bfloat16)
w2 = jnp.asarray(np.random.randn(3,3,256,256).astype('float32')).astype(jnp.bfloat16)
@jax.jit
def conv_nhwc(x, w):
    for _ in range(10):
        x = lax.conv_general_dilated(x, w, (1,1), [(1,1),(1,1)],
                                     dimension_numbers=('NHWC','HWIO','NHWC'))
    return x
timeit('10x conv NHWC bf16', conv_nhwc, x_nhwc, w2)
# flops: 10 * 2*B*56*56*256*256*9 = 
fl = 10*2*B*56*56*256*256*9
print("flops per call: %.1f G" % (fl/1e9))
