"""Benchmark suite: training throughput on one chip, multiple models.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.
The headline metric stays the flagship Transformer-LM (so vs_baseline is
comparable across rounds); additional model rows (larger LM, ResNet-50,
CTR sparse-embedding) ride in the "models" extra — the bench-suite shape
of the reference (benchmark/fluid/fluid_benchmark.py: mnist/resnet/...
with examples/sec = num_samples / elapsed, :297-301).

Measurement contract (round-3 redesign):
- steady state is measured with Executor.run_fused — K steps scanned
  on-device per call over pre-staged DISTINCT batches — because the chip
  sits behind a network tunnel whose per-launch latency (~1s) and
  device->host fetch (~0.5s) would otherwise dominate; round 2's
  per-step-fetch loop under-measured the machine by ~3x for exactly this
  reason (BENCH_r02 95.5k tok/s vs 275k+ measured fused on the same model).
- compile/warmup time is reported separately (compile_s), never mixed into
  throughput; the one trailing sync per measurement is included in the
  timed window and its standalone cost reported as sync_ms.
- a JSON line is ALWAYS emitted: the measurement runs in a child process
  with a timeout; TPU failure falls back to a labeled CPU run.
- every row must end its FIRST pass at a NON-DEGENERATE loss (VERDICT r4
  weak #3): labels come from a fixed random TEACHER function of the
  inputs (learnable structure, not memorizable noise), sequence/CTR rows
  stage one DISTINCT batch per step, image rows train at lr 0.02 (0.005
  for resnet50, which fits the teacher fastest), and
  final_loss is taken from the first (compile) pass — the timing rounds
  that follow re-train over the same staged stream, so any loss taken
  after them measures memorization of the stage. Long-run convergence
  evidence lives in BASELINE.md (2000-step LM + the round-5 conv/CTR
  appendix, fresh data every window).
"""
import glob
import json
import os
import re
import subprocess
import sys
import time

TPU_TIMEOUT_S = 2400          # compile times under chip contention vary 5x
CPU_TIMEOUT_S = 900
TPU_MODEL_BUDGET_S = 1700     # leave headroom for JSON emission

# committed flagship-LM training-throughput baseline for the goodput
# sentinel (like tools/servebench.py SERVING_ROW_BASELINE): a reading
# below baseline * PADDLE_PERFWATCH_ROW_DRIFT trips bench_row_drift
TRAIN_ROW_BASELINE = {'cpu': 12167.0, 'source': 'BENCH_r09'}

def _peak_for(kind):
    # one source of truth for the per-chip peak table: the goodput layer
    # (paddle_tpu/goodput.py PEAK_FLOPS) — the live step_mfu gauge and
    # this offline column must divide by the SAME denominator
    from paddle_tpu.goodput import peak_flops_for
    return peak_flops_for(kind)


def _lm_train_flops_per_step(cfg, batch):
    """Model FLOPs of one train step (fwd matmuls+attention, x3 for bwd)."""
    B, L, d, V, dff = batch, cfg.seq_len, cfg.d_model, cfg.vocab_size, cfg.d_ff
    per_layer = (2 * B * L * d * 3 * d       # qkv proj
                 + 2 * B * L * L * d         # scores
                 + 2 * B * L * L * d         # context
                 + 2 * B * L * d * d         # out proj
                 + 2 * B * L * d * dff * 2)  # ffn1 + ffn2
    fwd = cfg.n_layer * per_layer + 2 * B * L * d * V  # + lm head
    return 3 * fwd


def _measure_steps(exe, program, scope, batches, loss_var, k_per_call,
                   rounds, steps=None):
    """Steady-state timing: `rounds` fused calls of k_per_call steps each
    over distinct batches pre-staged ON DEVICE (what a prefetching input
    pipeline provides — upload is not part of step time, exactly like the
    reference's reader threads double-buffering to the GPU,
    operators/reader/buffered_reader.h:30); returns (sec_per_step,
    last_loss, compile_s)."""
    import numpy as np
    import jax
    if any(isinstance(v, tuple) for b in batches for v in b.values()):
        # LoD feeds can't pre-stack on device; run_fused stages them
        # (identical-LoD contract) — feeds are small for ragged models
        stacked = batches
    else:
        stacked = {name: jax.device_put(
            np.stack([np.asarray(b[name]) for b in batches]))
            for name in batches[0]}
        jax.block_until_ready(stacked)
    steps = steps or k_per_call
    t0 = time.time()
    out = exe.run_fused(program, stacked, fetch_list=[loss_var],
                        scope=scope, return_numpy=True,
                        steps=steps)                     # compile + sync
    compile_s = time.time() - t0
    # the reported loss comes from THIS first pass over the staged stream
    # — the timing rounds below re-train over the same staged batches, so
    # their loss measures memorization of the stage, not learning
    loss = float(np.asarray(out[0]).reshape(-1)[0])
    # each round is timed separately (call + its own sync); the BEST round
    # is reported — the chip may be time-shared with other tenants, and the
    # fastest window estimates the uncontended machine. The goodput layer
    # accounts the SAME rounds live: per-round (device-busy, flops)
    # deltas give the live MFU of the best window — the cross-check
    # column against this file's offline formula.
    from paddle_tpu import goodput as _goodput
    from paddle_tpu import analysis as _analysis
    # warm the one-time XLA cost analysis BEFORE the measured window so
    # the first round's stats() read doesn't pay it inside the wall
    _analysis.lookup(program, kind='fused')
    _goodput.reset()
    best = float('inf')
    best_rate = 0.0
    prev = _goodput.stats()
    for r in range(rounds):
        t0 = time.time()
        last = exe.run_fused(program, stacked, fetch_list=[loss_var],
                             scope=scope, return_numpy=False, steps=steps)
        float(np.asarray(last[0]).reshape(-1)[0])        # sync
        best = min(best, time.time() - t0)
        cur = _goodput.stats()
        d_busy = cur['productive_s'] - prev['productive_s']
        d_flops = cur['flops'] - prev['flops']
        prev = cur
        if d_busy > 0:
            best_rate = max(best_rate, d_flops / d_busy)
    final = _goodput.stats()
    peak, _bw = _goodput.device_peaks()
    gp_cols = {
        'goodput_frac': round(final['goodput_frac'], 4),
        'live_flops_per_s': round(best_rate, 1),
        'live_mfu': round(best_rate / peak, 4) if peak else None,
    }
    return best / steps, loss, compile_s, gp_cols


def _program_cost_row(program, memory=False):
    """XLA analytics columns for one bench row: per-STEP flops / bytes
    accessed from the registered executable, plus buffer-assignment peak
    bytes when `memory` (costs one extra XLA compile — CPU rows only;
    TPU compiles are minutes). XLA's HloCostAnalysis counts a while-loop
    BODY once regardless of trip count (measured: identical flops for a
    4-step and an 8-step fused scan of the same program), so the
    registered flops are ALREADY per step — rows before r08 divided by
    the scan length again and under-reported these columns by k x."""
    try:
        from paddle_tpu import analysis
        rec = analysis.lookup(program, memory=memory)
        if rec is None:
            return {}
        out = {}
        if rec.flops is not None:
            out['flops'] = rec.flops
            out['bytes_accessed'] = rec.bytes_accessed
        if rec.peak_bytes is not None:
            out['peak_bytes'] = rec.peak_bytes
        return out
    except Exception as e:  # noqa: BLE001 — advisory columns only
        return {'analytics_error': '%s: %s' % (type(e).__name__,
                                               str(e)[:120])}


def _bench_lm(cfg_kwargs, batch, k_per_call, rounds, amp,
              steps_per_call=None):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.contrib import mixed_precision as mp
    from paddle_tpu.models.transformer import build_lm, LMConfig

    cfg = LMConfig(**cfg_kwargs)
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        tokens, labels, logits, avg_loss = build_lm(cfg)
        # fuse=True: one fused_adam unit over the whole parameter set
        # (kernel tier applies per PADDLE_FUSED_TIER; 'off' is bitwise
        # per-param adam, so the row is comparable across tiers)
        opt = fluid.optimizer.Adam(learning_rate=1e-4, fuse=True)
        if amp:
            opt = mp.decorate(opt)
        opt.minimize(avg_loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    batches = [{'tokens': rng.randint(0, cfg.vocab_size,
                                      (batch, cfg.seq_len)).astype('int64'),
                'labels': rng.randint(0, cfg.vocab_size,
                                      (batch, cfg.seq_len)).astype('int64')}
               for _ in range(k_per_call)]
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        sec_step, loss, compile_s, gp_cols = _measure_steps(
            exe, main_p, scope, batches, avg_loss, k_per_call, rounds,
            steps=steps_per_call or max(120, k_per_call))
    row = {
        'tokens_per_sec': round(batch * cfg.seq_len / sec_step, 1),
        'step_ms': round(sec_step * 1000, 2),
        'compile_s': round(compile_s, 1),
        'final_loss': round(loss, 4),
        'flops_per_step': _lm_train_flops_per_step(cfg, batch),
        'config': 'L%d d%d ff%d V%d seq%d b%d' % (
            cfg.n_layer, cfg.d_model, cfg.d_ff, cfg.vocab_size,
            cfg.seq_len, batch),
    }
    row.update(_program_cost_row(main_p))
    row.update(gp_cols)
    return row


def _bench_image_model(build_fn, label_str, batch, k_per_call, rounds,
                       amp, img_shape=(3, 224, 224), n_class=1000,
                       dataset='imagenet', lr=0.02):
    """Shared image-model measurement (resnet50 / se_resnext / vgg rows):
    Momentum + keep-bf16-activations AMP (+13% images/sec measured on
    v5e), 24+-step fused windows."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.contrib import mixed_precision as mp

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        img, label, pred, avg_cost, acc = build_fn()
        # low lr (not the reference harness's 0.1): with 4 staged batches
        # a 240-step window at 0.1 memorizes to ~0 loss, which proves
        # nothing about training dynamics; resnet50 fits the teacher fast
        # enough to need 0.005
        opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        if amp:
            opt = mp.decorate(opt, keep_bf16_activations=True)
        opt.minimize(avg_cost)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # teacher labels: class = argmax of a fixed random projection of the
    # 8x8-downsampled image — learnable structure rather than pure noise
    c, h, w = img_shape
    pool = (h % 8 == 0 and w % 8 == 0)   # exact 8x8 pooling when possible

    def _features(imgs):
        if pool:
            imgs = imgs.reshape(imgs.shape[0], c, 8, h // 8, 8, w // 8) \
                .mean(axis=(3, 5))
        return imgs.reshape(imgs.shape[0], -1)

    feat_dim = _features(np.zeros((1,) + tuple(img_shape),
                                  'float32')).shape[1]
    teacher = rng.randn(feat_dim, n_class).astype('float32')

    def _teacher_label(imgs):
        return np.argmax(_features(imgs) @ teacher, 1) \
            .astype('int64').reshape(-1, 1)

    batches = []
    for _ in range(k_per_call):
        imgs = rng.randn(batch, *img_shape).astype('float32')
        batches.append({'img': imgs, 'label': _teacher_label(imgs)})
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        sec_step, loss, compile_s, gp_cols = _measure_steps(
            exe, main_p, scope, batches, avg_cost, k_per_call, rounds,
            steps=max(240, k_per_call))
    row = {
        'images_per_sec': round(batch / sec_step, 1),
        'step_ms': round(sec_step * 1000, 2),
        'compile_s': round(compile_s, 1),
        'final_loss': round(loss, 4),
        'config': '%s %s b%d' % (label_str, dataset, batch),
    }
    row.update(_program_cost_row(main_p))
    row.update(gp_cols)
    return row


def _bench_resnet50(batch, k_per_call, rounds, amp):
    from paddle_tpu.models.resnet import build as build_resnet
    return _bench_image_model(
        lambda: build_resnet('imagenet', depth=50), 'resnet50', batch,
        k_per_call, rounds, amp, lr=0.005)


def _bench_bert(batch, k_per_call, rounds, amp):
    """BERT-base pretraining samples/sec (BASELINE.md north-star row)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.contrib import mixed_precision as mp
    from paddle_tpu.models.bert import (BertConfig, build_bert_pretrain,
                                        make_pretrain_batch)

    cfg = BertConfig(seq_len=128, max_predictions=20)   # BERT-base
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        total, mlm_loss, nsp_loss = build_bert_pretrain(cfg)
        opt = fluid.optimizer.Adam(learning_rate=1e-4, fuse=True)
        if amp:
            opt = mp.decorate(opt)
        opt.minimize(total)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    batches = [make_pretrain_batch(cfg, batch, rng)
               for _ in range(k_per_call)]
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        sec_step, loss, compile_s, gp_cols = _measure_steps(
            exe, main_p, scope, batches, total, k_per_call, rounds,
            steps=max(120, k_per_call))
    # model FLOPs: encoder matmuls+attention (x3 for bwd) + MLM head over
    # the P masked positions + NSP head
    B, L, d, V, dff = batch, cfg.seq_len, cfg.d_model, cfg.vocab_size, \
        cfg.d_ff
    per_layer = (2 * B * L * d * 3 * d + 2 * B * L * L * d * 2
                 + 2 * B * L * d * d + 2 * B * L * d * dff * 2)
    fwd = cfg.n_layer * per_layer \
        + 2 * B * cfg.max_predictions * d * V \
        + 2 * B * d * d + 2 * B * L * d * d   # mlm transform + pooler-ish
    row = {
        'samples_per_sec': round(batch / sec_step, 1),
        'step_ms': round(sec_step * 1000, 2),
        'compile_s': round(compile_s, 1),
        'final_loss': round(loss, 4),
        'flops_per_step': 3 * fwd,
        'config': 'bert-base L%d d%d seq%d b%d' % (
            cfg.n_layer, cfg.d_model, cfg.seq_len, batch),
    }
    row.update(gp_cols)
    return row


def _bench_stacked_lstm(batch, seq_len, k_per_call, rounds):
    """Stacked dynamic-LSTM sentiment model over ragged (LoD) input — the
    reference benchmark/fluid/models/stacked_dynamic_lstm.py row; exercises
    the static-LoD ragged pipeline + lax.scan recurrences.

    A realistic stream is MIXED-length, and run_fused binds one LoD per
    compiled window (VERDICT r4 weak #5), so this row measures a
    bucketed stream the way reader/bucketing.py serves one:
    BUCKET-MAJOR — three bucket shapes (seq/2, 3seq/4, seq) measured as
    separate fused windows, each its own compile, with the reported rate
    = total samples / total time blended across buckets. (Interleaved
    mixed-LoD lists are also supported by run_fused itself via
    consecutive-segment splitting, with trajectory parity — see
    tests/test_run_fused.py — but bucket-major is how a throughput
    pipeline would actually serve the stream.)"""
    import numpy as np
    import paddle_tpu as fluid

    vocab, emb_dim, hid = 5000, 128, 128
    layers_n = 3
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        words = fluid.layers.data(name='words', shape=[1], dtype='int64',
                                  lod_level=1)
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(words, size=[vocab, emb_dim])
        h = emb
        for _ in range(layers_n):
            proj = fluid.layers.fc(h, size=hid * 4)
            h, _ = fluid.layers.dynamic_lstm(input=proj, size=hid * 4)
        last = fluid.layers.sequence_last_step(h)
        pred = fluid.layers.fc(last, size=2, act='softmax')
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    tok_score = rng.randn(vocab).astype('float32')
    n_steps = max(30, k_per_call)
    buckets = sorted({seq_len // 2, 3 * seq_len // 4, seq_len})

    def make_batches(sl):
        lod = [list(range(0, (batch + 1) * sl, sl))]
        out = []
        for _ in range(n_steps):
            w = rng.randint(0, vocab, (batch * sl, 1)).astype('int64')
            sent = (tok_score[w.reshape(batch, sl)].mean(1) > 0)
            out.append({'words': (w, lod),
                        'label': sent.astype('int64').reshape(-1, 1)})
        return out

    per_bucket = {}
    total_time = total_samples = total_tokens = 0.0
    compile_total = 0.0
    lossv = None
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        for sl in buckets:
            sec_step, lossv, compile_s, gp_cols = _measure_steps(
                exe, main_p, scope, make_batches(sl), loss, n_steps,
                rounds, steps=n_steps)
            per_bucket['seq%d' % sl] = {
                'samples_per_sec': round(batch / sec_step, 1),
                'step_ms': round(sec_step * 1000, 2),
                'compile_s': round(compile_s, 1)}
            total_time += sec_step * n_steps
            total_samples += batch * n_steps
            total_tokens += batch * sl * n_steps
            compile_total += compile_s
    return {
        'samples_per_sec': round(total_samples / total_time, 1),
        'tokens_per_sec': round(total_tokens / total_time, 1),
        'step_ms': round(total_time / (len(buckets) * n_steps) * 1000, 2),
        'compile_s': round(compile_total, 1),
        'final_loss': round(lossv, 4),
        'buckets': per_bucket,
        'config': 'stacked_lstm L%d h%d mixed-seq%s b%d' % (
            layers_n, hid, buckets, batch),
        # goodput columns from the LAST bucket's measured window (each
        # bucket resets the live accounting window)
        **gp_cols,
    }


def _bench_se_resnext(batch, k_per_call, rounds, amp):
    """SE-ResNeXt-50 (reference benchmark/fluid/models/se_resnext.py)."""
    from paddle_tpu.models.se_resnext import build as build_se
    return _bench_image_model(build_se, 'se_resnext50', batch,
                              k_per_call, rounds, amp)


def _bench_vgg(batch, k_per_call, rounds, amp):
    """VGG16-BN cifar10 (reference benchmark/fluid/models/vgg.py:28
    vgg16_bn_drop; fluid_benchmark default data_set cifar10)."""
    from paddle_tpu.models.vgg import build as build_vgg
    return _bench_image_model(
        lambda: build_vgg(class_dim=10, image_shape=(3, 32, 32)),
        'vgg16', batch, k_per_call, rounds, amp,
        img_shape=(3, 32, 32), n_class=10, dataset='cifar10')


def _bench_nmt(batch, seq_len, k_per_call, rounds):
    """Attention seq2seq NMT train + beam-search generation timing
    (reference benchmark/fluid/models/machine_translation.py:186:
    emb/enc/dec 512, dict 30000; its harness trains only, is_generating=
    False — the generation timing is our addition). Train feeds are
    ragged LoD batches with one shared bucket shape per fused window."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.models.seq2seq import (Seq2SeqConfig, build_nmt_train,
                                           build_nmt_generate)

    cfg = Seq2SeqConfig()       # reference scale: 512/512/512, V=30000
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        feeds, avg_cost, _pred = build_nmt_train(cfg)
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    lod = [list(range(0, (batch + 1) * seq_len, seq_len))]
    total = batch * seq_len
    batches = [{
        'source_sequence': (rng.randint(
            1, cfg.dict_size, (total, 1)).astype('int64'), lod),
        'target_sequence': (rng.randint(
            1, cfg.dict_size, (total, 1)).astype('int64'), lod),
        'label_sequence': (rng.randint(
            1, cfg.dict_size, (total, 1)).astype('int64'), lod),
    } for _ in range(k_per_call)]
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        sec_step, loss, compile_s, gp_cols = _measure_steps(
            exe, main_p, scope, batches, avg_cost, k_per_call, rounds)
    out = {
        'samples_per_sec': round(batch / sec_step, 1),
        'tokens_per_sec': round(total / sec_step, 1),
        'step_ms': round(sec_step * 1000, 2),
        'compile_s': round(compile_s, 1),
        'final_loss': round(loss, 4),
        **gp_cols,
        'config': 'nmt emb%d enc%d dec%d V%d seq%d b%d' % (
            cfg.embedding_dim, cfg.encoder_size, cfg.decoder_size,
            cfg.dict_size, seq_len, batch),
    }
    # beam-search generation, measured at a CACHED COMPILED STEP: bind()
    # compiles the While decode once and the timing loop re-dispatches
    # that executable directly — no per-sentence program re-trace, no
    # per-call feed re-preparation or cache-key hashing (the timing
    # includes one relay round-trip; reported per sentence)
    try:
        from paddle_tpu.contrib.decoder import BeamSearchDecoder
        gmain, gstart = fluid.Program(), fluid.Program()
        gcfg = Seq2SeqConfig(beam_size=3)
        with fluid.program_guard(gmain, gstart):
            gfeeds, (ids_v, sc_v) = build_nmt_generate(gcfg, max_len=50)
        gb = 8
        src = (rng.randint(1, cfg.dict_size,
                           (gb * seq_len, 1)).astype('int64'),
               [list(range(0, (gb + 1) * seq_len, seq_len))])
        init_ids, init_scores = BeamSearchDecoder.make_initial_beams(
            gb, gcfg.beam_size, 0)
        gscope = fluid.Scope()
        with fluid.scope_guard(gscope):
            exe.run(gstart, scope=gscope)
            feed = {'source_sequence': src, 'init_ids': init_ids,
                    'init_scores': init_scores}
            bound = exe.bind(gmain, feed, fetch_list=[ids_v, sc_v],
                             scope=gscope)             # compiles once
            best = float('inf')
            for _ in range(max(1, rounds)):
                t0 = time.time()
                bound(bound.example_feed)
                best = min(best, time.time() - t0)
        out['beam_decode_ms_per_sentence'] = round(best * 1000 / gb, 2)
        out['beam_config'] = 'beam%d maxlen50 b%d cached-step' % (
            gcfg.beam_size, gb)
    except Exception as e:
        out['beam_error'] = '%s: %s' % (type(e).__name__, str(e)[:150])
    return out


def _bench_ctr(batch, k_per_call, rounds, vocab=100000, dim=16,
               is_distributed=False):
    """Wide&deep-style CTR: multi-slot embedding lookups + MLP, the sparse
    workload BASELINE.md's north-star table names (DeepFM/CTR).
    is_distributed=True sizes the table for the vocab-sharded path
    (reference lookup_table is_distributed / parameter_prefetch) — on the
    single bench chip the shard is the whole table; the 8-way sharded
    placement itself is validated by dryrun_multichip's V=1M mesh case."""
    import numpy as np
    import paddle_tpu as fluid

    slots = 26
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        ids = fluid.layers.data(name='ids', shape=[slots], dtype='int64')
        label = fluid.layers.data(name='label', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(
            input=fluid.layers.reshape(ids, [-1, slots, 1]),
            size=[vocab, dim], is_sparse=True,
            is_distributed=is_distributed)
        flat = fluid.layers.reshape(emb, [-1, slots * dim])
        h = fluid.layers.fc(flat, size=400, act='relu')
        h = fluid.layers.fc(h, size=400, act='relu')
        p = fluid.layers.fc(h, size=1, act='sigmoid')
        loss = fluid.layers.mean(fluid.layers.log_loss(p, label))
        fluid.optimizer.Adagrad(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # one DISTINCT batch per step (ids are tiny; nothing repeats, so the
    # window measures online learning, not memorization) with teacher
    # labels: click iff the ids' fixed random scores sum positive —
    # exactly the per-id structure the embedding model can learn
    n_steps = max(150, k_per_call)
    id_score = rng.randn(vocab).astype('float32')
    batches = []
    for _ in range(n_steps):
        ids = rng.randint(0, vocab, (batch, slots)).astype('int64')
        lbl = (id_score[ids].sum(1) > 0).astype('float32').reshape(-1, 1)
        batches.append({'ids': ids, 'label': lbl})
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        sec_step, loss, compile_s, gp_cols = _measure_steps(
            exe, main_p, scope, batches, loss, n_steps, rounds,
            steps=n_steps)
    row = {
        'samples_per_sec': round(batch / sec_step, 1),
        'step_ms': round(sec_step * 1000, 2),
        'compile_s': round(compile_s, 1),
        'final_loss': round(loss, 4),
        'config': 'ctr v%d s%d d%d b%d' % (vocab, slots, dim, batch),
    }
    row.update(gp_cols)
    return row


def _machine_window(pred, feed, over_fn):
    """Shared differential-window device-resident rate (the lstmroof.py
    slope method): machine_ms = (t(k2) - t(k1)) / (k2 - k1), best-of-3
    per window. A single fixed-k window divides the RELAY round-trip
    (0.1-6 s depending on tunnel load) by k and leaks it into the number;
    the slope cancels the constant term entirely. LARGE float feeds are
    generated ON device (uploading K image batches through the relay is
    not serving latency) while small float feeds keep their real values
    (BERT's input_mask is a 0/1 contract; noise would corrupt the
    attention bias). Returns one of {'ms': float},
    {'unstable': [t1, t2]}, {'skipped': 'time budget'} — ONE
    implementation so the fp32 and int8 rows can never drift apart on
    method."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    k1, k2 = 8, 40

    def _stage(v):
        arr = np.asarray(v)
        if arr.dtype.kind == 'f' and arr.nbytes > (1 << 20):
            return jax.random.normal(jax.random.PRNGKey(0),
                                     (k1,) + arr.shape, jnp.float32)
        return jax.device_put(np.stack([arr] * k1))
    stacked = {kk: _stage(v) for kk, v in feed.items()}

    def _timed(n_steps):
        with fluid.scope_guard(pred.scope):
            pred.executor.run_fused(
                pred.program, stacked, fetch_list=pred.fetch_vars,
                steps=n_steps)                            # compile
            best = float('inf')
            for _ in range(3):
                t0 = time.time()
                pred.executor.run_fused(
                    pred.program, stacked, fetch_list=pred.fetch_vars,
                    steps=n_steps)
                best = min(best, time.time() - t0)
        return best
    t1 = _timed(k1)
    if over_fn():
        # mark the cut so a consumer can tell 'metric cut by budget'
        # from 'bench version without the metric'
        return {'skipped': 'time budget'}
    t2 = _timed(k2)
    # best-of-3 only rejects jitter when at least one sample per window
    # is clean; a non-positive slope means the relay moved under us —
    # re-measure the pair once, and if it is STILL unstable publish the
    # raw windows instead of a negative "serving rate"
    if t2 <= t1 and not over_fn():
        t1, t2 = _timed(k1), _timed(k2)
    if t2 > t1:
        return {'ms': round((t2 - t1) * 1000 / (k2 - k1), 2)}
    return {'unstable': [round(t1, 3), round(t2, 3)]}


def _bench_inference(rounds=9, deadline=None):
    """Predictor (deploy-path) latency: save_inference_model ->
    load_inference_model -> Predictor.run at batch 1 and 128, p50 ms per
    call (the reference inference/tests/api/analyzer_resnet50_tester.cc /
    analyzer_bert_tester pattern). The per-call number includes the
    ~0.15 s relay round-trip this chip sits behind, so a device-resident
    `machine_ms` is also reported for b128: K forwards scanned in ONE
    compiled call on the predictor's own pruned program (what an
    on-device serving loop would see). `deadline` (epoch seconds) bounds
    the row — each part needs a fresh XLA compile, and compile time under
    chip contention is the budget risk."""
    import shutil
    import tempfile
    import numpy as np
    import paddle_tpu as fluid

    out = {}

    def _over():
        return deadline is not None and time.time() > deadline

    def _row(name, build_prog, make_feed, fetch_pick):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feeds, targets = build_prog(main)
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        d = tempfile.mkdtemp(prefix='bench_infer_')
        try:
            with fluid.scope_guard(scope):
                exe.run(startup, scope=scope)
                fluid.io.save_inference_model(d, feeds, targets, exe,
                                              main_program=main)
            pred = fluid.create_predictor(d)
            row = {}
            for b in (1, 128):
                if _over():
                    row['skipped_b%d' % b] = 'time budget'
                    continue
                feed = make_feed(b)
                pred.run(feed)                       # compile
                # a >8 MB feed makes each call relay-upload-bound
                # (~10 s for b128 images): fewer rounds, same p50 story
                n_bytes = sum(np.asarray(v).nbytes for v in feed.values())
                n_rounds = min(rounds, 5) if n_bytes > (8 << 20) else rounds
                times = []
                for _ in range(n_rounds):
                    t0 = time.time()
                    pred.run(feed)
                    times.append((time.time() - t0) * 1000)
                times.sort()
                row['p50_ms_b%d' % b] = round(times[len(times) // 2], 2)
                # device-resident serving rate: K forwards, one call.
                # LARGE float feeds (images) are generated ON device —
                # uploading K image batches through the relay is not
                # serving latency — but small float feeds keep their real
                # values (BERT's input_mask is a 0/1 contract; feeding it
                # noise would corrupt the attention bias).
                # b128 only: each machine window is another full compile.
                if b != 128:
                    continue
                if _over():
                    row['skipped_machine_b%d' % b] = 'time budget'
                    continue
                win = _machine_window(pred, feed, _over)
                if 'ms' in win:
                    row['machine_ms_b%d' % b] = win['ms']
                elif 'unstable' in win:
                    row['machine_unstable_b%d' % b] = win['unstable']
                else:
                    row['skipped_machine_b%d' % b] = win['skipped']
            out[name] = row
        finally:
            shutil.rmtree(d, ignore_errors=True)

    rng = np.random.RandomState(0)

    def _resnet_prog(main):
        from paddle_tpu.models.resnet import build as build_resnet
        img, label, pred_v, avg_cost, acc = build_resnet('imagenet',
                                                         depth=50)
        return ['img'], [pred_v]

    def _resnet_feed(b):
        return {'img': rng.randn(b, 3, 224, 224).astype('float32')}

    from paddle_tpu.models.bert import (BertConfig, build_bert_pretrain,
                                        make_pretrain_batch)
    bcfg = BertConfig(seq_len=128, max_predictions=20)

    def _bert_prog(main):
        total, mlm, nsp = build_bert_pretrain(bcfg, is_test=True)
        return ['tokens', 'segments', 'input_mask', 'mlm_positions',
                'mlm_labels', 'nsp_labels'], [total]

    def _bert_feed(b):
        return make_pretrain_batch(bcfg, b, rng)

    for name, fns in (('resnet50_infer', (_resnet_prog, _resnet_feed)),
                      ('bert_infer', (_bert_prog, _bert_feed))):
        if _over():
            out[name] = {'skipped': 'time budget'}
            continue
        try:
            _row(name, fns[0], fns[1], None)
        except Exception as e:
            out[name] = {'error': '%s: %s' % (type(e).__name__,
                                              str(e)[:200])}

    # int8 BERT inference: the SAME program post-training-quantized
    # (contrib.quantize.post_training_quantize — calibrated int8 GEMMs,
    # int8 weight blobs in the artifact). Contract: machine_ms_b128 beats
    # the fp32 bert_infer row at equal accuracy (loss_int8 within 1% of
    # loss_fp32 on the shared eval batch; the convergence harness
    # (tools/convergence.py) carries the long-run accuracy evidence), and
    # the quantized program serves with zero recompiles after warmup.
    if not _over():
        try:
            out['bert_infer_int8'] = _bert_int8_row(
                bcfg, rng, rounds, deadline,
                fp32_row=out.get('bert_infer'))
        except Exception as e:
            out['bert_infer_int8'] = {'error': '%s: %s' % (
                type(e).__name__, str(e)[:200])}
    else:
        out['bert_infer_int8'] = {'skipped': 'time budget'}
    return out


def _bert_int8_row(bcfg, rng, rounds, deadline, fp32_row=None):
    """PTQ int8 BERT: quantize -> export -> Predictor -> timed like the
    fp32 row (same differential-window machine_ms method)."""
    import shutil
    import tempfile
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.contrib.quantize import post_training_quantize
    from paddle_tpu.models.bert import build_bert_pretrain, \
        make_pretrain_batch

    def _over():
        return deadline is not None and time.time() > deadline

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        total, mlm, nsp = build_bert_pretrain(bcfg, is_test=True)
    feed_names = ['tokens', 'segments', 'input_mask', 'mlm_positions',
                  'mlm_labels', 'nsp_labels']
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    d = tempfile.mkdtemp(prefix='bench_int8_')
    row = {}
    try:
        with fluid.scope_guard(scope):
            exe.run(startup, scope=scope)
            infer = main.clone(for_test=True)
            eval_feed = make_pretrain_batch(bcfg, 128, rng)
            ref, = exe.run(infer, feed=eval_feed, fetch_list=[total],
                           scope=scope)
            row['loss_fp32'] = round(
                float(np.asarray(ref).reshape(-1)[0]), 4)
            calib = [make_pretrain_batch(bcfg, 16, rng) for _ in range(2)]
            n_q = post_training_quantize(exe, infer, scope, calib)
            row['quantized_matmuls'] = len(n_q)
            fluid.io.save_inference_model(
                d, feed_names, [infer.global_block().var(total.name)],
                exe, main_program=infer)
        pred = fluid.create_predictor(d)
        got, = pred.run(eval_feed)                    # compile
        row['loss_int8'] = round(
            float(np.asarray(got).reshape(-1)[0]), 4)
        denom = abs(row['loss_fp32']) or 1.0
        row['loss_rel_err'] = round(
            abs(row['loss_int8'] - row['loss_fp32']) / denom, 5)
        # zero-recompile serving contract after the warmup call above
        before = monitor.counters()
        times = []
        for _ in range(min(rounds, 5)):
            t0 = time.time()
            pred.run(eval_feed)
            times.append((time.time() - t0) * 1000)
        times.sort()
        row['p50_ms_b128'] = round(times[len(times) // 2], 2)
        row['recompiles_after_warmup'] = int(monitor.counter_delta(
            before).get('compile_cache_miss', 0))
        if _over():
            row['skipped_machine_b128'] = 'time budget'
            return row
        # the SAME _machine_window as the fp32 bert_infer row — shared
        # implementation, so the vs_fp32 ratio can never become a
        # methodology artifact
        win = _machine_window(pred, eval_feed, _over)
        if 'ms' in win:
            row['machine_ms_b128'] = win['ms']
            fp32_ms = (fp32_row or {}).get('machine_ms_b128')
            if fp32_ms:
                row['vs_fp32'] = round(fp32_ms / win['ms'], 3)
        elif 'unstable' in win:
            row['machine_unstable_b128'] = win['unstable']
        else:
            row['skipped_machine_b128'] = win['skipped']
        return row
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _child(mode):
    """Run the measurement on `mode` in {'tpu','cpu'}; print the JSON line."""
    if mode == 'cpu':
        os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    if mode == 'cpu':
        try:  # the image's sitecustomize overrides the env var; re-assert
            jax.config.update('jax_platforms', 'cpu')
        except Exception:
            pass
    import numpy as np

    dev = jax.devices()[0]
    on_tpu = dev.platform == 'tpu'
    if mode == 'tpu' and not on_tpu:
        sys.exit(3)  # tunnel gave us CPU; let the parent label the fallback
    kind = getattr(dev, 'device_kind', '') or ''
    start = time.time()

    # attach monitor counter DELTAS (cache hits, donations, bytes moved)
    # to each row so BENCH_*.json carries causal context, not just timings
    from paddle_tpu import monitor as _monitor
    _COUNTER_PREFIXES = ('compile_cache', 'donation', 'feed_host_bytes',
                         'fetch_host_bytes', 'nan_check',
                         'fused_kernel_dispatch', 'quantized_program',
                         'kv_prefix_hit', 'kv_prefix_tokens_saved',
                         'kv_block_cow')

    def _with_counters(fn, *args, **kw):
        before = _monitor.counters()
        row = fn(*args, **kw)
        if isinstance(row, dict):
            row['counters'] = {
                k: v for k, v in _monitor.counter_delta(before).items()
                if k.startswith(_COUNTER_PREFIXES)}
        return row

    # standalone device->host sync cost, for transparency
    t0 = time.time()
    float(jax.numpy.zeros(()))
    sync_ms = round((time.time() - t0) * 1000, 1)

    # steady-state per-run host overhead (residency + donation contract:
    # after warmup, a run() dispatch must not re-stage state through the
    # host) and compile-cache reuse for a rebuilt identical program in a
    # fresh Executor — measured, not asserted
    try:
        from tools.runoverhead import measure_run_overhead
        run_overhead = measure_run_overhead(30 if on_tpu else 200)
    except Exception as e:
        run_overhead = {'error': '%s: %s' % (type(e).__name__,
                                             str(e)[:200])}

    # serving-engine row: dynamic-batching request throughput vs
    # sequential Predictor.run on a mixed-shape concurrent load, p50/p99
    # latency, recompiles-after-warmup (contract: 0), shed behavior.
    # best-of-rounds minima on both sides (tools/servebench.py)
    try:
        from tools.servebench import measure_serving
        serving = measure_serving(rounds=3 if on_tpu else 5,
                                  requests_per_client=20 if on_tpu else 40)
    except Exception as e:
        serving = {'error': '%s: %s' % (type(e).__name__, str(e)[:200])}

    # multi-tenant fleet row: fp32 + PTQ-int8 models co-resident in one
    # ModelFleet behind the goodput-priced Router — premium closed-loop
    # deadline traffic (contract: p99 under deadline, 0 errors) next to
    # a flooding quota'd batch tenant (contract: sheds structured, never
    # starves the deadline class), with a mid-bench hot-swap of the
    # premium model under live load (contract: dropped_inflight == 0,
    # recompiles_after_warmup == 0) and LIVE goodput.cost_estimate
    # pricing per model (tools/servebench.py measure_fleet / --fleet)
    try:
        from tools.servebench import measure_fleet
        serving_fleet = measure_fleet(
            requests_per_client=20 if on_tpu else 40)
    except Exception as e:
        serving_fleet = {'error': '%s: %s'
                         % (type(e).__name__, str(e)[:200])}

    # generative-decode row: continuous-batching GenerateEngine with the
    # device-resident KV cache vs the sequential re-traced greedy
    # baseline — tokens/sec, ENGINE-attributed per-token p50/p99 (step
    # time charged to each token the step emitted), recompiles-after-
    # warmup (contract: 0), kv occupancy, and the PAGED columns: the
    # same workload at the same KV HBM budget through the block-table
    # cache (block utilization, prefix-share hit rate, peak concurrent
    # sequences — contract: >= 2x the contiguous slots — and exact
    # greedy parity vs the contiguous engine). The companion
    # shared-prefix row (one system prompt, N clients) proves physical
    # block sharing (refcounts) + measurably reduced prefill
    # (tools/servebench.py measure_generate / measure_shared_prefix;
    # contract: >=10x sentences/s vs re-trace).
    # ROW-SCHEMA NOTE (per-token latency attribution): rounds up to and
    # including BENCH_r06 computed ms_per_token_p50/p99 from CLIENT
    # ARRIVAL GAPS — tokens buffered in the stream queue drain in ~0
    # time, so those rows carry a bogus p50 (e.g. 0.003 ms against a
    # 72 ms p99 in r06). PR 12 switched the attribution to engine step
    # time charged per emitted token; r07+ rows are comparable to each
    # other but NOT to the p50 column of older rows (p99 was dominated
    # by real step time and remains roughly comparable).
    try:
        from tools.servebench import measure_generate
        generate = measure_generate(rounds=2 if on_tpu else 3)
    except Exception as e:
        generate = {'error': '%s: %s' % (type(e).__name__, str(e)[:200])}
    try:
        from tools.servebench import measure_shared_prefix
        generate_shared_prefix = measure_shared_prefix()
    except Exception as e:
        generate_shared_prefix = {'error': '%s: %s'
                                  % (type(e).__name__, str(e)[:200])}

    # speculative-decode row: the decode-heavy greedy workload through
    # the paged engine plain vs SPECULATIVE (draft = target: accept
    # rate 1.0 — one drafter dispatch + one spec_k+1-wide verify
    # replace spec_k+1 sequential steps; contract: >= 1.5x engine
    # tokens/sec, exact greedy parity, 0 recompiles), plus the
    # chunked-prefill proof: a prompt past the widest bucket admitted
    # with a bit-exact continuation (tools/servebench.py
    # measure_speculative / --speculative)
    try:
        from tools.servebench import measure_speculative
        generate_speculative = measure_speculative(
            rounds=3 if on_tpu else 4)
    except Exception as e:
        generate_speculative = {'error': '%s: %s'
                                % (type(e).__name__, str(e)[:200])}

    # async-pipeline row: overlapped input pipeline (DevicePrefetcher ->
    # run_async, bounded in-flight window) vs the synchronous step loop
    # on an input-bound workload (tools/pipebench.py; contract: >=1.3x
    # steps/sec at recompiles_after_warmup=0 with exact trajectory
    # parity)
    try:
        from tools.pipebench import measure_pipeline
        async_pipeline = measure_pipeline(rounds=2 if on_tpu else 3)
    except Exception as e:
        async_pipeline = {'error': '%s: %s' % (type(e).__name__,
                                               str(e)[:200])}

    # parameter-server CTR row: the ctr_sharded_v1m shape with the
    # embedding table PS-RESIDENT on live socket shards (paddle_tpu/ps)
    # — samples/s with the pull-prefetch overlap vs the serialized
    # pull->run->push loop, pull/push counter + byte deltas, and
    # recompiles_after_warmup (contract: overlap > no_overlap at 0
    # recompiles; tools/psbench.py)
    try:
        from tools.psbench import measure_ctr_ps
        ctr_ps = measure_ctr_ps(rounds=2 if on_tpu else 3)
    except Exception as e:
        ctr_ps = {'error': '%s: %s' % (type(e).__name__, str(e)[:200])}

    # elastic-resume chaos row: a fatal fault kills a training step
    # mid-run; elastic_train_loop restores the latest checkpoint
    # RESHARDED onto half the devices and replays
    # (tools/chaosbench.py; contract: trajectory_parity True — the
    # recovered run bit-matches the uninterrupted one)
    try:
        from tools.chaosbench import measure_elastic_resume
        elastic_resume = measure_elastic_resume()
    except Exception as e:
        elastic_resume = {'error': '%s: %s' % (type(e).__name__,
                                               str(e)[:200])}

    # shrink-THEN-grow chaos row: the kill halves the fleet, capacity
    # later returns and the loop re-expands onto the full mesh via a
    # checkpoint-publish barrier (time_to_recover both directions;
    # contract: trajectory_parity True). Runs as a subprocess — the
    # drill needs an 8-way CPU mesh forced before jax initializes,
    # which this process's jax can no longer do.
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          'tools', 'chaosbench.py'), '--grow'],
            capture_output=True, text=True, timeout=600)
        line = [l for l in res.stdout.splitlines()
                if l.startswith('{')][-1]
        elastic_grow_back = json.loads(line)
        elastic_grow_back.pop('metric', None)
    except Exception as e:
        elastic_grow_back = {'error': '%s: %s' % (type(e).__name__,
                                                  str(e)[:200])}

    # XLA cost/memory analytics smoke (tools/costreport.py — the
    # Executor.explain CLI): flops + buffer-assignment peak for the
    # mnist-mlp reference programs. Memory stats cost one extra XLA
    # compile per program — cheap on CPU, minutes on TPU, so the TPU
    # line keeps cost analysis only.
    try:
        from tools.costreport import measure_costreport
        costreport = measure_costreport(batch=64 if on_tpu else 8,
                                        memory=not on_tpu)
    except Exception as e:
        costreport = {'error': '%s: %s' % (type(e).__name__,
                                           str(e)[:200])}

    # mesh-partitioned fused-kernel smoke (tools/kernbench.py --mesh 2):
    # each fused unit must dispatch its PARTITIONED impl under
    # mesh(data=2) — the mesh_dispatch sub-dicts carry the
    # fused_kernel_dispatch_total{...,mesh=n} proof rows. Tiny configs:
    # this is a dispatch/coverage row, not a timing row. On a
    # single-device host it runs as a SUBPROCESS of the kernbench CLI
    # (which forces its own virtual multi-device CPU) so this child's
    # topology — and every other row's timing — stays untouched.
    try:
        if len(jax.devices()) >= 2:
            from tools.kernbench import measure_kernbench
            kernbench_mesh = measure_kernbench(
                tiers=['off', 'pallas' if on_tpu else 'interpret'],
                rounds=1, k=2, size='small', mesh=2)
        else:
            res = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              'tools', 'kernbench.py'),
                 '--tiers', 'off,interpret', '--rounds', '1', '--k', '2',
                 '--mesh', '2'],
                capture_output=True, text=True, timeout=600,
                env=dict(os.environ))
            kernbench_mesh = json.loads(
                (res.stdout or '').strip().splitlines()[-1])
    except Exception as e:
        kernbench_mesh = {'error': '%s: %s' % (type(e).__name__,
                                               str(e)[:200])}

    if on_tpu:
        flagship_cfg = dict(vocab_size=32000, seq_len=512, d_model=512,
                            n_head=8, n_layer=6, d_ff=2048, dropout=0.1,
                            attn_dropout=0.0, use_flash_attention=True)
        flag = _with_counters(_bench_lm, flagship_cfg, batch=64,
                              k_per_call=30, rounds=3, amp=True)
    else:
        flag = _with_counters(
            _bench_lm, dict(vocab_size=1024, seq_len=64, d_model=128,
                            n_head=4, n_layer=2, d_ff=256, dropout=0.1,
                            attn_dropout=0.0, use_flash_attention=True),
            batch=8, k_per_call=4, rounds=2, amp=False,
            steps_per_call=4)

    peak = _peak_for(kind) if on_tpu else None
    mfu = None
    if peak:
        mfu = round(flag['flops_per_step']
                    / (flag['step_ms'] / 1000) / peak, 4)

    # live-vs-offline MFU cross-check on the flagship row: the goodput
    # layer's best-window live flops rate vs this file's analytic
    # formula at the best step time. The ratio is peak-independent, so
    # the agreement verdict is defined on cpu_fallback rounds too (where
    # both MFU numbers are None absent a known peak — same provenance
    # caveat as the rest of a cpu_fallback line).
    goodput_xcheck = None
    if flag.get('live_flops_per_s') and flag.get('flops_per_step'):
        offline_rate = flag['flops_per_step'] / (flag['step_ms'] / 1000.0)
        ratio = flag['live_flops_per_s'] / offline_rate
        goodput_xcheck = {
            'live_mfu': flag.get('live_mfu'),
            'offline_mfu': mfu,
            'live_flops_per_s': flag['live_flops_per_s'],
            'offline_flops_per_s': round(offline_rate, 1),
            'live_vs_offline': round(ratio, 4),
            'within_10pct': bool(abs(ratio - 1.0) <= 0.10),
            'goodput_frac': flag.get('goodput_frac'),
        }

    models = {}
    if on_tpu:
        def _try(name, fn, *args, **kw):
            for attempt in range(2):      # one retry for relay flakes
                if time.time() - start > TPU_MODEL_BUDGET_S:
                    models[name] = {'skipped': 'time budget'}
                    return
                try:
                    models[name] = _with_counters(fn, *args, **kw)
                    return
                except Exception as e:  # failed extra must not kill the line
                    models[name] = {'error': '%s: %s' % (
                        type(e).__name__, str(e)[:200])}
                    time.sleep(5)

        def _set_mfu(name):
            r = models.get(name)
            if isinstance(r, dict) and peak and 'flops_per_step' in r:
                r['mfu'] = round(r['flops_per_step']
                                 / (r['step_ms'] / 1000) / peak, 4)

        _try('lm_large', _bench_lm,
             dict(vocab_size=32000, seq_len=512, d_model=1024, n_head=16,
                  n_layer=8, d_ff=4096, dropout=0.1, attn_dropout=0.0,
                  use_flash_attention=True),
             32, 20, 2, True)
        _set_mfu('lm_large')
        _try('lm_long_seq8k', _bench_lm,
             dict(vocab_size=32000, seq_len=8192, d_model=512, n_head=8,
                  n_layer=4, d_ff=2048, dropout=0.0, attn_dropout=0.0,
                  use_flash_attention=True),
             2, 10, 2, True)
        _set_mfu('lm_long_seq8k')
        _try('resnet50', _bench_resnet50, 128, 4, 2, True)
        _try('bert_base', _bench_bert, 128, 10, 2, True)
        _set_mfu('bert_base')
        _try('se_resnext', _bench_se_resnext, 128, 4, 2, True)
        _try('vgg16', _bench_vgg, 128, 10, 3, True)
        _try('ctr_sharded_v1m', _bench_ctr, 512, 20, 2,
             vocab=1 << 20, dim=32, is_distributed=True)
        _try('stacked_lstm', _bench_stacked_lstm, 32, 128, 10, 2)
        _try('ctr_sparse', _bench_ctr, 512, 50, 3)
        # inference (~6 fresh compiles, 2 models) runs BEFORE nmt: its two
        # rows are required deliverables, while nmt's ~500 s while-loop
        # train compile is the budget whale — nmt goes last so the
        # elapsed-budget guard above makes IT the row that absorbs
        # chip-contention overruns, not everything after it. Bounded at
        # ~600 s so a hung relay can't starve nmt in the good case.
        _try('inference', _bench_inference,
             deadline=min(start + TPU_MODEL_BUDGET_S - 120,
                          time.time() + 600))
        _try('machine_translation', _bench_nmt, 32, 30, 6, 2)
    for r in models.values():
        r.pop('flops_per_step', None)
    flag.pop('flops_per_step', None)

    tokens_per_sec = flag['tokens_per_sec']
    if not on_tpu and TRAIN_ROW_BASELINE.get('cpu'):
        # drift-watch the training flagship row too (the serving rows
        # already register theirs in servebench) — same committed-number
        # contract, keyed to the platform the baseline was measured on
        from paddle_tpu import goodput
        goodput.note_bench_row('transformer_lm_train_throughput',
                               tokens_per_sec, TRAIN_ROW_BASELINE['cpu'])
    print(json.dumps({
        'metric': 'transformer_lm_train_throughput',
        'value': round(tokens_per_sec, 2),
        'unit': 'tokens/sec',
        'vs_baseline': _vs_baseline(tokens_per_sec,
                                    'tpu' if on_tpu else 'cpu'),
        'platform': ('tpu' if on_tpu else 'cpu'),
        'device_kind': kind,
        'mfu': mfu,
        'step_ms': flag['step_ms'],
        'compile_s': flag['compile_s'],
        'sync_ms': sync_ms,
        'run_overhead': run_overhead,
        'serving': serving,
        'serving_fleet': serving_fleet,
        'generate': generate,
        'generate_shared_prefix': generate_shared_prefix,
        'generate_speculative': generate_speculative,
        'async_pipeline': async_pipeline,
        'ctr_ps': ctr_ps,
        'elastic_resume': elastic_resume,
        'elastic_grow_back': elastic_grow_back,
        'costreport': costreport,
        'kernbench_mesh': kernbench_mesh,
        'goodput': goodput_xcheck,
        'flops': flag.get('flops'),
        'peak_bytes': flag.get('peak_bytes'),
        'final_loss': flag['final_loss'],
        'amp': bool(on_tpu),
        'flash_attention': True,
        'fused_steps_per_call': 120 if on_tpu else 4,
        'config': flag['config'],
        'counters': flag.get('counters'),
        'models': models,
    }))


def _vs_baseline(value, platform):
    """Ratio vs the newest prior round's recorded throughput on the SAME
    platform (the driver writes BENCH_r01.json, BENCH_r02.json, ...); a
    cpu_fallback round must not become the baseline for a TPU round."""
    best = None
    for path in sorted(glob.glob('BENCH_r*.json')):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        parsed = rec.get('parsed') if isinstance(rec, dict) else None
        if not isinstance(parsed, dict):
            parsed = rec if isinstance(rec, dict) and 'value' in rec else None
        if not parsed or not parsed.get('value'):
            continue
        prev_platform = str(parsed.get('platform', 'tpu')).replace(
            '_fallback', '')
        if prev_platform != platform:
            continue
        best = float(parsed['value'])  # sorted() => last one wins
    return round(value / best, 4) if best else 1.0


def _run_child(mode, timeout):
    env = dict(os.environ, BENCH_CHILD=mode)
    try:
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, 'timeout after %ds' % timeout
    for line in reversed((res.stdout or '').strip().splitlines()):
        try:
            rec = json.loads(line)
            if isinstance(rec, dict) and 'metric' in rec:
                return rec, None
        except ValueError:
            continue
    tail = (res.stderr or '')[-400:]
    return None, 'rc=%d %s' % (res.returncode, re.sub(r'\s+', ' ', tail))


def main():
    mode = os.environ.get('BENCH_CHILD')
    if mode:
        return _child(mode)

    errors = []
    for attempt in range(2):  # TPU, with one retry for tunnel flakes
        rec, err = _run_child('tpu', TPU_TIMEOUT_S)
        if rec:
            print(json.dumps(rec))
            return
        errors.append('tpu[%d]: %s' % (attempt, err))
        if attempt == 0:
            time.sleep(20)
    rec, err = _run_child('cpu', CPU_TIMEOUT_S)
    if rec:
        rec['platform'] = 'cpu_fallback'
        rec['tpu_errors'] = errors
        print(json.dumps(rec))
        return
    errors.append('cpu: %s' % err)
    # the contract line is emitted no matter what
    print(json.dumps({
        'metric': 'transformer_lm_train_throughput', 'value': 0,
        'unit': 'tokens/sec', 'vs_baseline': 0.0, 'error': '; '.join(errors)}))


if __name__ == '__main__':
    main()
