"""Benchmark: flagship Transformer-LM training throughput on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md: published={}), so
vs_baseline is reported against our own first-round recorded value when
BENCH_r1.json exists, else 1.0.

Metric: tokens/sec of full train steps (fwd+bwd+Adam, bf16 matmul inputs on
TPU) on a GPT-style LM — the TPU analog of the reference's examples/sec
(benchmark/fluid/fluid_benchmark.py:297-301).
"""
import json
import os
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import build_lm, LMConfig

    on_tpu = any(d.platform == 'tpu' for d in jax.devices())
    if on_tpu:
        cfg = LMConfig(vocab_size=32000, seq_len=512, d_model=512, n_head=8,
                       n_layer=6, d_ff=2048, dropout=0.1)
        batch = 32
        steps, warmup = 20, 3
    else:  # CPU smoke config
        cfg = LMConfig(vocab_size=1024, seq_len=64, d_model=128, n_head=4,
                       n_layer=2, d_ff=256, dropout=0.1)
        batch = 8
        steps, warmup = 5, 1

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        tokens, labels, logits, avg_loss = build_lm(cfg)
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {
        'tokens': rng.randint(0, cfg.vocab_size,
                              (batch, cfg.seq_len)).astype('int64'),
        'labels': rng.randint(0, cfg.vocab_size,
                              (batch, cfg.seq_len)).astype('int64'),
    }
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        for _ in range(warmup):
            exe.run(main_p, feed=feed, fetch_list=[avg_loss], scope=scope)
        t0 = time.time()
        for _ in range(steps):
            out = exe.run(main_p, feed=feed, fetch_list=[avg_loss],
                          scope=scope)
        dt = time.time() - t0
    tokens_per_sec = steps * batch * cfg.seq_len / dt

    vs_baseline = 1.0
    if os.path.exists('BENCH_r1.json'):
        try:
            with open('BENCH_r1.json') as f:
                prev = json.load(f)
            if prev.get('value'):
                vs_baseline = tokens_per_sec / float(prev['value'])
        except Exception:
            pass
    print(json.dumps({
        'metric': 'transformer_lm_train_throughput',
        'value': round(tokens_per_sec, 2),
        'unit': 'tokens/sec',
        'vs_baseline': round(vs_baseline, 4),
    }))


if __name__ == '__main__':
    main()
