"""Benchmark: flagship Transformer-LM training throughput on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no in-tree numbers (BASELINE.md: published={}), so
vs_baseline compares against the most recent prior round's recorded value
(BENCH_r*.json written by the driver), else 1.0.

Metric: tokens/sec of full train steps (fwd+bwd+Adam, bf16 MXU compute via
contrib.mixed_precision, fp32 master weights) on a GPT-style LM — the TPU
analog of the reference's examples/sec (benchmark/fluid/fluid_benchmark.py:
297-301). Extras: mfu (model FLOPs / step-time / chip peak), platform, config.

Robustness contract (the round-1 bench died in backend init and recorded
nothing): the measurement runs in a CHILD process so a hung/unavailable TPU
tunnel is bounded by a timeout and killed; the parent retries once, then
falls back to a labeled CPU run; a JSON line is ALWAYS emitted.
"""
import glob
import json
import os
import re
import subprocess
import sys
import time

TPU_TIMEOUT_S = 1500      # first compile on chip is slow; bound, don't trust
CPU_TIMEOUT_S = 900

# peak dense bf16 FLOP/s per chip, by device_kind substring
PEAK_FLOPS = [
    ('v6', 918e12), ('v5p', 459e12), ('v5', 197e12),  # v5 lite / v5e
    ('v4', 275e12), ('v3', 123e12), ('v2', 45e12),
]


def _lm_train_flops_per_step(cfg, batch):
    """Model FLOPs of one train step (fwd matmuls+attention, x3 for bwd)."""
    B, L, d, V, dff = batch, cfg.seq_len, cfg.d_model, cfg.vocab_size, cfg.d_ff
    per_layer = (2 * B * L * d * 3 * d       # qkv proj
                 + 2 * B * L * L * d         # scores
                 + 2 * B * L * L * d         # context
                 + 2 * B * L * d * d         # out proj
                 + 2 * B * L * d * dff * 2)  # ffn1 + ffn2
    fwd = cfg.n_layer * per_layer + 2 * B * L * d * V  # + lm head
    return 3 * fwd


def _child(mode):
    """Run the measurement on `mode` in {'tpu','cpu'}; print the JSON line."""
    if mode == 'cpu':
        os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    if mode == 'cpu':
        try:  # the image's sitecustomize overrides the env var; re-assert
            jax.config.update('jax_platforms', 'cpu')
        except Exception:
            pass
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.contrib import mixed_precision as mp
    from paddle_tpu.models.transformer import build_lm, LMConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == 'tpu'
    if mode == 'tpu' and not on_tpu:
        sys.exit(3)  # tunnel gave us CPU; let the parent label the fallback

    if on_tpu:
        cfg = LMConfig(vocab_size=32000, seq_len=512, d_model=512, n_head=8,
                       n_layer=6, d_ff=2048, dropout=0.1, attn_dropout=0.0,
                       use_flash_attention=True)   # pallas fused attention
        batch, steps, warmup = 64, 30, 5
    else:  # CPU smoke config
        cfg = LMConfig(vocab_size=1024, seq_len=64, d_model=128, n_head=4,
                       n_layer=2, d_ff=256, dropout=0.1)
        batch, steps, warmup = 8, 5, 1

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        tokens, labels, logits, avg_loss = build_lm(cfg)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        if on_tpu:
            opt = mp.decorate(opt)  # bf16 MXU compute, fp32 master weights
        opt.minimize(avg_loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {
        'tokens': rng.randint(0, cfg.vocab_size,
                              (batch, cfg.seq_len)).astype('int64'),
        'labels': rng.randint(0, cfg.vocab_size,
                              (batch, cfg.seq_len)).astype('int64'),
    }
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        for _ in range(warmup):
            exe.run(main_p, feed=feed, fetch_list=[avg_loss], scope=scope)
        t0 = time.time()
        for _ in range(steps):
            out = exe.run(main_p, feed=feed, fetch_list=[avg_loss],
                          scope=scope)
        loss = float(np.asarray(out[0]).reshape(-1)[0])
        dt = time.time() - t0
    tokens_per_sec = steps * batch * cfg.seq_len / dt

    mfu = None
    kind = getattr(dev, 'device_kind', '') or ''
    if on_tpu:
        peak = next((p for pat, p in PEAK_FLOPS
                     if pat in kind.lower().replace(' ', '')), None)
        if peak:
            flops = _lm_train_flops_per_step(cfg, batch)
            mfu = round(flops * steps / dt / peak, 4)

    print(json.dumps({
        'metric': 'transformer_lm_train_throughput',
        'value': round(tokens_per_sec, 2),
        'unit': 'tokens/sec',
        'vs_baseline': _vs_baseline(tokens_per_sec,
                                    'tpu' if on_tpu else 'cpu'),
        'platform': ('tpu' if on_tpu else 'cpu'),
        'device_kind': kind,
        'mfu': mfu,
        'step_ms': round(1000 * dt / steps, 2),
        'final_loss': round(loss, 4),
        'amp': bool(on_tpu),
        'flash_attention': bool(
            getattr(cfg, 'use_flash_attention', False)
            and not getattr(cfg, 'attn_dropout', 0.0)),  # effective state
        'config': 'L%d d%d ff%d V%d seq%d b%d' % (
            cfg.n_layer, cfg.d_model, cfg.d_ff, cfg.vocab_size,
            cfg.seq_len, batch),
    }))


def _vs_baseline(value, platform):
    """Ratio vs the newest prior round's recorded throughput on the SAME
    platform (the driver writes BENCH_r01.json, BENCH_r02.json, ...); a
    cpu_fallback round must not become the baseline for a TPU round."""
    best = None
    for path in sorted(glob.glob('BENCH_r*.json')):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        parsed = rec.get('parsed') if isinstance(rec, dict) else None
        if not isinstance(parsed, dict):
            parsed = rec if isinstance(rec, dict) and 'value' in rec else None
        if not parsed or not parsed.get('value'):
            continue
        prev_platform = str(parsed.get('platform', 'tpu')).replace(
            '_fallback', '')
        if prev_platform != platform:
            continue
        best = float(parsed['value'])  # sorted() => last one wins
    return round(value / best, 4) if best else 1.0


def _run_child(mode, timeout):
    env = dict(os.environ, BENCH_CHILD=mode)
    try:
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, 'timeout after %ds' % timeout
    for line in reversed((res.stdout or '').strip().splitlines()):
        try:
            rec = json.loads(line)
            if isinstance(rec, dict) and 'metric' in rec:
                return rec, None
        except ValueError:
            continue
    tail = (res.stderr or '')[-400:]
    return None, 'rc=%d %s' % (res.returncode, re.sub(r'\s+', ' ', tail))


def main():
    mode = os.environ.get('BENCH_CHILD')
    if mode:
        return _child(mode)

    errors = []
    for attempt in range(2):  # TPU, with one retry for tunnel flakes
        rec, err = _run_child('tpu', TPU_TIMEOUT_S)
        if rec:
            print(json.dumps(rec))
            return
        errors.append('tpu[%d]: %s' % (attempt, err))
        if attempt == 0:
            time.sleep(20)
    rec, err = _run_child('cpu', CPU_TIMEOUT_S)
    if rec:
        rec['platform'] = 'cpu_fallback'
        rec['tpu_errors'] = errors
        print(json.dumps(rec))
        return
    errors.append('cpu: %s' % err)
    # the contract line is emitted no matter what
    print(json.dumps({
        'metric': 'transformer_lm_train_throughput', 'value': 0,
        'unit': 'tokens/sec', 'vs_baseline': 0.0, 'error': '; '.join(errors),
    }))


if __name__ == '__main__':
    main()
