"""CompiledProgram: execution-strategy wrapper over a Program.

Reference python/paddle/fluid/compiler.py:37 CompiledProgram +
with_data_parallel:77 (which wraps the C++ ParallelExecutor,
parallel_executor.cc:184). TPU-native redesign: data parallelism is SPMD —
the SAME compiled XLA program runs over a jax.sharding.Mesh with the batch
dimension sharded; gradient allreduce (psum over ICI) is inserted by the XLA
SPMD partitioner, replacing the whole OpHandle/NCCL machinery. See
parallel/spmd.py for the execution path.
"""
from . import monitor
from .framework import default_main_program

__all__ = ['CompiledProgram', 'ExecutionStrategy', 'BuildStrategy']


class ExecutionStrategy(object):
    """Knobs of reference details/execution_strategy.h:22 — mostly no-ops
    under XLA (scheduling is the compiler's job), kept for API parity."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = False


class BuildStrategy(object):
    """Reference details/build_strategy.h:34-96. On TPU:
    - reduce_strategy AllReduce vs Reduce → psum vs reduce_scatter grads
    - memory_optimize/inplace → XLA buffer assignment + donation (always on)
    - fuse_* → XLA fusion (always on)
    """

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.memory_optimize = False
        self.enable_inplace = False
        self.num_trainers = 1
        self.trainer_id = 0


class CompiledProgram(object):
    def __init__(self, program=None):
        self._program = program if program is not None \
            else default_main_program()
        self._is_data_parallel = False
        self._loss_name = None
        self._build_strategy = None
        self._exec_strategy = None
        self._share_vars_from = None
        self._places = None
        self._spmd = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def with_inference_optimize(self, config=None):
        return self

    # duck-typed hook called by Executor.run
    def _executor_run(self, executor, feed, fetch_list, scope, return_numpy,
                      donate=None):
        if not self._is_data_parallel:
            # recurses into Executor.run, which carries the observability
            # instrumentation — no metrics here or they'd double-count
            return executor.run(self._program, feed=feed,
                                fetch_list=fetch_list, scope=scope,
                                return_numpy=return_numpy, donate=donate)
        # the SPMD runner manages donation itself (sharded jit with
        # donate_argnums baked in); a per-call donate override does not
        # apply on this path — same as the historical PADDLE_DONATE env,
        # which it never consulted either
        from .parallel import spmd
        if self._spmd is None:
            self._spmd = spmd.DataParallelRunner(
                self._program, loss_name=self._loss_name,
                build_strategy=self._build_strategy, places=self._places)
        # the SPMD runner never reaches Executor._run_impl, so the run-level
        # metrics are recorded at this delegation instead (compile-cache
        # counters live in spmd.DataParallelRunner.run)
        with monitor.timed_span('run', 'executor_run_seconds'):
            monitor.inc('executor_run_total')
            return self._spmd.run(executor, feed, fetch_list, scope,
                                  return_numpy)
