"""Persistence: save/load vars, params, persistables, inference models.

Capability parity with reference python/paddle/fluid/io.py (save_vars:92,
save_params, save_persistables:441, load_vars, load_params,
load_persistables:657, save_inference_model:862, load_inference_model:1014).

TPU-native redesign: the Scope IS the checkpoint ("everything persistable is
the checkpoint", reference operators/save_op.cc raw serialization) — we
serialize scope entries with numpy .npz (single-file, save_combine-style) or
one .npy per var (per-var files, save-op style). Inference models serialize
the pruned Program via a durable versioned JSON schema (core/serialization.py)
+ params, the analog of the reference's `__model__` ProgramDesc proto + param
files — no pickle, so saved models survive refactors and load cross-process.
"""
import json
import os

import numpy as np

from .framework import Program, Parameter, Variable, default_main_program
from .executor import global_scope
from .core import serialization as _ser

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'export_stablehlo_model',
    'load_stablehlo_model', 'get_program_parameter',
]


def _is_persistable(var):
    return var.persistable


def _is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        val = scope.get(name)
        if val is None:
            raise RuntimeError("variable %r has no value in scope" % name)
        if getattr(val, 'is_deleted', None) is not None and val.is_deleted():
            # a donated run consumed this buffer and the scope was never
            # rebound (a stale scope snapshot, or an aborted run) — fail
            # with the cause instead of jax's opaque deleted-buffer error
            raise RuntimeError(
                "variable %r holds a donated (deleted) device buffer — it "
                "was consumed by a donated executor run. Save from the "
                "live scope (which is rebound to the new state after every "
                "run), or opt out of donation with PADDLE_DONATE=0." % name)
        # explicit host materialization point: scope values stay
        # device-resident across runs and are only pulled host-side here
        arrays[name] = np.asarray(val)
    # atomic tmp+fsync+rename publication (resilience.atomic_file): a
    # crash — or an injected ckpt_write fault — mid-save leaves the old
    # params file or none, never a torn one the loader would half-read.
    # Sweep dead writers' leftovers first so crashes don't accumulate
    # full-size partial files until the directory hits ENOSPC.
    from . import resilience
    resilience.sweep_stale_tmp_files(dirname)
    if filename is not None:
        if not filename.endswith('.npz'):
            filename += '.npz'  # np.savez appends it anyway; keep load in sync
        with resilience.atomic_file(os.path.join(dirname, filename)) as tmp:
            np.savez(tmp, **arrays)
    else:
        for name, arr in arrays.items():
            path = os.path.join(dirname, name.replace('/', '%2F') + '.npy')
            with resilience.atomic_file(path) as tmp:
                np.save(tmp, arr)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is not None:
        if not filename.endswith('.npz'):
            filename += '.npz'
        data = np.load(os.path.join(dirname, filename))
        stored = {k: data[k] for k in data.files}
    else:
        stored = None
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        if stored is not None:
            if name not in stored:
                raise RuntimeError("variable %r not found in %s"
                                   % (name, filename))
            scope.set(name, stored[name])
        else:
            path = os.path.join(dirname, name.replace('/', '%2F') + '.npy')
            if not os.path.exists(path):
                raise RuntimeError("variable file %r not found" % path)
            scope.set(name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def get_program_parameter(program):
    return program.all_parameters()


MODEL_FILENAME = '__model__'
PARAMS_FILENAME = '__params__.npz'


def _prune_for_inference(main_program, target_names):
    """clone(for_test) + strip training-only ops + prune. Stripping
    happens BEFORE pruning: optimizer ops write ParamOut under the
    parameter's own name, so dependency-based pruning alone would drag the
    whole backward+optimizer graph into the export (reference strips by op
    role, op_proto_maker.h:26-36). Shared by save_inference_model and
    export_stablehlo_model."""
    inference_program = main_program.clone(for_test=True)
    gb = inference_program.global_block()
    gb.ops = [op for op in gb.ops
              if getattr(op, 'role', 'Forward') not in
              ('Backward', 'Optimize')]
    inference_program._bump_version()
    return inference_program._prune(target_names)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to feed/fetch + serialize program & params
    (reference io.py:862)."""
    if main_program is None:
        main_program = default_main_program()
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    target_names = [t.name for t in target_vars]

    pruned = _prune_for_inference(main_program, target_names)
    # _prune keeps all persistables; drop the ones no remaining op touches
    # (optimizer accumulators, learning rate) so the export carries only
    # the weights the model actually reads
    pg = pruned.global_block()
    used = set(target_names)
    for op in pruned.blocks[0].ops:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    for block in pruned.blocks[1:]:
        for op in block.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
    import collections as _c
    pg.vars = _c.OrderedDict(
        (k, v) for k, v in pg.vars.items()
        if k in used or not v.persistable)
    pruned._bump_version()

    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    blob = _ser.program_to_dict(pruned)
    blob['feed_names'] = list(feeded_var_names)
    blob['fetch_names'] = target_names
    with open(model_path, 'w') as f:
        json.dump(blob, f)
    # save ALL persistables, not just Parameters: batch-norm moving stats etc.
    # are persistable plain Variables (reference io.py:1011 does the same)
    save_persistables(executor, dirname, pruned,
                      filename=params_filename or PARAMS_FILENAME)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, pserver_endpoints=None):
    """Returns (program, feed_names, fetch_names) (reference io.py:1014)."""
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    with open(model_path, 'r') as f:
        blob = json.load(f)
    program = _ser.program_from_dict(blob)
    load_persistables(executor, dirname, program,
                      filename=params_filename or PARAMS_FILENAME)
    fetch_vars = [program.global_block().var(n)
                  for n in blob['fetch_names']]
    if any(op.type in ('quantized_matmul', 'quantize')
           or (op.type == 'fake_dequantize_max_abs'
               and op.input('X')
               and op.input('X')[0].endswith('.int8'))
           for op in program.global_block().ops):
        # a serving process loading an int8 artifact counts it: obsreport/
        # bench deltas show quantized programs actually serving
        from . import monitor
        monitor.inc('quantized_program_total', labels={'kind': 'loaded'})
    return program, blob['feed_names'], fetch_vars


def export_stablehlo_model(dirname, feeded_var_names, target_vars, executor,
                           example_feeds, main_program=None, scope=None):
    """Serialize the pruned inference computation as portable StableHLO
    (the deployment analog of the reference's __model__ ProgramDesc +
    AnalysisPredictor, inference/io.cc — but as a compiler-level artifact:
    the loaded module needs NO framework at all, only jax.export).

    Parameters are baked into the module as constants from `scope`.
    `example_feeds`: {name: ndarray-or-(shape, dtype)} fixing input
    signatures (XLA needs static shapes). Writes __model__.stablehlo plus
    a small JSON manifest; returns the manifest dict."""
    import jax
    from jax import export as jexport
    import numpy as _np
    from .core import lowering as _low
    from .executor import global_scope as _gs, Executor as _Exe

    if main_program is None:
        main_program = default_main_program()
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    target_names = [t.name for t in target_vars]
    scope = scope if scope is not None else _gs()

    pruned = _prune_for_inference(main_program, target_names)

    read, written = _low.analyze_state(pruned, target_names)
    needed = _Exe._read_before_write(pruned, read, written,
                                     set(feeded_var_names), target_names)
    fn, ro_names, rw_names = _low.build_fn(pruned, target_names, needed,
                                           written)
    state = {}
    for n in list(ro_names) + list(rw_names):
        v = scope.get(n)
        if v is None:
            raise RuntimeError(
                "export_stablehlo_model: persistable %r is not in the "
                "scope — run the startup program / load params first" % n)
        state[n] = _np.asarray(v)

    def _spec(v):
        if isinstance(v, tuple):
            shape, dtype = v
            return jax.ShapeDtypeStruct(tuple(shape), _np.dtype(dtype))
        arr = _np.asarray(v)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    feed_specs = {n: _spec(example_feeds[n]) for n in feeded_var_names}
    key = jax.random.PRNGKey(0)     # inference clone: no random ops live

    def infer(*feed_vals):
        feed = dict(zip(feeded_var_names, feed_vals))
        ro = {n: state[n] for n in ro_names}
        rw = {n: state[n] for n in rw_names}
        fetches, _ = fn(feed, ro, rw, key)
        return tuple(fetches)

    exported = jexport.export(jax.jit(infer))(
        *[feed_specs[n] for n in feeded_var_names])
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, '__model__.stablehlo')
    with open(path, 'wb') as f:
        f.write(exported.serialize())
    manifest = {
        'format': 'stablehlo', 'version': 1,
        'feed_names': list(feeded_var_names),
        'fetch_names': target_names,
        'feed_shapes': {n: list(feed_specs[n].shape)
                        for n in feeded_var_names},
    }
    with open(os.path.join(dirname, '__model__.stablehlo.json'),
              'w') as f:
        json.dump(manifest, f)
    return manifest


def load_stablehlo_model(dirname):
    """Load a StableHLO export: returns (callable, manifest). The callable
    takes feeds positionally in manifest['feed_names'] order and returns
    the fetch tuple — no Program/Scope machinery involved."""
    from jax import export as jexport
    with open(os.path.join(dirname, '__model__.stablehlo'), 'rb') as f:
        exported = jexport.deserialize(f.read())
    with open(os.path.join(dirname, '__model__.stablehlo.json')) as f:
        manifest = json.load(f)
    return exported.call, manifest
