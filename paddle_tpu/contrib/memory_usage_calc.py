"""Estimate a Program's memory usage (reference
python/paddle/fluid/contrib/memory_usage_calc.py memory_usage).

The estimate sums var sizes with -1 batch dims bound to `batch_size`. On
TPU the number is a lower bound on HBM residency (XLA buffer assignment
reuses/fuses aggressively, and rematerialization trades it for FLOPs), so
like the reference the result is reported as a range.
"""
import numpy as np

__all__ = ['memory_usage']

_DTYPE_SIZE = {
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'int8': 1, 'uint8': 1, 'int16': 2, 'int32': 4, 'int64': 8, 'bool': 1,
}


def memory_usage(program, batch_size):
    """Returns (low_mb, high_mb): estimated memory range for one iteration
    at `batch_size` (reference returns the same +-30% band)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0
    for block in program.blocks:
        for var in block.vars.values():
            shape = getattr(var, 'shape', None)
            if not shape:
                continue
            size = _DTYPE_SIZE.get(str(var.dtype), 4)
            n = 1
            for d in shape:
                if d is None or d < 0:
                    d = batch_size
                n *= int(d)
            total += n * size
    mb = total / (1024.0 ** 2)
    return mb * 0.7, mb * 1.3
