"""Estimate a Program's memory usage (reference
python/paddle/fluid/contrib/memory_usage_calc.py memory_usage).

Two tiers, same public name:

- When a compiled executable for this program has been registered with
  ``paddle_tpu.analysis`` (any ``Executor.run`` / ``Executor.explain`` of
  it in this process) **at a matching batch size**, the estimate comes
  from XLA's buffer assignment — argument + output + temp - aliased
  bytes, the real peak the compiler planned — reported as a tight ±10%
  band (XLA's number is exact for the compiled signature; the band covers
  allocator slop only).
- Otherwise the static fallback sums var sizes with -1 batch dims bound
  to `batch_size`. On TPU that is a lower bound on HBM residency (XLA
  buffer assignment reuses/fuses aggressively, and rematerialization
  trades memory for FLOPs), so like the reference the result is a wide
  ±30% band.
"""
import numpy as np

__all__ = ['memory_usage']

_DTYPE_SIZE = {
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'int8': 1, 'uint8': 1, 'int16': 2, 'int32': 4, 'int64': 8, 'bool': 1,
}

_MB = 1024.0 ** 2


def _compiled_peak_mb(program, batch_size):
    """XLA-compiled peak (MB) for this program at this batch size, or
    None when no matching executable has been analyzed yet."""
    try:
        from .. import analysis
        # 'run' records only: a fused entry's peak covers the WHOLE
        # k-step scan (stacked feeds included) — not one iteration
        rec = analysis.lookup(program, kind='run')
        if rec is None or rec.feed_batch not in (None, int(batch_size)):
            # a compiled record at a DIFFERENT batch must not be scaled —
            # activations scale with batch but params don't; fall back
            return None
        if rec.peak_bytes is None:
            rec.materialize_memory()
        if rec.peak_bytes:
            return rec.peak_bytes / _MB
    except Exception:                   # noqa: BLE001 — estimator only
        return None
    return None


def _static_estimate_mb(program, batch_size):
    total = 0
    for block in program.blocks:
        for var in block.vars.values():
            shape = getattr(var, 'shape', None)
            if not shape:
                continue
            size = _DTYPE_SIZE.get(str(var.dtype), 4)
            n = 1
            for d in shape:
                if d is None or d < 0:
                    d = batch_size
                n *= int(d)
            total += n * size
    return total / _MB


def memory_usage(program, batch_size):
    """Returns (low_mb, high_mb): estimated memory range for one iteration
    at `batch_size`. Backed by XLA buffer-assignment numbers when the
    program has a compiled executable in this process (±10% band), else
    the reference's static ±30% band."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    peak_mb = _compiled_peak_mb(program, batch_size)
    if peak_mb is not None:
        return peak_mb * 0.9, peak_mb * 1.1
    mb = _static_estimate_mb(program, batch_size)
    return mb * 0.7, mb * 1.3
