"""Automatic mixed precision (bf16) — TPU analog of the reference fp16 path.

Reference: paddle/contrib/float16/float16_transpiler.py:66 (program rewrite
casting ops to fp16) and the fluid AMP design (white/black op lists + a
decorated optimizer). TPU redesign:

- dtype policy is **bf16**, which shares fp32's exponent range — so no loss
  scaling machinery is required (the reference's fp16 needs it; bf16 doesn't).
- instead of splicing cast ops into the program (which would materialize
  bf16 copies), `rewrite_program_bf16` marks MXU-heavy ops with an attr that
  their lowering consults (core/amp.py): inputs are cast inside the traced
  function and XLA fuses the casts into the surrounding HLO, accumulation
  stays fp32 via preferred_element_type.
- parameters remain fp32 in the Scope: master weights for free.

Usage::

    opt = fluid.optimizer.Adam(1e-4)
    opt = fluid.contrib.mixed_precision.decorate(opt)
    opt.minimize(avg_loss)           # rewrites the program + appends backward

or rewrite an existing (inference) program in place::

    fluid.contrib.mixed_precision.rewrite_program_bf16(main_program)
"""
from ..core.amp import AMP_ATTR, AMP_KEEP_ATTR

__all__ = ['AutoMixedPrecisionLists', 'rewrite_program_bf16', 'decorate',
           'OptimizerWithMixedPrecision']

# Ops whose FLOPs dominate and that are numerically safe in bf16 with fp32
# accumulation: they run on the MXU.
WHITE_LIST = {
    'mul', 'matmul', 'fc', 'flash_attention', 'fused_ffn_tail',
    'conv2d', 'depthwise_conv2d', 'conv2d_transpose',
    'depthwise_conv2d_transpose', 'conv3d', 'conv3d_transpose',
}

# Numerically sensitive ops that must stay fp32 (kept for API parity /
# custom-list validation; nothing ever casts them in this design).
BLACK_LIST = {
    'softmax', 'softmax_with_cross_entropy', 'cross_entropy',
    'sigmoid_cross_entropy_with_logits', 'layer_norm', 'batch_norm',
    'group_norm', 'mean', 'reduce_mean', 'reduce_sum', 'sum', 'exp', 'log',
}


class AutoMixedPrecisionLists(object):
    """White/black op-type lists controlling which ops compute in bf16
    (reference fluid AMP AutoMixedPrecisionLists)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        for t in (custom_white_list or []):
            if t in self.black_list:
                raise ValueError(
                    "op %r is in both custom white list and black list" % t)
            self.white_list.add(t)
        for t in (custom_black_list or []):
            self.white_list.discard(t)
            self.black_list.add(t)


KEEP_ACTIVATION_OPS = {'conv2d', 'depthwise_conv2d', 'batch_norm'}


def rewrite_program_bf16(program, amp_lists=None, dtype='bfloat16',
                         keep_bf16_activations=False):
    """Mark every white-listed op in `program` to compute in `dtype`.

    The mark (core/amp.py AMP_ATTR) makes the op's lowering cast its fp32
    compute inputs to bf16; accumulation and outputs stay fp32 — unless
    keep_bf16_activations is set, in which case conv/bn outputs STAY bf16
    (dtype-preserving ops like relu/pool propagate it), halving activation
    HBM traffic for conv nets; dense heads/losses still compute f32
    because mul/softmax cast back.
    """
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    n = 0
    for block in program.blocks:
        for op in block.ops:
            if op.type in amp_lists.white_list:
                op.attrs[AMP_ATTR] = dtype
                n += 1
            if keep_bf16_activations and op.type in KEEP_ACTIVATION_OPS \
                    and op.type not in amp_lists.black_list:
                op.attrs[AMP_ATTR] = dtype
                op.attrs[AMP_KEEP_ATTR] = True
    program._bump_version()
    return n


class OptimizerWithMixedPrecision(object):
    """Optimizer wrapper: rewrites the program for bf16 compute, then runs
    the wrapped optimizer on the (fp32 master) parameters.

    bf16 needs no loss scaling; `init_loss_scaling` other than 1.0 is
    rejected rather than silently mis-applied (scaling the loss without an
    unscale step would multiply the effective learning rate).
    """

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, dtype='bfloat16',
                 keep_bf16_activations=False):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        if use_dynamic_loss_scaling or float(init_loss_scaling) != 1.0:
            # bf16 has fp32's exponent range; loss scaling is an fp16
            # artifact. Accept-and-ignore would hide a config error.
            raise ValueError(
                "loss scaling is unnecessary for bf16 (same exponent range "
                "as fp32); use init_loss_scaling=1.0 and "
                "use_dynamic_loss_scaling=False")
        self._dtype = dtype
        self._keep_acts = keep_bf16_activations

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        rewrite_program_bf16(program, self._amp_lists, self._dtype,
                             keep_bf16_activations=self._keep_acts)
        return self._optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)

    def backward(self, *args, **kwargs):
        return self._optimizer.backward(*args, **kwargs)

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             use_dynamic_loss_scaling=False, keep_bf16_activations=False):
    """Wrap `optimizer` for bf16 mixed-precision training (reference
    fluid.contrib.mixed_precision.decorate). keep_bf16_activations keeps
    conv/bn outputs bf16 in HBM (conv-net bandwidth mode)."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists=amp_lists, init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        keep_bf16_activations=keep_bf16_activations)
