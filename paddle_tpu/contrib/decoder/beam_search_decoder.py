"""Training/beam-search decoder API (reference
python/paddle/fluid/contrib/decoder/beam_search_decoder.py: InitState :43,
StateCell :159, TrainingDecoder :384, BeamSearchDecoder :523).

The API is kept; the decode dataflow is TPU-native: the reference shrinks
beams through LoD and re-expands states with sequence_expand inside a
While; here beams live in a DENSE [batch*beam] layout (dead lanes masked
at -1e9, the ops/control_flow_ops.py beam_search design), states are
carried as parent-block vars re-gathered by parent_idx each step, and the
loop is a While with max_trip_count so the whole decode compiles to one
bounded XLA loop.
"""
import contextlib

import numpy as np

from ... import layers
from ...layers import control_flow
from ...param_attr import ParamAttr

__all__ = ['InitState', 'StateCell', 'TrainingDecoder',
           'BeamSearchDecoder']


class _DecoderType(object):
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial hidden state (reference :43): an explicit variable, or a
    fill_constant_batch_size_like over `init_boot`."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the shape of '
                'InitState.')
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value,
                shape=shape or [-1] + list(init_boot.shape[1:]),
                dtype=dtype)
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell(object):
    """Stores the decoder's recurrent state(s) and the updater computing
    the next state from the current inputs (reference :159).

        cell = StateCell(inputs={'x': None}, states={'h': h_init},
                         out_state='h')

        @cell.state_updater
        def updater(cell):
            h_prev = cell.get_state('h')
            x = cell.get_input('x')
            cell.set_state('h', layers.fc([x, h_prev], ...))
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._out_state = out_state
        self._cur_states = {}
        self._updater = None
        self._decoder = None

    # -- decoder binding ---------------------------------------------------
    def _enter_decoder(self, decoder):
        self._decoder = decoder
        self._cur_states = {}
        if decoder.type == _DecoderType.TRAINING:
            self._mems = {
                n: decoder.dynamic_rnn.memory(
                    init=st.value, need_reorder=st.need_reorder)
                for n, st in self._init_states.items()}
            self._cur_states = dict(self._mems)
        else:
            # beam mode: states are parent-block vars assigned per step
            self._cur_states = {n: st.value
                                for n, st in self._init_states.items()}
        self._pending = {}

    def _leave_decoder(self, decoder):
        self._decoder = None

    # -- user API ----------------------------------------------------------
    def state_updater(self, updater):
        self._updater = updater
        return updater

    def get_state(self, name):
        if name in self._pending:
            return self._pending[name]
        return self._cur_states[name]

    def get_input(self, name):
        if self._cur_inputs.get(name) is None:
            raise ValueError('input %r not provided to compute_state'
                             % name)
        return self._cur_inputs[name]

    def set_state(self, name, value):
        self._pending[name] = value

    def compute_state(self, inputs):
        self._cur_inputs = dict(inputs)
        self._pending = {}
        if self._updater is None:
            raise ValueError('no state_updater registered')
        self._updater(self)

    def update_states(self):
        """Commit pending states (training mode: rnn.update_memory)."""
        if self._decoder is not None and \
                self._decoder.type == _DecoderType.TRAINING:
            for n, new in self._pending.items():
                self._decoder.dynamic_rnn.update_memory(self._mems[n], new)
                self._cur_states[n] = new
        else:
            self._cur_states.update(self._pending)
        self._pending = {}

    def out_state(self):
        return self.get_state(self._out_state)


class TrainingDecoder(object):
    """Teacher-forced decoder over a DynamicRNN (reference :384)."""

    def __init__(self, state_cell, name=None):
        self._rnn = control_flow.DynamicRNN(name=name)
        self._state_cell = state_cell
        self._type = _DecoderType.TRAINING
        self._outputs = []

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._rnn

    @property
    def type(self):
        return self._type

    @contextlib.contextmanager
    def block(self):
        with self._rnn.block():
            self._state_cell._enter_decoder(self)
            yield
            self._state_cell._leave_decoder(self)

    def step_input(self, x):
        return self._rnn.step_input(x)

    def static_input(self, x):
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        return self._rnn(*args, **kwargs)


class BeamSearchDecoder(object):
    """Beam-search decode loop (reference :523). Dense-beam TPU layout:
    init_ids/init_scores are [batch*beam, 1] (lane 0 of each instance
    live, other lanes at -1e9 — use `make_initial_beams` for the standard
    start state).

        decoder = BeamSearchDecoder(cell, init_ids, init_scores,
                                    target_dict_dim=V, word_dim=D,
                                    max_len=T, beam_size=B, end_id=E)
        decoder.decode()
        ids, scores = decoder()     # [batch, B, T], [batch, B]
    """

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None, embedding_param_attr=None,
                 score_param_attr=None, score_bias_attr=None):
        self._state_cell = state_cell
        self._type = _DecoderType.BEAM_SEARCH
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._v = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk = min(int(topk_size), int(target_dict_dim))
        self._sparse_emb = sparse_emb
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._emb_attr = embedding_param_attr
        self._score_w_attr = score_param_attr
        self._score_b_attr = score_bias_attr
        self._decoded = None

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def type(self):
        return self._type

    @staticmethod
    def make_initial_beams(batch_size, beam_size, start_id):
        """(init_ids [batch*beam, 1] int64, init_scores [batch*beam, 1]):
        every lane starts at start_id; only lane 0 is live."""
        ids = np.full((batch_size * beam_size, 1), start_id, np.int64)
        scores = np.full((batch_size * beam_size, 1), -1e9, np.float32)
        scores[::beam_size] = 0.0
        return ids, scores

    def decode(self):
        cell = self._state_cell
        cell._enter_decoder(self)
        max_len = self._max_len

        counter = layers.fill_constant(shape=[1], dtype='int64', value=0)
        limit = layers.fill_constant(shape=[1], dtype='int64',
                                     value=max_len)
        ids_arr = control_flow.create_array('int64', capacity=max_len)
        sc_arr = control_flow.create_array('float32', capacity=max_len)
        par_arr = control_flow.create_array('int32', capacity=max_len)

        # carried prev ids/scores + states as parent-block vars
        prev_ids = layers.assign(self._init_ids)
        prev_scores = layers.assign(self._init_scores)
        state_vars = {n: layers.assign(cell._cur_states[n])
                      for n in cell._state_names}

        cond = control_flow.less_than(counter, limit)
        loop = control_flow.While(cond, max_trip_count=max_len)
        with loop.block():
            emb = layers.embedding(
                prev_ids, size=[self._v, self._word_dim],
                is_sparse=self._sparse_emb, param_attr=self._emb_attr)
            emb = layers.reshape(emb, [-1, self._word_dim])
            feed = {}
            for name in cell._inputs:
                feed.setdefault(name, emb)
            for name, var in self._input_var_dict.items():
                feed[name] = var
            cell._cur_states = dict(state_vars)
            cell.compute_state(inputs=feed)
            out_state = cell.out_state()
            probs = layers.fc(out_state, size=self._v, act='softmax',
                              param_attr=self._score_w_attr,
                              bias_attr=self._score_b_attr)
            topk_scores, topk_ids = layers.topk(probs, k=self._topk)
            acc = layers.elementwise_add(
                layers.log(topk_scores), prev_scores)
            sid, ssc, parent = control_flow.beam_search(
                prev_ids, prev_scores, topk_ids, acc,
                beam_size=self._beam_size, end_id=self._end_id, level=0)
            # commit: arrays record this step; states re-gathered by parent
            control_flow.array_write(sid, counter, ids_arr)
            control_flow.array_write(ssc, counter, sc_arr)
            control_flow.array_write(parent, counter, par_arr)
            cell.update_states()
            for n, var in state_vars.items():
                layers.assign(layers.gather(cell._cur_states[n], parent),
                              var)
            layers.assign(sid, prev_ids)
            layers.assign(ssc, prev_scores)
            layers.increment(counter, value=1, in_place=True)
            control_flow.less_than(counter, limit, cond=cond)
        cell._leave_decoder(self)
        self._decoded = (ids_arr, sc_arr, par_arr)

    def __call__(self):
        if self._decoded is None:
            raise ValueError('call decode() before the decoder')
        ids_arr, sc_arr, par_arr = self._decoded
        return layers.beam_search_decode(
            ids_arr, sc_arr, par_arr, beam_size=self._beam_size,
            end_id=self._end_id)
