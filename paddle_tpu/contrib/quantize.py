"""Quantization-aware training transpiler (reference
python/paddle/fluid/contrib/quantize/quantize_transpiler.py
QuantizeTranspiler + contrib/slim/quantization/quantization_pass.py).

Program rewrite: before every quantizable op (mul / conv2d /
depthwise_conv2d), each input is routed through fake_quantize ->
fake_dequantize, simulating int-N precision while training stays fp32.
Gradients flow via the straight-through estimator inside the quant ops
(ops/quant_ops.py), so the fp32 master weights keep training — the same
net effect as the reference routing grad ops around the quant pair.
"""
import numpy as np

from ..framework import default_main_program, default_startup_program
from ..core.types import VarType

__all__ = ['QuantizeTranspiler']

_QUANTIZABLE_OP_TYPES = ('mul', 'conv2d', 'depthwise_conv2d')


def _quantized_var_name(name):
    return "%s.quantized" % name


def _dequantized_var_name(name):
    return "%s.dequantized" % name


def _scale_name(name):
    return "%s.scale" % name


class QuantizeTranspiler(object):
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type='abs_max',
                 weight_quantize_type='abs_max', window_size=10000):
        quant_types = ('abs_max', 'range_abs_max')
        if weight_quantize_type not in quant_types:
            raise ValueError("Unknown weight_quantize_type: %r"
                             % (weight_quantize_type,))
        if activation_quantize_type not in quant_types:
            raise ValueError("Unknown activation_quantize_type: %r"
                             % (activation_quantize_type,))
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size
        self.is_test = False

    # ------------------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake quant/dequant pairs in front of quantizable ops
        (reference training_transpile). Must run BEFORE
        optimizer.minimize: the backward meta-op then differentiates the
        rewritten forward, giving STE gradients to the fp32 weights."""
        self.is_test = False
        program = program if program is not None else \
            default_main_program()
        startup = startup_program if startup_program is not None else \
            default_startup_program()

        if any(op.type == 'backward'
               for block in program.blocks for op in block.ops):
            raise ValueError(
                "QuantizeTranspiler.training_transpile must be applied "
                "before optimizer.minimize()/append_backward()")

        params = set(p.name for p in program.all_parameters())
        for block in program.blocks:   # sub-blocks too (While/cond bodies)
            dequanted = {}
            i = 0
            while i < len(block.ops):
                op = block.ops[i]
                if op.type in _QUANTIZABLE_OP_TYPES:
                    for name in list(op.input_arg_names):
                        if name not in dequanted:
                            is_w = name in params
                            bits = self.weight_bits if is_w else \
                                self.activation_bits
                            qtype = self.weight_quantize_type if is_w \
                                else self.activation_quantize_type
                            n_ins = self._insert_quant_dequant(
                                program, startup, block, i, name, bits,
                                qtype)
                            dequanted[name] = _dequantized_var_name(name)
                            i += n_ins
                        op._rename_input(name, dequanted[name])
                i += 1
        program._bump_version()
        return program

    def _insert_quant_dequant(self, program, startup, block, idx, name,
                              bits, qtype):
        """Insert the pair at block.ops[idx]; returns #ops inserted."""
        src = block._find_var_recursive(name)
        qname = _quantized_var_name(name)
        dqname = _dequantized_var_name(name)
        sname = _scale_name(name)
        qv = block.create_var(name=qname, dtype=src.dtype,
                              shape=src.shape)
        sv = block.create_var(name=sname, dtype=src.dtype, shape=(1,))
        dqv = block.create_var(name=dqname, dtype=src.dtype,
                               shape=src.shape)
        bin_cnt = (1 << (bits - 1)) - 1
        n = 0
        if qtype == 'abs_max':
            block._insert_op(
                idx, type='fake_quantize_abs_max', inputs={'X': [name]},
                outputs={'Out': [qname], 'OutScale': [sname]},
                attrs={'bit_length': bits})
            n += 1
        else:
            n += self._insert_range_quant(program, startup, block, idx,
                                          name, qname, sname, bits)
        block._insert_op(
            idx + n, type='fake_dequantize_max_abs',
            inputs={'X': [qname], 'Scale': [sname]},
            outputs={'Out': [dqname]},
            attrs={'max_range': float(bin_cnt)})
        return n + 1

    def _insert_range_quant(self, program, startup, block, idx, name,
                            qname, sname, bits):
        """range_abs_max needs persistable scale state + a step counter
        (reference _create_global_step + InScale/OutScales plumbing)."""
        in_scale = block.create_var(
            name="%s.in_scale" % name, dtype='float32', shape=(1,),
            persistable=True)
        scales = block.create_var(
            name="%s.scales" % name, dtype='float32',
            shape=(self.window_size,), persistable=True)
        it = block.create_var(
            name="%s.iter" % name, dtype='int64', shape=(1,),
            persistable=True)
        # init state in the startup program
        sgb = startup.global_block()
        for v, value, dtype, shape in (
                (in_scale, 1e-8, 'float32', (1,)),
                (scales, 0.0, 'float32', (self.window_size,)),
                (it, 0, 'int64', (1,))):
            sgb.create_var(name=v.name, dtype=dtype, shape=shape,
                           persistable=True)
            sgb.append_op(type='fill_constant', outputs={'Out': [v.name]},
                          attrs={'shape': list(shape), 'dtype': dtype,
                                 'value': value})
        # quantize with the 0-based step, then advance the counter
        block._insert_op(
            idx, type='fake_quantize_range_abs_max',
            inputs={'X': [name], 'InScale': [in_scale.name],
                    'Iter': [it.name], 'OutScales': [scales.name]},
            outputs={'Out': [qname], 'OutScale': [in_scale.name],
                     'OutScales': [scales.name]},
            attrs={'bit_length': bits, 'window_size': self.window_size,
                   'is_test': False})
        # expose the fresh scale under the dequant's expected name
        block._insert_op(
            idx + 1, type='assign', inputs={'X': [in_scale.name]},
            outputs={'Out': [sname]})
        block._insert_op(
            idx + 2, type='increment', inputs={'X': [it.name]},
            outputs={'Out': [it.name]}, attrs={'step': 1.0})
        return 3

    # ------------------------------------------------------------------
    def freeze_program(self, program, place=None, scope=None):
        """Inference rewrite (reference freeze_program, simplified for the
        static-LoD/XLA design): switch range_abs_max quant ops to is_test
        (use the learned running scale, no state updates) and strip the
        training-only state machinery — the step-counter increments and
        window buffers — so inference is idempotent. The quant/dequant
        simulation stays in the graph, so the exported model reproduces
        quantized numerics exactly."""
        iter_names = set()
        for block in program.blocks:
            for op in block.ops:
                if op.type == 'fake_quantize_range_abs_max':
                    op.set_attr('is_test', True)
                    # is_test reads InScale only
                    op.outputs.pop('OutScales', None)
                    op.inputs.pop('OutScales', None)
                    for n in op.inputs.pop('Iter', []):
                        iter_names.add(n)
        for block in program.blocks:
            block.ops = [
                op for op in block.ops
                if not (op.type == 'increment'
                        and op.output_arg_names
                        and op.output_arg_names[0] in iter_names)]
        program._is_test = True
        program._bump_version()
        return program

    def convert_to_int8_program(self, program, place=None, scope=None):
        """Weight-only int8 INFERENCE rewrite: the executing program reads
        int8 weight blobs (int8(weight)/fp32(act) — int8 storage and HBM
        traffic, fp32 matmuls; XLA fuses the dequant cast into the GEMM).

        For each weight a quantizable op consumes, the fp32 param is
        replaced by '<w>.int8' (int8 persistable) + '<w>.int8_scale' and a
        `fake_dequantize_max_abs` op rematerializes fp32 just-in-time —
        the existing ops/quant_ops.py pipeline, now fed by a REAL int8
        blob. Works on a frozen QAT program (trained quant numerics) or a
        plain inference program (plain abs-max PTQ of the weights).
        save_inference_model then exports the int8 blobs and DROPS the
        unused fp32 originals, so the artifact shrinks ~4x on the
        quantized weights; the loaded program serves through the
        Predictor/ServingEngine warmup path with zero recompiles like any
        other program. Returns {param_name: (int8 blob, scale)}."""
        from ..executor import global_scope
        from .. import monitor
        scope = scope if scope is not None else global_scope()
        blobs = self.convert_to_int8(program, place=place, scope=scope)
        if not blobs:
            return blobs
        bin_cnt = (1 << (self.weight_bits - 1)) - 1
        for block in program.blocks:
            i = 0
            while i < len(block.ops):
                op = block.ops[i]
                if op.type in _QUANTIZABLE_OP_TYPES:
                    for name in list(op.input_arg_names):
                        base = name[:-len('.dequantized')] \
                            if name.endswith('.dequantized') else name
                        if base not in blobs:
                            continue
                        w8, scale = blobs[base]
                        sarr = np.asarray(scale, 'float32').reshape(-1)
                        w8n, sn, dqn = (base + '.int8',
                                        base + '.int8_scale',
                                        base + '.int8_deq')
                        if block._find_var_recursive(w8n) is None:
                            block.create_var(name=w8n, shape=w8.shape,
                                             dtype='int8', persistable=True)
                            block.create_var(name=sn, shape=sarr.shape,
                                             dtype='float32',
                                             persistable=True)
                            block.create_var(name=dqn, shape=w8.shape,
                                             dtype='float32')
                            scope.set(w8n, w8)
                            scope.set(sn, sarr)
                            block._insert_op(
                                i, type='fake_dequantize_max_abs',
                                inputs={'X': [w8n], 'Scale': [sn]},
                                outputs={'Out': [dqn]},
                                attrs={'max_range': float(bin_cnt)})
                            i += 1
                        op._rename_input(name, dqn)
                i += 1
        # the weight's old fake-quant chain (ending in the '.dequantized'
        # name nothing consumes after the rename) is left to XLA DCE at
        # lowering and to _prune on export — no graph surgery needed
        program._bump_version()
        monitor.inc('quantized_program_total',
                    labels={'kind': 'weight_only_int8'})
        return blobs

    def convert_to_int8(self, program, place=None, scope=None):
        """Quantize the weights of quantizable ops to int8 (reference
        convert_to_int8): w_int8 = round(w / scale * bin_cnt). 2-D
        (fc/mul) weights quantize PER OUTPUT CHANNEL — one max-abs scale
        per column, so a single outlier column no longer sets every
        column's quantization step (the per-tensor bound was ~2% on the
        BERT rank-3 fc's; per-channel tightens it under 0.5%) — other
        ranks keep the per-tensor scale. Returns {param_name:
        (int8 ndarray, scale)} where scale is a float (per-tensor) or a
        [out_channels] float32 vector; the scale travels with the blob so
        consumers can reconstruct w ≈ int8 * scale / bin_cnt. Biases and
        params of non-quantizable ops are left fp32 (training never
        simulated their quantization)."""
        from ..executor import global_scope
        scope = scope if scope is not None else global_scope()
        # only params consumed by quantizable ops (their quant pair was
        # trained); note the transpiled program feeds them via the
        # '.dequantized' alias, so match on the original name
        quantized_params = set()
        params = set(p.name for p in program.all_parameters())
        for block in program.blocks:
            for op in block.ops:
                if op.type in _QUANTIZABLE_OP_TYPES:
                    for n in op.input_arg_names:
                        base = n[:-len('.dequantized')] \
                            if n.endswith('.dequantized') else n
                        if base in params:
                            quantized_params.add(base)
        out = {}
        bin_cnt = (1 << (self.weight_bits - 1)) - 1
        for name in sorted(quantized_params):
            w = scope.get(name)
            if w is None:
                continue
            w = np.asarray(w)
            if w.ndim == 2:
                # per-output-channel: one scale per column of [in, out]
                scale = np.max(np.abs(w), axis=0).astype('float32')
                scale[scale == 0.0] = 1.0
                blob = np.clip(np.round(w / scale[None, :] * bin_cnt),
                               -bin_cnt - 1, bin_cnt).astype(np.int8)
                out[name] = (blob, scale)
                continue
            scale = float(np.max(np.abs(w))) or 1.0
            blob = np.clip(np.round(w / scale * bin_cnt),
                           -bin_cnt - 1, bin_cnt).astype(np.int8)
            out[name] = (blob, scale)
        return out


def calibrate_scales(exe, program, scope, feed_batches, var_names):
    """Post-training int8 calibration: run `program` over the calibration
    `feed_batches` and collect the running abs-max of each variable in
    `var_names`, returning {name: scale} suitable for the int8
    `quantize`/`dequantize` ops (Scale = bin_max / abs_max convention left
    to the caller). The TPU analog of reference
    contrib/int8_inference/utility.py's sampling pass."""
    maxes = {n: 0.0 for n in var_names}
    for feed in feed_batches:
        outs = exe.run(program, feed=feed, fetch_list=list(var_names),
                       scope=scope)
        for n, v in zip(var_names, outs):
            m = float(np.max(np.abs(np.asarray(v))))
            if m > maxes[n]:
                maxes[n] = m
    return {n: (m if m > 0 else 1.0) for n, m in maxes.items()}


def post_training_quantize(exe, program, scope, feed_batches,
                           weight_bits=8):
    """Post-training int8 quantization of an INFERENCE program (reference
    contrib/int8_inference/utility.py + the mkldnn quantize/dequantize op
    pipeline): calibrate activation scales over `feed_batches`, quantize
    fc/mul weights to int8 blobs in the scope, and rewrite each eligible
    mul op into quantize(int8) -> quantized_matmul(int8 x int8 -> int32 ->
    rescale). Returns the list of rewritten op indices.

    Eligible: mul ops whose Y is a 2-D parameter and whose X flattens to
    rows at x_num_col_dims (the fc hot path — including the rank-3
    [B, L, d] fc's of BERT/transformer stacks, x_num_col_dims=2). Other
    ops stay fp32 — mixed int8/fp32 inference like the reference's
    quantize/dequantize sandwiches.
    """
    from .. import monitor
    block = program.global_block()
    bin_max = float((1 << (weight_bits - 1)) - 1)      # 127

    # 1) find eligible muls and the activation vars to calibrate
    params = set(p.name for p in program.all_parameters())
    targets = []
    for idx, op in enumerate(block.ops):
        if op.type != 'mul':
            continue
        xnc = int(op.attr('x_num_col_dims', 1))
        x_name = op.input('X')[0]
        w_name = op.input('Y')[0]
        if w_name not in params or int(op.attr('y_num_col_dims', 1)) != 1:
            continue
        xv = block._find_var_recursive(x_name)
        if xv is not None and xv.shape and len(xv.shape) != xnc + 1:
            continue
        wv = block._find_var_recursive(w_name)
        if wv is not None and wv.shape and len(wv.shape) != 2:
            continue
        targets.append((idx, op, x_name, w_name))
    if not targets:
        return []

    # 2) calibrate activation abs-max
    act_names = sorted({x for _, _, x, _ in targets})
    maxes = calibrate_scales(exe, program, scope, feed_batches, act_names)

    # 3) quantize weights offline + rewrite ops (reverse order keeps
    # earlier indices valid while inserting). Weight scales are PER
    # OUTPUT CHANNEL (max-abs per column of the [in, out] weight): an
    # outlier column no longer dictates every column's step — measured
    # parity on the BERT rank-3 fc's tightens from <2% to <0.5%.
    for idx, op, x_name, w_name in reversed(targets):
        w = np.asarray(scope.get(w_name))
        w_absmax = np.max(np.abs(w), axis=0)
        w_absmax[w_absmax == 0.0] = 1.0
        sw = (bin_max / w_absmax).astype('float32')        # [out]
        w8 = np.clip(np.round(w * sw[None, :]), -bin_max - 1,
                     bin_max).astype(np.int8)
        w8_name = w_name + '.int8'
        block.create_var(name=w8_name, shape=w8.shape, dtype='int8',
                         persistable=True)
        scope.set(w8_name, w8)
        sx = bin_max / maxes[x_name]
        x8_name = x_name + '.int8'
        xv = block._find_var_recursive(x_name)
        block.create_var(name=x8_name,
                         shape=tuple(xv.shape) if xv is not None and
                         xv.shape else (-1,),
                         dtype='int8')
        out_name = op.output('Out')[0]
        op.type = 'quantized_matmul'
        op.inputs = {'X': [x8_name], 'Y': [w8_name]}
        op.outputs = {'Out': [out_name]}
        op.attrs = {'scale_x': sx,
                    'scale_y': [float(v) for v in sw]}
        block._insert_op(
            idx, type='quantize', inputs={'Input': [x_name]},
            outputs={'Output': [x8_name]},
            attrs={'Scale': sx, 'is_negative_input': True})
    program._bump_version()
    monitor.inc('quantized_program_total', labels={'kind': 'ptq_int8'})
    # indices shift with each insertion: report the FINAL positions
    return [i for i, o in enumerate(block.ops)
            if o.type == 'quantized_matmul']
