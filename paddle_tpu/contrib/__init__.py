"""Contrib subpackage (reference python/paddle/fluid/contrib/).

Currently: mixed_precision (the TPU bf16 analog of
reference paddle/contrib/float16/float16_transpiler.py), slim quantization.
"""
from . import mixed_precision  # noqa: F401
from . import quantize  # noqa: F401
from . import slim  # noqa: F401
from . import memory_usage_calc  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from . import op_frequence  # noqa: F401
from .op_frequence import op_freq_statistic, top_offenders  # noqa: F401
from . import hdfs_utils  # noqa: F401
from . import decoder  # noqa: F401
from . import float16  # noqa: F401
from . import reader  # noqa: F401
from .quantize import QuantizeTranspiler  # noqa: F401
from . import trainer  # noqa: F401
from .trainer import (Trainer, Inferencer, BeginEpochEvent,  # noqa: F401
                      EndEpochEvent, BeginStepEvent, EndStepEvent)
