"""Pruners + pruning strategies.

Reference: slim/prune/pruner.py — MagnitudePruner (threshold mask),
RatioPruner (top-|w| ratio mask) — and prune strategies driven by the
CompressPass callbacks.

TPU-native additions: masks are computed in numpy over scope state (the
executor re-lowers from scope each run, so updated arrays are simply picked
up; no graph surgery needed for soft pruning), and ChannelPruner performs
REAL structured pruning — conv output channels are removed physically,
with dependent vars (conv bias, batch_norm stats, the next conv's input
channels, the first FC's rows) resized to match, shrinking the exported
parameter count.
"""
import numpy as np

__all__ = ['Pruner', 'MagnitudePruner', 'RatioPruner', 'PruneStrategy',
           'ChannelPruner']

from .core import Strategy


class Pruner(object):
    """mask = pruner.prune(param_array): 1 keeps, 0 prunes (reference
    slim/prune/pruner.py:21)."""

    def prune(self, param):
        raise NotImplementedError


class MagnitudePruner(Pruner):
    """Zero weights with |w| < threshold (reference pruner.py:33)."""

    def __init__(self, threshold):
        self.threshold = float(threshold)

    def prune(self, param):
        return (np.abs(param) >= self.threshold).astype(param.dtype)


class RatioPruner(Pruner):
    """Keep the largest-|w| `ratio` fraction per parameter (reference
    pruner.py:51: ratio=0.4 keeps 40%, prunes 60%)."""

    def __init__(self, ratios=None):
        self.ratios = dict(ratios or {})

    def prune(self, param, ratio=None):
        if ratio is None:
            ratio = self.ratios.get('*', 1.0)
        if ratio >= 1.0:
            return np.ones_like(param)
        k = max(int(ratio * param.size), 1)
        flat = np.abs(param).reshape(-1)
        thresh = np.partition(flat, -k)[-k]
        return (np.abs(param) >= thresh).astype(param.dtype)


class PruneStrategy(Strategy):
    """Applies pruner masks to named parameters in the scope after every
    batch while active (masks are recomputed each epoch begin, frozen
    within the epoch so pruned weights stay zero through optimizer
    updates)."""

    def __init__(self, pruner, params=None, ratios=None, start_epoch=0,
                 end_epoch=1000):
        super(PruneStrategy, self).__init__(start_epoch, end_epoch)
        self._pruner = pruner
        self._params = list(params or [])
        self._ratios = dict(ratios or {})
        self._masks = {}

    def _param_names(self, context):
        if self._params:
            return self._params
        return [p.name for p in context.train_program.all_parameters()]

    def on_epoch_begin(self, context):
        self._masks = {}
        for name in self._param_names(context):
            value = context.scope.get(name)
            if value is None:
                continue
            arr = np.asarray(value)
            if name in self._ratios and isinstance(self._pruner, RatioPruner):
                mask = self._pruner.prune(arr, self._ratios[name])
            else:
                mask = self._pruner.prune(arr)
            self._masks[name] = mask
        self._apply(context)

    def on_batch_end(self, context):
        self._apply(context)

    def _apply(self, context):
        for name, mask in self._masks.items():
            value = context.scope.get(name)
            if value is not None:
                context.scope.set(name, np.asarray(value) * mask)

    def sparsity(self, context):
        """Fraction of pruned (zero-masked) weights across masked params."""
        total = kept = 0
        for mask in self._masks.values():
            total += mask.size
            kept += int(mask.sum())
        return 1.0 - (kept / total if total else 1.0)


# ---------------------------------------------------------------------------
# structured channel pruning
# ---------------------------------------------------------------------------

_CHANNEL_KEEPING = {'relu', 'relu6', 'sigmoid', 'tanh', 'pool2d', 'dropout',
                    'elementwise_add', 'scale', 'leaky_relu'}


class ChannelPruner(object):
    """Physically remove conv output channels with the lowest filter L1
    norms (structured filter pruning), resizing dependent vars:

    - the conv Filter [O,I,h,w] -> [O',I,h,w] and its bias [O] -> [O'];
    - batch_norm Scale/Bias/Mean/Variance over the pruned channels;
    - the NEXT conv's Filter input channels [O2,O,h,w] -> [O2,O',h,w];
    - the first FC's weight rows (NCHW-flattened: channel c owns the
      contiguous row block [c*H*W, (c+1)*H*W)).

    The executor recompiles from the rewritten scope/program, so training
    continues (finetune) on the smaller network directly — the TPU-native
    analog of reference slim channel pruning on IrGraph.
    """

    def __init__(self, program, scope):
        self._program = program
        self._scope = scope

    def _ops(self):
        return list(self._program.global_block().ops)

    def _consumers(self, var_name):
        out = []
        for op in self._ops():
            if var_name in op.input_arg_names:
                out.append(op)
        return out

    def _resize(self, name, new_arr, indexer=None):
        old = self._scope.get(name)
        old_shape = None if old is None else tuple(np.asarray(old).shape)
        self._scope.set(name, new_arr)
        var = self._program.global_block()._find_var_recursive(name)
        if var is not None:
            var.shape = tuple(new_arr.shape)
        if indexer is None or old_shape is None:
            return
        # optimizer accumulators (moments, velocities, ...) are named
        # '<param>_<slot>' and share the parameter's shape — resize them
        # identically so finetuning continues on the pruned network
        prefix = name + '_'
        for other in list(self._scope.names()):
            if not other.startswith(prefix):
                continue
            val = self._scope.get(other)
            if val is None or tuple(np.asarray(val).shape) != old_shape:
                continue
            self._scope.set(other, indexer(np.asarray(val)))
            ovar = self._program.global_block()._find_var_recursive(other)
            if ovar is not None:
                ovar.shape = tuple(new_arr.shape)

    def prune_conv(self, filter_name, keep_ratio):
        """Prune the conv2d whose Filter parameter is `filter_name` to
        round(O * keep_ratio) output channels; returns kept indices."""
        w = np.asarray(self._scope.get(filter_name))
        o = w.shape[0]
        keep_n = max(int(round(o * keep_ratio)), 1)
        norms = np.abs(w).reshape(o, -1).sum(axis=1)
        keep = np.sort(np.argsort(norms)[-keep_n:])
        self._resize(filter_name, w[keep], indexer=lambda a: a[keep])

        conv_op = None
        for op in self._ops():
            if op.type in ('conv2d', 'depthwise_conv2d') and \
                    filter_name in op.input('Filter'):
                conv_op = op
                break
        if conv_op is None:
            raise ValueError("no conv2d consumes Filter %r" % filter_name)
        out_name = conv_op.output('Output')[0]
        self._propagate(out_name, keep, orig_c=o)
        return keep

    def _propagate(self, var_name, keep, orig_c):
        """Walk consumers of `var_name` (a [N,C,H,W] activation whose C was
        pruned to `keep`; `orig_c` = channel count before pruning) and
        resize channel-dependent vars."""
        for op in self._consumers(var_name):
            if op.type in ('conv2d',):
                fname = op.input('Filter')[0]
                w = np.asarray(self._scope.get(fname))
                self._resize(fname, w[:, keep],
                             indexer=lambda a: a[:, keep])
            elif op.type == 'depthwise_conv2d':
                fname = op.input('Filter')[0]
                w = np.asarray(self._scope.get(fname))
                self._resize(fname, w[keep], indexer=lambda a: a[keep])
                self._propagate(op.output('Output')[0], keep, orig_c)
            elif op.type == 'batch_norm':
                for slot in ('Scale', 'Bias', 'Mean', 'Variance'):
                    n = op.input(slot)[0]
                    self._resize(n, np.asarray(self._scope.get(n))[keep],
                                 indexer=lambda a: a[keep])
                self._propagate(op.output('Y')[0], keep, orig_c)
            elif op.type == 'elementwise_add' and op.attr('axis', -1) == 1:
                # conv bias add: Y is the [C] bias param
                bname = op.input('Y')[0]
                b = self._scope.get(bname)
                if b is not None and np.asarray(b).ndim == 1:
                    self._resize(bname, np.asarray(b)[keep],
                                 indexer=lambda a: a[keep])
                self._propagate(op.output('Out')[0], keep, orig_c)
            elif op.type == 'elementwise_add':
                # residual join: the other branch (activation OR a
                # channel-shaped persistable) still carries orig_c
                # channels, so walking through would leave a runtime shape
                # mismatch. Pruning across a residual requires aligning
                # both producers; not supported — fail loudly instead of
                # mis-pruning.
                other = [n for n in op.input_arg_names if n != var_name]
                if other:
                    raise ValueError(
                        "ChannelPruner: conv %r feeds a residual "
                        "elementwise_add (other input %r); pruning across "
                        "residual joins is unsupported — exclude this conv "
                        "from prune targets" % (var_name, other[0]))
                self._propagate(op.output('Out')[0], keep, orig_c)
            elif op.type == 'mul':
                # first FC after flatten: rows are NCHW-flattened
                in_var = self._program.global_block()._find_var_recursive(
                    op.input('X')[0])
                wname = op.input('Y')[0]
                w = np.asarray(self._scope.get(wname))
                shape = in_var.shape if in_var is not None else None
                if shape is not None and len(shape) >= 4:
                    hw = int(np.prod(shape[2:]))
                elif w.shape[0] % orig_c == 0:
                    # flattened NCHW input (reshape/flatten before the fc):
                    # rows per channel from the weight itself
                    hw = w.shape[0] // orig_c
                else:
                    raise ValueError(
                        "cannot infer spatial size feeding mul %r" % wname)
                rows = np.concatenate(
                    [np.arange(c * hw, (c + 1) * hw) for c in keep])
                self._resize(wname, w[rows], indexer=lambda a: a[rows])
            elif op.type in _CHANNEL_KEEPING or op.type in (
                    'relu', 'pool2d'):
                outs = op.output('Out') or op.output('Output')
                if outs:
                    self._propagate(outs[0], keep, orig_c)
            # ops that flatten/reshape before mul keep NCHW row order;
            # reshape/flatten pass channel blocks through contiguously
            elif op.type in ('reshape', 'reshape2', 'flatten', 'flatten2',
                             'squeeze', 'squeeze2'):
                # a concrete target dim that folds the channel axis must
                # shrink with it (e.g. reshape([-1, C*H*W]))
                shape_attr = op.attr('shape', None)
                if shape_attr:
                    new_shape = [
                        (d // orig_c) * len(keep)
                        if d > 0 and d >= orig_c and d % orig_c == 0
                        else d
                        for d in shape_attr]
                    op.set_attr('shape', new_shape)
                outs = op.output('Out')
                if outs:
                    self._propagate(outs[0], keep, orig_c)
