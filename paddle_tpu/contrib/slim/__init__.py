"""Model-compression framework (reference contrib/slim parity).

Reference: python/paddle/fluid/contrib/slim/ — CompressPass + Strategy
orchestration (core/compress_pass.py, core/strategy.py), magnitude/ratio
pruners (prune/pruner.py), QAT strategy (quantization/quantization_pass.py).

TPU-native design: because the executor re-lowers programs from scope state
each run, compression acts directly on the state pytree (numpy masks /
physically resized arrays) plus lightweight program-desc rewrites — no
IrGraph pass machinery is needed. Channel pruning REALLY shrinks parameter
shapes (conv filter + dependent BN/conv/fc vars), so exported inference
models get smaller, not just sparser.
"""
from .core import Context, Strategy, CompressPass
from .prune import (Pruner, MagnitudePruner, RatioPruner, PruneStrategy,
                    ChannelPruner)
from .quantization import QuantizationStrategy

__all__ = ['Context', 'Strategy', 'CompressPass', 'Pruner',
           'MagnitudePruner', 'RatioPruner', 'PruneStrategy',
           'ChannelPruner', 'QuantizationStrategy']
