"""QAT as a CompressPass strategy.

Reference: slim/quantization/quantization_pass.py rewrites the IrGraph with
fake-quant/dequant ops at a given epoch; here the existing
QuantizeTranspiler (contrib/quantize.py — same fake-quant op semantics,
program-level rewrite) is applied to context.train_program when the
strategy activates, and the frozen int8 inference program is produced at
compress end.
"""
from .core import Strategy

__all__ = ['QuantizationStrategy']


class QuantizationStrategy(Strategy):
    def __init__(self, start_epoch=0, end_epoch=1000, weight_bits=8,
                 activation_bits=8, activation_quantize_type='abs_max',
                 freeze_on_end=True, int8_on_end=True):
        super(QuantizationStrategy, self).__init__(start_epoch, end_epoch)
        from ..quantize import QuantizeTranspiler
        self._transpiler = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            activation_quantize_type=activation_quantize_type)
        self._applied = False
        self._freeze = freeze_on_end
        # int8_on_end additionally produces int8_program: the frozen
        # program with REAL int8 weight blobs in the scope
        # (QuantizeTranspiler.convert_to_int8_program — int8(weight)/
        # fp32(act) execution, exportable via save_inference_model)
        self._int8 = int8_on_end
        self.freeze_program = None
        self.int8_program = None
        self.int8_blobs = None

    def on_compress_begin(self, context):
        # fake-quant insertion must precede backward construction, so the
        # rewrite happens at compress begin (CompressPass then calls
        # optimizer.minimize on the rewritten program)
        if self._applied:
            return
        self._transpiler.training_transpile(
            context.train_program, context.startup_program)
        self._applied = True

    def on_compress_end(self, context):
        if not (self._applied and self._freeze):
            return
        prog = (context.eval_program or context.train_program).clone(
            for_test=True)
        self._transpiler.freeze_program(prog, scope=context.scope)
        self.freeze_program = prog
        if self._int8:
            int8 = prog.clone(for_test=True)
            self.int8_blobs = self._transpiler.convert_to_int8_program(
                int8, scope=context.scope)
            self.int8_program = int8
