"""CompressPass: epoch/batch-driven compression orchestration.

Reference contract (slim/core/compress_pass.py:45 CompressPass,
slim/core/strategy.py Strategy): strategies register callbacks
(on_compress_begin / on_epoch_begin / on_batch_begin / on_batch_end /
on_epoch_end / on_compress_end) and a Context carries (executor, scope,
programs, epoch, batch) between them; CompressPass.apply runs the training
loop with the callbacks woven in.
"""

__all__ = ['Context', 'Strategy', 'CompressPass']


class Context(object):
    """Mutable state shared by strategies (reference compress_pass.py:21)."""

    def __init__(self, exe, scope, train_program=None, eval_program=None,
                 startup_program=None):
        self.exe = exe
        self.scope = scope
        self.train_program = train_program
        self.eval_program = eval_program
        self.startup_program = startup_program
        self.epoch = 0
        self.batch = 0
        self.metrics = {}


class Strategy(object):
    """Base strategy active in [start_epoch, end_epoch] (reference
    slim/core/strategy.py)."""

    def __init__(self, start_epoch=0, end_epoch=10):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def active(self, epoch):
        return self.start_epoch <= epoch <= self.end_epoch

    def on_compress_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_compress_end(self, context):
        pass


class CompressPass(object):
    """Runs `epochs` passes over `train_reader`, executing `fetch_list` on
    the (possibly strategy-rewritten) context.train_program each batch and
    invoking strategy callbacks around it (reference compress_pass.py:45).

    train_feeder(batch_data) must return the feed dict for Executor.run.
    """

    def __init__(self, executor, scope, train_program, train_reader,
                 train_feeder, fetch_list=None, epochs=1,
                 eval_program=None, startup_program=None,
                 optimizer=None, loss=None):
        self._exe = executor
        self._scope = scope
        self._train_program = train_program
        self._train_reader = train_reader
        self._train_feeder = train_feeder
        self._fetch_list = list(fetch_list or [])
        self._epochs = epochs
        self._eval_program = eval_program
        self._startup_program = startup_program
        # when given, CompressPass owns backward construction: strategies
        # that rewrite the forward program (QAT) run on_compress_begin
        # BEFORE minimize, like the reference compressor built from config
        # (slim/core/pass_builder.py:21 build_compressor)
        self._optimizer = optimizer
        self._loss = loss
        self._strategies = []

    def add_strategy(self, strategy):
        self._strategies.append(strategy)
        return self

    def apply(self):
        """Run the compression training loop; returns the Context (whose
        train_program/scope hold the compressed result)."""
        ctx = Context(self._exe, self._scope,
                      train_program=self._train_program,
                      eval_program=self._eval_program,
                      startup_program=self._startup_program)
        for s in self._strategies:
            s.on_compress_begin(ctx)
        if self._optimizer is not None and self._loss is not None:
            from ... import program_guard, Scope, scope_guard
            with program_guard(ctx.train_program,
                               ctx.startup_program or ctx.train_program):
                self._optimizer.minimize(self._loss)
            if ctx.startup_program is not None:
                # initialize ONLY vars the rewrite/minimize created — the
                # full startup would re-randomize pretrained weights
                tmp = Scope()
                with scope_guard(tmp):
                    self._exe.run(ctx.startup_program, scope=tmp)
                for name in tmp.names():
                    if not self._scope.has(name):
                        self._scope.set(name, tmp.get(name))
        for epoch in range(self._epochs):
            ctx.epoch = epoch
            act = [s for s in self._strategies if s.active(epoch)]
            for s in act:
                s.on_epoch_begin(ctx)
            for batch_id, data in enumerate(self._train_reader()):
                ctx.batch = batch_id
                for s in act:
                    s.on_batch_begin(ctx)
                feed = self._train_feeder(data)
                outs = self._exe.run(ctx.train_program, feed=feed,
                                     fetch_list=self._fetch_list,
                                     scope=self._scope)
                ctx.metrics['last_fetch'] = outs
                for s in act:
                    s.on_batch_end(ctx)
            for s in act:
                s.on_epoch_end(ctx)
        for s in self._strategies:
            s.on_compress_end(ctx)
        return ctx
