"""HDFS client over the `hadoop fs` CLI (reference
python/paddle/fluid/contrib/utils/hdfs_utils.py HDFSClient).

The reference shells out to the hadoop binary; so does this — with a
clear error when no hadoop toolchain is installed (the TPU training path
reads from local disk / GCS mounts instead)."""
import os
import subprocess

__all__ = ['HDFSClient']


class HDFSClient(object):
    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = os.path.join(hadoop_home, 'bin', 'hadoop') \
            if hadoop_home else 'hadoop'
        self._configs = dict(configs or {})

    def _run(self, *args):
        cmd = [self._hadoop, 'fs']
        for k, v in self._configs.items():
            cmd += ['-D', '%s=%s' % (k, v)]
        cmd += list(args)
        try:
            res = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError:
            raise RuntimeError(
                "hadoop binary %r not found — HDFSClient needs a hadoop "
                "installation (pass hadoop_home=)" % self._hadoop)
        return res.returncode, res.stdout, res.stderr

    def is_exist(self, path):
        rc, _, _ = self._run('-test', '-e', path)
        return rc == 0

    def is_dir(self, path):
        rc, _, _ = self._run('-test', '-d', path)
        return rc == 0

    def delete(self, path):
        rc, _, err = self._run('-rm', '-r', path)
        return rc == 0

    def upload(self, hdfs_path, local_path, overwrite=False):
        args = ['-put'] + (['-f'] if overwrite else []) + \
            [local_path, hdfs_path]
        rc, _, err = self._run(*args)
        if rc != 0:
            raise RuntimeError("hdfs upload failed: %s" % err.strip())
        return True

    def download(self, hdfs_path, local_path):
        rc, _, err = self._run('-get', hdfs_path, local_path)
        if rc != 0:
            raise RuntimeError("hdfs download failed: %s" % err.strip())
        return True

    def ls(self, path):
        rc, out, err = self._run('-ls', path)
        if rc != 0:
            raise RuntimeError("hdfs ls failed: %s" % err.strip())
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def makedirs(self, path):
        rc, _, err = self._run('-mkdir', '-p', path)
        return rc == 0
