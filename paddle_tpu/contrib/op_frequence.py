"""Op-frequency statistics (reference
python/paddle/fluid/contrib/op_frequence.py op_freq_statistic) + measured
top-offender ranking backed by the analysis attribution tables.

`op_freq_statistic` keeps the reference's STATIC census (how often each
op type appears in the program) — useful for program-shape questions, but
a count is not a cost. The fused-kernel tier is evidence-driven, so
"which ops burn the cycles" must come from ONE source of truth: the
measured per-op attribution table (`paddle_tpu.analysis.op_profile()`,
filled by ``PADDLE_PROFILE_OPS=1`` / ``profiler.profile_ops()`` runs).
`top_offenders` joins that table with the static census and REFUSES to
rank from counts alone — no silent fallback that would dress a census up
as a measurement.
"""
from collections import OrderedDict

__all__ = ['op_freq_statistic', 'top_offenders']


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_op_freq): single-op counts and adjacent
    op-pair counts over the program's blocks, most frequent first.
    STATIC program census — for measured cost ranking use
    :func:`top_offenders`."""
    uni, adj = {}, {}
    prev = None
    for block in program.blocks:
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = prev + '->' + op.type
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
        prev = None
    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj


def top_offenders(program=None, profile=None, limit=None):
    """Measured top offenders: rows from the analysis op-attribution
    table (total/avg seconds, calls, out_bytes, time ratio), optionally
    joined with the static op count of `program`.

    `profile` defaults to the live ``analysis.op_profile()`` — run the
    workload under ``PADDLE_PROFILE_OPS=1`` (or ``profiler.profile_ops()``)
    first. Raises RuntimeError when no attribution data exists instead of
    silently ranking by static count: a census cannot name the ops that
    burn the cycles."""
    from .. import analysis
    p = profile if profile is not None else analysis.op_profile()
    if not p.get('ops'):
        raise RuntimeError(
            "top_offenders: the op-attribution table is empty — run the "
            "workload under PADDLE_PROFILE_OPS=1 (or inside "
            "profiler.profile_ops()) so there is measured per-op time to "
            "rank by; op_freq_statistic() gives the static census only")
    counts = op_freq_statistic(program)[0] if program is not None else {}
    rows = []
    for r in p['ops'][:limit]:
        row = dict(r)
        if counts:
            row['program_count'] = counts.get(r['type'], 0)
        rows.append(row)
    return rows
