"""Op-frequency statistics (reference
python/paddle/fluid/contrib/op_frequence.py op_freq_statistic)."""
from collections import OrderedDict

__all__ = ['op_freq_statistic']


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_op_freq): single-op counts and adjacent
    op-pair counts over the global block, most frequent first."""
    uni, adj = {}, {}
    prev = None
    for block in program.blocks:
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = prev + '->' + op.type
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
        prev = None
    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj
