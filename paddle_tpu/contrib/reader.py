"""contrib.reader: ctr_reader (reference
python/paddle/fluid/contrib/reader/ctr_reader.py).

The reference's ctr_reader is a C++ reader op streaming MultiSlot CTR
files into a LoDTensorBlockingQueue; here the same contract composes from
the native MultiSlot parser (async_executor.MultiSlotDataFeed over
native/multislot.cc) and the py_reader queue: declare feed vars, call
ctr_reader(...), start()/run()/EOF/reset().
"""
from ..layers.io import create_py_reader_by_data

__all__ = ['ctr_reader']


def ctr_reader(feed_data, capacity, thread_num, batch_size, file_list,
               slots, name=None):
    """Returns a started-able reader feeding `feed_data` vars from
    MultiSlot `file_list`. `slots`: list of name, (name, type) or
    (name, type, is_dense) — defaults 'uint64' sparse; order must match
    feed_data. `thread_num` is accepted for reference-API parity but the
    feeder is single-threaded here (the native C++ parser makes parsing
    cheap; AsyncExecutor.run provides the multi-threaded file pool)."""
    from ..async_executor import DataFeedDesc, MultiSlotDataFeed
    desc = DataFeedDesc(batch_size=batch_size)
    for sl in slots:
        if isinstance(sl, (tuple, list)):
            nm = sl[0]
            tp = sl[1] if len(sl) > 1 else 'uint64'
            dense = bool(sl[2]) if len(sl) > 2 else False
            desc.add_slot(nm, tp, is_dense=dense)
        else:
            desc.add_slot(sl, 'uint64', is_dense=False)
    feed = MultiSlotDataFeed(desc)
    reader = create_py_reader_by_data(capacity, feed_data, name=name)
    names = [sl['name'] for sl in desc.slots if sl['is_used']]

    def _source():
        for path in file_list:
            for batch in feed.batches_from_file(path):
                yield tuple(batch[n] for n in names)

    reader.decorate_paddle_reader(_source)
    return reader
