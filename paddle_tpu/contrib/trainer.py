"""High-level Trainer/Inferencer API (reference
python/paddle/fluid/contrib/trainer.py Trainer:169 / inferencer.py —
the book 'high-level-api' test surface).

The Trainer owns its programs and scope: `train_func` builds the forward
and returns [loss, *metrics]; `optimizer_func` returns the optimizer. Each
`train()` epoch streams a reader through the executor and fires the event
handler with Begin/End Epoch/Step events. On TPU the underlying executor
is the whole-program-compiled one; pass parallel=True to run data-parallel
over the visible mesh (the reference's ParallelExecutor path)."""
import numpy as np

from ..framework import Program, program_guard
from ..executor import Executor, Scope, scope_guard
from ..data_feeder import DataFeeder
from .. import io as _io
from .. import unique_name

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'Trainer', 'Inferencer']


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        # reference: handler may flip this to request metric fetches
        self.fetch_metrics = True


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class Trainer(object):
    def __init__(self, train_func, optimizer_func, place=None,
                 parallel=False, checkpoint_config=None):
        self.place = place
        self.parallel = parallel
        # CheckpointConfig(checkpoint_dir, epoch_interval) — saved via
        # fluid.checkpoint after every epoch_interval epochs (step-based
        # saving is not supported; pass a handler that calls
        # fluid.checkpoint.save_checkpoint for finer control)
        self.checkpoint_config = checkpoint_config
        if checkpoint_config is not None and \
                getattr(checkpoint_config, 'step_interval', None):
            # the reference CheckpointConfig defaults step_interval=10;
            # only epoch-based saving is implemented here
            import warnings
            warnings.warn(
                "CheckpointConfig.step_interval is ignored — checkpoints "
                "save per epoch_interval; save manually in an "
                "EndStepEvent handler for step-based saving",
                stacklevel=2)
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        with program_guard(self.train_program, self.startup_program):
            with unique_name.guard():   # reference Trainer does the same:
                # fresh name counters so re-built programs (Inferencer)
                # and other processes reproduce identical names — the
                # optimizer's lr/accumulator vars included, or
                # checkpoints would not be portable across processes
                outs = train_func()
                outs = outs if isinstance(outs, (list, tuple)) else [outs]
                self.train_func_outputs = list(outs)
                self.loss = outs[0]
                # test program BEFORE optimizer ops (reference clones
                # here)
                self.test_program = self.train_program.clone(
                    for_test=True)
                optimizer = optimizer_func()
                optimizer.minimize(self.loss)
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program, scope=self.scope)
        self._compiled = None
        self.__stopped = False

    def _train_target(self):
        if not self.parallel:
            return self.train_program
        if self._compiled is None:
            from ..compiler import CompiledProgram
            self._compiled = CompiledProgram(
                self.train_program).with_data_parallel(
                    loss_name=self.loss.name)
        return self._compiled

    def stop(self):
        self.__stopped = True

    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None):
        if reader is None:
            raise ValueError(
                "Trainer.train needs a reader (a no-arg callable yielding "
                "batches); got None")
        feeder = DataFeeder(feed_list=feed_order,
                            place=self.place,
                            program=self.train_program) \
            if feed_order else None
        target = self._train_target()
        fetch = [v.name for v in self.train_func_outputs]
        with scope_guard(self.scope):
            for epoch_id in range(num_epochs):
                if self.__stopped:
                    return
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stopped:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    feed = feeder.feed(data) if feeder else data
                    if begin.fetch_metrics:
                        metrics = self.exe.run(target, feed=feed,
                                               fetch_list=fetch,
                                               scope=self.scope)
                    else:
                        self.exe.run(target, feed=feed, scope=self.scope)
                        metrics = None
                    event_handler(EndStepEvent(epoch_id, step_id,
                                               metrics))
                event_handler(EndEpochEvent(epoch_id))
                self._maybe_checkpoint(epoch_id)

    def _maybe_checkpoint(self, epoch_id):
        cc = self.checkpoint_config
        if cc is None:
            return
        d = getattr(cc, 'checkpoint_dir', None) or \
            (cc if isinstance(cc, str) else None)
        if not d:
            return
        every = getattr(cc, 'epoch_interval', 1) or 1
        if (epoch_id + 1) % every == 0:
            from .. import checkpoint as _ckpt
            _ckpt.save_checkpoint(d, self.train_program, scope=self.scope)

    def test(self, reader, feed_order):
        feeder = DataFeeder(feed_list=feed_order, place=self.place,
                            program=self.test_program)
        fetch = [v.name for v in self.train_func_outputs]
        from ..average import WeightedAverage
        avgs = [WeightedAverage() for _ in fetch]
        with scope_guard(self.scope):
            for data in reader():
                outs = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=fetch, scope=self.scope)
                for avg, o in zip(avgs, outs):
                    avg.add(value=float(np.mean(np.asarray(o))),
                            weight=len(data))
        return [a.eval() for a in avgs]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            _io.save_persistables(self.exe, param_path,
                                  self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        targets = [self.train_func_outputs[i]
                   for i in target_var_indexes]
        with scope_guard(self.scope):
            _io.save_inference_model(param_path, feeded_var_names,
                                     targets, self.exe,
                                     main_program=self.test_program)


class Inferencer(object):
    """reference contrib/inferencer.py: infer_func rebuilds the forward;
    params load from a Trainer.save_params / save_inference_model dir."""

    def __init__(self, infer_func, param_path, place=None,
                 parallel=False):
        if parallel:
            raise NotImplementedError(
                "Inferencer(parallel=True): run the returned program "
                "through CompiledProgram.with_data_parallel instead")
        self.place = place
        self.scope = Scope()
        self.inference_program = Program()
        startup = Program()
        with program_guard(self.inference_program, startup):
            with unique_name.guard():   # same fresh-name discipline as
                # Trainer, so parameter names line up with saved params
                self.predict_var = infer_func()
        self.exe = Executor(place)
        with scope_guard(self.scope):
            _io.load_persistables(self.exe, param_path,
                                  self.inference_program)

    def infer(self, inputs, return_numpy=True):
        with scope_guard(self.scope):
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var],
                                scope=self.scope,
                                return_numpy=return_numpy)
