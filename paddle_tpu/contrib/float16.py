"""float16 transpiler (reference paddle/contrib/float16/
float16_transpiler.py:66 Float16Transpiler).

TPU divergence, by design: the numerically robust reduced precision on
TPU is bfloat16 (same exponent range as fp32 — no loss-scaling machinery
needed), so `float16_transpile` marks the program's MXU-heavy ops with
the bf16 AMP policy (contrib/mixed_precision) instead of rewriting var
dtypes to fp16. The observable contract matches: matmuls/convs execute in
reduced precision, parameters and the program's var dtypes stay fp32.
"""
from . import mixed_precision as _mp

__all__ = ['float16_transpile', 'Float16Transpiler']


def float16_transpile(program, place=None, scope=None, dtype='bfloat16'):
    """Mark `program` for reduced-precision compute (bf16 on TPU)."""
    _mp.rewrite_program_bf16(program, dtype=dtype,
                             amp_lists=_mp.AutoMixedPrecisionLists())
    return program


class Float16Transpiler(object):
    """Reference-shaped class API."""

    def transpile(self, program, place=None, scope=None):
        return float16_transpile(program, place=place, scope=scope)
