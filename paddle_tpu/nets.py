"""Composite networks (reference python/paddle/fluid/nets.py:19-25:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""
from . import layers

__all__ = ['simple_img_conv_pool', 'img_conv_group', 'glu',
           'scaled_dot_product_attention']


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type='max',
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type='max', use_cudnn=True):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _to_list(v):
        if isinstance(v, (list, tuple)):
            return list(v)
        return [v] * len(conv_num_filter)

    conv_padding = _to_list(conv_padding)
    conv_filter_size = _to_list(conv_filter_size)
    param_attr = _to_list(param_attr)
    conv_with_batchnorm = _to_list(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _to_list(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (reference nets.py).
    Dense batched matmuls — MXU-friendly."""
    if num_heads != 1:
        def _split_heads(x):
            hidden = x.shape[2]
            r = layers.reshape(x, shape=[x.shape[0], x.shape[1], num_heads,
                                         hidden // num_heads])
            return layers.transpose(r, perm=[0, 2, 1, 3])
        q, k, v = map(_split_heads, (queries, keys, values))
    else:
        q, k, v = queries, keys, values
    d = q.shape[-1]
    scaled_q = layers.scale(q, scale=d ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    if num_heads == 1:
        return ctx_multiheads
    t = layers.transpose(ctx_multiheads, perm=[0, 2, 1, 3])
    return layers.reshape(t, shape=[t.shape[0], t.shape[1],
                                    t.shape[2] * t.shape[3]])
