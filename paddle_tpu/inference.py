"""Inference predictor facade: load a saved model -> compiled callable.

Reference counterpart: AnalysisPredictor (paddle/fluid/inference/api/
analysis_predictor.cc:183 Run; api_impl.cc NativePredictor). TPU-native
redesign: the predictor owns a private Scope + Executor; the first run jits
the pruned inference program for the feed signature and XLA caches the
compiled executable, which IS the "analysis + optimization" stage (fusion,
layout, memory planning all happen in XLA rather than hand-written passes).
"""
import numpy as np

from . import monitor
from .executor import Executor, Scope, scope_guard
from . import io as _io

__all__ = ['PredictorConfig', 'Predictor', 'create_predictor']


class PredictorConfig(object):
    """Analog of AnalysisConfig (contrib/inference AnalysisConfig)."""

    def __init__(self, model_dir=None, model_filename=None,
                 params_filename=None):
        self.model_dir = model_dir
        self.model_filename = model_filename
        self.params_filename = params_filename


class Predictor(object):
    def __init__(self, config):
        if isinstance(config, str):
            config = PredictorConfig(model_dir=config)
        self.config = config
        self.scope = Scope()
        self.executor = Executor()
        with scope_guard(self.scope):
            prog, feed_names, fetch_vars = _io.load_inference_model(
                config.model_dir, self.executor,
                model_filename=config.model_filename,
                params_filename=config.params_filename)
        self.program = prog
        self.feed_names = list(feed_names)
        self.fetch_vars = fetch_vars

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return [v.name for v in self.fetch_vars]

    def run(self, feed, return_numpy=True, donate=None):
        """feed: dict name->array, or list of arrays in feed_names order.
        Returns list of numpy arrays in fetch order
        (AnalysisPredictor::Run analog). Feed names are validated against
        get_input_names() up front: a missing or extra key raises KeyError
        naming the offenders instead of failing deep inside dispatch.

        Params stay device-resident across calls (the executor caches the
        device copy into the predictor's private scope on first use), so
        steady-state cost is feed upload + one compiled call + fetch.
        `return_numpy=False` keeps the fetches device-resident too — no
        host sync — for callers that chain them into another device
        computation (feeding a second predictor, device-side post-
        processing); feeds may likewise be jax.Arrays and are then never
        staged through the host."""
        if not isinstance(feed, dict):
            arrays = list(feed)
            if len(arrays) != len(self.feed_names):
                raise ValueError(
                    "expected %d inputs %s, got %d"
                    % (len(self.feed_names), self.feed_names, len(arrays)))
            feed = dict(zip(self.feed_names, arrays))
        missing = sorted(n for n in self.feed_names if n not in feed)
        extra = sorted(k for k in feed if k not in self.feed_names)
        if missing or extra:
            raise KeyError(
                "Predictor.run feed does not match get_input_names() %s:%s%s"
                % (self.feed_names,
                   ' missing %s' % missing if missing else '',
                   ' unexpected %s' % extra if extra else ''))
        # rides the executor's own run/compile instrumentation; the
        # predictor-level counter + span separate serving traffic from
        # training runs in the same process
        monitor.inc('predictor_run_total')
        with monitor.span('predictor.run'):
            with scope_guard(self.scope):
                outs = self.executor.run(self.program, feed=feed,
                                         fetch_list=self.fetch_vars,
                                         return_numpy=return_numpy,
                                         donate=donate)
        if not return_numpy:
            return list(outs)
        return [np.asarray(o) for o in outs]


def create_predictor(config):
    return Predictor(config)
