"""Parameter initializers — append init ops to the startup program.

Capability parity with reference python/paddle/fluid/initializer.py (Constant,
Uniform, Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray).
Initializers are ops in the startup program, so `exe.run(startup_program)`
materializes all parameters on device in one compiled XLA program.
"""
import numpy as np

__all__ = [
    'Constant', 'Uniform', 'Normal', 'TruncatedNormal', 'Xavier', 'MSRA',
    'Bilinear', 'NumpyArrayInitializer', 'ConstantInitializer',
    'UniformInitializer', 'NormalInitializer', 'TruncatedNormalInitializer',
    'XavierInitializer', 'MSRAInitializer', 'BilinearInitializer',
    'force_init_on_cpu', 'init_on_cpu',
]


def force_init_on_cpu():
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type='fill_constant',
            outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': float(self._value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type='uniform_random',
            outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'min': self._low, 'max': self._high, 'seed': self._seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='gaussian_random',
            outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self._mean, 'std': self._std, 'seed': self._seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='truncated_gaussian_random',
            outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self._mean, 'std': self._std, 'seed': self._seed})


def _fans(var):
    shape = var.shape
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) >= 3:
        receptive = int(np.prod(shape[2:]))
        return shape[1] * receptive, shape[0] * receptive
    return shape[0], shape[0]


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform = uniform
        self._fan_in, self._fan_out, self._seed = fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fans(var)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        if self._uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fans(var)
        fi = self._fan_in if self._fan_in is not None else fi
        if self._uniform:
            limit = float(np.sqrt(6.0 / fi))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / fi))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel for conv_transpose (reference
    initializer.py Bilinear)."""

    def __call__(self, var, block):
        shape = var.shape  # (C_in, C_out, kh, kw) or (C, 1, kh, kw)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs 4-D weights")
        weight = np.zeros(shape, dtype='float32')
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape[2:]))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            v = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[:, :, y, x] = v
        return block.append_op(
            type='assign_value',
            outputs={'Out': [var.name]},
            attrs={'shape': list(shape), 'dtype': var.dtype,
                   'values': weight.flatten().tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type='assign_value',
            outputs={'Out': [var.name]},
            attrs={'shape': list(self._value.shape), 'dtype': var.dtype,
                   'values': self._value.flatten().tolist()})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
