// Native MultiSlot text parser (reference framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance): the CTR ingestion hot path —
// "<n> <v...>" per slot per line — parsed in C++ instead of per-token
// Python. Exposed through a C ABI for the ctypes loader
// (paddle_tpu/native/__init__.py), like the recordio component.
//
// Two-call protocol per file:
//   h = ms_parse_file(path, n_slots, is_float[], err*)  -> handle or null
//   ms_num_samples(h); per slot: ms_slot_total(h, s) then
//   ms_slot_copy_(u64|float)(h, s, vals_out, lens_out) where lens_out has
//   one entry per sample. ms_free(h) releases everything.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotData {
  std::vector<int64_t> ivals;
  std::vector<float> fvals;
  std::vector<int64_t> lens;   // per-sample value counts
};

struct Parsed {
  std::vector<SlotData> slots;
  int64_t n_samples = 0;
  std::string error;
};

// strtoll/strtof based tokenizer over one line
bool parse_line(const char* p, int n_slots, const int* is_float,
                Parsed* out) {
  char* end = nullptr;
  for (int s = 0; s < n_slots; ++s) {
    long long n = strtoll(p, &end, 10);
    if (end == p || n < 0) return false;
    p = end;
    SlotData& sd = out->slots[s];
    if (is_float[s]) {
      for (long long i = 0; i < n; ++i) {
        float v = strtof(p, &end);
        if (end == p) return false;
        p = end;
        sd.fvals.push_back(v);
      }
    } else {
      for (long long i = 0; i < n; ++i) {
        unsigned long long v = strtoull(p, &end, 10);
        if (end == p) return false;
        // ids index embedding tables as int64: reject >= 2^63 instead of
        // silently wrapping negative (same contract as the python parser)
        if (v > 0x7fffffffffffffffULL) return false;
        p = end;
        sd.ivals.push_back(static_cast<int64_t>(v));
      }
    }
    sd.lens.push_back(n);
  }
  return true;
}

}  // namespace

extern "C" {

void* ms_parse_file(const char* path, int n_slots, const int* is_float,
                    char** err_out) {
  static thread_local std::string err;
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    if (err_out) *err_out = const_cast<char*>(err.c_str());
    return nullptr;
  }
  Parsed* out = new Parsed();
  out->slots.resize(n_slots);
  std::string line;
  char buf[1 << 16];
  std::string pending;
  while (fgets(buf, sizeof(buf), f)) {
    pending += buf;
    if (!pending.empty() && pending.back() != '\n' && !feof(f)) {
      continue;                      // long line: keep accumulating
    }
    // trim
    size_t a = pending.find_first_not_of(" \t\r\n");
    if (a == std::string::npos) {
      pending.clear();
      continue;
    }
    if (!parse_line(pending.c_str() + a, n_slots, is_float, out)) {
      err = "malformed MultiSlot line: " + pending.substr(a, 80);
      if (err_out) *err_out = const_cast<char*>(err.c_str());
      fclose(f);
      delete out;
      return nullptr;
    }
    out->n_samples += 1;
    pending.clear();
  }
  // a final line without trailing newline may still be pending
  size_t a2 = pending.find_first_not_of(" \t\r\n");
  if (a2 != std::string::npos) {
    if (!parse_line(pending.c_str() + a2, n_slots, is_float, out)) {
      err = "malformed MultiSlot line: " + pending.substr(a2, 80);
      if (err_out) *err_out = const_cast<char*>(err.c_str());
      fclose(f);
      delete out;
      return nullptr;
    }
    out->n_samples += 1;
  }
  fclose(f);
  return out;
}

int64_t ms_num_samples(void* h) {
  return static_cast<Parsed*>(h)->n_samples;
}

int64_t ms_slot_total(void* h, int slot) {
  Parsed* p = static_cast<Parsed*>(h);
  const SlotData& sd = p->slots[slot];
  return sd.ivals.empty() ? static_cast<int64_t>(sd.fvals.size())
                          : static_cast<int64_t>(sd.ivals.size());
}

void ms_slot_copy_u64(void* h, int slot, int64_t* vals, int64_t* lens) {
  Parsed* p = static_cast<Parsed*>(h);
  const SlotData& sd = p->slots[slot];
  if (!sd.ivals.empty())
    memcpy(vals, sd.ivals.data(), sd.ivals.size() * sizeof(int64_t));
  memcpy(lens, sd.lens.data(), sd.lens.size() * sizeof(int64_t));
}

void ms_slot_copy_float(void* h, int slot, float* vals, int64_t* lens) {
  Parsed* p = static_cast<Parsed*>(h);
  const SlotData& sd = p->slots[slot];
  if (!sd.fvals.empty())
    memcpy(vals, sd.fvals.data(), sd.fvals.size() * sizeof(float));
  memcpy(lens, sd.lens.data(), sd.lens.size() * sizeof(int64_t));
}

void ms_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
