"""Native (C++) runtime components, built lazily with the system toolchain.

The reference implements its IO/runtime tier in C++ (paddle/fluid/recordio/,
framework/data_feed.cc); here the native pieces compile on first use into
shared libraries loaded via ctypes — no pybind/pybind11 dependency.
"""
import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_libs = {}

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _build_dir():
    d = os.environ.get('PADDLE_TPU_NATIVE_CACHE')
    if not d:
        d = os.path.join(_SRC_DIR, '_build')
    os.makedirs(d, exist_ok=True)
    return d


def load_library(name, sources, extra_link=()):
    """Compile (once) and dlopen lib<name>.so from `sources` (.cc files in
    this directory). Recompiles when any source is newer than the .so."""
    with _lock:
        if name in _libs:
            return _libs[name]
        so_path = os.path.join(_build_dir(), 'lib%s.so' % name)
        srcs = [os.path.join(_SRC_DIR, s) for s in sources]
        stale = (not os.path.exists(so_path) or
                 any(os.path.getmtime(s) > os.path.getmtime(so_path)
                     for s in srcs))
        if stale:
            cmd = ['g++', '-O2', '-std=c++14', '-shared', '-fPIC',
                   '-o', so_path] + srcs + list(extra_link)
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except FileNotFoundError:
                raise RuntimeError(
                    "g++ not found: the native %s component needs a C++ "
                    "toolchain (reference builds this tier with CMake)"
                    % name)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    "building native %s failed:\n%s" % (name, e.stderr))
        lib = ctypes.CDLL(so_path)
        _libs[name] = lib
        return lib
