// Chunked record file format — the TPU-native analog of reference
// paddle/fluid/recordio/ ({header,chunk,writer,scanner}.cc) feeding
// create_recordio_file_reader_op. Fresh design, C ABI for ctypes:
//
//   file  := chunk*
//   chunk := MAGIC(4) | flags(u8) | num_records(u32) | raw_len(u32)
//            | stored_len(u32) | crc32(u32) | payload[stored_len]
//   payload (after optional zlib inflate) := (rec_len(u32) | bytes)*
//
// flags bit 0: payload zlib-compressed. crc32 covers the STORED payload.
// All integers little-endian. Records are opaque byte strings; the Python
// layer (paddle_tpu/recordio.py) serializes tensors into them.
//
// Build: g++ -O2 -shared -fPIC recordio.cc -o librecordio.so -lz

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x54505552;  // "RUPT"
constexpr uint8_t kFlagCompressed = 1;

struct Writer {
  FILE* f = nullptr;
  bool compress = false;
  size_t chunk_records = 0;    // flush threshold
  std::string buf;             // pending payload
  uint32_t pending = 0;
  std::string error;

  bool FlushChunk() {
    if (pending == 0) return true;
    // the chunk header stores 32-bit lengths: a chunk larger than 4 GiB
    // would silently truncate and corrupt the stream — refuse instead
    // (writers should also flush on an accumulated-bytes threshold)
    if (buf.size() > UINT32_MAX) {
      error = "chunk exceeds 4 GiB (32-bit length field); flush more often";
      return false;
    }
    const std::string* payload = &buf;
    std::string comp;
    uint8_t flags = 0;
    if (compress) {
      uLongf bound = compressBound(buf.size());
      comp.resize(bound);
      if (compress2(reinterpret_cast<Bytef*>(&comp[0]), &bound,
                    reinterpret_cast<const Bytef*>(buf.data()), buf.size(),
                    Z_DEFAULT_COMPRESSION) != Z_OK) {
        error = "zlib compress failed";
        return false;
      }
      comp.resize(bound);
      if (comp.size() < buf.size()) {
        payload = &comp;
        flags |= kFlagCompressed;
      }
    }
    uint32_t raw_len = static_cast<uint32_t>(buf.size());
    uint32_t stored_len = static_cast<uint32_t>(payload->size());
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(payload->data()),
                         payload->size());
    uint32_t head[1] = {kMagic};
    if (fwrite(head, 4, 1, f) != 1 || fwrite(&flags, 1, 1, f) != 1 ||
        fwrite(&pending, 4, 1, f) != 1 || fwrite(&raw_len, 4, 1, f) != 1 ||
        fwrite(&stored_len, 4, 1, f) != 1 || fwrite(&crc, 4, 1, f) != 1 ||
        (stored_len && fwrite(payload->data(), stored_len, 1, f) != 1)) {
      error = "short write";
      return false;
    }
    buf.clear();
    pending = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::string chunk;           // decoded payload of the current chunk
  size_t pos = 0;              // cursor into chunk
  uint32_t remaining = 0;      // records left in the current chunk
  std::string record;          // last record returned
  std::string error;

  bool LoadChunk() {
    uint32_t magic = 0;
    if (fread(&magic, 4, 1, f) != 1) return false;  // clean EOF
    if (magic != kMagic) {
      error = "bad chunk magic (corrupt or not a recordio file)";
      return false;
    }
    uint8_t flags;
    uint32_t num, raw_len, stored_len, crc;
    if (fread(&flags, 1, 1, f) != 1 || fread(&num, 4, 1, f) != 1 ||
        fread(&raw_len, 4, 1, f) != 1 || fread(&stored_len, 4, 1, f) != 1 ||
        fread(&crc, 4, 1, f) != 1) {
      error = "truncated chunk header";
      return false;
    }
    std::string stored(stored_len, '\0');
    if (stored_len && fread(&stored[0], stored_len, 1, f) != 1) {
      error = "truncated chunk payload";
      return false;
    }
    uint32_t got = crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
                         stored.size());
    if (got != crc) {
      error = "chunk crc mismatch";
      return false;
    }
    if (flags & kFlagCompressed) {
      chunk.resize(raw_len);
      uLongf out_len = raw_len;
      if (uncompress(reinterpret_cast<Bytef*>(&chunk[0]), &out_len,
                     reinterpret_cast<const Bytef*>(stored.data()),
                     stored.size()) != Z_OK ||
          out_len != raw_len) {
        error = "zlib inflate failed";
        return false;
      }
    } else {
      chunk.swap(stored);
    }
    pos = 0;
    remaining = num;
    return true;
  }

  bool Next() {
    while (remaining == 0) {
      if (!LoadChunk()) return false;
    }
    if (pos + 4 > chunk.size()) {
      error = "corrupt chunk: record header past payload";
      return false;
    }
    uint32_t len;
    memcpy(&len, chunk.data() + pos, 4);
    pos += 4;
    if (pos + len > chunk.size()) {
      error = "corrupt chunk: record past payload";
      return false;
    }
    record.assign(chunk, pos, len);
    pos += len;
    --remaining;
    return true;
  }
};

}  // namespace

extern "C" {

void* recordio_writer_open(const char* path, int compress,
                           int chunk_records) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->compress = compress != 0;
  w->chunk_records = chunk_records > 0 ? chunk_records : 1000;
  return w;
}

int recordio_writer_write(void* handle, const char* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(handle);
  w->buf.append(reinterpret_cast<const char*>(&len), 4);
  w->buf.append(data, len);
  ++w->pending;
  // flush on record count OR accumulated bytes: many large records must
  // not accumulate past the 32-bit chunk length field (1 GiB threshold
  // keeps chunks comfortably under the 4 GiB format limit)
  if (w->pending >= w->chunk_records ||
      w->buf.size() >= (1ull << 30)) {
    return w->FlushChunk() ? 0 : -1;
  }
  return 0;
}

int recordio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = w->FlushChunk() ? 0 : -1;
  if (fclose(w->f) != 0) rc = -1;
  delete w;
  return rc;
}

const char* recordio_writer_error(void* handle) {
  return static_cast<Writer*>(handle)->error.c_str();
}

void* recordio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// returns 1 with *data/*len set; 0 on clean EOF; -1 on error
int recordio_scanner_next(void* handle, const char** data, uint32_t* len) {
  Scanner* s = static_cast<Scanner*>(handle);
  if (!s->Next()) {
    return s->error.empty() ? 0 : -1;
  }
  *data = s->record.data();
  *len = static_cast<uint32_t>(s->record.size());
  return 1;
}

const char* recordio_scanner_error(void* handle) {
  return static_cast<Scanner*>(handle)->error.c_str();
}

void recordio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
