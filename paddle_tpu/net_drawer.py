"""Program visualization (reference python/paddle/fluid/net_drawer.py):
draw a Program's op graph. Delegates to the graphviz writer in
debugger.py (the maintained path); kept as a module for API parity."""
import json

from .debugger import draw_block_graphviz

__all__ = ['draw_graph']


def draw_graph(startup_program, main_program, path='graph.dot', **kwargs):
    """Write main_program's global block as graphviz dot to `path`
    (reference draw_graph merges startup+main; startup is init-only here
    and omitted from the drawing)."""
    return draw_block_graphviz(main_program, path)


def op_summary(program):
    """JSON-able op summary (name/inputs/outputs per op) — the structure
    the reference's drawer renders."""
    out = []
    for op in program.global_block().ops:
        out.append({'type': op.type,
                    'inputs': {k: list(v) for k, v in op.inputs.items()},
                    'outputs': {k: list(v) for k, v in op.outputs.items()}})
    return json.dumps(out)
