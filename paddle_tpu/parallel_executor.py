"""ParallelExecutor: legacy multi-device API (reference
python/paddle/fluid/parallel_executor.py:41, wrapping the C++ SSA-graph
runtime at framework/parallel_executor.cc:184).

TPU-native: a thin veneer over CompiledProgram.with_data_parallel — the SPMD
mesh path. Kept because reference user scripts and tests construct it
directly.
"""
from . import monitor
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from .framework import default_main_program
from .executor import Executor, global_scope

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


class ParallelExecutor(object):
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program if main_program is not None \
            else default_main_program()
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=share_vars_from._compiled
            if isinstance(share_vars_from, ParallelExecutor)
            else share_vars_from)
        self._executor = Executor()
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        # run latency/compile metrics are recorded downstream (serial
        # programs in Executor._run_impl, data-parallel ones at the
        # CompiledProgram delegation + spmd runner); this counter + span
        # only tag the traffic as the SPMD path
        monitor.inc('parallel_executor_run_total')
        with monitor.span('parallel_executor.run'):
            return self._executor.run(self._compiled, feed=feed,
                                      fetch_list=fetch_list,
                                      scope=self._scope,
                                      return_numpy=return_numpy)

    @property
    def device_count(self):
        from .parallel.mesh import default_device_count
        return default_device_count()
