"""Long-tail ops: fc, 3-D conv-transpose/pool, unpool, spp, conv_shift,
modified_huber_loss, similarity_focus, tree_conv, positive_negative_pair,
get_places, py_func.

Reference: operators/{fc_op, conv_transpose_op (3d), pool_with_index_op,
unpool_op, spp_op, conv_shift_op, modified_huber_loss_op,
similarity_focus_op, tree_conv_op (+math/tree2col), positive_negative_pair_op,
controlflow/get_places_op, py_func_op}.cc
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core import amp


# ---------------------------------------------------------------------------
# fc (the fused op form; layers.fc composes mul+sum, but programs built
# from fc op descs — e.g. loaded reference models — need the op itself)
# ---------------------------------------------------------------------------

@register_op('fc')
def _fc(ctx, op):
    """reference operators/fc_op.cc: Out = sum_i X_i W_i (+ Bias); W is a
    list parallel to Input, and leading dims up to in_num_col_dims are
    preserved in the output."""
    xs = ctx.in_list(op, 'Input')
    ws = ctx.in_list(op, 'W')
    bias = ctx.in1(op, 'Bias')
    col = op.attr('in_num_col_dims', 1)
    if len(ws) != len(xs):
        raise ValueError(
            "fc: expected one W per Input (%d inputs, %d weights)"
            % (len(xs), len(ws)))
    out = None
    lead_shape = None
    for x, w in zip(xs, ws):
        lead_shape = x.shape[:col]
        lead = int(np.prod(lead_shape)) if col else 1
        flat = x.reshape(lead, -1)
        y = jnp.matmul(*amp.cast_compute(op, flat, w),
                       preferred_element_type=amp.accum_dtype(flat))
        y = y.astype(x.dtype)
        out = y if out is None else out + y
    if bias is not None:
        out = out + bias.reshape(1, -1)
    ctx.out(op, 'Out', out.reshape(tuple(lead_shape) + (out.shape[-1],)))


# ---------------------------------------------------------------------------
# 3-D conv transpose + pooling with index + unpool + spp
# ---------------------------------------------------------------------------

def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


@register_op('conv3d_transpose')
def _conv3d_transpose(ctx, op):
    """reference conv_transpose_op.cc 3-D registration (gradient-of-conv
    formulation: lhs-dilate the input by stride)."""
    x = ctx.in1(op, 'Input')       # NCDHW
    w = ctx.in1(op, 'Filter')      # (C_in, C_out/groups, kd, kh, kw)
    strides = _triple(op.attr('strides', [1, 1, 1]))
    pads = _triple(op.attr('paddings', [0, 0, 0]))
    dilations = _triple(op.attr('dilations', [1, 1, 1]))
    groups = op.attr('groups', 1) or 1
    ks = [(w.shape[2 + i] - 1) * dilations[i] + 1 for i in range(3)]
    out_dtype = x.dtype
    x, w = amp.cast_compute(op, x, w)
    from .nn_ops import _transpose_kernel
    out = lax.conv_general_dilated(
        x, _transpose_kernel(w, groups, 3),
        window_strides=(1, 1, 1),
        padding=[(k - 1 - p, k - 1 - p) for k, p in zip(ks, pads)],
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'),
        feature_group_count=groups,
        preferred_element_type=amp.accum_dtype(x))
    ctx.out(op, 'Output', out.astype(out_dtype))


def _gathered_max(x, flat_idx, flat_valid, out_sz, nsp):
    """Shared tail of the pool-with-index gather: masked max + flat argmax
    position per output cell."""
    spatial = x.shape[-nsp:]
    lead = x.shape[:-nsp]
    xf = x.reshape(lead + (int(np.prod(spatial)),))
    taps = jnp.take(xf, jnp.asarray(flat_idx), axis=-1)    # [..., O, K]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    taps = jnp.where(jnp.asarray(flat_valid), taps, neg)
    vals = jnp.max(taps, -1)
    arg = jnp.argmax(taps, -1)
    # per output position o: flat_idx[o, arg[..., o]]
    flat_pos = jnp.take_along_axis(
        jnp.broadcast_to(jnp.asarray(flat_idx), vals.shape + (
            flat_idx.shape[1],)), arg[..., None], axis=-1)[..., 0]
    return (vals.reshape(lead + tuple(out_sz)),
            flat_pos.reshape(lead + tuple(out_sz)).astype(jnp.int32))


def _window_maps(out_sz, starts, wins, spatial, ends=None):
    """Flat gather map [prod(out), prod(win)] + validity mask: coord =
    start + win offset, valid while < end (adaptive) or inside the plane
    (fixed windows)."""
    nsp = len(spatial)
    idx = None
    valid = None
    for i in range(nsp):
        coord = starts[i].reshape(starts[i].shape + (1,) * nsp) + \
            wins[i].reshape((1,) * nsp + wins[i].shape)
        if ends is not None:
            ok = coord < ends[i].reshape(ends[i].shape + (1,) * nsp)
        else:
            ok = (coord >= 0) & (coord < spatial[i])
        flat = np.clip(coord, 0, spatial[i] - 1)
        idx = flat if idx is None else idx * spatial[i] + flat
        valid = ok if valid is None else (valid & ok)
    n_out = int(np.prod(out_sz))
    return idx.reshape(n_out, -1), valid.reshape(n_out, -1)


def _pool_with_index(x, ksize, strides, pads, adaptive=False):
    """Max pool over the trailing spatial dims returning (values, flat
    argmax indices into the unpadded spatial plane) — reference
    pool_with_index_op (MaxPool2dWithIndexFunctor, adaptive variant
    included). Static window gather: index maps are numpy constants."""
    nsp = len(ksize)
    spatial = x.shape[-nsp:]
    if adaptive:
        # reference AdaptiveStartIndex/AdaptiveEndIndex: ksize is the
        # OUTPUT size; windows have variable extents, padded to the max
        out_sz = list(ksize)
        per_dim = []
        for i in range(nsp):
            s = [int(np.floor(o * spatial[i] / out_sz[i]))
                 for o in range(out_sz[i])]
            e = [int(np.ceil((o + 1) * spatial[i] / out_sz[i]))
                 for o in range(out_sz[i])]
            per_dim.append((s, e))
        kmax = [max(e - s for s, e in zip(*d)) for d in per_dim]
        grids = np.meshgrid(*[np.arange(o) for o in out_sz],
                            indexing='ij')
        starts = [np.asarray(per_dim[i][0])[grids[i]] for i in range(nsp)]
        ends = [np.asarray(per_dim[i][1])[grids[i]] for i in range(nsp)]
        wins = np.meshgrid(*[np.arange(k) for k in kmax], indexing='ij')
        flat_idx, flat_valid = _window_maps(out_sz, starts, wins, spatial,
                                            ends=ends)
    else:
        out_sz = [(spatial[i] + 2 * pads[i] - ksize[i]) // strides[i] + 1
                  for i in range(nsp)]
        grids = np.meshgrid(*[np.arange(o) for o in out_sz], indexing='ij')
        starts = [g * strides[i] - pads[i] for i, g in enumerate(grids)]
        wins = np.meshgrid(*[np.arange(k) for k in ksize], indexing='ij')
        flat_idx, flat_valid = _window_maps(out_sz, starts, wins, spatial)
    return _gathered_max(x, flat_idx, flat_valid, out_sz, nsp)


@register_op('max_pool3d_with_index')
def _max_pool3d_with_index(ctx, op):
    x = ctx.in1(op, 'X')
    ksize = _triple(op.attr('ksize'))
    strides = _triple(op.attr('strides', [1, 1, 1]))
    pads = _triple(op.attr('paddings', [0, 0, 0]))
    if op.attr('global_pooling', False):
        ksize = x.shape[-3:]
        strides = (1, 1, 1)
        pads = (0, 0, 0)
    vals, mask = _pool_with_index(x, ksize, strides, pads,
                                  adaptive=op.attr('adaptive', False))
    ctx.out(op, 'Out', vals)
    ctx.out(op, 'Mask', mask)


@register_op('unpool')
def _unpool(ctx, op):
    """reference unpool_op.cc: scatter pooled values back to the argmax
    positions recorded by max_pool2d_with_index."""
    x = ctx.in1(op, 'X')            # [N, C, oh, ow]
    mask = ctx.in1(op, 'Indices')   # flat positions into H*W
    ksize = op.attr('ksize')
    strides = op.attr('strides', [1, 1])
    pads = op.attr('paddings', [0, 0])
    n, c, oh, ow = x.shape
    H = (oh - 1) * strides[0] - 2 * pads[0] + ksize[0]
    W = (ow - 1) * strides[1] - 2 * pads[1] + ksize[1]

    def one(xi, mi):
        # xi/mi [c, oh, ow] -> scatter into [c, H*W]; assignment (not
        # accumulate): overlapping windows sharing an argmax write the
        # same max once, matching reference unpool_op.cc
        flat = jnp.zeros((c, H * W), x.dtype)
        cols = mi.reshape(c, -1).astype(jnp.int32)
        vals = xi.reshape(c, -1)
        flat = jax.vmap(lambda f, co, v: f.at[co].set(v, mode='drop'))(
            flat, cols, vals)
        return flat.reshape(c, H, W)

    ctx.out(op, 'Out', jax.vmap(one)(x, mask))


@register_op('spp')
def _spp(ctx, op):
    """reference spp_op.h: spatial pyramid pooling — at level p, a plain
    pool2d with kernel = ceil(dim / 2^p), stride = kernel, padding =
    (kernel * bins - dim + 1) / 2, exclusive averaging; levels flattened
    and concatenated."""
    from .nn_ops import _pool
    x = ctx.in1(op, 'X')            # [N, C, H, W]
    height = op.attr('pyramid_height')
    ptype = op.attr('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for level in range(height):
        bins = 2 ** level
        kh = -(-h // bins)          # ceil
        kw = -(-w // bins)
        ph_ = (kh * bins - h + 1) // 2
        pw_ = (kw * bins - w + 1) // 2
        level_out = _pool(x, (kh, kw), (kh, kw), (ph_, pw_), ptype,
                          True, False, False, False)
        outs.append(level_out.reshape(n, c * bins * bins))
    ctx.out(op, 'Out', jnp.concatenate(outs, 1))


# ---------------------------------------------------------------------------
# conv_shift / modified huber / similarity focus / pn-pair
# ---------------------------------------------------------------------------

@register_op('conv_shift')
def _conv_shift(ctx, op):
    """reference conv_shift_op.cc (NTM circular convolution):
    Out[i] = sum_j X[(i + j) mod M] * Y[j], j centered on 0."""
    x = ctx.in1(op, 'X')            # [B, M]
    y = ctx.in1(op, 'Y')            # [B, N], N odd
    m = x.shape[1]
    n = y.shape[1]
    half = (n - 1) // 2
    shifts = jnp.arange(m)[:, None] + (jnp.arange(n)[None, :] - half)
    idx = jnp.mod(shifts, m)                       # [M, N]
    gathered = x[:, idx]                           # [B, M, N]
    ctx.out(op, 'Out', jnp.sum(gathered * y[:, None, :], -1))


@register_op('modified_huber_loss')
def _modified_huber_loss(ctx, op):
    """reference modified_huber_loss_op.cc: binary labels in {0,1} mapped
    to {-1,1}; quadratic inside the margin, linear outside."""
    x = ctx.in1(op, 'X').reshape(-1)
    y = ctx.in1(op, 'Y').reshape(-1).astype(x.dtype) * 2.0 - 1.0
    prod = x * y
    loss = jnp.where(prod >= -1.0,
                     jnp.square(jnp.maximum(0.0, 1.0 - prod)),
                     -4.0 * prod)
    ctx.out(op, 'IntermediateVal', prod.reshape(-1, 1))
    ctx.out(op, 'Out', loss.reshape(-1, 1))


@register_op('similarity_focus')
def _similarity_focus(ctx, op):
    """reference similarity_focus_op.cc: greedy row/column-exclusive
    argmax mask over the plane selected by (axis, indexes), broadcast to
    X's shape."""
    x = ctx.in1(op, 'X')            # [N, A, B, C]
    axis = op.attr('axis')
    indexes = [int(i) for i in op.attr('indexes')]
    if axis not in (1, 2, 3):
        raise ValueError("similarity_focus: axis must be 1, 2 or 3")

    def greedy_mask(t):
        """t [B, C] -> 0/1 mask with min(B, C) exclusive maxima."""
        b, c = t.shape
        k = min(b, c)

        def body(_, state):
            mask, rowf, colf = state
            masked = jnp.where(rowf[:, None] & colf[None, :], t, -jnp.inf)
            p = jnp.argmax(masked)
            i, j = p // c, p % c
            mask = mask.at[i, j].set(1.0)
            rowf = rowf.at[i].set(False)
            colf = colf.at[j].set(False)
            return mask, rowf, colf

        mask, _, _ = lax.fori_loop(
            0, k, body, (jnp.zeros_like(t), jnp.ones((b,), bool),
                         jnp.ones((c,), bool)))
        return mask

    moved = jnp.moveaxis(x, axis, 1)           # [N, S, P, Q]
    planes = moved[:, jnp.asarray(indexes)]    # [N, len(idx), P, Q]
    masks = jax.vmap(jax.vmap(greedy_mask))(planes)
    combined = jnp.max(masks, axis=1)          # elementwise-or
    out = jnp.broadcast_to(combined[:, None], moved.shape)
    ctx.out(op, 'Out', jnp.moveaxis(out, 1, axis).astype(x.dtype))


@register_op('positive_negative_pair')
def _positive_negative_pair(ctx, op):
    """reference positive_negative_pair_op.cc: count correctly/incorrectly
    ordered (pos, neg) pairs per query for LTR eval. QueryID groups rows;
    ties count as 0.5/0.5."""
    score = ctx.in1(op, 'Score').reshape(-1)
    label = ctx.in1(op, 'Label').reshape(-1)
    qid = ctx.in1(op, 'QueryID').reshape(-1)
    weight = ctx.in1(op, 'Weight')
    acc_pos = ctx.in1(op, 'AccumulatePositivePair')
    acc_neg = ctx.in1(op, 'AccumulateNegativePair')
    acc_neu = ctx.in1(op, 'AccumulateNeutralPair')
    accs = (acc_pos, acc_neg, acc_neu)
    if any(a is not None for a in accs) and any(a is None for a in accs):
        raise ValueError(
            "positive_negative_pair: supply all three Accumulate* inputs "
            "or none")

    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), 1)
    pair = same_q & (upper > 0) & (label[:, None] != label[None, :])
    if weight is not None:
        wv = weight.reshape(-1)
        pw = (wv[:, None] + wv[None, :]) * 0.5   # reference: mean weight
    else:
        pw = jnp.ones_like(score)[:, None] * jnp.ones_like(score)[None, :]
    hi_first = label[:, None] > label[None, :]
    s_hi = jnp.where(hi_first, score[:, None], score[None, :])
    s_lo = jnp.where(hi_first, score[None, :], score[:, None])
    pos = jnp.sum(jnp.where(pair & (s_hi > s_lo), pw, 0.0))
    neg = jnp.sum(jnp.where(pair & (s_hi < s_lo), pw, 0.0))
    neu = jnp.sum(jnp.where(pair & (s_hi == s_lo), pw, 0.0))
    if acc_pos is not None:
        pos = pos + acc_pos.reshape(())
        neg = neg + acc_neg.reshape(())
        neu = neu + acc_neu.reshape(())
    ctx.out(op, 'PositivePair', pos.reshape(1))
    ctx.out(op, 'NegativePair', neg.reshape(1))
    ctx.out(op, 'NeutralPair', neu.reshape(1))


@register_op('get_places')
def _get_places(ctx, op):
    """reference controlflow/get_places_op.cc: device-count constant (the
    consumer ParallelDo is superseded by SPMD, but programs carrying the
    op still lower)."""
    count = op.attr('device_count', 0)
    if not count:
        count = len(jax.devices())
    ctx.out(op, 'Out', jnp.arange(count, dtype=jnp.int32))


# ---------------------------------------------------------------------------
# tree_conv (TBCNN, reference tree_conv_op + math/tree2col)
# ---------------------------------------------------------------------------

def _tree_patch_maps(edges, max_node, max_depth):
    """numpy port of Tree2ColUtil: per root node, the DFS patch (node,
    eta_l, eta_r, eta_t) truncated at max_depth. Returns dense
    [n_nodes, max_patch] index + [n_nodes, max_patch, 3] eta arrays."""
    tr = {}
    node_count = 0
    for u, v in edges:
        if u == 0 or v == 0:
            break
        tr.setdefault(int(u), []).append(int(v))
        node_count += 1
    node_count += 1
    if node_count > max_node:
        raise ValueError(
            "tree_conv: EdgeSet implies %d nodes but NodesVector has "
            "only %d rows" % (node_count, max_node))

    patches = []
    for root in range(1, node_count + 1):
        # iterative DFS mirroring construct_patch
        patch = [(root, 1, 1, 0)]
        stack = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, _, _, depth = stack[-1]
            children = tr.get(node, [])
            advanced = False
            for i, v in enumerate(children):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(children), depth + 1))
                    patch.append((v, i + 1, len(children), depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        patches.append(patch)

    max_patch = max(len(p) for p in patches)
    idx = np.zeros((len(patches), max_patch), np.int32)
    eta = np.zeros((len(patches), max_patch, 3), np.float32)
    for r, patch in enumerate(patches):
        for k, (node, index, pclen, depth) in enumerate(patch):
            # reference math/tree2col.h eta formulas
            eta_t = (max_depth - depth) / max_depth
            tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            idx[r, k] = node - 1
            eta[r, k] = (eta_l, eta_r, eta_t)
    return idx, eta, len(patches)


@register_op('tree_conv', static_inputs=('EdgeSet',))
def _tree_conv(ctx, op):
    """reference tree_conv_op.h: per sample, tree2col builds a
    [nodes, 3*F] patch matrix (eta-weighted sums over each node's
    max_depth neighborhood), then patch @ Filter. The tree structure
    (EdgeSet) binds statically — the static-LoD policy applied to trees."""
    nodes = ctx.in1(op, 'NodesVector')     # [N, max_nodes, F]
    filt = ctx.in1(op, 'Filter')           # [F, 3, out_size, num_filters]
    edges = ctx.in1_static(op, 'EdgeSet')  # [N, max_edges, 2] static
    max_depth = op.attr('max_depth')
    n, max_nodes, f = nodes.shape
    out_size, num_filters = filt.shape[2], filt.shape[3]
    w = jnp.reshape(filt, (f * 3, out_size * num_filters))

    outs = []
    for b in range(n):
        idx, eta, n_nodes = _tree_patch_maps(
            np.asarray(edges[b]).reshape(-1, 2), max_nodes, max_depth)
        feats = nodes[b][jnp.asarray(idx)]          # [nodes, P, F]
        etas = jnp.asarray(eta)                     # [nodes, P, 3]
        # patch[:, i*3+k] = sum_p eta[p,k] * feat[p,i]
        patch = jnp.einsum('npf,npk->nfk', feats, etas)  # [nodes, F, 3]
        patch = patch.reshape(n_nodes, f * 3)
        out = patch @ w                              # [nodes, OS*NF]
        pad = jnp.zeros((max_nodes - n_nodes, out.shape[1]), out.dtype)
        outs.append(jnp.concatenate([out, pad], 0))
    out = jnp.stack(outs).reshape(n, max_nodes, out_size, num_filters)
    ctx.out(op, 'Out', out)


# ---------------------------------------------------------------------------
# py_func: host callback (reference py_func_op.cc, SURVEY §7 hard part 7)
# ---------------------------------------------------------------------------

_py_func_registry = []


def register_py_func(fn):
    _py_func_registry.append(fn)
    return len(_py_func_registry) - 1


@register_op('py_func')
def _py_func(ctx, op):
    """reference operators/py_func_op.cc: call a registered Python callable
    on host with the op's inputs; outputs' shapes/dtypes come from the
    declared out vars. Lowers to jax.pure_callback; with a registered
    backward callable the grad is a second pure_callback (reference
    py_func grad registration)."""
    xs = ctx.in_list(op, 'X')
    fwd_id = op.attr('forward_callable_id')
    bwd_id = op.attr('backward_callable_id', -1)
    out_names = op.output('Out')
    shapes, dtypes = [], []
    for nm in out_names:
        v = ctx.var(nm)
        if v is None or v.shape is None or any(
                d is None or d < 0 for d in v.shape):
            raise ValueError(
                "py_func output %r needs a fully-known static shape "
                "(host callbacks cannot infer shapes under XLA)" % nm)
        shapes.append(tuple(v.shape))
        dtypes.append(v.dtype)
    result_spec = tuple(jax.ShapeDtypeStruct(s, d)
                        for s, d in zip(shapes, dtypes))
    if fwd_id >= len(_py_func_registry) or \
            (bwd_id >= 0 and bwd_id >= len(_py_func_registry)):
        raise ValueError(
            "py_func callable id %d is not registered in this process — "
            "py_func programs are not serializable across processes; "
            "rebuild the program (layers.py_func re-registers the "
            "callables)" % max(fwd_id, bwd_id))
    fwd = _py_func_registry[fwd_id]

    def host_call(*arrays):
        out = fwd(*arrays)
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(np.asarray(o).astype(d.dtype).reshape(d.shape)
                     for o, d in zip(out, result_spec))

    if ctx.params.get('host_eager'):
        # executor host segment (backends without callback support): the
        # values are concrete — call the registered function directly
        outs = host_call(*[np.asarray(x) for x in xs])
    elif bwd_id < 0:
        outs = jax.pure_callback(host_call, result_spec, *xs)
    else:
        bwd = _py_func_registry[bwd_id]
        # reference py_func_op.cc backward: callable receives (forward
        # inputs minus skip_vars_in_backward_input) + forward outputs +
        # output grads, and returns a grad per (non-skipped) input; skipped
        # inputs get zero grads
        skip = set(op.attr('backward_skip_inputs', []) or [])
        in_names = op.input('X')
        keep_idx = [i for i, nm in enumerate(in_names) if nm not in skip]

        @jax.custom_vjp
        def call(*args):
            return jax.pure_callback(host_call, result_spec, *args)

        def call_fwd(*args):
            outs = jax.pure_callback(host_call, result_spec, *args)
            return outs, (args, outs)

        def call_bwd(res, cts):
            args, outs_v = res
            in_spec = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                            for a in args)
            kept_spec = tuple(in_spec[i] for i in keep_idx)

            def host_grad(*arrays):
                grads = bwd(*arrays)
                grads = grads if isinstance(grads, (list, tuple)) \
                    else [grads]
                return tuple(
                    np.asarray(g).astype(s.dtype).reshape(s.shape)
                    for g, s in zip(grads, kept_spec))

            kept_args = tuple(args[i] for i in keep_idx)
            kept_grads = jax.pure_callback(host_grad, kept_spec,
                                           *kept_args, *outs_v, *cts)
            full = [jnp.zeros(s.shape, s.dtype) for s in in_spec]
            for i, g in zip(keep_idx, kept_grads):
                full[i] = g
            return tuple(full)

        call.defvjp(call_fwd, call_bwd)
        outs = call(*xs)

    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    for nm, o in zip(out_names, outs):
        ctx.set(nm, o)


@register_op('switch_moe')
def _switch_moe_op(ctx, op):
    """Program-level switch-MoE FFN (TPU-native EP extension; functional
    core in parallel/moe.py). Inputs X [N, T, d] (or [n, d]), RouterW
    [d, E], ExpertWIn [E, d, ff], ExpertBIn [E, ff], ExpertWOut [E, ff, d],
    ExpertBOut [E, d]; outputs Out (same shape as X, dropped tokens zero —
    add the residual in the program) and AuxLoss (scalar load-balancing
    term). Under an active mesh with an 'expert' axis the all_to_all EP
    dataflow runs; otherwise a dense single-device evaluation."""
    x = ctx.in1(op, 'X')
    rw = ctx.in1(op, 'RouterW')
    wi = ctx.in1(op, 'ExpertWIn')
    bi = ctx.in1(op, 'ExpertBIn')
    wo = ctx.in1(op, 'ExpertWOut')
    bo = ctx.in1(op, 'ExpertBOut')
    cf = float(op.attr('capacity_factor', 1.25))
    orig_shape = x.shape
    xt = x.reshape(-1, x.shape[-1])
    from ..parallel.api import get_active_mesh
    mesh = get_active_mesh()
    n_exp = wi.shape[0]
    if mesh is not None and 'expert' in mesh.axis_names and \
            mesh.shape['expert'] > 1 and \
            n_exp % mesh.shape['expert'] == 0 and \
            xt.shape[0] % mesh.shape['expert'] == 0:
        from ..parallel.moe import switch_moe
        out, aux = switch_moe(xt, rw, wi, bi, wo, bo, mesh,
                              capacity_factor=cf)
    else:
        # dense single-device evaluation (same semantics, no drops)
        probs = jax.nn.softmax(xt @ rw, axis=-1)
        idx = jnp.argmax(probs, axis=-1)
        gate = jnp.max(probs, axis=-1)
        h = jax.nn.relu(jnp.einsum('nd,edf->enf', xt, wi)
                        + bi[:, None, :])
        y_all = jnp.einsum('enf,efd->end', h, wo) + bo[:, None, :]
        sel = jax.nn.one_hot(idx, n_exp, dtype=xt.dtype)   # [n, E]
        out = jnp.einsum('ne,end->nd', sel, y_all) * gate[:, None]
        frac_tokens = jnp.mean(sel, axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        aux = n_exp * jnp.sum(frac_tokens * frac_probs)
    ctx.out(op, 'Out', out.reshape(orig_shape))
    ctx.out(op, 'AuxLoss', aux.reshape(1))
