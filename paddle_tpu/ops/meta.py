"""Meta / framework ops: backward, feed/fetch boundary, constants, casts.

Reference counterparts: controlflow/feed_op.cc, fetch_op.cc (subsumed by the
compiled function's inputs/outputs), fill_constant_op.cc, assign_op.cc,
cast_op.cc, scale_op.cc, increment_op.cc, clip_op.cc, clip_by_norm_op.cc,
fill_zeros_like_op.cc, shape_op.cc, print_op.cc.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax  # noqa: F401

from ..core.registry import register_op
from .common import np_dtype


@register_op('backward')
def _backward(ctx, op):
    # Never lowered directly: core/lowering.py:lower_block intercepts it and
    # runs the forward segment under jax.vjp. Reaching here is a bug.
    raise RuntimeError("'backward' op must be handled by lower_block")


@register_op('feed')
def _feed(ctx, op):
    # feed values are function inputs; nothing to do (kept for program parity)
    pass


@register_op('fetch')
def _fetch(ctx, op):
    ctx.out(op, 'Out', ctx.in1(op, 'X'))


@register_op('fill_constant')
def _fill_constant(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape', ()))
    value = op.attr('value', 0.0)
    ctx.out(op, 'Out', jnp.full(shape, value, dtype=dtype))
    # the value is a trace-time constant; record it so shape-bearing
    # consumers (TensorArray write indices etc.) can stay static. Only
    # small constants — the consumers need scalars, not zeroed buffers.
    if int(np.prod(shape or (1,))) <= 16:
        ctx.set_static(op.output('Out')[0],
                       np.full(shape, value, dtype=dtype))


@register_op('fill_constant_batch_size_like')
def _fill_constant_bsl(ctx, op):
    x = ctx.in1(op, 'Input')
    dtype = np_dtype(op.attr('dtype'))
    shape = list(op.attr('shape'))
    in_idx = op.attr('input_dim_idx', 0)
    out_idx = op.attr('output_dim_idx', 0)
    shape[out_idx] = x.shape[in_idx]
    ctx.out(op, 'Out', jnp.full(tuple(shape), op.attr('value', 0.0),
                                dtype=dtype))


@register_op('fill_zeros_like')
def _fill_zeros_like(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.zeros_like(x))


@register_op('fill')
def _fill(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape'))
    value = np.asarray(op.attr('value'), dtype=dtype).reshape(shape)
    ctx.out(op, 'Out', jnp.asarray(value))


@register_op('assign')
def _assign(ctx, op):
    ctx.out(op, 'Out', ctx.in1(op, 'X'))


@register_op('assign_value')
def _assign_value(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape'))
    values = op.attr('values')
    ctx.out(op, 'Out', jnp.asarray(np.asarray(values, dtype=dtype)
                                   .reshape(shape)))


@register_op('shape')
def _shape(ctx, op):
    x = ctx.in1(op, 'Input')
    ctx.out(op, 'Out', jnp.asarray(np.asarray(x.shape, dtype=np.int32)))


@register_op('cast')
def _cast(ctx, op):
    x = ctx.in1(op, 'X')
    out_dtype = np_dtype(op.attr('out_dtype'))
    ctx.out(op, 'Out', x.astype(out_dtype))


@register_op('scale')
def _scale(ctx, op):
    x = ctx.in1(op, 'X')
    scale = op.attr('scale', 1.0)
    bias = op.attr('bias', 0.0)
    bias_after_scale = op.attr('bias_after_scale', True)
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        # reference scale_op SelectedRows kernel: scale values, keep rows.
        # A bias would have to touch every implicit zero row too -> densify.
        if bias != 0.0:
            x = x.to_dense()
        else:
            ctx.out(op, 'Out', x.scale(scale))
            return
    if bias_after_scale:
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    ctx.out(op, 'Out', out.astype(x.dtype))


@register_op('increment')
def _increment(ctx, op):
    x = ctx.in1(op, 'X')
    step = op.attr('step', 1.0)
    ctx.out(op, 'Out', x + jnp.asarray(step, dtype=x.dtype))


@register_op('clip')
def _clip(ctx, op):
    x = ctx.in1(op, 'X')
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        # merge duplicates first: clip does not distribute over addition,
        # so clipping per-occurrence values would diverge from the dense
        # equivalent when an id repeats in the batch
        rows, vals = x.merged()
        ctx.out(op, 'Out', SelectedRows(
            rows, jnp.clip(vals, op.attr('min'), op.attr('max')), x.height))
        return
    ctx.out(op, 'Out', jnp.clip(x, op.attr('min'), op.attr('max')))


@register_op('clip_by_norm')
def _clip_by_norm(ctx, op):
    """reference clip_by_norm_op.h (dense + SelectedRows kernel: merge rows,
    then clip values by the merged norm)."""
    x = ctx.in1(op, 'X')
    max_norm = op.attr('max_norm')
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        rows, vals = x.merged()
        norm = jnp.sqrt(jnp.sum(vals.astype(jnp.float32) ** 2))
        factor = jnp.where(norm > max_norm,
                           max_norm / jnp.maximum(norm, 1e-12), 1.0)
        ctx.out(op, 'Out', SelectedRows(
            rows, vals * factor.astype(vals.dtype), x.height))
        return
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    factor = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                       1.0)
    ctx.out(op, 'Out', (x * factor.astype(x.dtype)))


@register_op('print')
def _print(ctx, op):
    x = ctx.in1(op, 'X')
    message = op.attr('message', '')
    if ctx.params.get('host_eager'):
        # executor host segment: the value is concrete — print directly
        print(message, np.asarray(x))
    else:
        # jax.debug.print needs host-callback support, which is probed
        # (not inferred from the backend NAME — the axon relay reports
        # 'tpu' yet rejects send/recv callbacks at run time). Main-block
        # prints after the backward op get the segmented host path; a
        # print in a differentiated forward span or inside a control-flow
        # sub-block cannot be split out, so on callback-less backends it
        # degrades to a passthrough instead of a runtime abort.
        from ..executor import _callbacks_supported
        try:
            supports_cb = _callbacks_supported()
        except Exception:
            supports_cb = False
        if supports_cb:
            jax.debug.print(message + " {}", x)
    ctx.out(op, 'Out', x)


@register_op('one_hot')
def _one_hot(ctx, op):
    x = ctx.in1(op, 'X')
    depth = op.attr('depth')
    ids = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    out = jax.nn.one_hot(ids, depth, dtype=jnp.float32)
    ctx.out(op, 'Out', out)


@register_op('sharding_constraint')
def _sharding_constraint(ctx, op):
    """Pin an activation's sharding (TPU-native primitive; no reference
    analog — this is how sequence/activation parallelism is expressed).
    No-op when traced outside a mesh context."""
    x = ctx.in1(op, 'X')
    spec = tuple(op.attr('spec', ()))
    try:
        from jax.sharding import PartitionSpec, NamedSharding
        from ..parallel import api as _papi
        mesh = _papi.get_active_mesh()
        if mesh is not None:
            axes = set(mesh.axis_names)
            ok = all((a is None or
                      (a in axes if isinstance(a, str)
                       else all(s in axes for s in a)))
                     for a in spec)
            if ok:
                x = jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, PartitionSpec(*spec)))
    except Exception:
        pass
    ctx.out(op, 'Out', x)


@register_op('is_empty')
def _is_empty(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.asarray(x.size == 0))


@register_op('delete_var')
def _delete_var(ctx, op):
    pass
