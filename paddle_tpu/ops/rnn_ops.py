"""Recurrent ops (LSTM/GRU family + row_conv) via lax.scan over padded
batches with static-LoD ragged <-> padded index maps.

Reference semantics (verified against the op specs, not ported):
- lstm_op.cc:106-179 — Input (T,4D) pre-projected, Weight (D,4D) =
  {W_ch,W_ih,W_fh,W_oh} (gate order [c,i,f,o]), Bias (1,4D) or (1,7D) with
  peepholes {b_c,b_i,b_f,b_o,W_ic,W_fc,W_oc}; i/f gates peek c_{t-1}, o gate
  peeks c_t (math/detail/lstm_kernel.h:30-51).
- lstmp_op.cc:137 — adds ProjWeight (D,P), recurrent state is the projection.
- gru_op.cc — Input (T,3D) [u,r,c], Weight (D,3D) = [W_u W_r | W_c], Bias
  (1,3D); h = (1-u)*h_prev + u*c_cand (math/detail/gru_kernel.h:58-68,
  origin_mode flips the convex combination).
- gru_unit_op.cc:104-114 — single step, activations as int enums
  (gru_unit_op.h:34 identity=0 sigmoid=1 tanh=2 relu=3).
- lstm_unit_op.cc — gate order [i,f,o,j], C = c_prev*sigm(f+forget_bias)
  + sigm(i)*tanh(j); H = sigm(o)*tanh(C)... (doc says H = C * sigm(o);
  kernel uses tanh(C)*sigm(o) — we follow the kernel, lstm_unit_op.h).
- row_conv_op.cc — lookahead conv: out_i = sum_j x_{i+j} .* W_j within the
  sequence.

The scan carries (N, D) state over maxT steps — batched matmuls each step,
MXU-friendly; XLA unrolls nothing and fuses the elementwise gate math.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.lod import lengths_from_offsets, context_maps


_ACT = {
    'identity': lambda x: x,
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'relu': jax.nn.relu,
}
_ACT_BY_ID = ['identity', 'sigmoid', 'tanh', 'relu']


def _act(name):
    if isinstance(name, int):
        name = _ACT_BY_ID[name]
    if name not in _ACT:
        raise NotImplementedError("rnn activation %r" % name)
    return _ACT[name]


def _padded_maps(offsets, reverse=False):
    """(gather_idx (N,maxT), scatter_idx (T,)) between ragged rows and a
    padded (N, maxT) layout. scatter_idx[t] = (n*maxT + step) of ragged row
    t. All numpy → static XLA constants. Padded lanes gather row 0 but are
    never scattered back, so no masking is needed."""
    lens = lengths_from_offsets(offsets)
    n, maxt = len(lens), (max(lens) if lens else 0)
    gidx = np.zeros((n, maxt), dtype=np.int32)
    sidx = np.zeros((offsets[-1],), dtype=np.int32)
    for i, ln in enumerate(lens):
        rows = np.arange(offsets[i], offsets[i + 1])
        steps = np.arange(ln)
        if reverse:
            rows = rows[::-1]
        gidx[i, :ln] = rows
        sidx[rows] = i * maxt + steps
    return gidx, sidx, n, maxt


def _to_padded(x, gidx, n, maxt):
    return jnp.take(x, jnp.asarray(gidx.reshape(-1)), axis=0).reshape(
        (n, maxt) + x.shape[1:])


def _to_ragged(padded, sidx):
    flat = padded.reshape((-1,) + padded.shape[2:])
    return jnp.take(flat, jnp.asarray(sidx), axis=0)


def _lod_offsets(ctx, op, slot='Input'):
    lod = ctx.in1_lod(op, slot)
    if not lod:
        raise ValueError(
            "op %s requires LoD input (ragged sequences); feed (array, lod)"
            % op.type)
    return lod, lod[-1]


# ---------------------------------------------------------------------------
# lstm / lstmp
# ---------------------------------------------------------------------------

def _lstm_impl(ctx, op, with_projection):
    x = ctx.in1(op, 'Input')                    # (T, 4D) ragged
    w = ctx.in1(op, 'Weight')                   # (D,4D); lstmp: (P,4D)
    bias = ctx.in1(op, 'Bias')                  # (1, 4D) or (1, 7D)
    lod, offsets = _lod_offsets(ctx, op)
    # frame size D comes from the gate width (reference lstmp_op.cc:51-63:
    # Weight is (P, 4D) under projection, so w.shape[0] would be P)
    d = w.shape[1] // 4
    use_peepholes = bool(op.attr('use_peepholes', True))
    reverse = bool(op.attr('is_reverse', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_state = _act(op.attr('cell_activation', 'tanh'))
    act_cand = _act(op.attr('candidate_activation', 'tanh'))

    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(x, gidx, n, maxt)           # (N, maxT, 4D)

    b = bias.reshape(-1)
    b_gates = b[:4 * d]
    if use_peepholes:
        w_ic, w_fc, w_oc = b[4 * d:5 * d], b[5 * d:6 * d], b[6 * d:7 * d]
    else:
        w_ic = w_fc = w_oc = jnp.zeros((d,), x.dtype)

    if with_projection:
        proj_w = ctx.in1(op, 'ProjWeight')      # (D, P)
        p = proj_w.shape[1]
        act_proj = _act(op.attr('proj_activation', 'tanh'))
        rec_dim = p
    else:
        rec_dim = d

    h0 = ctx.in1(op, 'H0')
    c0 = ctx.in1(op, 'C0')
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, rec_dim), x.dtype)
    c_init = c0.astype(x.dtype) if c0 is not None else \
        jnp.zeros((n, d), x.dtype)

    def step(carry, xt):
        h_prev, c_prev = carry
        gates = xt + b_gates + h_prev @ w          # (N, 4D)
        gc = gates[:, 0:d]
        gi = gates[:, d:2 * d]
        gf = gates[:, 2 * d:3 * d]
        go = gates[:, 3 * d:4 * d]
        cand = act_cand(gc)
        i = act_gate(gi + c_prev * w_ic)
        f = act_gate(gf + c_prev * w_fc)
        c = cand * i + c_prev * f
        o = act_gate(go + c * w_oc)
        h = o * act_state(c)
        if with_projection:
            h = act_proj(h @ proj_w)
        gate_out = jnp.concatenate([cand, i, f, o], axis=1)
        return (h, c), (h, c, gate_out)

    (_, _), (hs, cs, gs) = lax.scan(step, (h_init, c_init),
                                    xp.transpose(1, 0, 2))
    hs = hs.transpose(1, 0, 2)                  # (N, maxT, rec)
    cs = cs.transpose(1, 0, 2)
    gs = gs.transpose(1, 0, 2)

    hidden = _to_ragged(hs, sidx)
    cell = _to_ragged(cs, sidx)
    out_slot = 'Projection' if with_projection else 'Hidden'
    ctx.out(op, out_slot, hidden)
    if op.output(out_slot):
        ctx.set_lod(op.output(out_slot)[0], lod)
    ctx.out(op, 'Cell', cell)
    if op.output('Cell'):
        ctx.set_lod(op.output('Cell')[0], lod)
    if op.output('BatchGate'):
        ctx.out(op, 'BatchGate', _to_ragged(gs, sidx))
    if op.output('BatchCellPreAct'):
        ctx.out(op, 'BatchCellPreAct', cell)
    if with_projection and op.output('Hidden'):
        # lstmp also exposes the pre-projection hidden? reference outputs
        # Projection (main) + (Batch)Hidden intermediates; we give cell-side
        ctx.out(op, 'Hidden', hidden)


@register_op('lstm')
def _lstm(ctx, op):
    _lstm_impl(ctx, op, with_projection=False)


@register_op('lstmp')
def _lstmp(ctx, op):
    _lstm_impl(ctx, op, with_projection=True)


# ---------------------------------------------------------------------------
# gru (dynamic) — reference gru_op.cc
# ---------------------------------------------------------------------------

@register_op('gru')
def _gru(ctx, op):
    x = ctx.in1(op, 'Input')                    # (T, 3D) [u, r, c]
    w = ctx.in1(op, 'Weight')                   # (D, 3D) [W_u W_r | W_c]
    lod, offsets = _lod_offsets(ctx, op)
    d = w.shape[0]
    bias = ctx.in1(op, 'Bias')
    b = bias.reshape(-1) if bias is not None else jnp.zeros((3 * d,), x.dtype)
    reverse = bool(op.attr('is_reverse', False))
    origin_mode = bool(op.attr('origin_mode', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_node = _act(op.attr('activation', 'tanh'))

    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(x, gidx, n, maxt)

    w_ur = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    h0 = ctx.in1(op, 'H0')
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, d), x.dtype)

    def step(h_prev, xt):
        xur = xt[:, :2 * d] + b[:2 * d]
        xc = xt[:, 2 * d:] + b[2 * d:]
        ur = act_gate(xur + h_prev @ w_ur)
        u, r = ur[:, :d], ur[:, d:]
        c = act_node(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = u * h_prev + (1.0 - u) * c
        else:
            h = (1.0 - u) * h_prev + u * c
        return h, (h, jnp.concatenate([ur, c], axis=1), r * h_prev)

    _, (hs, gs, rhs) = lax.scan(step, h_init, xp.transpose(1, 0, 2))
    hs = hs.transpose(1, 0, 2)
    hidden = _to_ragged(hs, sidx)
    ctx.out(op, 'Hidden', hidden)
    if op.output('Hidden'):
        ctx.set_lod(op.output('Hidden')[0], lod)
    if op.output('BatchGate'):
        ctx.out(op, 'BatchGate', _to_ragged(gs.transpose(1, 0, 2), sidx))
    if op.output('BatchResetHiddenPrev'):
        ctx.out(op, 'BatchResetHiddenPrev',
                _to_ragged(rhs.transpose(1, 0, 2), sidx))
    if op.output('BatchHidden'):
        ctx.out(op, 'BatchHidden', hidden)


# ---------------------------------------------------------------------------
# gru_unit — one step (reference gru_unit_op.cc; int activation enums)
# ---------------------------------------------------------------------------

@register_op('gru_unit')
def _gru_unit(ctx, op):
    x = ctx.in1(op, 'Input')                    # (N, 3D)
    h_prev = ctx.in1(op, 'HiddenPrev')          # (N, D)
    w = ctx.in1(op, 'Weight')                   # (D, 3D)
    bias = ctx.in1(op, 'Bias')
    d = h_prev.shape[1]
    b = bias.reshape(-1) if bias is not None else jnp.zeros((3 * d,), x.dtype)
    act_gate = _act(op.attr('gate_activation', 1))
    act_node = _act(op.attr('activation', 2))
    origin_mode = bool(op.attr('origin_mode', False))

    xur = x[:, :2 * d] + b[:2 * d]
    xc = x[:, 2 * d:] + b[2 * d:]
    ur = act_gate(xur + h_prev @ w[:, :2 * d])
    u, r = ur[:, :d], ur[:, d:]
    reset_h = r * h_prev
    c = act_node(xc + reset_h @ w[:, 2 * d:])
    if origin_mode:
        h = u * h_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * h_prev + u * c
    ctx.out(op, 'Gate', jnp.concatenate([ur, c], axis=1))
    ctx.out(op, 'ResetHiddenPrev', reset_h)
    ctx.out(op, 'Hidden', h)


# ---------------------------------------------------------------------------
# lstm_unit — one step (reference lstm_unit_op.cc; gate order [i,f,o,j])
# ---------------------------------------------------------------------------

@register_op('lstm_unit')
def _lstm_unit(ctx, op):
    x = ctx.in1(op, 'X')                        # (N, 4D)
    c_prev = ctx.in1(op, 'C_prev')              # (N, D)
    forget_bias = float(op.attr('forget_bias', 0.0))
    d = c_prev.shape[-1]
    i = x[..., 0:d]
    f = x[..., d:2 * d]
    o = x[..., 2 * d:3 * d]
    j = x[..., 3 * d:4 * d]
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    ctx.out(op, 'C', c)
    ctx.out(op, 'H', h)


# ---------------------------------------------------------------------------
# row_conv — lookahead convolution (reference row_conv_op.cc)
# ---------------------------------------------------------------------------

@register_op('row_conv')
def _row_conv(ctx, op):
    x = ctx.in1(op, 'X')                        # (T, D) ragged
    filt = ctx.in1(op, 'Filter')                # (context, D)
    lod, offsets = _lod_offsets(ctx, op, 'X')
    context = filt.shape[0]
    t = x.shape[0]

    idx, valid = context_maps(offsets, context, 0)
    gathered = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0) \
        .reshape(t, context, x.shape[1])
    gathered = gathered * jnp.asarray(valid)[:, :, None].astype(x.dtype)
    out = (gathered * filt[None, :, :]).sum(axis=1)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod)
