"""Recurrent ops (LSTM/GRU family + row_conv) via lax.scan over padded
batches with static-LoD ragged <-> padded index maps.

Reference semantics (verified against the op specs, not ported):
- lstm_op.cc:106-179 — Input (T,4D) pre-projected, Weight (D,4D) =
  {W_ch,W_ih,W_fh,W_oh} (gate order [c,i,f,o]), Bias (1,4D) or (1,7D) with
  peepholes {b_c,b_i,b_f,b_o,W_ic,W_fc,W_oc}; i/f gates peek c_{t-1}, o gate
  peeks c_t (math/detail/lstm_kernel.h:30-51).
- lstmp_op.cc:137 — adds ProjWeight (D,P), recurrent state is the projection.
- gru_op.cc — Input (T,3D) [u,r,c], Weight (D,3D) = [W_u W_r | W_c], Bias
  (1,3D); h = (1-u)*h_prev + u*c_cand (math/detail/gru_kernel.h:58-68,
  origin_mode flips the convex combination).
- gru_unit_op.cc:104-114 — single step, activations as int enums
  (gru_unit_op.h:34 identity=0 sigmoid=1 tanh=2 relu=3).
- lstm_unit_op.cc — gate order [i,f,o,j], C = c_prev*sigm(f+forget_bias)
  + sigm(i)*tanh(j); H = sigm(o)*tanh(C)... (doc says H = C * sigm(o);
  kernel uses tanh(C)*sigm(o) — we follow the kernel, lstm_unit_op.h).
- row_conv_op.cc — lookahead conv: out_i = sum_j x_{i+j} .* W_j within the
  sequence.

The scan carries (N, D) state over maxT steps — batched matmuls each step,
MXU-friendly; XLA unrolls nothing and fuses the elementwise gate math.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.lod import lengths_from_offsets, context_maps


_ACT = {
    'identity': lambda x: x,
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'relu': jax.nn.relu,
}
_ACT_BY_ID = ['identity', 'sigmoid', 'tanh', 'relu']


def _act(name):
    if isinstance(name, int):
        name = _ACT_BY_ID[name]
    if name not in _ACT:
        raise NotImplementedError("rnn activation %r" % name)
    return _ACT[name]


def _padded_maps(offsets, reverse=False):
    """(gather_idx (N,maxT), scatter_idx (T,)) between ragged rows and a
    padded (N, maxT) layout. scatter_idx[t] = (n*maxT + step) of ragged row
    t. All numpy → static XLA constants. Padded lanes gather row 0 but are
    never scattered back, so no masking is needed."""
    lens = lengths_from_offsets(offsets)
    n, maxt = len(lens), (max(lens) if lens else 0)
    gidx = np.zeros((n, maxt), dtype=np.int32)
    sidx = np.zeros((offsets[-1],), dtype=np.int32)
    for i, ln in enumerate(lens):
        rows = np.arange(offsets[i], offsets[i + 1])
        steps = np.arange(ln)
        if reverse:
            rows = rows[::-1]
        gidx[i, :ln] = rows
        sidx[rows] = i * maxt + steps
    return gidx, sidx, n, maxt


def _to_padded(x, gidx, n, maxt):
    return jnp.take(x, jnp.asarray(gidx.reshape(-1)), axis=0).reshape(
        (n, maxt) + x.shape[1:])


def _to_ragged(padded, sidx):
    flat = padded.reshape((-1,) + padded.shape[2:])
    return jnp.take(flat, jnp.asarray(sidx), axis=0)


def _lod_offsets(ctx, op, slot='Input'):
    lod = ctx.in1_lod(op, slot)
    if not lod:
        raise ValueError(
            "op %s requires LoD input (ragged sequences); feed (array, lod)"
            % op.type)
    return lod, lod[-1]


# ---------------------------------------------------------------------------
# lstm / lstmp
# ---------------------------------------------------------------------------

def _lstm_impl(ctx, op, with_projection):
    x = ctx.in1(op, 'Input')                    # (T, 4D) ragged
    w = ctx.in1(op, 'Weight')                   # (D,4D); lstmp: (P,4D)
    bias = ctx.in1(op, 'Bias')                  # (1, 4D) or (1, 7D)
    lod, offsets = _lod_offsets(ctx, op)
    # frame size D comes from the gate width (reference lstmp_op.cc:51-63:
    # Weight is (P, 4D) under projection, so w.shape[0] would be P)
    d = w.shape[1] // 4
    use_peepholes = bool(op.attr('use_peepholes', True))
    reverse = bool(op.attr('is_reverse', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_state = _act(op.attr('cell_activation', 'tanh'))
    act_cand = _act(op.attr('candidate_activation', 'tanh'))

    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(x, gidx, n, maxt)           # (N, maxT, 4D)

    b = bias.reshape(-1)
    b_gates = b[:4 * d]
    if use_peepholes:
        w_ic, w_fc, w_oc = b[4 * d:5 * d], b[5 * d:6 * d], b[6 * d:7 * d]
    else:
        w_ic = w_fc = w_oc = jnp.zeros((d,), x.dtype)

    if with_projection:
        proj_w = ctx.in1(op, 'ProjWeight')      # (D, P)
        p = proj_w.shape[1]
        act_proj = _act(op.attr('proj_activation', 'tanh'))
        rec_dim = p
    else:
        rec_dim = d

    h0 = ctx.in1(op, 'H0')
    c0 = ctx.in1(op, 'C0')
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, rec_dim), x.dtype)
    c_init = c0.astype(x.dtype) if c0 is not None else \
        jnp.zeros((n, d), x.dtype)

    def step(carry, xt):
        h_prev, c_prev = carry
        gates = xt + b_gates + h_prev @ w          # (N, 4D)
        gc = gates[:, 0:d]
        gi = gates[:, d:2 * d]
        gf = gates[:, 2 * d:3 * d]
        go = gates[:, 3 * d:4 * d]
        cand = act_cand(gc)
        i = act_gate(gi + c_prev * w_ic)
        f = act_gate(gf + c_prev * w_fc)
        c = cand * i + c_prev * f
        o = act_gate(go + c * w_oc)
        h = o * act_state(c)
        if with_projection:
            h = act_proj(h @ proj_w)
        gate_out = jnp.concatenate([cand, i, f, o], axis=1)
        return (h, c), (h, c, gate_out)

    (_, _), (hs, cs, gs) = lax.scan(step, (h_init, c_init),
                                    xp.transpose(1, 0, 2))
    hs = hs.transpose(1, 0, 2)                  # (N, maxT, rec)
    cs = cs.transpose(1, 0, 2)
    gs = gs.transpose(1, 0, 2)

    hidden = _to_ragged(hs, sidx)
    cell = _to_ragged(cs, sidx)
    out_slot = 'Projection' if with_projection else 'Hidden'
    ctx.out(op, out_slot, hidden)
    if op.output(out_slot):
        ctx.set_lod(op.output(out_slot)[0], lod)
    ctx.out(op, 'Cell', cell)
    if op.output('Cell'):
        ctx.set_lod(op.output('Cell')[0], lod)
    if op.output('BatchGate'):
        ctx.out(op, 'BatchGate', _to_ragged(gs, sidx))
    if op.output('BatchCellPreAct'):
        ctx.out(op, 'BatchCellPreAct', cell)
    if with_projection and op.output('Hidden'):
        # lstmp also exposes the pre-projection hidden? reference outputs
        # Projection (main) + (Batch)Hidden intermediates; we give cell-side
        ctx.out(op, 'Hidden', hidden)


@register_op('lstm')
def _lstm(ctx, op):
    _lstm_impl(ctx, op, with_projection=False)


@register_op('lstmp')
def _lstmp(ctx, op):
    _lstm_impl(ctx, op, with_projection=True)


# ---------------------------------------------------------------------------
# gru (dynamic) — reference gru_op.cc
# ---------------------------------------------------------------------------

@register_op('gru')
def _gru(ctx, op):
    x = ctx.in1(op, 'Input')                    # (T, 3D) [u, r, c]
    w = ctx.in1(op, 'Weight')                   # (D, 3D) [W_u W_r | W_c]
    lod, offsets = _lod_offsets(ctx, op)
    d = w.shape[0]
    bias = ctx.in1(op, 'Bias')
    b = bias.reshape(-1) if bias is not None else jnp.zeros((3 * d,), x.dtype)
    reverse = bool(op.attr('is_reverse', False))
    origin_mode = bool(op.attr('origin_mode', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_node = _act(op.attr('activation', 'tanh'))

    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(x, gidx, n, maxt)

    w_ur = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    h0 = ctx.in1(op, 'H0')
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, d), x.dtype)

    def step(h_prev, xt):
        xur = xt[:, :2 * d] + b[:2 * d]
        xc = xt[:, 2 * d:] + b[2 * d:]
        ur = act_gate(xur + h_prev @ w_ur)
        u, r = ur[:, :d], ur[:, d:]
        c = act_node(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = u * h_prev + (1.0 - u) * c
        else:
            h = (1.0 - u) * h_prev + u * c
        return h, (h, jnp.concatenate([ur, c], axis=1), r * h_prev)

    _, (hs, gs, rhs) = lax.scan(step, h_init, xp.transpose(1, 0, 2))
    hs = hs.transpose(1, 0, 2)
    hidden = _to_ragged(hs, sidx)
    ctx.out(op, 'Hidden', hidden)
    if op.output('Hidden'):
        ctx.set_lod(op.output('Hidden')[0], lod)
    if op.output('BatchGate'):
        ctx.out(op, 'BatchGate', _to_ragged(gs.transpose(1, 0, 2), sidx))
    if op.output('BatchResetHiddenPrev'):
        ctx.out(op, 'BatchResetHiddenPrev',
                _to_ragged(rhs.transpose(1, 0, 2), sidx))
    if op.output('BatchHidden'):
        ctx.out(op, 'BatchHidden', hidden)


# ---------------------------------------------------------------------------
# gru_unit — one step (reference gru_unit_op.cc; int activation enums)
# ---------------------------------------------------------------------------

@register_op('gru_unit')
def _gru_unit(ctx, op):
    x = ctx.in1(op, 'Input')                    # (N, 3D)
    h_prev = ctx.in1(op, 'HiddenPrev')          # (N, D)
    w = ctx.in1(op, 'Weight')                   # (D, 3D)
    bias = ctx.in1(op, 'Bias')
    d = h_prev.shape[1]
    b = bias.reshape(-1) if bias is not None else jnp.zeros((3 * d,), x.dtype)
    act_gate = _act(op.attr('gate_activation', 1))
    act_node = _act(op.attr('activation', 2))
    origin_mode = bool(op.attr('origin_mode', False))

    xur = x[:, :2 * d] + b[:2 * d]
    xc = x[:, 2 * d:] + b[2 * d:]
    ur = act_gate(xur + h_prev @ w[:, :2 * d])
    u, r = ur[:, :d], ur[:, d:]
    reset_h = r * h_prev
    c = act_node(xc + reset_h @ w[:, 2 * d:])
    if origin_mode:
        h = u * h_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * h_prev + u * c
    ctx.out(op, 'Gate', jnp.concatenate([ur, c], axis=1))
    ctx.out(op, 'ResetHiddenPrev', reset_h)
    ctx.out(op, 'Hidden', h)


# ---------------------------------------------------------------------------
# lstm_unit — one step (reference lstm_unit_op.cc; gate order [i,f,o,j])
# ---------------------------------------------------------------------------

@register_op('lstm_unit')
def _lstm_unit(ctx, op):
    x = ctx.in1(op, 'X')                        # (N, 4D)
    c_prev = ctx.in1(op, 'C_prev')              # (N, D)
    forget_bias = float(op.attr('forget_bias', 0.0))
    d = c_prev.shape[-1]
    i = x[..., 0:d]
    f = x[..., d:2 * d]
    o = x[..., 2 * d:3 * d]
    j = x[..., 3 * d:4 * d]
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    ctx.out(op, 'C', c)
    ctx.out(op, 'H', h)


# ---------------------------------------------------------------------------
# row_conv — lookahead convolution (reference row_conv_op.cc)
# ---------------------------------------------------------------------------

@register_op('row_conv')
def _row_conv(ctx, op):
    x = ctx.in1(op, 'X')                        # (T, D) ragged
    filt = ctx.in1(op, 'Filter')                # (context, D)
    lod, offsets = _lod_offsets(ctx, op, 'X')
    context = filt.shape[0]
    t = x.shape[0]

    idx, valid = context_maps(offsets, context, 0)
    gathered = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0) \
        .reshape(t, context, x.shape[1])
    gathered = gathered * jnp.asarray(valid)[:, :, None].astype(x.dtype)
    out = (gathered * filt[None, :, :]).sum(axis=1)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod)


# ---------------------------------------------------------------------------
# attention_lstm — reference attention_lstm_op.cc
# ---------------------------------------------------------------------------

@register_op('attention_lstm')
def _attention_lstm(ctx, op):
    """reference operators/attention_lstm_op.cc:211-227 (doc) and the CPU
    kernel :335-404: per step, attention over the WHOLE sequence scored by
    fc([x, expand(c_{t-1})]) -> relu -> optional scalar fc -> relu ->
    softmax; the attended sum-pooled x drives one LSTM step with gate
    order [forget, input, output, candidate] (kernel :380-396).

    Batched TPU formulation: sequences padded to (N, maxT), softmax masked
    to valid rows; one lax.scan instead of the reference's per-sequence
    per-step BLAS loop."""
    x = ctx.in1(op, 'X')                       # LoD (T, M)
    c0 = ctx.in1(op, 'C0')                     # (N, D)
    h0 = ctx.in1(op, 'H0')
    atten_w = ctx.in1(op, 'AttentionWeight')   # (M+D, 1)
    atten_b = ctx.in1(op, 'AttentionBias')     # (1, 1) optional
    atten_s = ctx.in1(op, 'AttentionScalar')   # (1, 1) optional
    atten_sb = ctx.in1(op, 'AttentionScalarBias')
    lstm_w = ctx.in1(op, 'LSTMWeight')         # (D+M, 4D) [h-part; x-part]
    lstm_b = ctx.in1(op, 'LSTMBias')           # (1, 4D)
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_cell = _act(op.attr('cell_activation', 'tanh'))
    act_cand = _act(op.attr('candidate_activation', 'tanh'))

    lod, offsets = _lod_offsets(ctx, op, 'X')
    m = x.shape[1]
    d = lstm_w.shape[1] // 4
    gidx, sidx, n, maxt = _padded_maps(offsets)
    lens = jnp.asarray(lengths_from_offsets(offsets))
    mask = jnp.arange(maxt)[None, :] < lens[:, None]        # (N, maxT)

    # x(TxM) * atten_w[:M] part, shared across steps (kernel :336-338)
    atted_x = x @ atten_w[:m] + (atten_b.reshape(()) if atten_b is not None
                                 else 0.0)                  # (T, 1)
    xp = _to_padded(x, gidx, n, maxt)                       # (N, maxT, M)
    axp = _to_padded(atted_x, gidx, n, maxt)[..., 0]        # (N, maxT)

    w_h = lstm_w[:d]                                        # (D, 4D)
    w_x = lstm_w[d:]                                        # (M, 4D)
    b = lstm_b.reshape(-1)
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, d), x.dtype)
    c_init = c0.astype(x.dtype)

    def step(carry, t):
        h_prev, c_prev = carry
        cell_bias = c_prev @ atten_w[m:]                    # (N, 1)
        e = jax.nn.relu(axp + cell_bias)                    # (N, maxT)
        if atten_s is not None:
            e = e * atten_s.reshape(())
            e = jax.nn.relu(e + (atten_sb.reshape(())
                                 if atten_sb is not None else 0.0))
        e = jnp.where(mask, e, -1e30)
        p = jax.nn.softmax(e, axis=1)
        lstm_x = jnp.einsum('nt,ntm->nm', p, xp)            # (N, M)
        g = lstm_x @ w_x + h_prev @ w_h + b                 # (N, 4D)
        f = act_gate(g[:, :d])
        i = act_gate(g[:, d:2 * d])
        o = act_gate(g[:, 2 * d:3 * d])
        cand = act_cand(g[:, 3 * d:])
        c_new = f * c_prev + i * cand
        h_new = act_cell(c_new) * o
        active = mask[:, t][:, None]
        h = jnp.where(active, h_new, h_prev)
        c = jnp.where(active, c_new, c_prev)
        return (h, c), (h, c, p, lstm_x, g)

    (_, _), (hs, cs, ps, lxs, gs) = lax.scan(
        step, (h_init, c_init), jnp.arange(maxt))
    hs = hs.transpose(1, 0, 2)                              # (N, maxT, D)
    cs = cs.transpose(1, 0, 2)
    ctx.out(op, 'Hidden', _to_ragged(hs, sidx))
    ctx.out(op, 'Cell', _to_ragged(cs, sidx))
    for slot in ('Hidden', 'Cell'):
        if op.output(slot):
            ctx.set_lod(op.output(slot)[0], lod)
    ctx.out(op, 'AttentionedX', atted_x)
    # workspace outputs hold their values after the final step of the last
    # sequence, like the reference's reused scratch buffers
    ctx.out(op, 'AttentionFCOut', ps[-1, -1][:, None])      # (maxT, 1)
    ctx.out(op, 'LSTMX', lxs[-1, -1][None])                 # (1, M)
    ctx.out(op, 'LSTMOUT', gs[-1, -1][None])                # (1, 4D)


# ---------------------------------------------------------------------------
# cudnn_lstm — reference cudnn_lstm_op.cc (multi-layer dense LSTM)
# ---------------------------------------------------------------------------

@register_op('cudnn_lstm', needs_rng=True)
def _cudnn_lstm(ctx, op):
    """reference operators/cudnn_lstm_op.cc:56-125: dense (no-LoD)
    multi-layer, optionally bidirectional LSTM over Input
    [seq_len, batch, input_size] with one flat weight blob W.

    The cuDNN-packed blob layout is hardware-specific; the TPU-native blob
    is defined as, per layer then per direction:
      Wx (in_l, 4H) | Wh (H, 4H) | bx (4H) | bh (4H)
    with in_l = input_size at layer 0 else H*num_directions, gate order
    [i, f, c, o] (cuDNN's). Inter-layer dropout with prob `dropout_prob`
    when not is_test (cudnn_lstm_op.cc:109-124)."""
    x = ctx.in1(op, 'Input')                  # (T, B, in)
    init_h = ctx.in1(op, 'InitH')             # (L*dirs, B, H)
    init_c = ctx.in1(op, 'InitC')
    w = ctx.in1(op, 'W').reshape(-1)
    hidden = int(op.attr('hidden_size', 100))
    layers = int(op.attr('num_layers', 1))
    bidirec = bool(op.attr('is_bidirec', False))
    dropout = float(op.attr('dropout_prob', 0.0))
    is_test = bool(op.attr('is_test', False))
    dirs = 2 if bidirec else 1
    t_len, batch, in_size = x.shape

    def one_direction(inp, wx, wh, bx, bh, h0, c0, reverse):
        if reverse:
            inp = inp[::-1]

        def step(carry, xt):
            h_prev, c_prev = carry
            g = xt @ wx + h_prev @ wh + bx + bh
            i = jax.nn.sigmoid(g[:, :hidden])
            f = jax.nn.sigmoid(g[:, hidden:2 * hidden])
            cand = jnp.tanh(g[:, 2 * hidden:3 * hidden])
            o = jax.nn.sigmoid(g[:, 3 * hidden:])
            c = f * c_prev + i * cand
            h = o * jnp.tanh(c)
            return (h, c), h

        (h_last, c_last), hs = lax.scan(step, (h0, c0), inp)
        if reverse:
            hs = hs[::-1]
        return hs, h_last, c_last

    pos = 0

    def take(nelem, shape):
        nonlocal pos
        out = w[pos:pos + nelem].reshape(shape)
        pos += nelem
        return out

    cur = x
    last_h, last_c = [], []
    key = ctx.rng()
    for layer in range(layers):
        in_l = cur.shape[-1]
        outs = []
        for di in range(dirs):
            wx = take(in_l * 4 * hidden, (in_l, 4 * hidden))
            wh = take(hidden * 4 * hidden, (hidden, 4 * hidden))
            bx = take(4 * hidden, (4 * hidden,))
            bh = take(4 * hidden, (4 * hidden,))
            sidx_state = layer * dirs + di
            hs, h_l, c_l = one_direction(
                cur, wx, wh, bx, bh, init_h[sidx_state], init_c[sidx_state],
                reverse=(di == 1))
            outs.append(hs)
            last_h.append(h_l)
            last_c.append(c_l)
        cur = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if dropout and not is_test and layer < layers - 1:
            key = jax.random.fold_in(key, layer)
            keep = jax.random.bernoulli(key, 1.0 - dropout, cur.shape)
            cur = jnp.where(keep, cur / (1.0 - dropout), 0.0)
    ctx.out(op, 'Out', cur)
    ctx.out(op, 'last_h', jnp.stack(last_h))
    ctx.out(op, 'last_c', jnp.stack(last_c))
