"""Fused-kernel tier selection: PADDLE_FUSED_TIER + per-op dispatch.

The kernel tier decides HOW a fusable op lowers (SURVEY §2.4: the
reference's operators/fused/ + jit/ runtime-codegen layer picks a kernel
per op; here one knob picks the lowering family for every fused unit):

- ``off``      — the unfused composition, bit-identical to the lowering
                 that existed before the fused tier (the parity anchor:
                 ``PADDLE_FUSED_TIER=off`` reproduces legacy numerics).
- ``xla``      — a restructured single-expression emission that avoids
                 materializing large intermediates and leans on XLA's own
                 fusion (e.g. the one-hot-free cross-entropy backward, the
                 flattened whole-parameter-set Adam update). Also accepted
                 as ``xla-fused``.
- ``pallas``   — the hand-written Pallas kernels (TPU).
- ``interpret``— the same Pallas kernels through the interpreter
                 (CPU-testable cross-check, like attention's
                 ``use_pallas='interpret'``).

Default (unset/auto): ``pallas`` on a TPU backend, ``off`` elsewhere — CPU
test suites see legacy numerics unless they opt in.

Dispatch is resolved at TRACE time (op lowerings consult it while the
program compiles), so steady-state dispatch costs nothing per run; the
executor folds :func:`cache_token` — one env read — into its compile-cache
keys so flipping the knob recompiles instead of serving stale kernels.
Every resolution lands in the ``fused_kernel_dispatch_total{op,impl}``
counter, so bench counter deltas and obsreport show which tier actually
ran (and when a shape forced a per-op fallback).
"""
import os

import jax

from .. import monitor

__all__ = ['resolve_tier', 'dispatch', 'cache_token', 'TIERS']

TIERS = ('off', 'xla', 'pallas', 'interpret')

_ALIASES = {
    '': None, 'auto': None, 'default': None,
    'off': 'off', '0': 'off', 'none': 'off',
    'xla': 'xla', 'xla-fused': 'xla', 'xla_fused': 'xla', '1': 'xla',
    'pallas': 'pallas',
    'interpret': 'interpret',
}


def resolve_tier():
    """The requested tier: env override, else pallas on TPU / off on CPU."""
    raw = os.environ.get('PADDLE_FUSED_TIER', '')
    tier = _ALIASES.get(str(raw).strip().lower(), '__bad__')
    if tier == '__bad__':
        raise ValueError(
            "PADDLE_FUSED_TIER=%r: expected one of off|xla|pallas|interpret"
            % (raw,))
    if tier is not None:
        return tier
    return 'pallas' if jax.default_backend() == 'tpu' else 'off'


def cache_token():
    """The NORMALIZED tier spelling, for compile-cache keys (env read +
    one alias-dict read — the only per-run cost of the fused tier on the
    Executor hot path; backend probing and counters happen at trace
    time). Normalizing means 'off'/'0'/'none' (or ''/'auto') share cache
    entries instead of forcing a recompile over a spelling change; an
    unknown value keys as itself and raises at the next trace."""
    raw = os.environ.get('PADDLE_FUSED_TIER', '')
    return _ALIASES.get(str(raw).strip().lower(), raw)


def dispatch(op, pallas_ok=True, xla_ok=True, tier=None, count=True):
    """Resolve the impl for one fused unit and count the decision.

    ``pallas_ok``: the shapes tile for the Pallas kernel (when False, a
    pallas/interpret request degrades to the xla tier — the per-op
    fallback rule); ``xla_ok``: the restructured emission supports this
    op instance (else everything degrades to 'off'). ``count=False``
    skips the counter — used by lowerings re-entered on the sparse-grad
    SCOUT pass (core/lowering.py lowers the forward segment twice for
    is_sparse programs; counting both would double every dispatch the
    bench deltas report). Returns one of
    'off' | 'xla' | 'pallas' | 'interpret'.
    """
    impl = tier if tier is not None else resolve_tier()
    if impl in ('pallas', 'interpret') and not pallas_ok:
        impl = 'xla'
    if impl == 'xla' and not xla_ok:
        impl = 'off'
    if count:
        monitor.inc('fused_kernel_dispatch_total',
                    labels={'op': op, 'impl': impl})
    return impl
