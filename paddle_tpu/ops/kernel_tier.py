"""Fused-kernel tier selection: PADDLE_FUSED_TIER + per-op dispatch.

The kernel tier decides HOW a fusable op lowers (SURVEY §2.4: the
reference's operators/fused/ + jit/ runtime-codegen layer picks a kernel
per op; here one knob picks the lowering family for every fused unit):

- ``off``      — the unfused composition, bit-identical to the lowering
                 that existed before the fused tier (the parity anchor:
                 ``PADDLE_FUSED_TIER=off`` reproduces legacy numerics).
- ``xla``      — a restructured single-expression emission that avoids
                 materializing large intermediates and leans on XLA's own
                 fusion (e.g. the one-hot-free cross-entropy backward, the
                 flattened whole-parameter-set Adam update). Also accepted
                 as ``xla-fused``.
- ``pallas``   — the hand-written Pallas kernels (TPU).
- ``interpret``— the same Pallas kernels through the interpreter
                 (CPU-testable cross-check, like attention's
                 ``use_pallas='interpret'``).

Default (unset/auto): ``pallas`` on a TPU backend, ``off`` elsewhere — CPU
test suites see legacy numerics unless they opt in.

Dispatch is resolved at TRACE time (op lowerings consult it while the
program compiles), so steady-state dispatch costs nothing per run; the
executor folds :func:`cache_token` — one env read — into its compile-cache
keys so flipping the knob recompiles instead of serving stale kernels.
Every resolution lands in the ``fused_kernel_dispatch_total{op,impl,mesh}``
counter (``mesh='1'`` single-device, ``'n'`` under an active >1-device
mesh), so bench counter deltas and obsreport show which tier actually
ran (and when a shape forced a per-op fallback).

Mesh-native fused units partition through :func:`partitioned_call` — the
shard_map-over-mesh wrapper extracted from ops/attention_ops.py (riding
parallel/ring_attention._shard_map), so every fused unit shards the way
flash attention already does instead of falling back to the xla tier the
moment a mesh is active.
"""
import os

import jax

from .. import monitor

__all__ = ['resolve_tier', 'dispatch', 'cache_token', 'TIERS',
           'partitioned_call', 'mesh_axis']

TIERS = ('off', 'xla', 'pallas', 'interpret')

_ALIASES = {
    '': None, 'auto': None, 'default': None,
    'off': 'off', '0': 'off', 'none': 'off',
    'xla': 'xla', 'xla-fused': 'xla', 'xla_fused': 'xla', '1': 'xla',
    'pallas': 'pallas',
    'interpret': 'interpret',
}


def resolve_tier():
    """The requested tier: env override, else pallas on TPU / off on CPU."""
    raw = os.environ.get('PADDLE_FUSED_TIER', '')
    tier = _ALIASES.get(str(raw).strip().lower(), '__bad__')
    if tier == '__bad__':
        raise ValueError(
            "PADDLE_FUSED_TIER=%r: expected one of off|xla|pallas|interpret"
            % (raw,))
    if tier is not None:
        return tier
    return 'pallas' if jax.default_backend() == 'tpu' else 'off'


def cache_token():
    """The NORMALIZED tier spelling, for compile-cache keys (env read +
    one alias-dict read — the only per-run cost of the fused tier on the
    Executor hot path; backend probing and counters happen at trace
    time). Normalizing means 'off'/'0'/'none' (or ''/'auto') share cache
    entries instead of forcing a recompile over a spelling change; an
    unknown value keys as itself and raises at the next trace."""
    raw = os.environ.get('PADDLE_FUSED_TIER', '')
    return _ALIASES.get(str(raw).strip().lower(), raw)


def dispatch(op, pallas_ok=True, xla_ok=True, tier=None, count=True,
             mesh=None):
    """Resolve the impl for one fused unit and count the decision.

    ``pallas_ok``: the shapes tile for the Pallas kernel (when False, a
    pallas/interpret request degrades to the xla tier — the per-op
    fallback rule); ``xla_ok``: the restructured emission supports this
    op instance (else everything degrades to 'off'). ``count=False``
    skips the counter — used by lowerings re-entered on the sparse-grad
    SCOUT pass (core/lowering.py lowers the forward segment twice for
    is_sparse programs; counting both would double every dispatch the
    bench deltas report). ``mesh``: the active mesh (or None) — labels
    the counter ``mesh='n'`` when the decision ran under a >1-device
    mesh, so sharded bench rows prove which impl actually partitioned.
    Returns one of 'off' | 'xla' | 'pallas' | 'interpret'.
    """
    impl = tier if tier is not None else resolve_tier()
    if impl in ('pallas', 'interpret') and not pallas_ok:
        impl = 'xla'
    if impl == 'xla' and not xla_ok:
        impl = 'off'
    if count:
        meshed = mesh is not None and getattr(mesh, 'size', 1) > 1
        monitor.inc('fused_kernel_dispatch_total',
                    labels={'op': op, 'impl': impl,
                            'mesh': 'n' if meshed else '1'})
    return impl


# ---------------------------------------------------------------------------
# SPMD: the shared shard_map-over-mesh wrapper (extracted from
# ops/attention_ops.py so every fused unit partitions the way flash
# attention does)
# ---------------------------------------------------------------------------

def partitioned_call(fn, mesh, in_specs, out_specs):
    """shard_map ``fn`` over ``mesh`` with the given PartitionSpecs — one
    kernel invocation per shard, XLA stitching the shards back together.
    Rides parallel/ring_attention._shard_map (manual-over-all-axes with
    the jax-version fallbacks handled there); axes a spec does not name
    see replicated data, so e.g. a data-only spec under
    mesh(data=2, model=2) runs the same per-shard kernel on both model
    rows. A pallas custom call cannot be auto-partitioned by the XLA
    SPMD partitioner — this wrapper is what lets the fused tier survive
    an active mesh at all."""
    from ..parallel.ring_attention import _shard_map
    return _shard_map(fn, mesh, in_specs, out_specs)


def mesh_axis(mesh, name, dim_size):
    """Mesh axis ``name`` if present, >1, and divides ``dim_size``; else
    None (the caller leaves that dimension unsharded)."""
    if name in mesh.axis_names and mesh.shape[name] > 1 \
            and dim_size % mesh.shape[name] == 0:
        return name
    return None
