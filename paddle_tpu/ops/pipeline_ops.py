"""gpipe_run: the meta-op emitted by transpiler.PipelineTranspiler.

One op holding the program's repeated layer run. Without a 'pipe' mesh
axis it lowers to the serial layer loop (identical math to the original
program); under a MeshRunner mesh with a 'pipe' axis it lowers to the
lax.ppermute microbatch pipeline (parallel/pipeline.py gpipe) — stage
parameters are stacked [n_stages, layers_per_stage, ...] inside the trace,
so jax.vjp delivers per-layer gradients to the original parameter names
and the program's optimizer ops run unchanged.

No reference counterpart: fluid ~1.3 has no pipeline parallelism (SURVEY
§2.7); this is the TPU-native extension at Program level.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op

_composition_logged = set()


def _log_once(key, message):
    """One-time composition diagnostics: silent fallbacks to full-batch
    replication are correct but lose the sharding win — say so, once."""
    if key in _composition_logged:
        return
    _composition_logged.add(key)
    import logging
    logging.getLogger('paddle_tpu.pipeline').warning(message)


def _bindings(op):
    slot_names = list(op.attr('slot_names'))
    flat = list(op.attr('bindings_flat'))
    n_layers = int(op.attr('n_layers'))
    e = len(slot_names)
    assert len(flat) == n_layers * e, (len(flat), n_layers, e)
    return slot_names, [flat[k * e:(k + 1) * e] for k in range(n_layers)]


def _lower_segment(ctx, sub, env, key):
    """Trace the layer-0 segment ops with `env` bindings; returns the
    segment's env after lowering."""
    from ..core.lowering import lower_ops
    child = ctx.child(env, block=sub)
    child.base_key = key
    lower_ops(child, sub.ops, 0, len(sub.ops))
    return child.env


@register_op('gpipe_run', needs_rng=True)
def _gpipe_run(ctx, op):
    from ..parallel.api import get_active_mesh
    sub = ctx.program.block(int(op.attr('sub_block')))
    n_layers = int(op.attr('n_layers'))
    # a boundary may carry K tensors (residual trunk + branch, h/c pairs);
    # legacy single-activation programs carry in_var/out_var
    in_vars = list(op.attr('in_vars') or [op.attr('in_var')])
    out_vars = list(op.attr('out_vars') or [op.attr('out_var')])
    shared = list(op.attr('shared_names') or [])
    slot_names, bindings = _bindings(op)

    act = tuple(ctx.get(n) for n in op.input('X'))
    shared_vals = {n: ctx.get(n) for n in shared}
    base_key = ctx.rng()

    mesh = get_active_mesh()
    n_stages = int(op.attr('num_stages'))
    pipelined = mesh is not None and mesh.shape.get('pipe', 1) > 1
    if pipelined and mesh.shape['pipe'] != n_stages:
        raise ValueError(
            "gpipe_run was transpiled for %d stages but the mesh 'pipe' "
            "axis has size %d" % (n_stages, mesh.shape['pipe']))

    if not pipelined:
        # serial fallback: the original layer loop, same math
        for k in range(n_layers):
            env = dict(shared_vals)
            env.update(zip(in_vars, act))
            for sname, real in zip(slot_names, bindings[k]):
                env[sname] = ctx.get(real)
            seg_env = _lower_segment(ctx, sub, env,
                                     jax.random.fold_in(base_key, k))
            act = tuple(seg_env[n] for n in out_vars)
        for j, n in enumerate(op.output('Out')):
            ctx.set(n, act[j])
        return

    from ..parallel.pipeline import gpipe
    lps = n_layers // n_stages
    # stack each external slot over layers -> [S, lps, ...]; stacking
    # happens inside the trace, so AD routes the stacked cotangent back to
    # each layer's own parameter name
    stacked = tuple(
        jnp.stack([ctx.get(bindings[k][e]) for k in range(n_layers)])
        .reshape((n_stages, lps) + tuple(
            jnp.shape(ctx.get(bindings[0][e]))))
        for e in range(len(slot_names)))

    def stage_fn(params, x, extra):
        from jax import lax
        from ..parallel import api as _papi
        s = lax.axis_index('pipe')
        # the stage body runs per device inside shard_map (manual mesh):
        # ops must lower single-device — nested SPMD dispatch (e.g. the
        # flash-attention shard_map path) would see a mismatched mesh
        prev, _papi._ACTIVE_MESH = _papi._ACTIVE_MESH, None
        try:
            for jj in range(lps):
                env = dict(extra)
                env.update(zip(in_vars, x))
                for e, sname in enumerate(slot_names):
                    env[sname] = params[e][jj]
                key = jax.random.fold_in(base_key, s * lps + jj)
                seg_env = _lower_segment(ctx, sub, env, key)
                x = tuple(seg_env[n] for n in out_vars)
        finally:
            _papi._ACTIVE_MESH = prev
        return x

    # compose with data parallelism when the mesh carries a 'data' axis:
    # microbatch rows shard over it and param cotangents psum over it
    # (parallel/pipeline.py batch_axis) — falls back to replication when
    # the per-microbatch row count does not divide the axis. The axis-name
    # contract ('data', literally) and the divisibility rule are
    # documented in docs/parallelism.md.
    n_micro = int(op.attr('num_microbatches') or 0) or n_stages
    batch_axis = None
    if mesh.shape.get('data', 1) > 1:
        b0 = int(jnp.shape(act[0])[0])
        if b0 % n_micro == 0 and (b0 // n_micro) % mesh.shape['data'] == 0:
            batch_axis = 'data'
    gated = False
    if batch_axis is not None:
        from ..parallel.ring_attention import shard_map_supports_axis_names
        beyond = set(mesh.axis_names) - {'pipe', 'data'}
        if beyond and not shard_map_supports_axis_names():
            # manual-over-all fallback with axes OUTSIDE the manual set:
            # cotangent psum semantics for those axes are jax-version-
            # dependent — gate composition off (replicate: correct but
            # duplicated compute) rather than risk silently wrong grads
            _log_once(('gated', tuple(sorted(mesh.axis_names))),
                      "gpipe_run: batch_axis composition DISABLED — this "
                      "jax's shard_map lacks axis_names and the mesh has "
                      "axes %s beyond {pipe, data}; the batch replicates "
                      "over non-pipe axes (correct, duplicated compute). "
                      "Upgrade jax for manual-over-subset shard_map."
                      % sorted(beyond))
            batch_axis = None
            gated = True
    # (skip when gated: the axis qualified — the cause was shard_map
    # support, already diagnosed above; a second "name it 'data'" log
    # would send the operator after the wrong fix)
    if batch_axis is None and not gated and any(
            mesh.shape[a] > 1 for a in mesh.axis_names if a != 'pipe'):
        _log_once(('noengage', tuple(sorted(mesh.axis_names)), n_micro),
                  "gpipe_run: mesh %s has a >1 non-pipe axis but batch "
                  "composition did NOT engage — it requires an axis "
                  "literally named 'data' whose size divides "
                  "B//num_microbatches (see docs/parallelism.md). The "
                  "batch is replicated per non-pipe device: correct "
                  "math, duplicated compute."
                  % dict(mesh.shape))
    out = gpipe(stage_fn, stacked, act, mesh,
                num_microbatches=n_micro, extra=shared_vals,
                batch_axis=batch_axis)
    for j, n in enumerate(op.output('Out')):
        ctx.set(n, out[j])
