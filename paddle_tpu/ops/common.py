"""Shared helpers for op lowerings."""
import numpy as np
import jax.numpy as jnp

from ..core.types import convert_np_dtype_to_dtype_


def np_dtype(attr_val, default='float32'):
    if attr_val is None:
        attr_val = default
    return convert_np_dtype_to_dtype_(attr_val)


def broadcast_y_to(x, y, axis):
    """Reference elementwise axis-broadcast semantics
    (operators/elementwise/elementwise_op.h): align y's dims to x starting at
    `axis` (-1 = trailing alignment, numpy-style)."""
    if axis is None:
        axis = -1
    if y.ndim == x.ndim or y.ndim == 0 or axis == -1:
        return y
    target = [1] * x.ndim
    for i, s in enumerate(y.shape):
        target[axis + i] = s
    return y.reshape(target)


def flatten_to_2d(x, num_col_dims):
    """Reference `mul` op x_num_col_dims semantics (operators/mul_op.cc)."""
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    tail = int(np.prod(x.shape[num_col_dims:])) if num_col_dims < x.ndim else 1
    return x.reshape(lead, tail)
