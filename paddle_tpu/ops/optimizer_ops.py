"""Optimizer ops: functional (param, grad, state) -> (param', state') updates.

Reference: operators/optimizers/*.cc (sgd, momentum, lars_momentum, adagrad,
adam, adamax, adadelta, decayed_adagrad, ftrl, rmsprop, proximal_gd,
proximal_adagrad — each with dense + SelectedRows kernels). Here each is a pure
jnp expression inside the compiled step; XLA buffer donation makes the update
in-place. sgd/momentum/adam/adagrad additionally handle SelectedRows sparse
grads row-wise (scatter updates touch only the looked-up embedding rows);
the rest densify via _dense_grad like reference ops without a SelectedRows
kernel.
"""
import numpy as np
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows


def _lr(ctx, op):
    lr = ctx.in1(op, 'LearningRate')
    return lr.reshape(()) if lr.ndim else lr


def _dense_grad(ctx, op):
    """Grad input, densified if sparse (for optimizers without a row-wise
    kernel — the analog of ops lacking a SelectedRows kernel in the
    reference, which would densify via scatter first)."""
    g = ctx.in1(op, 'Grad')
    return g.to_dense() if isinstance(g, SelectedRows) else g


@register_op('sgd')
def _sgd(ctx, op):
    """reference operators/optimizers/sgd_op.h: dense kernel + SelectedRows
    kernel (row-wise axpy). Sparse: scatter-add touches only the looked-up
    rows; duplicate rows accumulate, exactly matching the dense result."""
    p = ctx.in1(op, 'Param')
    g = ctx.in1(op, 'Grad')
    lr = _lr(ctx, op)
    if isinstance(g, SelectedRows):
        upd = (-lr).astype(p.dtype) * g.values.astype(p.dtype)
        ctx.out(op, 'ParamOut', p.at[g.rows].add(upd, mode='drop'))
        return
    ctx.out(op, 'ParamOut', p - lr.astype(p.dtype) * g.astype(p.dtype))


@register_op('momentum')
def _momentum(ctx, op):
    """reference operators/optimizers/momentum_op.h (dense +
    SparseMomentumFunctor: merged rows, velocity/param updated row-wise;
    untouched rows keep stale velocity — 'lazy' semantics)."""
    p = ctx.in1(op, 'Param')
    g = ctx.in1(op, 'Grad')
    v = ctx.in1(op, 'Velocity')
    lr = _lr(ctx, op)
    mu = op.attr('mu')
    nesterov = op.attr('use_nesterov', False)
    if isinstance(g, SelectedRows):
        rows, gv = g.merged()
        gv = gv.astype(p.dtype)
        v_r = mu * v[rows] + gv
        if nesterov:
            p_r = p[rows] - (gv + mu * v_r) * lr
        else:
            p_r = p[rows] - lr * v_r
        ctx.out(op, 'ParamOut', p.at[rows].set(p_r, mode='drop'))
        ctx.out(op, 'VelocityOut', v.at[rows].set(v_r, mode='drop'))
        return
    v_out = mu * v + g
    if nesterov:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    ctx.out(op, 'ParamOut', p_out)
    ctx.out(op, 'VelocityOut', v_out)


@register_op('lars_momentum')
def _lars_momentum(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    v = ctx.in1(op, 'Velocity')
    lr = _lr(ctx, op)
    mu = op.attr('mu')
    coeff = op.attr('lars_coeff', 0.001)
    decay = op.attr('lars_weight_decay', 0.0005)
    pn = jnp.sqrt(jnp.sum(p * p))
    gn = jnp.sqrt(jnp.sum(g * g))
    local_lr = jnp.where(pn > 0, lr * coeff * pn / (gn + decay * pn + 1e-12),
                         lr)
    v_out = mu * v + local_lr * (g + decay * p)
    ctx.out(op, 'ParamOut', p - v_out)
    ctx.out(op, 'VelocityOut', v_out)


@register_op('adam')
def _adam(ctx, op):
    """reference operators/optimizers/adam_op.h: dense + SparseAdamFunctor
    over merged grad rows (lazy semantics: only touched rows advance their
    moments; BetaPow still advances globally)."""
    p = ctx.in1(op, 'Param')
    g = ctx.in1(op, 'Grad')
    m1 = ctx.in1(op, 'Moment1')
    m2 = ctx.in1(op, 'Moment2')
    b1p = ctx.in1(op, 'Beta1Pow').reshape(())
    b2p = ctx.in1(op, 'Beta2Pow').reshape(())
    lr = _lr(ctx, op)
    b1 = op.attr('beta1', 0.9)
    b2 = op.attr('beta2', 0.999)
    eps = op.attr('epsilon', 1e-8)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SelectedRows):
        po, m1o, m2o = _adam_sparse(p, g, m1, m2, lr_t, b1, b2, eps)
        ctx.out(op, 'ParamOut', po)
        ctx.out(op, 'Moment1Out', m1o)
        ctx.out(op, 'Moment2Out', m2o)
    else:
        m1o = b1 * m1 + (1 - b1) * g
        m2o = b2 * m2 + (1 - b2) * g * g
        ctx.out(op, 'ParamOut', p - lr_t * m1o / (jnp.sqrt(m2o) + eps))
        ctx.out(op, 'Moment1Out', m1o)
        ctx.out(op, 'Moment2Out', m2o)
    ctx.out(op, 'Beta1PowOut', (b1p * b1).reshape(1))
    ctx.out(op, 'Beta2PowOut', (b2p * b2).reshape(1))


def _adam_dense(p, g, m1, m2, lr_t, b1, b2, eps):
    """The exact per-parameter dense Adam expressions of the `adam` op —
    shared so fused_adam's 'off' tier is bit-identical by construction."""
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    return p - lr_t * m1o / (jnp.sqrt(m2o) + eps), m1o, m2o


def _adam_sparse(p, g, m1, m2, lr_t, b1, b2, eps):
    """The adam op's SelectedRows (lazy) row-wise update — ONE copy shared
    by `adam` and `fused_adam` so their sparse semantics cannot drift."""
    rows, gv = g.merged()
    gv = gv.astype(p.dtype)
    m1r = b1 * m1[rows] + (1 - b1) * gv
    m2r = b2 * m2[rows] + (1 - b2) * gv * gv
    p_r = p[rows] - lr_t * m1r / (jnp.sqrt(m2r) + eps)
    return (p.at[rows].set(p_r, mode='drop'),
            m1.at[rows].set(m1r, mode='drop'),
            m2.at[rows].set(m2r, mode='drop'))


def _fused_adam_kernel(b1, b2, eps, lrt_ref, p_ref, g_ref, m1_ref, m2_ref,
                       po_ref, m1o_ref, m2o_ref):
    lrt = lrt_ref[0, 0]
    g = g_ref[...]
    m1o = b1 * m1_ref[...] + (1 - b1) * g
    m2o = b2 * m2_ref[...] + (1 - b2) * g * g
    po_ref[...] = p_ref[...] - lrt * m1o / (jnp.sqrt(m2o) + eps)
    m1o_ref[...] = m1o
    m2o_ref[...] = m2o


def _mesh_spec_ok(mesh, spec, shape):
    """True when `spec` evenly tiles `shape` over `mesh` — shard_map's
    divisibility rule; a param that fails it takes the per-param
    fallback instead of the partitioned fused path."""
    entries = tuple(spec) if spec is not None else ()
    if len(entries) > len(shape):
        return False
    for dim, ax in zip(shape, entries):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        size = 1
        for a in axes:
            if a not in mesh.axis_names:
                return False
            size *= int(mesh.shape[a])
        if size and dim % size != 0:
            return False
    return True


def _fused_adam_group_spmd(mesh, spec, ps, gs, m1s, m2s, lr_t, b1, b2,
                           eps, impl):
    """One fused Adam pass for a group of params sharing PartitionSpec
    `spec`, partitioned per shard via kernel_tier.partitioned_call: each
    shard flattens+concats its LOCAL blocks and runs the elementwise
    kernel — the update is elementwise, so any partitioning is exact and
    comms-free (replicated params redundantly update on every device,
    the replicated path). Returns (params_out, m1_out, m2_out) lists."""
    from jax.sharding import PartitionSpec as P
    from .kernel_tier import partitioned_call
    k = len(ps)

    def inner(lrt, *blocks):
        lp, lg = blocks[:k], blocks[k:2 * k]
        lm1, lm2 = blocks[2 * k:3 * k], blocks[3 * k:]
        shapes = [b.shape for b in lp]
        sizes = [int(np.prod(s)) for s in shapes]
        cat = lambda vs: jnp.concatenate([v.reshape(-1) for v in vs]) \
            if k > 1 else vs[0].reshape(-1)
        pf, gf, m1f, m2f = cat(lp), cat(lg), cat(lm1), cat(lm2)
        if impl in ('pallas', 'interpret'):
            po, m1o, m2o = _fused_adam_flat(pf, gf, m1f, m2f, lrt, b1,
                                            b2, eps, impl == 'interpret')
        else:
            po, m1o, m2o = _adam_dense(pf, gf, m1f, m2f, lrt, b1, b2, eps)
        outs = []
        for which in (po, m1o, m2o):
            off = 0
            for s, sz in zip(shapes, sizes):
                outs.append(which[off:off + sz].reshape(s))
                off += sz
        return tuple(outs)

    in_specs = (P(),) + (spec,) * (4 * k)
    out_specs = (spec,) * (3 * k)
    outs = partitioned_call(inner, mesh, in_specs, out_specs)(
        lr_t, *(list(ps) + list(gs) + list(m1s) + list(m2s)))
    return outs[:k], outs[k:2 * k], outs[2 * k:]


def _fused_adam_flat(p, g, m1, m2, lr_t, b1, b2, eps, interpret):
    """One elementwise Pallas pass over the flattened-and-concatenated
    parameter set ([L] padded to (R, 128) tiles)."""
    import functools
    import jax
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    L = p.shape[0]
    bn = 256
    row_bytes = bn * 128
    R = -(-L // row_bytes) * bn                  # rows, multiple of bn
    pad = R * 128 - L

    def shape2(v):
        return jnp.pad(v, (0, pad)).reshape(R, 128)

    lrt2 = lr_t.astype(jnp.float32).reshape(1, 1)
    spec = pl.BlockSpec((bn, 128), lambda i: (i, 0))
    po, m1o, m2o = pl.pallas_call(
        functools.partial(_fused_adam_kernel, float(b1), float(b2),
                          float(eps)),
        grid=(R // bn,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((R, 128), jnp.float32)] * 3,
        compiler_params=_compiler_params(pltpu, ("arbitrary",)),
        interpret=interpret,
    )(lrt2, shape2(p), shape2(g), shape2(m1), shape2(m2))
    return (po.reshape(-1)[:L], m1o.reshape(-1)[:L], m2o.reshape(-1)[:L])


@register_op('fused_adam')
def _fused_adam(ctx, op):
    """Whole-parameter-set Adam as ONE op (reference operators/fused — the
    multi_tensor_adam idea): list inputs Params/Grads/Moment1s/Moment2s/
    Beta1Pows/Beta2Pows, one LearningRate. Attribution-wise the entire
    update is a single unit (one row under PADDLE_PROFILE_OPS) instead of
    N per-param op dispatches.

    Tiers (ops/kernel_tier.py): 'off' applies the adam op's exact per-
    param expressions (bitwise legacy parity); 'xla' flattens+concats the
    dense group into one vector so the update is one fused elementwise
    loop; 'pallas'/'interpret' run that vector through one Pallas kernel.
    SelectedRows (sparse) grads always take the per-param row-wise path —
    the per-op fallback rule. The fused tiers read the FIRST fused
    param's beta-pows for the shared lr_t: every program this op is
    built into initializes and advances all beta-pow accumulators
    identically.

    Under an active >1-device mesh the update partitions instead of
    falling back: params group by their own PartitionSpec (the active
    runner's rules via parallel.api.get_active_param_spec) and each
    group runs per shard through kernel_tier.partitioned_call — local
    blocks flattened+concatenated, no all-gather of sharded state;
    replicated params take the replicated path, and a spec that does
    not evenly tile its param falls back per-param (_mesh_spec_ok).
    """
    from . import kernel_tier
    names_p = op.input('Params')
    ps = [ctx.get(n) for n in names_p]
    gs = [ctx.get(n) for n in op.input('Grads')]
    m1s = [ctx.get(n) for n in op.input('Moment1s')]
    m2s = [ctx.get(n) for n in op.input('Moment2s')]
    b1ps = [ctx.get(n) for n in op.input('Beta1Pows')]
    b2ps = [ctx.get(n) for n in op.input('Beta2Pows')]
    lr = _lr(ctx, op)
    b1 = op.attr('beta1', 0.9)
    b2 = op.attr('beta2', 0.999)
    eps = op.attr('epsilon', 1e-8)

    dense = [i for i, g in enumerate(gs)
             if not isinstance(g, SelectedRows)
             and ps[i].dtype == jnp.float32]
    from ..parallel.api import get_active_mesh, get_active_param_spec
    mesh = get_active_mesh()
    sharded = mesh is not None and mesh.size > 1
    groups = None
    if sharded and dense:
        # mesh-native path: partition each flattened segment by the
        # param's OWN PartitionSpec (kernel_tier.partitioned_call per
        # spec-group) — no all-gather of a sharded parameter set, and
        # replicated params take the replicated path. A param whose spec
        # does not evenly tile its shape falls back per-param.
        from jax.sharding import PartitionSpec as P
        spec_fn = get_active_param_spec() or (lambda n: P())
        groups = {}
        for i in dense:
            spec = spec_fn(names_p[i]) or P()
            if _mesh_spec_ok(mesh, spec, ps[i].shape):
                groups.setdefault(tuple(spec), []).append(i)
        fusable = sorted(i for idxs in groups.values() for i in idxs)
    else:
        fusable = list(dense)
    impl = kernel_tier.dispatch('fused_adam',
                                pallas_ok=bool(fusable),
                                xla_ok=bool(fusable), mesh=mesh)

    fused = set(fusable) if impl != 'off' else set()
    if fused:
        first = fusable[0]
        lr_t0 = lr * jnp.sqrt(1 - b2ps[first].reshape(())) \
            / (1 - b1ps[first].reshape(()))
        dense_g = lambda i: gs[i].astype(jnp.float32)
        if sharded:
            from jax.sharding import PartitionSpec as P
            for spec_key, idxs in sorted(groups.items(),
                                         key=lambda kv: kv[1][0]):
                po, m1o, m2o = _fused_adam_group_spmd(
                    mesh, P(*spec_key), [ps[i] for i in idxs],
                    [dense_g(i) for i in idxs],
                    [m1s[i] for i in idxs], [m2s[i] for i in idxs],
                    lr_t0, b1, b2, eps, impl)
                for j, i in enumerate(idxs):
                    ctx.out(op, 'ParamsOut', po[j], idx=i)
                    ctx.out(op, 'Moment1sOut', m1o[j], idx=i)
                    ctx.out(op, 'Moment2sOut', m2o[j], idx=i)
        else:
            sizes = [int(np.prod(ps[i].shape)) for i in fusable]
            cat = lambda vs: jnp.concatenate(
                [vs[i].reshape(-1) for i in fusable])
            p_f, g_f = cat(ps), cat([g.astype(jnp.float32) if not
                                     isinstance(g, SelectedRows) else g
                                     for g in gs])
            m1_f, m2_f = cat(m1s), cat(m2s)
            if impl in ('pallas', 'interpret'):
                po, m1o, m2o = _fused_adam_flat(
                    p_f, g_f, m1_f, m2_f, lr_t0, b1, b2, eps,
                    impl == 'interpret')
            else:
                po, m1o, m2o = _adam_dense(p_f, g_f, m1_f, m2_f, lr_t0,
                                           b1, b2, eps)
            off = 0
            for k, i in enumerate(fusable):
                sl = slice(off, off + sizes[k])
                ctx.out(op, 'ParamsOut', po[sl].reshape(ps[i].shape),
                        idx=i)
                ctx.out(op, 'Moment1sOut', m1o[sl].reshape(ps[i].shape),
                        idx=i)
                ctx.out(op, 'Moment2sOut', m2o[sl].reshape(ps[i].shape),
                        idx=i)
                off += sizes[k]

    for i in range(len(ps)):
        b1p = b1ps[i].reshape(())
        b2p = b2ps[i].reshape(())
        if i not in fused:
            p, g, m1, m2 = ps[i], gs[i], m1s[i], m2s[i]
            lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
            if isinstance(g, SelectedRows):
                po_i, m1o_i, m2o_i = _adam_sparse(p, g, m1, m2, lr_t,
                                                  b1, b2, eps)
            else:
                po_i, m1o_i, m2o_i = _adam_dense(
                    p, g.astype(p.dtype), m1, m2, lr_t, b1, b2, eps)
            ctx.out(op, 'ParamsOut', po_i, idx=i)
            ctx.out(op, 'Moment1sOut', m1o_i, idx=i)
            ctx.out(op, 'Moment2sOut', m2o_i, idx=i)
        ctx.out(op, 'Beta1PowsOut', (b1p * b1).reshape(1), idx=i)
        ctx.out(op, 'Beta2PowsOut', (b2p * b2).reshape(1), idx=i)


@register_op('adamax')
def _adamax(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    m = ctx.in1(op, 'Moment')
    inf = ctx.in1(op, 'InfNorm')
    b1p = ctx.in1(op, 'Beta1Pow').reshape(())
    lr = _lr(ctx, op)
    b1 = op.attr('beta1', 0.9)
    b2 = op.attr('beta2', 0.999)
    eps = op.attr('epsilon', 1e-8)
    mo = b1 * m + (1 - b1) * g
    info = jnp.maximum(b2 * inf, jnp.abs(g))
    lr_t = lr / (1 - b1p)
    ctx.out(op, 'ParamOut', p - lr_t * mo / (info + eps))
    ctx.out(op, 'MomentOut', mo)
    ctx.out(op, 'InfNormOut', info)


@register_op('adagrad')
def _adagrad(ctx, op):
    """reference operators/optimizers/adagrad_op.h (dense + SparseAdagrad:
    merged rows, moment/param updated row-wise)."""
    p = ctx.in1(op, 'Param')
    g = ctx.in1(op, 'Grad')
    m = ctx.in1(op, 'Moment')
    lr = _lr(ctx, op)
    eps = op.attr('epsilon', 1e-6)
    if isinstance(g, SelectedRows):
        rows, gv = g.merged()
        gv = gv.astype(p.dtype)
        m_r = m[rows] + gv * gv
        p_r = p[rows] - lr * gv / (jnp.sqrt(m_r) + eps)
        ctx.out(op, 'ParamOut', p.at[rows].set(p_r, mode='drop'))
        ctx.out(op, 'MomentOut', m.at[rows].set(m_r, mode='drop'))
        return
    mo = m + g * g
    ctx.out(op, 'ParamOut', p - lr * g / (jnp.sqrt(mo) + eps))
    ctx.out(op, 'MomentOut', mo)


@register_op('decayed_adagrad')
def _decayed_adagrad(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    m = ctx.in1(op, 'Moment')
    lr = _lr(ctx, op)
    decay = op.attr('decay', 0.95)
    eps = op.attr('epsilon', 1e-6)
    mo = decay * m + (1 - decay) * g * g
    ctx.out(op, 'ParamOut', p - lr * g / (jnp.sqrt(mo) + eps))
    ctx.out(op, 'MomentOut', mo)


@register_op('adadelta')
def _adadelta(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    eg = ctx.in1(op, 'AvgSquaredGrad')
    ex = ctx.in1(op, 'AvgSquaredUpdate')
    rho = op.attr('rho', 0.95)
    eps = op.attr('epsilon', 1e-6)
    ego = rho * eg + (1 - rho) * g * g
    update = -jnp.sqrt((ex + eps) / (ego + eps)) * g
    exo = rho * ex + (1 - rho) * update * update
    ctx.out(op, 'ParamOut', p + update)
    ctx.out(op, 'AvgSquaredGradOut', ego)
    ctx.out(op, 'AvgSquaredUpdateOut', exo)


@register_op('rmsprop')
def _rmsprop(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    ms = ctx.in1(op, 'MeanSquare')
    mom = ctx.in1(op, 'Moment')
    lr = _lr(ctx, op)
    rho = op.attr('decay', 0.95)
    eps = op.attr('epsilon', 1e-6)
    momentum = op.attr('momentum', 0.0)
    centered = op.attr('centered', False)
    mso = rho * ms + (1 - rho) * g * g
    ctx.out(op, 'MeanSquareOut', mso)
    if centered:
        mg = ctx.in1(op, 'MeanGrad')
        mgo = rho * mg + (1 - rho) * g
        denom = mso - mgo * mgo + eps
        ctx.out(op, 'MeanGradOut', mgo)
    else:
        denom = mso + eps
    momo = momentum * mom + lr * g / jnp.sqrt(denom)
    ctx.out(op, 'MomentOut', momo)
    ctx.out(op, 'ParamOut', p - momo)


@register_op('ftrl')
def _ftrl(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    sq = ctx.in1(op, 'SquaredAccumulator')
    lin = ctx.in1(op, 'LinearAccumulator')
    lr = _lr(ctx, op)
    l1 = op.attr('l1', 0.0)
    l2 = op.attr('l2', 0.0)
    power = op.attr('lr_power', -0.5)
    new_sq = sq + g * g
    sigma = (new_sq ** -power - sq ** -power) / lr
    lino = lin + g - sigma * p
    y = new_sq ** -power / lr + 2 * l2
    p_out = jnp.where(jnp.abs(lino) > l1,
                      (jnp.sign(lino) * l1 - lino) / y, 0.0)
    ctx.out(op, 'ParamOut', p_out)
    ctx.out(op, 'SquaredAccumOut', new_sq)
    ctx.out(op, 'LinearAccumOut', lino)


@register_op('proximal_gd')
def _proximal_gd(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    lr = _lr(ctx, op)
    l1 = op.attr('l1', 0.0)
    l2 = op.attr('l2', 0.0)
    prox = p - lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / \
        (1.0 + lr * l2)
    ctx.out(op, 'ParamOut', p_out)


@register_op('proximal_adagrad')
def _proximal_adagrad(ctx, op):
    p = ctx.in1(op, 'Param')
    g = _dense_grad(ctx, op)
    m = ctx.in1(op, 'Moment')
    lr = _lr(ctx, op)
    l1 = op.attr('l1', 0.0)
    l2 = op.attr('l2', 0.0)
    mo = m + g * g
    lr_t = lr / jnp.sqrt(mo)
    prox = p - lr_t * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0) / \
        (1.0 + lr_t * l2)
    ctx.out(op, 'ParamOut', p_out)
    ctx.out(op, 'MomentOut', mo)


@register_op('average_accumulates')
def _average_accumulates(ctx, op):
    # ModelAverage support (reference optimizer.py:1484 + operators/
    # average_accumulates_op.cc): accumulate sums of params over windows.
    p = ctx.in1(op, 'param')
    sum1 = ctx.in1(op, 'in_sum_1')
    sum2 = ctx.in1(op, 'in_sum_2')
    sum3 = ctx.in1(op, 'in_sum_3')
    num_acc = ctx.in1(op, 'in_num_accumulates').reshape(())
    old_num = ctx.in1(op, 'in_old_num_accumulates').reshape(())
    num_upd = ctx.in1(op, 'in_num_updates').reshape(())
    avg_window = op.attr('average_window', 10000.0)
    max_avg = op.attr('max_average_window', 10000)
    min_avg = op.attr('min_average_window', 10000)
    k_max_num_accumulates = 16384  # reference average_accumulates_op.h
    num_acc = num_acc + 1
    num_upd = num_upd + 1
    sum1 = sum1 + p
    # periodic fold of sum1 into sum2 to bound fp error
    fold = (num_upd % k_max_num_accumulates) == 0
    sum2 = jnp.where(fold, sum2 + sum1, sum2)
    sum1 = jnp.where(fold, jnp.zeros_like(sum1), sum1)
    # window shift: reference condition uses min(max_window, updates*rate)
    window = jnp.minimum(jnp.asarray(float(max_avg)),
                         num_upd.astype(jnp.float32) * avg_window)
    do_shift = (num_acc >= min_avg) & \
        (num_acc.astype(jnp.float32) >= window)
    sum3o = jnp.where(do_shift, sum1 + sum2, sum3)
    sum1o = jnp.where(do_shift, jnp.zeros_like(sum1), sum1)
    sum2o = jnp.where(do_shift, jnp.zeros_like(sum2), sum2)
    old_o = jnp.where(do_shift, num_acc, old_num)
    acc_o = jnp.where(do_shift, jnp.zeros_like(num_acc), num_acc)
    ctx.out(op, 'out_sum_1', sum1o)
    ctx.out(op, 'out_sum_2', sum2o)
    ctx.out(op, 'out_sum_3', sum3o)
    ctx.out(op, 'out_num_accumulates', acc_o.reshape(1))
    ctx.out(op, 'out_old_num_accumulates', old_o.reshape(1))
    ctx.out(op, 'out_num_updates', num_upd.reshape(1))
