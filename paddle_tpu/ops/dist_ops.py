"""Distributed-training ops: the reference pserver data-path tail, realized
TPU-natively.

Reference files:
- operators/distributed_ops/split_ids_op.cc      (ids partitioned by owner)
- operators/distributed/parameter_prefetch.cc:177 (split->prefetch->merge)
- operators/distributed_ops/merge_ids_op.cc      (reassemble per-id rows)
- operators/split_selected_rows_op.cc            (SelectedRows by height section)
- operators/distributed_ops/split_byref_op.cc    (dense dim-0 split)
- operators/lookup_sparse_table_op.cc            (pserver-side table lookup)
- operators/distributed_ops/fake_init_op.cc      (placeholder init)
- operators/distributed_ops/checkpoint_notify_op.cc (pserver checkpoint RPC)
- operators/distributed_ops/ref_by_trainer_id_op.cc (per-trainer select)

On TPU there is no pserver process: the id exchange the reference performs
over gRPC becomes one SPMD gather against a 'model'-axis vocab-sharded table
(XLA partitions jnp.take into masked shard-local gathers + a psum over ICI —
exactly the split_ids -> shard lookup -> merge_ids pipeline, compiled).
These ops keep the reference *program* vocabulary runnable: shapes must be
static under XLA, so the variable-length outputs of the RPC versions become
fixed-capacity masked tensors (capacity = the input length), documented per
op below. The round-trip contracts (split+merge = identity; split
SelectedRows -> to_dense == sliced to_dense) are preserved and tested.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows


# ---------------------------------------------------------------------------
# vocab-sharded lookup helper (the actual TPU pserver replacement)
# ---------------------------------------------------------------------------

def sharded_lookup_reference(shards, flat_ids):
    """Host/testing reference for what XLA's partitioner emits for a gather
    from a dim-0-sharded table: every shard gathers locally with masking,
    then the partial results are summed (each id is owned by exactly one
    shard). `shards`: list of [V/S, D] arrays; returns [N, D]."""
    n = flat_ids.shape[0]
    d = shards[0].shape[1]
    out = jnp.zeros((n, d), shards[0].dtype)
    base = 0
    for sh in shards:
        local = flat_ids - base
        owned = (local >= 0) & (local < sh.shape[0])
        rows = jnp.where(owned, local, 0)
        out = out + jnp.where(owned[:, None], jnp.take(sh, rows, axis=0), 0)
        base += sh.shape[0]
    return out


def table_sharding_constraint(w):
    """Pin an is_distributed embedding table to the 'model' mesh axis
    (dim 0 = vocab) when tracing under a mesh that has one. XLA then
    partitions the consuming gather into shard-local masked gathers + psum
    over ICI and the SelectedRows scatter-update into shard-local masked
    scatters — no [vocab, dim] tensor is ever materialized per device."""
    from ..parallel.api import get_active_mesh
    mesh = get_active_mesh()
    if mesh is not None and mesh.shape.get('model', 1) > 1 \
            and w.ndim >= 1 and w.shape[0] % mesh.shape['model'] == 0:
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(*(('model',) + (None,) * (w.ndim - 1)))
        return lax.with_sharding_constraint(w, NamedSharding(mesh, spec))
    return w


# ---------------------------------------------------------------------------
# split_ids / merge_ids
# ---------------------------------------------------------------------------

@register_op('split_ids', share_lod=False)
def _split_ids(ctx, op):
    """Partition ids by owner shard: out[k] holds the ids with id %% N == k.

    Static-shape divergence from split_ids_op.cc: every output keeps the
    input's length (capacity); slots whose id belongs to another shard carry
    the sentinel -1, and the original position is preserved. merge_ids
    understands this layout and round-trips exactly.

    Id-range limit: with JAX x64 disabled (this framework's default),
    jnp.int64 silently narrows to int32, so ids must fit in [0, 2^31) —
    merge_ids/lookup below cast to int32 anyway. Vocabularies beyond 2^31
    rows need jax_enable_x64; the sharded-embedding path (tensor_ops
    lookup_table is_distributed) has the same contract.
    """
    ids = ctx.in1(op, 'Ids')
    flat = ids.reshape(-1).astype(jnp.int64) \
        if ids.dtype == jnp.int64 else ids.reshape(-1).astype(jnp.int32)
    outs = op.output('Out')
    n = len(outs)
    for k in range(n):
        owned = (flat % n) == k
        ctx.out(op, 'Out', jnp.where(owned, flat, -1), idx=k)


@register_op('merge_ids')
def _merge_ids(ctx, op):
    """Inverse of split_ids + per-shard lookup (merge_ids_op.cc): given the
    original Ids, the per-shard id slices (Rows, the split_ids outputs) and
    the per-shard lookup results X (row-aligned with Rows), emit each id's
    embedding row in the original order. With the fixed-capacity split_ids
    layout the owner shard holds position i's row at position i, so the
    merge is a select over the owner axis."""
    ids = ctx.in1(op, 'Ids')
    xs = ctx.in_list(op, 'X')
    flat = ids.reshape(-1).astype(jnp.int32)
    n = len(xs)
    stacked = jnp.stack(xs)                       # [N_shard, L, D]
    owner = (flat % n).astype(jnp.int32)          # [L]
    out = stacked[owner, jnp.arange(flat.shape[0])]
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], ctx.in1_lod(op, 'Ids'))


# ---------------------------------------------------------------------------
# SelectedRows / dense splitting
# ---------------------------------------------------------------------------

def _sections_from(op, total, attr='height_sections'):
    secs = [int(s) for s in (op.attr(attr) or [])]
    if not secs:
        n = len(op.output('Out'))
        if total % n:
            raise ValueError(
                "%s: height %d not divisible into %d equal sections — pass "
                "height_sections" % (op.type, total, n))
        secs = [total // n] * n
    return secs


@register_op('split_selected_rows')
def _split_selected_rows(ctx, op):
    """Split a SelectedRows by height sections (split_selected_rows_op.cc):
    out[k] owns the rows falling in its height range, with row indices
    rebased to the section start. Static-shape divergence: every output
    keeps the input's row capacity; non-owned slots carry row == section
    height (the SelectedRows sentinel — dropped by to_dense/scatter)."""
    x = ctx.get(op.input('X')[0])
    if not isinstance(x, SelectedRows):
        raise TypeError("split_selected_rows expects a SelectedRows input "
                        "(got %r)" % type(x).__name__)
    secs = _sections_from(op, x.height)
    base = 0
    for k, h in enumerate(secs):
        local = x.rows - base
        owned = (local >= 0) & (local < h)
        rows = jnp.where(owned, local, h)
        vals = jnp.where(owned[:, None], x.values, 0)
        ctx.set(op.output('Out')[k], SelectedRows(rows.astype(jnp.int32),
                                                  vals, h))
        base += h


@register_op('split_byref')
def _split_byref(ctx, op):
    """Dense dim-0 split (split_byref_op.cc). The reference avoids a copy by
    aliasing pserver-bound sections; under XLA, slices of one buffer fuse
    into their consumers, which is the same zero-copy outcome."""
    x = ctx.in1(op, 'X')
    secs = [int(s) for s in (op.attr('sections') or [])]
    if not secs:
        num = int(op.attr('num', 0) or len(op.output('Out')))
        secs = [x.shape[0] // num] * num
    base = 0
    for k, h in enumerate(secs):
        ctx.out(op, 'Out', lax.slice_in_dim(x, base, base + h, axis=0),
                idx=k)
        base += h


# ---------------------------------------------------------------------------
# lookup_sparse_table / fake_init
# ---------------------------------------------------------------------------

@register_op('lookup_sparse_table')
def _lookup_sparse_table(ctx, op):
    """Pserver-side table lookup (lookup_sparse_table_op.cc). The reference
    auto-grows a hash table for unseen ids (auto_grown_table=True); XLA
    requires static shapes, so the TPU table is pre-sized at startup (the
    uniform-random init the reference applies on growth happens up front in
    the initializer) and ids index it directly — out-of-range ids clamp, as
    with lookup_table."""
    from .tensor_ops import embedding_epilogue
    w = ctx.in1(op, 'W')
    ids = ctx.in1(op, 'Ids')
    flat = ids.reshape(-1).astype(jnp.int32)
    w = table_sharding_constraint(w)
    out = jnp.take(w, flat, axis=0)
    ctx.out(op, 'Out', embedding_epilogue(out, flat, ids, w,
                                          op.attr('padding_idx', -1)))


@register_op('ps_lookup_table')
def _ps_lookup_table(ctx, op):
    """PS-remote embedding lookup (paddle_tpu/ps): the [height, width]
    table lives on parameter servers, NOT in this program. `Rows` is a
    FED [n, width] tensor of pulled rows in flat-id order (the trainer's
    PSTrainerSession / serving PSRowResolver supplies it per batch); the
    lowering applies only the lookup_table epilogue (padding_idx zeroing
    + id-shape restore). Gradients: the rows feed is a dense wrt of the
    backward op (ps/program.py wires it), so the pullback's cotangent
    w.r.t. the feed IS the per-position row gradient pushed back to the
    servers — no [height, width] cotangent can exist."""
    from .tensor_ops import embedding_epilogue
    rows_name = op.input('Rows')[0]
    if not ctx.has(rows_name):
        raise KeyError(
            "ps_lookup_table(table=%r): rows feed %r was not supplied — "
            "drive this program through ps.PSTrainerSession (training) "
            "or a serving PSRowResolver, which pull the rows per batch"
            % (op.attr('table_name'), rows_name))
    rows = ctx.get(rows_name)
    ids = ctx.in1(op, 'Ids')
    flat = ids.reshape(-1).astype(jnp.int32)
    if rows.shape[0] != flat.shape[0]:
        raise ValueError(
            "ps_lookup_table(table=%r): rows feed %r has %d rows for %d "
            "ids — the pull must cover ids.reshape(-1) in order"
            % (op.attr('table_name'), rows_name, rows.shape[0],
               flat.shape[0]))

    class _WShape(object):          # epilogue reads w.shape only
        shape = (int(op.attr('height')), int(rows.shape[1]))

    ctx.out(op, 'Out', embedding_epilogue(rows, flat, ids, _WShape,
                                          op.attr('padding_idx', -1)))


@register_op('fake_init', stateful=True)
def _fake_init(ctx, op):
    """fake_init_op.cc: declare a var's shape without materializing data —
    used for vars the pserver owns so trainers don't double-init them. On
    TPU all state is SPMD-shared, so the placeholder is a zero tensor of
    the declared shape (never read before being written/prefetched)."""
    shape = tuple(int(s) for s in op.attr('shape', [1]))
    from .common import np_dtype
    dtype = np_dtype(op.attr('dtype'))
    ctx.out(op, 'Out', jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# control-plane ops
# ---------------------------------------------------------------------------

@register_op('checkpoint_notify', stateful=True)
def _checkpoint_notify(ctx, op):
    """checkpoint_notify_op.cc sends the checkpoint dir to each pserver over
    RPC. TPU-natively the executor IS the checkpoint writer: lowering emits
    nothing, and Executor.run saves the scope's persistables to attr `dir`
    after every run of a program containing this op (executor.py), which
    matches the reference timing (a notify per execution)."""
    # no device computation; host-side effect handled by the executor


@register_op('ref_by_trainer_id')
def _ref_by_trainer_id(ctx, op):
    """ref_by_trainer_id_op.cc: Out = X[trainer_id]. The trainer id tensor
    is a runtime scalar; all X entries share a shape, so the select lowers
    to a stack + dynamic index (one XLA dynamic-slice)."""
    xs = ctx.in_list(op, 'X')
    tid = ctx.in1(op, 'TrainerId').reshape(()).astype(jnp.int32)
    if len(xs) == 1:
        ctx.out(op, 'Out', xs[0])
        return
    ctx.out(op, 'Out', jnp.stack(xs)[jnp.clip(tid, 0, len(xs) - 1)])


# ---------------------------------------------------------------------------
# fused convs (conv_fusion_op.cc, fused/fusion_conv_inception_op.cu)
# ---------------------------------------------------------------------------

def _act(name, x):
    if name in (None, '', 'identity', 'linear'):
        return x
    fns = {'relu': jax.nn.relu, 'relu6': lambda v: jnp.clip(v, 0, 6),
           'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh}
    if name not in fns:
        raise NotImplementedError("conv fusion activation %r" % name)
    return fns[name](x)


def _conv_nhwc(x, w, strides, pads, dilations, groups, accum):
    """NCHW-contract conv computed channels-minor (see nn_ops._conv2d: NHWC
    measured 11x faster on v5e; the transposes cancel between fused ops)."""
    return jnp.transpose(lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 1)), jnp.transpose(w, (2, 3, 1, 0)),
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
        feature_group_count=groups,
        preferred_element_type=accum), (0, 3, 1, 2))


@register_op('conv2d_fusion')
def _conv2d_fusion(ctx, op):
    """conv_fusion_op.cc: y = act(conv(x) + residual + bias), optionally
    split along channels into Outputs. One composite emission — XLA fuses
    the epilogue into the conv the way cudnnConvolutionBiasActivationForward
    did on GPU."""
    from ..core import amp
    from .nn_ops import _pair
    x = ctx.in1(op, 'Input')
    w = ctx.in1(op, 'Filter')
    bias = ctx.in1(op, 'Bias')
    residual = ctx.in1(op, 'ResidualData')
    out_dtype = x.dtype
    x, w = amp.cast_compute(op, x, w)
    out = _conv_nhwc(x, w, _pair(op.attr('strides', [1, 1])),
                     _pair(op.attr('paddings', [0, 0])),
                     _pair(op.attr('dilations', [1, 1])),
                     op.attr('groups', 1) or 1, amp.accum_dtype(x))
    if residual is not None:
        out = out + residual.astype(out.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1, 1)
    out = _act(op.attr('activation', 'relu'), out)
    out = out.astype(amp.result_dtype(op, x, out_dtype))
    split = [int(s) for s in (op.attr('split_channels') or [])]
    if split and op.output('Outputs'):
        base = 0
        for k, c in enumerate(split):
            ctx.out(op, 'Outputs',
                    lax.slice_in_dim(out, base, base + c, axis=1), idx=k)
            base += c
    ctx.out(op, 'Output', out)


@register_op('conv2d_inception_fusion')
def _conv2d_inception_fusion(ctx, op):
    """fused/fusion_conv_inception_op.cu: the GoogLeNet inception cell as
    one op. Branches (all same-HW, NCHW):
      b0: 3x3 pool (pad 1, stride 1) -> 1x1 conv f0        -> oc0
      b1: 1x1 conv f1 -> first oc1 channels to the output,
          remaining 2*f2_ic channels feed b2
      b2: 3x3 conv f2, groups=2, pad 1 -> first oc2 to the output,
          remaining f3_ic channels feed b3
      b3: 3x3 conv f3, pad 1                                -> oc3
    Output = concat([b0, b1[:oc1], b2[:oc2], b3], channel); every conv adds
    bias + activation. The pointer arithmetic of the CUDA kernel becomes
    channel slices that XLA fuses."""
    from ..core import amp
    x = ctx.in1(op, 'Input')
    filters = ctx.in_list(op, 'Filter')
    biases = ctx.in_list(op, 'Bias')
    act = op.attr('activation', 'relu')
    pool_type = op.attr('pooling_type', 'max')
    exclusive = op.attr('exclusive', True)
    out_dtype = x.dtype
    x, filters[0] = amp.cast_compute(op, x, filters[0])
    filters = [filters[0]] + [f.astype(x.dtype) for f in filters[1:]]
    accum = amp.accum_dtype(x)

    def conv(inp, f, b, pad, groups=1):
        y = _conv_nhwc(inp, f, (1, 1), (pad, pad), (1, 1), groups, accum)
        return _act(act, y + b.astype(y.dtype).reshape(1, -1, 1, 1))

    from .nn_ops import _pool
    pooled = _pool(x, (3, 3), (1, 1), (1, 1), pool_type, exclusive,
                   False, False, False)
    b0 = conv(pooled, filters[0], biases[0], 0)
    b1_full = conv(x, filters[1], biases[1], 0)
    oc1 = filters[1].shape[0] - filters[2].shape[1] * 2
    b2_in = lax.slice_in_dim(b1_full, oc1, b1_full.shape[1], axis=1)
    b2_full = conv(b2_in, filters[2], biases[2], 1, groups=2)
    oc2 = filters[2].shape[0] - filters[3].shape[1]
    b3_in = lax.slice_in_dim(b2_full, oc2, b2_full.shape[1], axis=1)
    b3 = conv(b3_in, filters[3], biases[3], 1)
    out = jnp.concatenate(
        [b0, lax.slice_in_dim(b1_full, 0, oc1, axis=1),
         lax.slice_in_dim(b2_full, 0, oc2, axis=1), b3], axis=1)
    out = out.astype(amp.result_dtype(op, x, out_dtype))
    for k in range(len(op.output('TempOutput') or [])):
        ctx.out(op, 'TempOutput',
                jnp.zeros((1,), out.dtype), idx=k)  # scratch in reference
    ctx.out(op, 'Output', out)
