"""Random ops: init distributions + dropout + random_crop.

Reference: operators/uniform_random_op.cc, gaussian_random_op.cc,
truncated_gaussian_random_op.cc, dropout_op.cc, random_crop_op.cc.
Keys derive deterministically from the run key + op index (core/lowering.py),
so dropout masks are reproducible given program.random_seed, matching the
reference's seeded-philox behavior.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import np_dtype


def _maybe_seeded_key(ctx, op):
    seed = op.attr('seed', 0)
    key = ctx.rng()
    if seed:
        key = jax.random.PRNGKey(seed)
        key = jax.random.fold_in(key, ctx.op_index)
    return key


@register_op('uniform_random', needs_rng=True)
def _uniform_random(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape'))
    lo = op.attr('min', -1.0)
    hi = op.attr('max', 1.0)
    out = jax.random.uniform(_maybe_seeded_key(ctx, op), shape,
                             dtype=jnp.float32, minval=lo, maxval=hi)
    ctx.out(op, 'Out', out.astype(dtype))


@register_op('uniform_random_batch_size_like', needs_rng=True)
def _uniform_random_bsl(ctx, op):
    x = ctx.in1(op, 'Input')
    dtype = np_dtype(op.attr('dtype'))
    shape = list(op.attr('shape'))
    shape[op.attr('output_dim_idx', 0)] = x.shape[op.attr('input_dim_idx', 0)]
    out = jax.random.uniform(_maybe_seeded_key(ctx, op), tuple(shape),
                             dtype=jnp.float32,
                             minval=op.attr('min', -1.0),
                             maxval=op.attr('max', 1.0))
    ctx.out(op, 'Out', out.astype(dtype))


@register_op('gaussian_random', needs_rng=True)
def _gaussian_random(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape'))
    mean = op.attr('mean', 0.0)
    std = op.attr('std', 1.0)
    out = mean + std * jax.random.normal(_maybe_seeded_key(ctx, op), shape,
                                         dtype=jnp.float32)
    ctx.out(op, 'Out', out.astype(dtype))


@register_op('truncated_gaussian_random', needs_rng=True)
def _truncated_gaussian_random(ctx, op):
    dtype = np_dtype(op.attr('dtype'))
    shape = tuple(op.attr('shape'))
    mean = op.attr('mean', 0.0)
    std = op.attr('std', 1.0)
    out = mean + std * jax.random.truncated_normal(
        _maybe_seeded_key(ctx, op), -2.0, 2.0, shape, dtype=jnp.float32)
    ctx.out(op, 'Out', out.astype(dtype))


@register_op('dropout', needs_rng=True)
def _dropout(ctx, op):
    x = ctx.in1(op, 'X')
    prob = op.attr('dropout_prob', 0.5)
    is_test = op.attr('is_test', False)
    impl = op.attr('dropout_implementation', 'downgrade_in_infer')
    if is_test:
        if impl == 'downgrade_in_infer':
            out = x * (1.0 - prob)
        else:
            out = x
        ctx.out(op, 'Out', out)
        ctx.out(op, 'Mask', jnp.ones_like(x))
        return
    keep = jax.random.bernoulli(_maybe_seeded_key(ctx, op), 1.0 - prob,
                                x.shape)
    mask = keep.astype(x.dtype)
    if impl == 'upscale_in_train':
        out = jnp.where(prob < 1.0, x * mask / (1.0 - prob),
                        jnp.zeros_like(x))
    else:
        out = x * mask
    ctx.out(op, 'Out', out)
    ctx.out(op, 'Mask', mask)


@register_op('random_crop', needs_rng=True)
def _random_crop(ctx, op):
    x = ctx.in1(op, 'X')
    shape = op.attr('shape')
    key = _maybe_seeded_key(ctx, op)
    n_crop = len(shape)
    starts = []
    for i, s in enumerate(shape):
        dim = x.shape[x.ndim - n_crop + i]
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, dim - s + 1))
    idx = [slice(None)] * (x.ndim - n_crop)
    out = jax.lax.dynamic_slice(
        x, [0] * (x.ndim - n_crop) + starts,
        list(x.shape[:x.ndim - n_crop]) + list(shape))
    ctx.out(op, 'Out', out)
