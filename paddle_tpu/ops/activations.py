"""Activation ops.

Reference: operators/activation_op.cc:559 REGISTER_ACTIVATION_OP + functor list
activation_op.h:983-1014 (31 activations, each with a hand-written grad
functor). Here each is one jnp expression; JAX AD supplies the gradients and
XLA fuses them into surrounding matmuls (HBM-bandwidth win on TPU).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _register_act(name, fn, attrs=()):
    @register_op(name)
    def _lower(ctx, op, _fn=fn, _attrs=attrs):
        kw = {a: op.attr(a, d) for a, d in _attrs}
        # unary elementwise is layout-invariant: follow the producer's
        # NHWC twin (core/lowering.py) so conv stacks stay channels-minor
        if ctx.has_nhwc(op, 'X'):
            ctx.out_nhwc(op, 'Out', _fn(ctx.in_nhwc(op, 'X'), **kw))
            return
        x = ctx.in1(op, 'X')
        ctx.out(op, 'Out', _fn(x, **kw))


_register_act('sigmoid', jax.nn.sigmoid)
_register_act('logsigmoid', jax.nn.log_sigmoid)
_register_act('exp', jnp.exp)
_register_act('relu', jax.nn.relu)
_register_act('gelu', lambda x: jax.nn.gelu(x, approximate=False))
_register_act('tanh', jnp.tanh)
_register_act('sqrt', jnp.sqrt)
_register_act('rsqrt', jax.lax.rsqrt)
_register_act('abs', jnp.abs)
_register_act('ceil', jnp.ceil)
_register_act('floor', jnp.floor)
_register_act('cos', jnp.cos)
_register_act('sin', jnp.sin)
_register_act('round', jnp.round)
_register_act('reciprocal', lambda x: 1.0 / x)
_register_act('log', jnp.log)
_register_act('square', jnp.square)
_register_act('softplus', jax.nn.softplus)
_register_act('softsign', jax.nn.soft_sign)
_register_act('tanh_shrink', lambda x: x - jnp.tanh(x))

_register_act('softshrink',
              lambda x, lambda_: jnp.where(x > lambda_, x - lambda_,
                                           jnp.where(x < -lambda_,
                                                     x + lambda_, 0.0)),
              attrs=(('lambda_', 0.5),))
_register_act('brelu',
              lambda x, t_min, t_max: jnp.clip(x, t_min, t_max),
              attrs=(('t_min', 0.0), ('t_max', 24.0)))
_register_act('soft_relu',
              lambda x, threshold: jnp.log1p(
                  jnp.exp(jnp.clip(x, -threshold, threshold))),
              attrs=(('threshold', 40.0),))
_register_act('pow', lambda x, factor: jnp.power(x, factor),
              attrs=(('factor', 1.0),))
_register_act('stanh',
              lambda x, scale_a, scale_b: scale_b * jnp.tanh(scale_a * x),
              attrs=(('scale_a', 0.67), ('scale_b', 1.7159)))
_register_act('relu6',
              lambda x, threshold: jnp.clip(x, 0.0, threshold),
              attrs=(('threshold', 6.0),))
_register_act('leaky_relu',
              lambda x, alpha: jnp.where(x >= 0, x, alpha * x),
              attrs=(('alpha', 0.02),))
_register_act('elu',
              lambda x, alpha: jnp.where(x >= 0, x,
                                         alpha * (jnp.exp(x) - 1.0)),
              attrs=(('alpha', 1.0),))
_register_act('hard_shrink',
              lambda x, threshold: jnp.where(jnp.abs(x) > threshold, x, 0.0),
              attrs=(('threshold', 0.5),))
_register_act('hard_sigmoid',
              lambda x, slope, offset: jnp.clip(slope * x + offset, 0.0, 1.0),
              attrs=(('slope', 0.2), ('offset', 0.5)))
_register_act('swish',
              lambda x, beta: x * jax.nn.sigmoid(beta * x),
              attrs=(('beta', 1.0),))
_register_act('thresholded_relu',
              lambda x, threshold: jnp.where(x > threshold, x, 0.0),
              attrs=(('threshold', 1.0),))
_register_act('selu',
              lambda x, scale, alpha: scale * jnp.where(
                  x >= 0, x, alpha * (jnp.exp(x) - 1.0)),
              attrs=(('scale', 1.0507009873554805),
                     ('alpha', 1.6732632423543772)))
_register_act('prelu_simple', lambda x, alpha: jnp.where(x >= 0, x, alpha * x),
              attrs=(('alpha', 0.25),))


@register_op('prelu')
def _prelu(ctx, op):
    x = ctx.in1(op, 'X')
    alpha = ctx.in1(op, 'Alpha')
    mode = op.attr('mode', 'all')
    if mode == 'all':
        a = alpha.reshape(())
    elif mode == 'channel':
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    ctx.out(op, 'Out', jnp.where(x >= 0, x, a * x))


@register_op('maxout')
def _maxout(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    groups = op.attr('groups')
    n, c, h, w = x.shape
    out = x.reshape(n, c // groups, groups, h, w).max(axis=2)
    ctx.out(op, 'Out', out)
