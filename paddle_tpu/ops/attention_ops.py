"""Fused attention: the pallas kernel tier (SURVEY §2.4: the TPU analog of
the reference's operators/jit/ runtime-codegen kernels, with the same
refer-vs-optimized cross-checking discipline — see tests/test_attention.py).

`flash_attention` computes softmax(QK^T * scale + causal mask) V in one
kernel: scores and probabilities live in VMEM only and never round-trip
through HBM, which is the memory-bandwidth win on TPU (attention is
HBM-bound at small d_head). One grid cell per (batch * head); each cell's
Q/K/V tile fits VMEM for the seq lengths this kernel accepts (<= ~2k at
d_head 64). The backward pass recomputes attention with the plain jnp
formulation under jax AD (flash-style backward is a later optimization);
forward-only inference gets the full benefit.

Selection mirrors the reference jit-kernel `UseMe` pattern: on TPU the
pallas kernel runs compiled; elsewhere the jnp reference implementation is
used (the kernel itself is cross-checked against it in interpret mode).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


def _attention_ref(q, k, v, scale, causal):
    """Plain jnp reference ([BH, L, dh] each) — also the backward path."""
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        ln = q.shape[1]
        mask = jnp.tril(jnp.ones((ln, ln), bool))
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqk,bkd->bqd', p.astype(v.dtype), v)


def _flash_kernel(scale, causal, q_ref, k_ref, v_ref, o_ref):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        ln = q.shape[0]
        rows = lax.broadcasted_iota(jnp.int32, (ln, ln), 0)
        cols = lax.broadcasted_iota(jnp.int32, (ln, ln), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    z = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p / z, v.astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0] = o.astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, interpret):
    from jax.experimental import pallas as pl
    bh, ln, dh = q.shape
    kernel = functools.partial(_flash_kernel, scale, causal)
    spec = pl.BlockSpec((1, ln, dh), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(bh,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((bh, ln, dh), q.dtype),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, use_pallas):
    if use_pallas:
        return _flash_fwd_pallas(q, k, v, scale, causal,
                                 interpret=(use_pallas == 'interpret'))
    return _attention_ref(q, k, v, scale, causal)


def _flash_fwd(q, k, v, scale, causal, use_pallas):
    return _flash(q, k, v, scale, causal, use_pallas), (q, k, v)


def _flash_bwd(scale, causal, use_pallas, res, ct):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _attention_ref(a, b, c, scale, causal),
                     q, k, v)
    return vjp(ct)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale=None, causal=True, use_pallas=None):
    """q/k/v: [B, H, L, dh] (or [BH, L, dh]). On TPU lowers to the pallas
    kernel; elsewhere to the jnp reference (use_pallas='interpret' forces
    the kernel through the pallas interpreter for cross-checking)."""
    shape4 = q.ndim == 4
    if shape4:
        b, h, ln, dh = q.shape
        q = q.reshape(b * h, ln, dh)
        k = k.reshape(b * h, ln, dh)
        v = v.reshape(b * h, ln, dh)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if use_pallas is None:
        use_pallas = jax.default_backend() == 'tpu'
    out = _flash(q, k, v, float(scale), bool(causal), use_pallas)
    if shape4:
        out = out.reshape(b, h, ln, dh)
    return out


@register_op('flash_attention')
def _flash_attention_op(ctx, op):
    """Program-level op: inputs Q, K, V [B, H, L, dh]; attrs scale (float,
    default dh^-0.5) and causal (bool). AMP-markable: under bf16 policy the
    kernel's matmuls run bf16 with fp32 softmax/accumulation (the kernel
    upcasts internally with preferred_element_type)."""
    from ..core import amp
    q = ctx.in1(op, 'Q')
    k = ctx.in1(op, 'K')
    v = ctx.in1(op, 'V')
    out_dtype = q.dtype
    q, k, v = amp.cast_compute(op, q, k, v)
    scale = op.attr('scale', 0.0) or None
    causal = op.attr('causal', True)
    use_pallas = None
    from ..parallel.api import get_active_mesh
    mesh = get_active_mesh()
    if mesh is not None and mesh.size > 1:
        # under SPMD the XLA partitioner cannot split a pallas custom
        # call; the einsum formulation partitions cleanly over the
        # mesh instead (per-chip fusion is a later shard_map step)
        use_pallas = False
    out = flash_attention(q, k, v, scale=scale, causal=causal,
                          use_pallas=use_pallas)
    ctx.out(op, 'Out', out.astype(out_dtype))
