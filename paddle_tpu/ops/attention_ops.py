"""Blocked flash attention: the pallas kernel tier (SURVEY §2.4: the TPU
analog of the reference's operators/jit/ runtime-codegen kernels
(jit/kernel_base.h:24-52), with the same refer-vs-optimized cross-checking
discipline of operators/jit/test.cc — see tests/test_attention.py).

Forward: FlashAttention-2 style. Grid (batch*head, q_block, k_block); the
k dimension is innermost+sequential so f32 scratch (running max, running
denominator, output accumulator) carries across k blocks — scores for one
(q_block, k_block) tile live in VMEM only and never round-trip through HBM.
Matmuls feed the MXU in the input dtype (bf16 under AMP) with f32
accumulation via preferred_element_type; causal tiles below the diagonal
are skipped with predication. Alongside O it emits per-row LSE
(logsumexp), the residual the backward needs.

Backward: two pallas kernels (the FlashAttention-2 split):
  - dQ:    grid (bh, q_block, k_block), accumulates dQ across k blocks;
  - dK/dV: grid (bh, k_block, q_block), accumulates dK and dV across
           q blocks.
Both recompute the probability tile from (Q, K, LSE) instead of storing it
— O(L) memory, O(L^2) recompute, the standard trade on HBM-bound hardware.
delta = rowsum(dO * O) is precomputed outside the kernels (XLA fuses it).

Under SPMD (an active MeshRunner mesh) the op no longer falls back to
einsum: it wraps the kernel in shard_map over the (data, model) axes —
batch and heads are embarrassingly parallel — and when the sequence axis
itself is sharded it dispatches to the ring-attention path
(parallel/ring_attention.py), making ring the long-context execution mode
of this same op rather than a parallel universe.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


def _attention_ref(q, k, v, scale, causal):
    """Plain jnp reference ([BH, L, dh] each) — the 'refer' tier."""
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        ln = q.shape[1]
        mask = jnp.tril(jnp.ones((ln, ln), bool))
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqk,bkd->bqd', p.astype(v.dtype), v)


def _pick_block(ln, pref):
    """Largest power-of-two tile (<= pref) dividing the sequence length."""
    b = pref
    while b > 128:
        if ln % b == 0:
            return b
        b //= 2
    return b if ln % b == 0 else ln


def _compiler_params(pltpu, semantics):
    cls = getattr(pltpu, 'CompilerParams', None) or \
        getattr(pltpu, 'TPUCompilerParams')
    try:
        return cls(dimension_semantics=semantics)
    except TypeError:       # field not supported on this version
        return cls()


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------

def _fwd_kernel(scale, causal, nk, has_bias, *refs):
    import jax.experimental.pallas as pl
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
        bias_ref = None
    i, j = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            # per-key additive bias (padding masks: 0 keep / -1e9 drop)
            s = s + bias_ref[0, 0][None, :]
        if causal:
            rows = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = rows >= cols
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            # rows whose tile slice is fully masked have m_new == _NEG_INF
            # and exp(_NEG_INF - _NEG_INF) == 1; force masked entries to 0
            p = jnp.where(mask, p, 0.0)
        if bias_ref is not None:
            # exact zero for dropped keys (-1e8 or lower — covers the
            # documented -1e9 pad convention), independent of underflow
            p = jnp.where(bias_ref[0, 0][None, :] > -1e8, p, 0.0)
        l_scr[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        pv = lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv

    if causal:
        # tile visible iff its first key column <= last query row
        pl.when(j * bk <= i * bq + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, 0] + jnp.log(
            jnp.maximum(l_scr[:, 0], 1e-30))


def _flash_fwd_pallas(q, k, v, scale, causal, interpret, block_q, block_k,
                      bias=None, n_heads=1):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    bh, ln, dh = q.shape
    bq = _pick_block(ln, block_q)
    bk = _pick_block(ln, block_k)
    nq, nk = ln // bq, ln // bk
    has_bias = bias is not None
    kernel = functools.partial(_fwd_kernel, scale, causal, nk, has_bias)
    qspec = pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0))
    ins = [q, k, v]
    in_specs = [qspec, kspec, kspec]
    if has_bias:
        # bias [B, L]: each (batch*head) row b maps to batch b // n_heads
        ins.append(bias.astype(jnp.float32)[:, None, :])
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda b, i, j: (b // n_heads, 0, j)))
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[qspec,
                   pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((bh, ln, dh), q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, ln), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*ins)
    return o, lse[:, 0]


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------

def _bwd_dq_kernel(scale, causal, nk, has_bias, *refs):
    import jax.experimental.pallas as pl
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        bias_ref = None
    i, j = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0][None, :]
        if causal:
            rows = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])   # masked entries underflow
        if bias_ref is not None:
            # all-padded rows have lse = log(1e-30); without the forward's
            # exact zeroing p explodes to ~e^69 and poisons dQ
            p = jnp.where(bias_ref[0, 0][None, :] > -1e8, p, 0.0)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_scr[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(j * bk <= i * bq + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(scale, causal, nq, has_bias, *refs):
    import jax.experimental.pallas as pl
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        bias_ref = None
    i, j = pl.program_id(1), pl.program_id(2)      # i: k block, j: q block
    bk, bq = k_ref.shape[1], q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0][None, :]
        if causal:
            rows = j * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = i * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])            # [bq, bk]
        if bias_ref is not None:
            p = jnp.where(bias_ref[0, 0][None, :] > -1e8, p, 0.0)
        dv_scr[...] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dk_scr[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # tile visible iff its last query row >= first key column
        pl.when(j * bq + bq - 1 >= i * bk)(_compute)
    else:
        _compute()

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, scale, causal, interpret,
                      block_q, block_k, bias=None, n_heads=1):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    bh, ln, dh = q.shape
    bq = _pick_block(ln, block_q)
    bk = _pick_block(ln, block_k)
    nq, nk = ln // bq, ln // bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse3 = lse[:, None, :]
    delta3 = delta[:, None, :]
    has_bias = bias is not None
    bias3 = bias.astype(jnp.float32)[:, None, :] if has_bias else None

    qspec = pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0))
    kspec_j = pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))
    ins = [q, k, v, do, lse3, delta3]
    in_specs = [qspec, kspec_j, kspec_j, qspec, rowspec, rowspec]
    if has_bias:
        ins.append(bias3)
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda b, i, j: (b // n_heads, 0, j)))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale, causal, nk, has_bias),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((bh, ln, dh), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*ins)[0]

    # k-major grid: q blocks stream innermost
    qspec_j = pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, j, 0))
    kspec_i = pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, i, 0))
    rowspec_j = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, j))
    ins2 = [q, k, v, do, lse3, delta3]
    in_specs2 = [qspec_j, kspec_i, kspec_i, qspec_j, rowspec_j, rowspec_j]
    if has_bias:
        ins2.append(bias3)
        in_specs2.append(pl.BlockSpec(
            (1, 1, bk), lambda b, i, j: (b // n_heads, 0, i)))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale, causal, nq, has_bias),
        grid=(bh, nk, nq),
        in_specs=in_specs2,
        out_specs=[kspec_i, kspec_i],
        out_shape=[jax.ShapeDtypeStruct((bh, ln, dh), k.dtype),
                   jax.ShapeDtypeStruct((bh, ln, dh), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, dh), jnp.float32),
                        pltpu.VMEM((bk, dh), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*ins2)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom_vjp wrapper ([BH, L, dh] level)
# --------------------------------------------------------------------------

# default tile sizes; the round-3 sweep measured 512x512 optimal at
# d_head 64 (256/128 tiles 1.5-2.5x slower). Env-overridable so perf
# sweeps (tools/mfuexp.py) can re-measure without editing source.
import os as _os
_DEF_BQ = int(_os.environ.get('PADDLE_FLASH_BQ', '512'))
_DEF_BK = int(_os.environ.get('PADDLE_FLASH_BK', '512'))


def _fwd_impl(q, k, v, scale, causal, impl):
    if impl in ('pallas', 'interpret'):
        return _flash_fwd_pallas(q, k, v, scale, causal,
                                 impl == 'interpret', _DEF_BQ, _DEF_BK)
    return _attention_ref(q, k, v, scale, causal), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, impl):
    return _fwd_impl(q, k, v, scale, causal, impl)[0]


def _flash_fwd(q, k, v, scale, causal, impl):
    o, lse = _fwd_impl(q, k, v, scale, causal, impl)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, impl, res, ct):
    q, k, v, o, lse = res
    if impl in ('pallas', 'interpret'):
        return _flash_bwd_pallas(q, k, v, o, lse, ct, scale, causal,
                                 impl == 'interpret', _DEF_BQ, _DEF_BK)
    _, vjp = jax.vjp(lambda a, b, c: _attention_ref(a, b, c, scale, causal),
                     q, k, v)
    return vjp(ct)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _attention_ref_biased(q, k, v, bias, scale, causal, n_heads):
    """jnp reference with per-key additive bias [B, L] (row b of the
    [BH, L, dh] inputs belongs to batch b // n_heads)."""
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + jnp.repeat(bias.astype(jnp.float32), n_heads, axis=0)[:, None, :]
    if causal:
        ln = q.shape[1]
        mask = jnp.tril(jnp.ones((ln, ln), bool))
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqk,bkd->bqd', p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_biased(q, k, v, bias, scale, causal, impl, n_heads):
    return _fwd_impl_biased(q, k, v, bias, scale, causal, impl,
                            n_heads)[0]


def _fwd_impl_biased(q, k, v, bias, scale, causal, impl, n_heads):
    if impl in ('pallas', 'interpret'):
        return _flash_fwd_pallas(q, k, v, scale, causal,
                                 impl == 'interpret', _DEF_BQ, _DEF_BK,
                                 bias=bias, n_heads=n_heads)
    return _attention_ref_biased(q, k, v, bias, scale, causal,
                                 n_heads), None


def _flash_biased_fwd(q, k, v, bias, scale, causal, impl, n_heads):
    o, lse = _fwd_impl_biased(q, k, v, bias, scale, causal, impl, n_heads)
    return o, (q, k, v, bias, o, lse)


def _flash_biased_bwd(scale, causal, impl, n_heads, res, ct):
    q, k, v, bias, o, lse = res
    # bias is a padding mask: treated as non-differentiable (zero grad)
    if impl in ('pallas', 'interpret'):
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, o, lse, ct, scale, causal, impl == 'interpret',
            _DEF_BQ, _DEF_BK, bias=bias, n_heads=n_heads)
        return dq, dk, dv, jnp.zeros_like(bias)
    _, vjp = jax.vjp(
        lambda a, b, c: _attention_ref_biased(a, b, c, bias, scale,
                                              causal, n_heads), q, k, v)
    dq, dk, dv = vjp(ct)
    return dq, dk, dv, jnp.zeros_like(bias)


_flash_biased.defvjp(_flash_biased_fwd, _flash_biased_bwd)


def _resolve_impl(use_pallas):
    if use_pallas is None:
        return 'pallas' if jax.default_backend() == 'tpu' else 'ref'
    if use_pallas == 'interpret':
        return 'interpret'
    return 'pallas' if use_pallas else 'ref'


def flash_attention(q, k, v, scale=None, causal=True, use_pallas=None,
                    key_padding_bias=None, num_heads=1):
    """q/k/v: [B, H, L, dh] (or [BH, L, dh]). On TPU lowers to the blocked
    pallas kernels (fwd + dq/dkv bwd); elsewhere to the jnp reference
    (use_pallas='interpret' forces the kernels through the pallas
    interpreter for cross-checking). key_padding_bias: optional [B, L]
    additive per-key bias (0 keep / -1e9 drop — BERT-style padding masks),
    fused into the kernel; treated as non-differentiable."""
    shape4 = q.ndim == 4
    if shape4:
        b, h, ln, dh = q.shape
        num_heads = h
        q = q.reshape(b * h, ln, dh)
        k = k.reshape(b * h, ln, dh)
        v = v.reshape(b * h, ln, dh)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    impl = _resolve_impl(use_pallas)
    if impl == 'pallas' and q.shape[1] % 128 and q.shape[1] > 1024:
        # no 128-multiple tile divides L: the kernel would need one full-L
        # VMEM tile; the fused-by-XLA reference is the safer lowering
        impl = 'ref'
    if key_padding_bias is not None:
        out = _flash_biased(q, k, v, key_padding_bias, float(scale),
                            bool(causal), impl, int(num_heads))
    else:
        out = _flash(q, k, v, float(scale), bool(causal), impl)
    if shape4:
        out = out.reshape(b, h, ln, dh)
    return out


# --------------------------------------------------------------------------
# SPMD: shard_map over (data, model); ring dispatch for a sharded seq axis
# --------------------------------------------------------------------------

def _shard_map(fn, mesh, in_specs, out_specs):
    # shared fused-tier wrapper (ops/kernel_tier.partitioned_call) — this
    # module's original helper, extracted so CE/adam/embedding/layernorm
    # partition the same way
    from .kernel_tier import partitioned_call
    return partitioned_call(fn, mesh, in_specs, out_specs)


def _mesh_axis(mesh, name, dim_size):
    """Axis name if present, >1, and divides dim_size; else None."""
    from .kernel_tier import mesh_axis
    return mesh_axis(mesh, name, dim_size)


def flash_attention_spmd(q, k, v, mesh, scale=None, causal=True,
                         use_pallas=None, ring_zigzag=False,
                         key_padding_bias=None):
    """[B, H, L, dh] under an active mesh: batch sharded over 'data', heads
    over 'model', kernel per shard via shard_map. If the 'seq' axis shards
    L, dispatches to ring attention (the long-context mode); ring_zigzag
    uses the balanced causal layout (parallel/ring_attention.py)."""
    from jax.sharding import PartitionSpec as P
    b, h, ln, dh = q.shape
    if scale is None:
        scale = dh ** -0.5
    data_ax = _mesh_axis(mesh, 'data', b)
    model_ax = _mesh_axis(mesh, 'model', h)
    seq_ax = _mesh_axis(mesh, 'seq', ln)
    if seq_ax is not None:
        if key_padding_bias is not None:
            # ring + bias would need the bias rotating with K/V blocks;
            # the partitionable einsum reference covers this case
            return _flash_biased(
                q.reshape(b * h, ln, dh), k.reshape(b * h, ln, dh),
                v.reshape(b * h, ln, dh), key_padding_bias,
                float(scale), bool(causal), 'ref',
                h).reshape(b, h, ln, dh)
        from ..parallel.ring_attention import ring_attention
        zz = (bool(ring_zigzag) and causal
              and ln % (2 * mesh.shape[seq_ax]) == 0)
        return ring_attention(q, k, v, mesh, axis_name=seq_ax,
                              scale=scale, causal=causal,
                              batch_axis=data_ax, head_axis=model_ax,
                              zigzag=zz)
    impl = _resolve_impl(use_pallas)
    if impl == 'pallas' and ln % 128 and ln > 1024:
        # same guard as flash_attention: no 128-multiple tile divides L,
        # so the kernel would need one full-L VMEM tile per program
        impl = 'ref'
    spec = P(data_ax, model_ax, None, None)

    if key_padding_bias is None:
        def inner(ql, kl, vl):
            lb, lh = ql.shape[0], ql.shape[1]
            o = _flash(ql.reshape(lb * lh, ln, dh),
                       kl.reshape(lb * lh, ln, dh),
                       vl.reshape(lb * lh, ln, dh), float(scale),
                       bool(causal), impl)
            return o.reshape(lb, lh, ln, dh)

        return _shard_map(inner, mesh, (spec, spec, spec), spec)(q, k, v)

    # the [B, L] bias shards along the batch axis like Q/K/V
    bspec = P(data_ax, None)

    def inner_biased(ql, kl, vl, bl):
        lb, lh = ql.shape[0], ql.shape[1]
        o = _flash_biased(ql.reshape(lb * lh, ln, dh),
                          kl.reshape(lb * lh, ln, dh),
                          vl.reshape(lb * lh, ln, dh), bl, float(scale),
                          bool(causal), impl, lh)
        return o.reshape(lb, lh, ln, dh)

    return _shard_map(inner_biased, mesh, (spec, spec, spec, bspec),
                      spec)(q, k, v, key_padding_bias)


@register_op('flash_attention')
def _flash_attention_op(ctx, op):
    """Program-level op: inputs Q, K, V [B, H, L, dh]; attrs scale (float,
    default dh^-0.5) and causal (bool). Under bf16 AMP the kernel's matmuls
    run bf16 on the MXU with f32 accumulation (preferred_element_type) and
    f32 softmax state. Under an active SPMD mesh the kernel runs per shard
    via shard_map (ring attention when the sequence axis is sharded)."""
    from ..core import amp
    q = ctx.in1(op, 'Q')
    k = ctx.in1(op, 'K')
    v = ctx.in1(op, 'V')
    out_dtype = q.dtype
    q, k, v = amp.cast_compute(op, q, k, v)
    bias = ctx.in1(op, 'KeyPaddingBias')       # optional [B, L]
    if bias is not None and q.ndim != 4:
        raise NotImplementedError(
            "flash_attention KeyPaddingBias needs 4-d [B, H, L, dh] Q "
            "(the bias row maps to batch via the head dim)")
    # missing attr -> kernel default dh**-0.5; a present value (incl. 0.0)
    # is literal. Legacy programs that stored 0.0 meaning "default" keep
    # that behavior.
    scale = op.attr('scale', None)
    scale = None if scale is None or scale == 0.0 else float(scale)
    causal = op.attr('causal', True)
    from ..parallel.api import get_active_mesh
    mesh = get_active_mesh()
    use_pallas = None
    if jax.default_backend() != 'tpu':
        # on CPU (virtual-mesh tests, dryrun) exercise the real kernels
        # through the pallas interpreter under SPMD; plain jnp otherwise
        use_pallas = 'interpret' if mesh is not None else False
    if mesh is not None and mesh.size > 1:
        if q.ndim != 4:
            # 3-d [BH, L, dh]: no batch/head axes to shard_map over; the
            # XLA auto-partitioner cannot split a pallas custom call, so
            # lower the partitionable einsum reference instead
            out = flash_attention(q, k, v, scale=scale, causal=causal,
                                  use_pallas=False)
        else:
            out = flash_attention_spmd(
                q, k, v, mesh, scale=scale, causal=causal,
                use_pallas=use_pallas,
                ring_zigzag=op.attr('ring_zigzag', False),
                key_padding_bias=bias)
    else:
        out = flash_attention(q, k, v, scale=scale, causal=causal,
                              use_pallas=use_pallas,
                              key_padding_bias=bias)
    ctx.out(op, 'Out', out.astype(out_dtype))
