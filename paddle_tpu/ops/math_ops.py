"""Dense math ops: mul/matmul, elementwise family, reductions, norms.

Reference counterparts: operators/mul_op.cc, matmul_op.cc,
elementwise/elementwise_*_op.cc (axis broadcast), reduce_ops/reduce_*_op.cc,
sum_op.cc, mean_op.cc, cumsum_op.cc, sign_op.cc, l1_norm_op.cc,
squared_l2_norm_op.cc, squared_l2_distance_op.cc, cos_sim_op.cc,
bilinear_tensor_product_op.cc, minus_op.cc. All lower to jnp/lax; matmuls hit
the MXU, and bf16/fp32 mixed precision is handled by dtype of the operands.
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core import amp
from ..core.registry import register_op
from .common import broadcast_y_to, flatten_to_2d


@register_op('mul')
def _mul(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    xnc = op.attr('x_num_col_dims', 1)
    ynk = op.attr('y_num_col_dims', 1)
    x2 = flatten_to_2d(x, xnc)
    y2 = flatten_to_2d(y, ynk)
    x2, y2 = amp.cast_compute(op, x2, y2)
    out = jnp.dot(x2, y2, preferred_element_type=jnp.float32)
    out = out.astype(x.dtype)
    out_shape = x.shape[:xnc] + y.shape[ynk:]
    ctx.out(op, 'Out', out.reshape(out_shape))


@register_op('matmul')
def _matmul(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    tx = op.attr('transpose_X', False)
    ty = op.attr('transpose_Y', False)
    alpha = op.attr('alpha', 1.0)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if y.ndim == 1:
        y = y.reshape(-1, 1)
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out_dtype = x.dtype
    x, y = amp.cast_compute(op, x, y)
    out = jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(out_dtype)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, dtype=out.dtype)
    ctx.out(op, 'Out', out)


# -- elementwise family ------------------------------------------------------

def _register_elementwise(name, fn):
    @register_op(name)
    def _lower(ctx, op, _fn=fn):
        x = ctx.in1(op, 'X')
        y = ctx.in1(op, 'Y')
        from ..core.selected_rows import SelectedRows
        if isinstance(x, SelectedRows):
            # Only mul/div distribute over the implicit zero rows; anything
            # else (add/sub/max/pow/...) must see the dense tensor or the
            # untouched rows silently miss the operation.
            if name in ('elementwise_mul', 'elementwise_div') \
                    and getattr(y, 'size', 0) == 1:
                # e.g. global-norm clip's grad * factor (reference
                # elementwise_mul SelectedRows kernel)
                ctx.out(op, 'Out',
                        SelectedRows(x.rows, _fn(x.values, y.reshape(())),
                                     x.height))
                return
            x = x.to_dense()
        # layout-twin path (core/lowering.py ctx.nhwc): keep channels-minor
        # residual adds / conv-bias adds / SE-style scales transpose-free
        if ctx.has_nhwc(op, 'X') and getattr(x, 'ndim', 0) == 4 \
                and not isinstance(y, SelectedRows):
            xt = ctx.in_nhwc(op, 'X')
            axis = op.attr('axis', -1)
            yt = None
            if getattr(y, 'ndim', None) == 4:
                yt = ctx.in_nhwc(op, 'Y')      # twin or transposed NCHW
            elif getattr(y, 'ndim', None) == 1 and axis == 1 \
                    and y.shape[0] == x.shape[1]:
                yt = y.reshape((1, 1, 1, -1))  # per-channel bias/scale
            elif getattr(y, 'size', 0) == 1:
                yt = y
            if yt is not None:
                ctx.out_nhwc(op, 'Out', _fn(xt, yt))
                return
        y = broadcast_y_to(x, y, op.attr('axis', -1))
        ctx.out(op, 'Out', _fn(x, y))


_register_elementwise('elementwise_add', lambda x, y: x + y)
_register_elementwise('elementwise_sub', lambda x, y: x - y)
_register_elementwise('elementwise_mul', lambda x, y: x * y)
_register_elementwise('elementwise_div', lambda x, y: x / y)
_register_elementwise('elementwise_max', jnp.maximum)
_register_elementwise('elementwise_min', jnp.minimum)
_register_elementwise('elementwise_pow', jnp.power)
_register_elementwise('elementwise_mod', jnp.mod)
_register_elementwise('elementwise_floordiv', jnp.floor_divide)


@register_op('minus')
def _minus(ctx, op):
    ctx.out(op, 'Out', ctx.in1(op, 'X') - ctx.in1(op, 'Y'))


@register_op('sum')
def _sum(ctx, op):
    """reference sum_op: mixing a SelectedRows input with dense inputs
    densifies (used by append_regularization_ops on sparse grads)."""
    from ..core.selected_rows import SelectedRows
    xs = [x.to_dense() if isinstance(x, SelectedRows) else x
          for x in ctx.in_list(op, 'X')]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.out(op, 'Out', out)


@register_op('mean')
def _mean(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.mean(x).reshape(1))


# -- reductions --------------------------------------------------------------

def _register_reduce(name, fn):
    @register_op(name)
    def _lower(ctx, op, _fn=fn):
        x = ctx.in1(op, 'X')
        dim = op.attr('dim', [0])
        keep_dim = op.attr('keep_dim', False)
        reduce_all = op.attr('reduce_all', False)
        if reduce_all:
            axes = None
        else:
            if not isinstance(dim, (list, tuple)):
                dim = [dim]
            axes = tuple(d % x.ndim for d in dim)
        out = _fn(x, axis=axes, keepdims=keep_dim)
        if axes is None and not keep_dim:
            out = out.reshape(())
        ctx.out(op, 'Out', out)


_register_reduce('reduce_sum', jnp.sum)
_register_reduce('reduce_mean', jnp.mean)
_register_reduce('reduce_max', jnp.max)
_register_reduce('reduce_min', jnp.min)
_register_reduce('reduce_prod', jnp.prod)
_register_reduce('reduce_all', jnp.all)
_register_reduce('reduce_any', jnp.any)


@register_op('cumsum')
def _cumsum(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', -1)
    exclusive = op.attr('exclusive', False)
    reverse = op.attr('reverse', False)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis, dtype=x.dtype)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    ctx.out(op, 'Out', out)


@register_op('sign')
def _sign(ctx, op):
    ctx.out(op, 'Out', jnp.sign(ctx.in1(op, 'X')))


@register_op('l1_norm')
def _l1_norm(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.sum(jnp.abs(x)).reshape(()))


@register_op('squared_l2_norm')
def _squared_l2_norm(ctx, op):
    x = ctx.in1(op, 'X')
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        # merge first so duplicate rows accumulate before squaring, matching
        # the norm of the equivalent dense gradient (GradientClipByGlobalNorm
        # over sparse grads, reference clip.py:275-277)
        _, vals = x.merged()
        x = vals
    ctx.out(op, 'Out', jnp.sum(x * x).reshape(1))


@register_op('squared_l2_distance')
def _squared_l2_distance(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    sub = x - y
    ctx.out(op, 'sub_result', sub)
    ctx.out(op, 'Out', jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)),
                               keepdims=True))


@register_op('cos_sim')
def _cos_sim(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'XNorm', xn)
    ctx.out(op, 'YNorm', yn)


@register_op('norm')
def _norm(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', -1)
    eps = op.attr('epsilon', 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    ctx.out(op, 'Norm', norm)
    ctx.out(op, 'Out', x / norm)


@register_op('bilinear_tensor_product')
def _bilinear_tensor_product(ctx, op):
    x = ctx.in1(op, 'X')         # (N, M)
    y = ctx.in1(op, 'Y')         # (N, P)
    w = ctx.in1(op, 'Weight')    # (K, M, P)
    bias = ctx.in1(op, 'Bias')
    out = jnp.einsum('nm,kmp,np->nk', x, w, y)
    if bias is not None:
        out = out + bias
    ctx.out(op, 'Out', out)


@register_op('log_loss')
def _log_loss(ctx, op):
    p = ctx.in1(op, 'Predicted')
    y = ctx.in1(op, 'Labels')
    eps = op.attr('epsilon', 1e-4)
    out = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    ctx.out(op, 'Loss', out)


@register_op('huber_loss')
def _huber_loss(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    delta = op.attr('delta', 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    ctx.out(op, 'Residual', r)
    ctx.out(op, 'Out', loss)


@register_op('hinge_loss')
def _hinge_loss(ctx, op):
    logits = ctx.in1(op, 'Logits')
    labels = ctx.in1(op, 'Labels')
    ctx.out(op, 'Loss',
            jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits))


@register_op('rank_loss')
def _rank_loss(ctx, op):
    label = ctx.in1(op, 'Label')
    left = ctx.in1(op, 'Left')
    right = ctx.in1(op, 'Right')
    d = left - right
    out = jnp.logaddexp(0.0, d) - label * d
    ctx.out(op, 'Out', out)


@register_op('margin_rank_loss')
def _margin_rank_loss(ctx, op):
    label = ctx.in1(op, 'Label')
    x1 = ctx.in1(op, 'X1')
    x2 = ctx.in1(op, 'X2')
    margin = op.attr('margin', 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'Activated', (out > 0).astype(x1.dtype))


@register_op('smooth_l1_loss')
def _smooth_l1_loss(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    iw = ctx.in1(op, 'InsideWeight')
    ow = ctx.in1(op, 'OutsideWeight')
    sigma = op.attr('sigma', 1.0)
    s2 = sigma * sigma
    d = x - y
    if iw is not None:
        d = d * iw
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    if ow is not None:
        loss = loss * ow
    ctx.out(op, 'Diff', d)
    ctx.out(op, 'Out', jnp.sum(loss, axis=tuple(range(1, loss.ndim)),
                               keepdims=True))


@register_op('bpr_loss')
def _bpr_loss(ctx, op):
    x = ctx.in1(op, 'X')          # (N, C) logits
    label = ctx.in1(op, 'Label')  # (N, 1)
    lab = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = -(x - pos)
    loss = -jnp.log(jnp.clip(1.0 / (1.0 + jnp.exp(diff)), 1e-20, 1.0))
    n = x.shape[1]
    mask = jnp.ones_like(loss).at[jnp.arange(x.shape[0]), lab].set(0.0)
    out = jnp.sum(loss * mask, axis=1, keepdims=True) / (n - 1)
    ctx.out(op, 'Y', out)


@register_op('teacher_student_sigmoid_loss')
def _ts_sigmoid_loss(ctx, op):
    x = ctx.in1(op, 'X')
    label = ctx.in1(op, 'Label')
    soft_max_up = op.attr('soft_max_up_bound', 15.0)
    soft_max_lo = op.attr('soft_max_lower_bound', -15.0)
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    # teacher (soft) + student (hard) composite CE on sigmoid
    out = jnp.logaddexp(0.0, z) - label * z
    ctx.out(op, 'Y', out)
