"""Fused FFN tail: matmul + bias + gelu + matmul + bias (+ dropout) as
one kernel-tier unit — MFU push round 4 (BENCH_r06 top_offenders rank
``dropout``/``gelu``/the residual ``layer_norm`` rows as the remaining
unfused tail of the flagship LM; the reference collapses exactly this
composition in operators/fused/fused_feedforward_op).

The unit covers the transformer block's whole FFN sublayer:

    y = dropout(gelu(x @ W1 + b1) @ W2 + b2)

Tiers (ops/kernel_tier.py):
- off:       the mul -> elementwise_add -> gelu -> mul ->
             elementwise_add -> dropout lowerings composed, expression
             for expression (the bitwise parity anchor, amp casts
             included);
- xla:       one fused emission under a custom_vjp: the backward saves
             (x, pre1) and recomputes gelu(pre1) instead of keeping the
             [N, d_ff] activation as a residual — one fewer d_ff-wide
             tensor in HBM than jax AD of the unfused chain;
- pallas:    a tiled matmul-epilogue kernel: each row block runs
             x @ W1 + b1, gelu, @ W2 + b2 (and the dropout multiply)
             without the [bn, d_ff] intermediate ever visiting HBM;
             backward shares the xla tier's recompute emission (its
             gradient is three MXU matmuls XLA already schedules well);
- interpret: the pallas kernel through the interpreter (CPU tests).

Dropout RNG: the op draws ONE key from the program's counted stream
(core/lowering.py ctx.rng(): run counter + op index), so masks replay
exactly across checkpoint save/restore and are identical across tiers
within one program build. Because the fused op replaces six ops with
one, op indices downstream SHIFT relative to the unfused build — masks
therefore differ between fused and unfused program STRUCTURES (the same
precedent fused_ln_residual set in PR 11); bitwise off-tier parity is
asserted for dropout-free/is_test trajectories, which is also the only
regime the pre-PR trajectory tests pin.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import amp
from ..core.registry import register_op
from .common import broadcast_y_to, flatten_to_2d


def ffn_shapes_ok(n, d_in, d_ff, d_out):
    """Tiling rule for the pallas kernel: every matmul axis fills whole
    128-lane tiles, the row count tiles a power-of-two block, and both
    weight panels (+ one row block of every operand) fit VMEM together
    (f32 budget ~12 MB of the ~16 MB/core)."""
    from .ce_ops import _pick_block
    if d_in % 128 or d_ff % 128 or d_out % 128:
        return False
    bn = _pick_block(n, 128, 8)
    if bn is None:
        return False
    weights = (d_in * d_ff + d_ff * d_out) * 4
    rows = bn * (d_in + 2 * d_ff + 2 * d_out) * 4
    return weights + rows <= 12 * 1024 * 1024


def ffn_spmd_ok(mesh, n, d_in, d_ff, d_out):
    """Per-shard rule under a mesh: rows partition over 'data', weights
    ride replicated (tensor-parallel FFN sharding stays on the unfused
    path — parallel/api.py's column/row split of ffn1/ffn2)."""
    from .kernel_tier import mesh_axis
    ax = mesh_axis(mesh, 'data', n)
    n_loc = n // mesh.shape[ax] if ax else n
    return ffn_shapes_ok(n_loc, d_in, d_ff, d_out)


# ---------------------------------------------------------------------------
# pallas forward kernel: one row block through both matmuls per program
# ---------------------------------------------------------------------------

def _ffn_fwd_kernel(has_mask, *refs):
    if has_mask:
        (x_ref, w1_ref, b1_ref, w2_ref, b2_ref, mk_ref,
         y_ref, p1_ref) = refs
    else:
        x_ref, w1_ref, b1_ref, w2_ref, b2_ref, y_ref, p1_ref = refs
    x = x_ref[...]
    pre1 = jnp.dot(x, w1_ref[...],
                   preferred_element_type=jnp.float32) + b1_ref[...]
    h = jax.nn.gelu(pre1, approximate=False).astype(x.dtype)
    y = jnp.dot(h, w2_ref[...],
                preferred_element_type=jnp.float32) + b2_ref[...]
    y = y.astype(y_ref.dtype)
    if has_mask:
        y = y * mk_ref[...]
    y_ref[...] = y
    # pre1 is the ONLY saved d_ff-wide residual (bwd recomputes gelu)
    p1_ref[...] = pre1.astype(p1_ref.dtype)


def _ffn_fwd_pallas(x, w1, b1, w2, b2, mask, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    from .ce_ops import _pick_block
    n, d_in = x.shape
    d_ff = w1.shape[1]
    d_out = w2.shape[1]
    bn = _pick_block(n, 128, 8)
    row_in = pl.BlockSpec((bn, d_in), lambda i: (i, 0))
    row_out = pl.BlockSpec((bn, d_out), lambda i: (i, 0))
    row_ff = pl.BlockSpec((bn, d_ff), lambda i: (i, 0))

    def full(a, b):
        return pl.BlockSpec((a, b), lambda i: (0, 0))
    in_specs = [row_in,
                full(d_in, d_ff), full(1, d_ff),
                full(d_ff, d_out), full(1, d_out)]
    args = [x, w1, b1.reshape(1, d_ff), w2, b2.reshape(1, d_out)]
    if mask is not None:
        in_specs.append(row_out)
        args.append(mask)
    y, pre1 = pl.pallas_call(
        functools.partial(_ffn_fwd_kernel, mask is not None),
        grid=(n // bn,),
        in_specs=in_specs,
        out_specs=[row_out, row_ff],
        out_shape=[jax.ShapeDtypeStruct((n, d_out), x.dtype),
                   jax.ShapeDtypeStruct((n, d_ff), jnp.float32)],
        compiler_params=_compiler_params(pltpu, ("arbitrary",)),
        interpret=interpret,
    )(*args)
    return y, pre1


# ---------------------------------------------------------------------------
# custom_vjp core: both fused tiers share the recompute backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def fused_ffn_core(x, w1, b1, w2, b2, mask, impl):
    """y [N, d_out] for rows x [N, d_in]:
    ``y = (gelu(x @ w1 + b1) @ w2 + b2) * mask`` (``mask`` is the
    pre-scaled keep mask, or None when dropout is inactive). ``impl`` in
    'xla' | 'pallas' | 'interpret' — the 'off' tier lowers the legacy
    composition and never reaches here. The backward saves (x, pre1)
    and recomputes gelu, so no [N, d_ff] activation residual exists."""
    return _ffn_fwd(x, w1, b1, w2, b2, mask, impl)[0]


def _ffn_fwd(x, w1, b1, w2, b2, mask, impl):
    if impl in ('pallas', 'interpret'):
        y, pre1 = _ffn_fwd_pallas(x, w1, b1, w2, b2, mask,
                                  impl == 'interpret')
        cdf = None            # TPU trade: recompute erf, save HBM
    else:
        pre1 = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
        # gelu expanded so the erf factor (cdf) is a named value: the
        # backward reuses it for BOTH the recomputed activation
        # (h = pre1 * cdf) and the gelu derivative — zero erf calls in
        # the backward instead of the two a naive recompute costs (erf
        # dominates the epilogue on CPU)
        cdf = _gelu_cdf(pre1)
        h = (pre1 * cdf).astype(x.dtype)
        y = (jnp.dot(h, w2, preferred_element_type=jnp.float32)
             + b2).astype(x.dtype)
        if mask is not None:
            y = y * mask
    return y, (x, w1, w2, pre1, cdf, mask)


def _gelu_cdf(pre1):
    """Phi(x) — the erf factor of exact gelu, f32."""
    return 0.5 * (1.0 + jax.lax.erf(pre1 * np.float32(1.0 / np.sqrt(2.0))))


def _ffn_bwd(impl, res, dy):
    x, w1, w2, pre1, cdf, mask = res
    dyf = dy.astype(jnp.float32)
    if mask is not None:
        dyf = dyf * mask.astype(jnp.float32)
    if cdf is None:                 # pallas tiers saved pre1 only
        cdf = _gelu_cdf(pre1)
    h = pre1 * cdf                  # gelu recomputed from cdf: no erf
    db2 = jnp.sum(dyf, axis=0).astype(w2.dtype)
    dh = jnp.dot(dyf, w2.T.astype(jnp.float32),
                 preferred_element_type=jnp.float32)
    dw2 = jnp.dot(h.T, dyf,
                  preferred_element_type=jnp.float32).astype(w2.dtype)
    phi = jnp.exp(-0.5 * pre1 * pre1) * np.float32(
        1.0 / np.sqrt(2.0 * np.pi))
    dpre1 = dh * (cdf + pre1 * phi)
    db1 = jnp.sum(dpre1, axis=0).astype(w1.dtype)
    dx = jnp.dot(dpre1, w1.T.astype(jnp.float32),
                 preferred_element_type=jnp.float32).astype(x.dtype)
    dw1 = jnp.dot(x.T.astype(jnp.float32), dpre1,
                  preferred_element_type=jnp.float32).astype(w1.dtype)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dx, dw1, db1, dw2, db2, dmask


fused_ffn_core.defvjp(_ffn_fwd, _ffn_bwd)


def fused_ffn_spmd(x, w1, b1, w2, b2, mask, mesh, impl):
    """Mesh-partitioned FFN tail: rows over 'data' via
    kernel_tier.partitioned_call — the kernel is row-independent, so the
    partitioned call needs no comms; weights ride replicated and their
    cotangents psum through shard_map's transpose. The dropout mask is
    drawn ONCE on the global shape and sharded like the rows, so masks
    are identical with and without a mesh."""
    from jax.sharding import PartitionSpec as P
    from .kernel_tier import partitioned_call, mesh_axis
    data_ax = mesh_axis(mesh, 'data', x.shape[0])
    rowp = P(data_ax, None)
    if mask is None:
        def inner(xl, a1, c1, a2, c2):
            return fused_ffn_core(xl, a1, c1, a2, c2, None, impl)
        return partitioned_call(inner, mesh,
                                (rowp, P(), P(), P(), P()),
                                rowp)(x, w1, b1, w2, b2)

    def inner_m(xl, a1, c1, a2, c2, mk):
        return fused_ffn_core(xl, a1, c1, a2, c2, mk, impl)
    return partitioned_call(inner_m, mesh,
                            (rowp, P(), P(), P(), P(), rowp),
                            rowp)(x, w1, b1, w2, b2, mask)


# ---------------------------------------------------------------------------
# the program-level op
# ---------------------------------------------------------------------------

def _ffn_rng_active(op):
    """Static RNG predicate for executor.bind's needs_rng scan: only a
    TRAIN-mode op with a live dropout probability draws a key — decode
    towers (is_test, prob 0) keep the RNG-free single-PRNGKey fast
    path."""
    return (not op.attr('is_test', False)
            and op.attr('dropout_prob', 0.0) > 0.0)


def _dropout_mask(ctx, op, shape, dtype):
    """The keep mask of the legacy dropout lowering (random_ops._dropout),
    pre-scaled for 'upscale_in_train': key from the counted stream (or
    the op's explicit seed attr, same override rule)."""
    prob = op.attr('dropout_prob', 0.5)
    seed = op.attr('seed', 0)
    key = ctx.rng()
    if seed:
        key = jax.random.PRNGKey(seed)
        key = jax.random.fold_in(key, ctx.op_index)
    keep = jax.random.bernoulli(key, 1.0 - prob, shape)
    return keep.astype(dtype)


@register_op('fused_ffn_tail', needs_rng=_ffn_rng_active)
def _fused_ffn_tail_op(ctx, op):
    """Out = dropout(gelu(X @ W1 + B1) @ W2 + B2): the transformer FFN
    sublayer as one unit. Attrs: x_num_col_dims (the mul flatten rule),
    dropout_prob / is_test / seed / dropout_implementation (the dropout
    op's contract; 'upscale_in_train' is the fused fast path). The 'off'
    tier reproduces the six-op composition BITWISE (amp casts
    included)."""
    from . import kernel_tier
    from ..parallel.api import get_active_mesh
    x = ctx.in1(op, 'X')
    w1 = ctx.in1(op, 'W1')
    b1 = ctx.in1(op, 'B1')
    w2 = ctx.in1(op, 'W2')
    b2 = ctx.in1(op, 'B2')
    xnc = op.attr('x_num_col_dims', 1)
    prob = op.attr('dropout_prob', 0.0)
    is_test = op.attr('is_test', False)
    dimpl = op.attr('dropout_implementation', 'upscale_in_train')
    drop_active = bool(prob) and not is_test

    d_in = w1.shape[0]
    d_ff = w1.shape[1]
    d_out = w2.shape[1]
    n = int(np.prod(x.shape[:xnc])) if xnc > 0 else 1
    amp_dt = op.attr(amp.AMP_ATTR, None)
    # the fused emissions assume the standard tail: trailing-axis matmuls,
    # f32 row streams, upscale dropout — anything else takes the off tier
    fusable = (x.shape[xnc:] == w1.shape[:1] and x.ndim == xnc + 1
               and x.dtype == jnp.dtype(jnp.float32)
               and (not drop_active or (dimpl == 'upscale_in_train'
                                        and prob < 1.0)))
    mesh = get_active_mesh()
    meshed = mesh is not None and mesh.size > 1
    # AMP-marked instances run the xla tier (the casts wrap the fused
    # emission the way mul's lowering wraps each dot); the pallas kernel
    # is written for f32 row tiles, so it stands down under amp
    if fusable and not amp_dt:
        pallas_ok = ffn_spmd_ok(mesh, n, d_in, d_ff, d_out) if meshed \
            else ffn_shapes_ok(n, d_in, d_ff, d_out)
    else:
        pallas_ok = False
    impl = kernel_tier.dispatch(
        'fused_ffn_tail', pallas_ok=pallas_ok, xla_ok=fusable,
        mesh=mesh, count=getattr(ctx, 'sparse_mode', None) != 'scout')

    if impl == 'off':
        # bitwise legacy: mul + elementwise_add + gelu + mul +
        # elementwise_add + dropout lowerings composed (the parity anchor)
        x2 = flatten_to_2d(x, xnc)
        w1_2 = flatten_to_2d(w1, 1)
        x2, w1_2 = amp.cast_compute(op, x2, w1_2)
        h = jnp.dot(x2, w1_2, preferred_element_type=jnp.float32)
        h = h.astype(x.dtype).reshape(x.shape[:xnc] + w1.shape[1:])
        h = h + broadcast_y_to(h, b1, xnc)
        h = jax.nn.gelu(h, approximate=False)
        h2 = flatten_to_2d(h, xnc)
        w2_2 = flatten_to_2d(w2, 1)
        h2, w2_2 = amp.cast_compute(op, h2, w2_2)
        y = jnp.dot(h2, w2_2, preferred_element_type=jnp.float32)
        y = y.astype(h.dtype).reshape(h.shape[:xnc] + w2.shape[1:])
        y = y + broadcast_y_to(y, b2, xnc)
        if drop_active:
            keep = _dropout_mask(ctx, op, y.shape, y.dtype)
            if dimpl == 'upscale_in_train':
                y = jnp.where(prob < 1.0, y * keep / (1.0 - prob),
                              jnp.zeros_like(y))
            else:
                y = y * keep
        elif is_test and bool(prob) and dimpl == 'downgrade_in_infer':
            y = y * (1.0 - prob)
        ctx.out(op, 'Out', y)
        return

    lead = x.shape[:xnc]
    x2 = x.reshape(n, d_in)
    w1c, w2c = w1, w2
    if amp_dt:
        x2, w1c, w2c = amp.cast_compute(op, x2, w1, w2)
    mask = None
    if drop_active:
        # mask on the GLOBAL row shape, pre-scaled, f32: identical across
        # fused tiers and across mesh layouts for one program build
        mask = _dropout_mask(ctx, op, (n, d_out),
                             jnp.float32) / np.float32(1.0 - prob)
    if meshed and impl in ('pallas', 'interpret'):
        y2 = fused_ffn_spmd(x2, w1c, b1, w2c, b2, mask, mesh, impl)
    else:
        y2 = fused_ffn_core(x2, w1c, b1, w2c, b2, mask, impl)
    ctx.out(op, 'Out', y2.astype(x.dtype).reshape(lead + (d_out,)))
