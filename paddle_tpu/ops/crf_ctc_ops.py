"""Structured-prediction op family: linear-chain CRF, Viterbi decoding,
CTC loss/greedy decode, edit distance, chunk evaluation.

Reference semantics (studied from the op definitions, not ported):
- linear_chain_crf_op.cc/.h — Transition parameter [n+2, n]: row 0 start
  weights, row 1 end weights, rows 2.. the [n, n] transition matrix
  (from-tag major). Output LogLikelihood is the per-sequence NEGATIVE log
  conditional likelihood (book label_semantic_roles minimizes its mean).
  Reference runs a normalized linear-space forward pass; we run the same
  recursion in log space with a lax.scan over padded [N, maxT] batches —
  numerically safer and XLA-friendly — and let JAX AD produce the exact
  marginal-difference gradient the reference hand-codes.
- crf_decoding_op.cc — Viterbi; with Label given, emits the 0/1
  per-position correctness mask instead of the path.
- warpctc_op.cc — CTC loss on unnormalized logits (softmax inside);
  per-sequence loss [num_seqs, 1]; norm_by_times divides by length. The
  reference dynloads Baidu warp-ctc; we implement the standard log-space
  alpha recursion (blank-extended labels) under lax.scan, gradient via AD
  through log-softmax (identical to warp-ctc's analytic gradient).
- ctc_align_op.cc — collapse repeats then drop blanks. The reference
  shrinks the tensor (dynamic shape); under XLA the output keeps the input
  LoD with each sequence left-justified and -1 padding (same information,
  static shape) — consumers read tokens until the first -1.
- edit_distance_op.cc — Levenshtein DP, optional normalization by ref len.
- chunk_eval_op.cc — precision/recall/F1 over IOB/IOE/IOBES/plain chunk
  schemes; id = chunk_type * num_tag_types + tag_type, O = num_chunk_types
  * num_tag_types.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.lod import lengths_from_offsets
from .rnn_ops import _padded_maps, _to_padded, _to_ragged

NEG = -1e9


def _padded_from_lod(ctx, op, slot):
    lod = ctx.in1_lod(op, slot)
    if not lod:
        raise ValueError("op %s input %s needs LoD (ragged sequences)"
                         % (op.type, slot))
    offsets = lod[-1]
    gidx, sidx, n, maxt = _padded_maps(offsets)
    lens = np.asarray(lengths_from_offsets(offsets), np.int32)
    return offsets, gidx, sidx, n, maxt, lens


# ---------------------------------------------------------------------------
# linear_chain_crf
# ---------------------------------------------------------------------------

def _crf_unpack(transition):
    return transition[0], transition[1], transition[2:]


@register_op('linear_chain_crf')
def _linear_chain_crf(ctx, op):
    emission = ctx.in1(op, 'Emission')          # [total, n] ragged
    transition = ctx.in1(op, 'Transition')      # [n+2, n]
    label = ctx.in1(op, 'Label')                # [total, 1] ragged
    offsets, gidx, sidx, n_seq, maxt, lens = _padded_from_lod(
        ctx, op, 'Emission')
    n_tag = emission.shape[-1]

    e = _to_padded(emission, gidx, n_seq, maxt)             # [N, T, n]
    y = _to_padded(label.reshape(-1), gidx, n_seq, maxt)    # [N, T]
    y = y.astype('int32')
    lens_j = jnp.asarray(lens)
    w_start, w_end, w_trans = _crf_unpack(transition)

    tm = e.swapaxes(0, 1)                                    # [T, N, n]
    ym = y.swapaxes(0, 1)                                    # [T, N]
    step_idx = jnp.arange(maxt)

    # --- partition function: log-space forward recursion ----------------
    alpha0 = w_start[None, :] + tm[0]                        # [N, n]

    def fwd(alpha, xt):
        e_t, t = xt
        nxt = e_t + jax.scipy.special.logsumexp(
            alpha[:, :, None] + w_trans[None, :, :], axis=1)
        valid = (t < lens_j)[:, None]
        alpha = jnp.where(valid, nxt, alpha)
        return alpha, alpha

    alpha_last, alphas = lax.scan(fwd, alpha0, (tm[1:], step_idx[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_last + w_end[None, :], axis=1)

    # --- gold path score -------------------------------------------------
    batch = jnp.arange(n_seq)
    em_gold = jnp.take_along_axis(e, y[:, :, None], axis=2)[:, :, 0]  # [N,T]
    t_mask = step_idx[None, :] < lens_j[:, None]
    em_score = jnp.sum(jnp.where(t_mask, em_gold, 0.0), axis=1)
    start_score = w_start[y[:, 0]]
    last_y = y[batch, jnp.maximum(lens_j - 1, 0)]
    end_score = w_end[last_y]
    trans_pairs = w_trans[y[:, :-1], y[:, 1:]]               # [N, T-1]
    pair_mask = step_idx[None, 1:] < lens_j[:, None]
    trans_score = jnp.sum(jnp.where(pair_mask, trans_pairs, 0.0), axis=1)
    gold = em_score + start_score + end_score + trans_score

    nll = (log_z - gold).reshape(n_seq, 1)
    ctx.out(op, 'LogLikelihood', nll)

    # caches for reference-API parity
    all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, N, n]
    ctx.out(op, 'Alpha', _to_ragged(all_alphas.swapaxes(0, 1), sidx))
    ctx.set_lod(op.output('Alpha')[0], (offsets,))
    ctx.out(op, 'EmissionExps', jnp.exp(emission))
    ctx.out(op, 'TransitionExps', jnp.exp(transition))
    ctx.lod_explicit.add(op.output('LogLikelihood')[0])


# ---------------------------------------------------------------------------
# crf_decoding (Viterbi)
# ---------------------------------------------------------------------------

@register_op('crf_decoding')
def _crf_decoding(ctx, op):
    emission = ctx.in1(op, 'Emission')
    transition = ctx.in1(op, 'Transition')
    label = ctx.in1(op, 'Label', None)
    offsets, gidx, sidx, n_seq, maxt, lens = _padded_from_lod(
        ctx, op, 'Emission')
    lens_j = jnp.asarray(lens)
    w_start, w_end, w_trans = _crf_unpack(transition)

    e = _to_padded(emission, gidx, n_seq, maxt)
    tm = e.swapaxes(0, 1)                                    # [T, N, n]
    step_idx = jnp.arange(maxt)

    delta0 = w_start[None, :] + tm[0]

    def fwd(delta, xt):
        e_t, t = xt
        scores = delta[:, :, None] + w_trans[None, :, :]     # [N, from, to]
        best_from = jnp.argmax(scores, axis=1)               # [N, n]
        nxt = e_t + jnp.max(scores, axis=1)
        valid = (t < lens_j)[:, None]
        delta = jnp.where(valid, nxt, delta)
        return delta, best_from

    delta_last, bps = lax.scan(fwd, delta0, (tm[1:], step_idx[1:]))
    # bps[t-1]: best predecessor for step t
    last_tag = jnp.argmax(delta_last + w_end[None, :], axis=1)  # [N]

    batch = jnp.arange(n_seq)

    if maxt == 1:
        path = last_tag[:, None]
    else:
        def back(tag, xt):
            bp_t, t = xt                                     # bp for step t+1
            prev = bp_t[batch, tag]
            # only follow the pointer if step t+1 is within the sequence
            tag_out = jnp.where(t + 1 < lens_j, prev, tag)
            return tag_out, tag_out

        # walk t = maxt-2 .. 0 emitting the tag at position t
        _, tags_rev = lax.scan(back, last_tag,
                               (bps[::-1], step_idx[maxt - 2::-1]))
        path = jnp.concatenate([tags_rev[::-1].T,
                                last_tag[:, None]], axis=1)  # [N, T]
        # position len-1 of each sequence holds its final tag
        pos = step_idx[None, :]
        path = jnp.where(pos == (lens_j[:, None] - 1),
                         last_tag[:, None], path)

    ragged = _to_ragged(path[:, :, None], sidx).reshape(-1, 1).astype('int64')
    if label is not None:
        correct = (ragged == label.astype('int64')).astype('int64')
        ctx.out(op, 'ViterbiPath', correct)
    else:
        ctx.out(op, 'ViterbiPath', ragged)
    ctx.set_lod(op.output('ViterbiPath')[0], (offsets,))


# ---------------------------------------------------------------------------
# warpctc
# ---------------------------------------------------------------------------

@register_op('warpctc')
def _warpctc(ctx, op):
    logits = ctx.in1(op, 'Logits')              # [totalT, C] ragged
    label = ctx.in1(op, 'Label')                # [totalL, 1] ragged
    blank = int(op.attr('blank', 0))
    norm_by_times = bool(op.attr('norm_by_times', False))

    t_off, t_gidx, _, n_seq, maxt, t_lens = _padded_from_lod(
        ctx, op, 'Logits')
    l_lod = ctx.in1_lod(op, 'Label')
    if not l_lod:
        raise ValueError("warpctc Label needs LoD")
    l_offsets = l_lod[-1]
    l_gidx, _, _, maxl = _padded_maps(l_offsets)
    l_lens = np.asarray(lengths_from_offsets(l_offsets), np.int32)

    lp = jax.nn.log_softmax(
        _to_padded(logits, t_gidx, n_seq, maxt), axis=-1)    # [N, T, C]
    y = _to_padded(label.reshape(-1), l_gidx, n_seq, maxl)   # [N, L]
    y = y.astype('int32')

    t_lens_j = jnp.asarray(t_lens)
    l_lens_j = jnp.asarray(l_lens)

    # blank-extended labels l' of length S = 2*maxl + 1
    S = 2 * maxl + 1
    ext = jnp.full((n_seq, S), blank, dtype='int32')
    ext = ext.at[:, 1::2].set(y)                             # [N, S]
    s_idx = jnp.arange(S)
    s_valid = s_idx[None, :] < (2 * l_lens_j[:, None] + 1)

    # allow skip from s-2 when l'_s != blank and l'_s != l'_{s-2}
    ext_m2 = jnp.concatenate(
        [jnp.full((n_seq, 2), -1, 'int32'), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    lp_tm = lp.swapaxes(0, 1)                                # [T, N, C]
    batch = jnp.arange(n_seq)

    def emit(lp_t):
        return lp_t[batch[:, None], ext]                     # [N, S]

    alpha0 = jnp.full((n_seq, S), NEG)
    alpha0 = alpha0.at[:, 0].set(emit(lp_tm[0])[:, 0])
    if maxl > 0:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(l_lens_j > 0, emit(lp_tm[0])[:, 1], NEG))
    alpha0 = jnp.where(s_valid, alpha0, NEG)

    def step(alpha, xt):
        lp_t, t = xt
        a_m1 = jnp.concatenate(
            [jnp.full((n_seq, 1), NEG), alpha[:, :-1]], axis=1)
        a_m2 = jnp.concatenate(
            [jnp.full((n_seq, 2), NEG), alpha[:, :-2]], axis=1)
        paths = jnp.logaddexp(alpha, a_m1)
        paths = jnp.where(can_skip, jnp.logaddexp(paths, a_m2), paths)
        nxt = emit(lp_t) + paths
        nxt = jnp.where(s_valid, nxt, NEG)
        valid_t = (t < t_lens_j)[:, None]
        return jnp.where(valid_t, nxt, alpha), None

    step_idx = jnp.arange(1, maxt)
    alpha_last, _ = lax.scan(step, alpha0, (lp_tm[1:], step_idx))

    end1 = alpha_last[batch, 2 * l_lens_j]                   # final blank
    end2 = jnp.where(l_lens_j > 0,
                     alpha_last[batch, jnp.maximum(2 * l_lens_j - 1, 0)],
                     NEG)
    loss = -jnp.logaddexp(end1, end2)
    if norm_by_times:
        loss = loss / jnp.maximum(t_lens_j.astype(loss.dtype), 1.0)
    ctx.out(op, 'Loss', loss.reshape(n_seq, 1))
    ctx.lod_explicit.add(op.output('Loss')[0])


# ---------------------------------------------------------------------------
# ctc_align
# ---------------------------------------------------------------------------

@register_op('ctc_align')
def _ctc_align(ctx, op):
    x = ctx.in1(op, 'Input')                    # [total, 1] ragged ids
    blank = int(op.attr('blank', 0))
    offsets, gidx, sidx, n_seq, maxt, lens = _padded_from_lod(
        ctx, op, 'Input')
    ids = _to_padded(x.reshape(-1), gidx, n_seq, maxt).astype('int32')
    valid = jnp.arange(maxt)[None, :] < jnp.asarray(lens)[:, None]

    prev = jnp.concatenate(
        [jnp.full((n_seq, 1), -1, 'int32'), ids[:, :-1]], axis=1)
    keep = valid & (ids != blank) & (ids != prev)
    # left-justify kept tokens; dropped slots -> -1 padding
    pos = jnp.cumsum(keep.astype('int32'), axis=1) - 1
    out = jnp.full((n_seq, maxt + 1), -1, dtype='int32')
    rows = jnp.arange(n_seq)[:, None].repeat(maxt, 1)
    cols = jnp.where(keep, pos, maxt)           # dump dropped into col maxt
    out = out.at[rows.reshape(-1), cols.reshape(-1)].set(
        jnp.where(keep, ids, -1).reshape(-1))
    out = out[:, :maxt]
    ragged = _to_ragged(out[:, :, None], sidx)
    ctx.out(op, 'Output', ragged.reshape(-1, 1).astype('int64'))
    ctx.set_lod(op.output('Output')[0], (offsets,))


# ---------------------------------------------------------------------------
# edit_distance
# ---------------------------------------------------------------------------

def _trim_sentinel(toks, lens):
    """Effective lengths ignoring the -1 padding ctc_align leaves in its
    left-justified static-shape output (tokens after the first -1 are
    padding, not hypothesis tokens) — so ctc_greedy_decoder output composes
    with edit_distance exactly like the reference's shrunk tensors."""
    is_pad = (toks == -1)
    first_pad = jnp.where(is_pad.any(axis=1),
                          jnp.argmax(is_pad, axis=1),
                          toks.shape[1]).astype(lens.dtype)
    return jnp.minimum(lens, first_pad)


@register_op('edit_distance')
def _edit_distance(ctx, op):
    hyp = ctx.in1(op, 'Hyps')                   # [totalH, 1] ragged
    ref = ctx.in1(op, 'Refs')                   # [totalR, 1] ragged
    normalized = bool(op.attr('normalized', False))

    h_off, h_gidx, _, n_seq, maxh, h_lens = _padded_from_lod(
        ctx, op, 'Hyps')
    r_lod = ctx.in1_lod(op, 'Refs')
    r_gidx, _, r_n, maxr = _padded_maps(r_lod[-1])
    r_lens = np.asarray(lengths_from_offsets(r_lod[-1]), np.int32)

    H = _to_padded(hyp.reshape(-1), h_gidx, n_seq, maxh).astype('int32')
    R = _to_padded(ref.reshape(-1), r_gidx, n_seq, maxr).astype('int32')
    # Only Hyps get sentinel trimming: hypotheses come from ctc_align, whose
    # static-shape output left-justifies tokens and pads with -1. Refs are
    # user labels; the reference implementation has no sentinel semantics for
    # them, and -1 must stay a legitimate (mismatching) token there.
    h_lens_j = _trim_sentinel(H, jnp.asarray(h_lens))
    r_lens_j = jnp.asarray(r_lens)

    # DP rows over hypothesis positions; vectorized over batch and ref cols
    j_idx = jnp.arange(maxr + 1)
    row0 = jnp.broadcast_to(j_idx[None, :].astype('float32'),
                            (n_seq, maxr + 1))

    def dp(prev_row, xt):
        h_tok, i = xt                                        # h_tok: [N]
        sub_cost = (H[:, i][:, None] != R).astype('float32')  # [N, maxr]
        # new_row[0] = i+1
        def col_step(left, cols):
            prev_j, prev_jm1, sub = cols                     # [N] each
            val = jnp.minimum(jnp.minimum(prev_j + 1.0, left + 1.0),
                              prev_jm1 + sub)
            return val, val

        init = jnp.full((n_seq,), i + 1, dtype='float32')
        _, cols = lax.scan(
            col_step, init,
            (prev_row[:, 1:].T, prev_row[:, :-1].T, sub_cost.T))
        new_row = jnp.concatenate([init[:, None], cols.T], axis=1)
        valid = (i < h_lens_j)[:, None]
        row = jnp.where(valid, new_row, prev_row)
        return row, None

    i_idx = jnp.arange(maxh)
    final_row, _ = lax.scan(dp, row0, (H.T, i_idx))
    dist = final_row[jnp.arange(n_seq), r_lens_j]
    if normalized:
        dist = dist / jnp.maximum(r_lens_j.astype('float32'), 1.0)
    ctx.out(op, 'Out', dist.reshape(n_seq, 1))
    ctx.out(op, 'SequenceNum', jnp.asarray([n_seq], dtype='int64'))
    ctx.lod_explicit.add(op.output('Out')[0])


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------

_SCHEMES = {'IOB': 2, 'IOE': 2, 'IOBES': 4, 'plain': 1}


def _chunk_masks(ids, scheme, num_chunk_types, first, last, nxt_first,
                 excluded):
    """begin/end/inside masks + per-position chunk type for one scheme.
    ids: [T] padded flat; first/last: sequence-boundary masks."""
    tag_num = _SCHEMES[scheme]
    o_id = num_chunk_types * tag_num
    is_o = ids >= o_id
    ctype = jnp.where(is_o, -1, ids // tag_num)
    tag = jnp.where(is_o, -1, ids % tag_num)
    if excluded:
        excl = jnp.zeros_like(is_o)
        for e in excluded:
            excl = excl | (ctype == int(e))
        is_o = is_o | excl
        ctype = jnp.where(is_o, -1, ctype)
        tag = jnp.where(is_o, -1, tag)

    inside = ~is_o
    prev_inside = jnp.concatenate([jnp.array([False]), inside[:-1]])
    prev_inside = prev_inside & ~first
    prev_type = jnp.concatenate([jnp.array([-1]), ctype[:-1]])
    prev_tag = jnp.concatenate([jnp.array([-1]), tag[:-1]])
    next_inside = jnp.concatenate([inside[1:], jnp.array([False])])
    next_inside = next_inside & ~nxt_first
    next_type = jnp.concatenate([ctype[1:], jnp.array([-1])])
    next_tag = jnp.concatenate([tag[1:], jnp.array([-1])])
    diff_prev = ~prev_inside | (prev_type != ctype)
    diff_next = ~next_inside | (next_type != ctype)

    if scheme == 'plain':
        begin = inside & diff_prev
        end = inside & diff_next
    elif scheme == 'IOB':        # B=0, I=1
        begin = inside & ((tag == 0) | diff_prev)
        end = inside & (diff_next | (next_tag == 0))
    elif scheme == 'IOE':        # I=0, E=1
        begin = inside & (diff_prev | (prev_tag == 1))
        end = inside & ((tag == 1) | diff_next)
    else:                        # IOBES: B=0, I=1, E=2, S=3
        begin = inside & ((tag == 0) | (tag == 3) |
                          (diff_prev | (prev_tag == 2) | (prev_tag == 3)))
        end = inside & ((tag == 2) | (tag == 3) |
                        (diff_next | (next_tag == 0) | (next_tag == 3)))
    return begin, end, inside, ctype


@register_op('chunk_eval')
def _chunk_eval(ctx, op):
    inference = ctx.in1(op, 'Inference')        # [total, 1] ragged int
    label = ctx.in1(op, 'Label')
    scheme = op.attr('chunk_scheme', 'IOB')
    num_chunk_types = int(op.attr('num_chunk_types'))
    excluded = list(op.attr('excluded_chunk_types', []) or [])

    lod = ctx.in1_lod(op, 'Inference')
    if not lod:
        raise ValueError("chunk_eval needs LoD input")
    offsets = lod[-1]
    total = offsets[-1]
    firsts = np.zeros(total, bool)
    firsts[np.asarray(offsets[:-1], np.int64)] = True
    first = jnp.asarray(firsts)
    nxt_first = jnp.concatenate([first[1:], jnp.array([True])])
    last = nxt_first

    inf = inference.reshape(-1).astype('int32')
    lab = label.reshape(-1).astype('int32')
    b_i, e_i, in_i, t_i = _chunk_masks(inf, scheme, num_chunk_types,
                                       first, last, nxt_first, excluded)
    b_l, e_l, in_l, t_l = _chunk_masks(lab, scheme, num_chunk_types,
                                       first, last, nxt_first, excluded)

    idx = jnp.arange(total)

    def starts_of(begin, inside):
        # start index of the chunk covering each position (-1 outside)
        def step(cur, xt):
            b, ins, i = xt
            cur = jnp.where(b, i, jnp.where(ins, cur, -1))
            return cur, cur
        _, s = lax.scan(step, jnp.asarray(-1, 'int32'),
                        (begin, inside, idx.astype('int32')))
        return s

    s_i = starts_of(b_i, in_i)
    s_l = starts_of(b_l, in_l)

    match = (e_i & e_l & (s_i == s_l) & (s_i >= 0) &
             (t_i == t_l))
    num_correct = jnp.sum(match).astype('int64')
    num_inf = jnp.sum(b_i).astype('int64')
    num_lab = jnp.sum(b_l).astype('int64')

    prec = num_correct / jnp.maximum(num_inf, 1).astype('float32')
    rec = num_correct / jnp.maximum(num_lab, 1).astype('float32')
    f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec), 0.0)
    prec = jnp.where(num_inf > 0, prec, 0.0)
    rec = jnp.where(num_lab > 0, rec, 0.0)

    ctx.out(op, 'Precision', prec.reshape(1))
    ctx.out(op, 'Recall', rec.reshape(1))
    ctx.out(op, 'F1-Score', f1.reshape(1))
    ctx.out(op, 'NumInferChunks', num_inf.reshape(1))
    ctx.out(op, 'NumLabelChunks', num_lab.reshape(1))
    ctx.out(op, 'NumCorrectChunks', num_correct.reshape(1))
    for slot in ('Precision', 'Recall', 'F1-Score', 'NumInferChunks',
                 'NumLabelChunks', 'NumCorrectChunks'):
        names = op.output(slot)
        if names:
            ctx.lod_explicit.add(names[0])
