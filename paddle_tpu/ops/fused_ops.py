"""Fused-op surface (reference operators/fused/) + file IO ops.

The reference ships hand-fused CPU/CUDA kernels for these; on TPU the
whole program compiles through XLA, which performs the same fusions
automatically, so each op lowers to its unfused composition — the op
SURFACE is kept (programs built by the reference's fuse passes or user
code execute correctly), while the fusion itself is the compiler's job
(SURVEY §2.2). Each lowering cites the reference op it matches and is
tested against a composition of our own unfused ops.

Also here: save/load/save_combine/load_combine (reference save_op.cc:36,
load_op, save_combine_op, load_combine_op) — save streams device values to
host .npz via ordered io_callback inside the compiled step; load binds the
file contents at trace time (static weights); and rnn_memory_helper
(identity with gradient, reference rnn_memory_helper_op.cc).
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.lod import segment_ids, lengths_from_offsets, context_maps
from .rnn_ops import _padded_maps, _to_padded, _to_ragged, _act


def _ew(name, x, y, axis=-1):
    """Broadcast like our elementwise ops: y reshaped to x rank at axis."""
    if y.ndim < x.ndim:
        if axis < 0:
            axis = x.ndim - y.ndim
        shape = [1] * x.ndim
        for i, d in enumerate(y.shape):
            shape[axis + i] = d
        y = y.reshape(shape)
    if name == 'elementwise_add':
        return x + y
    if name == 'elementwise_mul':
        return x * y
    if name == 'elementwise_sub':
        return x - y
    raise NotImplementedError("fused_elemwise binary functor %r" % name)


_UNARY = {'relu': jax.nn.relu, 'tanh': jnp.tanh,
          'sigmoid': jax.nn.sigmoid}


@register_op('fused_elemwise_activation')
def _fused_elemwise_activation(ctx, op):
    """reference fused/fused_elemwise_activation_op.cc: functor_list of
    two; unary-compound = unary(binary(x, y)), binary-compound =
    binary(x, unary(y)). `scale` attr parameterizes the scale functor."""
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    functors = [str(f) for f in op.attr('functor_list')]
    axis = int(op.attr('axis', -1))
    scale = float(op.attr('scale', 0.0))
    if len(functors) != 2:
        raise ValueError("functor_list must have exactly 2 entries")

    def unary(name, v):
        if name == 'scale':
            return v * scale
        if name in _UNARY:
            return _UNARY[name](v)
        raise NotImplementedError(
            "fused_elemwise unary functor %r" % name)

    if functors[1].startswith('elementwise_'):
        # unary(binary(x, y)) — unary compound
        inter = _ew(functors[1], x, y, axis)
        out = unary(functors[0], inter)
    else:
        # binary(x, unary(y))
        inter = unary(functors[1], y)
        out = _ew(functors[0], x, inter, axis)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'IntermediateOut', inter)


def _fusion_lstm_core(ctx, op, xx, lod):
    """Shared LSTM tail for fusion_lstm / fused_embedding_fc_lstm (gate
    order [c, i, f, o]: fusion_lstm_op.cc:134 'Weight = {W_cx, W_ix,
    W_fx, W_ox}')."""
    wh = ctx.in1(op, 'WeightH')                 # (D, 4D)
    bias = ctx.in1(op, 'Bias')
    d = wh.shape[0]
    use_peepholes = bool(op.attr('use_peepholes', False))
    reverse = bool(op.attr('is_reverse', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_cell = _act(op.attr('cell_activation', 'tanh'))
    act_cand = _act(op.attr('candidate_activation', 'tanh'))
    offsets = lod[-1]
    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(xx, gidx, n, maxt)          # (N, maxT, 4D)
    b = bias.reshape(-1)
    b_gates = b[:4 * d]
    if use_peepholes:
        w_ic, w_fc, w_oc = (b[4 * d:5 * d], b[5 * d:6 * d],
                            b[6 * d:7 * d])
    else:
        w_ic = w_fc = w_oc = jnp.zeros((d,), xx.dtype)
    h0 = ctx.in1(op, 'H0')
    c0 = ctx.in1(op, 'C0')
    h_init = h0.astype(xx.dtype) if h0 is not None else \
        jnp.zeros((n, d), xx.dtype)
    c_init = c0.astype(xx.dtype) if c0 is not None else \
        jnp.zeros((n, d), xx.dtype)

    def step(carry, xt):
        h_prev, c_prev = carry
        g = xt + b_gates + h_prev @ wh
        cand = act_cand(g[:, :d])
        i = act_gate(g[:, d:2 * d] + c_prev * w_ic)
        f = act_gate(g[:, 2 * d:3 * d] + c_prev * w_fc)
        c = cand * i + c_prev * f
        o = act_gate(g[:, 3 * d:] + c * w_oc)
        h = o * act_cell(c)
        return (h, c), (h, c)

    _, (hs, cs) = lax.scan(step, (h_init, c_init), xp.transpose(1, 0, 2))
    hidden = _to_ragged(hs.transpose(1, 0, 2), sidx)
    cell = _to_ragged(cs.transpose(1, 0, 2), sidx)
    ctx.out(op, 'Hidden', hidden)
    ctx.out(op, 'Cell', cell)
    for slot in ('Hidden', 'Cell'):
        if op.output(slot):
            ctx.set_lod(op.output(slot)[0], lod)


@register_op('fusion_lstm')
def _fusion_lstm(ctx, op):
    """reference fused/fusion_lstm_op.cc: x-projection fused into the
    recurrence; XX = X @ WeightX."""
    x = ctx.in1(op, 'X')                        # LoD (T, M)
    wx = ctx.in1(op, 'WeightX')                 # (M, 4D)
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("fusion_lstm requires LoD X")
    xx = x @ wx
    ctx.out(op, 'XX', xx)
    _fusion_lstm_core(ctx, op, xx, lod)


@register_op('fused_embedding_fc_lstm')
def _fused_embedding_fc_lstm(ctx, op):
    """reference fused/fused_embedding_fc_lstm_op.cc: the embedding table
    stores pre-projected gate inputs (V, 4D); lookup replaces the fc."""
    ids = ctx.in1(op, 'Ids')                    # LoD (T, 1) int64
    emb = ctx.in1(op, 'Embeddings')             # (V, 4D)
    lod = ctx.in1_lod(op, 'Ids')
    if not lod:
        raise ValueError("fused_embedding_fc_lstm requires LoD Ids")
    xx = jnp.take(emb, ids.reshape(-1).astype(jnp.int32), axis=0)
    ctx.out(op, 'XX', xx)
    _fusion_lstm_core(ctx, op, xx, lod)


@register_op('fusion_gru')
def _fusion_gru(ctx, op):
    """reference fused/fusion_gru_op.cc: gru with the x-projection fused;
    gate layout [u, r | c] like gru_op."""
    x = ctx.in1(op, 'X')                        # LoD (T, M)
    wx = ctx.in1(op, 'WeightX')                 # (M, 3D)
    wh = ctx.in1(op, 'WeightH')                 # (D, 3D)
    bias = ctx.in1(op, 'Bias')
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("fusion_gru requires LoD X")
    d = wh.shape[0]
    b = bias.reshape(-1) if bias is not None else jnp.zeros((3 * d,),
                                                            x.dtype)
    reverse = bool(op.attr('is_reverse', False))
    origin_mode = bool(op.attr('origin_mode', False))
    act_gate = _act(op.attr('gate_activation', 'sigmoid'))
    act_node = _act(op.attr('activation', 'tanh'))
    xx = x @ wx
    ctx.out(op, 'XX', xx)
    offsets = lod[-1]
    gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
    xp = _to_padded(xx, gidx, n, maxt)
    w_ur, w_c = wh[:, :2 * d], wh[:, 2 * d:]
    h0 = ctx.in1(op, 'H0')
    h_init = h0.astype(x.dtype) if h0 is not None else \
        jnp.zeros((n, d), x.dtype)

    def step(h_prev, xt):
        xur = xt[:, :2 * d] + b[:2 * d]
        xc = xt[:, 2 * d:] + b[2 * d:]
        ur = act_gate(xur + h_prev @ w_ur)
        u, r = ur[:, :d], ur[:, d:]
        c = act_node(xc + (r * h_prev) @ w_c)
        h = u * h_prev + (1.0 - u) * c if origin_mode \
            else (1.0 - u) * h_prev + u * c
        return h, h

    _, hs = lax.scan(step, h_init, xp.transpose(1, 0, 2))
    hidden = _to_ragged(hs.transpose(1, 0, 2), sidx)
    ctx.out(op, 'Hidden', hidden)
    if op.output('Hidden'):
        ctx.set_lod(op.output('Hidden')[0], lod)


@register_op('fusion_repeated_fc_relu')
def _fusion_repeated_fc_relu(ctx, op):
    """reference fused/fusion_repeated_fc_relu_op.cc: stacked
    relu(x @ W + b)."""
    x = ctx.in1(op, 'X')
    ws = ctx.in_list(op, 'W')
    bs = ctx.in_list(op, 'Bias')
    cur = x
    for w, b in zip(ws, bs):
        cur = jax.nn.relu(cur @ w + b.reshape(-1))
    ctx.out(op, 'Out', cur)
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], ctx.in1_lod(op, 'X'))


@register_op('fusion_seqconv_eltadd_relu')
def _fusion_seqconv_eltadd_relu(ctx, op):
    """reference fused/fusion_seqconv_eltadd_relu_op.cc:
    relu(sequence_conv(x) + bias)."""
    x = ctx.in1(op, 'X')                        # LoD (T, M)
    filt = ctx.in1(op, 'Filter')                # (ctx_len*M, out)
    bias = ctx.in1(op, 'Bias')
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("fusion_seqconv_eltadd_relu requires LoD X")
    ctx_len = int(op.attr('contextLength'))
    ctx_start = int(op.attr('contextStart', 0))
    t, m = x.shape
    idx, valid = context_maps(lod[-1], ctx_len, ctx_start)
    mat = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0).reshape(
        t, ctx_len, m)
    mat = mat * jnp.asarray(valid)[:, :, None].astype(x.dtype)
    col = mat.reshape(t, ctx_len * m)
    ctx.out(op, 'ColMat', col)
    out = jax.nn.relu(col @ filt + bias.reshape(-1))
    ctx.out(op, 'Out', out)
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], lod)


@register_op('fusion_seqexpand_concat_fc')
def _fusion_seqexpand_concat_fc(ctx, op):
    """reference fused/fusion_seqexpand_concat_fc_op.cc: first input is a
    (T, M0) LoD sequence; the rest are per-sequence (N, Mi) rows expanded
    along it; concat features then fc (+activation)."""
    xs = ctx.in_list(op, 'X')
    w = ctx.in1(op, 'FCWeight')
    b = ctx.in1(op, 'FCBias')
    act = _act(op.attr('fc_activation', 'identity'))
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("fusion_seqexpand_concat_fc requires LoD X[0]")
    seg = jnp.asarray(segment_ids(lod[-1]))
    parts = [xs[0]] + [jnp.take(xi, seg, axis=0) for xi in xs[1:]]
    cat = jnp.concatenate(parts, axis=1)
    out = cat @ w
    if b is not None:
        out = out + b.reshape(-1)
    out = act(out)
    ctx.out(op, 'Out', out)
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], lod)


@register_op('fusion_seqpool_concat')
def _fusion_seqpool_concat(ctx, op):
    """reference fused/fusion_seqpool_concat_op.cc: sequence_pool each
    LoD input (SUM/AVERAGE/SQRT) then concat along axis 1."""
    names = op.input('X')
    pooltype = str(op.attr('pooltype', 'SUM')).upper()
    pooled = []
    for name in names:
        x = ctx.get(name)
        lod = ctx.lod_of(name)
        if not lod:
            raise ValueError("fusion_seqpool_concat input %r needs LoD"
                             % name)
        offsets = lod[-1]
        n = len(offsets) - 1
        seg = jnp.asarray(segment_ids(offsets))
        s = jax.ops.segment_sum(x, seg, num_segments=n)
        lens = jnp.asarray(
            np.asarray(lengths_from_offsets(offsets), np.float32))
        if pooltype == 'AVERAGE':
            s = s / jnp.maximum(lens, 1.0)[:, None]
        elif pooltype == 'SQRT':
            s = s / jnp.sqrt(jnp.maximum(lens, 1.0))[:, None]
        elif pooltype != 'SUM':
            raise NotImplementedError(
                "fusion_seqpool_concat pooltype %r" % pooltype)
        pooled.append(s)
    ctx.out(op, 'Out', jnp.concatenate(pooled, axis=1))
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], ())


@register_op('fusion_squared_mat_sub')
def _fusion_squared_mat_sub(ctx, op):
    """reference fused/fusion_squared_mat_sub_op.cc:
    Out = scalar * ((X@Y)^2 - (X^2)@(Y^2))."""
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    scalar = float(op.attr('scalar', 1.0))
    xy = x @ y
    out = scalar * (xy * xy - (x * x) @ (y * y))
    ctx.out(op, 'SquaredX', x * x)
    ctx.out(op, 'SquaredY', y * y)
    ctx.out(op, 'SquaredXY', xy * xy)
    ctx.out(op, 'Out', out)


@register_op('fusion_transpose_flatten_concat', share_lod=False)
def _fusion_transpose_flatten_concat(ctx, op):
    """reference fused/fusion_transpose_flatten_concat_op.cc: per input
    transpose(trans_axis) -> flatten(flatten_axis) -> concat(concat_axis)."""
    xs = ctx.in_list(op, 'X')
    trans = [int(a) for a in op.attr('trans_axis')]
    flat_axis = int(op.attr('flatten_axis'))
    concat_axis = int(op.attr('concat_axis'))
    outs = []
    for x in xs:
        t = jnp.transpose(x, trans)
        lead = int(np.prod(t.shape[:flat_axis])) if flat_axis else 1
        outs.append(t.reshape(lead, -1))
    ctx.out(op, 'Out', jnp.concatenate(outs, axis=concat_axis))


# ---------------------------------------------------------------------------
# file IO ops — reference save_op.cc:36 / load_op.cc / *_combine variants
# ---------------------------------------------------------------------------

def _save_cb(path, overwrite):
    def cb(*arrays):
        if os.path.exists(path) and not overwrite:
            raise RuntimeError("save op: %r exists and overwrite=False"
                               % path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        np.savez(path, *[np.asarray(a) for a in arrays])
        return np.zeros((), np.int32)
    return cb


def _io_callback(cb, args, host_eager=False):
    if host_eager:
        # executor host segment: values are concrete, write directly
        return cb(*[np.asarray(a) for a in args])
    try:
        return jax.experimental.io_callback(
            cb, jax.ShapeDtypeStruct((), jnp.int32), *args, ordered=True)
    except (AttributeError, ImportError):
        return jax.pure_callback(cb, jax.ShapeDtypeStruct((), jnp.int32),
                                 *args)


@register_op('save', stateful=True)
def _save(ctx, op):
    """reference save_op.cc:36: serialize one variable to file_path. The
    write happens via ordered io_callback inside the compiled step."""
    x = ctx.in1(op, 'X')
    path = str(op.attr('file_path'))
    overwrite = bool(op.attr('overwrite', True))
    _io_callback(_save_cb(path, overwrite), [x],
                 host_eager=ctx.params.get('host_eager', False))


@register_op('save_combine', stateful=True)
def _save_combine(ctx, op):
    """reference save_combine_op.cc: many variables, one file."""
    xs = ctx.in_list(op, 'X')
    path = str(op.attr('file_path'))
    overwrite = bool(op.attr('overwrite', True))
    _io_callback(_save_cb(path, overwrite), xs,
                 host_eager=ctx.params.get('host_eager', False))


def _npz_arrays(path):
    if not os.path.exists(path) and os.path.exists(path + '.npz'):
        path = path + '.npz'
    with np.load(path) as z:
        return [z['arr_%d' % i] for i in range(len(z.files))]


@register_op('load')
def _load(ctx, op):
    """reference load_op.cc: read file_path into the output variable. The
    file binds at program-compile time (weights are compile-time constants
    to XLA, like the inference-engine param load, inference/io.cc)."""
    arrays = _npz_arrays(str(op.attr('file_path')))
    ctx.out(op, 'Out', jnp.asarray(arrays[0]))


@register_op('load_combine')
def _load_combine(ctx, op):
    """reference load_combine_op.cc: one file, many output variables."""
    arrays = _npz_arrays(str(op.attr('file_path')))
    names = op.output('Out')
    if len(arrays) < len(names):
        raise ValueError("load_combine: file has %d arrays, program wants "
                         "%d outputs" % (len(arrays), len(names)))
    for i, name in enumerate(names):
        ctx.set(name, jnp.asarray(arrays[i]))


@register_op('rnn_memory_helper')
def _rnn_memory_helper(ctx, op):
    """reference rnn_memory_helper_op.cc: identity used by the recurrent
    machinery to materialize a step's memory (gradient = identity)."""
    ctx.out(op, 'Out', ctx.in1(op, 'X'))


@register_op('detection_map')
def _detection_map(ctx, op):
    """reference operators/detection_map_op.cc — single-batch mAP (the
    class_pos_count/true_pos/false_pos accumulation states are served by
    metrics.DetectionMAP, which owns the cross-batch bookkeeping in this
    design; feeding input states here raises). Computed host-side through
    pure_callback on the shared numpy evaluator (it is a metric: no
    gradient, data-dependent control flow)."""
    det = ctx.in1(op, 'DetectRes')          # LoD (M, 6) [label,score,4box]
    label = ctx.in1(op, 'Label')            # LoD (N, 6) or (N, 5)
    if op.input('PosCount') or op.input('TruePos') or op.input('FalsePos'):
        raise NotImplementedError(
            "detection_map input accumulation states: use "
            "metrics.DetectionMAP for cross-batch accumulation")
    det_lod = ctx.in1_lod(op, 'DetectRes')
    lab_lod = ctx.in1_lod(op, 'Label')
    if not (det_lod and lab_lod):
        raise ValueError("detection_map requires LoD DetectRes and Label")
    overlap = float(op.attr('overlap_threshold', 0.5))
    evaluate_difficult = bool(op.attr('evaluate_difficult', True))
    ap_type = str(op.attr('ap_type', 'integral'))
    class_num = int(op.attr('class_num', 0) or 0)
    d_off, l_off = det_lod[-1], lab_lod[-1]

    def compute(det_np, lab_np):
        from ..metrics import DetectionMAP
        det_np = np.asarray(det_np)
        lab_np = np.asarray(lab_np)
        ncls = class_num or int(max(det_np[:, 0].max(initial=0),
                                    lab_np[:, 0].max(initial=0))) + 1
        m = DetectionMAP(class_num=ncls, overlap_threshold=overlap,
                         evaluate_difficult=evaluate_difficult,
                         ap_version=('11point' if ap_type == '11point'
                                     else 'integral'))
        for i in range(len(d_off) - 1):
            det_i = det_np[d_off[i]:d_off[i + 1]]
            lab_i = lab_np[l_off[i]:l_off[i + 1]]
            if lab_i.shape[1] == 6:
                boxes = lab_i[:, 2:6]
                labels = lab_i[:, 0].astype(np.int64)
                difficult = lab_i[:, 1] > 0
            else:
                boxes = lab_i[:, 1:5]
                labels = lab_i[:, 0].astype(np.int64)
                difficult = None
            m.update(det_i, boxes, labels, difficult)
        return np.float32(m.eval())

    if ctx.params.get('host_eager'):
        out = jnp.asarray(compute(np.asarray(det), np.asarray(label)))
    else:
        out = jax.pure_callback(
            compute, jax.ShapeDtypeStruct((), jnp.float32), det, label)
    ctx.out(op, 'MAP', out.reshape(1))
    ctx.out(op, 'AccumPosCount', jnp.zeros((0, 1), jnp.int32))
    ctx.out(op, 'AccumTruePos', jnp.zeros((0, 2), jnp.float32))
    ctx.out(op, 'AccumFalsePos', jnp.zeros((0, 2), jnp.float32))
