"""Fake-quantization ops for QAT (reference operators/fake_quantize_op.{cc,h}
fake_quantize_abs_max / fake_quantize_range_abs_max /
fake_dequantize_max_abs).

TPU-native notes:
- `round` has a zero gradient, so quantization uses a straight-through
  estimator (round_ste: y + stop_grad(round(y) - y)) — exactly the training
  semantics the reference achieves by routing grad ops around the quant ops
  (quantize_transpiler.py _transpile_backward).
- Scales are stop_gradient (the reference computes them outside AD).
- range_abs_max's sliding scale window is functional state: InScale /
  OutScales / Iter are persistable vars updated in the compiled step.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _round_ste(x):
    return x + lax.stop_gradient(jnp.round(x) - x)


@register_op('fake_quantize_abs_max')
def _fake_quantize_abs_max(ctx, op):
    """Out = round(X / max|X| * bin_cnt) (integer-valued float), OutScale =
    max|X| (reference FakeQuantizeAbsMaxKernel)."""
    x = ctx.in1(op, 'X')
    bit_length = op.attr('bit_length', 8)
    bin_cnt = (1 << (bit_length - 1)) - 1
    scale = lax.stop_gradient(jnp.max(jnp.abs(x)))
    scale = jnp.maximum(scale, 1e-8)
    out = _round_ste(x / scale * bin_cnt)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'OutScale', scale.reshape(1))


@register_op('fake_quantize_range_abs_max')
def _fake_quantize_range_abs_max(ctx, op):
    """Sliding-window max-abs scale (reference FindRangeAbsMaxFunctor):
    scales_arr[iter % window] = cur; running max updated incrementally,
    recomputed over the window when the evicted entry was the max."""
    x = ctx.in1(op, 'X')
    in_scale = ctx.in1(op, 'InScale').reshape(())
    it = ctx.in1(op, 'Iter')
    bit_length = op.attr('bit_length', 8)
    window = op.attr('window_size', 10000)
    is_test = op.attr('is_test', False)
    bin_cnt = (1 << (bit_length - 1)) - 1

    if is_test:
        scale = lax.stop_gradient(in_scale)
        out = _round_ste(jnp.clip(x, -scale, scale) / scale * bin_cnt)
        ctx.out(op, 'Out', out)
        ctx.out(op, 'OutScale', scale.reshape(1))
        return

    scales_arr = ctx.in1(op, 'OutScales')
    cur = lax.stop_gradient(jnp.max(jnp.abs(x)))
    # Iter is the 0-based step count (the transpiler increments AFTER this
    # op): slot k holds step k's scale, the window covers steps
    # max(0, it-window+1)..it, i.e. min(it+1, window) live slots
    it0 = (it.reshape(()) if it is not None else jnp.asarray(0)).astype(
        jnp.int32)
    idx = jnp.mod(it0, window)
    removed = scales_arr.reshape(-1)[idx]
    new_arr = scales_arr.reshape(-1).at[idx].set(cur)
    size = jnp.minimum(it0 + 1, window)
    in_window = jnp.arange(new_arr.shape[0]) < size
    window_max = jnp.max(jnp.where(in_window, new_arr, 0.0))
    scale = jnp.where(
        in_scale < cur, cur,
        jnp.where(jnp.abs(removed - in_scale) < 1e-6, window_max, in_scale))
    scale = jnp.maximum(lax.stop_gradient(scale), 1e-8)

    out = _round_ste(jnp.clip(x, -scale, scale) / scale * bin_cnt)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'OutScale', scale.reshape(1))
    ctx.out(op, 'OutScales', new_arr.reshape(scales_arr.shape))


@register_op('fake_dequantize_max_abs')
def _fake_dequantize_max_abs(ctx, op):
    """Out = X * Scale / max_range (reference FakeDequantizeMaxAbsKernel).
    X may be a REAL int8 blob (the weight-only int8 inference path,
    QuantizeTranspiler.convert_to_int8_program): the cast to f32 happens
    here and XLA fuses it into the consuming matmul — int8 storage/HBM
    traffic, fp32 compute. Scale may be a PER-OUTPUT-CHANNEL vector
    (size == X.shape[-1], broadcast along the last axis — the fc/mul
    weight [in, out] layout) instead of a scalar; per-channel scales
    tighten weight-only parity on wide fc's where one outlier column
    used to set every column's step."""
    x = ctx.in1(op, 'X').astype(jnp.float32)
    scale = ctx.in1(op, 'Scale')
    max_range = op.attr('max_range')
    n = int(np.prod(scale.shape)) if getattr(scale, 'shape', None) else 1
    if n > 1:
        if n != x.shape[-1]:
            raise ValueError(
                "fake_dequantize_max_abs: per-channel Scale of size %d "
                "must match X's last dim %d" % (n, x.shape[-1]))
        scale = scale.reshape((1,) * (x.ndim - 1) + (n,))
    else:
        scale = scale.reshape(())
    ctx.out(op, 'Out', x * lax.stop_gradient(scale) / max_range)


@register_op('fake_channel_wise_quantize_abs_max')
def _fake_channel_wise_quantize_abs_max(ctx, op):
    """Per-output-channel (dim 0) abs-max quantization (reference
    fake_channel_wise_quantize_abs_max, used for conv weights)."""
    x = ctx.in1(op, 'X')
    bit_length = op.attr('bit_length', 8)
    bin_cnt = (1 << (bit_length - 1)) - 1
    axes = tuple(range(1, x.ndim))
    scale = lax.stop_gradient(jnp.max(jnp.abs(x), axis=axes))
    scale = jnp.maximum(scale, 1e-8)
    bshape = (-1,) + (1,) * (x.ndim - 1)
    out = _round_ste(x / scale.reshape(bshape) * bin_cnt)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'OutScale', scale)


@register_op('quantize')
def _quantize(ctx, op):
    """reference operators/quantize_op.cc (mkldnn int8 inference path):
    Output = round(Input * Scale) as int8 (is_negative_input=True) or
    uint8."""
    x = ctx.in1(op, 'Input')
    scale = float(op.attr('Scale', 1.0))
    neg = bool(op.attr('is_negative_input', False))
    q = jnp.round(x.astype(jnp.float32) * scale)
    if neg:
        out = jnp.clip(q, -128, 127).astype(jnp.int8)
    else:
        out = jnp.clip(q, 0, 255).astype(jnp.uint8)
    ctx.out(op, 'Output', out)


@register_op('dequantize')
def _dequantize(ctx, op):
    """reference operators/dequantize_op.cc: Output = Input / Scale as
    float32."""
    x = ctx.in1(op, 'Input')
    scale = float(op.attr('Scale', 1.0))
    ctx.out(op, 'Output', x.astype(jnp.float32) / scale)


@register_op('quantized_matmul')
def _quantized_matmul(ctx, op):
    """Real int8 GEMM for the post-training-quantized inference path: int8
    inputs accumulate in int32 on the MXU (preferred_element_type) and the
    product of the two quantization scales rescales back to float — the
    TPU analog of the reference's mkldnn int8 kernels
    (operators/mkldnn/ int8 conv/fc; INT8 MXU throughput is 2x bf16 on
    v5e)."""
    x8 = ctx.in1(op, 'X')                  # int8 [N, K]
    w8 = ctx.in1(op, 'Y')                  # int8 [K, M]
    sx = float(op.attr('scale_x', 1.0))
    # scale_y: scalar (per-tensor) or a per-OUTPUT-CHANNEL list of M
    # scales (contrib/quantize.py per-channel PTQ) — the rescale then
    # broadcasts down the output-channel (last) axis
    sw_attr = op.attr('scale_y', 1.0)
    sw = np.asarray(sw_attr, dtype=np.float32)
    acc = jax.lax.dot_general(
        x8, w8, (((x8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    if sw.ndim:
        sw = sw.reshape((1,) * (acc.ndim - 1) + (-1,))
    ctx.out(op, 'Out', acc.astype(jnp.float32) / (sx * sw))
