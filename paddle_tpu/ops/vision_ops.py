"""Vision ops: RoI pooling family + spatial sampling.

Reference: operators/roi_pool_op.{cc,h}, roi_align_op.{cc,h},
psroi_pool_op.{cc,h}, grid_sampler_op.cc, affine_grid_op.cc.

TPU-native design: bins with data-dependent extents (roi_pool / psroi_pool)
are evaluated as masked reductions over the full static H x W plane — a
dense, MXU/VPU-friendly formulation with no dynamic slicing; roi_align's
sample grid is static once sampling_ratio > 0 and lowers to batched bilinear
gathers. All are differentiable through JAX AD (gather <-> scatter-add
transposition reproduces the reference's hand-written grad kernels).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _roi_batch_ids(lod, rois_num):
    """Static batch id per RoI from the LoD (reference roi_pool_op.h
    'calculate batch id index for each roi according to LoD')."""
    if not lod:
        return np.zeros((rois_num,), np.int32), 1
    offsets = lod[-1]
    ids = np.zeros((rois_num,), np.int32)
    for i in range(len(offsets) - 1):
        ids[offsets[i]:offsets[i + 1]] = i
    return ids, len(offsets) - 1


@register_op('roi_pool')
def _roi_pool(ctx, op):
    """reference operators/roi_pool_op.h: max pool over adaptive bins.
    Bin extents are data dependent -> masked max over the full plane."""
    x = ctx.in1(op, 'X')
    rois = ctx.in1(op, 'ROIs')
    lod = ctx.in1_lod(op, 'ROIs')
    ph = op.attr('pooled_height')
    pw = op.attr('pooled_width')
    scale = op.attr('spatial_scale', 1.0)

    n, c, h, w = x.shape
    r = rois.shape[0]
    batch_ids, _ = _roi_batch_ids(lod, r)

    def one_roi(roi, feat):
        # integer roi extents (reference: round then +1, min size 1)
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bsh = rh / ph
        bsw = rw / pw
        pi = jnp.arange(ph, dtype=jnp.float32)
        pj = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(pi * bsh) + y1, 0, h)      # [ph]
        hend = jnp.clip(jnp.ceil((pi + 1) * bsh) + y1, 0, h)
        wstart = jnp.clip(jnp.floor(pj * bsw) + x1, 0, w)
        wend = jnp.clip(jnp.ceil((pj + 1) * bsw) + x1, 0, w)
        hh = jnp.arange(h, dtype=jnp.float32)
        ww = jnp.arange(w, dtype=jnp.float32)
        hmask = (hh[None, :] >= hstart[:, None]) & \
                (hh[None, :] < hend[:, None])                   # [ph, h]
        wmask = (ww[None, :] >= wstart[:, None]) & \
                (ww[None, :] < wend[:, None])                   # [pw, w]
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]
        # [ph, pw, h, w]; bins with empty extent -> all-False -> output 0
        neg = jnp.asarray(-jnp.inf, x.dtype)
        masked = jnp.where(mask[None], feat[:, None, None, :, :], neg)
        out = jnp.max(masked, axis=(3, 4))                       # [c, ph, pw]
        # true flat argmax into the h*w plane (reference roi_pool_op.h
        # argmax semantics: -1 for empty bins)
        flat = masked.reshape(masked.shape[:3] + (-1,))
        am = jnp.argmax(flat, axis=3).astype(jnp.int32)
        am = jnp.where(jnp.isfinite(out), am, -1)
        return jnp.where(jnp.isfinite(out), out, 0.0), am

    feats = x[jnp.asarray(batch_ids)]          # [R, c, h, w]
    out, argmax = jax.vmap(one_roi)(rois, feats)
    ctx.out(op, 'Out', out)
    argm = op.output('Argmax')
    if argm:
        ctx.set(argm[0], argmax)
    ctx.set_lod(op.output('Out')[0], ())


@register_op('roi_align')
def _roi_align(ctx, op):
    """reference operators/roi_align_op.h: average of bilinear samples on a
    fixed sub-grid per bin. sampling_ratio must be > 0 on TPU (the reference
    falls back to ceil(roi_size/pooled) which is data dependent -> dynamic
    shape)."""
    x = ctx.in1(op, 'X')
    rois = ctx.in1(op, 'ROIs')
    lod = ctx.in1_lod(op, 'ROIs')
    ph = op.attr('pooled_height')
    pw = op.attr('pooled_width')
    scale = op.attr('spatial_scale', 1.0)
    sampling_ratio = op.attr('sampling_ratio', -1)
    if sampling_ratio <= 0:
        raise ValueError(
            "roi_align on TPU needs sampling_ratio > 0 (a static sample "
            "grid); the reference's adaptive ceil(roi/pooled) grid is data "
            "dependent and cannot be compiled to static shapes")
    s = int(sampling_ratio)

    n, c, h, w = x.shape
    r = rois.shape[0]
    batch_ids, _ = _roi_batch_ids(lod, r)

    def bilinear(feat, y, xq):
        """feat [c,h,w]; y/xq scalars; reference bilinear_interpolate with
        zero outside [-1, dim] and edge clamping."""
        oob = (y < -1.0) | (y > h) | (xq < -1.0) | (xq > w)
        y = jnp.clip(y, 0.0, None)
        xq = jnp.clip(xq, 0.0, None)
        y0 = jnp.clip(jnp.floor(y), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xq), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        yy = jnp.where(y0 >= h - 1, jnp.asarray(h - 1, y.dtype), y)
        xx = jnp.where(x0 >= w - 1, jnp.asarray(w - 1, xq.dtype), xq)
        ly, lx = yy - y0, xx - x0
        hy, hx = 1.0 - ly, 1.0 - lx
        y0i, x0i, y1i, x1i = (y0.astype(jnp.int32), x0.astype(jnp.int32),
                              y1.astype(jnp.int32), x1.astype(jnp.int32))
        v = (feat[:, y0i, x0i] * hy * hx + feat[:, y0i, x1i] * hy * lx +
             feat[:, y1i, x0i] * ly * hx + feat[:, y1i, x1i] * ly * lx)
        return jnp.where(oob, 0.0, v)

    def one_roi(roi, feat):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bsh = rh / ph
        bsw = rw / pw
        pi = jnp.arange(ph, dtype=jnp.float32)[:, None]         # [ph,1]
        pj = jnp.arange(pw, dtype=jnp.float32)[:, None]
        iy = jnp.arange(s, dtype=jnp.float32)[None, :]          # [1,s]
        ys = y1 + pi * bsh + (iy + 0.5) * bsh / s               # [ph,s]
        xs = x1 + pj * bsw + (iy + 0.5) * bsw / s               # [pw,s]
        # all sample points [ph,s,pw,s]
        yy = ys[:, :, None, None]
        xx = xs[None, None, :, :]
        samp = jax.vmap(jax.vmap(jax.vmap(jax.vmap(
            lambda a, b: bilinear(feat, a, b)))))(
                jnp.broadcast_to(yy, (ph, s, pw, s)),
                jnp.broadcast_to(xx, (ph, s, pw, s)))
        # samp [ph,s,pw,s,c] -> avg over sample grid
        return jnp.mean(samp, axis=(1, 3)).transpose(2, 0, 1)   # [c,ph,pw]

    feats = x[jnp.asarray(batch_ids)]
    out = jax.vmap(one_roi)(rois, feats)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], ())


@register_op('psroi_pool')
def _psroi_pool(ctx, op):
    """reference operators/psroi_pool_op.h: position-sensitive RoI average
    pooling — output channel c's bin (ph, pw) averages input channel
    (c * pooled_h + ph) * pooled_w + pw over the bin extent."""
    x = ctx.in1(op, 'X')
    rois = ctx.in1(op, 'ROIs')
    lod = ctx.in1_lod(op, 'ROIs')
    ph = op.attr('pooled_height')
    pw = op.attr('pooled_width')
    oc = op.attr('output_channels')
    scale = op.attr('spatial_scale', 1.0)

    n, c, h, w = x.shape
    if c != oc * ph * pw:
        raise ValueError(
            "psroi_pool: input channels (%d) must equal output_channels * "
            "pooled_height * pooled_width (%d)" % (c, oc * ph * pw))
    r = rois.shape[0]
    batch_ids, _ = _roi_batch_ids(lod, r)

    def one_roi(roi, feat):
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = (jnp.round(roi[2]) + 1.0) * scale
        y2 = (jnp.round(roi[3]) + 1.0) * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bsh = rh / ph
        bsw = rw / pw
        pi = jnp.arange(ph, dtype=jnp.float32)
        pj = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(pi * bsh + y1), 0, h)
        hend = jnp.clip(jnp.ceil((pi + 1) * bsh + y1), 0, h)
        wstart = jnp.clip(jnp.floor(pj * bsw + x1), 0, w)
        wend = jnp.clip(jnp.ceil((pj + 1) * bsw + x1), 0, w)
        hh = jnp.arange(h, dtype=jnp.float32)
        ww = jnp.arange(w, dtype=jnp.float32)
        hmask = (hh[None, :] >= hstart[:, None]) & \
                (hh[None, :] < hend[:, None])
        wmask = (ww[None, :] >= wstart[:, None]) & \
                (ww[None, :] < wend[:, None])
        mask = (hmask[:, None, :, None] & wmask[None, :, None, :]
                ).astype(x.dtype)                     # [ph, pw, h, w]
        fmap = feat.reshape(oc, ph, pw, h, w)
        sums = jnp.einsum('cpqhw,pqhw->cpq', fmap, mask)
        counts = jnp.sum(mask, axis=(2, 3))           # [ph, pw]
        return jnp.where(counts[None] > 0, sums / jnp.maximum(counts, 1.0),
                         0.0)

    feats = x[jnp.asarray(batch_ids)]
    out = jax.vmap(one_roi)(rois, feats)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], ())


@register_op('affine_grid', static_inputs=('OutputShape',))
def _affine_grid(ctx, op):
    """reference operators/affine_grid_op.cc: Theta [N,2,3] -> sampling grid
    [N, H, W, 2] over normalized coords linspace(-1, 1, dim)."""
    theta = ctx.in1(op, 'Theta')
    shape_attr = op.attr('output_shape', [])
    if shape_attr:
        n, c, h, w = [int(v) for v in shape_attr]
    else:
        out_shape = ctx.in1_static(op, 'OutputShape')
        n, c, h, w = [int(v) for v in np.asarray(out_shape).reshape(-1)]
    xs = jnp.linspace(-1.0, 1.0, w)
    ys = jnp.linspace(-1.0, 1.0, h)
    xg, yg = jnp.meshgrid(xs, ys)                     # [h, w]
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], -1)              # [h, w, 3]
    out = jnp.einsum('hwk,njk->nhwj', base, theta)    # [n, h, w, 2]
    ctx.out(op, 'Output', out)


@register_op('grid_sampler')
def _grid_sampler(ctx, op):
    """reference operators/grid_sampler_op.cc: bilinear sampling of X
    [N,C,H,W] at Grid [N,H,W,2] coords in [-1,1] (zero padding outside)."""
    x = ctx.in1(op, 'X')
    grid = ctx.in1(op, 'Grid')
    n, c, h, w = x.shape

    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0         # [n, gh, gw]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1 = x0 + 1
    y1 = y0 + 1

    def gather(feat, yy, xx):
        """feat [c,h,w]; indices may be out of range -> contribute 0."""
        inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        v = feat[:, yc, xc]                            # [c, gh, gw]
        return jnp.where(inb[None], v, 0.0)

    wa = (x1 - gx) * (y1 - gy)
    wb = (x1 - gx) * (gy - y0)
    wc = (gx - x0) * (y1 - gy)
    wd = (gx - x0) * (gy - y0)

    def one(feat, x0i, y0i, x1i, y1i, wa_, wb_, wc_, wd_):
        va = gather(feat, y0i, x0i)
        vb = gather(feat, y1i, x0i)
        vc = gather(feat, y0i, x1i)
        vd = gather(feat, y1i, x1i)
        return va * wa_[None] + vb * wb_[None] + vc * wc_[None] + \
            vd * wd_[None]

    out = jax.vmap(one)(x, x0, y0, x1, y1, wa, wb, wc, wd)
    ctx.out(op, 'Output', out)
