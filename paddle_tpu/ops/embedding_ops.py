"""Fused embedding gather(+bias) — the sparse-path kernel tier.

The reference serves embedding lookups through lookup_table_op.cc (dense
gather) and the distributed prefetch pipeline; here the gather itself
becomes a Pallas kernel when the tier allows: ids are SCALAR-PREFETCHED
(pltpu.PrefetchScalarGridSpec) so each grid step's BlockSpec index_map
picks the table row to DMA — the classic Pallas embedding idiom: row
fetches pipeline back-to-back without materializing an index tensor on
the vector unit, and the optional per-feature bias adds inside the same
kernel (one HBM pass instead of gather-then-add).

Gradients: the dense path carries a custom_vjp whose backward is the
scatter-add transpose (XLA's native scatter — already a single fused HLO,
which is why there is no Pallas scatter tier; the fallback rule is
documented in docs/executor_performance.md). The SPARSE path
(is_sparse=True embeddings) never differentiates through the gather at
all: core/lowering.py's scout/dummy mechanism holds the table out of AD,
so the kernel simply gathers stop_gradient rows — composing with
SelectedRows grads unchanged.

Used by the lookup_table lowering (tensor_ops) and the program-level
``fused_embedding_gather`` op registered here (W, Ids, optional Bias).
"""
import functools

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def pallas_shapes_ok(w, n_ids):
    """Kernel tiling rule: features must fill whole lanes (the row DMA is
    [1, D]); any id count works (grid is per-id)."""
    return w.ndim == 2 and w.shape[1] % 128 == 0 and n_ids >= 1 and \
        w.dtype == jnp.float32


def spmd_gather_ok(mesh, w, n_ids, w_spec=None):
    """Mesh-partitioning rule for the gather kernel: ids partition over
    'data' (kernel per shard via kernel_tier.partitioned_call, table
    replicated into each shard) — so the TABLE itself must be replicated.
    A sharded table (`w_spec` names a mesh axis, or the is_distributed
    vocab-sharded pin) keeps the XLA gather, which the SPMD partitioner
    turns into shard-local masked gathers + psum; an explicitly
    replicated spec (P() or P(None, ...)) stays eligible."""
    if w_spec is not None and any(e is not None for e in tuple(w_spec)):
        return False
    from .kernel_tier import mesh_axis
    data_ax = mesh_axis(mesh, 'data', n_ids)
    n_loc = n_ids // mesh.shape[data_ax] if data_ax else n_ids
    return pallas_shapes_ok(w, n_loc)


def _gather_kernel(has_bias, *refs):
    if has_bias:
        ids_ref, row_ref, bias_ref, out_ref = refs
        out_ref[...] = row_ref[...] + bias_ref[...]
    else:
        ids_ref, row_ref, out_ref = refs
        out_ref[...] = row_ref[...]


def _gather_pallas(w, flat_ids, bias, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = flat_ids.shape[0]
    d = w.shape[1]
    has_bias = bias is not None
    # clamp like jnp.take's default TPU behavior (out-of-range ids clamp)
    ids32 = jnp.clip(flat_ids.astype(jnp.int32), 0, w.shape[0] - 1)
    in_specs = [pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0))]
    ins = [w]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, d), lambda i, ids: (0, 0)))
        ins.append(bias.reshape(1, d))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, d), lambda i, ids: (i, 0)),
    )
    return pl.pallas_call(
        functools.partial(_gather_kernel, has_bias),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, d), w.dtype),
        interpret=interpret,
    )(ids32, *ins)


def _gather_ref(w, flat_ids, bias):
    out = jnp.take(w, flat_ids, axis=0)
    return out if bias is None else out + bias.reshape(1, -1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gather_grad(w, flat_ids, bias, impl, w_shape, w_dtype_str):
    return _gather_impl(w, flat_ids, bias, impl)


def _gather_impl(w, flat_ids, bias, impl):
    if impl in ('pallas', 'interpret'):
        return _gather_pallas(w, flat_ids, bias, impl == 'interpret')
    return _gather_ref(w, flat_ids, bias)


def _gather_grad_fwd(w, flat_ids, bias, impl, w_shape, w_dtype_str):
    return _gather_impl(w, flat_ids, bias, impl), \
        (flat_ids, bias is not None)


def _gather_grad_bwd(impl, w_shape, w_dtype_str, res, ct):
    flat_ids, has_bias = res
    dw = jnp.zeros(w_shape, w_dtype_str).at[flat_ids].add(
        ct.astype(w_dtype_str), mode='drop')
    db = jnp.sum(ct, axis=0) if has_bias else None
    return dw, None, db


_gather_grad.defvjp(_gather_grad_fwd, _gather_grad_bwd)


def _gather_dispatch(w, flat_ids, bias, impl, differentiable):
    if differentiable:
        return _gather_grad(w, flat_ids, bias, impl,
                            tuple(w.shape), str(w.dtype))
    return _gather_pallas(w, flat_ids, bias, impl == 'interpret')


def embedding_gather(w, flat_ids, bias=None, impl='off', differentiable=True):
    """Rows of ``w`` at ``flat_ids`` (+ optional per-feature ``bias``).

    impl: 'off'/'xla' -> plain jnp gather (+add) with jnp's own AD (the
    transpose IS XLA's scatter-add — bitwise today's path);
    'pallas'/'interpret' -> the scalar-prefetch kernel, wrapped in a
    custom_vjp whose backward is the same scatter-add transpose.
    ``differentiable=False`` skips the vjp wrapper (the sparse scout/apply
    path holds w out of AD already).

    Under an active >1-device mesh the kernel runs PER SHARD via
    kernel_tier.partitioned_call: ids partition over 'data', the table
    rides replicated into every shard (dispatch only picks pallas here
    when the table IS replicated — spmd_gather_ok), and the dense
    backward's scatter-add cotangent psums across the data axis through
    shard_map's transpose. The sparse path's replicated-rows pin
    (core/lowering.py) is untouched — it operates on the optimizer-side
    SelectedRows scatter, not this gather."""
    flat_ids = flat_ids.astype(jnp.int32)
    if impl in ('pallas', 'interpret'):
        from ..parallel.api import get_active_mesh
        mesh = get_active_mesh()
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P
            from .kernel_tier import partitioned_call, mesh_axis
            data_ax = mesh_axis(mesh, 'data', flat_ids.shape[0])
            has_bias = bias is not None

            def inner(wl, il, *mb):
                return _gather_dispatch(wl, il, mb[0] if mb else None,
                                        impl, differentiable)

            in_specs = [P(), P(data_ax)] + ([P()] if has_bias else [])
            args = [w, flat_ids] + ([bias] if has_bias else [])
            return partitioned_call(inner, mesh, tuple(in_specs),
                                    P(data_ax, None))(*args)
        return _gather_dispatch(w, flat_ids, bias, impl, differentiable)
    return _gather_ref(w, flat_ids, bias)


@register_op('fused_embedding_gather')
def _fused_embedding_gather(ctx, op):
    """Program-level fused gather+bias: inputs W [V, D], Ids (any shape,
    trailing 1 folds like lookup_table), optional Bias [D]; output
    Out [..., D]. Rides the same sparse scout/apply mechanism as
    lookup_table when W is an is_sparse wrt table."""
    from . import kernel_tier
    from .tensor_ops import embedding_epilogue, lookup_gather
    from ..parallel.api import get_active_mesh, get_active_param_spec
    w = ctx.in1(op, 'W')
    ids = ctx.in1(op, 'Ids')
    bias = ctx.in1(op, 'Bias')
    flat = ids.reshape(-1).astype(jnp.int32)
    mesh = get_active_mesh()
    if mesh is not None and mesh.size > 1:
        # mesh-native: ids partition over 'data' via partitioned_call
        # (embedding_gather routes through shard_map); a SHARDED table
        # falls back to the XLA gather the partitioner can split
        spec_fn = get_active_param_spec()
        w_spec = spec_fn(op.input('W')[0]) if spec_fn else None
        ok = spmd_gather_ok(mesh, w, int(flat.shape[0]), w_spec)
    else:
        ok = pallas_shapes_ok(w, int(flat.shape[0]))
    impl = kernel_tier.dispatch(
        'fused_embedding_gather', pallas_ok=ok, mesh=mesh,
        count=getattr(ctx, 'sparse_mode', None) != 'scout')
    out = lookup_gather(ctx, op, w, flat, bias=bias, impl=impl)
    ctx.out(op, 'Out', embedding_epilogue(
        out, flat, ids, w, op.attr('padding_idx', -1)))
