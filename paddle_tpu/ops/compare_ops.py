"""Compare / logical ops (reference operators/controlflow/compare_op.cc,
logical_op.cc)."""
import jax.numpy as jnp

from ..core.registry import register_op
from .common import broadcast_y_to


def _register_cmp(name, fn):
    @register_op(name)
    def _lower(ctx, op, _fn=fn):
        x = ctx.in1(op, 'X')
        y = ctx.in1(op, 'Y')
        y = broadcast_y_to(x, y, op.attr('axis', -1))
        ctx.out(op, 'Out', _fn(x, y))


_register_cmp('equal', lambda x, y: x == y)
_register_cmp('not_equal', lambda x, y: x != y)
_register_cmp('less_than', lambda x, y: x < y)
_register_cmp('less_equal', lambda x, y: x <= y)
_register_cmp('greater_than', lambda x, y: x > y)
_register_cmp('greater_equal', lambda x, y: x >= y)
_register_cmp('logical_and', jnp.logical_and)
_register_cmp('logical_or', jnp.logical_or)
_register_cmp('logical_xor', jnp.logical_xor)


@register_op('logical_not')
def _logical_not(ctx, op):
    ctx.out(op, 'Out', jnp.logical_not(ctx.in1(op, 'X')))


@register_op('isfinite')
def _isfinite(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.all(jnp.isfinite(x)).reshape(1))
