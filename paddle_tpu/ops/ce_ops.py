"""Fused softmax-cross-entropy over logits: the V=32000 lm_head tail as one
blocked kernel (softmax + label gather + NLL in a single pass).

Motivation (BENCH r03-r05 + PADDLE_PROFILE_OPS attribution): the lm_*
rows' flat MFU sits in the loss tail — ``softmax_with_cross_entropy`` over
``[B*L, 32000]`` logits. The unfused lowering materializes a full
probability/one-hot intermediate on the backward pass; this kernel streams
vocab blocks through VMEM keeping only per-row running max / running
denominator / picked-logit scratch (FlashAttention's online-softmax trick
applied to the loss), and the backward recomputes the probability TILE
from (logits, LSE) — O(N) residuals, no ``[N, V]`` one-hot ever exists.

Tiers (ops/kernel_tier.py):
- off:       nn_ops._ce_hard (bit-identical legacy path);
- xla:       one-hot-free jnp emission (scatter-subtract backward), XLA
             fuses the forward reduction chain;
- pallas:    the blocked kernels below;
- interpret: the same kernels through the Pallas interpreter (CPU tests).

Both fused tiers keep the ``ignore_index`` contract: ignored rows emit 0
loss and 0 gradient.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pick_block(n, pref, mult):
    """Largest power-of-two tile <= pref that divides n and is a multiple
    of mult; None when no such tile exists (caller falls back a tier)."""
    b = pref
    while b >= mult:
        if n % b == 0:
            return b
        b //= 2
    return None


def pallas_shapes_ok(n, v):
    """Can the kernels tile [n, v] logits? (the per-op fallback rule)"""
    return _pick_block(n, 256, 128) is not None and \
        _pick_block(v, 2048, 128) is not None


# --------------------------------------------------------------------------
# forward kernel: loss + lse in one sweep over vocab blocks
# --------------------------------------------------------------------------

def _fwd_kernel(nj, ignore_index, *refs):
    import jax.experimental.pallas as pl
    (x_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr, pick_scr) = refs
    j = pl.program_id(1)
    bn, bv = x_ref.shape

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        pick_scr[...] = jnp.zeros(pick_scr.shape, jnp.float32)

    s = x_ref[...].astype(jnp.float32)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_scr[...] = jnp.broadcast_to(
        l_scr[:, :1] * jnp.exp(m_prev - m_new)
        + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True), l_scr.shape)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    cols = j * bv + lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lab = lab_ref[0]                                   # [bn] int32
    hit = cols == lab[:, None]
    # each row's label lands in exactly one vocab block, so += accumulates
    # one real value (ignore_index never matches: it is outside [0, V))
    pick_scr[...] += jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        pick_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        lse = m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
        lse_ref[0] = lse
        loss = lse - pick_scr[:, 0]
        loss_ref[0] = jnp.where(lab_ref[0] != ignore_index, loss, 0.0)


def _fused_ce_fwd_pallas(logits, labels, ignore_index, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    n, v = logits.shape
    bn = _pick_block(n, 256, 128)
    bv = _pick_block(v, 2048, 128)
    nj = v // bv
    lab2 = labels.astype(jnp.int32)[None, :]
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nj, int(ignore_index)),
        grid=(n // bn, nj),
        in_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_specs=[pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                   pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32),
                        pltpu.VMEM((bn, 128), jnp.float32),
                        pltpu.VMEM((bn, 128), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, lab2)
    return loss[0], lse[0]


# --------------------------------------------------------------------------
# backward kernel: dlogits tile recomputed from (logits, lse) — no
# [N, V] softmax/one-hot residual
# --------------------------------------------------------------------------

def _bwd_kernel(ignore_index, x_ref, lab_ref, lse_ref, ct_ref, dx_ref):
    import jax.experimental.pallas as pl
    j = pl.program_id(1)
    bn, bv = x_ref.shape
    s = x_ref[...].astype(jnp.float32)
    lab = lab_ref[0]
    ct = jnp.where(lab != ignore_index, ct_ref[0], 0.0)    # [bn]
    p = jnp.exp(s - lse_ref[0][:, None])
    cols = j * bv + lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = cols == lab[:, None]
    dx_ref[...] = ((p - jnp.where(hit, 1.0, 0.0))
                   * ct[:, None]).astype(dx_ref.dtype)


def _fused_ce_bwd_pallas(logits, labels, lse, ct, ignore_index, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    n, v = logits.shape
    bn = _pick_block(n, 256, 128)
    bv = _pick_block(v, 2048, 128)
    lab2 = labels.astype(jnp.int32)[None, :]
    return pl.pallas_call(
        functools.partial(_bwd_kernel, int(ignore_index)),
        grid=(n // bn, v // bv),
        in_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j))],
        out_shape=[jax.ShapeDtypeStruct((n, v), logits.dtype)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, lab2, lse[None, :], ct.astype(jnp.float32)[None, :])[0]


# --------------------------------------------------------------------------
# xla tier: one-hot-free jnp emission
# --------------------------------------------------------------------------

def _ce_fwd_xla(logits, labels, ignore_index):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = (m[:, 0] + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1)))
    safe = jnp.clip(labels, 0, x.shape[-1] - 1)
    picked = jnp.take_along_axis(x, safe[:, None], axis=-1)[:, 0]
    loss = jnp.where(labels != ignore_index, lse - picked, 0.0)
    return loss, lse


def _ce_bwd_xla(logits, labels, lse, ct, ignore_index):
    x = logits.astype(jnp.float32)
    ct_eff = jnp.where(labels != ignore_index, ct, 0.0)
    g = jnp.exp(x - lse[:, None]) * ct_eff[:, None]
    safe = jnp.clip(labels, 0, x.shape[-1] - 1)
    # scatter-subtract at the label column instead of building a [N, V]
    # one-hot (the memory the fused tier exists to avoid)
    g = g.at[jnp.arange(g.shape[0]), safe].add(-ct_eff)
    return g.astype(logits.dtype)


# --------------------------------------------------------------------------
# custom_vjp wrapper
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_ce(logits, labels, ignore_index, impl):
    """loss [N] for logits [N, V], int labels [N]. ``impl`` in
    'xla' | 'pallas' | 'interpret' (the 'off' tier never reaches here)."""
    return _fused_fwd(logits, labels, ignore_index, impl)[0]


def _fused_fwd(logits, labels, ignore_index, impl):
    labels = labels.astype(jnp.int32)
    if impl in ('pallas', 'interpret'):
        loss, lse = _fused_ce_fwd_pallas(logits, labels, ignore_index,
                                         impl == 'interpret')
    else:
        loss, lse = _ce_fwd_xla(logits, labels, ignore_index)
    return loss, (logits, labels, lse)


def _fused_ce_bwd(ignore_index, impl, res, ct):
    logits, labels, lse = res
    if impl in ('pallas', 'interpret'):
        g = _fused_ce_bwd_pallas(logits, labels, lse, ct, ignore_index,
                                 impl == 'interpret')
    else:
        g = _ce_bwd_xla(logits, labels, lse, ct, ignore_index)
    return g, None


fused_softmax_ce.defvjp(_fused_fwd, _fused_ce_bwd)


# --------------------------------------------------------------------------
# SPMD: mesh-partitioned fused CE (ops/kernel_tier.partitioned_call)
#
# Batch rows shard over 'data' (each shard runs the whole kernel on its
# rows — no comms at all); a vocab-sharded 'model' axis runs the kernel on
# partial vocab blocks and combines with an lse-aware all-reduce:
# lse_g = pmax + log(psum(exp(lse_l - pmax))), pick_g = psum(pick_l) — the
# online-softmax merge rule applied across shards instead of vocab blocks.
# --------------------------------------------------------------------------

# kernel-level ignore sentinel for the vocab-sharded partial passes: the
# locally-shifted label is -1 for rows whose label lives on another shard
# (misses every column >= 0), so the kernel's own ignore masking must be a
# no-op — -2 never equals a shifted label
_NO_IGNORE = -2


def spmd_shapes_ok(mesh, n, v):
    """Per-SHARD tiling rule under a mesh: each shard's [n_local, v_local]
    logits block must tile for the kernels (the per-op fallback rule,
    evaluated on the post-partitioning shapes)."""
    from .kernel_tier import mesh_axis
    data_ax = mesh_axis(mesh, 'data', n)
    model_ax = mesh_axis(mesh, 'model', v)
    n_loc = n // mesh.shape[data_ax] if data_ax else n
    v_loc = v // mesh.shape[model_ax] if model_ax else v
    return pallas_shapes_ok(n_loc, v_loc)


def _partial_stats(logits, lab_l, impl):
    """Per-shard (lse_local, pick_local) over a partial vocab block.
    ``lab_l`` is already shifted into the local column space (-1 = label
    lives on another shard -> pick contribution 0)."""
    if impl in ('pallas', 'interpret'):
        loss_l, lse_l = _fused_ce_fwd_pallas(logits, lab_l, _NO_IGNORE,
                                             impl == 'interpret')
        # the kernel emits loss = lse - pick (ignore masking defused via
        # the sentinel), so the picked logit inverts exactly
        return lse_l, lse_l - loss_l
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse_l = m[:, 0] + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1))
    safe = jnp.clip(lab_l, 0, x.shape[-1] - 1)
    picked = jnp.take_along_axis(x, safe[:, None], axis=-1)[:, 0]
    return lse_l, jnp.where(lab_l >= 0, picked, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _sharded_vocab_ce(logits, labels, ignore_index, impl, vocab_axis):
    """Per-shard body under shard_map when the VOCAB axis is sharded:
    logits [n_loc, v_loc] local block, labels [n_loc] GLOBAL ids.
    Returns this shard's PARTIAL loss (partials psum to the true loss):
    an output the transpose treats as genuinely sharded — claiming a
    replicated [n] loss instead makes shard_map's reverse rule average
    the cotangent over the vocab axis (measured ct/axis_size on jax
    0.4.37 with replication checking off), silently halving dlogits."""
    return _sharded_vocab_ce_fwd(logits, labels, ignore_index, impl,
                                 vocab_axis)[0]


def _shift_labels(labels, vloc, vocab_axis):
    off = lax.axis_index(vocab_axis).astype(jnp.int32) * vloc
    shifted = labels - off
    in_rng = (shifted >= 0) & (shifted < vloc)
    return jnp.where(in_rng, shifted, -1)


def _sharded_vocab_ce_fwd(logits, labels, ignore_index, impl, vocab_axis):
    labels = labels.astype(jnp.int32)
    lab_l = _shift_labels(labels, logits.shape[1], vocab_axis)
    lse_l, pick_l = _partial_stats(logits, lab_l, impl)
    mx = lax.pmax(lse_l, vocab_axis)
    lse_g = mx + jnp.log(lax.psum(jnp.exp(lse_l - mx), vocab_axis))
    # decompose loss = lse_g - pick_g into per-shard partials that sum
    # exactly once across the axis: share_i = exp(lse_l - lse_g) is this
    # shard's softmax mass (psums to 1), pick lives on one shard only
    partial = jnp.exp(lse_l - lse_g) * lse_g - pick_l
    partial = jnp.where(labels != ignore_index, partial, 0.0)
    # residuals: O(N) lse_g instead of any [n, v] intermediate; the
    # backward is comms-free (each shard owns its dlogits block)
    return partial, (logits, labels, lab_l, lse_g)


def _sharded_vocab_ce_bwd(ignore_index, impl, vocab_axis, res, ct):
    logits, labels, lab_l, lse_g = res
    ct_eff = jnp.where(labels != ignore_index, ct, 0.0).astype(jnp.float32)
    if impl in ('pallas', 'interpret'):
        g = _fused_ce_bwd_pallas(logits, lab_l, lse_g, ct_eff, _NO_IGNORE,
                                 impl == 'interpret')
    else:
        x = logits.astype(jnp.float32)
        gmat = jnp.exp(x - lse_g[:, None]) * ct_eff[:, None]
        safe = jnp.clip(lab_l, 0, x.shape[-1] - 1)
        gmat = gmat.at[jnp.arange(x.shape[0]), safe].add(
            -jnp.where(lab_l >= 0, ct_eff, 0.0))
        g = gmat.astype(logits.dtype)
    return g, None


_sharded_vocab_ce.defvjp(_sharded_vocab_ce_fwd, _sharded_vocab_ce_bwd)


def fused_softmax_ce_spmd(logits, labels, mesh, ignore_index, impl):
    """Mesh-partitioned fused CE: loss [N] for logits [N, V] under an
    active mesh. Rows shard over 'data', vocab over 'model' (each only
    when present, >1 and dividing); kernel per shard via
    kernel_tier.partitioned_call. Batch-only sharding is comms-free;
    a sharded vocab axis pays one pmax + two psums of [n_loc] vectors."""
    from jax.sharding import PartitionSpec as P
    from .kernel_tier import partitioned_call, mesh_axis
    n, v = logits.shape
    data_ax = mesh_axis(mesh, 'data', n)
    model_ax = mesh_axis(mesh, 'model', v)
    lab = labels.astype(jnp.int32)
    if model_ax is None:
        def inner(xl, ll):
            return fused_softmax_ce(xl, ll, ignore_index, impl)
        return partitioned_call(inner, mesh,
                                (P(data_ax, None), P(data_ax)),
                                P(data_ax))(logits, lab)

    # each vocab shard emits a [1, n_loc] PARTIAL row (see
    # _sharded_vocab_ce: a replicated-loss claim mis-transposes); the
    # stacked [msize, n] partials sum to the loss outside the shard_map
    def inner_sharded(xl, ll):
        return _sharded_vocab_ce(xl, ll, ignore_index, impl,
                                 model_ax)[None, :]
    parts = partitioned_call(inner_sharded, mesh,
                             (P(data_ax, model_ax), P(data_ax)),
                             P(model_ax, data_ax))(logits, lab)
    return jnp.sum(parts, axis=0)
