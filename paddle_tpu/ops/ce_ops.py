"""Fused softmax-cross-entropy over logits: the V=32000 lm_head tail as one
blocked kernel (softmax + label gather + NLL in a single pass).

Motivation (BENCH r03-r05 + PADDLE_PROFILE_OPS attribution): the lm_*
rows' flat MFU sits in the loss tail — ``softmax_with_cross_entropy`` over
``[B*L, 32000]`` logits. The unfused lowering materializes a full
probability/one-hot intermediate on the backward pass; this kernel streams
vocab blocks through VMEM keeping only per-row running max / running
denominator / picked-logit scratch (FlashAttention's online-softmax trick
applied to the loss), and the backward recomputes the probability TILE
from (logits, LSE) — O(N) residuals, no ``[N, V]`` one-hot ever exists.

Tiers (ops/kernel_tier.py):
- off:       nn_ops._ce_hard (bit-identical legacy path);
- xla:       one-hot-free jnp emission (scatter-subtract backward), XLA
             fuses the forward reduction chain;
- pallas:    the blocked kernels below;
- interpret: the same kernels through the Pallas interpreter (CPU tests).

Both fused tiers keep the ``ignore_index`` contract: ignored rows emit 0
loss and 0 gradient.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pick_block(n, pref, mult):
    """Largest power-of-two tile <= pref that divides n and is a multiple
    of mult; None when no such tile exists (caller falls back a tier)."""
    b = pref
    while b >= mult:
        if n % b == 0:
            return b
        b //= 2
    return None


def pallas_shapes_ok(n, v):
    """Can the kernels tile [n, v] logits? (the per-op fallback rule)"""
    return _pick_block(n, 256, 128) is not None and \
        _pick_block(v, 2048, 128) is not None


# --------------------------------------------------------------------------
# forward kernel: loss + lse in one sweep over vocab blocks
# --------------------------------------------------------------------------

def _fwd_kernel(nj, ignore_index, *refs):
    import jax.experimental.pallas as pl
    (x_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr, pick_scr) = refs
    j = pl.program_id(1)
    bn, bv = x_ref.shape

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        pick_scr[...] = jnp.zeros(pick_scr.shape, jnp.float32)

    s = x_ref[...].astype(jnp.float32)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_scr[...] = jnp.broadcast_to(
        l_scr[:, :1] * jnp.exp(m_prev - m_new)
        + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True), l_scr.shape)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    cols = j * bv + lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lab = lab_ref[0]                                   # [bn] int32
    hit = cols == lab[:, None]
    # each row's label lands in exactly one vocab block, so += accumulates
    # one real value (ignore_index never matches: it is outside [0, V))
    pick_scr[...] += jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        pick_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        lse = m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
        lse_ref[0] = lse
        loss = lse - pick_scr[:, 0]
        loss_ref[0] = jnp.where(lab_ref[0] != ignore_index, loss, 0.0)


def _fused_ce_fwd_pallas(logits, labels, ignore_index, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    n, v = logits.shape
    bn = _pick_block(n, 256, 128)
    bv = _pick_block(v, 2048, 128)
    nj = v // bv
    lab2 = labels.astype(jnp.int32)[None, :]
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nj, int(ignore_index)),
        grid=(n // bn, nj),
        in_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_specs=[pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                   pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32),
                        pltpu.VMEM((bn, 128), jnp.float32),
                        pltpu.VMEM((bn, 128), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, lab2)
    return loss[0], lse[0]


# --------------------------------------------------------------------------
# backward kernel: dlogits tile recomputed from (logits, lse) — no
# [N, V] softmax/one-hot residual
# --------------------------------------------------------------------------

def _bwd_kernel(ignore_index, x_ref, lab_ref, lse_ref, ct_ref, dx_ref):
    import jax.experimental.pallas as pl
    j = pl.program_id(1)
    bn, bv = x_ref.shape
    s = x_ref[...].astype(jnp.float32)
    lab = lab_ref[0]
    ct = jnp.where(lab != ignore_index, ct_ref[0], 0.0)    # [bn]
    p = jnp.exp(s - lse_ref[0][:, None])
    cols = j * bv + lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = cols == lab[:, None]
    dx_ref[...] = ((p - jnp.where(hit, 1.0, 0.0))
                   * ct[:, None]).astype(dx_ref.dtype)


def _fused_ce_bwd_pallas(logits, labels, lse, ct, ignore_index, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    n, v = logits.shape
    bn = _pick_block(n, 256, 128)
    bv = _pick_block(v, 2048, 128)
    lab2 = labels.astype(jnp.int32)[None, :]
    return pl.pallas_call(
        functools.partial(_bwd_kernel, int(ignore_index)),
        grid=(n // bn, v // bv),
        in_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, i))],
        out_specs=[pl.BlockSpec((bn, bv), lambda i, j: (i, j))],
        out_shape=[jax.ShapeDtypeStruct((n, v), logits.dtype)],
        compiler_params=_compiler_params(
            pltpu, ("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, lab2, lse[None, :], ct.astype(jnp.float32)[None, :])[0]


# --------------------------------------------------------------------------
# xla tier: one-hot-free jnp emission
# --------------------------------------------------------------------------

def _ce_fwd_xla(logits, labels, ignore_index):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = (m[:, 0] + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1)))
    safe = jnp.clip(labels, 0, x.shape[-1] - 1)
    picked = jnp.take_along_axis(x, safe[:, None], axis=-1)[:, 0]
    loss = jnp.where(labels != ignore_index, lse - picked, 0.0)
    return loss, lse


def _ce_bwd_xla(logits, labels, lse, ct, ignore_index):
    x = logits.astype(jnp.float32)
    ct_eff = jnp.where(labels != ignore_index, ct, 0.0)
    g = jnp.exp(x - lse[:, None]) * ct_eff[:, None]
    safe = jnp.clip(labels, 0, x.shape[-1] - 1)
    # scatter-subtract at the label column instead of building a [N, V]
    # one-hot (the memory the fused tier exists to avoid)
    g = g.at[jnp.arange(g.shape[0]), safe].add(-ct_eff)
    return g.astype(logits.dtype)


# --------------------------------------------------------------------------
# custom_vjp wrapper
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_ce(logits, labels, ignore_index, impl):
    """loss [N] for logits [N, V], int labels [N]. ``impl`` in
    'xla' | 'pallas' | 'interpret' (the 'off' tier never reaches here)."""
    return _fused_fwd(logits, labels, ignore_index, impl)[0]


def _fused_fwd(logits, labels, ignore_index, impl):
    labels = labels.astype(jnp.int32)
    if impl in ('pallas', 'interpret'):
        loss, lse = _fused_ce_fwd_pallas(logits, labels, ignore_index,
                                         impl == 'interpret')
    else:
        loss, lse = _ce_fwd_xla(logits, labels, ignore_index)
    return loss, (logits, labels, lse)


def _fused_ce_bwd(ignore_index, impl, res, ct):
    logits, labels, lse = res
    if impl in ('pallas', 'interpret'):
        g = _fused_ce_bwd_pallas(logits, labels, lse, ct, ignore_index,
                                 impl == 'interpret')
    else:
        g = _ce_bwd_xla(logits, labels, lse, ct, ignore_index)
    return g, None


fused_softmax_ce.defvjp(_fused_fwd, _fused_ce_bwd)
