"""Tensor manipulation ops: reshape/transpose/concat/split/gather/pad/...

Reference: operators/reshape_op.cc (reshape2 carries XShape for grad — not
needed under JAX AD but emitted for program parity), transpose_op.cc,
concat_op.cc, split_op.cc, squeeze/unsqueeze/flatten/stack/unstack/expand/
pad/slice/gather/scatter/lookup_table/top_k/arg_{max,min}/argsort ops.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import np_dtype


def _infer_reshape(x, shape):
    shape = list(shape)
    # fluid semantics: 0 means copy input dim; -1 inferred
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = int(x.size) // max(known, 1)
    return tuple(shape)


@register_op('reshape')
def _reshape(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', x.reshape(_infer_reshape(x, op.attr('shape'))))


@register_op('reshape2')
def _reshape2(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', x.reshape(_infer_reshape(x, op.attr('shape'))))
    if op.output('XShape'):
        ctx.out(op, 'XShape', jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op('transpose', share_lod=False)
def _transpose(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.transpose(x, op.attr('axis')))


@register_op('transpose2', share_lod=False)
def _transpose2(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.transpose(x, op.attr('axis')))
    if op.output('XShape'):
        ctx.out(op, 'XShape', jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op('concat')
def _concat(ctx, op):
    xs = ctx.in_list(op, 'X')
    ctx.out(op, 'Out', jnp.concatenate(xs, axis=op.attr('axis', 0)))


@register_op('split')
def _split(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', 0)
    num = op.attr('num', 0)
    sections = op.attr('sections', [])
    outs = op.output('Out')
    if sections:
        idx = np.cumsum(sections)[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num or len(outs), axis=axis)
    for i, p in enumerate(parts):
        ctx.out(op, 'Out', p, idx=i)


def _register_shape_ops():
    @register_op('squeeze')
    def _squeeze(ctx, op):
        x = ctx.in1(op, 'X')
        axes = op.attr('axes', [])
        if axes:
            out = x.reshape(tuple(s for i, s in enumerate(x.shape)
                                  if not (i in axes and s == 1)))
        else:
            out = jnp.squeeze(x)
        ctx.out(op, 'Out', out)

    @register_op('squeeze2')
    def _squeeze2(ctx, op):
        _squeeze(ctx, op)
        if op.output('XShape'):
            x = ctx.in1(op, 'X')
            ctx.out(op, 'XShape', jnp.zeros((0,) + x.shape, dtype=x.dtype))

    @register_op('unsqueeze')
    def _unsqueeze(ctx, op):
        x = ctx.in1(op, 'X')
        out = x
        for a in sorted(op.attr('axes')):
            out = jnp.expand_dims(out, a)
        ctx.out(op, 'Out', out)

    @register_op('unsqueeze2')
    def _unsqueeze2(ctx, op):
        _unsqueeze(ctx, op)
        if op.output('XShape'):
            x = ctx.in1(op, 'X')
            ctx.out(op, 'XShape', jnp.zeros((0,) + x.shape, dtype=x.dtype))

    @register_op('flatten')
    def _flatten(ctx, op):
        x = ctx.in1(op, 'X')
        axis = op.attr('axis', 1)
        lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
        ctx.out(op, 'Out', x.reshape(lead, -1))

    @register_op('flatten2')
    def _flatten2(ctx, op):
        _flatten(ctx, op)
        if op.output('XShape'):
            x = ctx.in1(op, 'X')
            ctx.out(op, 'XShape', jnp.zeros((0,) + x.shape, dtype=x.dtype))


_register_shape_ops()


@register_op('stack', share_lod=False)
def _stack(ctx, op):
    xs = ctx.in_list(op, 'X')
    ctx.out(op, 'Y', jnp.stack(xs, axis=op.attr('axis', 0)))


@register_op('unstack', share_lod=False)
def _unstack(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', 0)
    parts = jnp.split(x, x.shape[axis], axis=axis)
    for i, p in enumerate(parts):
        ctx.out(op, 'Y', jnp.squeeze(p, axis=axis), idx=i)


@register_op('expand')
def _expand(ctx, op):
    x = ctx.in1(op, 'X')
    times = op.attr('expand_times')
    ctx.out(op, 'Out', jnp.tile(x, times))


@register_op('tile')
def _tile(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jnp.tile(x, op.attr('repeat_times')))


@register_op('pad')
def _pad(ctx, op):
    x = ctx.in1(op, 'X')
    paddings = op.attr('paddings')
    pad_value = op.attr('pad_value', 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.out(op, 'Out', jnp.pad(x, cfg, constant_values=pad_value))


@register_op('pad2d')
def _pad2d(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    p = op.attr('paddings')  # [top, bottom, left, right]
    mode = op.attr('mode', 'constant')
    value = op.attr('pad_value', 0.0)
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == 'constant':
        out = jnp.pad(x, cfg, constant_values=value)
    elif mode == 'reflect':
        out = jnp.pad(x, cfg, mode='reflect')
    else:
        out = jnp.pad(x, cfg, mode='edge')
    ctx.out(op, 'Out', out)


@register_op('pad_constant_like')
def _pad_constant_like(ctx, op):
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    value = op.attr('pad_value', 0.0)
    cfg = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.out(op, 'Out', jnp.pad(y, cfg, constant_values=value))


@register_op('slice')
def _slice(ctx, op):
    x = ctx.in1(op, 'Input')
    axes = op.attr('axes')
    starts = op.attr('starts')
    ends = op.attr('ends')
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    ctx.out(op, 'Out', x[tuple(idx)])


@register_op('strided_slice', share_lod=False)
def _strided_slice(ctx, op):
    x = ctx.in1(op, 'Input')
    axes = op.attr('axes')
    starts = op.attr('starts')
    ends = op.attr('ends')
    strides = op.attr('strides')
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    ctx.out(op, 'Out', x[tuple(idx)])


@register_op('crop', share_lod=False)
def _crop(ctx, op):
    x = ctx.in1(op, 'X')
    offsets = op.attr('offsets')
    shape = op.attr('shape')
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.out(op, 'Out', x[idx])


@register_op('gather', share_lod=False)
def _gather(ctx, op):
    x = ctx.in1(op, 'X')
    index = ctx.in1(op, 'Index').reshape(-1).astype(jnp.int32)
    ctx.out(op, 'Out', jnp.take(x, index, axis=0))


@register_op('scatter', share_lod=False)
def _scatter(ctx, op):
    x = ctx.in1(op, 'X')
    ids = ctx.in1(op, 'Ids').reshape(-1).astype(jnp.int32)
    updates = ctx.in1(op, 'Updates')
    overwrite = op.attr('overwrite', True)
    if overwrite:
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    ctx.out(op, 'Out', out)


@register_op('gather_nd', share_lod=False)
def _gather_nd(ctx, op):
    x = ctx.in1(op, 'X')
    index = ctx.in1(op, 'Index').astype(jnp.int32)
    ctx.out(op, 'Out', x[tuple(jnp.moveaxis(index, -1, 0))])


@register_op('lookup_table')
def _lookup_table(ctx, op):
    """Embedding gather (reference operators/lookup_table_op.cc). The
    is_sparse SelectedRows grad path is realized by the backward lowering
    (core/lowering.py): in 'scout' mode we record this site's ids; in 'apply'
    mode the table is held out of AD and a zero dummy of the gathered-rows
    shape carries the gradient instead, so no dense [vocab, dim] cotangent is
    ever built."""
    w = ctx.in1(op, 'W')
    ids = ctx.in1(op, 'Ids')
    padding_idx = op.attr('padding_idx', -1)
    flat = ids.reshape(-1).astype(jnp.int32)
    if op.attr('is_distributed', False):
        # vocab-sharded table (reference is_distributed prefetch path,
        # operators/distributed/parameter_prefetch.cc:177): pin dim 0 to the
        # 'model' mesh axis; XLA partitions the take into shard-local masked
        # gathers + psum over ICI — the split_ids/prefetch/merge_ids RPC
        # pipeline as one compiled SPMD gather (ops/dist_ops.py)
        from .dist_ops import table_sharding_constraint
        w = table_sharding_constraint(w)

    from . import kernel_tier
    from .embedding_ops import pallas_shapes_ok, spmd_gather_ok
    from ..parallel.api import get_active_mesh, get_active_param_spec
    mesh = get_active_mesh()
    if mesh is not None and mesh.size > 1:
        # mesh-native: the kernel runs per shard (ids over 'data') via
        # kernel_tier.partitioned_call inside embedding_gather. A SHARDED
        # table — the is_distributed vocab pin above or a param rule —
        # keeps the XLA gather the SPMD partitioner splits into
        # shard-local masked gathers + psum (the dist_ops pipeline).
        spec_fn = get_active_param_spec()
        w_spec = spec_fn(op.input('W')[0]) if spec_fn else None
        ok = not op.attr('is_distributed', False) and \
            spmd_gather_ok(mesh, w, int(flat.shape[0]), w_spec)
    else:
        ok = pallas_shapes_ok(w, int(flat.shape[0]))
    impl = kernel_tier.dispatch(
        'lookup_table', pallas_ok=ok,
        xla_ok=False,   # no distinct xla tier: the gather IS one HLO
        mesh=mesh,
        count=getattr(ctx, 'sparse_mode', None) != 'scout')
    out = lookup_gather(ctx, op, w, flat, impl=impl)
    ctx.out(op, 'Out', embedding_epilogue(out, flat, ids, w, padding_idx))


def lookup_gather(ctx, op, w, flat, bias=None, impl='off'):
    """Shared lookup_table / fused_embedding_gather gather body: routes
    the is_sparse scout/apply mechanism (core/lowering.py sparse grads)
    around whichever gather impl the kernel tier picked."""
    from .embedding_ops import embedding_gather
    w_name = op.input('W')[0]
    sparse = w_name in getattr(ctx, 'sparse_tables', ())
    mode = getattr(ctx, 'sparse_mode', None)
    if sparse and mode == 'scout':
        ctx.sparse_sites.append((w_name, flat, w.shape[1], w.dtype))
    if sparse and mode == 'apply':
        k = ctx.sparse_counter[0]
        ctx.sparse_counter[0] += 1
        # bias adds OUTSIDE the differentiable=False kernel: the table is
        # stop_gradient'd but a trainable Bias is not, and jax cannot
        # transpose through a raw pallas_call — the add after the gather
        # keeps the bias on plain-jnp AD while the dummy carries the
        # table's sparse grad
        out = embedding_gather(lax.stop_gradient(w), flat,
                               impl=impl, differentiable=False) \
            + ctx.env['@sparse%d' % k]
        if bias is not None:
            out = out + bias.reshape(1, -1)
    else:
        out = embedding_gather(w, flat, bias=bias, impl=impl)
    return out


def embedding_epilogue(out, flat, ids, w, padding_idx):
    """Shared lookup_table / lookup_sparse_table tail: zero the padding_idx
    rows and restore the ids' leading shape (a trailing 1 folds into the
    embedding dim, fluid convention)."""
    if padding_idx is not None and padding_idx != -1:
        pad = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
        out = jnp.where((flat == pad)[:, None], 0.0, out)
    out_shape = ids.shape[:-1] + (w.shape[1],) if ids.shape and \
        ids.shape[-1] == 1 else ids.shape + (w.shape[1],)
    return out.reshape(out_shape)


@register_op('top_k')
def _top_k(ctx, op):
    x = ctx.in1(op, 'X')
    k = op.attr('k', 1)
    vals, idx = lax.top_k(x, k)
    ctx.out(op, 'Out', vals)
    ctx.out(op, 'Indices', idx.astype(jnp.int64))


@register_op('arg_max')
def _arg_max(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', -1)
    ctx.out(op, 'Out', jnp.argmax(x, axis=axis).astype(jnp.int64))


@register_op('arg_min')
def _arg_min(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', -1)
    ctx.out(op, 'Out', jnp.argmin(x, axis=axis).astype(jnp.int64))


@register_op('argsort', share_lod=False)
def _argsort(ctx, op):
    x = ctx.in1(op, 'X')
    axis = op.attr('axis', -1)
    idx = jnp.argsort(x, axis=axis)
    ctx.out(op, 'Indices', idx.astype(jnp.int64))
    ctx.out(op, 'Out', jnp.sort(x, axis=axis))


@register_op('reverse', share_lod=False)
def _reverse(ctx, op):
    x = ctx.in1(op, 'X')
    axes = op.attr('axis')
    if not isinstance(axes, (list, tuple)):
        axes = [axes]
    ctx.out(op, 'Out', jnp.flip(x, axis=tuple(axes)))


@register_op('multiplex', share_lod=False)
def _multiplex(ctx, op):
    ids = ctx.in1(op, 'Ids').reshape(-1).astype(jnp.int32)
    xs = jnp.stack(ctx.in_list(op, 'X'), axis=0)
    ctx.out(op, 'Out', xs[ids, jnp.arange(xs.shape[1])])


@register_op('where', share_lod=False)
def _where(ctx, op):
    cond = ctx.in1(op, 'Condition')
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    ctx.out(op, 'Out', jnp.where(cond, x, y))


@register_op('space_to_depth')
def _space_to_depth(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    bs = op.attr('blocksize')
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // bs, bs, w // bs, bs)
    out = out.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * bs * bs,
                                                  h // bs, w // bs)
    ctx.out(op, 'Out', out)


@register_op('shuffle_channel')
def _shuffle_channel(ctx, op):
    x = ctx.in1(op, 'X')
    group = op.attr('group')
    n, c, h, w = x.shape
    out = x.reshape(n, group, c // group, h, w).swapaxes(1, 2) \
           .reshape(n, c, h, w)
    ctx.out(op, 'Out', out)


@register_op('label_smooth')
def _label_smooth(ctx, op):
    x = ctx.in1(op, 'X')
    dist = ctx.in1(op, 'PriorDist')
    eps = op.attr('epsilon', 0.0)
    if dist is not None:
        out = (1.0 - eps) * x + eps * dist
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    ctx.out(op, 'Out', out)


def position_encoding_table(max_len, d_model):
    """The sinusoid table add_position_encoding applies, as a
    [max_len, d_model] float32 array. ALSO gathered row-wise by the
    generative decode path (models/transformer.py): a token's embedding
    must be identical whether it entered via a full prefill forward or a
    single decode step, so both paths MUST build the table through this
    one function."""
    pos = np.arange(max_len)[:, None]
    half = d_model // 2
    freq = np.power(10000.0, -np.arange(half) / float(half))
    enc = np.zeros((max_len, d_model), dtype=np.float32)
    enc[:, :half] = np.sin(pos * freq)
    enc[:, half:2 * half] = np.cos(pos * freq)
    return enc


@register_op('add_position_encoding')
def _add_position_encoding(ctx, op):
    x = ctx.in1(op, 'X')  # (N, L, D)
    alpha = op.attr('alpha', 1.0)
    beta = op.attr('beta', 1.0)
    n, l, d = x.shape
    enc = position_encoding_table(l, d)
    ctx.out(op, 'Out', alpha * x + beta * jnp.asarray(enc))


@register_op('sampling_id')
def _sampling_id(ctx, op):
    x = ctx.in1(op, 'X')  # (N, C) probs
    key = ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.clip(x, 1e-20, 1.0)),
                                 axis=-1)
    ctx.out(op, 'Out', ids.astype(jnp.int64))


@register_op('hash')
def _hash(ctx, op):
    x = ctx.in1(op, 'X').astype(jnp.uint32)
    num_hash = op.attr('num_hash', 1)
    mod_by = op.attr('mod_by', 100000)
    outs = []
    v = x.reshape(x.shape[0], -1)
    for i in range(num_hash):
        h = jnp.sum(v * jnp.uint32(2654435761 + i * 97), axis=-1)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    ctx.out(op, 'Out', jnp.stack(outs, axis=-1)[:, :, None])


@register_op('diag', share_lod=False)
def _diag(ctx, op):
    d = ctx.in1(op, 'Diagonal')
    ctx.out(op, 'Out', jnp.diag(d))


@register_op('get_tensor_from_selected_rows')
def _get_tensor_from_selected_rows(ctx, op):
    """reference get_tensor_from_selected_rows_op.cc: the values tensor."""
    from ..core.selected_rows import SelectedRows
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', x.values if isinstance(x, SelectedRows) else x)


@register_op('merge_selected_rows')
def _merge_selected_rows(ctx, op):
    """reference merge_selected_rows_op.cc (MergeAdd: sum duplicate rows).
    Static-shape version: freed slots park on an out-of-range sentinel row."""
    from ..core.selected_rows import SelectedRows
    x = ctx.in1(op, 'X')
    if isinstance(x, SelectedRows):
        rows, vals = x.merged()
        x = SelectedRows(rows, vals, x.height)
    ctx.out(op, 'Out', x)
