"""Training-health stat harvesting op (health.py).

One op, appended at the end of an instrumented training program, reduces
every gradient / parameter / pre-update copy / tagged activation into a
single small float32 vector — the ONE extra fetch the health observatory
rides on the existing step dispatch. Pure jnp reductions: they fuse into
the step's XLA program and run on the global arrays under a mesh, so
multi-chip programs report global (not per-shard) norms for free.

Output layout (health.instrument builds the matching decode schema):

    [ per-grad L2 norm            x len(Grads)
      per-param update/param     x len(Params)   (||p - p_pre|| / ||p_pre||)
      per-site activation RMS    x len(Acts)
      global grad L2 norm
      global param L2 norm
      non-finite grad entries (count)
      |g| > attr('large') entries (count)
      mean loss                               ]  (only when Loss is given)
"""
import jax.numpy as jnp

from ..core.registry import register_op


def _dense_values(x):
    # SelectedRows grads (sparse embeddings): the implicit zero rows
    # contribute nothing to norms/counts — reduce over the values only
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        return x.values
    return x


@register_op('health_stats', share_lod=False)
def _health_stats(ctx, op):
    f32 = jnp.float32
    grads = [_dense_values(g).astype(f32)
             for g in ctx.in_list(op, 'Grads')]
    params = [p.astype(f32) for p in ctx.in_list(op, 'Params')]
    pres = [p.astype(f32) for p in ctx.in_list(op, 'Pre')]
    acts = ctx.in_list(op, 'Acts')
    loss = ctx.in1(op, 'Loss')
    large = float(op.attr('large', 1e3))

    parts = []
    gsq = jnp.asarray(0.0, f32)
    nonfinite = jnp.asarray(0.0, f32)
    big = jnp.asarray(0.0, f32)
    for g in grads:
        sq = jnp.sum(g * g)
        gsq = gsq + sq
        nonfinite = nonfinite + jnp.sum(~jnp.isfinite(g)).astype(f32)
        big = big + jnp.sum(jnp.abs(g) > large).astype(f32)
        parts.append(jnp.sqrt(sq))

    psq = jnp.asarray(0.0, f32)
    for p, pre in zip(params, pres):
        psq = psq + jnp.sum(p * p)
        d = p - pre
        pre_norm = jnp.sqrt(jnp.sum(pre * pre))
        # zero-init params (biases at step 1) have no meaningful relative
        # update — report 0 instead of ||d||/eps, which would poison the
        # drift detector's baseline with a ~1e10 reading
        ratio = jnp.sqrt(jnp.sum(d * d)) / (pre_norm + 1e-12)
        parts.append(jnp.where(pre_norm > 0, ratio, jnp.asarray(0.0, f32)))

    for a in acts:
        a = a.astype(f32)
        parts.append(jnp.sqrt(jnp.mean(a * a)))

    parts.append(jnp.sqrt(gsq))
    parts.append(jnp.sqrt(psq))
    parts.append(nonfinite)
    parts.append(big)
    if loss is not None:
        parts.append(jnp.mean(loss.astype(f32)))

    ctx.out(op, 'Out', jnp.stack([p.reshape(()) for p in parts])
            if parts else jnp.zeros((0,), f32))
