"""Detection op family (reference paddle/fluid/operators/detection/, ~25 ops).

TPU-native redesign principles:
- Box generators (prior_box / density_prior_box / anchor_generator) depend
  only on static shapes + attrs, so they are computed with numpy at trace
  time and enter the XLA program as constants (zero FLOPs per step).
- Ragged ground-truth boxes ride the static-LoD subsystem (core/lod.py):
  per-instance slices have static extents, so matching/assignment vectorize
  into gathers with no dynamic shapes.
- Data-dependent-length outputs (multiclass_nms detections, mined negative
  indices) cannot carry a runtime LoD under XLA; they are emitted as
  fixed-capacity arrays padded with -1 sentinels (same policy as ctc_align).
  Consumers in this module (target_assign) understand the sentinel.
- Sequential-by-nature algorithms (greedy bipartite match, NMS suppression)
  run as lax.fori_loop over a precomputed similarity/IoU matrix: the matrix
  is one MXU-friendly batched op, the loop body is O(capacity) cheap vector
  work.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_EPS = 1e-6


# ---------------------------------------------------------------------------
# Box generators: trace-time numpy constants
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    """reference prior_box_op.h ExpandAspectRatios."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / ar)
    return out


@register_op('prior_box')
def _prior_box(ctx, op):
    """reference operators/detection/prior_box_op.{cc,h}: SSD prior boxes for
    one feature map. Output Boxes/Variances [H, W, num_priors, 4], a pure
    function of static shapes and attrs -> numpy constant."""
    feat = ctx.in1(op, 'Input')
    image = ctx.in1(op, 'Image')
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]

    min_sizes = [float(s) for s in op.attr('min_sizes')]
    max_sizes = [float(s) for s in (op.attr('max_sizes') or [])]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            "prior_box: max_sizes (%d) must have the same length as "
            "min_sizes (%d)" % (len(max_sizes), len(min_sizes)))
    ars = _expand_aspect_ratios(op.attr('aspect_ratios', [1.0]),
                                op.attr('flip', False))
    variances = [float(v) for v in op.attr('variances',
                                           [0.1, 0.1, 0.2, 0.2])]
    clip = op.attr('clip', False)
    step_w = op.attr('step_w', 0.0)
    step_h = op.attr('step_h', 0.0)
    offset = op.attr('offset', 0.5)
    mmo = op.attr('min_max_aspect_ratios_order', False)

    sw = step_w if step_w else float(iw) / fw
    sh = step_h if step_h else float(ih) / fh

    # per-center list of (half_w, half_h), reference enumeration order
    halves = []
    for s, ms in enumerate(min_sizes):
        if mmo:
            halves.append((ms / 2., ms / 2.))
            if max_sizes:
                m = math.sqrt(ms * max_sizes[s]) / 2.
                halves.append((m, m))
            for ar in ars:
                if abs(ar - 1.) < 1e-6:
                    continue
                halves.append((ms * math.sqrt(ar) / 2.,
                               ms / math.sqrt(ar) / 2.))
        else:
            for ar in ars:
                halves.append((ms * math.sqrt(ar) / 2.,
                               ms / math.sqrt(ar) / 2.))
            if max_sizes:
                m = math.sqrt(ms * max_sizes[s]) / 2.
                halves.append((m, m))
    halves = np.asarray(halves, np.float32)            # [P, 2]
    num_priors = halves.shape[0]

    cx = (np.arange(fw, dtype=np.float32) + offset) * sw   # [W]
    cy = (np.arange(fh, dtype=np.float32) + offset) * sh   # [H]
    cxg, cyg = np.meshgrid(cx, cy)                         # [H, W]
    c = np.stack([cxg, cyg], -1)[:, :, None, :]            # [H, W, 1, 2]
    mins = (c - halves[None, None]) / np.array([iw, ih], np.float32)
    maxs = (c + halves[None, None]) / np.array([iw, ih], np.float32)
    boxes = np.concatenate([mins, maxs], -1)               # [H, W, P, 4]
    if clip:
        boxes = np.clip(boxes, 0., 1.)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, num_priors, 4)).copy()
    ctx.out(op, 'Boxes', jnp.asarray(boxes.astype(np.float32)))
    ctx.out(op, 'Variances', jnp.asarray(var))


@register_op('density_prior_box')
def _density_prior_box(ctx, op):
    """reference operators/detection/density_prior_box_op.h."""
    feat = ctx.in1(op, 'Input')
    image = ctx.in1(op, 'Image')
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]

    fixed_sizes = [float(s) for s in op.attr('fixed_sizes', [])]
    fixed_ratios = [float(r) for r in op.attr('fixed_ratios', [])]
    densities = [int(d) for d in op.attr('densities', [])]
    variances = [float(v) for v in op.attr('variances',
                                           [0.1, 0.1, 0.2, 0.2])]
    clip = op.attr('clip', False)
    step_w = op.attr('step_w', 0.0)
    step_h = op.attr('step_h', 0.0)
    offset = op.attr('offset', 0.5)

    sw = step_w if step_w else float(iw) / fw
    sh = step_h if step_h else float(ih) / fh
    step_average = int((sw + sh) * 0.5)

    # per-center offsets/sizes of all priors (numpy-vectorized: constant
    # evaluation must stay O(ms) even on 200x200 RPN maps)
    doff, dhalf = [], []          # center offset (dx, dy), half size (w, h)
    for s, fixed_size in enumerate(fixed_sizes):
        density = densities[s]
        shift = step_average // density
        base = -step_average / 2. + shift / 2.
        for r in fixed_ratios:
            bwr = fixed_size * math.sqrt(r) / 2.
            bhr = fixed_size / math.sqrt(r) / 2.
            for di in range(density):
                for dj in range(density):
                    doff.append((base + dj * shift, base + di * shift))
                    dhalf.append((bwr, bhr))
    doff = np.asarray(doff, np.float32)          # [P, 2]
    dhalf = np.asarray(dhalf, np.float32)        # [P, 2]
    num_priors = doff.shape[0]

    cx = (np.arange(fw, dtype=np.float32) + offset) * sw
    cy = (np.arange(fh, dtype=np.float32) + offset) * sh
    cxg, cyg = np.meshgrid(cx, cy)                           # [H, W]
    centers = np.stack([cxg, cyg], -1)[:, :, None, :] + doff[None, None]
    dims = np.array([iw, ih], np.float32)
    mins = np.maximum((centers - dhalf[None, None]) / dims, 0.)
    maxs = np.minimum((centers + dhalf[None, None]) / dims, 1.)
    boxes = np.concatenate([mins, maxs], -1)                 # [H, W, P, 4]
    if clip:
        boxes = np.clip(boxes, 0., 1.)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, num_priors, 4)).copy()
    ctx.out(op, 'Boxes', jnp.asarray(boxes))
    ctx.out(op, 'Variances', jnp.asarray(var))


@register_op('anchor_generator')
def _anchor_generator(ctx, op):
    """reference operators/detection/anchor_generator_op.h (Faster-RCNN
    anchors). Output Anchors/Variances [H, W, num_anchors, 4]."""
    feat = ctx.in1(op, 'Input')
    fh, fw = feat.shape[2], feat.shape[3]
    anchor_sizes = [float(s) for s in op.attr('anchor_sizes')]
    aspect_ratios = [float(r) for r in op.attr('aspect_ratios')]
    stride = [float(s) for s in op.attr('stride')]
    variances = [float(v) for v in op.attr('variances',
                                           [0.1, 0.1, 0.2, 0.2])]
    offset = op.attr('offset', 0.5)
    sw, sh = stride[0], stride[1]

    # per-center anchor half-extents (numpy-vectorized over the grid)
    halves = []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = sw * sh
            base_w = round(math.sqrt(area / ar))
            base_h = round(base_w * ar)
            halves.append((0.5 * ((size / sw) * base_w - 1),
                           0.5 * ((size / sh) * base_h - 1)))
    halves = np.asarray(halves, np.float32)                  # [A, 2]
    num_anchors = halves.shape[0]

    xc = np.arange(fw, dtype=np.float32) * sw + offset * (sw - 1)
    yc = np.arange(fh, dtype=np.float32) * sh + offset * (sh - 1)
    xg, yg = np.meshgrid(xc, yc)                             # [H, W]
    ctr = np.stack([xg, yg], -1)[:, :, None, :]              # [H, W, 1, 2]
    anchors = np.concatenate([ctr - halves[None, None],
                              ctr + halves[None, None]], -1)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, num_anchors, 4)).copy()
    ctx.out(op, 'Anchors', jnp.asarray(anchors))
    ctx.out(op, 'Variances', jnp.asarray(var))


# ---------------------------------------------------------------------------
# Box arithmetic
# ---------------------------------------------------------------------------

def _center_size(box, normalized):
    """(cx, cy, w, h) of corner-format boxes [..., 4]."""
    un = 0.0 if normalized else 1.0
    w = box[..., 2] - box[..., 0] + un
    h = box[..., 3] - box[..., 1] + un
    cx = box[..., 0] + w / 2
    cy = box[..., 1] + h / 2
    return cx, cy, w, h


@register_op('box_coder')
def _box_coder(ctx, op):
    """reference operators/detection/box_coder_op.h.
    encode_center_size: TargetBox [M,4] x PriorBox [P,4] -> [M,P,4].
    decode_center_size: TargetBox [M,P,4] with PriorBox broadcast along
    `axis` -> [M,P,4]."""
    prior = ctx.in1(op, 'PriorBox')
    prior_var = ctx.in1(op, 'PriorBoxVar')
    target = ctx.in1(op, 'TargetBox')
    code_type = op.attr('code_type', 'encode_center_size')
    normalized = op.attr('box_normalized', True)
    axis = op.attr('axis', 0)
    var_attr = op.attr('variance', [])

    pcx, pcy, pw, ph = _center_size(prior, normalized)

    if code_type == 'encode_center_size':
        tcx = (target[..., 2] + target[..., 0]) / 2
        tcy = (target[..., 3] + target[..., 1]) / 2
        un = 0.0 if normalized else 1.0
        tw = target[..., 2] - target[..., 0] + un
        th = target[..., 3] - target[..., 1] + un
        # [M, 1] x [1, P]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], -1)          # [M, P, 4]
        if prior_var is not None:
            out = out / prior_var[None, :, :]
        elif var_attr:
            out = out / jnp.asarray(var_attr, out.dtype)
        ctx.out(op, 'OutputBox', out)
        ctx.set_lod(op.output('OutputBox')[0], ctx.in1_lod(op, 'TargetBox'))
        return

    # decode_center_size: prior broadcast along `axis` of target [M, P, 4]
    if target.ndim == 2:
        target = target[:, None, :]
    if prior_var is not None:
        var = prior_var
    elif var_attr:
        var = jnp.broadcast_to(jnp.asarray(var_attr, target.dtype),
                               prior.shape)
    else:
        var = jnp.ones_like(prior)
    if axis == 0:
        # prior indexed by target dim 1
        pcx, pcy, pw, ph = pcx[None, :], pcy[None, :], pw[None, :], ph[None, :]
        var = var[None, :, :]
    else:
        pcx, pcy, pw, ph = pcx[:, None], pcy[:, None], pw[:, None], ph[:, None]
        var = var[:, None, :]
    dcx = var[..., 0] * target[..., 0] * pw + pcx
    dcy = var[..., 1] * target[..., 1] * ph + pcy
    dw = jnp.exp(var[..., 2] * target[..., 2]) * pw
    dh = jnp.exp(var[..., 3] * target[..., 3]) * ph
    un = 0.0 if normalized else 1.0
    out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                     dcx + dw / 2 - un, dcy + dh / 2 - un], -1)
    ctx.out(op, 'OutputBox', out)


def _iou_matrix(x, y, normalized=True):
    """Pairwise IoU of corner boxes x [N,4], y [M,4] -> [N,M]."""
    un = 0.0 if normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + un) * (x[:, 3] - x[:, 1] + un)
    area_y = (y[:, 2] - y[:, 0] + un) * (y[:, 3] - y[:, 1] + un)
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + un, 0.0)
    ih = jnp.maximum(iy2 - iy1 + un, 0.0)
    inter = iw * ih
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(inter > 0, inter / union, 0.0)


@register_op('iou_similarity')
def _iou_similarity(ctx, op):
    """reference operators/detection/iou_similarity_op.h: IoU matrix between
    X [N,4] (LoD-capable) and Y [M,4]."""
    x = ctx.in1(op, 'X')
    y = ctx.in1(op, 'Y')
    normalized = op.attr('box_normalized', True)
    ctx.out(op, 'Out', _iou_matrix(x, y, normalized))
    ctx.set_lod(op.output('Out')[0], ctx.in1_lod(op, 'X'))


@register_op('box_clip')
def _box_clip(ctx, op):
    """reference operators/detection/box_clip_op.h ClipTiledBoxes: clip boxes
    to the original image extent im_info=(h, w, scale)."""
    boxes = ctx.in1(op, 'Input')
    im_info = ctx.in1(op, 'ImInfo')
    lod = ctx.in1_lod(op, 'Input')
    offsets = lod[-1] if lod else (0, boxes.shape[0])
    outs = []
    for i in range(len(offsets) - 1):
        seg = boxes[offsets[i]:offsets[i + 1]]
        im_w = jnp.round(im_info[i, 1] / im_info[i, 2])
        im_h = jnp.round(im_info[i, 0] / im_info[i, 2])
        hi = jnp.stack([im_w - 1, im_h - 1, im_w - 1, im_h - 1])
        clipped = jnp.clip(seg.reshape(-1, 4), 0.0, hi)
        outs.append(clipped.reshape(seg.shape))
    out = jnp.concatenate(outs, 0) if len(outs) > 1 else outs[0]
    ctx.out(op, 'Output', out)
    ctx.set_lod(op.output('Output')[0], lod)


@register_op('polygon_box_transform')
def _polygon_box_transform(ctx, op):
    """reference operators/detection/polygon_box_transform_op.cc (EAST text
    detection geometry): out = 4 * pixel coordinate - in, even channels use
    the column index, odd channels the row index."""
    x = ctx.in1(op, 'Input')
    n, c, h, w = x.shape
    col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :], (h, w))
    row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    grid = jnp.where((jnp.arange(c) % 2 == 0)[:, None, None],
                     col[None], row[None])          # [C, H, W]
    ctx.out(op, 'Output', grid[None] * 4 - x)


# ---------------------------------------------------------------------------
# Matching / assignment
# ---------------------------------------------------------------------------

def _bipartite_greedy(dist):
    """Greedy bipartite match of one instance's [R, C] similarity matrix
    (reference bipartite_match_op.cc BipartiteMatch): repeatedly take the
    globally largest remaining entry (> 0) among unmatched rows/cols.
    Returns (match [C] int32 row-index-or--1, match_dist [C])."""
    r, c = dist.shape
    match0 = jnp.full((c,), -1, jnp.int32)
    mdist0 = jnp.zeros((c,), dist.dtype)
    rowfree0 = jnp.ones((r,), bool)

    def body(_, state):
        match, mdist, rowfree = state
        masked = jnp.where(rowfree[:, None] & (match == -1)[None, :],
                           dist, -1.0)
        k = jnp.argmax(masked)
        i, j = k // c, k % c
        ok = masked.reshape(-1)[k] > _EPS
        match = jnp.where(ok, match.at[j].set(i.astype(jnp.int32)), match)
        mdist = jnp.where(ok, mdist.at[j].set(dist[i, j]), mdist)
        rowfree = jnp.where(ok, rowfree.at[i].set(False), rowfree)
        return match, mdist, rowfree

    match, mdist, _ = lax.fori_loop(0, min(r, c), body,
                                    (match0, mdist0, rowfree0))
    return match, mdist


def _argmax_match(dist, match, mdist, threshold):
    """reference bipartite_match_op.cc ArgMaxMatch: for still-unmatched
    columns, match the row with max dist if >= threshold."""
    col_max = jnp.max(dist, 0)
    col_arg = jnp.argmax(dist, 0).astype(jnp.int32)
    extra = (match == -1) & (col_max >= threshold) & (col_max > _EPS)
    return (jnp.where(extra, col_arg, match),
            jnp.where(extra, col_max, mdist))


@register_op('bipartite_match')
def _bipartite_match(ctx, op):
    """reference operators/detection/bipartite_match_op.cc. DistMat is
    [sum_rows, C] with LoD over instances (or a single instance without);
    outputs ColToRowMatchIndices / ColToRowMatchDist [n, C]."""
    dist = ctx.in1(op, 'DistMat')
    lod = ctx.in1_lod(op, 'DistMat')
    match_type = op.attr('match_type', 'bipartite')
    threshold = op.attr('dist_threshold', 0.5)

    offsets = lod[-1] if lod else (0, dist.shape[0])
    matches, dists = [], []
    c = dist.shape[1]
    for i in range(len(offsets) - 1):
        seg = dist[offsets[i]:offsets[i + 1]]
        if seg.shape[0] == 0:
            # image with no ground-truth rows: nothing to match
            # (reference CPU op leaves the -1/0 initialization)
            matches.append(jnp.full((c,), -1, jnp.int32))
            dists.append(jnp.zeros((c,), dist.dtype))
            continue
        m, d = _bipartite_greedy(seg)
        if match_type == 'per_prediction':
            m, d = _argmax_match(seg, m, d, threshold)
        matches.append(m)
        dists.append(d)
    ctx.out(op, 'ColToRowMatchIndices', jnp.stack(matches))
    ctx.out(op, 'ColToRowMatchDist', jnp.stack(dists))
    ctx.set_lod(op.output('ColToRowMatchIndices')[0], ())
    ctx.set_lod(op.output('ColToRowMatchDist')[0], ())


@register_op('target_assign')
def _target_assign(ctx, op):
    """reference operators/detection/target_assign_op.{cc,h}: gather targets
    X [sum_M, P, K] (LoD over instances) by MatchIndices [N, Np];
    Out[i][j] = X[lod[i] + match[i][j]][j % P], weight 1 where matched,
    else mismatch_value / weight 0. NegIndices marks negatives: target
    mismatch_value with weight 1.

    TPU deviation: NegIndices is the fixed-shape [N, Q] -1-padded array
    emitted by mine_hard_examples (not a ragged LoD tensor)."""
    x = ctx.in1(op, 'X')
    match = ctx.in1(op, 'MatchIndices')
    neg = ctx.in1(op, 'NegIndices')
    mismatch_value = op.attr('mismatch_value', 0)
    lod = ctx.in1_lod(op, 'X')
    n, np_ = match.shape
    if x.ndim == 2:
        x = x[:, None, :]
    p = x.shape[1]
    offsets = (lod[-1] if lod else (0, x.shape[0]))
    if len(offsets) - 1 != n:
        raise ValueError(
            "target_assign: X has %d instances (lod) but MatchIndices has "
            "batch %d" % (len(offsets) - 1, n))

    cols = jnp.arange(np_) % p
    outs, weights = [], []
    for i in range(n):
        xi = x[offsets[i]:offsets[i + 1]]       # [Mi, P, K]
        mi = match[i]                            # [Np]
        valid = mi > -1
        idx = jnp.clip(mi, 0, max(xi.shape[0] - 1, 0))
        gathered = xi[idx, cols]                 # [Np, K]
        out_i = jnp.where(valid[:, None], gathered,
                          jnp.asarray(mismatch_value, x.dtype))
        w_i = valid.astype(jnp.float32)
        if neg is not None:
            neg_i = neg[i].reshape(-1).astype(jnp.int32)
            sent = jnp.where(neg_i < 0, np_, neg_i)   # -1 -> dropped
            out_i = out_i.at[sent].set(
                jnp.asarray(mismatch_value, x.dtype), mode='drop')
            w_i = w_i.at[sent].set(1.0, mode='drop')
        outs.append(out_i)
        weights.append(w_i)
    ctx.out(op, 'Out', jnp.stack(outs))
    ctx.out(op, 'OutWeight', jnp.stack(weights)[:, :, None])
    ctx.set_lod(op.output('Out')[0], ())


@register_op('mine_hard_examples')
def _mine_hard_examples(ctx, op):
    """reference operators/detection/mine_hard_examples_op.cc. Selects hard
    negative priors by descending loss.

    max_negative: eligible = unmatched & match_dist < neg_dist_threshold;
    select min(neg_pos_ratio * num_pos, num_eligible) largest-loss ones.
    hard_example: eligible = all; select min(sample_size, Np); positives not
    selected are demoted to -1 in UpdatedMatchIndices.

    TPU deviation: NegIndices is [N, Np] int32, the selected prior indices in
    descending-loss order, -1-padded (the reference emits a ragged LoD
    tensor; fixed capacity keeps shapes static under XLA)."""
    cls_loss = ctx.in1(op, 'ClsLoss')
    loc_loss = ctx.in1(op, 'LocLoss')
    match = ctx.in1(op, 'MatchIndices')
    mdist = ctx.in1(op, 'MatchDist')
    ratio = op.attr('neg_pos_ratio', 3.0)
    thr = op.attr('neg_dist_threshold', 0.5)
    sample_size = op.attr('sample_size', 0) or 0
    mining_type = op.attr('mining_type', 'max_negative')

    n, np_ = match.shape
    loss = cls_loss.reshape(n, np_)
    if mining_type == 'hard_example' and loc_loss is not None:
        loss = loss + loc_loss.reshape(n, np_)

    if mining_type == 'max_negative':
        eligible = (match == -1) & (mdist < thr)
        num_pos = jnp.sum((match != -1).astype(jnp.int32), 1)       # [N]
        quota = (num_pos.astype(jnp.float32) * ratio).astype(jnp.int32)
    elif mining_type == 'hard_example':
        eligible = jnp.ones_like(match, bool)
        quota = jnp.full((n,), int(sample_size), jnp.int32)
    else:
        raise ValueError("mine_hard_examples: unknown mining_type %r"
                         % mining_type)

    masked_loss = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked_loss, 1)                  # [N, Np] desc
    n_eligible = jnp.sum(eligible.astype(jnp.int32), 1)
    n_sel = jnp.minimum(quota, n_eligible)                # [N]
    rank = jnp.arange(np_)[None, :]
    sel_sorted = rank < n_sel[:, None]                    # positions kept
    neg_indices = jnp.where(sel_sorted, order, -1).astype(jnp.int32)

    if mining_type == 'hard_example':
        # scatter selection flags back to prior positions
        sel = jnp.zeros((n, np_), bool)
        sel = jax.vmap(
            lambda s, o, f: s.at[o].set(f))(sel, order, sel_sorted)
        updated = jnp.where((match > -1) & ~sel, -1, match)
        # positives selected keep their match; drop them from the neg list
        is_neg = jax.vmap(lambda m, o: m[o] == -1)(match, order)
        neg_indices = jnp.where(sel_sorted & is_neg, order, -1).astype(
            jnp.int32)
        ctx.out(op, 'UpdatedMatchIndices', updated)
    else:
        ctx.out(op, 'UpdatedMatchIndices', match)
    ctx.out(op, 'NegIndices', neg_indices)
    ctx.set_lod(op.output('NegIndices')[0], ())


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------

def _greedy_suppress(iou, valid, nms_threshold, nms_eta):
    """Greedy NMS keep-mask over score-sorted candidates (reference
    multiclass_nms_op.cc NMSFast's adaptive-threshold state machine).
    iou [K,K] of the sorted candidates; valid [K] candidate mask."""
    k = valid.shape[0]

    def body(i, state):
        keep, thr = state
        sup = jnp.max(jnp.where(keep & (jnp.arange(k) < i), iou[:, i], 0.0))
        ok = valid[i] & (sup <= thr)
        keep = keep.at[i].set(ok)
        thr = jnp.where(ok & (nms_eta < 1.0) & (thr > 0.5), thr * nms_eta,
                        thr)
        return keep, thr

    keep, _ = lax.fori_loop(
        0, k, body, (jnp.zeros((k,), bool),
                     jnp.asarray(nms_threshold, jnp.float32)))
    return keep


def _nms_class(boxes, scores, score_threshold, nms_top_k, nms_threshold,
               nms_eta, normalized):
    """Greedy NMS for one class (reference multiclass_nms_op.cc NMSFast).
    boxes [M,4], scores [M] -> (keep mask over top-K candidates, their
    indices into the original M, their scores). Static capacity K."""
    m = boxes.shape[0]
    k = m if nms_top_k < 0 else min(int(nms_top_k), m)
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    cand_scores = jnp.where(scores > score_threshold, scores, neg_inf)
    top_scores, top_idx = lax.top_k(cand_scores, k)
    top_boxes = boxes[top_idx]
    iou = _iou_matrix(top_boxes, top_boxes, normalized)
    keep = _greedy_suppress(iou, top_scores > neg_inf, nms_threshold,
                            nms_eta)
    return keep, top_idx, top_scores


@register_op('multiclass_nms')
def _multiclass_nms(ctx, op):
    """reference operators/detection/multiclass_nms_op.cc. BBoxes [N, M, 4],
    Scores [N, C, M] -> Out [N * keep_top_k, 6] rows (label, score, x1, y1,
    x2, y2).

    TPU deviation: the reference output is ragged (LoD over images, length =
    per-image detection count). Here every image occupies exactly keep_top_k
    rows; slots beyond the real detections carry label -1 (the ctc_align
    sentinel policy). keep_top_k must be >= 0 for a static capacity."""
    bboxes = ctx.in1(op, 'BBoxes')
    scores = ctx.in1(op, 'Scores')
    background = op.attr('background_label', 0)
    score_threshold = op.attr('score_threshold')
    nms_top_k = op.attr('nms_top_k')
    nms_threshold = op.attr('nms_threshold', 0.3)
    nms_eta = op.attr('nms_eta', 1.0)
    keep_top_k = op.attr('keep_top_k')
    normalized = op.attr('normalized', True)
    if keep_top_k is None or keep_top_k < 0:
        raise ValueError(
            "multiclass_nms: keep_top_k must be a non-negative static "
            "capacity on TPU (the ragged reference output would need "
            "dynamic shapes)")

    n, m, _ = bboxes.shape
    c = scores.shape[1]

    def per_image(boxes, sc):
        sel_scores, sel_labels, sel_pos = [], [], []
        for cls in range(c):
            if cls == background:
                continue
            keep, top_idx, top_scores = _nms_class(
                boxes, sc[cls], score_threshold, nms_top_k, nms_threshold,
                nms_eta, normalized)
            sel_scores.append(jnp.where(keep, top_scores, -jnp.inf))
            sel_labels.append(jnp.full(keep.shape, cls, jnp.int32))
            sel_pos.append(top_idx)
        all_scores = jnp.concatenate(sel_scores)
        all_labels = jnp.concatenate(sel_labels)
        all_pos = jnp.concatenate(sel_pos)
        kk = min(int(keep_top_k), all_scores.shape[0])
        final_scores, fi = lax.top_k(all_scores, kk)
        ok = final_scores > -jnp.inf
        labels = jnp.where(ok, all_labels[fi], -1)
        fboxes = boxes[all_pos[fi]]
        row = jnp.concatenate(
            [labels[:, None].astype(boxes.dtype),
             jnp.where(ok, final_scores, 0.0)[:, None].astype(boxes.dtype),
             jnp.where(ok[:, None], fboxes, -1.0)], 1)
        if kk < keep_top_k:
            pad = jnp.full((int(keep_top_k) - kk, 6), -1.0, boxes.dtype)
            row = jnp.concatenate([row, pad], 0)
        return row

    out = jax.vmap(per_image)(bboxes, scores)     # [N, keep_top_k, 6]
    ctx.out(op, 'Out', out.reshape(n * int(keep_top_k), 6))
    ctx.set_lod(op.output('Out')[0], ())


# ---------------------------------------------------------------------------
# YOLO / RCNN family
# ---------------------------------------------------------------------------

def _sce(x, label):
    """Numerically-stable sigmoid cross entropy (reference yolov3_loss_op.h
    SigmoidCrossEntropy)."""
    return jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _cxcywh_iou(b1, b2):
    """IoU of center-size boxes (reference yolov3_loss_op.h CalcBoxIoU).
    b1 [..., 4], b2 [..., 4] broadcastable."""
    l = jnp.maximum(b1[..., 0] - b1[..., 2] / 2, b2[..., 0] - b2[..., 2] / 2)
    r = jnp.minimum(b1[..., 0] + b1[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2)
    t = jnp.maximum(b1[..., 1] - b1[..., 3] / 2, b2[..., 1] - b2[..., 3] / 2)
    b = jnp.minimum(b1[..., 1] + b1[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2)
    iw = jnp.maximum(r - l, 0.0)
    ih = jnp.maximum(b - t, 0.0)
    inter = iw * ih
    union = b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op('yolov3_loss')
def _yolov3_loss(ctx, op):
    """reference operators/detection/yolov3_loss_op.{cc,h}. X is
    [N, mask_num*(5+C), H, W]; GTBox [N, B, 4] center-size relative coords;
    GTLabel [N, B] int. Loss [N] per image, fully vectorized:
    - location/class loss at each gt's best-anchor cell,
    - objectness loss: 1-target at matched cells, 0-target elsewhere except
      cells whose best pred-gt IoU exceeds ignore_thresh (masked out)."""
    x = ctx.in1(op, 'X')
    gtbox = ctx.in1(op, 'GTBox')
    gtlabel = ctx.in1(op, 'GTLabel')
    anchors = [int(a) for a in op.attr('anchors')]
    anchor_mask = [int(a) for a in op.attr('anchor_mask')]
    class_num = op.attr('class_num')
    ignore_thresh = op.attr('ignore_thresh')
    downsample = op.attr('downsample_ratio', 32)

    n, _, h, w = x.shape
    b = gtbox.shape[1]
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    input_size = downsample * h
    xr = x.reshape(n, mask_num, 5 + class_num, h, w)
    gtlabel = gtlabel.astype(jnp.int32)

    anchors_np = np.asarray(anchors, np.float32).reshape(an_num, 2)
    mask_anchors = anchors_np[np.asarray(anchor_mask)]       # [mask, 2]

    # --- predicted boxes per (mask, cell) for the ignore rule ------------
    gi = jnp.arange(w, dtype=jnp.float32)[None, :]
    gj = jnp.arange(h, dtype=jnp.float32)[:, None]
    px = (gi + jax.nn.sigmoid(xr[:, :, 0])) / w              # [n,mask,h,w]
    py = (gj + jax.nn.sigmoid(xr[:, :, 1])) / h
    pw_ = jnp.exp(xr[:, :, 2]) * \
        jnp.asarray(mask_anchors[:, 0])[None, :, None, None] / input_size
    ph_ = jnp.exp(xr[:, :, 3]) * \
        jnp.asarray(mask_anchors[:, 1])[None, :, None, None] / input_size
    pred = jnp.stack([px, py, pw_, ph_], -1)                 # [n,mask,h,w,4]

    gt_valid = (gtbox[..., 2] > 1e-6) & (gtbox[..., 3] > 1e-6)   # [n,b]
    iou_pg = _cxcywh_iou(pred[:, :, :, :, None, :],
                         gtbox[:, None, None, None, :, :])   # [n,mask,h,w,b]
    iou_pg = jnp.where(gt_valid[:, None, None, None, :], iou_pg, 0.0)
    best_iou = jnp.max(iou_pg, -1) if b else jnp.zeros_like(px)
    ignore = best_iou > ignore_thresh                        # obj = -1

    # --- per-gt best anchor (over ALL anchors, centered at origin) -------
    an_wh = jnp.asarray(anchors_np) / input_size             # [an, 2]
    zeros2 = jnp.zeros((an_num, 2))
    an_boxes = jnp.concatenate([zeros2, an_wh], -1)          # [an, 4]
    gt_shift = gtbox.at[..., 0:2].set(0.0)                   # [n, b, 4]
    iou_ga = _cxcywh_iou(gt_shift[:, :, None, :],
                         an_boxes[None, None, :, :])         # [n, b, an]
    best_n = jnp.argmax(iou_ga, -1)                          # [n, b]
    # map anchor index -> position in anchor_mask (or -1)
    mask_lookup = np.full((an_num,), -1, np.int32)
    for mi, av in enumerate(anchor_mask):
        mask_lookup[av] = mi
    mask_idx = jnp.asarray(mask_lookup)[best_n]              # [n, b]
    matched = gt_valid & (mask_idx >= 0)

    gx_cell = jnp.clip((gtbox[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gy_cell = jnp.clip((gtbox[..., 1] * h).astype(jnp.int32), 0, h - 1)

    def per_image(xi, gt, lab, m_idx, gxc, gyc, ok, bn):
        """xi [mask,5+C,h,w]; loop over B gts (B static)."""
        loss = 0.0
        obj_pos = jnp.zeros((mask_num, h, w), bool)
        for t in range(b):
            mi = jnp.clip(m_idx[t], 0, mask_num - 1)
            cell = xi[mi, :, gyc[t], gxc[t]]                 # [5+C]
            tx = gt[t, 0] * w - gxc[t]
            ty = gt[t, 1] * h - gyc[t]
            anc = jnp.asarray(anchors_np)[jnp.clip(bn[t], 0, an_num - 1)]
            tw = jnp.log(jnp.maximum(gt[t, 2] * input_size / anc[0], 1e-9))
            th = jnp.log(jnp.maximum(gt[t, 3] * input_size / anc[1], 1e-9))
            scale = 2.0 - gt[t, 2] * gt[t, 3]
            loc = (_sce(cell[0], tx) + _sce(cell[1], ty)) * scale + \
                0.5 * ((cell[2] - tw) ** 2 + (cell[3] - th) ** 2) * scale
            onehot = jax.nn.one_hot(lab[t], class_num)
            cls = jnp.sum(_sce(cell[5:], onehot))
            loss = loss + jnp.where(ok[t], loc + cls, 0.0)
            obj_pos = jnp.where(
                ok[t], obj_pos.at[mi, gyc[t], gxc[t]].set(True), obj_pos)
        return loss, obj_pos

    loc_cls_loss, obj_pos = jax.vmap(per_image)(
        xr, gtbox, gtlabel, mask_idx, gx_cell, gy_cell, matched, best_n)

    obj_logit = xr[:, :, 4]                                  # [n,mask,h,w]
    pos_loss = jnp.where(obj_pos, _sce(obj_logit, 1.0), 0.0)
    neg_loss = jnp.where((~obj_pos) & (~ignore),
                         _sce(obj_logit, 0.0), 0.0)
    obj_loss = jnp.sum(pos_loss + neg_loss, axis=(1, 2, 3))
    loss = loc_cls_loss + obj_loss

    ctx.out(op, 'Loss', loss)
    objness = jnp.where(obj_pos, 1.0, jnp.where(ignore, -1.0, 0.0))
    ctx.out(op, 'ObjectnessMask', objness)
    ctx.out(op, 'GTMatchMask', jnp.where(matched, mask_idx, -1))


@register_op('generate_proposals')
def _generate_proposals(ctx, op):
    """reference operators/detection/generate_proposals_op.cc: decode RPN
    deltas against anchors, clip, filter small, NMS.

    TPU deviation: RpnRois is [N * post_nms_topN, 4] with a uniform static
    LoD (post_nms_topN rows per image); empty slots carry zeros with
    probability 0 (the reference emits ragged counts)."""
    scores = ctx.in1(op, 'Scores')          # [N, A, H, W]
    deltas = ctx.in1(op, 'BboxDeltas')      # [N, 4A, H, W]
    im_info = ctx.in1(op, 'ImInfo')         # [N, 3]
    anchors = ctx.in1(op, 'Anchors')        # [H, W, A, 4]
    variances = ctx.in1(op, 'Variances')
    pre_n = op.attr('pre_nms_topN', 6000)
    post_n = op.attr('post_nms_topN', 1000)
    nms_thresh = op.attr('nms_thresh', 0.5)
    min_size = op.attr('min_size', 0.1)
    eta = op.attr('eta', 1.0)

    n, a, h, w = scores.shape
    total = h * w * a
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4)

    def per_image(sc, dl, info):
        # scores laid out [A, H, W] -> hwa order to match anchors [H,W,A]
        s = sc.transpose(1, 2, 0).reshape(-1)            # [total]
        d = dl.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(int(pre_n), total) if pre_n > 0 else total
        top_s, idx = lax.top_k(s, k)
        anc_k = anc[idx]
        var_k = var[idx]
        d_k = d[idx]
        # decode (reference BoxCoder in generate_proposals: variances
        # multiply deltas; exp clamped)
        aw = anc_k[:, 2] - anc_k[:, 0] + 1.0
        ah = anc_k[:, 3] - anc_k[:, 1] + 1.0
        acx = anc_k[:, 0] + aw / 2
        acy = anc_k[:, 1] + ah / 2
        cx = var_k[:, 0] * d_k[:, 0] * aw + acx
        cy = var_k[:, 1] * d_k[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(var_k[:, 2] * d_k[:, 2],
                                 math.log(1000. / 16.))) * aw
        bh = jnp.exp(jnp.minimum(var_k[:, 3] * d_k[:, 3],
                                 math.log(1000. / 16.))) * ah
        props = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
        # clip to image
        im_h, im_w = info[0], info[1]
        hi = jnp.stack([im_w - 1, im_h - 1, im_w - 1, im_h - 1])
        props = jnp.clip(props, 0.0, hi)
        # filter small (reference FilterBoxes: size in original image space)
        ws = props[:, 2] - props[:, 0] + 1
        hs = props[:, 3] - props[:, 1] + 1
        ms = min_size * info[2]
        keep_size = (ws >= ms) & (hs >= ms)
        s_f = jnp.where(keep_size, top_s, -jnp.inf)
        # NMS over k candidates (scores already sorted desc)
        iou = _iou_matrix(props, props, normalized=False)
        keep = _greedy_suppress(iou, s_f > -jnp.inf, nms_thresh, eta)
        kept_scores = jnp.where(keep, s_f, -jnp.inf)
        pk = min(int(post_n), k)
        fin_s, fi = lax.top_k(kept_scores, pk)
        ok = fin_s > -jnp.inf
        rois = jnp.where(ok[:, None], props[fi], 0.0)
        probs = jnp.where(ok, fin_s, 0.0)
        if pk < post_n:
            rois = jnp.concatenate(
                [rois, jnp.zeros((int(post_n) - pk, 4), rois.dtype)], 0)
            probs = jnp.concatenate(
                [probs, jnp.zeros((int(post_n) - pk,), probs.dtype)], 0)
        return rois, probs

    rois, probs = jax.vmap(per_image)(scores, deltas, im_info)
    out_rois = rois.reshape(n * int(post_n), 4)
    out_probs = probs.reshape(n * int(post_n), 1)
    ctx.out(op, 'RpnRois', out_rois)
    ctx.out(op, 'RpnRoiProbs', out_probs)
    uniform = tuple(int(post_n) * i for i in range(n + 1))
    ctx.set_lod(op.output('RpnRois')[0], (uniform,))
    if op.output('RpnRoiProbs'):
        ctx.set_lod(op.output('RpnRoiProbs')[0], (uniform,))


@register_op('rpn_target_assign', needs_rng=True)
def _rpn_target_assign(ctx, op):
    """reference operators/detection/rpn_target_assign_op.cc: sample
    rpn_batch_size_per_im anchors per image (fg by IoU >= positive_overlap
    or best-per-gt, bg by IoU < negative_overlap), random subsampling.

    TPU deviation: fixed capacities — LocationIndex is
    [N * fg_quota] (-1-padded via clamp + zero BBoxInsideWeight),
    ScoreIndex is [N * rpn_batch_size_per_im]; indices are into the
    flattened [N * A] anchor-score array, matching the reference's use
    after reshape(cls_logits, [-1, 1])."""
    anchor = ctx.in1(op, 'Anchor')          # [A, 4] (or [H,W,A,4])
    gt_boxes = ctx.in1(op, 'GtBoxes')       # LoD [sum_g, 4]
    is_crowd = ctx.in1(op, 'IsCrowd')       # optional LoD [sum_g] int
    im_info = ctx.in1(op, 'ImInfo')
    lod = ctx.in1_lod(op, 'GtBoxes')
    batch_per_im = op.attr('rpn_batch_size_per_im', 256)
    straddle_thresh = op.attr('rpn_straddle_thresh', 0.0)
    pos_overlap = op.attr('rpn_positive_overlap', 0.7)
    neg_overlap = op.attr('rpn_negative_overlap', 0.3)
    fg_frac = op.attr('rpn_fg_fraction', 0.5)
    use_random = op.attr('use_random', True)

    anc = anchor.reshape(-1, 4)
    a = anc.shape[0]
    offsets = lod[-1] if lod else (0, gt_boxes.shape[0])
    n = len(offsets) - 1
    fg_quota = int(batch_per_im * fg_frac)

    key = ctx.rng()

    loc_idx, score_idx, tgt_label, tgt_bbox, inside_w = [], [], [], [], []
    for i in range(n):
        gt = gt_boxes[offsets[i]:offsets[i + 1]]
        empty_gt = gt.shape[0] == 0
        if empty_gt:
            # no ground truth: every anchor is background-eligible
            # (reference samples only negatives for such images)
            gt = jnp.full((1, 4), -1e4, gt_boxes.dtype)
        iou = _iou_matrix(anc, gt, normalized=False)     # [A, G]
        if is_crowd is not None and not empty_gt:
            # crowd gt boxes never produce positives (reference
            # rpn_target_assign_op.cc FilterCrowdGt)
            crowd = is_crowd[offsets[i]:offsets[i + 1]].reshape(-1) > 0
            iou = jnp.where(crowd[None, :], 0.0, iou)
        # anchors straddling the image border beyond the threshold are
        # excluded entirely (reference: inds_inside when straddle >= 0)
        if straddle_thresh >= 0:
            im_h, im_w = im_info[i, 0], im_info[i, 1]
            inside = ((anc[:, 0] >= -straddle_thresh) &
                      (anc[:, 1] >= -straddle_thresh) &
                      (anc[:, 2] < im_w + straddle_thresh) &
                      (anc[:, 3] < im_h + straddle_thresh))
        else:
            inside = jnp.ones((a,), bool)
        amax = jnp.max(iou, 1)
        agt = jnp.argmax(iou, 1)
        # best anchor for each gt is fg too
        best_per_gt = jnp.max(iou, 0)                    # [G]
        is_best = jnp.any(iou == jnp.maximum(best_per_gt[None, :], 1e-12),
                          1) & (amax > 0)
        fg = ((amax >= pos_overlap) | is_best) & inside
        bg = (~fg) & (amax < neg_overlap) & inside

        ki = jax.random.fold_in(key, i)
        rand = jax.random.uniform(ki, (a,)) if use_random else \
            jnp.arange(a, dtype=jnp.float32) / a
        # rank fg anchors randomly, keep fg_quota
        fg_rank = jnp.argsort(jnp.argsort(
            jnp.where(fg, rand, 2.0)))                   # stable rank
        fg_keep = fg & (fg_rank < fg_quota)
        n_fg = jnp.sum(fg_keep.astype(jnp.int32))
        bg_quota = batch_per_im - n_fg
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, rand, 2.0)))
        bg_keep = bg & (bg_rank < bg_quota)

        # fixed-capacity index lists: order anchors by (fg_keep desc, rank)
        fg_order = jnp.argsort(jnp.where(fg_keep, fg_rank, a + 1))
        fg_sel = fg_order[:fg_quota]                     # [fg_quota]
        fg_valid = fg_keep[fg_sel]
        sel_priority = jnp.where(fg_keep, fg_rank,
                                 jnp.where(bg_keep, fg_quota + bg_rank,
                                           2 * a + 1))
        all_order = jnp.argsort(sel_priority)
        sc_sel = all_order[:batch_per_im]
        sc_valid = (fg_keep | bg_keep)[sc_sel]

        # targets
        gt_of = jnp.clip(agt[fg_sel], 0, max(gt.shape[0] - 1, 0))
        gtb = gt[gt_of]
        ab = anc[fg_sel]
        aw = ab[:, 2] - ab[:, 0] + 1.0
        ah = ab[:, 3] - ab[:, 1] + 1.0
        acx = ab[:, 0] + aw / 2
        acy = ab[:, 1] + ah / 2
        gw = gtb[:, 2] - gtb[:, 0] + 1.0
        gh = gtb[:, 3] - gtb[:, 1] + 1.0
        gcx = gtb[:, 0] + gw / 2
        gcy = gtb[:, 1] + gh / 2
        tb = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                        jnp.log(gw / aw), jnp.log(gh / ah)], -1)

        # pad unfilled score slots by repeating the last valid sample (so
        # padding never trains an arbitrary anchor; duplicates only occur
        # when fewer than batch_per_im anchors are eligible)
        last_valid = jnp.maximum(
            jnp.max(jnp.where(sc_valid,
                              jnp.arange(sc_sel.shape[0]), -1)), 0)
        fill = sc_sel[last_valid]
        sc_final = jnp.where(sc_valid, sc_sel, fill)
        loc_idx.append(jnp.where(fg_valid, fg_sel + i * a, 0))
        score_idx.append(sc_final + i * a)
        tgt_label.append(fg_keep[sc_final].astype(jnp.int32))
        tgt_bbox.append(jnp.where(fg_valid[:, None],
                                  jnp.nan_to_num(tb), 0.0))
        inside_w.append(jnp.where(fg_valid[:, None],
                                  jnp.ones_like(tb), 0.0))

    ctx.out(op, 'LocationIndex',
            jnp.concatenate(loc_idx).astype(jnp.int32))
    ctx.out(op, 'ScoreIndex', jnp.concatenate(score_idx).astype(jnp.int32))
    ctx.out(op, 'TargetLabel',
            jnp.concatenate(tgt_label).reshape(-1, 1))
    ctx.out(op, 'TargetBBox', jnp.concatenate(tgt_bbox))
    ctx.out(op, 'BBoxInsideWeight', jnp.concatenate(inside_w))
    for slot in ('LocationIndex', 'ScoreIndex', 'TargetLabel',
                 'TargetBBox', 'BBoxInsideWeight'):
        if op.output(slot):
            ctx.set_lod(op.output(slot)[0], ())


@register_op('generate_proposal_labels', needs_rng=True)
def _generate_proposal_labels(ctx, op):
    """reference operators/detection/generate_proposal_labels_op.cc
    (SampleRoisForOneImage): mix RPN proposals with ground truth, split
    into fg (IoU > fg_thresh) / bg (bg_thresh_lo <= IoU < bg_thresh_hi),
    subsample to batch_size_per_im with fg_fraction, and emit per-class
    expanded regression targets.

    TPU deviation (the rpn_target_assign fixed-quota policy): every image
    emits exactly batch_size_per_im rows (uniform static LoD); when fewer
    eligible boxes exist, slots repeat the last valid sample so padding
    never trains a fabricated example."""
    rpn_rois = ctx.in1(op, 'RpnRois')          # LoD [sum_r, 4]
    gt_classes = ctx.in1(op, 'GtClasses')      # LoD [sum_g, 1]
    is_crowd = ctx.in1(op, 'IsCrowd')          # LoD [sum_g, 1]
    gt_boxes = ctx.in1(op, 'GtBoxes')          # LoD [sum_g, 4]
    im_info = ctx.in1(op, 'ImInfo')            # [N, 3]
    roi_lod = ctx.in1_lod(op, 'RpnRois')
    gt_lod = ctx.in1_lod(op, 'GtBoxes')
    batch = int(op.attr('batch_size_per_im', 256))
    fg_fraction = op.attr('fg_fraction', 0.25)
    fg_thresh = op.attr('fg_thresh', 0.25)
    bg_hi = op.attr('bg_thresh_hi', 0.5)
    bg_lo = op.attr('bg_thresh_lo', 0.0)
    weights = [float(w) for w in op.attr('bbox_reg_weights',
                                         [0.1, 0.1, 0.2, 0.2])]
    class_nums = int(op.attr('class_nums'))
    use_random = op.attr('use_random', True)

    roff = roi_lod[-1] if roi_lod else (0, rpn_rois.shape[0])
    goff = gt_lod[-1] if gt_lod else (0, gt_boxes.shape[0])
    n = len(roff) - 1
    fg_quota = int(round(fg_fraction * batch))
    key = ctx.rng()

    rois_o, labels_o, tgt_o, biw_o, bow_o = [], [], [], [], []
    for i in range(n):
        rois_i = rpn_rois[roff[i]:roff[i + 1]] / im_info[i, 2]
        gt_i = gt_boxes[goff[i]:goff[i + 1]]
        cls_i = gt_classes[goff[i]:goff[i + 1]].reshape(-1).astype(
            jnp.int32)
        crowd_i = is_crowd[goff[i]:goff[i + 1]].reshape(-1) > 0 \
            if is_crowd is not None else jnp.zeros(gt_i.shape[0], bool)
        boxes = jnp.concatenate([gt_i, rois_i], 0)     # gt first (ref)
        p = boxes.shape[0]
        n_gt = gt_i.shape[0]
        if n_gt == 0:
            overlaps = jnp.zeros((p, 1))
            cls_i = jnp.zeros((1,), jnp.int32)
            gt_i = jnp.zeros((1, 4), boxes.dtype)
            crowd_i = jnp.zeros((1,), bool)
        else:
            # pixel +1 convention like the reference BboxOverlaps
            overlaps = _iou_matrix(boxes, gt_i, normalized=False)
        max_ov = jnp.max(overlaps, 1)
        arg_gt = jnp.argmax(overlaps, 1)
        # crowd gt boxes (the first n_gt rows of `boxes`) are excluded
        # from both fg and bg (reference sets their max_overlap to -1)
        row_is_crowd = jnp.concatenate(
            [crowd_i, jnp.zeros((p - crowd_i.shape[0],), bool)]) \
            if n_gt else jnp.zeros((p,), bool)
        max_ov = jnp.where(row_is_crowd, -1.0, max_ov)

        fg = max_ov > fg_thresh
        bg = (~fg) & (max_ov >= bg_lo) & (max_ov < bg_hi)
        ki = jax.random.fold_in(key, i)
        rand = jax.random.uniform(ki, (p,)) if use_random else \
            jnp.arange(p, dtype=jnp.float32) / p
        fg_rank = jnp.argsort(jnp.argsort(jnp.where(fg, rand, 2.0)))
        fg_keep = fg & (fg_rank < fg_quota)
        n_fg = jnp.sum(fg_keep.astype(jnp.int32))
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, rand, 2.0)))
        bg_keep = bg & (bg_rank < (batch - n_fg))
        priority = jnp.where(fg_keep, fg_rank,
                             jnp.where(bg_keep, fg_quota + bg_rank,
                                       2 * p + 1))
        order = jnp.argsort(priority)
        if p >= batch:
            sel = order[:batch]
            in_range = jnp.ones((batch,), bool)
        else:
            sel = jnp.concatenate(
                [order, jnp.zeros((batch - p,), order.dtype)])
            in_range = jnp.arange(batch) < p
        valid = (fg_keep | bg_keep)[sel] & in_range
        # repeat the last valid sample into padding slots
        last = jnp.maximum(jnp.max(jnp.where(
            valid, jnp.arange(batch), -1)), 0)
        sel = jnp.where(valid, sel, sel[last])
        is_fg = fg_keep[sel]

        sboxes = boxes[sel]
        sgt = gt_i[jnp.clip(arg_gt[sel], 0, gt_i.shape[0] - 1)]
        labels = jnp.where(is_fg, cls_i[jnp.clip(
            arg_gt[sel], 0, cls_i.shape[0] - 1)], 0)

        # BoxToDelta with reg weights (reference bbox_util.h,
        # pixel +1 convention like rpn_target_assign)
        bw = sboxes[:, 2] - sboxes[:, 0] + 1.0
        bh = sboxes[:, 3] - sboxes[:, 1] + 1.0
        bcx = sboxes[:, 0] + bw / 2
        bcy = sboxes[:, 1] + bh / 2
        gw = sgt[:, 2] - sgt[:, 0] + 1.0
        gh = sgt[:, 3] - sgt[:, 1] + 1.0
        gcx = sgt[:, 0] + gw / 2
        gcy = sgt[:, 1] + gh / 2
        deltas = jnp.stack([(gcx - bcx) / bw / weights[0],
                            (gcy - bcy) / bh / weights[1],
                            jnp.log(gw / bw) / weights[2],
                            jnp.log(gh / bh) / weights[3]], -1)

        # expand per class: row j writes its 4 targets at label slot
        col = labels.astype(jnp.int32) * 4
        tgt = jnp.zeros((batch, 4 * class_nums), boxes.dtype)
        w = jnp.zeros((batch, 4 * class_nums), boxes.dtype)
        rows = jnp.arange(batch)
        for d in range(4):
            tgt = tgt.at[rows, col + d].set(
                jnp.where(is_fg, deltas[:, d], 0.0))
            w = w.at[rows, col + d].set(
                jnp.where(is_fg & (labels > 0), 1.0, 0.0))
        rois_o.append(sboxes * im_info[i, 2])
        labels_o.append(labels)
        tgt_o.append(tgt)
        biw_o.append(w)
        bow_o.append(w)

    uniform = tuple(batch * i for i in range(n + 1))
    ctx.out(op, 'Rois', jnp.concatenate(rois_o, 0))
    ctx.out(op, 'LabelsInt32',
            jnp.concatenate(labels_o).reshape(-1, 1))
    ctx.out(op, 'BboxTargets', jnp.concatenate(tgt_o, 0))
    ctx.out(op, 'BboxInsideWeights', jnp.concatenate(biw_o, 0))
    ctx.out(op, 'BboxOutsideWeights', jnp.concatenate(bow_o, 0))
    for slot in ('Rois', 'LabelsInt32', 'BboxTargets',
                 'BboxInsideWeights', 'BboxOutsideWeights'):
        if op.output(slot):
            ctx.set_lod(op.output(slot)[0], (uniform,))


# ---------------------------------------------------------------------------
# roi_perspective_transform — reference
# operators/detection/roi_perspective_transform_op.cc
# ---------------------------------------------------------------------------

def _in_quad(x, y, qx, qy, eps=1e-4):
    """Vectorized reference in_quad (op.cc:44-85): boundary test with eps
    tolerance + even-odd crossing count. x/y: any shape; qx/qy: (4,)."""
    on_edge = jnp.zeros(x.shape, bool)
    n_cross = jnp.zeros(x.shape, jnp.int32)
    for i in range(4):
        xs, ys = qx[i], qy[i]
        xe, ye = qx[(i + 1) % 4], qy[(i + 1) % 4]
        horiz = jnp.abs(ys - ye) < eps
        lo_x, hi_x = jnp.minimum(xs, xe), jnp.maximum(xs, xe)
        lo_y, hi_y = jnp.minimum(ys, ye), jnp.maximum(ys, ye)
        on_h = horiz & (jnp.abs(y - ys) < eps) & (x >= lo_x - eps) & \
            (x <= hi_x + eps)
        denom = jnp.where(horiz, 1.0, ye - ys)
        ix = (y - ys) * (xe - xs) / denom + xs
        on_e = (~horiz) & (jnp.abs(ix - x) < eps) & (y >= lo_y - eps) & \
            (y <= hi_y + eps)
        on_edge = on_edge | on_h | on_e
        counted = (~horiz) & ~(y < lo_y + eps) & ~(y - hi_y > eps) & \
            (ix - x > eps)
        n_cross = n_cross + counted.astype(jnp.int32)
    return on_edge | (n_cross % 2 == 1)


def _perspective_matrix(qx, qy, tw, th):
    """reference get_transform_matrix (op.cc:109-160): homography mapping
    output (w, h) grid coords to input quad coords, with the normalized
    width/height estimate."""
    x0, x1, x2, x3 = qx[0], qx[1], qx[2], qx[3]
    y0, y1, y2, y3 = qy[0], qy[1], qy[2], qy[3]
    len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
    len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
    len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
    len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    nh = float(th)
    nw = jnp.minimum(jnp.round(est_w * (nh - 1) /
                               jnp.maximum(est_h, 1e-6)) + 1, float(tw))
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1
    den = jnp.where(jnp.abs(den) < 1e-12, 1e-12, den)
    a31 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    a32 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    a21 = (y1 - y0 + a31 * (nw - 1) * y1) / (nw - 1)
    a22 = (y3 - y0 + a32 * (nh - 1) * y3) / (nh - 1)
    a11 = (x1 - x0 + a31 * (nw - 1) * x1) / (nw - 1)
    a12 = (x3 - x0 + a32 * (nh - 1) * x3) / (nh - 1)
    return jnp.stack([a11, a12, x0, a21, a22, y0, a31, a32,
                      jnp.ones_like(a11)])


def _bilinear_at(img, in_w, in_h):
    """reference bilinear_interpolate (op.cc:183-236): img (C, H, W);
    in_w/in_h (th, tw) source coords; zero outside [-0.5, dim-0.5]."""
    c, h, w = img.shape
    oob = (in_w < -0.5) | (in_w > w - 0.5) | (in_h < -0.5) | \
        (in_h > h - 0.5)
    iw = jnp.clip(in_w, 0.0, None)
    ih = jnp.clip(in_h, 0.0, None)
    wf = jnp.clip(jnp.floor(iw), 0, w - 1)
    hf = jnp.clip(jnp.floor(ih), 0, h - 1)
    iw = jnp.where(wf >= w - 1, float(w - 1), iw)
    ih = jnp.where(hf >= h - 1, float(h - 1), ih)
    wc = jnp.clip(wf + 1, 0, w - 1)
    hc = jnp.clip(hf + 1, 0, h - 1)
    w_fl = iw - wf
    h_fl = ih - hf
    wf_i, wc_i = wf.astype(jnp.int32), wc.astype(jnp.int32)
    hf_i, hc_i = hf.astype(jnp.int32), hc.astype(jnp.int32)
    v1 = img[:, hf_i, wf_i]
    v2 = img[:, hc_i, wf_i]
    v3 = img[:, hc_i, wc_i]
    v4 = img[:, hf_i, wc_i]
    val = ((1 - w_fl) * (1 - h_fl) * v1 + (1 - w_fl) * h_fl * v2 +
           w_fl * h_fl * v3 + w_fl * (1 - h_fl) * v4)
    return jnp.where(oob[None], 0.0, val)


@register_op('roi_perspective_transform')
def _roi_perspective_transform(ctx, op):
    """reference operators/detection/roi_perspective_transform_op.cc:
    ROIs (P, 8) quads [x1 y1 x2 y2 x3 y3 x4 y4] -> Out
    (P, C, th, tw) via a per-roi perspective (homography) warp with
    bilinear sampling; points outside the quad emit 0."""
    x = ctx.in1(op, 'X')                        # (N, C, H, W)
    rois = ctx.in1(op, 'ROIs')                  # LoD (P, 8)
    th = int(op.attr('transformed_height', 1))
    tw = int(op.attr('transformed_width', 1))
    scale = float(op.attr('spatial_scale', 1.0))
    lod = ctx.in1_lod(op, 'ROIs')
    from ..core.lod import segment_ids
    if lod:
        img_ids = jnp.asarray(segment_ids(lod[-1]))
    else:
        img_ids = jnp.zeros((rois.shape[0],), jnp.int32)

    qx = rois[:, 0::2] * scale                  # (P, 4)
    qy = rois[:, 1::2] * scale
    ow = jnp.arange(tw, dtype=x.dtype)
    oh = jnp.arange(th, dtype=x.dtype)
    grid_w, grid_h = jnp.meshgrid(ow, oh)       # (th, tw)

    def one_roi(img, qxi, qyi):
        m = _perspective_matrix(qxi, qyi, tw, th)
        wdenom = m[6] * grid_w + m[7] * grid_h + m[8]
        in_w = (m[0] * grid_w + m[1] * grid_h + m[2]) / wdenom
        in_h = (m[3] * grid_w + m[4] * grid_h + m[5]) / wdenom
        val = _bilinear_at(img, in_w, in_h)     # (C, th, tw)
        inside = _in_quad(in_w, in_h, qxi, qyi)
        return jnp.where(inside[None], val, 0.0)

    imgs = jnp.take(x, img_ids, axis=0)         # (P, C, H, W)
    out = jax.vmap(one_roi)(imgs, qx, qy)
    ctx.out(op, 'Out', out.astype(x.dtype))
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], lod)


# ---------------------------------------------------------------------------
# generate_mask_labels — reference
# operators/detection/generate_mask_labels_op.cc (Mask-RCNN mask targets)
# ---------------------------------------------------------------------------

def _poly_mask(points, box, resolution):
    """Rasterize one polygon (V, 2) into an (M, M) {0,1} mask w.r.t. `box`
    [x1 y1 x2 y2] — the capability of reference mask_util.cc Polys2MaskWrtBox
    (COCO RLE rasterization approximated by pixel-center point-in-polygon,
    even-odd rule)."""
    m = resolution
    w = jnp.maximum(box[2] - box[0], 1e-6)
    h = jnp.maximum(box[3] - box[1], 1e-6)
    px = (points[:, 0] - box[0]) * m / w        # (V,)
    py = (points[:, 1] - box[1]) * m / h
    gx = jnp.arange(m, dtype=jnp.float32) + 0.5
    gy = jnp.arange(m, dtype=jnp.float32) + 0.5
    gw, gh = jnp.meshgrid(gx, gy)               # (M, M)
    v = points.shape[0]
    inside = jnp.zeros((m, m), jnp.int32)
    for i in range(v):
        xs, ys = px[i], py[i]
        xe, ye = px[(i + 1) % v], py[(i + 1) % v]
        cond = ((ys > gh) != (ye > gh))
        ix = (gh - ys) * (xe - xs) / jnp.where(
            jnp.abs(ye - ys) < 1e-9, 1e-9, ye - ys) + xs
        inside = inside + (cond & (gw < ix)).astype(jnp.int32)
    return (inside % 2 == 1)


@register_op('generate_mask_labels')
def _generate_mask_labels(ctx, op):
    """reference operators/detection/generate_mask_labels_op.cc
    (SampleMaskForOneImage): for each sampled roi with a fg label, pick the
    gt segmentation whose polygon bounding box overlaps it most, rasterize
    the polygons into a resolution x resolution binary mask in roi
    coordinates, and expand to per-class targets (-1 = ignore).

    TPU deviation (static shapes, same policy as generate_proposal_labels):
    a mask-target row is emitted for EVERY input roi — bg rois carry class
    0 with an all -1 (ignore) target, which is exactly how the reference
    encodes maskless rows (op.cc:226-251 bg path + ExpandMaskTarget
    cls==0). RoiHasMaskInt32 is therefore the identity row map."""
    im_info = ctx.in1(op, 'ImInfo')             # (N, 3)
    gt_classes = ctx.in1(op, 'GtClasses')       # LoD (G, 1) int32
    is_crowd = ctx.in1(op, 'IsCrowd')           # LoD (G, 1) int32
    gt_segms = ctx.in1(op, 'GtSegms')           # LoD-3 (S, 2)
    rois = ctx.in1(op, 'Rois')                  # LoD (R, 4)
    labels = ctx.in1(op, 'LabelsInt32')         # LoD (R, 1) int32
    num_classes = int(op.attr('num_classes'))
    resolution = int(op.attr('resolution'))

    gt_lod = ctx.in1_lod(op, 'GtClasses')
    segm_lod = ctx.in1_lod(op, 'GtSegms')
    roi_lod = ctx.in1_lod(op, 'Rois')
    if not (gt_lod and segm_lod and len(segm_lod) >= 2 and roi_lod):
        raise ValueError("generate_mask_labels needs LoD GtClasses/"
                         "GtSegms(level>=2)/Rois")
    goff = gt_lod[-1]
    roff = roi_lod[-1]
    poly_off = segm_lod[-2]     # per-gt polygon boundaries
    vert_off = segm_lod[-1]     # per-polygon vertex boundaries
    n_img = len(goff) - 1
    msq = resolution * resolution

    out_rows = []
    for im in range(n_img):
        scale = im_info[im, 2]
        g_lo, g_hi = goff[im], goff[im + 1]
        r_lo, r_hi = roff[im], roff[im + 1]
        n_gt = g_hi - g_lo
        n_roi = r_hi - r_lo
        if n_roi == 0:
            continue
        im_rois = rois[r_lo:r_hi] / scale       # (Ri, 4)
        im_labels = labels[r_lo:r_hi].reshape(-1)

        gt_masks, gt_boxes, gt_valid = [], [], []
        for g in range(g_lo, g_hi):
            p_lo, p_hi = poly_off[g], poly_off[g + 1]
            pts_all = []
            mask = jnp.zeros((resolution, resolution), bool)
            box_pts = []
            for p in range(p_lo, p_hi):
                v_lo, v_hi = vert_off[p], vert_off[p + 1]
                pts = gt_segms[v_lo:v_hi]       # (V, 2)
                box_pts.append(pts)
            if box_pts:
                allpts = jnp.concatenate(box_pts, axis=0)
                box = jnp.stack([allpts[:, 0].min(), allpts[:, 1].min(),
                                 allpts[:, 0].max(), allpts[:, 1].max()])
            else:
                box = jnp.zeros((4,), jnp.float32)
            for pts in box_pts:
                mask = mask | _poly_mask(pts, box, resolution)
            gt_masks.append(mask)
            gt_boxes.append(box)
            gt_valid.append((gt_classes[g, 0] > 0) &
                            (is_crowd[g, 0] == 0))
        if gt_masks:
            gm = jnp.stack(gt_masks)            # (Gi, M, M)
            gb = jnp.stack(gt_boxes)            # (Gi, 4)
            gv = jnp.stack(gt_valid)            # (Gi,)
            iou = _iou_matrix(im_rois, gb)      # (Ri, Gi)
            iou = jnp.where(gv[None, :], iou, -1.0)
            best = jnp.argmax(iou, axis=1)      # (Ri,)
            roi_masks = jnp.take(gm, best, axis=0)  # (Ri, M, M)
            # rasterize w.r.t. the roi box, resampled from the gt-box mask:
            # sample grid of the roi in gt-box mask coords
            best_box = jnp.take(gb, best, axis=0)   # (Ri, 4)

            def resample(mask, gtb, roib):
                gw = jnp.maximum(gtb[2] - gtb[0], 1e-6)
                gh = jnp.maximum(gtb[3] - gtb[1], 1e-6)
                xs = (roib[0] + (roib[2] - roib[0]) *
                      (jnp.arange(resolution) + 0.5) / resolution)
                ys = (roib[1] + (roib[3] - roib[1]) *
                      (jnp.arange(resolution) + 0.5) / resolution)
                cx = jnp.clip(((xs - gtb[0]) * resolution / gw).astype(
                    jnp.int32), 0, resolution - 1)
                cy = jnp.clip(((ys - gtb[1]) * resolution / gh).astype(
                    jnp.int32), 0, resolution - 1)
                inx = ((xs >= gtb[0]) & (xs <= gtb[2]))[None, :]
                iny = ((ys >= gtb[1]) & (ys <= gtb[3]))[:, None]
                samp = mask[cy][:, cx]
                return samp & inx & iny
            roi_masks = jax.vmap(resample)(roi_masks, best_box, im_rois)
        else:
            roi_masks = jnp.zeros((n_roi, resolution, resolution), bool)
        fg = im_labels > 0
        flat = roi_masks.reshape(n_roi, msq).astype(jnp.int32)
        oh = jax.nn.one_hot(im_labels, num_classes,
                            dtype=jnp.int32)    # (Ri, K)
        expanded = jnp.where((oh[:, :, None] > 0) & fg[:, None, None],
                             flat[:, None, :], -1)
        out_rows.append(expanded.reshape(n_roi, num_classes * msq))
    mask_int32 = jnp.concatenate(out_rows, axis=0)
    ctx.out(op, 'MaskRois', rois)
    ctx.out(op, 'RoiHasMaskInt32',
            jnp.arange(rois.shape[0], dtype=jnp.int32)[:, None])
    ctx.out(op, 'MaskInt32', mask_int32)
    for slot in ('MaskRois', 'RoiHasMaskInt32', 'MaskInt32'):
        if op.output(slot):
            ctx.set_lod(op.output(slot)[0], (roi_lod[-1],))
