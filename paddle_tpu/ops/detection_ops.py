"""Detection ops (reference operators/detection/, ~25 ops) — stage 7."""

from ..core.registry import register_op
