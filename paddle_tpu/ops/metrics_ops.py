"""Metric ops (reference operators/metrics/accuracy_op.cc, auc_op.cc,
precision_recall_op.cc; operators/positive_negative_pair_op.cc)."""
import jax.numpy as jnp

from ..core.registry import register_op


@register_op('accuracy')
def _accuracy(ctx, op):
    indices = ctx.in1(op, 'Indices')   # (N, k) from top_k
    label = ctx.in1(op, 'Label')       # (N, 1)
    correct = jnp.any(indices == label.astype(indices.dtype), axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = indices.shape[0]
    ctx.out(op, 'Accuracy',
            (num_correct.astype(jnp.float32) / total).reshape(1))
    ctx.out(op, 'Correct', num_correct.reshape(1))
    ctx.out(op, 'Total', jnp.asarray([total], dtype=jnp.int32))


@register_op('auc')
def _auc(ctx, op):
    # streaming AUC with histogram stats, like reference auc_op
    preds = ctx.in1(op, 'Predict')     # (N, 2) [neg, pos] probs
    label = ctx.in1(op, 'Label')       # (N, 1)
    stat_pos_in = ctx.in1(op, 'StatPos')
    stat_neg_in = ctx.in1(op, 'StatNeg')
    num_thresholds = op.attr('num_thresholds', 4095)
    pos_prob = preds[:, -1]
    lab = label.reshape(-1).astype(jnp.int32)
    bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                    num_thresholds)
    one = jnp.ones_like(bins)
    pos_hist = jnp.zeros(num_thresholds + 1, jnp.int64).at[bins].add(
        (lab == 1).astype(jnp.int64))
    neg_hist = jnp.zeros(num_thresholds + 1, jnp.int64).at[bins].add(
        (lab == 0).astype(jnp.int64))
    # stats may arrive [T+1] or [1, T+1] (layers.auc / reference auc_op
    # both use a leading 1) — compute flat, emit in the input's shape
    stat_pos = stat_pos_in.reshape(-1).astype(jnp.int64) + pos_hist
    stat_neg = stat_neg_in.reshape(-1).astype(jnp.int64) + neg_hist
    # AUC by trapezoid over thresholds (descending)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1].astype(jnp.float64)
    tot_neg = fp[-1].astype(jnp.float64)
    tpr = tp.astype(jnp.float64) / jnp.maximum(tot_pos, 1)
    fpr = fp.astype(jnp.float64) / jnp.maximum(tot_neg, 1)
    auc = jnp.trapezoid(tpr, fpr) if hasattr(jnp, 'trapezoid') else \
        jnp.trapz(tpr, fpr)
    ctx.out(op, 'AUC', auc.astype(jnp.float32).reshape(1))
    ctx.out(op, 'StatPosOut', stat_pos.reshape(stat_pos_in.shape))
    ctx.out(op, 'StatNegOut', stat_neg.reshape(stat_neg_in.shape))


@register_op('precision_recall')
def _precision_recall(ctx, op):
    # macro/micro P/R/F1 over classes from max-prob predictions
    preds = ctx.in1(op, 'MaxProbs')
    indices = ctx.in1(op, 'Indices')
    label = ctx.in1(op, 'Labels')
    weights = ctx.in1(op, 'Weights')
    states = ctx.in1(op, 'StatesInfo')
    cls = op.attr('class_number')
    idx = indices.reshape(-1).astype(jnp.int32)
    lab = label.reshape(-1).astype(jnp.int32)
    w = weights.reshape(-1) if weights is not None else jnp.ones_like(
        idx, dtype=jnp.float32)
    tp = jnp.zeros(cls).at[idx].add(jnp.where(idx == lab, w, 0.0))
    fp = jnp.zeros(cls).at[idx].add(jnp.where(idx != lab, w, 0.0))
    fn = jnp.zeros(cls).at[lab].add(jnp.where(idx != lab, w, 0.0))
    new_states = states + jnp.stack(
        [tp, fp, fn, jnp.zeros(cls)], axis=1)
    stp, sfp, sfn = new_states[:, 0], new_states[:, 1], new_states[:, 2]
    prec = stp / jnp.maximum(stp + sfp, 1e-12)
    rec = stp / jnp.maximum(stp + sfn, 1e-12)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    mtp, mfp, mfn = jnp.sum(stp), jnp.sum(sfp), jnp.sum(sfn)
    mprec = mtp / jnp.maximum(mtp + mfp, 1e-12)
    mrec = mtp / jnp.maximum(mtp + mfn, 1e-12)
    mf1 = 2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-12)
    micro = jnp.stack([mprec, mrec, mf1])
    ctx.out(op, 'BatchMetrics', jnp.concatenate([macro, micro]))
    ctx.out(op, 'AccumMetrics', jnp.concatenate([macro, micro]))
    ctx.out(op, 'AccumStatesInfo', new_states)


@register_op('mean_iou')
def _mean_iou(ctx, op):
    pred = ctx.in1(op, 'Predictions').reshape(-1).astype(jnp.int32)
    label = ctx.in1(op, 'Labels').reshape(-1).astype(jnp.int32)
    num_classes = op.attr('num_classes')
    inter = jnp.zeros(num_classes).at[pred].add(
        (pred == label).astype(jnp.float32))
    pred_cnt = jnp.zeros(num_classes).at[pred].add(1.0)
    lab_cnt = jnp.zeros(num_classes).at[label].add(1.0)
    union = pred_cnt + lab_cnt - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    ctx.out(op, 'OutMeanIou', miou.reshape(1))
    ctx.out(op, 'OutWrong', (pred_cnt - inter).astype(jnp.int32))
    ctx.out(op, 'OutCorrect', inter.astype(jnp.int32))
