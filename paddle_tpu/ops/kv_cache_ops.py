"""Device-resident KV-cache ops for the generative decode engine.

The serving-side decode path (serving/generate.py) keeps one pair of
persistable cache buffers per engine, laid out

    [slots, layers, heads, max_len, head_dim]

and compiles exactly TWO program shapes per engine: a per-prompt-bucket
prefill and a single-token decode step. The cache vars are read-AND-written
persistables, so the executor's donation path (PR 1) aliases each step's
updated cache onto the previous buffer — the whole multi-hundred-MB cache
never doubles in HBM and never crosses the host. Three ops make that
expressible in program IR:

- ``kv_cache_prefill``: write a whole prompt's K (or V) rows
  ``[1, H, T, dh]`` into one slot's cache at positions ``0:T`` (the slot id
  is a runtime feed — one compiled prefill serves every slot).
- ``kv_cache_update``: the decode-step write — every slot deposits its new
  token's K (or V) row ``[S, H, dh]`` at its OWN position (a ``[S]`` feed),
  one scatter for the whole in-flight batch.
- ``kv_decode_attention``: one-query attention of every slot against its
  cached keys/values, masked at each slot's current length. Positions past
  a slot's write head carry stale garbage from earlier tenants of the slot;
  the mask zeroes their weights EXACTLY (post-softmax ``where``), so a
  slot's output is bit-identical whatever previously occupied the cache —
  the property the continuous batcher's parity contract
  (tests/test_generate.py) rests on.

All three are slot-row-independent: no op mixes data across the slot axis,
which is what makes admitting/evicting requests at token boundaries safe
while other slots are mid-sequence.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


@register_op('kv_cache_prefill', share_lod=False)
def _kv_cache_prefill(ctx, op):
    """Cache[slot, layer, :, 0:T, :] = New[0]  (T = prompt bucket)."""
    cache = ctx.in1(op, 'Cache')                # [S, Ln, H, M, dh]
    new = ctx.in1(op, 'New')                    # [1, H, T, dh]
    slot = ctx.in1(op, 'Slot').reshape(-1).astype(jnp.int32)
    layer = int(op.attr('layer'))
    upd = new[:, None].astype(cache.dtype)      # [1, 1, H, T, dh]
    zero = jnp.int32(0)
    out = lax.dynamic_update_slice(
        cache, upd, (slot[0], jnp.int32(layer), zero, zero, zero))
    ctx.out(op, 'Out', out)


@register_op('kv_cache_update', share_lod=False)
def _kv_cache_update(ctx, op):
    """Cache[s, layer, :, Positions[s], :] = New[s] for every slot s."""
    cache = ctx.in1(op, 'Cache')                # [S, Ln, H, M, dh]
    new = ctx.in1(op, 'New')                    # [S, H, dh]
    pos = ctx.in1(op, 'Positions').reshape(-1).astype(jnp.int32)
    layer = int(op.attr('layer'))
    s = jnp.arange(cache.shape[0])
    out = cache.at[s, layer, :, pos, :].set(new.astype(cache.dtype))
    ctx.out(op, 'Out', out)


@register_op('kv_decode_attention', share_lod=False)
def _kv_decode_attention(ctx, op):
    """One-query attention per slot over its cached K/V, masked to each
    slot's positions 0..Positions[s] (inclusive: the step's own token was
    just deposited at Positions[s] by kv_cache_update)."""
    q = ctx.in1(op, 'Q')                        # [S, H, dh]
    kc = ctx.in1(op, 'KCache')                  # [S, Ln, H, M, dh]
    vc = ctx.in1(op, 'VCache')
    pos = ctx.in1(op, 'Positions').reshape(-1)  # [S]
    layer = int(op.attr('layer'))
    scale = op.attr('scale', 1.0)
    k = kc[:, layer]                            # [S, H, M, dh]
    v = vc[:, layer]
    scores = jnp.einsum('shd,shmd->shm', q, k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.arange(k.shape[2])[None, None, :] <= pos[:, None, None]
    scores = jnp.where(m, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    # exact zero for masked positions: stale cache rows must contribute
    # 0 * garbage = 0 bit-exactly, not exp(-1e30 - max) * garbage
    w = jnp.where(m, w, 0.0)
    ctx.out(op, 'Out',
            jnp.einsum('shm,shmd->shd', w.astype(v.dtype), v))
