"""Device-resident KV-cache ops for the generative decode engine.

The serving-side decode path (serving/generate.py) keeps one pair of
persistable cache buffers per engine, laid out

    [slots, layers, heads, max_len, head_dim]

and compiles exactly TWO program shapes per engine: a per-prompt-bucket
prefill and a single-token decode step. The cache vars are read-AND-written
persistables, so the executor's donation path (PR 1) aliases each step's
updated cache onto the previous buffer — the whole multi-hundred-MB cache
never doubles in HBM and never crosses the host. Three ops make that
expressible in program IR:

- ``kv_cache_prefill``: write a whole prompt's K (or V) rows
  ``[1, H, T, dh]`` into one slot's cache at positions ``0:T`` (the slot id
  is a runtime feed — one compiled prefill serves every slot).
- ``kv_cache_update``: the decode-step write — every slot deposits its new
  token's K (or V) row ``[S, H, dh]`` at its OWN position (a ``[S]`` feed),
  one scatter for the whole in-flight batch.
- ``kv_decode_attention``: one-query attention of every slot against its
  cached keys/values, masked at each slot's current length. Positions past
  a slot's write head carry stale garbage from earlier tenants of the slot;
  the mask zeroes their weights EXACTLY (post-softmax ``where``), so a
  slot's output is bit-identical whatever previously occupied the cache —
  the property the continuous batcher's parity contract
  (tests/test_generate.py) rests on.

All three are slot-row-independent: no op mixes data across the slot axis,
which is what makes admitting/evicting requests at token boundaries safe
while other slots are mid-sequence.

PAGED variants (PR 12) break the contiguous row-span reservation: the
physical cache is ``[num_blocks, layers, heads, block_size, head_dim]``
and every slot addresses it through a runtime-fed BLOCK TABLE — logical
position ``p`` lives at ``(table[p // block_size], p % block_size)``.
The table is an ordinary feed, so ONE compiled program serves any
allocation pattern (the fixed-signature / zero-recompile contract is
untouched); HBM is committed block-by-block as sequences actually grow,
and requests with a common prompt prefix can point their leading table
entries at the SAME physical blocks (serving/kv_blocks.py refcounts
them, copy-on-write on the first divergent write). Physical block 0 is
reserved as the TRASH block: table filler entries and redirected
pad-row writes land there, so an idle slot's garbage computation can
never scribble over a live block. Masking keeps the exact-zero parity
contract of the contiguous ops: a masked (stale / trash / other-tenant)
position contributes ``0 * garbage = 0`` bit-exactly.

``kv_prefix_attention`` is what makes prefix sharing pay: a prefill
whose leading ``P`` positions are already cached computes only the
SUFFIX rows (queries at global positions ``P..P+T-1``) and attends them
against the block-table cache — prefix K/V are read, never recomputed,
so shared-prefix traffic buckets by suffix length and skips the shared
prefill compute entirely.

``sample_next_token`` is the sampling leg: temperature / top-k / top-p
over the step logits, driven by a HOST-FED per-slot uniform (the
engine owns one PRNG stream per request), so the op is deterministic,
``needs_rng``-free (bind's single-PRNGKey fast path still applies), and
``temperature == 0`` rows take the bitwise argmax branch — greedy stays
the bitwise default.

SPECULATIVE-DECODE ops (PR 13) widen the per-slot decode step from one
token to a window of ``W = spec_k + 1`` tokens so a target model can
VERIFY a draft model's K proposals in one batched dispatch:

- ``kv_cache_update_span_paged``: every slot deposits W new K (or V)
  rows at its own W positions through its block table — the wide
  sibling of ``kv_cache_update_paged``. A per-row ``Valid`` feed
  redirects rows the host has not budgeted (idle slots, positions at or
  past ``max_len``, positions past the slot's allocated blocks) to the
  trash block: a speculative write may be THROWN AWAY later, but it
  must never be able to scribble a live block it doesn't own.
- ``kv_verify_attention_paged``: W-query attention of every slot
  against its block-table cache, each query row (s, t) masked to
  positions ``<= Positions[s, t]`` — row t attends the cached history
  plus the window rows at or before it (deposited by the span write
  just above), exactly the causal view the plain decode step would have
  had at that position. The exact-zero post-softmax mask keeps the
  bitwise contract: verify logits for position p equal the plain
  decode step's logits at p, which is what lets the engine accept draft
  tokens with NO numeric drift from non-speculative greedy decode.

Speculative ROLLBACK needs no op at all: rejected rows sit at positions
strictly past the slot's accepted write head, where the position mask
already zeroes them, and the engine returns their tail blocks to the
allocator (serving/generate.py) — the block table is the rollback
mechanism, no cache bytes are copied or cleared.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


@register_op('kv_cache_prefill', share_lod=False)
def _kv_cache_prefill(ctx, op):
    """Cache[slot, layer, :, 0:T, :] = New[0]  (T = prompt bucket)."""
    cache = ctx.in1(op, 'Cache')                # [S, Ln, H, M, dh]
    new = ctx.in1(op, 'New')                    # [1, H, T, dh]
    slot = ctx.in1(op, 'Slot').reshape(-1).astype(jnp.int32)
    layer = int(op.attr('layer'))
    upd = new[:, None].astype(cache.dtype)      # [1, 1, H, T, dh]
    zero = jnp.int32(0)
    out = lax.dynamic_update_slice(
        cache, upd, (slot[0], jnp.int32(layer), zero, zero, zero))
    ctx.out(op, 'Out', out)


@register_op('kv_cache_update', share_lod=False)
def _kv_cache_update(ctx, op):
    """Cache[s, layer, :, Positions[s], :] = New[s] for every slot s."""
    cache = ctx.in1(op, 'Cache')                # [S, Ln, H, M, dh]
    new = ctx.in1(op, 'New')                    # [S, H, dh]
    pos = ctx.in1(op, 'Positions').reshape(-1).astype(jnp.int32)
    layer = int(op.attr('layer'))
    s = jnp.arange(cache.shape[0])
    out = cache.at[s, layer, :, pos, :].set(new.astype(cache.dtype))
    ctx.out(op, 'Out', out)


@register_op('kv_decode_attention', share_lod=False)
def _kv_decode_attention(ctx, op):
    """One-query attention per slot over its cached K/V, masked to each
    slot's positions 0..Positions[s] (inclusive: the step's own token was
    just deposited at Positions[s] by kv_cache_update)."""
    q = ctx.in1(op, 'Q')                        # [S, H, dh]
    kc = ctx.in1(op, 'KCache')                  # [S, Ln, H, M, dh]
    vc = ctx.in1(op, 'VCache')
    pos = ctx.in1(op, 'Positions').reshape(-1)  # [S]
    layer = int(op.attr('layer'))
    scale = op.attr('scale', 1.0)
    k = kc[:, layer]                            # [S, H, M, dh]
    v = vc[:, layer]
    scores = jnp.einsum('shd,shmd->shm', q, k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.arange(k.shape[2])[None, None, :] <= pos[:, None, None]
    scores = jnp.where(m, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    # exact zero for masked positions: stale cache rows must contribute
    # 0 * garbage = 0 bit-exactly, not exp(-1e30 - max) * garbage
    w = jnp.where(m, w, 0.0)
    ctx.out(op, 'Out',
            jnp.einsum('shm,shmd->shd', w.astype(v.dtype), v))


# ---------------------------------------------------------------------------
# paged (block-table) variants


def _block_of(table, pos, block_size):
    """(block id, in-block offset) of logical position(s) `pos` through a
    1-D block table. Out-of-range table indices clip to the last entry;
    unallocated entries hold 0 — the trash block — so a wild position can
    only ever touch trash."""
    idx = jnp.clip(pos // block_size, 0, table.shape[0] - 1)
    return table[idx].astype(jnp.int32), (pos % block_size).astype(jnp.int32)


@register_op('kv_cache_prefill_paged', share_lod=False)
def _kv_cache_prefill_paged(ctx, op):
    """Cache[table[(P+t)//bs], layer, :, (P+t)%bs, :] = New[0, :, t, :] for
    suffix rows t < Length; rows at or past the real suffix length are
    REDIRECTED to the trash block (a contiguous prefill could park pad
    rows in its own reserved span — a paged slot owns no span, so pad
    garbage must never land in a real block)."""
    cache = ctx.in1(op, 'Cache')                # [NB, Ln, H, bs, dh]
    new = ctx.in1(op, 'New')                    # [1, H, T, dh]
    table = ctx.in1(op, 'BlockTable').reshape(-1).astype(jnp.int32)
    pos = ctx.in1(op, 'Positions').reshape(-1).astype(jnp.int32)  # [T]
    length = ctx.in1(op, 'Length').reshape(-1).astype(jnp.int32)
    layer = int(op.attr('layer'))
    bs = int(op.attr('block_size'))
    rows = jnp.transpose(new[0], (1, 0, 2)).astype(cache.dtype)  # [T,H,dh]
    blk, off = _block_of(table, pos, bs)
    real = jnp.arange(rows.shape[0]) < length[0]
    blk = jnp.where(real, blk, 0)
    off = jnp.where(real, off, 0)
    out = cache.at[blk, layer, :, off, :].set(rows)
    ctx.out(op, 'Out', out)


@register_op('kv_cache_update_paged', share_lod=False)
def _kv_cache_update_paged(ctx, op):
    """Cache[tables[s][Positions[s]//bs], layer, :, Positions[s]%bs, :]
    = New[s] for every slot s. Idle slots feed position 0 against an
    all-zero table row, so their garbage row lands in the trash block.
    An optional per-slot ``Valid`` input ([S] or [S, 1]; nonzero = keep)
    redirects invalid rows to the trash block explicitly — the drafter's
    unrolled steps use it for positions at or past ``max_len``, where
    the clipped table lookup would otherwise target a LIVE block."""
    cache = ctx.in1(op, 'Cache')                # [NB, Ln, H, bs, dh]
    new = ctx.in1(op, 'New')                    # [S, H, dh]
    tables = ctx.in1(op, 'BlockTables').astype(jnp.int32)  # [S, MB]
    pos = ctx.in1(op, 'Positions').reshape(-1).astype(jnp.int32)
    valid = ctx.in1(op, 'Valid')                # optional [S]/[S, 1]
    layer = int(op.attr('layer'))
    bs = int(op.attr('block_size'))
    idx = jnp.clip(pos // bs, 0, tables.shape[1] - 1)
    blk = jnp.take_along_axis(tables, idx[:, None], axis=1)[:, 0]
    off = (pos % bs).astype(jnp.int32)
    if valid is not None:
        keep = valid.reshape(-1) != 0
        blk = jnp.where(keep, blk, 0)
        off = jnp.where(keep, off, 0)
    out = cache.at[blk, layer, :, off, :].set(new.astype(cache.dtype))
    ctx.out(op, 'Out', out)


@register_op('kv_cache_update_span_paged', share_lod=False)
def _kv_cache_update_span_paged(ctx, op):
    """Wide decode-step write: every slot deposits W rows —
    Cache[tables[s][Positions[s,t]//bs], layer, :, Positions[s,t]%bs, :]
    = New[s, :, t, :] for t < W. Rows with ``Valid[s, t] == 0`` (idle
    slots, positions past max_len or past the slot's allocated blocks)
    are redirected to the trash block: a speculative row may later be
    rolled back, but it must never be able to touch a live block the
    slot doesn't own."""
    cache = ctx.in1(op, 'Cache')                # [NB, Ln, H, bs, dh]
    new = ctx.in1(op, 'New')                    # [S, H, W, dh]
    tables = ctx.in1(op, 'BlockTables').astype(jnp.int32)  # [S, MB]
    pos = ctx.in1(op, 'Positions').astype(jnp.int32)       # [S, W]
    valid = ctx.in1(op, 'Valid')                # [S, W]
    layer = int(op.attr('layer'))
    bs = int(op.attr('block_size'))
    idx = jnp.clip(pos // bs, 0, tables.shape[1] - 1)
    blk = jnp.take_along_axis(tables, idx, axis=1)         # [S, W]
    off = (pos % bs).astype(jnp.int32)
    keep = valid.astype(jnp.int32) != 0
    blk = jnp.where(keep, blk, 0)
    off = jnp.where(keep, off, 0)
    rows = jnp.transpose(new, (0, 2, 1, 3)).astype(cache.dtype)  # [S,W,H,dh]
    S, W = pos.shape
    out = cache.at[blk.reshape(-1), layer, :, off.reshape(-1), :].set(
        rows.reshape(S * W, rows.shape[2], rows.shape[3]))
    ctx.out(op, 'Out', out)


@register_op('kv_verify_attention_paged', share_lod=False)
def _kv_verify_attention_paged(ctx, op):
    """W-query attention per slot over its block-table-gathered K/V:
    query row (s, t) sits at global position Positions[s, t] and attends
    every cached position <= Positions[s, t] — the slot's accepted
    history plus the verify window's own rows at or before t (the span
    write above deposited them). Per-row masking makes each row's
    output IDENTICAL to what the single-query decode attention would
    compute at that position, which is the bitwise foundation of
    speculative acceptance; masked (stale / trash / rolled-back) rows
    contribute exact 0."""
    q = ctx.in1(op, 'Q')                        # [S, H, W, dh]
    kc = ctx.in1(op, 'KCache')                  # [NB, Ln, H, bs, dh]
    vc = ctx.in1(op, 'VCache')
    tables = ctx.in1(op, 'BlockTables').astype(jnp.int32)  # [S, MB]
    pos = ctx.in1(op, 'Positions')              # [S, W]
    layer = int(op.attr('layer'))
    scale = op.attr('scale', 1.0)
    bs = int(op.attr('block_size'))
    S, MB = tables.shape
    H, dh = kc.shape[2], kc.shape[4]

    def gather(c):
        # [S, MB, H, bs, dh] -> [S, H, MB*bs, dh] (logical position order)
        g = c[:, layer][tables]
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(S, H, MB * bs, dh)

    k = gather(kc)
    v = gather(vc)
    scores = jnp.einsum('shtd,shmd->shtm', q, k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.arange(MB * bs)[None, None, None, :] <= \
        pos[:, None, :, None]                   # [S, 1, W, M]
    scores = jnp.where(m, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    w = jnp.where(m, w, 0.0)
    ctx.out(op, 'Out',
            jnp.einsum('shtm,shmd->shtd', w.astype(v.dtype), v))


@register_op('kv_decode_attention_paged', share_lod=False)
def _kv_decode_attention_paged(ctx, op):
    """One-query attention per slot over its BLOCK-TABLE-gathered K/V,
    masked to each slot's positions 0..Positions[s] exactly as the
    contiguous op: the gathered logical layout is table order x in-block
    offset, so the mask arithmetic is identical and masked (stale /
    trash / shared-beyond-prefix) rows contribute exact 0."""
    q = ctx.in1(op, 'Q')                        # [S, H, dh]
    kc = ctx.in1(op, 'KCache')                  # [NB, Ln, H, bs, dh]
    vc = ctx.in1(op, 'VCache')
    tables = ctx.in1(op, 'BlockTables').astype(jnp.int32)  # [S, MB]
    pos = ctx.in1(op, 'Positions').reshape(-1)  # [S]
    layer = int(op.attr('layer'))
    scale = op.attr('scale', 1.0)
    bs = int(op.attr('block_size'))
    S, MB = tables.shape
    H, dh = kc.shape[2], kc.shape[4]

    def gather(c):
        # [S, MB, H, bs, dh] -> [S, H, MB*bs, dh] (logical position order)
        g = c[:, layer][tables]
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(S, H, MB * bs, dh)

    k = gather(kc)
    v = gather(vc)
    scores = jnp.einsum('shd,shmd->shm', q, k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.arange(MB * bs)[None, None, :] <= pos[:, None, None]
    scores = jnp.where(m, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    w = jnp.where(m, w, 0.0)
    ctx.out(op, 'Out',
            jnp.einsum('shm,shmd->shd', w.astype(v.dtype), v))


@register_op('kv_prefix_attention', share_lod=False)
def _kv_prefix_attention(ctx, op):
    """Multi-query causal attention of one slot's prefill SUFFIX against
    its block-table cache: query row t sits at global position
    Positions[t] and attends every cached position <= Positions[t] —
    the shared prefix (cached by an earlier request) plus the suffix
    rows the surrounding program just deposited. With no shared prefix
    (Positions starting at 0) this is exactly the causal prefill
    attention, computed from the cache instead of a local K/V copy."""
    q = ctx.in1(op, 'Q')                        # [1, H, T, dh]
    kc = ctx.in1(op, 'KCache')                  # [NB, Ln, H, bs, dh]
    vc = ctx.in1(op, 'VCache')
    table = ctx.in1(op, 'BlockTable').reshape(-1).astype(jnp.int32)
    pos = ctx.in1(op, 'Positions').reshape(-1)  # [T] global query positions
    layer = int(op.attr('layer'))
    scale = op.attr('scale', 1.0)
    bs = int(op.attr('block_size'))
    MB = table.shape[0]
    H, dh = kc.shape[2], kc.shape[4]

    def gather(c):
        # [MB, H, bs, dh] -> [H, MB*bs, dh]
        return jnp.transpose(c[:, layer][table],
                             (1, 0, 2, 3)).reshape(H, MB * bs, dh)

    k = gather(kc)
    v = gather(vc)
    scores = jnp.einsum('htd,hmd->htm', q[0], k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.arange(MB * bs)[None, :] <= pos[:, None]       # [T, M]
    scores = jnp.where(m[None], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    w = jnp.where(m[None], w, 0.0)
    out = jnp.einsum('htm,hmd->htd', w.astype(v.dtype), v)
    ctx.out(op, 'Out', out[None])               # [1, H, T, dh]


@register_op('sample_next_token', share_lod=False)
def _sample_next_token(ctx, op):
    """Per-row temperature / top-k / top-p sampling driven by a host-fed
    uniform U[s] in [0, 1): sort the temperature-scaled distribution
    descending, intersect the top-k and top-p (nucleus) keep sets,
    renormalize, inverse-CDF sample with U. Rows with Temp <= 0 return
    the bitwise argmax (the greedy default); TopK <= 0 disables top-k,
    TopP <= 0 or >= 1 disables nucleus. Deterministic given U — the
    engine owns one host PRNG stream per request, so co-resident slots
    sample independently and a (seed, prompt) pair replays exactly."""
    logits = ctx.in1(op, 'Logits').astype(jnp.float32)     # [S, V]
    temp = ctx.in1(op, 'Temp').reshape(-1)                 # [S]
    topk = ctx.in1(op, 'TopK').reshape(-1).astype(jnp.int32)
    topp = ctx.in1(op, 'TopP').reshape(-1)
    u = ctx.in1(op, 'U').reshape(-1)
    V = logits.shape[1]
    greedy = jnp.argmax(logits, axis=1).astype(jnp.int64)
    t = jnp.where(temp > 0, temp, 1.0)[:, None]
    order = jnp.argsort(-logits, axis=1)                   # stable: ties
    sorted_logits = jnp.take_along_axis(logits / t, order, axis=1)
    probs = jax.nn.softmax(sorted_logits, axis=1)
    ranks = jnp.arange(V)[None, :]
    k_eff = jnp.where(topk > 0, topk, V)[:, None]
    p_on = (topp > 0) & (topp < 1.0)
    p_eff = jnp.where(p_on, topp, 1.0)[:, None]
    cum = jnp.cumsum(probs, axis=1)
    # nucleus keeps the smallest head with mass >= p (the first token
    # always survives); top-k keeps ranks < k; the sets intersect
    keep = (ranks < k_eff) & ((cum - probs < p_eff) | (ranks == 0))
    masked = jnp.where(keep, probs, 0.0)
    mcum = jnp.cumsum(masked, axis=1)
    total = mcum[:, -1:]
    # smallest kept index with cumulative mass > u * total
    j = jnp.sum(mcum <= u[:, None] * total, axis=1)
    j = jnp.minimum(j, jnp.sum(keep, axis=1) - 1)
    sampled = jnp.take_along_axis(order, j[:, None], axis=1)[:, 0]
    out = jnp.where(temp > 0, sampled.astype(jnp.int64), greedy)
    ctx.out(op, 'Out', out)
