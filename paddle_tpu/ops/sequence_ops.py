"""Sequence (ragged/LoD) ops — the reference's variable-length no-padding
differentiator (operators/sequence_ops/, 17 ops; LoD defined at
framework/lod_tensor.h:58), rebuilt for XLA static shapes.

Design (see core/lod.py): LoD offsets are compile-time constants; values are
traced arrays. Each op computes its ragged index maps with numpy at trace
time, so the emitted XLA program contains only static gathers/scatters and
segment reductions — exact reference semantics, no padding waste, and
MXU-friendly downstream shapes.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.lod import (normalize_lod, lengths_from_offsets, segment_ids,
                        lod_from_lengths, context_maps)
from .common import np_dtype


def _last_level(lod):
    if not lod:
        return None
    return lod[-1]


def _require_lod(ctx, op, slot='X'):
    lod = ctx.in1_lod(op, slot)
    if not lod:
        raise ValueError(
            "op %s requires a LoD (ragged) input in slot %s — feed it as "
            "(array, lod) or create_lod_tensor" % (op.type, slot))
    return lod


# ---------------------------------------------------------------------------
# sequence_pool (+ first/last steps) — reference sequence_pool_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_pool')
def _sequence_pool(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    n = len(offsets) - 1
    pooltype = op.attr('pooltype', 'AVERAGE').upper()
    ids = jnp.asarray(segment_ids(offsets))
    lens = np.asarray(lengths_from_offsets(offsets), dtype=np.float32)

    if pooltype in ('SUM', 'AVERAGE', 'SQRT'):
        out = jax.ops.segment_sum(x, ids, num_segments=n)
        if pooltype == 'AVERAGE':
            out = out / jnp.maximum(jnp.asarray(lens), 1.0).reshape(
                (-1,) + (1,) * (out.ndim - 1))
        elif pooltype == 'SQRT':
            out = out / jnp.sqrt(jnp.maximum(jnp.asarray(lens), 1.0)).reshape(
                (-1,) + (1,) * (out.ndim - 1))
    elif pooltype == 'MAX':
        out = jax.ops.segment_max(x, ids, num_segments=n)
        # empty sequences: segment_max yields -inf; reference leaves 0
        out = _zero_empty(out, lens)
    elif pooltype == 'LAST':
        idx = np.maximum(np.asarray(offsets[1:]) - 1, 0)
        out = jnp.take(x, jnp.asarray(idx.astype(np.int32)), axis=0)
        out = _zero_empty(out, lens)
    elif pooltype == 'FIRST':
        idx = np.minimum(np.asarray(offsets[:-1]), max(offsets[-1] - 1, 0))
        out = jnp.take(x, jnp.asarray(idx.astype(np.int32)), axis=0)
        out = _zero_empty(out, lens)
    else:
        raise NotImplementedError("sequence_pool pooltype %r" % pooltype)

    ctx.out(op, 'Out', out)
    # pooling consumes the last lod level (reference: out lod = lod[:-1])
    ctx.set_lod(op.output('Out')[0], lod[:-1])
    if op.output('MaxIndex'):
        if pooltype == 'MAX':
            # first row (within x) attaining the per-segment max — the
            # reference's MaxIndex used by its grad kernel
            rows = jnp.arange(x.shape[0]).reshape(
                (-1,) + (1,) * (x.ndim - 1))
            cand = jnp.where(x == out[ids], rows, x.shape[0])
            midx = jax.ops.segment_min(
                jnp.broadcast_to(cand, x.shape), ids, num_segments=n)
            midx = jnp.where(midx == x.shape[0], 0, midx)
            ctx.out(op, 'MaxIndex', midx.astype(jnp.int32))
        else:
            ctx.out(op, 'MaxIndex',
                    jnp.zeros((n,) + x.shape[1:], jnp.int32))


def _zero_empty(out, lens):
    empty = (lens == 0).reshape((-1,) + (1,) * (out.ndim - 1))
    return jnp.where(jnp.asarray(empty), jnp.zeros_like(out), out)


# ---------------------------------------------------------------------------
# sequence_softmax — reference sequence_ops/sequence_softmax_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_softmax')
def _sequence_softmax(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    n = len(offsets) - 1
    ids = jnp.asarray(segment_ids(offsets))
    # softmax over the rows of each sequence (per trailing feature); the
    # reference restricts X to (T,) / (T,1), this generalizes to (T, ...)
    seg_max = jax.ops.segment_max(x, ids, num_segments=n)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max,
                        jnp.zeros_like(seg_max))
    e = jnp.exp(x - seg_max[ids])
    denom = jax.ops.segment_sum(e, ids, num_segments=n)
    out = e / denom[ids]
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod)


# ---------------------------------------------------------------------------
# sequence_expand / sequence_expand_as — reference sequence_expand_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_expand')
def _sequence_expand(ctx, op):
    x = ctx.in1(op, 'X')
    x_lod = ctx.in1_lod(op, 'X')
    y_lod = _require_lod(ctx, op, 'Y')
    ref_level = op.attr('ref_level', -1)
    if ref_level == -1:
        ref_level = len(y_lod) - 1
    ref = y_lod[ref_level]
    reps = lengths_from_offsets(ref)

    if x_lod:
        x_off = x_lod[0]
    else:
        x_off = tuple(range(x.shape[0] + 1))
    if len(x_off) - 1 != len(reps):
        raise ValueError(
            "sequence_expand: X has %d sequences but Y ref level has %d"
            % (len(x_off) - 1, len(reps)))

    idx = []
    out_lens = []
    for i, rep in enumerate(reps):
        seq = list(range(x_off[i], x_off[i + 1]))
        for _ in range(rep):
            idx.extend(seq)
            if x_lod:
                out_lens.append(len(seq))
    if not idx:
        out = jnp.zeros((0,) + x.shape[1:], x.dtype)
    else:
        out = jnp.take(x, jnp.asarray(np.asarray(idx, np.int32)), axis=0)
    ctx.out(op, 'Out', out)
    if x_lod:
        ctx.set_lod(op.output('Out')[0], lod_from_lengths([out_lens]))


@register_op('sequence_expand_as')
def _sequence_expand_as(ctx, op):
    x = ctx.in1(op, 'X')
    y_lod = _require_lod(ctx, op, 'Y')
    reps = lengths_from_offsets(_last_level(y_lod))
    if x.shape[0] != len(reps):
        raise ValueError(
            "sequence_expand_as: X rows (%d) != Y sequences (%d)"
            % (x.shape[0], len(reps)))
    idx = np.repeat(np.arange(len(reps), dtype=np.int32),
                    np.asarray(reps, np.int32))
    out = jnp.take(x, jnp.asarray(idx), axis=0)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], (tuple(_last_level(y_lod)),))


# ---------------------------------------------------------------------------
# sequence_concat — reference sequence_ops/sequence_concat_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_concat')
def _sequence_concat(ctx, op):
    names = op.input('X')
    xs = [ctx.get(n) for n in names]
    offs = []
    for n in names:
        lod = ctx.lods.get(n, ())
        if not lod:
            raise ValueError("sequence_concat input %r has no LoD" % n)
        offs.append(_last_level(lod))
    n_seq = len(offs[0]) - 1
    if any(len(o) - 1 != n_seq for o in offs):
        raise ValueError("sequence_concat inputs disagree on sequence count")

    total = jnp.concatenate(xs, axis=0)
    bases = np.cumsum([0] + [x.shape[0] for x in xs])
    idx = []
    out_lens = []
    for i in range(n_seq):
        ln = 0
        for k, off in enumerate(offs):
            idx.extend(range(bases[k] + off[i], bases[k] + off[i + 1]))
            ln += off[i + 1] - off[i]
        out_lens.append(ln)
    out = jnp.take(total, jnp.asarray(np.asarray(idx, np.int32)), axis=0)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod_from_lengths([out_lens]))


# ---------------------------------------------------------------------------
# sequence_slice — reference sequence_ops/sequence_slice_op.cc
# Offset/Length are shape-bearing: bound statically.
# ---------------------------------------------------------------------------

@register_op('sequence_slice', static_inputs=('Offset', 'Length'))
def _sequence_slice(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    off = np.asarray(ctx.in1_static(op, 'Offset')).reshape(-1).astype(np.int64)
    length = np.asarray(ctx.in1_static(op, 'Length')).reshape(-1) \
        .astype(np.int64)
    n = len(offsets) - 1
    if off.size != n or length.size != n:
        raise ValueError("sequence_slice: Offset/Length must have one entry "
                         "per sequence")
    idx = []
    for i in range(n):
        start = offsets[i] + int(off[i])
        idx.extend(range(start, start + int(length[i])))
    out = jnp.take(x, jnp.asarray(np.asarray(idx, np.int32)), axis=0) \
        if idx else jnp.zeros((0,) + x.shape[1:], x.dtype)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0],
                lod_from_lengths([[int(l) for l in length]]))


# ---------------------------------------------------------------------------
# sequence_reshape — reference sequence_ops/sequence_reshape_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_reshape')
def _sequence_reshape(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    new_dim = int(op.attr('new_dim'))
    dim = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
    offsets = _last_level(lod)
    out_lens = []
    for ln in lengths_from_offsets(offsets):
        total = ln * dim
        if total % new_dim:
            raise ValueError(
                "sequence_reshape: sequence of %d elements not divisible by "
                "new_dim %d" % (total, new_dim))
        out_lens.append(total // new_dim)
    out = x.reshape(-1, new_dim)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod_from_lengths([out_lens]))


# ---------------------------------------------------------------------------
# sequence_pad / sequence_unpad — reference sequence_pad_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_pad')
def _sequence_pad(ctx, op):
    x = ctx.in1(op, 'X')
    pad_value = ctx.in1(op, 'PadValue')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    lens = lengths_from_offsets(offsets)
    n = len(lens)
    maxlen = max(lens) if lens else 0
    padded_length = int(op.attr('padded_length', -1))
    if padded_length == -1:
        padded_length = maxlen
    if padded_length < maxlen:
        raise ValueError("sequence_pad: padded_length %d < longest sequence "
                         "%d" % (padded_length, maxlen))
    step_shape = x.shape[1:]

    # gather map: (n, padded_length) row indices; invalid -> 0 + masked
    idx = np.zeros((n, padded_length), dtype=np.int32)
    mask = np.zeros((n, padded_length), dtype=bool)
    for i in range(n):
        ln = lens[i]
        idx[i, :ln] = np.arange(offsets[i], offsets[i + 1])
        mask[i, :ln] = True
    gathered = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0) \
        .reshape((n, padded_length) + step_shape)
    if pad_value.size > 0:
        pv = jnp.broadcast_to(pad_value.astype(x.dtype),
                              (n, padded_length) + step_shape)
    else:
        pv = jnp.zeros_like(gathered)
    m = jnp.asarray(mask).reshape((n, padded_length) + (1,) * len(step_shape))
    out = jnp.where(m, gathered, pv)
    ctx.out(op, 'Out', out)
    ctx.out(op, 'Length', jnp.asarray(np.asarray(lens, np.int64)))
    if op.output('Length'):
        # Length is a pure function of the static LoD: expose it statically
        # so sequence_unpad (static_inputs=('Length',)) composes with pad
        ctx.set_static(op.output('Length')[0], np.asarray(lens, np.int64))


@register_op('sequence_unpad', static_inputs=('Length',))
def _sequence_unpad(ctx, op):
    x = ctx.in1(op, 'X')              # (n, pad_len, ...)
    lens = np.asarray(ctx.in1_static(op, 'Length')).reshape(-1) \
        .astype(np.int64)
    n, pad_len = x.shape[0], x.shape[1]
    idx = []
    for i in range(int(n)):
        ln = int(min(lens[i], pad_len))
        idx.extend(i * pad_len + j for j in range(ln))
    flat = x.reshape((n * pad_len,) + x.shape[2:])
    out = jnp.take(flat, jnp.asarray(np.asarray(idx, np.int32)), axis=0) \
        if idx else jnp.zeros((0,) + x.shape[2:], x.dtype)
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0],
                lod_from_lengths([[int(l) for l in lens]]))


# ---------------------------------------------------------------------------
# sequence_reverse — reference sequence_ops/sequence_reverse_op.h
# ---------------------------------------------------------------------------

@register_op('sequence_reverse')
def _sequence_reverse(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    idx = np.arange(x.shape[0], dtype=np.int32)
    for i in range(len(offsets) - 1):
        idx[offsets[i]:offsets[i + 1]] = \
            idx[offsets[i]:offsets[i + 1]][::-1]
    out = jnp.take(x, jnp.asarray(idx), axis=0)
    ctx.out(op, 'Y', out)
    ctx.set_lod(op.output('Y')[0], lod)


# ---------------------------------------------------------------------------
# sequence_enumerate — reference sequence_ops/sequence_enumerate_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_enumerate')
def _sequence_enumerate(ctx, op):
    x = ctx.in1(op, 'X')
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    win = int(op.attr('win_size'))
    pad = op.attr('pad_value', 0)
    t = x.shape[0]
    flat = x.reshape(-1)

    idx = np.zeros((t, win), dtype=np.int32)
    valid = np.zeros((t, win), dtype=bool)
    for s in range(len(offsets) - 1):
        for p in range(offsets[s], offsets[s + 1]):
            for j in range(win):
                if p + j < offsets[s + 1]:
                    idx[p, j] = p + j
                    valid[p, j] = True
    vals = jnp.take(flat, jnp.asarray(idx.reshape(-1))).reshape(t, win)
    out = jnp.where(jnp.asarray(valid), vals,
                    jnp.full((t, win), pad, dtype=x.dtype))
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod)


# ---------------------------------------------------------------------------
# sequence_erase — reference sequence_ops/sequence_erase_op.cc
# output size depends on the *data*, so X is shape-bearing (static).
# ---------------------------------------------------------------------------

@register_op('sequence_erase', static_inputs=('X',))
def _sequence_erase(ctx, op):
    x_np = np.asarray(ctx.in1_static(op, 'X'))
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    tokens = set(int(t) for t in op.attr('tokens', []))
    flat = x_np.reshape(-1)
    kept = []
    out_lens = []
    for i in range(len(offsets) - 1):
        cnt = 0
        for p in range(offsets[i], offsets[i + 1]):
            if int(flat[p]) not in tokens:
                kept.append(flat[p])
                cnt += 1
        out_lens.append(cnt)
    out_np = np.asarray(kept, dtype=x_np.dtype).reshape(
        (-1,) + x_np.shape[1:])
    ctx.out(op, 'Out', jnp.asarray(out_np))
    ctx.set_lod(op.output('Out')[0], lod_from_lengths([out_lens]))


# ---------------------------------------------------------------------------
# sequence_scatter — reference sequence_ops/sequence_scatter_op.cc
# ---------------------------------------------------------------------------

@register_op('sequence_scatter', share_lod=False)
def _sequence_scatter(ctx, op):
    x = ctx.in1(op, 'X')          # (n, d)
    ids = ctx.in1(op, 'Ids')      # lod (t, 1) int
    upd = ctx.in1(op, 'Updates')  # lod (t,)
    lod = _require_lod(ctx, op, 'Ids')
    offsets = _last_level(lod)
    n = len(offsets) - 1
    if x.shape[0] != n:
        raise ValueError("sequence_scatter: X rows must equal Ids sequences")
    rows = jnp.asarray(segment_ids(offsets))      # (t,)
    cols = ids.reshape(-1).astype(jnp.int32)
    out = x.at[rows, cols].add(upd.reshape(-1).astype(x.dtype))
    ctx.out(op, 'Out', out)


# ---------------------------------------------------------------------------
# sequence_conv — reference sequence_ops/sequence_conv_op.cc +
# operators/math/context_project.h (im2col over ragged context windows)
# ---------------------------------------------------------------------------

@register_op('sequence_conv')
def _sequence_conv(ctx, op):
    x = ctx.in1(op, 'X')          # (t, d)
    filt = ctx.in1(op, 'Filter')  # (context_length*d, out_d)
    lod = _require_lod(ctx, op)
    offsets = _last_level(lod)
    ctx_len = int(op.attr('contextLength'))
    ctx_start = int(op.attr('contextStart', -(ctx_len // 2)))
    stride = int(op.attr('contextStride', 1))
    if stride != 1:
        raise NotImplementedError("sequence_conv contextStride must be 1 "
                                  "(reference enforces the same)")
    t, d = x.shape

    idx, valid = context_maps(offsets, ctx_len, ctx_start)
    ctx_mat = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0) \
        .reshape(t, ctx_len, d)
    ctx_mat = ctx_mat * jnp.asarray(valid)[:, :, None].astype(x.dtype)

    pad_names = op.input('PaddingData')
    if pad_names and op.attr('paddingTrainable', False):
        pad_data = ctx.get(pad_names[0])   # (up+down, d)
        up = max(0, -ctx_start)
        down = max(0, ctx_start + ctx_len - 1)
        rows, cols, pidx = [], [], []
        for s in range(len(offsets) - 1):
            lo, hi = offsets[s], offsets[s + 1]
            for p in range(lo, hi):
                for j in range(ctx_len):
                    q = p + ctx_start + j
                    if q < lo and up:
                        rows.append(p); cols.append(j)
                        pidx.append(q - lo + up)
                    elif q >= hi and down:
                        rows.append(p); cols.append(j)
                        pidx.append(up + q - hi)
        if rows:
            pad_rows = jnp.take(pad_data,
                                jnp.asarray(np.asarray(pidx, np.int32)),
                                axis=0)
            ctx_mat = ctx_mat.at[jnp.asarray(np.asarray(rows, np.int32)),
                                 jnp.asarray(np.asarray(cols, np.int32))] \
                .add(pad_rows.astype(x.dtype))

    out = ctx_mat.reshape(t, ctx_len * d) @ filt
    ctx.out(op, 'Out', out)
    ctx.set_lod(op.output('Out')[0], lod)


# ---------------------------------------------------------------------------
# lod_reset — reference lod_reset_op.cc
# ---------------------------------------------------------------------------

@register_op('lod_reset')
def _lod_reset(ctx, op):
    # NOTE: Y is deliberately NOT in static_inputs — the common pattern is
    # "copy Y's LoD", which needs only Y's static lod. Binding Y's data
    # statically would key the program cache on the batch contents and
    # recompile every step. The offsets-as-values form falls back to
    # static_value, which works for trace-time constants.
    x = ctx.in1(op, 'X')
    y_names = op.input('Y')
    if y_names:
        y_lod = ctx.lods.get(y_names[0], ())
        if y_lod:
            new_lod = (y_lod[-1],)
        else:
            off = np.asarray(ctx.static_value(y_names[0])).reshape(-1)
            new_lod = (tuple(int(v) for v in off),)
    else:
        target = op.attr('target_lod', [])
        new_lod = normalize_lod([list(target)])
    ctx.out(op, 'Out', x)
    ctx.set_lod(op.output('Out')[0], new_lod)


# ---------------------------------------------------------------------------
# sequence_mask — reference sequence_ops/sequence_mask_op.cc (dense lengths)
# ---------------------------------------------------------------------------

@register_op('sequence_mask')
def _sequence_mask(ctx, op):
    x = ctx.in1(op, 'X')
    maxlen = op.attr('maxlen', -1)
    dtype = np_dtype(op.attr('out_dtype', 'int64'))
    if maxlen is None or maxlen < 0:
        raise NotImplementedError(
            "sequence_mask with dynamic maxlen requires static shapes on "
            "TPU; pass maxlen explicitly")
    lens = x.reshape(x.shape + (1,))
    mask = jnp.arange(maxlen) < lens
    ctx.out(op, 'Y', mask.astype(dtype))


@register_op('fused_embedding_seq_pool')
def _fused_embedding_seq_pool(ctx, op):
    """reference operators/fused/fused_embedding_seq_pool_op.cc: embedding
    lookup + per-sequence sum pooling fused — the CTR hot path that never
    materializes the (T, D) lookup table output in HBM. The TPU lowering
    is take + segment_sum, which XLA fuses into one pass."""
    w = ctx.in1(op, 'W')                       # (V, D)
    ids = ctx.in1(op, 'Ids')                   # LoD (T, 1) int64
    combiner = op.attr('combiner', 'sum')
    if combiner != 'sum':
        raise NotImplementedError(
            "fused_embedding_seq_pool combiner %r (reference supports only "
            "'sum' too, fused_embedding_seq_pool_op.cc:96-103)" % combiner)
    lod = ctx.in1_lod(op, 'Ids')
    if not lod:
        raise ValueError("fused_embedding_seq_pool requires LoD Ids")
    offsets = lod[-1]
    n = len(offsets) - 1
    seg = segment_ids(offsets)
    emb = jnp.take(w, ids.reshape(-1).astype(jnp.int32), axis=0)  # (T, D)
    out = jax.ops.segment_sum(emb, jnp.asarray(seg), num_segments=n)
    ctx.out(op, 'Out', out)
    if op.output('Out'):
        ctx.set_lod(op.output('Out')[0], ())
