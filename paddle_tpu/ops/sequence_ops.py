"""Sequence (ragged/LoD) ops — placeholder module; full segment-id based
implementations land with the ragged tensor subsystem (stage 6).
Reference: operators/sequence_ops/ (17 ops)."""
import jax.numpy as jnp

from ..core.registry import register_op


@register_op('sequence_mask')
def _sequence_mask(ctx, op):
    x = ctx.in1(op, 'X')
    maxlen = op.attr('maxlen', -1)
    from .common import np_dtype
    dtype = np_dtype(op.attr('out_dtype', 'int64'))
    if maxlen is None or maxlen < 0:
        raise NotImplementedError(
            "sequence_mask with dynamic maxlen requires static shapes on "
            "TPU; pass maxlen explicitly")
    lens = x.reshape(x.shape + (1,))
    mask = jnp.arange(maxlen) < lens
    ctx.out(op, 'Y', mask.astype(dtype))
