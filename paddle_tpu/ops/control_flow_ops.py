"""Control-flow ops: while / conditional_block / recurrent (Static & Dynamic
RNN) / TensorArray ops / beam search — lowered to lax.while_loop, lax.cond and
lax.scan, the XLA-traceable equivalents of the reference's sub-block
interpreters.

Reference semantics (studied, not ported):
- while_op.cc:50,125 — runs its sub-block repeatedly via a nested Executor
  with one StepScope per iteration while a bool Condition var is true; vars
  of the parent scope modified in the block persist across iterations.
  TPU design: the "scope delta" (vars written by the block that already live
  in the parent env, plus every TensorArray touched) becomes the
  lax.while_loop carry pytree; everything else is closed over read-only.
- conditional_block_op.cc:72 — runs the block iff its (scalar) condition is
  true. TPU design: lax.cond over the written-vars carry; the false branch
  is identity, so only vars that pre-exist in the parent env may be written
  (the reference's Switch/IfElse usage — assigning pre-created vars like a
  learning-rate global — satisfies this).
- recurrent_op.cc — StaticRNN: per-step sub-block over time-major inputs
  with boot memories; lowered to lax.scan (MXU-batched per step).
  DynamicRNN additionally handles ragged LoD batches; the reference sorts by
  length and shrinks the batch (lod_rank_table + shrink_rnn_memory); on TPU
  we keep a static [N] batch and mask finished rows — identical math, XLA
  static shapes.
- tensor_array_read_write_op.cc (write_to_array/read_from_array),
  lod_array_length, tensor_array_to_tensor_op.cc, lod_tensor_to_array /
  array_to_lod_tensor (split rows per lod_rank_table) — TensorArray pytree
  in core/tensor_array.py.
- beam_search_op.cc / beam_search_decode_op.cc — LoD-encoded beams; our
  TPU-native design keeps a dense [batch*beam] layout (scores masked with
  -inf for dead lanes) and backtracks parent pointers with a reverse scan.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.tensor_array import TensorArray
from ..core.lod import lengths_from_offsets
from .rnn_ops import _padded_maps, _to_padded, _to_ragged


class EmptyTensorArray(object):
    """Placeholder for `create_array` before the first write: elem shape is
    unknown until a value is written. Writes during an abstract probe trace
    record shape/dtype (python side effect) so loop carries can be
    materialized with the right structure."""

    def __init__(self, capacity):
        self.capacity = int(capacity)
        self.elem_shape = None
        self.dtype = None

    def materialize(self):
        if self.elem_shape is None:
            raise ValueError(
                "TensorArray read/stacked before any write — write to it "
                "first (write_to_array) so its element shape is known")
        return TensorArray.empty(self.capacity, self.elem_shape, self.dtype)

    def record(self, value):
        self.elem_shape = tuple(value.shape)
        self.dtype = value.dtype


def _sub_block(ctx, op, attr='sub_block'):
    return ctx.program.block(int(op.attr(attr)))


def _bind_parent_declared(ctx, written):
    """Vars a block writes that are declared in the parent block but not yet
    bound in the env: materialize a zero init from the declared shape/dtype
    so the write is carried (reference: create var in parent, first assign
    inside the block). Unknowable shapes raise instead of silently dropping
    the write (ADVICE round 1)."""
    for n in sorted(written):
        if ctx.has(n):
            continue
        var = ctx.block._find_var_recursive(n)
        if var is None or getattr(var, 'persistable', False):
            continue  # block-local temporary (declared in sub-block) or state
        shape = getattr(var, 'shape', None)
        dtype = getattr(var, 'dtype', None)
        if shape is None or dtype is None or any(
                d is None or int(d) < 0 for d in shape):
            raise ValueError(
                "variable %r is declared in the parent block and first "
                "written inside a control-flow block, but its shape/dtype "
                "(%s, %s) is not fully known — assign it an initial value "
                "in the parent block first" % (n, shape, dtype))
        ctx.env[n] = jnp.zeros(tuple(int(d) for d in shape), dtype=dtype)


def _written_names(program, block, acc=None):
    """All var names any op in `block` (or nested sub-blocks) writes."""
    if acc is None:
        acc = set()
    from ..framework import SUB_BLOCK_ATTRS
    for op in block.ops:
        for n in op.output_arg_names:
            acc.add(n)
        for a in SUB_BLOCK_ATTRS:
            try:
                idx = op.attr(a)
            except Exception:
                idx = None
            if idx is not None:
                _written_names(program, program.block(int(idx)), acc)
    return acc


def _touched_arrays(ctx, block):
    """Names of TensorArray/placeholder vars in the parent env that ops of
    the block touch (read or write) — they must ride in the carry."""
    names = set()
    for op in block.ops:
        for n in list(op.input_arg_names) + list(op.output_arg_names):
            if ctx.has(n) and isinstance(
                    ctx.env[n], (TensorArray, EmptyTensorArray)):
                names.add(n)
    return names


def _materialize_empties(ctx, block, carried, run_probe):
    """Replace EmptyTensorArray placeholders that the loop body writes with
    concrete zero-filled TensorArrays, discovering element shapes via an
    abstract probe trace of the body (jax.eval_shape → no ops emitted)."""
    empties = [n for n in carried
               if isinstance(ctx.env.get(n), EmptyTensorArray)]
    if not empties:
        return
    try:
        jax.eval_shape(run_probe)
    except ValueError:
        # probe may fail on reads of not-yet-written arrays mid-block; any
        # placeholder that did get recorded is still materialized below
        pass
    for n in empties:
        ph = ctx.env[n]
        if ph.elem_shape is not None:
            ctx.env[n] = ph.materialize()
        else:
            # never written in the loop: drop from carry by materializing a
            # 1-element float buffer (kept structurally stable)
            ctx.env[n] = TensorArray.empty(ph.capacity, (1,), 'float32')


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

@register_op('while', stateful=True)
def _while(ctx, op):
    from ..core.lowering import lower_ops
    block = _sub_block(ctx, op)
    cond_name = op.input('Condition')[0]

    written = _written_names(ctx.program, block)
    _bind_parent_declared(ctx, written)
    carried = sorted(n for n in written if ctx.has(n))
    carried += sorted(_touched_arrays(ctx, block) - set(carried))
    if cond_name not in carried:
        raise ValueError(
            "while: condition %r is never updated inside the loop body — "
            "the loop would not terminate" % cond_name)

    def run_body(carry):
        env2 = dict(ctx.env)
        env2.update(carry)
        sub = ctx.child(env2, block=block)
        lower_ops(sub, block.ops, 0, len(block.ops))
        return {n: env2[n] for n in carried}

    _materialize_empties(
        ctx, block, carried,
        lambda: run_body({n: ctx.env[n] for n in carried}))

    init = {n: ctx.env[n] for n in carried}
    # dtype/weak-type stabilization: one abstract round-trip so the carry in
    # and out of the body agree (e.g. python-int increments promoting)
    out_shapes = jax.eval_shape(run_body, init)
    init = {n: jnp.asarray(v, out_shapes[n].dtype)
            if not isinstance(v, TensorArray) else v.clear_static()
            for n, v in init.items()}

    def cond_fn(carry):
        return jnp.reshape(jnp.asarray(carry[cond_name], bool), ())

    # Under the backward meta-op (ctx.wrt nonempty) lax.while_loop has no
    # reverse-mode rule (reference supports while_grad, while_op.cc:125);
    # lower to a bounded lax.scan with an active-mask instead. The bound
    # comes from While(max_trip_count=...) or, failing that, the smallest
    # capacity of a carried TensorArray (loops that write one slot per
    # iteration cannot exceed it).
    bound = op.attr('max_trip_count', None)
    if ctx.wrt:
        if bound is None:
            # infer only from arrays the body WRITES (a read-only array's
            # capacity says nothing about the trip count); loops appending
            # one slot per iteration cannot exceed the capacity. Loops that
            # overwrite a fixed slot should pass max_trip_count explicitly.
            caps = [v.capacity for n, v in init.items()
                    if isinstance(v, TensorArray) and n in written]
            bound = min(caps) if caps else None
        if bound is None:
            raise ValueError(
                "while inside a differentiated (training) program needs a "
                "static trip-count bound for reverse-mode AD: pass "
                "layers.While(cond, max_trip_count=N) or carry a "
                "TensorArray whose capacity bounds the loop")

        def scan_step(carry, _):
            new = lax.cond(cond_fn(carry), run_body, lambda c: c, carry)
            return new, None

        inferred = op.attr('max_trip_count', None) is None
        final, _ = lax.scan(scan_step, init, None, length=int(bound))
        if inferred:
            # An inferred bound (TensorArray capacity) is a heuristic: loops
            # that overwrite a fixed slot, or append past capacity, iterate
            # more times than it. Silent truncation would train on wrong
            # numbers — check the condition actually went false. (A
            # user-passed max_trip_count is an explicit contract and is not
            # checked.) debug.callback needs host-callback support.
            def _check_exhausted(c, _bound=int(bound)):
                if bool(np.any(np.asarray(c))):
                    raise RuntimeError(
                        "while: inferred trip-count bound %d (from TensorArray "
                        "capacity) was too small — the loop condition is still "
                        "true after %d iterations. Pass layers.While(cond, "
                        "max_trip_count=N) with the real bound." %
                        (_bound, _bound))
            try:
                supports_cb = jax.default_backend() in ('cpu', 'tpu', 'gpu')
            except Exception:
                supports_cb = False
            if supports_cb:
                jax.debug.callback(_check_exhausted, final[cond_name])
    else:
        final = lax.while_loop(cond_fn, run_body, init)
    for n in carried:
        ctx.set(n, final[n])


# ---------------------------------------------------------------------------
# conditional_block
# ---------------------------------------------------------------------------

@register_op('conditional_block', stateful=True)
def _conditional_block(ctx, op):
    from ..core.lowering import lower_ops
    block = _sub_block(ctx, op)
    cond_names = op.input('Cond') or op.input('Condition')
    is_scalar = bool(op.attr('is_scalar_condition', True))
    cond_vals = [ctx.get(n) for n in cond_names]
    written = _written_names(ctx.program, block)
    _bind_parent_declared(ctx, written)
    if not is_scalar:
        # reference semantics (conditional_block_op.cc:72): non-scalar mode
        # runs the block iff the Input tensors are non-empty (numel != 0) —
        # a STATIC property under XLA, so the branch resolves at trace time
        # and the block is inlined (or skipped) with no lax.cond round-trip
        if all(int(np.prod(np.shape(c))) != 0 for c in cond_vals):
            exported = {n for n in written if ctx.has(n)}
            exported |= _touched_arrays(ctx, block)
            sub = ctx.child(dict(ctx.env), block=block)
            lower_ops(sub, block.ops, 0, len(block.ops))
            for n in exported:
                if n in sub.env:
                    ctx.set(n, sub.env[n])
        return
    pred = jnp.reshape(jnp.asarray(cond_vals[0], bool), ())

    carried = sorted(n for n in written if ctx.has(n))
    carried += sorted(_touched_arrays(ctx, block) - set(carried))

    def run_body(carry):
        env2 = dict(ctx.env)
        env2.update(carry)
        sub = ctx.child(env2, block=block)
        lower_ops(sub, block.ops, 0, len(block.ops))
        return {n: env2[n] for n in carried}

    _materialize_empties(
        ctx, block, carried,
        lambda: run_body({n: ctx.env[n] for n in carried}))

    init = {n: ctx.env[n] for n in carried}
    out_shapes = jax.eval_shape(run_body, init)
    init = {n: jnp.asarray(v, out_shapes[n].dtype)
            if not isinstance(v, TensorArray) else v.clear_static()
            for n, v in init.items()}

    final = lax.cond(pred, run_body, lambda c: c, init)
    for n in carried:
        ctx.set(n, final[n])


# ---------------------------------------------------------------------------
# recurrent (StaticRNN + DynamicRNN)
# ---------------------------------------------------------------------------

@register_op('recurrent', stateful=True)
def _recurrent(ctx, op):
    from ..core.lowering import lower_ops
    block = _sub_block(ctx, op)
    xs_outer = list(op.input('X'))                 # sequence inputs
    xs_inner = list(op.attr('xs_inner'))           # per-step names in block
    boots = list(op.input('Boot'))                 # initial memories
    pre_names = list(op.attr('pre_names'))         # memory names (read)
    post_names = list(op.attr('post_names'))       # updated memory names
    ys_inner = list(op.attr('ys_inner'))           # step outputs in block
    outs = list(op.output('Out'))                  # stacked outputs
    last_outs = list(op.output('LastMem'))         # final memory values
    is_dynamic = bool(op.attr('is_dynamic', False))
    reverse = bool(op.attr('is_reverse', False))

    if is_dynamic:
        lod = ctx.in1_lod(op, 'X')
        if not lod:
            raise ValueError("DynamicRNN input needs LoD (ragged batch)")
        offsets = lod[-1]
        gidx, sidx, n, maxt = _padded_maps(offsets, reverse=reverse)
        lens = jnp.asarray(
            np.asarray(lengths_from_offsets(offsets), np.int32))
        seqs = [_to_padded(ctx.get(nm), gidx, n, maxt).swapaxes(0, 1)
                for nm in xs_outer]              # [maxT, N, ...]
        steps = maxt
        mask_tn = (jnp.arange(maxt)[:, None] < lens[None, :])  # [maxT, N]
    else:
        seqs = [ctx.get(nm) for nm in xs_outer]  # time-major [T, N, ...]
        steps = seqs[0].shape[0] if seqs else int(op.attr('max_steps', 0))
        mask_tn = None

    init_mems = {p: jnp.asarray(ctx.get(b))
                 for p, b in zip(pre_names, boots)}

    def step(carry, xt):
        xs_t, mask_t = xt
        env2 = dict(ctx.env)
        env2.update(carry)
        env2.update(xs_t)
        sub = ctx.child(env2, block=block)
        lower_ops(sub, block.ops, 0, len(block.ops))
        new_mems = {}
        for p, q in zip(pre_names, post_names):
            new = jnp.asarray(env2[q], carry[p].dtype)
            if mask_t is not None:
                m = mask_t.reshape((-1,) + (1,) * (new.ndim - 1))
                new = jnp.where(m, new, carry[p])
            new_mems[p] = new
        ys = []
        for y in ys_inner:
            v = env2[y]
            if mask_t is not None:
                m = mask_t.reshape((-1,) + (1,) * (v.ndim - 1))
                v = jnp.where(m, v, jnp.zeros_like(v))
            ys.append(v)
        return new_mems, tuple(ys)

    xs_scan = ({nm: s for nm, s in zip(xs_inner, seqs)},
               mask_tn if mask_tn is not None else None)
    final_mems, stacked = lax.scan(step, init_mems, xs_scan, length=steps)

    for i, o in enumerate(outs):
        y = stacked[i]                            # [T, N, ...]
        if is_dynamic:
            y = _to_ragged(y.swapaxes(0, 1), sidx)
            ctx.set(o, y)
            ctx.set_lod(o, (offsets,))
        else:
            ctx.set(o, y)
    for i, o in enumerate(last_outs):
        ctx.set(o, final_mems[pre_names[i]])


@register_op('drnn_boot_memory')
def _drnn_boot_memory(ctx, op):
    """DynamicRNN.memory(shape=, value=): a [num_seqs, *shape] constant
    boot memory — num_seqs comes from the static LoD of the RNN's first
    sequence input (the TPU analog of the reference's batch-ref memory)."""
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("drnn_boot_memory: sequence input has no LoD")
    n = len(lod[-1]) - 1
    shape = [int(s) for s in op.attr('shape')]
    val = float(op.attr('value', 0.0))
    dtype = op.attr('dtype', 'float32')
    ctx.out(op, 'Out', jnp.full([n] + shape, val, dtype=dtype))
    ctx.lod_explicit.add(op.output('Out')[0])


# ---------------------------------------------------------------------------
# TensorArray ops
# ---------------------------------------------------------------------------

@register_op('create_tensor_array', stateful=True)
def _create_tensor_array(ctx, op):
    cap = int(op.attr('capacity', 128))
    ctx.out(op, 'Out', EmptyTensorArray(cap))


@register_op('write_to_array', stateful=True)
def _write_to_array(ctx, op):
    """The array var is the op's Out (same var across writes, reference
    tensor_array_read_write_op.cc): read the current array value from the
    env under the output name, write, rebind."""
    x = ctx.in1(op, 'X')
    i = ctx.in1(op, 'I')
    out_name = op.output('Out')[0]
    arr = ctx.env.get(out_name)
    if isinstance(arr, EmptyTensorArray):
        arr.record(x)
        arr = arr.materialize()
    elif not isinstance(arr, TensorArray):
        ph = EmptyTensorArray(int(op.attr('capacity', 128)))
        ph.record(x)
        arr = ph.materialize()
    i_name = op.input('I')[0]
    static_i = ctx.statics.get(i_name)
    if static_i is not None:
        static_i = int(np.asarray(static_i).reshape(-1)[0])
    ctx.set(out_name, arr.write(i, x, static_i=static_i))


@register_op('read_from_array')
def _read_from_array(ctx, op):
    arr = ctx.in1(op, 'X')
    i = ctx.in1(op, 'I')
    if isinstance(arr, EmptyTensorArray):
        arr = arr.materialize()
    ctx.out(op, 'Out', arr.read(i))


@register_op('lod_array_length')
def _lod_array_length(ctx, op):
    arr = ctx.in1(op, 'X')
    n = arr.length if isinstance(arr, TensorArray) else jnp.asarray(0)
    ctx.out(op, 'Out', jnp.reshape(n, (1,)).astype('int64'))


@register_op('tensor_array_to_tensor')
def _tensor_array_to_tensor(ctx, op):
    """Concatenate/stack exactly the WRITTEN elements (reference
    tensor_array_to_tensor_op.cc concatenates size() tensors, not the
    backing capacity). With a static length the buffer is sliced to it. A
    traced length (array written under a lax.while_loop) cannot produce a
    dynamic output shape under XLA: the documented deviation is a
    capacity-sized output with unwritten slots masked to zero — consumers
    needing the exact extent read OutIndex[0] (= length) at runtime."""
    arr = ctx.in1(op, 'X')
    axis = int(op.attr('axis', 0))
    use_stack = bool(op.attr('use_stack', False))
    if isinstance(arr, EmptyTensorArray):
        arr = arr.materialize()
    static_len = arr.static_length is not None
    if static_len:
        length = int(arr.static_length)
        buf = arr.stack()[:length]                 # [len, ...]
    else:
        length = arr.capacity
        buf = arr.masked_stack()                   # [cap, ...], zeros beyond
    if use_stack:
        out = buf if axis == 0 else jnp.moveaxis(buf, 0, axis)
    else:
        parts = [buf[i] for i in range(length)]
        out = jnp.concatenate(parts, axis=axis) if parts else buf
    # per-element extent along the concat axis, one entry per written element
    extent = buf.shape[1 + axis] if buf.ndim > 1 + axis else 1
    if static_len:
        idx = jnp.full((max(length, 1),), extent, dtype='int32')
    else:
        # dynamic: [length, extent, extent, ...] — OutIndex[0] carries the
        # true element count so downstream can mask
        idx = jnp.full((length,), extent, dtype='int32').at[0].set(
            arr.length.astype('int32'))
    ctx.out(op, 'Out', out)
    ctx.out(op, 'OutIndex', idx)


# -- LoD <-> array glue (static-LoD versions) -------------------------------

@register_op('lod_rank_table')
def _lod_rank_table(ctx, op):
    """Static rank table: sequences sorted by decreasing length. Stored as a
    trace-time constant (set_static) — consumed by max_sequence_len etc."""
    lod = ctx.in1_lod(op, 'X')
    if not lod:
        raise ValueError("lod_rank_table: input has no LoD")
    level = int(op.attr('level', 0))
    lens = lengths_from_offsets(lod[level])
    order = sorted(range(len(lens)), key=lambda i: -lens[i])
    table = np.asarray([(i, lens[i]) for i in order], np.int64)
    name = op.output('Out')[0]
    ctx.set(name, jnp.asarray(table))
    ctx.set_static(name, table)


@register_op('max_sequence_len')
def _max_sequence_len(ctx, op):
    table = ctx.in1_static(op, 'RankTable')
    mx = int(table[0][1]) if len(table) else 0
    ctx.out(op, 'Out', jnp.asarray([mx], dtype='int64'))


@register_op('lod_tensor_to_array', stateful=True)
def _lod_tensor_to_array(ctx, op):
    """Split ragged rows into a TensorArray of per-timestep batches, sorted
    by the rank table (longest first) — reference
    lod_tensor_to_array_op.cc. Static LoD → static gather maps."""
    x = ctx.in1(op, 'X')
    lod = ctx.in1_lod(op, 'X')
    offsets = lod[-1]
    gidx, _, n, maxt = _padded_maps(offsets)
    lens = lengths_from_offsets(offsets)
    order = np.argsort(-np.asarray(lens), kind='stable')
    padded = _to_padded(x, gidx[order], n, maxt)   # [N_sorted, maxT, ...]
    tm = padded.swapaxes(0, 1)                     # [maxT, N, ...]
    ctx.out(op, 'Out', TensorArray(tm, jnp.asarray(maxt, jnp.int32)))
    name = op.output('Out')[0]
    ctx.set_static(name + '@order', np.asarray(order))
    ctx.set_static(name + '@lens', np.asarray(lens))


@register_op('array_to_lod_tensor')
def _array_to_lod_tensor(ctx, op):
    arr = ctx.in1(op, 'X')
    table_name = op.input('RankTable')[0]
    table = np.asarray(ctx.static_value(table_name))
    order = table[:, 0].astype(np.int64)
    lens_sorted = table[:, 1].astype(np.int64)
    tm = arr.stack()                               # [maxT, N, ...]
    padded = tm.swapaxes(0, 1)                     # [N_sorted, maxT, ...]
    lens = np.zeros(len(order), np.int64)
    lens[order] = lens_sorted
    # back to ragged in original sequence order
    parts = []
    inv = {int(o): i for i, o in enumerate(order)}
    for seq in range(len(order)):
        parts.append(padded[inv[seq], :int(lens[seq])])
    out = jnp.concatenate(parts, axis=0)
    ctx.out(op, 'Out', out)
    off = np.concatenate([[0], np.cumsum(lens)])
    ctx.set_lod(op.output('Out')[0], (tuple(int(v) for v in off),))


@register_op('shrink_rnn_memory')
def _shrink_rnn_memory(ctx, op):
    """Reference shrinks the batch as sorted sequences finish; with static
    masking the batch never shrinks — identity (mask handles validity)."""
    ctx.out(op, 'Out', ctx.in1(op, 'X'))


@register_op('reorder_lod_tensor_by_rank')
def _reorder_lod_tensor_by_rank(ctx, op):
    x = ctx.in1(op, 'X')
    table = np.asarray(ctx.in1_static(op, 'RankTable'))
    order = table[:, 0].astype(np.int64)
    lod = ctx.in1_lod(op, 'X')
    if lod:
        offsets = lod[-1]
        rows = np.concatenate(
            [np.arange(offsets[i], offsets[i + 1]) for i in order]
        ) if len(order) else np.zeros((0,), np.int64)
        out = jnp.take(x, jnp.asarray(rows), axis=0)
        lens = lengths_from_offsets(offsets)
        new_lens = [lens[i] for i in order]
        off = np.concatenate([[0], np.cumsum(new_lens)])
        ctx.out(op, 'Out', out)
        ctx.set_lod(op.output('Out')[0], (tuple(int(v) for v in off),))
    else:
        ctx.out(op, 'Out', jnp.take(x, jnp.asarray(order), axis=0))


@register_op('split_lod_tensor')
def _split_lod_tensor(ctx, op):
    """IfElse splitter. TPU design: no dynamic-shape split — both branches
    see the full batch; OutTrue/OutFalse are the input (merge selects by
    mask). Keeps shapes static; identical final results for row-wise
    bodies (the reference IfElse contract)."""
    x = ctx.in1(op, 'X')
    ctx.out(op, 'OutTrue', x)
    ctx.out(op, 'OutFalse', x)


@register_op('merge_lod_tensor')
def _merge_lod_tensor(ctx, op):
    mask = ctx.in1(op, 'Mask')
    t = ctx.in1(op, 'InTrue')
    f = ctx.in1(op, 'InFalse')
    m = jnp.asarray(mask, bool).reshape((-1,) + (1,) * (t.ndim - 1))
    ctx.out(op, 'Out', jnp.where(m, t, f))


# ---------------------------------------------------------------------------
# beam search (dense TPU layout)
# ---------------------------------------------------------------------------

@register_op('beam_search')
def _beam_search(ctx, op):
    """Dense beam-search step. pre_ids/pre_scores: [batch*beam, 1]; ids:
    [batch*beam, K] candidate token ids; scores: [batch*beam, K] accumulated
    log-probs of each candidate (reference beam_search_op.cc semantics with
    accumulated scores). Finished lanes (pre_id == end_id) contribute a
    single survival candidate (end_id, pre_score)."""
    pre_ids = ctx.in1(op, 'pre_ids')
    pre_scores = ctx.in1(op, 'pre_scores')
    ids = ctx.in1(op, 'ids')
    scores = ctx.in1(op, 'scores')
    beam = int(op.attr('beam_size'))
    end_id = int(op.attr('end_id'))

    bw = scores.shape[0]
    k = scores.shape[1]
    batch = bw // beam
    neg_inf = jnp.asarray(-1e9, scores.dtype)

    finished = (pre_ids.reshape(bw) == end_id)
    # finished lanes: candidate 0 = (end_id, pre_score); others -inf
    cand0 = jnp.zeros((bw, k), bool).at[:, 0].set(True)
    scores = jnp.where(finished[:, None],
                       jnp.where(cand0, pre_scores.reshape(bw, 1), neg_inf),
                       scores)
    ids = jnp.where(finished[:, None], end_id, ids)

    flat = scores.reshape(batch, beam * k)
    top_scores, top_idx = lax.top_k(flat, beam)        # [batch, beam]
    parent_beam = top_idx // k                         # [batch, beam]
    batch_base = jnp.arange(batch, dtype=top_idx.dtype)[:, None] * beam
    parent_row = (batch_base + parent_beam).reshape(bw)
    sel_ids = ids.reshape(batch, beam * k)[
        jnp.arange(batch)[:, None], top_idx].reshape(bw, 1)
    ctx.out(op, 'selected_ids', sel_ids.astype('int64'))
    ctx.out(op, 'selected_scores', top_scores.reshape(bw, 1))
    ctx.out(op, 'parent_idx', parent_row.astype('int32'))


@register_op('beam_search_decode')
def _beam_search_decode(ctx, op):
    """Backtrack stored (ids, parents) TensorArrays into full sentences:
    SentenceIds [batch, beam, T] (post-EOS positions filled with end_id),
    SentenceScores [batch, beam]."""
    ids_arr = ctx.in1(op, 'Ids')
    parents_arr = ctx.in1(op, 'Parents')
    scores_arr = ctx.in1(op, 'Scores', None)
    beam = int(op.attr('beam_size'))
    end_id = int(op.attr('end_id'))

    ids_buf = ids_arr.stack()                      # [T, bw, 1] or [T, bw]
    par_buf = parents_arr.stack()                  # [T, bw]
    T = ids_buf.shape[0]
    bw = par_buf.shape[1] if par_buf.ndim > 1 else par_buf.shape[0]
    ids_buf = ids_buf.reshape(T, bw)
    par_buf = par_buf.reshape(T, bw).astype('int32')
    n_steps = ids_arr.length

    def back(carry, xt):
        row = carry                                # [bw] row to follow
        step_ids, step_parents, t = xt
        valid = t < n_steps
        tok = jnp.where(valid, step_ids[row], end_id)
        new_row = jnp.where(valid, step_parents[row], row)
        return new_row, tok

    init_row = jnp.arange(bw, dtype='int32')
    _, toks = lax.scan(
        back, init_row,
        (ids_buf[::-1], par_buf[::-1], jnp.arange(T - 1, -1, -1)))
    sent = toks[::-1].swapaxes(0, 1)               # [bw, T]
    batch = bw // beam
    ctx.out(op, 'SentenceIds',
            sent.reshape(batch, beam, T).astype('int64'))
    if scores_arr is not None and op.output('SentenceScores'):
        sc_buf = scores_arr.stack().reshape(T, bw)
        last = jnp.maximum(n_steps - 1, 0)
        final_scores = lax.dynamic_index_in_dim(sc_buf, last, 0,
                                                keepdims=False)
        ctx.out(op, 'SentenceScores', final_scores.reshape(batch, beam))
