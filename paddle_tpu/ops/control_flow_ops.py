"""Control flow ops — while/conditional_block via lax loops (stage 6).
Reference: operators/controlflow/while_op.cc:50, conditional_block_op.cc:72."""

from ..core.registry import register_op
