"""Operator lowerings: each module registers op type -> jax lowering.

The registry (core/registry.py) replaces the reference's 356 REGISTER_OPERATOR
registrations (see SURVEY Appendix A; paddle/fluid/operators/). Every op here
is a pure jax emission into the whole-program trace — XLA provides the kernel,
fusion, and scheduling that the reference implemented per-op in C++/CUDA.
"""
from . import meta
from . import math_ops
from . import activations
from . import tensor_ops
from . import nn_ops
from . import optimizer_ops
from . import compare_ops
from . import random_ops
from . import metrics_ops
from . import sequence_ops
from . import rnn_ops
from . import control_flow_ops
from . import crf_ctc_ops
from . import detection_ops
from . import vision_ops
from . import quant_ops
from . import misc_ops
from . import attention_ops
from . import ce_ops
from . import ffn_ops
from . import embedding_ops
from . import kernel_tier
from . import kv_cache_ops
from . import fused_ops
from . import dist_ops
from . import pipeline_ops
from . import health_ops
