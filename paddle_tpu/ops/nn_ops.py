"""NN ops: softmax/losses, convolutions, pooling, normalization, resize.

Reference: operators/softmax_op.cc, cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
conv_op.cc (+conv_cudnn), conv_transpose_op.cc, pool_op.cc, batch_norm_op.cc,
layer_norm_op.cc, group_norm_op.cc, data_norm_op.cc, lrn_op.cc,
interpolate_op.cc, affine_channel_op.cc, nce_op.cc, hierarchical_sigmoid_op.cc.

Convs/matmuls use lax.conv_general_dilated / dot so XLA tiles them on the MXU;
bf16 inputs keep fp32 accumulation via preferred_element_type.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import amp
from ..core.registry import register_op


@register_op('softmax')
def _softmax(ctx, op):
    x = ctx.in1(op, 'X')
    ctx.out(op, 'Out', jax.nn.softmax(x, axis=-1))


def _gather_label(x, label):
    lab = label.reshape(-1).astype(jnp.int32)
    return jnp.take_along_axis(x, lab[:, None], axis=-1), lab


@register_op('cross_entropy')
def _cross_entropy(ctx, op):
    x = ctx.in1(op, 'X')           # (N, C) probabilities
    label = ctx.in1(op, 'Label')
    soft_label = op.attr('soft_label', False)
    ignore_index = op.attr('ignore_index', -100)
    xc = jnp.clip(x, 1e-20, 1.0)
    if soft_label:
        out = -jnp.sum(label * jnp.log(xc), axis=-1, keepdims=True)
    else:
        p, lab = _gather_label(xc, label)
        out = -jnp.log(p)
        mask = (lab != ignore_index)[:, None]
        out = jnp.where(mask, out, 0.0)
    ctx.out(op, 'Y', out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ce_hard(logits, lab, ignore_index):
    """Hard-label softmax cross entropy that residualizes ONLY the logits:
    the default AD path saves both logits and log_softmax — for an LM head
    that is two [tokens, vocab] HBM buffers; the analytic gradient
    softmax(x) - onehot needs just one."""
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits.astype(jnp.float32),
                                 lab[:, None], axis=-1)[:, 0]
    loss = lse - picked
    return jnp.where(lab != ignore_index, loss, 0.0)


def _ce_hard_fwd(logits, lab, ignore_index):
    return _ce_hard(logits, lab, ignore_index), (logits, lab)


def _ce_hard_bwd(ignore_index, res, ct):
    logits, lab = res
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(lab, logits.shape[-1], dtype=p.dtype)
    g = (p - onehot) * ct[:, None]
    g = jnp.where((lab != ignore_index)[:, None], g, 0.0)
    return g.astype(logits.dtype), None


_ce_hard.defvjp(_ce_hard_fwd, _ce_hard_bwd)


@register_op('softmax_with_cross_entropy')
def _softmax_with_ce(ctx, op):
    logits = ctx.in1(op, 'Logits')
    label = ctx.in1(op, 'Label')
    soft_label = op.attr('soft_label', False)
    ignore_index = op.attr('ignore_index', -100)
    if soft_label:
        log_sm = jax.nn.log_softmax(logits, axis=-1)
        ctx.out(op, 'Softmax', jnp.exp(log_sm))
        loss = -jnp.sum(label * log_sm, axis=-1, keepdims=True)
        ctx.out(op, 'Loss', loss)
        return
    lab = label.reshape(-1).astype(jnp.int32)
    impl = 'off'
    meshed = False
    if logits.ndim == 2:
        from . import kernel_tier
        from .ce_ops import (fused_softmax_ce, fused_softmax_ce_spmd,
                             pallas_shapes_ok, spmd_shapes_ok)
        from ..parallel.api import get_active_mesh
        mesh = get_active_mesh()
        meshed = mesh is not None and mesh.size > 1
        if meshed:
            # the kernel runs PER SHARD via kernel_tier.partitioned_call
            # (a pallas custom call cannot be auto-partitioned), so the
            # tiling rule applies to the post-partitioning local block
            pallas_ok = spmd_shapes_ok(mesh, logits.shape[0],
                                       logits.shape[1])
        else:
            pallas_ok = pallas_shapes_ok(logits.shape[0], logits.shape[1])
        impl = kernel_tier.dispatch(
            'softmax_with_cross_entropy', pallas_ok=pallas_ok, mesh=mesh,
            count=getattr(ctx, 'sparse_mode', None) != 'scout')
    if impl == 'off':
        loss = _ce_hard(logits, lab, ignore_index)
    elif meshed and impl in ('pallas', 'interpret'):
        # mesh-partitioned kernels: batch rows over 'data' (comms-free),
        # lse-aware all-reduce when 'model' shards the vocab
        loss = fused_softmax_ce_spmd(logits, lab, mesh, ignore_index,
                                     impl)
    else:
        # fused tier (ops/ce_ops.py): online-softmax single pass, backward
        # recomputed from (logits, lse) — no [N, V] one-hot/softmax
        # residual ever materializes. The xla emission is plain jnp, so
        # under a mesh the XLA SPMD partitioner shards it natively.
        loss = fused_softmax_ce(logits, lab, ignore_index, impl)
    ctx.out(op, 'Loss', loss[:, None])
    # the Softmax output only materializes if the program consumes it
    if op.output('Softmax'):
        ctx.out(op, 'Softmax', jax.nn.softmax(logits, axis=-1))


@register_op('sigmoid_cross_entropy_with_logits')
def _sigmoid_ce(ctx, op):
    x = ctx.in1(op, 'X')
    label = ctx.in1(op, 'Label')
    ignore_index = op.attr('ignore_index', -100)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = jnp.where(label == ignore_index, 0.0, loss)
    ctx.out(op, 'Out', loss)


# ---------------------------------------------------------------------------
# Convolution / pooling
# ---------------------------------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register_op('conv2d')
def _conv2d(ctx, op):
    x = ctx.in_nhwc(op, 'Input')   # channels-minor twin (or transposed)
    w = ctx.in1(op, 'Filter')      # OIHW (I = C/groups)
    strides = _pair(op.attr('strides', [1, 1]))
    pads = _pair(op.attr('paddings', [0, 0]))
    dilations = _pair(op.attr('dilations', [1, 1]))
    groups = op.attr('groups', 1) or 1
    out_dtype = x.dtype
    x, w = amp.cast_compute(op, x, w)
    # compute in NHWC: the TPU conv path is an order of magnitude faster
    # with channels-minor layouts (measured 11x on v5e). The output is
    # emitted as a layout twin (out_nhwc): downstream BN/pool/relu/
    # elementwise consume the NHWC value directly, so whole conv stacks
    # stay channels-minor in HBM (measured ~5x again over per-op
    # transpose round-trips) while env keeps the public NCHW contract.
    out = lax.conv_general_dilated(
        x, jnp.transpose(w, (2, 3, 1, 0)),
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
        feature_group_count=groups,
        preferred_element_type=amp.accum_dtype(x))
    ctx.out_nhwc(op, 'Output',
                 out.astype(amp.result_dtype(op, x, out_dtype)))


@register_op('depthwise_conv2d')
def _depthwise_conv2d(ctx, op):
    _conv2d(ctx, op)


@register_op('conv3d')
def _conv3d(ctx, op):
    x = ctx.in1(op, 'Input')       # NCDHW
    w = ctx.in1(op, 'Filter')
    strides = _pair(op.attr('strides', [1, 1, 1]), 3)
    pads = _pair(op.attr('paddings', [0, 0, 0]), 3)
    dilations = _pair(op.attr('dilations', [1, 1, 1]), 3)
    groups = op.attr('groups', 1) or 1
    out_dtype = x.dtype
    x, w = amp.cast_compute(op, x, w)
    # NDHWC internally — same channels-minor win as conv2d
    out = lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 4, 1)),
        jnp.transpose(w, (2, 3, 4, 1, 0)),
        window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dilations,
        dimension_numbers=('NDHWC', 'DHWIO', 'NDHWC'),
        feature_group_count=groups,
        preferred_element_type=amp.accum_dtype(x))
    ctx.out(op, 'Output',
            jnp.transpose(out, (0, 4, 1, 2, 3)).astype(out_dtype))


def _transpose_kernel(w, groups, n_sp):
    """(C_in, C_out/g, k...) deconv filter -> (C_out, C_in/g, k...) conv
    kernel with flipped spatial dims, handling groups (reference
    conv_transpose_op.cc grouped deconvolution)."""
    c_in = w.shape[0]
    c_out_g = w.shape[1]
    sp = w.shape[2:]
    if groups == 1:
        k = jnp.swapaxes(w, 0, 1)
    else:
        k = w.reshape((groups, c_in // groups, c_out_g) + sp)
        k = jnp.swapaxes(k, 1, 2)
        k = k.reshape((groups * c_out_g, c_in // groups) + sp)
    flip = (slice(None), slice(None)) + (slice(None, None, -1),) * n_sp
    return k[flip]


@register_op('conv2d_transpose')
def _conv2d_transpose(ctx, op):
    x = ctx.in1(op, 'Input')       # NCHW
    w = ctx.in1(op, 'Filter')      # (C_in, C_out/groups, kh, kw)
    strides = _pair(op.attr('strides', [1, 1]))
    pads = _pair(op.attr('paddings', [0, 0]))
    dilations = _pair(op.attr('dilations', [1, 1]))
    groups = op.attr('groups', 1) or 1
    kh = (w.shape[2] - 1) * dilations[0] + 1
    kw = (w.shape[3] - 1) * dilations[1] + 1
    out_dtype = x.dtype
    x, w = amp.cast_compute(op, x, w)
    # gradient-of-conv formulation: lhs-dilate input by stride
    out = lax.conv_general_dilated(
        x, _transpose_kernel(w, groups, 2),
        window_strides=(1, 1),
        padding=[(kh - 1 - pads[0], kh - 1 - pads[0]),
                 (kw - 1 - pads[1], kw - 1 - pads[1])],
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
        feature_group_count=groups,
        preferred_element_type=amp.accum_dtype(x))
    ctx.out(op, 'Output', out.astype(out_dtype))


@register_op('depthwise_conv2d_transpose')
def _depthwise_conv2d_transpose(ctx, op):
    _conv2d_transpose(ctx, op)


def _pool(x, ksize, strides, pads, ptype, exclusive, adaptive, global_pool,
          ceil_mode, channels_last=False):
    """Window pooling. channels_last=True pools a channels-minor (NHWC)
    value — the layout-twin path that keeps conv stacks transpose-free."""
    n_sp = len(ksize)
    sp0 = 1 if channels_last else 2         # first spatial axis
    sp_shape = x.shape[sp0:sp0 + n_sp]
    if global_pool:
        ksize = sp_shape
        pads = (0,) * n_sp
        strides = (1,) * n_sp
    if adaptive:
        # adaptive: output size = ksize; use even splits
        out_sz = ksize
        in_sz = sp_shape
        strides = tuple(i // o for i, o in zip(in_sz, out_sz))
        ksize = tuple(i - (o - 1) * s for i, o, s in
                      zip(in_sz, out_sz, strides))
        pads = (0,) * n_sp
    if channels_last:
        window = (1,) + tuple(ksize) + (1,)
        strides_full = (1,) + tuple(strides) + (1,)
        sp_pad = [(p, p) for p in pads]
    else:
        window = (1, 1) + tuple(ksize)
        strides_full = (1, 1) + tuple(strides)
        sp_pad = [(p, p) for p in pads]
    if ceil_mode:
        sp_pad = []
        for i, (p, k, s) in enumerate(zip(pads, ksize, strides)):
            in_dim = sp_shape[i]
            out_dim = -(-(in_dim + 2 * p - k) // s) + 1  # ceil
            needed = (out_dim - 1) * s + k - in_dim - p
            sp_pad.append((p, max(p, needed)))
    pad_full = ([(0, 0)] + sp_pad + [(0, 0)]) if channels_last else \
        ([(0, 0), (0, 0)] + sp_pad)
    if ptype == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides_full,
                                 pad_full)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_full, pad_full)
    if exclusive:
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                strides_full, pad_full)
        return s / cnt
    return s / float(np.prod(ksize))


@register_op('pool2d')
def _pool2d(ctx, op):
    args = (_pair(op.attr('ksize')), _pair(op.attr('strides', [1, 1])),
            _pair(op.attr('paddings', [0, 0])),
            op.attr('pooling_type', 'max'),
            op.attr('exclusive', True), op.attr('adaptive', False),
            op.attr('global_pooling', False), op.attr('ceil_mode', False))
    if ctx.has_nhwc(op, 'X'):
        ctx.out_nhwc(op, 'Out', _pool(ctx.in_nhwc(op, 'X'), *args,
                                      channels_last=True))
    else:
        ctx.out(op, 'Out', _pool(ctx.in1(op, 'X'), *args))


@register_op('pool3d')
def _pool3d(ctx, op):
    x = ctx.in1(op, 'X')
    out = _pool(x, _pair(op.attr('ksize'), 3),
                _pair(op.attr('strides', [1, 1, 1]), 3),
                _pair(op.attr('paddings', [0, 0, 0]), 3),
                op.attr('pooling_type', 'max'),
                op.attr('exclusive', True), op.attr('adaptive', False),
                op.attr('global_pooling', False), op.attr('ceil_mode', False))
    ctx.out(op, 'Out', out)


@register_op('max_pool2d_with_index')
def _max_pool2d_with_index(ctx, op):
    """reference pool_with_index_op.cc: Mask carries real flat argmax
    positions into H*W (consumed by unpool)."""
    from .misc_ops import _pool_with_index
    x = ctx.in1(op, 'X')
    ksize = _pair(op.attr('ksize'))
    strides = _pair(op.attr('strides', [1, 1]))
    pads = _pair(op.attr('paddings', [0, 0]))
    if op.attr('global_pooling', False):
        ksize = x.shape[-2:]
        strides = (1, 1)
        pads = (0, 0)
    vals, mask = _pool_with_index(x, ksize, strides, pads,
                                  adaptive=op.attr('adaptive', False))
    ctx.out(op, 'Out', vals)
    ctx.out(op, 'Mask', mask)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

@register_op('batch_norm')
def _batch_norm(ctx, op):
    # layout-twin path: when the producer left an NHWC twin (conv/pool),
    # normalize channels-minor — stats reduce over leading axes and the
    # affine broadcasts on the minor dim, so the conv stack never
    # materializes NCHW between ops
    twin = ctx.has_nhwc(op, 'X') and ctx.get(op.input('X')[0]).ndim == 4 \
        and op.attr('data_layout', 'NCHW') == 'NCHW'
    x = ctx.in_nhwc(op, 'X') if twin else ctx.in1(op, 'X')
    scale = ctx.in1(op, 'Scale')
    bias = ctx.in1(op, 'Bias')
    mean = ctx.in1(op, 'Mean')
    var = ctx.in1(op, 'Variance')
    x = amp.cast_compute(op, x)
    momentum = op.attr('momentum', 0.9)
    eps = op.attr('epsilon', 1e-5)
    is_test = op.attr('is_test', False)
    layout = 'NHWC' if twin else op.attr('data_layout', 'NCHW')
    use_global = op.attr('use_global_stats', False) or is_test

    if layout == 'NCHW':
        axes = (0,) + tuple(range(2, x.ndim))
        bshape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        bshape = (1,) * (x.ndim - 1) + (-1,)

    if use_global:
        m, v = mean, var
        ctx.out(op, 'MeanOut', mean)
        ctx.out(op, 'VarianceOut', var)
    else:
        # statistics ALWAYS accumulate in f32 (a bf16 mean over ~1e5
        # elements loses precision); running stats stay f32 state.
        # Two-pass mean/var (jnp.var): the one-pass E[x^2]-E[x]^2 form
        # cancels catastrophically for channels with large mean and tiny
        # variance (|m|^2*eps swamps the true variance)
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=axes)
        v = jnp.var(xf, axis=axes)
        ctx.out(op, 'MeanOut',
                momentum * mean + (1.0 - momentum) * lax.stop_gradient(m))
        ctx.out(op, 'VarianceOut',
                momentum * var + (1.0 - momentum) * lax.stop_gradient(v))
    ctx.out(op, 'SavedMean', m)
    ctx.out(op, 'SavedVariance', 1.0 / jnp.sqrt(v + eps))
    xn = (x - m.reshape(bshape)) / jnp.sqrt(v.reshape(bshape) + eps)
    y = xn * scale.reshape(bshape) + bias.reshape(bshape)
    if twin:
        ctx.out_nhwc(op, 'Y', y.astype(x.dtype))
    else:
        ctx.out(op, 'Y', y.astype(x.dtype))


@register_op('layer_norm')
def _layer_norm(ctx, op):
    x = ctx.in1(op, 'X')
    scale = ctx.in1(op, 'Scale')
    bias = ctx.in1(op, 'Bias')
    eps = op.attr('epsilon', 1e-5)
    bna = op.attr('begin_norm_axis', 1)
    axes = tuple(range(bna, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    y = (x - m) / jnp.sqrt(v + eps)
    tail = x.shape[bna:]
    if scale is not None:
        y = y * scale.reshape((1,) * bna + tail)
    if bias is not None:
        y = y + bias.reshape((1,) * bna + tail)
    ctx.out(op, 'Y', y)
    ctx.out(op, 'Mean', m.reshape(x.shape[:bna]).reshape(-1))
    ctx.out(op, 'Variance', v.reshape(x.shape[:bna]).reshape(-1))


# ---------------------------------------------------------------------------
# Fused LayerNorm + residual-add — the 4th kernel-tier unit
# (ops/kernel_tier.py). The pre-norm transformer block pays this pair
# twice per layer (residual add feeding the next norm); fusing them keeps
# the summed row in VMEM across both (one HBM pass), the fwd computes
# mean/rstddev in that same sweep, and the bwd recomputes x_hat from the
# saved O(N) stats instead of residualizing any normalized [N, D] tensor.
# ---------------------------------------------------------------------------

def ln_res_shapes_ok(n, d):
    """Tiling rule: full rows fit one (bn, d) VMEM block (d fills whole
    lanes, bounded so in+out+grad blocks stay well under VMEM), and the
    row count tiles a power-of-two block."""
    from .ce_ops import _pick_block
    return d % 128 == 0 and d <= 8192 and \
        _pick_block(n, 128, 8) is not None


def ln_res_spmd_ok(mesh, n, d):
    """Per-shard rule under a mesh: rows partition over 'data'."""
    from .kernel_tier import mesh_axis
    ax = mesh_axis(mesh, 'data', n)
    n_loc = n // mesh.shape[ax] if ax else n
    return ln_res_shapes_ok(n_loc, d)


def _ln_res_fwd_kernel(eps, x_ref, r_ref, sc_ref, b_ref,
                       s_ref, y_ref, m_ref, rs_ref):
    s = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    m = jnp.mean(s, axis=-1, keepdims=True)
    c = s - m
    rstd = 1.0 / jnp.sqrt(jnp.mean(c * c, axis=-1, keepdims=True) + eps)
    s_ref[...] = s.astype(s_ref.dtype)
    y_ref[...] = (c * rstd * sc_ref[...] + b_ref[...]).astype(y_ref.dtype)
    m_ref[0] = m[:, 0]
    rs_ref[0] = rstd[:, 0]


def _ln_res_fwd_pallas(x, r, scale, bias, eps, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    from .ce_ops import _pick_block
    n, d = x.shape
    bn = _pick_block(n, 128, 8)
    row = pl.BlockSpec((bn, d), lambda i: (i, 0))
    vec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat = pl.BlockSpec((1, bn), lambda i: (0, i))
    s, y, m, rs = pl.pallas_call(
        functools.partial(_ln_res_fwd_kernel, float(eps)),
        grid=(n // bn,),
        in_specs=[row, row, vec, vec],
        out_specs=[row, row, stat, stat],
        out_shape=[jax.ShapeDtypeStruct((n, d), x.dtype),
                   jax.ShapeDtypeStruct((n, d), x.dtype),
                   jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        compiler_params=_compiler_params(pltpu, ("arbitrary",)),
        interpret=interpret,
    )(x, r, scale.reshape(1, d), bias.reshape(1, d))
    return s, y, m[0], rs[0]


def _ln_res_bwd_kernel(s_ref, m_ref, rs_ref, sc_ref, dy_ref, ds_ref,
                       dx_ref):
    s = s_ref[...].astype(jnp.float32)
    m = m_ref[0][:, None]
    rstd = rs_ref[0][:, None]
    xhat = (s - m) * rstd
    dyw = dy_ref[...].astype(jnp.float32) * sc_ref[...]
    mean1 = jnp.mean(dyw, axis=-1, keepdims=True)
    mean2 = jnp.mean(dyw * xhat, axis=-1, keepdims=True)
    dx = rstd * (dyw - mean1 - xhat * mean2)
    dx_ref[...] = (dx + ds_ref[...].astype(jnp.float32)).astype(
        dx_ref.dtype)


def _ln_res_bwd_pallas(s, m, rs, scale, dy, ds, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .attention_ops import _compiler_params
    from .ce_ops import _pick_block
    n, d = s.shape
    bn = _pick_block(n, 128, 8)
    row = pl.BlockSpec((bn, d), lambda i: (i, 0))
    vec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat = pl.BlockSpec((1, bn), lambda i: (0, i))
    return pl.pallas_call(
        _ln_res_bwd_kernel,
        grid=(n // bn,),
        in_specs=[row, stat, stat, vec, row, row],
        out_specs=[row],
        out_shape=[jax.ShapeDtypeStruct((n, d), s.dtype)],
        compiler_params=_compiler_params(pltpu, ("arbitrary",)),
        interpret=interpret,
    )(s, m[None, :], rs[None, :], scale.reshape(1, d), dy, ds)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_ln_residual(x, r, scale, bias, eps, impl):
    """(y, s) for rows x, r [N, D]: s = x + r, y = LN(s) * scale + bias.
    ``impl`` in 'xla' | 'pallas' | 'interpret' (the 'off' tier lowers the
    legacy composition and never reaches here). Both outputs are consumed
    (y feeds the next sublayer, s carries the residual stream), so the
    bwd merges both cotangents; x_hat is recomputed from (s, mean, rstd)
    — O(N) residual stats, no [N, D] normalized tensor saved."""
    return _ln_res_fwd(x, r, scale, bias, eps, impl)[0]


def _ln_res_fwd(x, r, scale, bias, eps, impl):
    if impl in ('pallas', 'interpret'):
        s, y, m, rs = _ln_res_fwd_pallas(x, r, scale, bias, eps,
                                         impl == 'interpret')
    else:
        s = x + r
        sf = s.astype(jnp.float32)
        m = jnp.mean(sf, axis=-1)
        c = sf - m[:, None]
        rs = 1.0 / jnp.sqrt(jnp.mean(c * c, axis=-1) + eps)
        y = (c * rs[:, None] * scale + bias).astype(x.dtype)
    return (y, s), (s, m, rs, scale)


def _ln_res_bwd(eps, impl, res, cts):
    dy, ds = cts
    s, m, rs, scale = res
    if impl in ('pallas', 'interpret'):
        dx = _ln_res_bwd_pallas(s, m, rs, scale, dy, ds,
                                impl == 'interpret')
    else:
        sf = s.astype(jnp.float32)
        xhat = (sf - m[:, None]) * rs[:, None]
        dyw = dy.astype(jnp.float32) * scale
        mean1 = jnp.mean(dyw, axis=-1, keepdims=True)
        mean2 = jnp.mean(dyw * xhat, axis=-1, keepdims=True)
        dx = (rs[:, None] * (dyw - mean1 - xhat * mean2)
              + ds.astype(jnp.float32)).astype(s.dtype)
    # scale/bias grads: plain jnp reductions over the recomputed x_hat —
    # XLA fuses them into one pass over s; nothing [N, D] is saved
    xhat_f = (s.astype(jnp.float32) - m[:, None]) * rs[:, None]
    dscale = jnp.sum(dy.astype(jnp.float32) * xhat_f,
                     axis=0).astype(scale.dtype)
    dbias = jnp.sum(dy.astype(jnp.float32), axis=0).astype(scale.dtype)
    return dx, dx, dscale, dbias


fused_ln_residual.defvjp(_ln_res_fwd, _ln_res_bwd)


def fused_ln_residual_spmd(x, r, scale, bias, mesh, eps, impl):
    """Mesh-partitioned LN+residual: rows over 'data' via
    kernel_tier.partitioned_call — normalization is per-row, so the
    partitioned kernel needs no comms at all; scale/bias ride replicated
    and their cotangents psum through shard_map's transpose."""
    from jax.sharding import PartitionSpec as P
    from .kernel_tier import partitioned_call, mesh_axis
    data_ax = mesh_axis(mesh, 'data', x.shape[0])
    rowp = P(data_ax, None)

    def inner(xl, rl, sc, b):
        return fused_ln_residual(xl, rl, sc, b, eps, impl)

    return partitioned_call(inner, mesh, (rowp, rowp, P(), P()),
                            (rowp, rowp))(x, r, scale, bias)


@register_op('fused_ln_residual')
def _fused_ln_residual_op(ctx, op):
    """Program-level op: Y = layer_norm(X + Residual) * Scale + Bias,
    ResidualOut = X + Residual (both consumed: Y feeds the next sublayer,
    ResidualOut carries the residual stream). Attrs epsilon,
    begin_norm_axis (the normalized tail must be the LAST axis — the
    transformer wiring's case; anything else falls to 'off'). The 'off'
    tier reproduces elementwise_add + layer_norm BITWISE."""
    from . import kernel_tier
    from ..parallel.api import get_active_mesh
    x = ctx.in1(op, 'X')
    r = ctx.in1(op, 'Residual')
    scale = ctx.in1(op, 'Scale')
    bias = ctx.in1(op, 'Bias')
    eps = op.attr('epsilon', 1e-5)
    bna = op.attr('begin_norm_axis', x.ndim - 1)
    fusable = scale is not None and bias is not None and \
        bna == x.ndim - 1 and x.ndim >= 2
    n = int(np.prod(x.shape[:-1])) if fusable else 0
    d = x.shape[-1] if fusable else 0
    mesh = get_active_mesh()
    meshed = mesh is not None and mesh.size > 1
    if fusable:
        pallas_ok = ln_res_spmd_ok(mesh, n, d) if meshed \
            else ln_res_shapes_ok(n, d)
    else:
        pallas_ok = False
    impl = kernel_tier.dispatch(
        'fused_ln_residual', pallas_ok=pallas_ok, xla_ok=fusable,
        mesh=mesh, count=getattr(ctx, 'sparse_mode', None) != 'scout')
    if impl == 'off':
        # bitwise legacy: exactly the elementwise_add + layer_norm
        # lowerings composed (the parity anchor)
        s = x + r
        axes = tuple(range(bna, x.ndim))
        m = jnp.mean(s, axis=axes, keepdims=True)
        v = jnp.var(s, axis=axes, keepdims=True)
        y = (s - m) / jnp.sqrt(v + eps)
        tail = s.shape[bna:]
        if scale is not None:
            y = y * scale.reshape((1,) * bna + tail)
        if bias is not None:
            y = y + bias.reshape((1,) * bna + tail)
        ctx.out(op, 'Y', y)
        ctx.out(op, 'ResidualOut', s)
        return
    lead = x.shape[:-1]
    x2 = x.reshape(n, d)
    r2 = r.reshape(n, d)
    if meshed and impl in ('pallas', 'interpret'):
        y2, s2 = fused_ln_residual_spmd(x2, r2, scale, bias, mesh, eps,
                                        impl)
    else:
        y2, s2 = fused_ln_residual(x2, r2, scale, bias, eps, impl)
    ctx.out(op, 'Y', y2.reshape(lead + (d,)))
    ctx.out(op, 'ResidualOut', s2.reshape(lead + (d,)))


@register_op('group_norm')
def _group_norm(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    scale = ctx.in1(op, 'Scale')
    bias = ctx.in1(op, 'Bias')
    eps = op.attr('epsilon', 1e-5)
    groups = op.attr('groups')
    n, c = x.shape[:2]
    sp = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + sp)
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - m) / jnp.sqrt(v + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * len(sp)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    ctx.out(op, 'Y', y)
    ctx.out(op, 'Mean', m.reshape(n, groups))
    ctx.out(op, 'Variance', v.reshape(n, groups))


@register_op('data_norm')
def _data_norm(ctx, op):
    x = ctx.in1(op, 'X')
    sizes = ctx.in1(op, 'BatchSize')
    sums = ctx.in1(op, 'BatchSum')
    sqs = ctx.in1(op, 'BatchSquareSum')
    means = sums / sizes
    scales = jnp.sqrt(sizes / (sqs - sums * means + 1e-4))
    ctx.out(op, 'Means', means)
    ctx.out(op, 'Scales', scales)
    ctx.out(op, 'Y', (x - means) * scales)


@register_op('lrn')
def _lrn(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    n_ = op.attr('n', 5)
    k = op.attr('k', 2.0)
    alpha = op.attr('alpha', 1e-4)
    beta = op.attr('beta', 0.75)
    sq = x * x
    half = n_ // 2
    acc = lax.reduce_window(sq, 0.0, lax.add, (1, n_, 1, 1), (1, 1, 1, 1),
                            [(0, 0), (half, n_ - 1 - half), (0, 0), (0, 0)])
    mid = (k + alpha * acc) ** beta
    ctx.out(op, 'MidOut', mid)
    ctx.out(op, 'Out', x / mid)


@register_op('affine_channel')
def _affine_channel(ctx, op):
    x = ctx.in1(op, 'X')
    scale = ctx.in1(op, 'Scale')
    bias = ctx.in1(op, 'Bias')
    layout = op.attr('data_layout', 'NCHW')
    if layout == 'NCHW':
        bshape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        bshape = (1,) * (x.ndim - 1) + (-1,)
    ctx.out(op, 'Out', x * scale.reshape(bshape) + bias.reshape(bshape))


# ---------------------------------------------------------------------------
# Resize / interpolate
# ---------------------------------------------------------------------------

def _interp_sizes(op, x):
    out_h = op.attr('out_h', -1)
    out_w = op.attr('out_w', -1)
    scale = op.attr('scale', 0.0)
    if scale and (not out_h or out_h <= 0):
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return out_h, out_w


@register_op('bilinear_interp')
def _bilinear_interp(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    out_h, out_w = _interp_sizes(op, x)
    align = op.attr('align_corners', True)
    h, w = x.shape[2], x.shape[3]

    def src_idx(out_sz, in_sz):
        if align and out_sz > 1:
            return jnp.arange(out_sz) * ((in_sz - 1.0) / (out_sz - 1.0))
        ratio = in_sz / out_sz
        return jnp.maximum((jnp.arange(out_sz) + 0.5) * ratio - 0.5, 0.0) \
            if not align else jnp.zeros(out_sz)

    ys = src_idx(out_h, h)
    xs = src_idx(out_w, w)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).reshape(1, 1, -1, 1)
    wx = (xs - x0).reshape(1, 1, 1, -1)
    g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
    out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx) +
           g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
    ctx.out(op, 'Out', out.astype(x.dtype))


@register_op('nearest_interp')
def _nearest_interp(ctx, op):
    x = ctx.in1(op, 'X')
    out_h, out_w = _interp_sizes(op, x)
    align = op.attr('align_corners', True)
    h, w = x.shape[2], x.shape[3]
    if align and out_h > 1:
        ys = jnp.round(jnp.arange(out_h) * ((h - 1.0) / (out_h - 1.0)))
        xs = jnp.round(jnp.arange(out_w) * ((w - 1.0) / (out_w - 1.0)))
    else:
        ys = jnp.floor(jnp.arange(out_h) * (h / out_h))
        xs = jnp.floor(jnp.arange(out_w) * (w / out_w))
    ys = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
    xs = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
    ctx.out(op, 'Out', x[:, :, ys, :][:, :, :, xs])


# ---------------------------------------------------------------------------
# Sampled / hierarchical losses
# ---------------------------------------------------------------------------

@register_op('nce')
def _nce(ctx, op):
    # Noise-contrastive estimation: full-softmax equivalent computation on
    # TPU (dense matmul beats gather-sampling on MXU for moderate vocab);
    # sampling path kept for parity (reference operators/nce_op.cc).
    x = ctx.in1(op, 'Input')          # (N, D)
    label = ctx.in1(op, 'Label')      # (N, num_true)
    w = ctx.in1(op, 'Weight')         # (V, D)
    b = ctx.in1(op, 'Bias')           # (V,)
    num_neg = op.attr('num_neg_samples', 10)
    key = ctx.rng()
    n = x.shape[0]
    v = w.shape[0]
    neg = jax.random.randint(key, (n, num_neg), 0, v)
    lab = label[:, :1].reshape(-1).astype(jnp.int32)
    ids = jnp.concatenate([lab[:, None], neg], axis=1)       # (N, 1+num_neg)
    wg = w[ids]                                              # (N, S, D)
    logits = jnp.einsum('nd,nsd->ns', x, wg)
    if b is not None:
        logits = logits + b[ids]
    p_noise = 1.0 / v
    logits = logits - jnp.log(num_neg * p_noise)
    labels01 = jnp.concatenate(
        [jnp.ones((n, 1)), jnp.zeros((n, num_neg))], axis=1)
    loss = jnp.maximum(logits, 0) - logits * labels01 + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    ctx.out(op, 'Cost', jnp.sum(loss, axis=1, keepdims=True))
    ctx.out(op, 'SampleLogits', logits)
    ctx.out(op, 'SampleLabels', ids.astype(jnp.int64))


@register_op('hierarchical_sigmoid')
def _hsigmoid(ctx, op):
    # Default (complete binary tree) mode of reference hsigmoid
    # (operators/hierarchical_sigmoid_op.cc + math/matrix_bit_code.h).
    x = ctx.in1(op, 'X')              # (N, D)
    w = ctx.in1(op, 'W')              # (num_classes-1, D)
    label = ctx.in1(op, 'Label')      # (N, 1)
    bias = ctx.in1(op, 'Bias')
    num_classes = op.attr('num_classes')
    code_len = int(np.ceil(np.log2(num_classes)))
    lab = label.reshape(-1).astype(jnp.int32) + num_classes  # leaf index
    losses = []
    node = lab
    for _ in range(code_len):
        parent = node // 2
        sign = (node % 2).astype(x.dtype)          # 1 if right child
        idx = jnp.clip(parent - 1, 0, w.shape[0] - 1)
        valid = (parent >= 1) & (parent - 1 < w.shape[0])
        logit = jnp.einsum('nd,nd->n', x, w[idx])
        if bias is not None:
            logit = logit + bias.reshape(-1)[idx]
        l = jnp.maximum(logit, 0) - logit * sign + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
        losses.append(jnp.where(valid, l, 0.0))
        node = parent
    ctx.out(op, 'Out', jnp.stack(losses, 1).sum(1, keepdims=True))
    ctx.out(op, 'PreOut', jnp.zeros((x.shape[0], code_len), dtype=x.dtype))


@register_op('sample_logits')
def _sample_logits(ctx, op):
    logits = ctx.in1(op, 'Logits')
    labels = ctx.in1(op, 'Labels')
    num_samples = op.attr('num_samples')
    key = ctx.rng()
    n, v = logits.shape
    neg = jax.random.randint(key, (n, num_samples), 0, v)
    ids = jnp.concatenate([labels.astype(jnp.int32), neg], axis=1)
    out = jnp.take_along_axis(logits, ids, axis=1)
    ctx.out(op, 'SampledLogits', out)
    ctx.out(op, 'Samples', ids.astype(jnp.int64))
    ctx.out(op, 'SampledLabels',
            jnp.zeros((n, labels.shape[1]), dtype=jnp.int64))
    ctx.out(op, 'Probabilities', jnp.full_like(out, 1.0 / v))


@register_op('im2sequence', share_lod=False)
def _im2sequence(ctx, op):
    x = ctx.in1(op, 'X')  # NCHW
    kernels = op.attr('kernels')
    strides = op.attr('strides', [1, 1])
    paddings = op.attr('paddings', [0, 0, 0, 0])
    n, c, h, w = x.shape
    kh, kw = kernels
    xp = jnp.pad(x, [(0, 0), (0, 0), (paddings[0], paddings[2]),
                     (paddings[1], paddings[3])])
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(
                xp[:, :, i:i + oh * strides[0]:strides[0],
                   j:j + ow * strides[1]:strides[1]])
    out = jnp.stack(patches, axis=2).reshape(n, c * kh * kw, oh * ow)
    out = out.transpose(0, 2, 1).reshape(n * oh * ow, c * kh * kw)
    ctx.out(op, 'Out', out)
