"""ServingEngine: dynamic batching + bucket warmup + a predictor pool.

The reference framework's inference story stops at the single-request
AnalysisPredictor::Run; a fleet in front of real traffic needs the next
layer up — this module. One in-process engine composes the substrate the
runtime already ships:

- requests land in a BOUNDED `RequestQueue` (batcher.py) and are coalesced
  into dynamically-formed batches (``max_batch_size`` rows or
  ``max_wait_ms``, whichever first);
- every batch is padded onto the `BucketLadder` grid (bucketing.py), so
  steady-state traffic executes a FIXED set of feed signatures — all
  pre-compiled by ``warmup()`` through the PR 1 fingerprint compile cache
  (zero recompiles once warm);
- a pool of worker threads executes batches through the predictor's
  Executor with the per-call ``donate=False`` override (cached params are
  shared by every in-flight batch and must never be consumed), riding the
  executor's ``resilience.RetryPolicy`` at the run boundary: transient
  dispatch faults retry with backoff, exhausted retries surface as
  PER-REQUEST errors — the pool itself never dies;
- per-request deadlines + load shedding give the engine a real
  backpressure story: a full queue rejects with a structured
  `LoadShedError`, an expired request is dropped before it wastes
  accelerator time, and a caller never blocks past its deadline.

Instrumentation (monitor.py): ``serving_request_total{outcome}``
(ok|error|shed|deadline|rejected), ``serving_batch_total``,
``serving_queue_depth`` / ``serving_inflight_batches`` gauges,
``serving_batch_rows`` / ``serving_batch_fill`` / ``serving_queue_seconds``
/ ``serving_execute_seconds`` histograms, and ``serving.batch`` /
``serving.execute`` spans on the monitor ring. Full catalog + tuning
guide: docs/serving.md.
"""
import threading
import time

import numpy as np

from .. import blackbox
from .. import goodput
from .. import monitor
from .. import resilience
from .. import trace as trace_mod
from ..inference import Predictor, PredictorConfig
from .batcher import (ServingError, LoadShedError, DeadlineExceededError,
                      EngineStoppedError, Request, RequestQueue,
                      resolve_metrics_port, start_metrics_server)
from .bucketing import BucketLadder

__all__ = ['ServingConfig', 'ServingEngine', 'create_engine']


class ServingConfig(object):
    """Engine knobs. `model_dir` (or a ready `predictor`) names the model;
    the ladder defaults to power-of-two batch buckets up to
    ``max_batch_size``.

    - max_batch_size: total ROWS a formed batch may carry (the top batch
      bucket).
    - max_wait_ms: how long a forming batch waits for co-riders once its
      first request arrived. 0 disables coalescing delay (latency-first).
    - batch_buckets / seq_buckets / seq_axis / pad_value: the
      `BucketLadder` grid; seq_buckets=None serves fixed-shape models.
    - num_workers: concurrent batch executors (each dispatches through
      the shared predictor; the compile cache and params are shared).
    - queue_cap: bounded-queue depth in REQUESTS; beyond it submissions
      shed with `LoadShedError`.
    - default_deadline_s: per-request deadline when submit() gives none.
    - metrics_port: start a Prometheus ``/metrics`` endpoint
      (``monitor.serve_metrics``) with the engine; 0 binds an ephemeral
      port (read it back from ``engine.metrics_port``), None (default)
      falls back to the ``PADDLE_METRICS_PORT`` env var, and no endpoint
      is started when neither is set.
    - ps_resolver: a ``ps.PSRowResolver`` when the model's embedding
      tables are PS-resident (``ps.psify_predictor``): admission pulls
      the request's rows through the hot-row cache (`ps` trace stage),
      batch formation feeds each ``ps_lookup_table`` site from it — the
      table never fully resides in process, signatures stay fixed.
    - name: stable model name labelling this engine's goodput series
      (defaults to model_dir). A ModelFleet sets it to the fleet-wide
      model name so ``goodput.cost_estimate(name)`` keeps pricing the
      model across hot-swapped versions living in different dirs.
    """

    def __init__(self, model_dir=None, model_filename=None,
                 params_filename=None, max_batch_size=8, max_wait_ms=2.0,
                 batch_buckets=None, seq_buckets=None, seq_axis=1,
                 pad_value=0, num_workers=2, queue_cap=64,
                 default_deadline_s=30.0, metrics_port=None,
                 ps_resolver=None, name=None):
        self.ps_resolver = ps_resolver
        self.model_dir = model_dir
        self.name = name
        self.model_filename = model_filename
        self.params_filename = params_filename
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        if batch_buckets is None:
            batch_buckets, b = [], 1
            while b < self.max_batch_size:
                batch_buckets.append(b)
                b *= 2
            batch_buckets.append(self.max_batch_size)
        self.batch_buckets = batch_buckets
        self.seq_buckets = seq_buckets
        self.seq_axis = seq_axis
        self.pad_value = pad_value
        self.num_workers = max(1, int(num_workers))
        self.queue_cap = int(queue_cap)
        self.default_deadline_s = default_deadline_s
        self.metrics_port = metrics_port


class ServingEngine(object):
    """In-process serving engine over one loaded model. ::

        engine = fluid.serving.ServingEngine(
            fluid.serving.ServingConfig('model_dir', max_batch_size=8,
                                        seq_buckets=[32, 64, 128]))
        engine.warmup({'tokens': np.zeros((1, 40), 'int64')})
        with engine:                       # start()/stop()
            out = engine.run({'tokens': ids})        # blocking
            fut = engine.submit({'tokens': ids2})    # concurrent callers
            logits = fut.result()[0]
    """

    def __init__(self, config, predictor=None):
        if isinstance(config, str):
            config = ServingConfig(model_dir=config)
        self.config = config
        if predictor is None:
            predictor = Predictor(PredictorConfig(
                model_dir=config.model_dir,
                model_filename=config.model_filename,
                params_filename=config.params_filename))
        self.predictor = predictor
        # name the program's goodput series NOW: counters exported by a
        # periodic snapshot before the first stats() call would
        # otherwise label as the bare fingerprint and split the series
        try:
            goodput.name_model(predictor.program._fingerprint(),
                               config.name or config.model_dir
                               or 'serving')
        except Exception:       # noqa: BLE001 — telemetry only
            pass
        self.ladder = BucketLadder(config.batch_buckets,
                                   seq_buckets=config.seq_buckets,
                                   seq_axis=config.seq_axis,
                                   pad_value=config.pad_value)
        if self.ladder.max_rows != config.max_batch_size:
            raise ValueError(
                "batch_buckets %r must top out at max_batch_size %d"
                % (config.batch_buckets, config.max_batch_size))
        self.ps_resolver = config.ps_resolver
        self.queue = RequestQueue(config.queue_cap)
        self._workers = []
        self._started = False
        self._lock = threading.Lock()
        self._inflight_n = 0
        self._inflight_lock = threading.Lock()
        self._metrics_server = None
        monitor.set_gauge('serving_queue_depth', 0.0)

    @property
    def metrics_port(self):
        """Bound port of the engine's /metrics endpoint (None when not
        serving metrics — see ServingConfig.metrics_port)."""
        return self._metrics_server.port if self._metrics_server else None

    @property
    def metrics_url(self):
        return self._metrics_server.url if self._metrics_server else None

    def _resolve_metrics_port(self):
        return resolve_metrics_port(self.config.metrics_port)

    # ------------------------------------------------------------------
    # lifecycle
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self.queue.closed:
                raise EngineStoppedError(
                    "a stopped ServingEngine cannot restart — build a "
                    "fresh engine (the queue already failed its callers)")
            self._started = True
            if self._metrics_server is None:
                # a fleet scheduler pointing Prometheus at
                # PADDLE_METRICS_PORT sees every serving_* series
                # without extra wiring
                self._metrics_server = start_metrics_server(
                    self._resolve_metrics_port(), 'ServingEngine')
            for i in range(self.config.num_workers):
                t = threading.Thread(target=self._worker_loop,
                                     name='paddle-serving-%d' % i,
                                     daemon=True)
                t.start()
                self._workers.append(t)
        return self

    def stop(self, timeout_s=10.0):
        """Close the queue (queued requests fail with EngineStoppedError),
        let in-flight batches finish, join the workers."""
        with self._lock:
            self._started = False
        drained = self.queue.close()
        if drained:
            monitor.inc('serving_request_total', drained,
                        labels={'outcome': 'stopped'})
        for t in self._workers:
            t.join(timeout_s)
        self._workers = []
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    # request path
    def submit(self, feed, deadline_s=None, return_numpy=True):
        """Enqueue one request; returns the `Request` future. Raises
        synchronously for feeds the engine can never serve (KeyError for
        name mismatches — Predictor.run's contract — ValueError for
        ladder violations) and `LoadShedError` when the bounded queue is
        full; both count into ``serving_request_total``.

        `return_numpy=False` delivers DEVICE-RESIDENT fetch slices (no
        host sync) for callers that chain results into another device
        computation; the default materializes numpy per request — and
        only this request's rows ever cross to the host (batch padding
        stays on device either way)."""
        names = self.predictor.get_input_names()
        managed = (self.ps_resolver.managed_names
                   if self.ps_resolver is not None else ())
        missing = sorted(n for n in names
                         if n not in feed and n not in managed)
        extra = sorted(k for k in feed if k not in names)
        if missing or extra:
            monitor.inc('serving_request_total',
                        labels={'outcome': 'rejected'})
            raise KeyError(
                "serving feed does not match get_input_names() %s:%s%s"
                % (names, ' missing %s' % missing if missing else '',
                   ' unexpected %s' % extra if extra else ''))
        try:
            n_rows, seq_len, key = self.ladder.request_shape(feed)
        except ValueError:
            monitor.inc('serving_request_total',
                        labels={'outcome': 'rejected'})
            raise
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = Request(feed, n_rows, seq_len, key, deadline,
                      return_numpy=return_numpy)
        # every request is a traced unit of work: stage accounting (the
        # timing breakdown on req.timing) is unconditional; span-level
        # recording and the trace-log line ride head sampling
        req.trace = trace_mod.start('serving')
        if self.ps_resolver is not None:
            # ADMISSION pull: this request's embedding rows enter the
            # hot-row cache now (the 'ps' stage on the request trace),
            # so batch formation assembles rows feeds from cache hits
            try:
                with trace_mod.activate(req.trace):
                    self.ps_resolver.prewarm(feed)
            except Exception as e:     # noqa: BLE001 — per-request error
                monitor.inc('serving_request_total',
                            labels={'outcome': 'error'})
                req.fail(e)
                raise
        try:
            self.queue.put(req)
        except (LoadShedError, EngineStoppedError) as e:
            # finishes the trace with the right outcome (keep-errors: a
            # rejected request is never invisible in the trace log)
            monitor.inc('serving_request_total', labels={
                'outcome': 'shed' if isinstance(e, LoadShedError)
                else 'stopped'})
            req.fail(e)
            raise
        monitor.set_gauge('serving_queue_depth', self.queue.depth())
        return req

    def run(self, feed, deadline_s=None, timeout=None, return_numpy=True):
        """Blocking convenience: submit + result. Returns the fetch list
        (rows sliced back to this request; numpy unless
        return_numpy=False)."""
        return self.submit(feed, deadline_s=deadline_s,
                           return_numpy=return_numpy).result(timeout)

    # ------------------------------------------------------------------
    # warmup
    def warmup(self, example_feed):
        """Compile every ladder cell ahead of traffic by tiling/padding
        `example_feed` (ONE representative row, or any request-shaped
        feed) to each (batch bucket, seq bucket) signature and executing
        it. Steady-state traffic then hits the compile cache only.

        Routes through the process-wide warmup farm
        (paddle_tpu.warmfarm): cells whose signature another engine in
        this process already compiled are SKIPPED outright — the second
        process-sharing consumer of a signature set warms in ~0 s with a
        compile_seconds delta of ≈ 0 (the AOT-reuse contract; the
        executables live in the fingerprint cache, so this engine's
        traffic dispatches them directly).

        Returns {'buckets', 'compiles', 'reused', 'seconds'} where
        `compiles` is the compile_cache_miss delta — on a second warmup
        of the same engine (or a fresh engine over the same model in the
        same process) it is 0, the fingerprint-cache contract."""
        from ..warmfarm import farm
        t0 = time.perf_counter()
        before = monitor.counters()
        arrays = {n: np.asarray(v) for n, v in example_feed.items()}
        _, seq_len, _ = self.ladder.request_shape(arrays)
        cells = 0
        reused = 0
        for bb, sb in self.ladder.bucket_grid():
            feed = {}
            for name, a in arrays.items():
                v = a
                if sb is not None and seq_len is not None and \
                        a.ndim > self.ladder.seq_axis and \
                        a.shape[self.ladder.seq_axis] == seq_len:
                    # stretch/trim the example's seq axis to the bucket
                    take = min(a.shape[self.ladder.seq_axis], sb)
                    sl = [slice(None)] * a.ndim
                    sl[self.ladder.seq_axis] = slice(0, take)
                    v = a[tuple(sl)]
                    if take < sb:
                        pad = [(0, 0)] * a.ndim
                        pad[self.ladder.seq_axis] = (0, sb - take)
                        v = np.pad(v, pad, mode='constant',
                                   constant_values=self.ladder.pad_value)
                n = v.shape[0]
                if n < bb:
                    v = np.concatenate(
                        [v] * (bb // n) + [v[:bb % n]], axis=0)
                elif n > bb:
                    v = v[:bb]
                feed[name] = v
            if self.ps_resolver is not None:
                # rows feeds are part of the compiled signature: resolve
                # BEFORE the farm tracks it, exactly like live dispatch
                feed.update(self.ps_resolver.resolve(feed))
            p = self.predictor
            key, already = farm.track(p.executor, p.program, feed,
                                      fetch_list=p.fetch_vars,
                                      scope=p.scope, donate=False)
            if already:
                # another engine in this process already compiled this
                # cell AND the entry is still cache-resident (track's
                # LRU-eviction guard)
                reused += 1
            else:
                with monitor.span('serving.warmup'):
                    self._execute(feed)
                farm.commit(key)
            cells += 1
        delta = monitor.counter_delta(before)
        compiles = sum(v for k, v in delta.items()
                       if k.startswith('compile_cache_miss'))
        out = {'buckets': cells, 'compiles': int(compiles),
               'reused': reused,
               'seconds': round(time.perf_counter() - t0, 3)}
        monitor.inc('serving_warmup_total')
        monitor.set_gauge('serving_warmup_buckets', cells)
        return out

    # ------------------------------------------------------------------
    # worker pool
    def _execute(self, feed):
        """One batched dispatch through the predictor's executor. Params
        are cached device-side in the predictor's private scope and must
        survive every call: donation is overridden OFF per call (never
        via env — other threads may be training in this process).
        Transient dispatch faults retry inside the executor under the
        'run' site RetryPolicy; what escapes here is either permanent or
        retry-exhausted and becomes a per-request error upstream.

        Fetches stay DEVICE-RESIDENT (return_numpy=False): un-batching
        slices them on device and only each request's own rows are
        materialized at delivery (see _slice_result) — the padded batch
        never round-trips through the host."""
        if self.ps_resolver is not None:
            feed = dict(feed)
            feed.update(self.ps_resolver.resolve(feed))
        p = self.predictor
        return p.executor.run(p.program, feed=feed,
                              fetch_list=p.fetch_vars, scope=p.scope,
                              return_numpy=False, donate=False)

    def _worker_loop(self):
        """Pipelined worker: while batch K executes on the device, this
        thread forms batch K+1 (padding, stacking, bucket math) — the
        executor's async path makes the dispatch non-blocking, the
        worker-local `pending` slot keeps delivery in order. Delivery of
        an in-flight batch is never deferred behind an EMPTY queue: when
        there is nothing to form, the pending batch finishes
        immediately, so a lone request still sees dispatch-latency
        delivery."""
        poll = 0.05
        pending = None
        while True:
            if self.queue.closed and self.queue.depth() == 0:
                if pending is not None:
                    self._finish_batch(pending)
                return
            if pending is not None and self.queue.depth() == 0:
                self._finish_batch(pending)
                pending = None
            batch, expired = self.queue.take_batch(
                self.ladder.max_rows, self.config.max_wait_ms / 1000.0,
                poll_s=poll)
            now = time.monotonic()
            for r in expired:
                monitor.inc('serving_request_total',
                            labels={'outcome': 'deadline'})
                r.fail(DeadlineExceededError(
                    "deadline passed after %.3fs in queue"
                    % (now - r.enqueue_t)))
            if not batch:
                if pending is not None:
                    self._finish_batch(pending)
                    pending = None
                continue
            monitor.set_gauge('serving_queue_depth', self.queue.depth())
            nxt = self._dispatch_batch(batch)
            if pending is not None:
                # batch K+1 is dispatched: finishing K now overlaps its
                # delivery (host-side slicing/materialization) with K+1's
                # device execution
                self._finish_batch(pending)
            pending = nxt

    def _dispatch_batch(self, batch):
        """Form one padded batch and dispatch it asynchronously. Returns
        the pending (future, batch, padded_rows, t0, wall_us) record for
        `_finish_batch`, or None when formation failed (those requests
        are already failed — the pool never dies).

        Trace accounting: each request's 'queue' stage closes here
        (enqueue -> this worker picking it up, co-rider wait included)
        and the shared formation time lands as its 'batch' stage; for
        sampled traces the matching spans are stamped retrospectively —
        the queue span on the SUBMITTER's tid, formation on this
        worker's — so exported traces show the thread hop."""
        with monitor.span('serving.batch'):
            t_form0 = time.perf_counter()
            form_wall = time.time() * 1e6
            now_m = time.monotonic()
            n_rows = sum(r.n_rows for r in batch)
            for r in batch:
                qs = max(0.0, now_m - r.enqueue_t)
                monitor.observe('serving_queue_seconds', qs)
                # queue-SLO burn sentinel (perf_regression_total
                # {kind=queue_burn} once the EWMA burns past
                # PADDLE_PERFWATCH_QUEUE_SLO_MS)
                goodput.note_queue_wait(qs)
                if r.trace is not None:
                    r.trace.add_stage('queue', qs)
                    monitor.record_span('request.queue', r.enqueue_wall,
                                        qs * 1e6, tid=r._tid,
                                        trace=r.trace)
            try:
                padded = [self.ladder.pad_request(r.feed, r.seq_len)
                          for r in batch]
                stacked = {
                    name: np.concatenate([p[name] for p in padded], axis=0)
                    for name in padded[0]}
                stacked, padded_rows = self.ladder.pad_rows(stacked, n_rows)
                if self.ps_resolver is not None:
                    # rows feeds for the PADDED batch (pad-value ids hit
                    # the cache after the first batch of a bucket); the
                    # fed rows shape is a pure function of the bucketed
                    # ids shape, so signatures stay fixed
                    stacked.update(self.ps_resolver.resolve(stacked))
            except Exception as e:      # noqa: BLE001 — delivered per-request
                monitor.inc('serving_batch_error_total')
                blackbox.record('serving_batch_error', error=e,
                                stage='form', requests=len(batch))
                for r in batch:
                    monitor.inc('serving_request_total',
                                labels={'outcome': 'error'})
                    r.fail(e)
                return None
            monitor.observe('serving_batch_rows', n_rows)
            monitor.observe('serving_batch_fill',
                            n_rows / float(padded_rows))
            monitor.inc('serving_batch_total')
            monitor.inc('serving_batch_padded_rows', padded_rows - n_rows)
            form_s = time.perf_counter() - t_form0
            for r in batch:
                if r.trace is not None:
                    r.trace.add_stage('batch', form_s)
                    monitor.record_span('request.batch', form_wall,
                                        form_s * 1e6, trace=r.trace)
            t0 = time.perf_counter()
            monitor.set_gauge('serving_inflight_batches', self._inflight(1))
            p = self.predictor
            # donation stays off per call (shared cached params); faults
            # and retry-exhaustion surface on the future, failed below
            fut = p.executor.run_async(p.program, feed=stacked,
                                       fetch_list=p.fetch_vars,
                                       scope=p.scope, donate=False)
            return (fut, batch, padded_rows, t0, time.time() * 1e6)

    def _finish_batch(self, pending):
        """Wait for a dispatched batch, then deliver per-request slices.
        serving_execute_seconds spans dispatch→device completion (it may
        include host time the worker spent forming the NEXT batch — the
        overlap is the point)."""
        fut, batch, padded_rows, t0, disp_wall = pending
        try:
            try:
                with monitor.span('serving.execute'):
                    # device-resident fetches; result() blocks until the
                    # device completed, so the histogram still measures
                    # completion, not async dispatch
                    outs = fut.result(return_numpy=False)
            finally:
                monitor.set_gauge('serving_inflight_batches',
                                  self._inflight(-1))
            exec_s = time.perf_counter() - t0
            monitor.observe('serving_execute_seconds', exec_s)
            for r in batch:
                if r.trace is not None:
                    r.trace.add_stage('execute', exec_s)
                    monitor.record_span('request.execute', disp_wall,
                                        exec_s * 1e6, trace=r.trace)
        except Exception as e:      # noqa: BLE001 — delivered per-request
            # a failed batch fails ITS requests; the worker and the
            # pool live on (retry-exhausted transients land here too)
            monitor.inc('serving_batch_error_total')
            blackbox.record('serving_batch_error', error=e,
                            stage='execute', requests=len(batch),
                            padded_rows=padded_rows)
            for r in batch:
                if r.trace is not None:
                    r.trace.add_stage('execute',
                                      time.perf_counter() - t0)
                monitor.inc('serving_request_total',
                            labels={'outcome': 'error'})
                r.fail(e)
            return
        # batch-level fetches (no padded leading dim) are shared whole by
        # every request in the batch: materialize them host-side ONCE
        # here, not once per request in _slice_result
        shared_bytes = 0
        for i, o in enumerate(outs):
            if not (getattr(o, 'ndim', 0) and
                    getattr(o, 'shape', (None,))[0] == padded_rows) \
                    and not isinstance(o, np.ndarray):
                outs[i] = np.asarray(o)
                shared_bytes += int(outs[i].nbytes)
        if shared_bytes:
            monitor.inc('fetch_host_bytes', shared_bytes)
        off = 0
        for r in batch:
            # per-request delivery is individually guarded: one request
            # whose un-batching fails (odd fetch shape) must not strand
            # the rest of the batch or kill the worker — "the pool never
            # dies" covers the un-batch path too
            try:
                t_sync0 = time.perf_counter()
                sync_wall = time.time() * 1e6
                res = self._slice_result(outs, off, r, padded_rows)
                if r.trace is not None:
                    sync_s = time.perf_counter() - t_sync0
                    r.trace.add_stage('sync', sync_s)
                    monitor.record_span('request.sync', sync_wall,
                                        sync_s * 1e6, trace=r.trace)
                r.done(res)
                monitor.inc('serving_request_total',
                            labels={'outcome': 'ok'})
            except Exception as e:      # noqa: BLE001 — delivered per-request
                monitor.inc('serving_request_total',
                            labels={'outcome': 'error'})
                r.fail(e)
            off += r.n_rows

    def _inflight(self, d):
        with self._inflight_lock:
            self._inflight_n += d
            return self._inflight_n

    # ------------------------------------------------------------------
    def stats(self):
        """Engine statistics: queue/inflight state plus the live
        goodput/MFU block for THIS engine's program — device-busy
        seconds, delivered flops/s and utilization restricted to the
        predictor's compiled signatures (the process-wide loss buckets
        and regression log ride along; see paddle_tpu.goodput)."""
        out = {
            'queue_depth': self.queue.depth(),
            'inflight_batches': self._inflight(0),
            'workers': len(self._workers),
            'started': self._started,
        }
        try:
            fp = self.predictor.program._fingerprint()
            goodput.name_model(fp, self.config.name
                               or self.config.model_dir or 'serving')
            out['goodput'] = goodput.stats(fps=[fp])
        except Exception:       # noqa: BLE001 — stats stay best-effort
            out['goodput'] = goodput.stats(fps=[])
        return out

    def _slice_result(self, outs, off, req, padded_rows):
        """Un-batch: slice each fetch back to this request's rows, and
        un-pad sequence columns the bucket added. Fetches without the
        batched leading dim (batch-level scalars) are returned whole, as
        numpy — the worker loop materialized them once for the batch.

        Slicing happens on DEVICE (the executor handed us device-resident
        fetches): padded rows and other requests' rows never cross to the
        host. Only when the request asked for numpy (the default) are its
        own rows materialized — previously every request pulled the whole
        padded batch host-side per fetch."""
        out = []
        host_bytes = 0
        for o in outs:
            a = o
            if getattr(a, 'ndim', 0) and a.shape[0] == padded_rows:
                a = a[off:off + req.n_rows]
                if req.seq_len is not None:
                    sb = self.ladder.seq_bucket(req.seq_len)
                    ax = self.ladder.seq_axis
                    if sb is not None and sb != req.seq_len and \
                            a.ndim > ax and a.shape[ax] == sb:
                        sl = [slice(None)] * a.ndim
                        sl[ax] = slice(0, req.seq_len)
                        a = a[tuple(sl)]
            if req.return_numpy and not isinstance(a, np.ndarray):
                # batch-level fetches arrive pre-materialized (worker
                # loop, once per batch) — only this request's own sliced
                # rows cross here
                a = np.asarray(a)
                host_bytes += int(a.nbytes)
            out.append(a)
        if host_bytes:
            # the executor no longer counts these (return_numpy=False on
            # the batched run); the engine counts what actually crossed
            monitor.inc('fetch_host_bytes', host_bytes)
        return out


def create_engine(config, predictor=None):
    """Factory mirroring inference.create_predictor."""
    return ServingEngine(config, predictor=predictor)
