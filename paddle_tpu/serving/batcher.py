"""Request queue + dynamic batcher for the serving engine.

A `Request` is one caller's feed (leading dim = its row count) wrapped in
a future the caller blocks on. The `RequestQueue` is BOUNDED: a full queue
sheds new load immediately with a structured `LoadShedError` (reason,
depth, cap) instead of growing latency without bound — the reject is the
backpressure signal a closed-loop client needs to slow down.

Batch formation (`take_batch`) is the classic two-knob policy: starting
from the oldest compatible request, coalesce same-bucket requests until
the next one would overflow ``max_rows`` or ``max_wait_s`` has elapsed
since formation began, whichever first. Compatibility is the
`BucketLadder.request_shape` key — identical feed names/dtypes/padded
shapes — so a formed batch concatenates along axis 0 without any shape
negotiation. Incompatible requests stay queued IN ORDER for the next
worker; expired ones are completed with `DeadlineExceededError` at
collection time so a dead request never occupies accelerator time.
"""
import threading
import time

from .. import trace as trace_mod

__all__ = ['ServingError', 'LoadShedError', 'DeadlineExceededError',
           'EngineStoppedError', 'Request', 'RequestQueue']


def resolve_metrics_port(configured):
    """Shared ServingConfig/GenerateConfig `metrics_port` resolution: an
    explicit config value wins; else PADDLE_METRICS_PORT (unset or
    unparsable -> None, i.e. no endpoint)."""
    if configured is not None:
        return int(configured)
    import os
    env = os.environ.get('PADDLE_METRICS_PORT', '')
    if env == '':
        return None
    try:
        return int(env)
    except ValueError:
        return None


def start_metrics_server(port, owner):
    """Start the scrape endpoint that rides an engine's lifecycle: up
    before the first batch, down with stop(). A bind failure must not
    leave the engine half-started (queue open, zero workers): warn and
    serve without the endpoint. Returns the server or None."""
    if port is None:
        return None
    from .. import monitor
    try:
        return monitor.serve_metrics(port)
    except Exception as e:          # noqa: BLE001 — telemetry only
        import warnings
        warnings.warn(
            "%s: could not serve /metrics on port %s (%s); continuing "
            "without the endpoint" % (owner, port, e), stacklevel=3)
        return None


class ServingError(RuntimeError):
    """Base class of serving-engine request failures."""


class LoadShedError(ServingError):
    """The bounded queue rejected this request. Fields carry the
    structured reason a client/load-balancer routes on."""

    def __init__(self, reason, queue_depth, queue_cap):
        ServingError.__init__(
            self, "request shed (%s): queue depth %d at cap %d — retry "
            "against another replica or back off" %
            (reason, queue_depth, queue_cap))
        self.reason = reason
        self.queue_depth = queue_depth
        self.queue_cap = queue_cap


class DeadlineExceededError(ServingError):
    """The request's deadline passed before (or while) it was served."""


class EngineStoppedError(ServingError):
    """The engine was stopped while the request was queued."""


def _trace_outcome(error):
    """Map a request failure to its trace/metric outcome label."""
    if isinstance(error, DeadlineExceededError):
        return 'deadline'
    if isinstance(error, LoadShedError):
        return 'shed'
    if isinstance(error, EngineStoppedError):
        return 'stopped'
    return 'error'


class Request(object):
    """One in-flight request: feed + bucket metadata + a one-shot
    future. Workers call done()/fail(); the submitting thread blocks in
    result().

    `trace` (set by the engine at submit) is the request's causal trace
    (trace.py): the engine accumulates the latency-budget stages
    (queue/batch/execute/sync) on it, and done()/fail() finish it with
    the right outcome — the flattened breakdown lands on ``timing``
    (``{'trace_id', 'total_s', 'queue_s', ...}``)."""

    __slots__ = ('feed', 'n_rows', 'seq_len', 'key', 'deadline',
                 'enqueue_t', 'enqueue_wall', 'return_numpy', 'trace',
                 'timing', '_tid', '_event', '_result', '_error')

    def __init__(self, feed, n_rows, seq_len, key, deadline,
                 return_numpy=True):
        self.feed = feed
        self.n_rows = n_rows
        self.seq_len = seq_len
        self.key = key
        self.deadline = deadline
        # False keeps this request's sliced fetches device-resident —
        # the engine only materializes numpy per request on delivery
        self.return_numpy = return_numpy
        self.enqueue_t = time.monotonic()
        self.enqueue_wall = time.time() * 1e6
        self.trace = None
        self.timing = None
        self._tid = threading.get_ident()   # submitter (queue-span owner)
        self._event = threading.Event()
        self._result = None
        self._error = None

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline

    def _finish_trace(self, outcome, error=None):
        tr = self.trace
        if tr is None or self.timing is not None:
            return
        if 'queue' not in tr.stages:
            # never dispatched (expired/shed/stopped in queue): its whole
            # life was queue wait — account it so the breakdown composes
            tr.add_stage('queue', max(0.0,
                                      time.monotonic() - self.enqueue_t))
        self.timing = trace_mod.flat_timing(tr.finish(outcome, error=error))

    def done(self, result):
        self._result = result
        self._finish_trace('ok')
        self._event.set()

    def fail(self, error):
        self._error = error
        self._finish_trace(_trace_outcome(error), error)
        self._event.set()

    def result(self, timeout=None):
        """Block until served; raises the per-request error on failure.
        The default timeout is the request's own deadline plus a grace
        second (a caller must never hang past its deadline)."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic()) + 1.0
        if not self._event.wait(timeout):
            raise DeadlineExceededError(
                "request not served within %.3fs" % (timeout or 0.0))
        if self._error is not None:
            raise self._error
        return self._result


class RequestQueue(object):
    """Bounded FIFO of Requests with condition-variable handoff to the
    worker pool."""

    def __init__(self, cap):
        self._cap = max(1, int(cap))
        self._q = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

    @property
    def cap(self):
        return self._cap

    def depth(self):
        with self._lock:
            return len(self._q)

    def put(self, req):
        """Enqueue or shed. Raises LoadShedError when full (the caller
        surfaces it synchronously — shedding must cost nothing but the
        check) and EngineStoppedError after close()."""
        with self._lock:
            if self._closed:
                raise EngineStoppedError("serving engine is stopped")
            if len(self._q) >= self._cap:
                raise LoadShedError('queue_full', len(self._q), self._cap)
            self._q.append(req)
            self._cond.notify()

    def close(self):
        """Stop accepting requests and fail everything still queued —
        a stopped engine must not leave callers blocked forever."""
        with self._lock:
            self._closed = True
            drained, self._q = self._q, []
            self._cond.notify_all()
        for r in drained:
            r.fail(EngineStoppedError("serving engine stopped while the "
                                      "request was queued"))
        return len(drained)

    @property
    def closed(self):
        return self._closed

    def take_batch(self, max_rows, max_wait_s, poll_s=0.1):
        """Form one batch: [compatible requests], or (None, expired) when
        the queue stayed empty for poll_s (callers loop; lets workers
        observe shutdown). Returns (batch, expired) — `expired` requests
        were dropped at collection and must be failed by the caller
        OUTSIDE the queue lock."""
        expired = []
        with self._lock:
            if not self._q and not self._closed:
                self._cond.wait(poll_s)
            first = self._pop_live(None, expired)
            if first is None:
                return None, expired
            batch = [first]
            rows = first.n_rows
            t_close = time.monotonic() + max_wait_s
            while rows < max_rows:
                got = self._pop_live(first, expired,
                                     max_rows=max_rows - rows)
                if got is not None:
                    batch.append(got)
                    rows += got.n_rows
                    continue
                remaining = t_close - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        return batch, expired

    def _pop_live(self, proto, expired, max_rows=None):
        """Pop the oldest live request compatible with `proto` (None =
        any); collects expired requests into `expired` as it scans.
        Callers hold the lock."""
        now = time.monotonic()
        for i, r in enumerate(self._q):
            if r.expired(now):
                continue
            if proto is not None and (
                    r.key != proto.key or
                    (max_rows is not None and r.n_rows > max_rows)):
                continue
            # sweep expired entries sitting ahead of the pick so they
            # fail fast instead of rotting until a compatible scan
            keep = []
            for j, s in enumerate(self._q):
                if j == i:
                    continue
                (expired if s.expired(now) else keep).append(s)
            self._q = keep
            return r
        kept = [r for r in self._q if not r.expired(now)]
        expired.extend(r for r in self._q if r.expired(now))
        self._q = kept
        return None
