"""ModelFleet: many models resident in ONE process under a shared
HBM / paged-block budget, with zero-downtime hot-swap.

The inference layer up to PR 18 serves one model per engine; the
north-star traffic shape ("millions of users") runs a FLEET — an fp32
flagship, its int8 variant for the cheap tier, draft models for
speculation — co-resident so they share the process's compile cache,
warmup farm and HBM instead of paying a process each. This module is
that residency layer; router.py in front of it decides admission.

**Residency budget.** Two budgets, both optional:

- ``hbm_budget_bytes`` bounds summed parameter bytes across resident
  models (measured from each predictor's scope at deploy — int8
  artifacts really are ~4x cheaper here). A deploy that would overflow
  is REFUSED before it loads traffic-visible state.
- ``block_budget`` (+ ``block_size``) sizes ONE shared
  `BlockAllocator` pool for paged decode tenants: each attached
  `GenerateEngine` gets a `QuotaBlockAllocator` view
  (``fleet.block_view(tenant, quota)``) so per-tenant quotas are
  enforced against one physical free list, and one tenant's
  ``cache_full`` pressure can never evict another tenant's prefix
  blocks (each engine's PrefixCache lives over its own view).

**Zero-downtime hot-swap.** ``deploy(name, path)`` on an already-
resident name builds the NEW engine fully off to the side: load the
``load_inference_model`` artifact (fp32 or int8 — the loader
recognizes quantized blobs), warm every ladder cell through the
process-wide warmup farm (an artifact with the same program structure
re-warms at cache-hit speed — ``recompiles_after_warmup == 0`` is the
measured contract), start its workers, and only then atomically flip
the name to the new engine. The OLD engine is drained — submissions
already routed to it finish normally (its queue empties, in-flight
batches deliver) — and stopped only once idle, so a hot-swap under
live traffic completes with zero failed or dropped in-flight requests
(asserted in tests/test_fleet.py; measured in the ``serving_fleet``
bench row). A deploy that fails anywhere before the flip leaves the
old version serving untouched and publishes a ``deploy_failed``
flight-recorder bundle.

Metrics: ``fleet_deploy_total{outcome}``, ``fleet_models`` /
``fleet_resident_bytes`` gauges (docs/observability.md), plus the
router's ``fleet_request_total`` / ``fleet_scale_hint`` series.
"""
import threading
import time

import numpy as np

from .. import goodput
from .. import monitor
from .batcher import ServingError
from .engine import ServingConfig, ServingEngine
from .kv_blocks import BlockAllocator, QuotaBlockAllocator

__all__ = ['FleetError', 'ModelFleet']


class FleetError(ServingError):
    """Fleet-level deployment/residency failure (budget overflow,
    unknown model, missing block pool)."""


class ModelFleet(object):
    """Multi-model residency under shared budgets (module docstring). ::

        fleet = ModelFleet(hbm_budget_bytes=2 << 30)
        fleet.deploy('bert_fp32', 'models/bert_fp32',
                     warm_feed={'x': example})
        fleet.deploy('bert_int8', 'models/bert_int8',
                     warm_feed={'x': example})
        req = fleet.submit('bert_int8', {'x': rows})
        ...
        fleet.deploy('bert_fp32', 'models/bert_fp32_v2',
                     warm_feed={'x': example})   # hot-swap, zero drops
        fleet.stop()
    """

    def __init__(self, hbm_budget_bytes=None, block_budget=None,
                 block_size=16):
        self.hbm_budget_bytes = hbm_budget_bytes
        self._lock = threading.RLock()
        self._models = {}       # name -> record dict
        self._reserved = {}     # in-flight deploy token -> pending bytes
        self._block_pool = None
        if block_budget is not None:
            # +1 physical block: block 0 is the pool's reserved trash
            # block, so `block_budget` stays the ALLOCATABLE capacity
            self._block_pool = BlockAllocator(int(block_budget) + 1,
                                              int(block_size))

    # ------------------------------------------------------------------
    # residency
    @property
    def block_pool(self):
        return self._block_pool

    def block_view(self, tenant, quota):
        """A per-tenant `QuotaBlockAllocator` over the fleet's shared
        block pool — pass it to ``GenerateEngine(block_allocator=)``."""
        if self._block_pool is None:
            raise FleetError(
                "this fleet has no shared block pool — construct with "
                "block_budget= to host paged decode tenants")
        return QuotaBlockAllocator(self._block_pool, quota,
                                   tenant=tenant)

    def models(self):
        with self._lock:
            return sorted(self._models)

    def engine(self, name):
        """The CURRENT engine serving `name` (hot-swap flips this)."""
        with self._lock:
            return self._record(name)['engine']

    def version(self, name):
        with self._lock:
            return self._record(name)['version']

    def _record(self, name):
        rec = self._models.get(name)
        if rec is None:
            raise FleetError("no model %r resident (have: %s)"
                             % (name, sorted(self._models)))
        return rec

    @staticmethod
    def _resident_bytes(predictor):
        """Weight bytes resident for one loaded model (the HBM budget's
        unit of account): every PERSISTABLE array in the predictor's
        private scope — not just Parameters, because a PTQ artifact's
        int8 blobs are persistable plain Variables and they ARE the
        resident weights (counted at their real 1-byte width, which is
        what makes the int8 variant ~4x cheaper under the budget).

        Returns None when the scope walk itself fails — the caller must
        not price an unmeasurable model as free (deploy refuses it when
        an HBM budget is set, and counts the failure either way)."""
        total = 0
        try:
            for v in predictor.program.global_block().vars.values():
                if not getattr(v, 'persistable', False):
                    continue
                try:
                    total += int(np.asarray(
                        predictor.scope.get(v.name)).nbytes)
                except Exception:   # noqa: BLE001 — unmaterialized var
                    continue
        except Exception:           # noqa: BLE001 — measurement failed
            return None
        return total

    def _set_gauges_locked(self):
        monitor.set_gauge('fleet_models', float(len(self._models)))
        monitor.set_gauge('fleet_resident_bytes',
                          float(sum(r['bytes']
                                    for r in self._models.values())))

    # ------------------------------------------------------------------
    # deploy / hot-swap
    def deploy(self, name, path, warm_feed=None, drain_timeout_s=30.0,
               **config_kw):
        """Load (first deploy) or hot-swap (already-resident name) model
        `name` from the ``load_inference_model`` artifact at `path`.
        `warm_feed` (one representative request feed) warms every
        ladder cell through the warmup farm BEFORE the new version sees
        traffic; `config_kw` forwards to `ServingConfig`.

        Returns ``{'model', 'version', 'resident_bytes', 'warm',
        'swapped', 'drained_ok', 'seconds'}``. On any failure before
        the traffic flip the old version keeps serving and the error
        re-raises (``deploy_failed`` flight-recorder bundle +
        ``fleet_deploy_total{outcome=failed}``)."""
        t0 = time.perf_counter()
        engine = None
        token = object()        # this deploy's budget-reservation key
        try:
            cfg = ServingConfig(path, name=name, **config_kw)
            engine = ServingEngine(cfg)
            size = self._resident_bytes(engine.predictor)
            if size is None:
                monitor.inc('fleet_size_measure_errors_total')
                if self.hbm_budget_bytes is not None:
                    raise FleetError(
                        "could not measure resident bytes for %r — an "
                        "unmeasurable model cannot be admitted under "
                        "the %d-byte HBM budget"
                        % (name, self.hbm_budget_bytes))
                size = 0
            with self._lock:
                if self.hbm_budget_bytes is not None:
                    old = self._models.get(name)
                    projected = size + sum(self._reserved.values()) \
                        + sum(r['bytes']
                              for n, r in self._models.items()
                              if n != name) + (0 if old is None
                                               else old['bytes'])
                    # the old version stays resident until the new one
                    # is live — a swap transiently holds BOTH. The
                    # reservation makes check-and-charge atomic: a
                    # concurrent deploy prices this one in even though
                    # it only registers after warmup, seconds from now.
                    if projected > self.hbm_budget_bytes:
                        raise FleetError(
                            "deploying %r (%d bytes) would put fleet "
                            "residency at %d bytes, over the %d-byte "
                            "HBM budget" % (name, size, projected,
                                            self.hbm_budget_bytes))
                    self._reserved[token] = size
            warm = engine.warmup(warm_feed) \
                if warm_feed is not None else None
            engine.start()
        except Exception as e:
            with self._lock:
                self._reserved.pop(token, None)
            if engine is not None:
                try:
                    engine.stop(timeout_s=1.0)
                except Exception:   # noqa: BLE001 — best-effort cleanup
                    pass
            monitor.inc('fleet_deploy_total',
                        labels={'outcome': 'failed'})
            try:
                from .. import blackbox
                blackbox.record('deploy_failed', error=e, model=name,
                                path=str(path),
                                resident=sorted(self._models))
            except Exception:       # noqa: BLE001 — telemetry only
                monitor.inc('blackbox_write_errors_total')
            raise
        with self._lock:
            self._reserved.pop(token, None)
            old = self._models.get(name)
            version = 1 if old is None else old['version'] + 1
            self._models[name] = {
                'engine': engine, 'path': str(path), 'version': version,
                'bytes': size, 'warm': warm, 'external': False,
            }
            self._set_gauges_locked()
        drained_ok = True
        if old is not None:
            # new version is live — drain the old one WITHOUT failing
            # anything: its queue empties through its own workers,
            # in-flight batches deliver, then stop() joins an idle pool
            drained_ok = self._drain_and_stop(old['engine'],
                                              drain_timeout_s)
        monitor.inc('fleet_deploy_total', labels={'outcome': 'ok'})
        return {
            'model': name, 'version': version, 'resident_bytes': size,
            'warm': warm, 'swapped': old is not None,
            'drained_ok': drained_ok,
            'seconds': round(time.perf_counter() - t0, 3),
        }

    def attach(self, name, engine, resident_bytes=0):
        """Register a pre-built engine (e.g. a paged `GenerateEngine`
        over ``block_view(...)``) as resident model `name`. The fleet
        routes to it and stops it with the fleet; deploy-style
        hot-swap stays the ServingEngine path."""
        with self._lock:
            if name in self._models:
                raise FleetError("model %r already resident — deploy() "
                                 "is the swap path" % name)
            self._models[name] = {
                'engine': engine, 'path': None, 'version': 1,
                'bytes': int(resident_bytes), 'warm': None,
                'external': True,
            }
            self._set_gauges_locked()
        return engine

    def unload(self, name, drain_timeout_s=30.0):
        """Drain and stop model `name`, releasing its residency."""
        with self._lock:
            rec = self._record(name)
            del self._models[name]
            self._set_gauges_locked()
        return self._drain_and_stop(rec['engine'], drain_timeout_s)

    def _drain_and_stop(self, engine, timeout_s):
        """Wait until `engine` has nothing queued or in flight, then
        stop it. Returns True when it drained inside the timeout (a
        False stop still delivers in-flight batches; only still-QUEUED
        requests would fail — the fleet lock guarantees no new
        submissions target a flipped-out engine)."""
        def busy():
            if engine.queue.depth() > 0:
                return True
            infl = getattr(engine, '_inflight', None)
            if infl is not None:            # ServingEngine batches
                return infl(0) > 0
            slots = getattr(engine, '_slots', None)
            if slots is not None:           # GenerateEngine residents
                return any(s is not None for s in slots)
            return False

        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            try:
                if not busy():
                    drained = True
                    break
            except Exception:   # noqa: BLE001 — engine died mid-drain
                break
            time.sleep(0.005)
        engine.stop()
        return drained

    # ------------------------------------------------------------------
    # request path
    def submit(self, name, feed, deadline_s=None, **kw):
        """Submit one request to the CURRENT version of model `name`
        (the router's dispatch target). Holding the fleet lock across
        the engine's submit makes the hot-swap flip atomic against
        admissions: a request is either fully in the old engine's queue
        before the drain begins, or lands in the new one."""
        with self._lock:
            engine = self._record(name)['engine']
            return engine.submit(feed, deadline_s=deadline_s, **kw)

    def run(self, name, feed, deadline_s=None, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(name, feed, deadline_s=deadline_s,
                           **kw).result(timeout)

    # ------------------------------------------------------------------
    def stats(self):
        """Per-model residency + engine stats + live cost estimates,
        plus shared block-pool accounting when the fleet hosts paged
        tenants."""
        with self._lock:
            names = dict(self._models)
            pool = self._block_pool
        out = {'models': {}, 'hbm_budget_bytes': self.hbm_budget_bytes,
               'resident_bytes_total': 0}
        for name, rec in sorted(names.items()):
            try:
                estats = rec['engine'].stats()
            except Exception:   # noqa: BLE001 — stats stay best-effort
                estats = None
            out['models'][name] = {
                'version': rec['version'],
                'path': rec['path'],
                'resident_bytes': rec['bytes'],
                'warm': rec['warm'],
                'engine': estats,
                'cost': goodput.cost_estimate(name),
            }
            out['resident_bytes_total'] += rec['bytes']
        if pool is not None:
            out['blocks'] = {
                'block_size': pool.block_size,
                'capacity': pool.capacity,
                'in_use': pool.in_use(),
                'free': pool.available(),
            }
        return out

    def stop(self, drain_timeout_s=10.0):
        """Drain and stop every resident engine (process shutdown)."""
        with self._lock:
            recs = list(self._models.values())
            self._models = {}
            self._set_gauges_locked()
        for rec in recs:
            try:
                self._drain_and_stop(rec['engine'], drain_timeout_s)
            except Exception:   # noqa: BLE001 — shutdown is best-effort
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
