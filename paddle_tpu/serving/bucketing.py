"""Shape-bucket ladder for the serving engine: bounded compile count.

XLA compiles per feed signature, so an engine that dispatched each
dynamically-formed batch at its natural size would recompile on every new
(batch rows, sequence length) pair — unbounded compiles under organic
traffic, each a multi-second latency spike. The remedy is the same
canonical-padding recipe `reader/bucketing.py` applies to ragged training
batches, lifted to the request path: every batch is padded UP to a fixed
ladder of (batch-rows bucket, sequence bucket) cells, so the steady state
executes at most ``len(batch_buckets) * len(seq_buckets)`` distinct
signatures — all of which ``ServingEngine.warmup()`` compiles ahead of
traffic, making the steady state hit the PR 1 fingerprint compile cache
with zero recompiles.

Row padding replicates the LAST real row (real data keeps every model
numerically well-behaved — an all-zeros row can hit log(0)/division paths)
and the padded rows' outputs are discarded at un-batching time. Sequence
padding appends ``pad_value`` columns (token-id padding); outputs whose
sequence axis still carries the padded length are sliced back to each
request's real length on the way out.
"""
import numpy as np

from ..reader.bucketing import bucketize

__all__ = ['BucketLadder']


class BucketLadder(object):
    """The serving engine's shape policy.

    batch_buckets: ascending ladder of total-batch row counts; a formed
      batch of N rows pads to the smallest bucket >= N, and the batcher
      never coalesces past the largest bucket.
    seq_buckets: optional ladder for a variable sequence axis. A request's
      sequence length is the ``seq_axis`` extent of its feed arrays (every
      feed array whose rank exceeds ``seq_axis`` and whose ``seq_axis``
      extent equals the request's longest such extent is padded; arrays
      with other extents — fixed-size side inputs — pass through and
      become part of the bucket key instead).
    """

    def __init__(self, batch_buckets, seq_buckets=None, seq_axis=1,
                 pad_value=0):
        if not batch_buckets:
            raise ValueError("batch_buckets must be a non-empty ladder")
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        if any(b < 1 for b in self.batch_buckets):
            raise ValueError("batch_buckets must be >= 1: %r"
                             % (batch_buckets,))
        self.seq_buckets = (sorted(set(int(s) for s in seq_buckets))
                            if seq_buckets else None)
        if int(seq_axis) < 1:
            raise ValueError("seq_axis must be >= 1 (axis 0 is the batch "
                             "row dimension)")
        self.seq_axis = int(seq_axis)
        self.pad_value = pad_value

    @property
    def max_rows(self):
        return self.batch_buckets[-1]

    def batch_bucket(self, n_rows):
        return bucketize(n_rows, self.batch_buckets)

    def seq_bucket(self, length):
        if self.seq_buckets is None:
            return None
        return bucketize(length, self.seq_buckets)

    # ------------------------------------------------------------------
    def request_shape(self, feed):
        """Classify one request's feed: returns (n_rows, seq_len, key).

        n_rows: leading-dim row count shared by every feed array.
        seq_len: the request's real sequence extent (None without
          seq_buckets or when no array has a ``seq_axis`` dimension).
        key: the BUCKET-GROUP key — requests coalesce into one batch iff
          their keys are equal, i.e. identical feed names, dtypes,
          per-row shapes AFTER sequence padding, and seq bucket. The key
          is also the compile-signature identity warmup() enumerates.
        Raises ValueError (with a structured message) for feeds the
        ladder cannot serve — over-long sequences, over-wide requests,
        mismatched leading dims.
        """
        if not feed:
            raise ValueError("serving request: empty feed")
        arrays = {n: np.asarray(v) for n, v in feed.items()}
        rows = {a.shape[0] if a.ndim else None for a in arrays.values()}
        if None in rows or len(rows) != 1:
            raise ValueError(
                "serving request: every feed array needs the same leading "
                "batch dim; got %s"
                % {n: tuple(a.shape) for n, a in arrays.items()})
        n_rows = rows.pop()
        if n_rows < 1:
            raise ValueError("serving request: zero-row feed")
        if n_rows > self.max_rows:
            raise ValueError(
                "serving request: %d rows exceed the largest batch bucket "
                "%d — split the request or widen the ladder"
                % (n_rows, self.max_rows))

        seq_len = None
        if self.seq_buckets is not None:
            lens = [a.shape[self.seq_axis] for a in arrays.values()
                    if a.ndim > self.seq_axis]
            if lens:
                seq_len = max(lens)
                if seq_len > self.seq_buckets[-1]:
                    raise ValueError(
                        "serving request: sequence length %d exceeds the "
                        "largest seq bucket %d — trim the input or widen "
                        "the ladder" % (seq_len, self.seq_buckets[-1]))
        sb = self.seq_bucket(seq_len) if seq_len is not None else None

        key_parts = []
        for name in sorted(arrays):
            a = arrays[name]
            shape = list(a.shape[1:])
            if sb is not None and a.ndim > self.seq_axis and \
                    a.shape[self.seq_axis] == seq_len:
                shape[self.seq_axis - 1] = sb
            key_parts.append((name, str(a.dtype), tuple(shape)))
        return n_rows, seq_len, (sb, tuple(key_parts))

    def pad_request(self, feed, seq_len):
        """Pad one request's sequence axes up to the bucket (row count
        untouched). Returns {name: ndarray}."""
        if seq_len is None:
            return {n: np.asarray(v) for n, v in feed.items()}
        sb = self.seq_bucket(seq_len)
        out = {}
        for name, v in feed.items():
            a = np.asarray(v)
            if a.ndim > self.seq_axis and a.shape[self.seq_axis] == seq_len \
                    and sb > seq_len:
                pad = [(0, 0)] * a.ndim
                pad[self.seq_axis] = (0, sb - seq_len)
                a = np.pad(a, pad, mode='constant',
                           constant_values=self.pad_value)
            out[name] = a
        return out

    def pad_rows(self, stacked, n_rows):
        """Pad a concatenated {name: [N, ...]} batch up to the batch
        bucket by replicating the last real row; returns (padded_feed,
        padded_rows)."""
        b = self.batch_bucket(n_rows)
        if b == n_rows:
            return stacked, b
        out = {}
        for name, a in stacked.items():
            fill = np.repeat(a[-1:], b - n_rows, axis=0)
            out[name] = np.concatenate([a, fill], axis=0)
        return out, b

    def bucket_grid(self):
        """Every (batch_bucket, seq_bucket) cell warmup() must compile."""
        seqs = self.seq_buckets if self.seq_buckets is not None else [None]
        return [(bb, sb) for bb in self.batch_buckets for sb in seqs]
