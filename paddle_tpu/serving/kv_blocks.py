"""Physical KV-cache block accounting for the paged decode engine.

The paged cache (ops/kv_cache_ops.py paged variants) is a pool of
fixed-size physical blocks addressed through runtime-fed per-slot block
tables. Two host-side structures own the pool:

- ``BlockAllocator``: free-list + per-block refcounts over blocks
  ``1..num_blocks-1`` (block 0 is the TRASH block — table filler and
  pad-write target — and is never handed out). Admission becomes a
  blocks-available decision; a finished or evicted request's ``deref``
  returns refcount-0 blocks to the free list.
- ``PrefixCache``: content-addressed map from prompt-prefix CHAIN hashes
  (one per full block of prompt tokens) to the physical block already
  holding that prefix's K/V. A hit maps the new request's leading table
  entries onto the SAME physical blocks (refcount++) — the identical
  system prompt of a million-user service is stored once and its
  prefill computed once. The cache itself holds one reference per
  registered block, so prefix blocks survive their creator request and
  are reclaimed lazily, LRU-deepest-first, only under allocation
  pressure.

Speculative decoding (PR 13) rides the same accounting: the verify
window's tail blocks are ordinary refcount-1 allocations, and ROLLBACK
after a rejected draft is nothing but ``deref_many`` on the blocks past
the accepted write head — the block table is the rollback mechanism, so
a rejected speculation costs exactly the allocator bookkeeping of the
blocks it briefly held. The draft model keeps a SECOND allocator over
its own pool (sized ``slots * max_len / block_size`` + trash, so
per-slot growth can never starve) with no prefix cache — draft K/V are
model-specific throwaways.

Sharing is at FULL-BLOCK granularity. Because a block's K/V rows depend
only on tokens at or before them (causal), a block fully covered by
prompt tokens is immutable once prefilled — the one exception is a
request whose ENTIRE prompt lands on shared blocks (prompt length a
multiple of block_size and all blocks hit): its last prompt position
must be recomputed to produce the first token, which makes its final
block's row a divergent write → the engine copies that block first
(copy-on-write, ``kv_block_cow_total``) and writes into the private
copy. Neither sharer ever observes the other's tokens.
"""
import hashlib
import threading

__all__ = ['BlockAllocator', 'PrefixCache', 'QuotaBlockAllocator',
           'chain_hashes']


def chain_hashes(tokens, block_size):
    """One chained content hash per FULL block of `tokens`: hash i
    commits to every token in blocks 0..i, so equal hash means equal
    whole prefix (not just an equal i-th block)."""
    out, h = [], b'kv-prefix'
    n_full = len(tokens) // block_size
    for i in range(n_full):
        blk = tokens[i * block_size:(i + 1) * block_size]
        h = hashlib.sha1(
            h + b'|' + b','.join(b'%d' % int(t) for t in blk)).digest()
        out.append(h)
    return out


class BlockAllocator(object):
    """Free-list + refcount accounting over `num_blocks` physical blocks.
    Block 0 is reserved (trash) and never allocated; `capacity` is the
    usable pool size (num_blocks - 1).

    Thread-safe: a fleet hands per-tenant `QuotaBlockAllocator` views
    over ONE pool to multiple decode-loop threads, so every mutation
    (and every check that gates one) runs under the pool's reentrant
    `lock` — views take the SAME lock so their quota check-and-charge
    is atomic against concurrent tenants."""

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ValueError(
                "paged cache needs >= 2 physical blocks (block 0 is the "
                "reserved trash block), got %d" % num_blocks)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.lock = threading.RLock()
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks

    @property
    def capacity(self):
        return self.num_blocks - 1

    def available(self):
        with self.lock:
            return len(self._free)

    def in_use(self):
        with self.lock:
            return self.capacity - len(self._free)

    def refcount(self, bid):
        with self.lock:
            return self._ref[bid]

    def alloc(self, n):
        """n fresh blocks at refcount 1, or None when the free list is
        short (nothing is partially allocated on failure)."""
        with self.lock:
            if n > len(self._free):
                return None
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            return out

    def ref(self, bid):
        with self.lock:
            if self._ref[bid] < 1:
                raise ValueError("ref of unallocated block %d" % bid)
            self._ref[bid] += 1

    def deref(self, bid):
        """Drop one reference; a refcount-0 block returns to the free
        list. Returns True when the block was actually freed."""
        with self.lock:
            if self._ref[bid] < 1:
                raise ValueError("deref of unallocated block %d" % bid)
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                self._free.append(bid)
                return True
            return False

    def deref_many(self, bids):
        """`deref` a batch (slot release, speculative-tail rollback);
        returns how many blocks actually went back to the free list."""
        with self.lock:
            freed = 0
            for b in bids:
                if self.deref(b):
                    freed += 1
            return freed


class QuotaBlockAllocator(object):
    """A per-tenant VIEW over a shared ``BlockAllocator`` pool: the same
    interface a `GenerateEngine` allocates through, bounded by `quota`
    DISTINCT physical blocks. Multiple tenants resident in one process
    (ModelFleet) each hold a view over the one pool sized to the real
    HBM budget; a tenant's admission/growth then competes only inside
    its quota and the pool's free list — one tenant can never allocate
    the pool empty past its own share.

    Accounting: a view is charged one unit per DISTINCT block it holds
    at least one reference to (extra refs to an owned block — the
    within-tenant prefix-sharing case — consume no additional physical
    blocks and are not double-charged). ``in_use()`` is the tenant's
    footprint, ``capacity`` its quota, ``available()`` the admission
    headroom = min(pool free, quota remaining). Eviction isolation is
    structural: each tenant's `PrefixCache` is built over its own view,
    so ``evict_for`` under one tenant's allocation pressure only ever
    walks (and derefs) that tenant's entries.

    Every view method runs under the POOL's reentrant lock (the quota
    check and the pool mutation must be one atomic step — two tenants'
    decode threads race on the same free list otherwise)."""

    def __init__(self, pool, quota, tenant=None):
        quota = int(quota)
        if quota < 1:
            raise ValueError("block quota must be >= 1, got %d" % quota)
        self.pool = pool
        self.quota = quota
        self.tenant = tenant
        self.block_size = pool.block_size
        self.lock = pool.lock
        self._held = {}         # block id -> refs held through this view

    @property
    def capacity(self):
        return min(self.quota, self.pool.capacity)

    def available(self):
        with self.lock:
            return max(0, min(self.pool.available(),
                              self.quota - len(self._held)))

    def in_use(self):
        with self.lock:
            return len(self._held)

    def refcount(self, bid):
        return self.pool.refcount(bid)

    def alloc(self, n):
        with self.lock:
            if len(self._held) + n > self.quota:
                return None
            out = self.pool.alloc(n)
            if out is not None:
                for b in out:
                    self._held[b] = 1
            return out

    def ref(self, bid):
        with self.lock:
            if bid not in self._held and len(self._held) >= self.quota:
                raise ValueError(
                    "ref of block %d would exceed tenant %r quota %d"
                    % (bid, self.tenant, self.quota))
            self.pool.ref(bid)
            self._held[bid] = self._held.get(bid, 0) + 1

    def deref(self, bid):
        with self.lock:
            held = self._held.get(bid, 0)
            if held < 1:
                raise ValueError(
                    "deref of block %d not held by tenant %r"
                    % (bid, self.tenant))
            if held == 1:
                del self._held[bid]
            else:
                self._held[bid] = held - 1
            return self.pool.deref(bid)

    def deref_many(self, bids):
        with self.lock:
            freed = 0
            for b in bids:
                if self.deref(b):
                    freed += 1
            return freed


class PrefixCache(object):
    """hash-chain -> physical block map with LRU pressure eviction.

    Each registered block carries ONE cache reference (so it outlives
    its creator request). `match` walks the chain from depth 0 and
    returns the longest cached run; `evict_for` releases stale entries
    — least-recently-used first, deepest entry first within a tie, so a
    chain never loses a shallow link before its deeper ones — until the
    allocator can satisfy a request, and is only called under
    allocation pressure."""

    def __init__(self, alloc):
        self._alloc = alloc
        self._entries = {}      # hash -> [block_id, depth, last_used]
        self._clock = 0

    def __len__(self):
        return len(self._entries)

    def match(self, hashes):
        """Longest cached prefix run for `hashes` (chain order): the
        list of physical block ids, NOT yet referenced — the caller
        refs the ones it keeps."""
        self._clock += 1
        out = []
        for i, h in enumerate(hashes):
            e = self._entries.get(h)
            if e is None or e[1] != i:      # depth-checked: chains only
                break                       # ever match from the root
            e[2] = self._clock
            out.append(e[0])
        return out

    def register(self, h, depth, block_id):
        """Publish `block_id` as the home of chain hash `h` (depth =
        its block index within the prompt). First writer wins — an
        already-registered hash keeps its existing block."""
        if h in self._entries:
            return False
        self._clock += 1
        self._alloc.ref(block_id)
        self._entries[h] = [block_id, int(depth), self._clock]
        return True

    def evict_for(self, n_needed):
        """Drop cache-only entries (block refcount 1 — no live slot)
        until the allocator has `n_needed` free blocks. Returns the
        number of entries evicted."""
        if self._alloc.available() >= n_needed:
            return 0
        victims = sorted(self._entries.items(),
                         key=lambda kv: (kv[1][2], -kv[1][1]))
        evicted = 0
        for h, (bid, _depth, _used) in victims:
            if self._alloc.available() >= n_needed:
                break
            if self._alloc.refcount(bid) == 1:   # only the cache holds it
                del self._entries[h]
                self._alloc.deref(bid)
                evicted += 1
        return evicted

    def drop_all(self):
        """Release every cached entry (engine shutdown)."""
        for h, (bid, _d, _u) in list(self._entries.items()):
            del self._entries[h]
            self._alloc.deref(bid)
