"""Goodput-priced admission router for a multi-tenant model fleet.

The fleet (fleet.py) makes many models resident in one process; this
module decides WHO gets on the accelerator. Each tenant maps to one
resident model and carries a priority class, a default deadline and an
outstanding-work quota; the router prices every admission with the LIVE
per-model cost estimate ``goodput.cost_estimate(model)`` — device-
seconds per dispatch measured by the PR 14 accounting, never a
hardcoded table — and admits or sheds against three invariants:

- **tenant quota**: at most ``max_outstanding`` of a tenant's requests
  in flight (``LoadShedError(reason='tenant_quota')``).
- **deadline feasibility**: the estimated backlog of work at this
  tenant's priority or higher, plus this request's own estimated cost,
  must fit inside the request's deadline
  (``reason='deadline_unmeetable'`` — admitting would only burn device
  time on a request that cannot make it).
- **priority protection**: a LOWER-priority admission may only use the
  capacity slack that keeps every higher-priority tenant's deadline
  feasible: if total estimated backlog + this cost exceeds a
  higher-priority tenant's ``deadline_s * headroom_frac``, the cheap
  request sheds (``reason='priority_backlog'``) instead of starving the
  deadline traffic. High-priority admissions ignore lower-priority
  backlog entirely — the asymmetry is the point.

Before any dispatch has been accounted for a model, ``cost_estimate``
returns None and the router admits at ``default_cost_s`` (0 — admit and
learn); the estimates sharpen as traffic flows.

**Scale-out signal.** The router keeps a per-tenant queue-wait EWMA
(the PR 14 ``queue_burn`` sentinel shape, but per tenant — goodput's
own stream is process-wide). A tenant whose EWMA burns past its
``slo_ms`` drives the ``fleet_scale_hint{tenant}`` gauge (EWMA / SLO —
>1 means "add replicas") and the ``on_scale_hint(tenant, hint, state)``
callback a replica manager consumes, and publishes a
``fleet_slo_burn`` flight-recorder bundle (blackbox.py) carrying every
tenant's queue state. A shed storm (``storm_n`` sheds inside
``storm_window_s``) publishes the same kind with ``cause='shed_storm'``.

Metrics: ``fleet_request_total{tenant, outcome}``
(admitted|shed_tenant_quota|shed_deadline_unmeetable|
shed_priority_backlog), ``fleet_scale_hint{tenant}``. See
docs/serving.md "Multi-tenant fleet" for the policy math and
docs/observability.md for the series.
"""
import collections
import threading
import time

from .. import goodput
from .. import monitor
from .batcher import LoadShedError

__all__ = ['TenantConfig', 'Router']


class TenantConfig(object):
    """One tenant's admission contract.

    - model: resident model name in the fleet this tenant's traffic
      routes to.
    - priority: integer class, HIGHER is more important. Admission of a
      request only competes against backlog at its own priority or
      above; lower classes are invisible to it.
    - deadline_s: default per-request deadline (None = the engine's
      default; also disables the feasibility check).
    - max_outstanding: cap on this tenant's in-flight requests (None =
      unbounded — the engine queue_cap still backstops).
    - slo_ms: queue-wait SLO driving the per-tenant scale hint (None
      disables the hint for this tenant).
    - min_samples: waits observed before the EWMA may trip the hint.
    - headroom_frac: fraction of this tenant's deadline lower-priority
      work may fill before it sheds (protection threshold; 1.0 = the
      whole deadline).
    """

    def __init__(self, model, priority=0, deadline_s=None,
                 max_outstanding=None, slo_ms=None, min_samples=4,
                 headroom_frac=1.0):
        self.model = str(model)
        self.priority = int(priority)
        self.deadline_s = deadline_s
        self.max_outstanding = max_outstanding
        self.slo_ms = slo_ms
        self.min_samples = int(min_samples)
        self.headroom_frac = float(headroom_frac)


class Router(object):
    """Priority/deadline admission over a ModelFleet (module docstring
    has the policy). ::

        router = Router(fleet, tenants={
            'premium': TenantConfig('bert_fp32', priority=10,
                                    deadline_s=0.5, slo_ms=50.0),
            'batch':   TenantConfig('bert_int8', priority=0,
                                    deadline_s=30.0, max_outstanding=64),
        }, on_scale_hint=lambda tenant, hint, state: ...)
        req = router.submit('premium', {'x': rows})
        out = req.result()
    """

    def __init__(self, fleet, tenants=None, on_scale_hint=None,
                 default_cost_s=0.0, hint_cooldown_s=30.0,
                 storm_n=10, storm_window_s=5.0):
        self._fleet = fleet
        self._tenants = {}
        self._lock = threading.Lock()
        self._out = {}          # tenant -> [[req, est_s, t_submit], ...]
        self._waits = {}        # tenant -> {'n': int, 'ewma': float|None}
        self._sheds = {}        # tenant -> deque of shed perf times
        self._shed_n = {}       # tenant -> lifetime shed count
        self._burn_last = {}    # (tenant, cause) -> last publish time
        self._burns = []        # queued burn events, delivered unlocked
        self.on_scale_hint = on_scale_hint
        self.default_cost_s = float(default_cost_s)
        self.hint_cooldown_s = float(hint_cooldown_s)
        self.storm_n = int(storm_n)
        self.storm_window_s = float(storm_window_s)
        for name, cfg in (tenants or {}).items():
            self.add_tenant(name, cfg)

    def add_tenant(self, name, cfg):
        if not isinstance(cfg, TenantConfig):
            raise TypeError("add_tenant takes a TenantConfig, got %r"
                            % (cfg,))
        with self._lock:
            self._tenants[str(name)] = cfg
            self._out.setdefault(str(name), [])
            self._waits.setdefault(str(name), {'n': 0, 'ewma': None})
            self._sheds.setdefault(str(name),
                                   collections.deque(maxlen=256))
            self._shed_n.setdefault(str(name), 0)
        return cfg

    def cost(self, model):
        """Estimated device-seconds one dispatch of `model` costs right
        now (goodput.cost_estimate; default_cost_s before any sample)."""
        est = goodput.cost_estimate(model)
        if est is None:
            return self.default_cost_s
        return est['device_s_per_dispatch']

    # ------------------------------------------------------------------
    # admission
    def submit(self, tenant, feed, deadline_s=None, **kw):
        """Admit one request for `tenant` (raises KeyError for unknown
        tenants, LoadShedError with a structured reason on shed) and
        submit it to the tenant's model through the fleet. Returns the
        engine's Request future."""
        cfg = self._tenants[tenant]
        if deadline_s is None:
            deadline_s = cfg.deadline_s
        est = self.cost(cfg.model)
        # the admission decision and its bookkeeping are ONE locked
        # step: the provisional entry (req slot still None) lands in
        # the outstanding book before the lock drops, so concurrent
        # submits see each other's quota/backlog charge even though the
        # fleet dispatch happens unlocked below
        rec = [None, est, time.monotonic()]
        try:
            with self._lock:
                self._reap_locked()
                mine = self._out[tenant]
                if cfg.max_outstanding is not None and \
                        len(mine) >= cfg.max_outstanding:
                    raise self._shed_locked(tenant, 'tenant_quota',
                                            len(mine),
                                            cfg.max_outstanding)
                backlog_ge = 0.0
                backlog_all = 0.0
                for t, entries in self._out.items():
                    s = sum(e for _r, e, _t in entries)
                    backlog_all += s
                    if self._tenants[t].priority >= cfg.priority:
                        backlog_ge += s
                if deadline_s is not None and \
                        backlog_ge + est > deadline_s:
                    raise self._shed_locked(tenant,
                                            'deadline_unmeetable',
                                            len(mine),
                                            cfg.max_outstanding or 0)
                for hname, hcfg in self._tenants.items():
                    if hcfg.priority <= cfg.priority or \
                            hcfg.deadline_s is None:
                        continue
                    if backlog_all + est > \
                            hcfg.deadline_s * hcfg.headroom_frac:
                        raise self._shed_locked(tenant,
                                                'priority_backlog',
                                                len(mine),
                                                cfg.max_outstanding or 0)
                mine.append(rec)
        finally:
            self._deliver_burns()
        try:
            req = self._fleet.submit(cfg.model, feed,
                                     deadline_s=deadline_s, **kw)
        except BaseException:
            with self._lock:
                try:
                    self._out[tenant].remove(rec)
                except ValueError:  # reaped/cleared concurrently
                    pass
            raise
        with self._lock:
            rec[0] = req
        monitor.inc('fleet_request_total',
                    labels={'tenant': tenant, 'outcome': 'admitted'})
        return req

    def _shed_locked(self, tenant, reason, depth, cap):
        """Count one shed, check the storm detector, and build the
        LoadShedError the caller raises (callers hold _lock)."""
        monitor.inc('fleet_request_total',
                    labels={'tenant': tenant, 'outcome': 'shed_' + reason})
        now = time.perf_counter()
        self._sheds[tenant].append(now)
        self._shed_n[tenant] += 1
        lo = now - self.storm_window_s
        n = sum(1 for t in self._sheds[tenant] if t >= lo)
        if n >= self.storm_n and \
                self._burn_ok_locked(tenant, 'shed_storm'):
            self._queue_burn_locked(tenant, 'shed_storm',
                                    sheds_in_window=n,
                                    window_s=self.storm_window_s,
                                    last_reason=reason)
        return LoadShedError(reason, depth, cap)

    # ------------------------------------------------------------------
    # completion reaping + per-tenant queue-burn
    def _reap_locked(self):
        """Drop finished requests from the outstanding books and feed
        each tenant's queue-wait EWMA from the request's own timing
        breakdown (callers hold _lock). An entry whose req slot is
        still None is a submit() mid-dispatch — always live."""
        for tenant, entries in self._out.items():
            live = []
            for rec in entries:
                req = rec[0]
                if req is None or not req._event.is_set():
                    live.append(rec)
                    continue
                wait = None
                if req.timing is not None:
                    wait = req.timing.get('queue_s')
                if wait is not None:
                    hint = self._note_wait_locked(tenant, float(wait))
                    if hint is not None:
                        tenant_, h, ewma_ms, slo_ms = hint
                        self._queue_burn_locked(
                            tenant_, 'queue_burn', hint=round(h, 3),
                            ewma_ms=round(ewma_ms, 3), slo_ms=slo_ms)
            self._out[tenant] = live

    def _note_wait_locked(self, tenant, wait_s):
        """EWMA one observed queue wait; returns a (tenant, hint,
        ewma_ms, slo_ms) tuple when the SLO is burning past cooldown."""
        cfg = self._tenants[tenant]
        st = self._waits[tenant]
        st['n'] += 1
        a = 0.3
        st['ewma'] = wait_s if st['ewma'] is None else \
            a * wait_s + (1.0 - a) * st['ewma']
        if cfg.slo_ms is None or cfg.slo_ms <= 0:
            return None
        hint = st['ewma'] * 1e3 / cfg.slo_ms
        monitor.set_gauge('fleet_scale_hint', hint,
                          labels={'tenant': tenant})
        if hint > 1.0 and st['n'] >= cfg.min_samples and \
                self._burn_ok_locked(tenant, 'queue_burn'):
            return (tenant, hint, st['ewma'] * 1e3, cfg.slo_ms)
        return None

    def _burn_ok_locked(self, tenant, cause):
        now = time.perf_counter()
        last = self._burn_last.get((tenant, cause))
        if last is not None and now - last < self.hint_cooldown_s:
            return False
        self._burn_last[(tenant, cause)] = now
        return True

    def _queue_burn_locked(self, tenant, cause, **fields):
        """Snapshot the queue state for one SLO-burn event and queue it
        for delivery (callers hold _lock). Delivery — the flight-
        recorder bundle and the scale-hint callback — happens in
        `_deliver_burns` AFTER the lock drops, so a replica-manager
        hook may freely call router.stats() or router.submit() without
        deadlocking the request path."""
        self._burns.append((tenant, cause, fields,
                            self._queue_state_locked()))

    def _deliver_burns(self):
        """Drain queued burn events outside _lock (each event carries
        the state snapshot taken when it fired)."""
        while True:
            with self._lock:
                if not self._burns:
                    return
                tenant, cause, fields, state = self._burns.pop(0)
            try:
                from .. import blackbox
                blackbox.record('fleet_slo_burn', tenant=tenant,
                                cause=cause, tenants=state, **fields)
            except Exception:   # noqa: BLE001 — telemetry only
                monitor.inc('blackbox_write_errors_total')
            cb = self.on_scale_hint
            if cb is not None and cause == 'queue_burn':
                try:
                    cb(tenant, fields.get('hint', 1.0), state)
                except Exception:   # noqa: BLE001 — a broken replica-
                    pass            # manager hook must not fail requests

    def _queue_state_locked(self):
        out = {}
        for tenant, entries in self._out.items():
            cfg = self._tenants[tenant]
            st = self._waits[tenant]
            out[tenant] = {
                'model': cfg.model,
                'priority': cfg.priority,
                'outstanding': len(entries),
                'est_backlog_s': round(sum(e for _r, e, _t in entries),
                                       6),
                'ewma_wait_ms': round(st['ewma'] * 1e3, 3)
                if st['ewma'] is not None else None,
                'sheds': self._shed_n[tenant],
            }
        return out

    # ------------------------------------------------------------------
    def stats(self):
        """Per-tenant queue state + the live per-model cost estimates
        the admission math is currently pricing with."""
        with self._lock:
            self._reap_locked()
            state = self._queue_state_locked()
            models = sorted({c.model for c in self._tenants.values()})
        self._deliver_burns()
        return {
            'tenants': state,
            'costs': {m: goodput.cost_estimate(m) for m in models},
        }
