"""Continuous-batching generative decode engine with a device-resident
KV cache.

The PR 4 `ServingEngine` batches fixed-signature SINGLE-CALL predictors:
work is admitted at batch boundaries, so decode throughput of an
autoregressive model is bounded by the slowest sentence in each batch.
This module is the decode-native path:

- **Persistent device-resident KV cache.** One pair of persistable
  ``[slots, layers, heads, max_len, head_dim]`` buffers
  (models/transformer.py ``KV_CACHE_K``/``KV_CACHE_V``) lives in the
  engine's scope like any other executor state: the decode step reads AND
  writes them, so the PR 1 donation path aliases each step's update in
  place — the cache never doubles in HBM and never crosses the host.
- **Two compiled signatures, fixed forever.** A per-prompt-bucket
  ``prefill`` (prompt lengths pad onto ``prompt_buckets``, the
  reader/bucketing ladder idiom) and ONE single-token ``decode step``
  over all slots. ``warmup()`` compiles every cell through the PR 1
  fingerprint cache; steady-state traffic of ANY prompt/output-length mix
  re-executes exactly that set — ``recompiles_after_warmup = 0``.
- **In-flight (continuous) batching.** New requests are admitted into
  free cache slots at TOKEN boundaries — between decode steps — and
  finished / deadline-expired requests are evicted per step, so a long
  generation never holds short ones hostage. Every op in the step program
  is slot-row-independent (ops/kv_cache_ops.py), so co-residents never
  perturb each other's numerics: tests/test_generate.py pins exact parity
  between concurrent and sequential execution.
- **Streaming responses.** Each `GenerateRequest` is a future AND a token
  stream (``for tok in req.stream()``); per-request deadlines ride the
  PR 4 bounded `RequestQueue` (structured `LoadShedError` backpressure)
  and are enforced both in the queue and mid-generation.

Dispatch rides `Executor.bind` (PR 6): the per-token host tax is state
staging + one compiled call, with fault injection and retry at the 'run'
site exactly as `Executor.run` (a transient fault retries inside the
step; an exhausted retry fails the RESIDENT requests and the engine keeps
serving).

PAGED mode (PR 12, ``GenerateConfig(paged=True)``) replaces the
per-slot ``max_len`` row reservation with a BLOCK pool: the cache is
``[num_blocks, layers, heads, block_size, head_dim]`` and each slot
addresses it through a runtime-fed block table, so HBM is committed as
sequences actually grow — admission is a blocks-available decision
(serving/kv_blocks.py), eviction returns blocks, and a pool that runs
dry finishes the starved request with ``finish_reason='cache_full'``.
On top of the allocator rides PREFIX SHARING: prompts are chain-hashed
per full block, a hit maps the request's leading table entries onto the
blocks already holding that prefix (refcounted; copy-on-write when the
whole prompt lands on shared blocks), and the prefill buckets by
SUFFIX length — shared-prefix traffic skips both the duplicate storage
and the shared prefill compute. Both modes sample: per-request
temperature / top-k / top-p with an independent host PRNG stream per
request (``sample_seed`` replays exactly); temperature 0 stays the
bitwise greedy default, and the program count is unchanged —
``len(prompt_buckets) + 1`` fixed signatures, zero recompiles after
warmup under any mixed paged traffic.

SPECULATIVE DECODING (PR 13, ``GenerateConfig(speculative=True)``,
paged engines only) breaks the one-token-per-dispatch decode ceiling:
a DRAFT model (``draft_model``; default = the target config, so a
seed-built engine drafts with the target's own weights — the
100%-accept reference; an int8-converted or distilled small model is
the production draft) proposes ``spec_k`` greedy tokens per slot in
ONE dispatch (`build_lm_drafter` — the K steps are unrolled in-program,
argmax feeding the next step's embedding on-device), then the target
VERIFIES all proposals in one batched ``spec_k + 1``-wide step
(`build_lm_verify`). Accepted tokens advance both caches; the first
mismatch falls back to the target's own token — since every emitted
token IS the target's argmax given the previously emitted tokens,
greedy output is **bitwise identical** to non-speculative decode,
speculation only changes how many tokens land per dispatch (up to
``spec_k + 1``). Rejected rows roll back through the PAGED block
table: their positions sit past the accepted write head (masked to
exact zero by every later attention), and tail blocks holding no
accepted position return to the allocator — no cache bytes are copied
or cleared. The draft runs against its OWN scope (own parameters, own
paged block pool sized ``slots * max_len / block_size``) so target and
draft state never alias. Sampled requests co-resident on a speculative
engine fall the whole batch back to plain steps for those rounds
(``spec_fallback_total``) — speculation accelerates greedy traffic.

CHUNKED PREFILL (same PR, paged engines): prompts longer than the
widest bucket no longer reject at submit() — the prefill runs in
bucket-sized chunks, each chunk attending the cached prefix through
``kv_prefix_attention`` exactly like a shared-prefix suffix, so
admission now reaches ``max_len - 1`` tokens with ZERO new compiled
signatures and the continuation is bit-exact vs a single-shot prefill
through a wider bucket.

Monitor series: ``decode_tokens_total``, ``kv_slot_occupancy``,
``decode_step_seconds``, ``prefill_seconds``,
``generate_request_total{outcome=ok|error|shed|deadline|rejected|stopped}``,
``generate_queue_depth``, ``generate_step_error_total``,
``generate_warmup_total``; paged mode adds the block-level capacity
accounting ``kv_blocks_in_use`` / ``kv_blocks_free`` gauges (these
replace slot occupancy as the saturation signal — slots no longer bound
memory) and the ``kv_block_cow_total``,
``kv_prefix_hit_total{outcome=hit|miss}`` and
``kv_prefix_tokens_saved_total`` counters. Speculative engines add
``spec_propose_total`` / ``spec_accept_total`` /
``spec_fallback_total`` counters, ``spec_draft_seconds`` /
``spec_verify_seconds`` histograms, per-request ``draft`` / ``verify``
trace stages (sub-stages of the decode wall — tools/tracereport.py
breaks them out per kind) and a ``spec_accept_rate`` field in the
request timing. Full catalog: docs/observability.md; tuning guide:
docs/serving.md.
"""
import queue as _pyqueue
import threading
import time

import numpy as np

from .. import blackbox
from .. import goodput
from .. import monitor
from .. import trace as trace_mod
from .. import unique_name
from ..executor import Executor, Scope, scope_guard
from ..framework import Program, TPUPlace, program_guard
from ..models.transformer import (KV_CACHE_K, KV_CACHE_V, LMConfig,
                                  build_lm_decode_step, build_lm_prefill,
                                  build_lm_prefill_paged)
from ..reader.bucketing import bucketize
from .kv_blocks import BlockAllocator, PrefixCache, chain_hashes
from .batcher import (DeadlineExceededError, EngineStoppedError,
                      LoadShedError, Request, RequestQueue,
                      resolve_metrics_port, start_metrics_server)

__all__ = ['GenerateConfig', 'GenerateEngine', 'GenerateRequest',
           'GenerateResult']

_DONE = object()


def _sampling_stream(sample_seed):
    """One request's private sampling PRNG: a pinned seed replays the
    stream bit-exactly; None draws a fresh unpredictable one. Shared by
    submit()-side requests and the generate_once replay path — the
    'same (seed, prompt) replays the same tokens' contract depends on
    these two staying byte-identical."""
    seed = sample_seed if sample_seed is not None \
        else np.random.SeedSequence().entropy
    return np.random.Generator(np.random.Philox(int(seed)))


class GenerateResult(list):
    """What ``GenerateRequest.result()`` returns: the generated token ids
    (it IS a list — equality/iteration/len behave like the token list)
    plus the structured completion metadata a caller routing on latency
    needs:

    - ``finish_reason``: 'eos' | 'length' | 'cache_full'
    - ``timing``: the request's latency budget — ``queue_s``,
      ``prefill_s``, ``decode_step_s`` (sum over steps), ``total_s``,
      ``tokens``, ``step_s_mean`` / ``step_s_p99`` (per-token decode
      gaps), and the ``trace_id`` joining it to the trace log
      (docs/observability.md).
    """

    def __init__(self, tokens, finish_reason=None, timing=None):
        list.__init__(self, tokens)
        self.finish_reason = finish_reason
        self.timing = timing

    @property
    def tokens(self):
        return list(self)


class GenerateConfig(object):
    """Decode-engine knobs.

    - model: an `LMConfig` (decode programs share parameter names with
      `build_lm`, so a scope trained for the LM serves directly).
    - slots: KV-cache width — the max number of in-flight sequences.
    - max_len: cache length per slot; prompt + generated tokens beyond it
      end the request with finish_reason='cache_full'.
    - prompt_buckets: ascending prompt-length ladder; one prefill program
      compiles per bucket. Default: powers of two from 16 up to max_len/2.
    - eos_id: token ending a sequence (None = length-bounded only).
    - max_new_tokens: per-request generation cap when submit() gives none.
    - queue_cap / default_deadline_s: PR 4 bounded-queue semantics.
    - seed: parameter-init seed (two engines built with equal seeds hold
      identical weights — the parity-test contract).
    - metrics_port: as ServingConfig.metrics_port (None falls back to
      PADDLE_METRICS_PORT; the endpoint rides start()/stop()).
    - paged / block_size / num_blocks / prefix_sharing: paged-KV mode.
      `num_blocks` is the PHYSICAL pool size (block 0 is the reserved
      trash block, so `num_blocks - 1` blocks are allocatable); the
      default matches the contiguous cache's HBM exactly
      (slots * max_len / block_size), which is how the >= 2x-concurrency
      contract is stated. `prompt_buckets` bucket the prefill SUFFIX in
      paged mode — with prefix sharing, a request's prefill cost is its
      un-cached suffix, not its prompt.
    - temperature / top_k / top_p: engine-wide sampling defaults applied
      when submit() passes none. 0 / 0 / 0 = bitwise greedy.
    - speculative / spec_k / draft_model: speculative decoding (paged
      engines only). A draft LM proposes `spec_k` greedy tokens per
      decode round in one dispatch and the target verifies all of them
      in one `spec_k + 1`-wide batched step — greedy output stays
      bitwise identical to non-speculative decode, up to spec_k + 1
      tokens land per round. `draft_model` is the draft's LMConfig
      (must share the target's vocab); None drafts with the target
      config itself (a seed-built engine then drafts with identical
      weights — the 100%-accept reference; pass a smaller config, or an
      int8-converted variant's scope via GenerateEngine(draft_scope=),
      for a cheap production draft).
    """

    def __init__(self, model=None, slots=8, max_len=256,
                 prompt_buckets=None, eos_id=None, max_new_tokens=64,
                 pad_id=0, queue_cap=256, default_deadline_s=60.0,
                 seed=0, metrics_port=None, idle_poll_s=0.02,
                 paged=False, block_size=16, num_blocks=None,
                 prefix_sharing=True, temperature=0.0, top_k=0,
                 top_p=0.0, speculative=False, spec_k=4,
                 draft_model=None):
        self.model = model or LMConfig()
        self.slots = int(slots)
        self.max_len = int(max_len)
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2")
        self.paged = bool(paged)
        self.block_size = int(block_size)
        self.prefix_sharing = bool(prefix_sharing) and self.paged
        if self.paged:
            if self.block_size < 1:
                raise ValueError("block_size must be >= 1")
            if self.max_len % self.block_size:
                raise ValueError(
                    "paged mode needs max_len (%d) divisible by "
                    "block_size (%d) — the block table is "
                    "max_len/block_size entries wide"
                    % (self.max_len, self.block_size))
            if num_blocks is None:
                num_blocks = self.slots * self.max_len // self.block_size
            self.num_blocks = int(num_blocks)
            if self.num_blocks < 2:
                raise ValueError("num_blocks must be >= 2 (block 0 is "
                                 "the reserved trash block)")
        else:
            self.num_blocks = None
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.speculative = bool(speculative)
        self.spec_k = int(spec_k)
        self.draft_model = draft_model
        if self.speculative:
            if not self.paged:
                raise ValueError(
                    "speculative decoding rides the paged KV engine "
                    "(rollback is block-table truncation) — pass "
                    "paged=True")
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if draft_model is not None and \
                    draft_model.vocab_size != self.model.vocab_size:
                raise ValueError(
                    "draft_model.vocab_size (%d) must equal the target's "
                    "(%d) — draft proposals are target token ids"
                    % (draft_model.vocab_size, self.model.vocab_size))
        if prompt_buckets is None:
            prompt_buckets, b = [], 16
            while b <= self.max_len // 2:
                prompt_buckets.append(b)
                b *= 2
            if not prompt_buckets:
                prompt_buckets = [self.max_len // 2 or 1]
        self.prompt_buckets = sorted(set(int(b) for b in prompt_buckets))
        if not self.prompt_buckets:
            raise ValueError("prompt_buckets must not be empty")
        if self.prompt_buckets[0] < 1 or \
                self.prompt_buckets[-1] > self.max_len:
            raise ValueError(
                "prompt_buckets %r must lie in [1, max_len=%d]"
                % (prompt_buckets, self.max_len))
        self.eos_id = eos_id
        self.max_new_tokens = int(max_new_tokens)
        self.pad_id = int(pad_id)
        self.queue_cap = int(queue_cap)
        self.default_deadline_s = default_deadline_s
        self.seed = int(seed)
        self.metrics_port = metrics_port
        self.idle_poll_s = float(idle_poll_s)


class GenerateRequest(Request):
    """One prompt in flight: the PR 4 future contract (`result()`,
    `fail()`, deadline) plus a per-token stream. `result()` returns a
    `GenerateResult` — the generated-token list enriched with
    ``finish_reason`` and the ``timing`` breakdown (queue/prefill/
    per-token decode); ``for tok in req.stream()`` consumes tokens as
    decode steps deliver them. `finish_reason` is
    'eos' | 'length' | 'cache_full' after a normal finish."""

    __slots__ = ('prompt', 'max_new_tokens', 'tokens', 'finish_reason',
                 'step_s', '_stream_q', 'temperature', 'top_k', 'top_p',
                 'sample_seed', '_rng', 'spec_proposed', 'spec_accepted')

    def __init__(self, prompt, seq_len, bucket, deadline, max_new_tokens,
                 temperature=0.0, top_k=0, top_p=0.0, sample_seed=None):
        Request.__init__(self, {'prompt': prompt}, 1, seq_len, bucket,
                         deadline)
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens = []
        self.finish_reason = None
        self.step_s = []        # engine-attributed per-token step times
        self._stream_q = _pyqueue.Queue()   # (bounded by max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.sample_seed = sample_seed
        self._rng = None
        self.spec_proposed = 0  # draft tokens proposed for this request
        self.spec_accepted = 0  # ... that became emitted tokens

    def _draw_u(self):
        """Next uniform of this request's OWN sampling stream: one host
        PRNG per request, so co-resident slots sample independently and
        a (sample_seed, prompt) pair replays bit-exactly regardless of
        slot assignment or neighbors."""
        if self.temperature <= 0.0:
            return 0.0
        if self._rng is None:
            self._rng = _sampling_stream(self.sample_seed)
        return float(self._rng.random())

    # engine-side delivery ------------------------------------------------
    def _emit(self, tok):
        self.tokens.append(tok)
        self._stream_q.put(tok)

    def _finish(self, reason):
        self.finish_reason = reason
        tr = self.trace
        if tr is not None and self.timing is None:
            rec = tr.finish('ok', tokens=len(self.tokens))
            t = trace_mod.flat_timing(rec)
            t['tokens'] = len(self.tokens)
            t['finish_reason'] = reason
            if self.step_s:
                srt = sorted(self.step_s)
                t['step_s_mean'] = sum(srt) / len(srt)
                t['step_s_p99'] = srt[monitor._rank_idx(0.99, len(srt))]
            if self.spec_proposed:
                t['spec_proposed'] = self.spec_proposed
                t['spec_accepted'] = self.spec_accepted
                t['spec_accept_rate'] = round(
                    self.spec_accepted / float(self.spec_proposed), 4)
            self.timing = t
        Request.done(self, GenerateResult(self.tokens,
                                          finish_reason=reason,
                                          timing=self.timing))
        self._stream_q.put(_DONE)

    def fail(self, error):
        Request.fail(self, error)
        self._stream_q.put(_DONE)

    # consumer side -------------------------------------------------------
    def stream(self, timeout=None):
        """Yield generated tokens as they arrive; on a failed request the
        error raises AFTER the tokens already delivered. `timeout` bounds
        the wait for EACH token; with no explicit timeout the request's
        own deadline (+1s grace) bounds every wait instead — a consumer
        must never hang past its deadline, even on an engine that was
        never started (the result() contract)."""
        while True:
            t = timeout
            if t is None and self.deadline is not None:
                t = max(0.0, self.deadline - time.monotonic()) + 1.0
            try:
                item = self._stream_q.get(timeout=t)
            except _pyqueue.Empty:
                raise DeadlineExceededError(
                    "no token within %.3fs" % (t or 0.0))
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item


class _Slot(object):
    __slots__ = ('req', 'pos', 'generated', 'last', 'last_t', 'wall0',
                 'blocks', 'table', 'dblocks', 'dtable', 'draft_stale')

    def __init__(self, req, pos, last, blocks=None, table=None,
                 dblocks=None, dtable=None):
        self.req = req
        self.pos = pos          # cache position the NEXT step writes
        self.generated = 1      # prefill already emitted the first token
        self.last = last        # last generated token (next step's input)
        self.last_t = time.perf_counter()   # previous token's completion
        self.wall0 = time.time() * 1e6      # decode-phase start (us)
        self.blocks = blocks    # paged: physical block ids, table order
        self.table = table      # paged: np [max_blocks] int64, filler 0
        self.dblocks = dblocks  # speculative: DRAFT-pool block ids
        self.dtable = dtable    # speculative: draft block table
        # plain (fallback) steps write K/V into the TARGET cache only —
        # the draft cache misses those rows until a spec round resyncs
        self.draft_stale = False


class GenerateEngine(object):
    """In-process continuous-batching decode engine. ::

        cfg = fluid.serving.GenerateConfig(
            model=LMConfig(...), slots=8, max_len=256, eos_id=1)
        engine = fluid.serving.GenerateEngine(cfg)
        engine.warmup()                      # compiles every signature
        with engine:                         # start()/stop()
            req = engine.submit(prompt_ids, max_new_tokens=32)
            for tok in req.stream():         # streams per decode step
                ...
            full = engine.submit(p2).result()

    Pass ``scope=`` to serve already-trained parameters (names match
    build_lm); otherwise the engine initializes fresh parameters from
    ``config.seed``.

    ``block_allocator=`` (paged mode) injects a shared pool instead of
    the engine-private default — the multi-tenant residency path: a
    `ModelFleet` sizes ONE ``BlockAllocator`` to the real HBM budget
    and hands each co-resident engine a `QuotaBlockAllocator` view, so
    per-tenant quotas are enforced while every tenant draws from the
    same physical free list. The allocator's block_size must match the
    config's; the engine's prefix cache is built over the injected
    view, keeping cache-pressure eviction tenant-local.
    """

    def __init__(self, config=None, scope=None, draft_scope=None,
                 block_allocator=None):
        self.config = config or GenerateConfig()
        self.scope = scope if scope is not None else Scope()
        self.executor = Executor(TPUPlace(0))
        c = self.config
        if block_allocator is not None and not c.paged:
            raise ValueError(
                "block_allocator= injection is a paged-mode feature "
                "(the contiguous cache reserves slots * max_len rows "
                "up front) — pass paged=True")
        if c.paged:
            if block_allocator is not None:
                if block_allocator.block_size != c.block_size:
                    raise ValueError(
                        "injected allocator block_size %d != config "
                        "block_size %d — the paged kernels address the "
                        "cache through the table at the allocator's "
                        "granularity" % (block_allocator.block_size,
                                         c.block_size))
                self._alloc = block_allocator
            else:
                self._alloc = BlockAllocator(c.num_blocks, c.block_size)
            self._prefix = PrefixCache(self._alloc) \
                if c.prefix_sharing else None
            self._max_blocks = c.max_len // c.block_size
            self._cow_jit = None
            self._dcopy_jit = None
        else:
            self._alloc = None
            self._prefix = None
        if c.speculative:
            self._draft_cfg = c.draft_model or c.model
            # +1 over the all-slots-at-max_len footprint (the trash
            # block), so per-slot draft growth can never starve — the
            # draft pool needs no eviction or parking machinery
            self._draft_nb = c.slots * c.max_len // c.block_size + 1
            self._draft_alloc = BlockAllocator(self._draft_nb,
                                               c.block_size)
            self._draft_scope = draft_scope if draft_scope is not None \
                else Scope()
            # fresh draft scope + default draft config: alias the
            # TARGET's parameters (draft == target weights even for a
            # trained scope — the high-accept reference); a distinct
            # draft_model initializes from config.seed instead, and a
            # provided draft_scope serves its own (e.g. int8/distilled)
            # weights as-is
            self._draft_copies_target = draft_scope is None and \
                c.draft_model is None
        else:
            self._draft_cfg = None
            self._draft_alloc = None
            self._draft_scope = None
            self._draft_copies_target = False
        self._build_programs()
        self._init_state()
        self.queue = RequestQueue(self.config.queue_cap)
        self._slots = [None] * self.config.slots
        self._free = list(range(self.config.slots))[::-1]
        self._pending_admit = None   # popped but awaiting free blocks
        self._prefill_bound = {}
        self._draft_prefill_bound = {}
        self._step_bound = None
        self._drafter_bound = None
        self._verify_bound = None
        self._thread = None
        self._started = False
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._metrics_server = None
        self._decode_steps = 0
        self._decode_tokens = 0
        self._occ_sum = 0.0
        self._occ_peak = 0.0
        self._active_peak = 0
        self._blocks_peak = 0
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_fallbacks = 0
        self._spec_stale_rounds = 0
        self._goodput_fps = None
        # resolve + name the goodput fingerprint set NOW: a periodic
        # snapshot exporting counters before the first stats() call
        # would otherwise label them as bare fingerprints and split
        # each program's series in two
        self._goodput_fp_set()
        monitor.set_gauge('kv_slot_occupancy', 0.0)
        monitor.set_gauge('generate_queue_depth', 0.0)
        if c.paged:
            self._set_block_gauges()

    # ------------------------------------------------------------------
    # build + state
    def _build_programs(self):
        cfg, c = self.config.model, self.config
        self._step_prog, self._startup = Program(), Program()
        self._startup.random_seed = c.seed
        self._step_prog.random_seed = c.seed
        with program_guard(self._step_prog, self._startup):
            with unique_name.guard():
                self._step_vars = build_lm_decode_step(
                    cfg, c.slots, c.max_len,
                    block_size=c.block_size if c.paged else None,
                    num_blocks=c.num_blocks)
        self._prefill = {}
        for b in c.prompt_buckets:
            main, start = Program(), Program()
            main.random_seed = c.seed
            with program_guard(main, start):
                with unique_name.guard():
                    if c.paged:
                        v = build_lm_prefill_paged(
                            cfg, b, c.num_blocks, c.block_size,
                            self._max_blocks)
                    else:
                        v = build_lm_prefill(cfg, b, c.slots, c.max_len)
            self._prefill[b] = (main, v)
        if c.speculative:
            from ..models.transformer import (build_lm_drafter,
                                              build_lm_verify)
            dcfg = self._draft_cfg
            self._drafter_prog = Program()
            self._draft_startup = Program()
            self._drafter_prog.random_seed = c.seed
            self._draft_startup.random_seed = c.seed
            with program_guard(self._drafter_prog, self._draft_startup):
                with unique_name.guard():
                    self._drafter_vars = build_lm_drafter(
                        dcfg, c.slots, c.max_len, c.spec_k,
                        self._draft_nb, c.block_size)
            self._verify_prog = Program()
            self._verify_prog.random_seed = c.seed
            with program_guard(self._verify_prog, Program()):
                with unique_name.guard():
                    self._verify_vars = build_lm_verify(
                        cfg, c.slots, c.spec_k + 1, c.max_len,
                        c.num_blocks, c.block_size)
            self._draft_prefill = {}
            if not self._draft_copies_target:
                # a distinct draft prefills for real; the target-copy
                # fast path block-copies instead and never runs these
                for b in c.prompt_buckets:
                    main, start = Program(), Program()
                    main.random_seed = c.seed
                    with program_guard(main, start):
                        with unique_name.guard():
                            v = build_lm_prefill_paged(
                                dcfg, b, self._draft_nb, c.block_size,
                                self._max_blocks)
                    self._draft_prefill[b] = (main, v)

    def _init_state(self):
        import jax.numpy as jnp
        cfg, c = self.config.model, self.config
        with scope_guard(self.scope):
            if not self.scope.has('tok_emb.w'):
                # fresh engine: init params from config.seed; a provided
                # scope with trained weights skips this entirely
                self.executor.run(self._startup, scope=self.scope)
        if c.speculative and not self._draft_scope.has('tok_emb.w'):
            if self._draft_copies_target:
                # alias the target's parameter arrays (jax arrays are
                # immutable — zero-copy); the caches are NOT copied,
                # _ensure_cache gives the draft scope its own pool
                for name in self.scope.names():
                    if name not in (KV_CACHE_K, KV_CACHE_V):
                        self._draft_scope.set(name, self.scope.get(name))
            else:
                with scope_guard(self._draft_scope):
                    self.executor.run(self._draft_startup,
                                      scope=self._draft_scope)
        self._ensure_cache()

    def _ensure_cache(self):
        """Make the scope's gen_kv_k/v buffers match THIS engine's
        geometry. A provided scope may carry another engine's cache
        under the same names — contiguous vs paged, or a different
        slots/max_len/pool shape; the cache holds no trained state, so
        re-zeroing is always safe, while reusing a mismatched buffer
        would feed the compiled programs garbage shapes. Re-checked at
        warmup()/start()/generate_once() so engines sharing one trained
        scope SEQUENTIALLY each reclaim it (concurrent use of one scope
        by two live engines stays unsupported)."""
        import jax.numpy as jnp
        cfg, c = self.config.model, self.config
        dh = cfg.d_model // cfg.n_head
        if c.paged:
            shape = (c.num_blocks, cfg.n_layer, cfg.n_head,
                     c.block_size, dh)
        else:
            shape = (c.slots, cfg.n_layer, cfg.n_head, c.max_len, dh)
        have = self.scope.get(KV_CACHE_K)
        if have is None or tuple(have.shape) != shape:
            self.scope.set(KV_CACHE_K, jnp.zeros(shape, 'float32'))
            self.scope.set(KV_CACHE_V, jnp.zeros(shape, 'float32'))
        if c.speculative:
            dcfg = self._draft_cfg
            dshape = (self._draft_nb, dcfg.n_layer, dcfg.n_head,
                      c.block_size, dcfg.d_model // dcfg.n_head)
            dhave = self._draft_scope.get(KV_CACHE_K)
            if dhave is None or tuple(dhave.shape) != dshape:
                self._draft_scope.set(KV_CACHE_K,
                                      jnp.zeros(dshape, 'float32'))
                self._draft_scope.set(KV_CACHE_V,
                                      jnp.zeros(dshape, 'float32'))

    # ------------------------------------------------------------------
    # paged helpers
    @staticmethod
    def _sample_feed(n, temp=0.0, topk=0, topp=0.0, u=0.0):
        return {'gen_temp': np.full((n, 1), temp, 'float32'),
                'gen_topk': np.full((n, 1), topk, 'int64'),
                'gen_topp': np.full((n, 1), topp, 'float32'),
                'gen_u': np.full((n, 1), u, 'float32')}

    def _cow_copy(self, src, dst):
        """Device-side block copy for copy-on-write: duplicate physical
        block `src` into `dst` in BOTH caches. One jitted
        dynamic-slice/update pair, compiled once at warmup (src/dst are
        traced scalars), donation aliases the pool in place."""
        import jax
        if self._cow_jit is None:
            def _copy(cache, s, d):
                return cache.at[d].set(cache[s])
            # no donate: CPU ignores it with a warning, and COW is rare
            # enough that a transient copy of the pool is acceptable
            self._cow_jit = jax.jit(_copy)
        s = np.asarray(src, 'int32')
        d = np.asarray(dst, 'int32')
        for name in (KV_CACHE_K, KV_CACHE_V):
            self.scope.set(name, self._cow_jit(
                self.executor._state_value(self.scope, name,
                                           self._step_prog, cache=False),
                s, d))

    def _draft_cache_sync(self, dblocks, blocks):
        """Draft == target fast path: the draft prefill would recompute
        EXACTLY the K/V rows the target prefill just wrote (same
        config, aliased weights, same inputs), so copy the target's
        prompt blocks across pools device-side instead — one jitted
        scatter replaces a whole prefill forward. Fixed-width id
        vectors (trash-padded) keep it one compiled signature."""
        import jax
        if self._dcopy_jit is None:
            def _copy(dst, src, d_ids, s_ids):
                return dst.at[d_ids].set(src[s_ids])
            self._dcopy_jit = jax.jit(_copy)
        d_ids = np.zeros((self._max_blocks,), 'int32')
        s_ids = np.zeros((self._max_blocks,), 'int32')
        d_ids[:len(dblocks)] = dblocks
        s_ids[:len(blocks)] = blocks
        for name in (KV_CACHE_K, KV_CACHE_V):
            dst = self.executor._state_value(
                self._draft_scope, name, self._drafter_prog, cache=False)
            src = self.executor._state_value(
                self.scope, name, self._step_prog, cache=False)
            self._draft_scope.set(name,
                                  self._dcopy_jit(dst, src, d_ids, s_ids))

    def _set_block_gauges(self):
        used = self._alloc.in_use()
        self._blocks_peak = max(self._blocks_peak, used)
        monitor.set_gauge('kv_blocks_in_use', float(used))
        monitor.set_gauge('kv_blocks_free', float(self._alloc.available()))

    def _alloc_blocks(self, n):
        """n blocks, evicting idle prefix-cache entries under pressure;
        None when the pool genuinely cannot satisfy the request."""
        ids = self._alloc.alloc(n)
        if ids is None and self._prefix is not None:
            self._prefix.evict_for(n)
            ids = self._alloc.alloc(n)
        if ids is not None:
            self._set_block_gauges()
        return ids

    def _deref_blocks(self, blocks):
        self._alloc.deref_many(blocks)
        self._set_block_gauges()

    def _release_blocks(self, st):
        self._deref_blocks(st.blocks or [])
        st.blocks = []
        if st.dblocks:
            self._draft_alloc.deref_many(st.dblocks)
            st.dblocks = []

    def _slot_table(self, blocks):
        table = np.zeros((self._max_blocks,), 'int64')
        table[:len(blocks)] = blocks
        return table

    # ------------------------------------------------------------------
    # warmup
    def warmup(self):
        """Bind + compile every signature the engine will ever dispatch:
        one prefill per prompt bucket and the decode step. Returns
        {'buckets', 'compiles', 'reused', 'seconds'}; `compiles` is the
        compile_cache_miss delta — 0 when a structurally identical engine
        already warmed the process-wide fingerprint cache. Signatures
        register in the warmup farm (paddle_tpu.warmfarm), so `reused`
        reports how many of this engine's cells were already compiled by
        an earlier process-sharing consumer (bind() still executes each
        program once — it must prime THIS engine's KV-cache state — but
        a reused cell binds at cache-hit speed, compile_seconds ≈ 0)."""
        if self._started:
            # bind() EXECUTES each program once: re-warming a live engine
            # would zero cache rows of resident slots mid-generation
            raise RuntimeError(
                "warmup() executes the decode programs against the live "
                "KV cache and must not race the started engine loop — "
                "warm up before start() (start() warms up automatically)")
        self._ensure_cache()
        from ..warmfarm import farm
        t0 = time.perf_counter()
        before = monitor.counters()
        S = self.config.slots
        reused = 0
        paged = self.config.paged
        with monitor.span('generate.warmup'):
            for b, (prog, v) in sorted(self._prefill.items()):
                feed = {'gen_prompt': np.zeros((1, b), 'int64'),
                        'gen_len': np.ones((1, 1), 'int64')}
                if paged:
                    # an all-zero block table points every write at the
                    # reserved trash block — warmup never touches a row
                    # a live request could own
                    feed['gen_pos'] = np.zeros((1, b), 'int64')
                    feed['gen_btab'] = np.zeros((1, self._max_blocks),
                                                'int64')
                else:
                    feed['gen_slot'] = np.zeros((1, 1), 'int64')
                feed.update(self._sample_feed(1))
                key, already = farm.track(self.executor, prog, feed,
                                          fetch_list=[v['first_token']],
                                          scope=self.scope)
                self._prefill_bound[b] = self.executor.bind(
                    prog, feed, fetch_list=[v['first_token']],
                    scope=self.scope)
                if already:
                    reused += 1
                else:
                    farm.commit(key)
            feed = {'gen_tokens': np.zeros((S, 1), 'int64'),
                    'gen_pos': np.zeros((S, 1), 'int64')}
            if paged:
                feed['gen_btab'] = np.zeros((S, self._max_blocks),
                                            'int64')
            feed.update(self._sample_feed(S))
            key, already = farm.track(
                self.executor, self._step_prog, feed,
                fetch_list=[self._step_vars['next_tokens']],
                scope=self.scope)
            self._step_bound = self.executor.bind(
                self._step_prog, feed,
                fetch_list=[self._step_vars['next_tokens']],
                scope=self.scope)
            if already:
                reused += 1
            else:
                farm.commit(key)
            if self.config.speculative:
                reused += self._warm_spec(farm)
            if paged:
                # compile the copy-on-write block copy now (0 -> 0 is a
                # trash-block no-op) so steady traffic stays at zero
                # compiles even when the first COW lands mid-stream
                self._cow_copy(0, 0)
                if self.config.speculative and self._draft_copies_target:
                    # ... and the draft-pool prompt-block copy (same
                    # trash-block no-op) for the draft==target fast path
                    self._draft_cache_sync([0], [0])
        delta = monitor.counter_delta(before)
        compiles = sum(v for k, v in delta.items()
                       if k.startswith('compile_cache_miss'))
        monitor.inc('generate_warmup_total')
        return {'buckets': len(self._prefill_bound),
                'compiles': int(compiles), 'reused': int(reused),
                'seconds': round(time.perf_counter() - t0, 3)}

    def _warm_spec(self, farm):
        """Bind + compile the speculative signature set: one DRAFT
        prefill per prompt bucket (against the draft scope), the
        drafter (spec_k unrolled greedy steps) and the target's verify
        step. All-zero block tables and vmasks route every warmup write
        to the trash block of the respective pool. Returns how many
        cells the warmup farm had already compiled."""
        c = self.config
        S, K = c.slots, c.spec_k
        reused = 0
        # draft == target: admissions block-copy the target's prompt
        # rows across pools (_draft_cache_sync), so the draft prefill
        # programs are never dispatched — don't pay their compiles
        prefills = {} if self._draft_copies_target else \
            self._draft_prefill
        for b, (prog, v) in sorted(prefills.items()):
            feed = {'gen_prompt': np.zeros((1, b), 'int64'),
                    'gen_len': np.ones((1, 1), 'int64'),
                    'gen_pos': np.zeros((1, b), 'int64'),
                    'gen_btab': np.zeros((1, self._max_blocks), 'int64')}
            feed.update(self._sample_feed(1))
            key, already = farm.track(self.executor, prog, feed,
                                      fetch_list=[v['first_token']],
                                      scope=self._draft_scope)
            self._draft_prefill_bound[b] = self.executor.bind(
                prog, feed, fetch_list=[v['first_token']],
                scope=self._draft_scope)
            if already:
                reused += 1
            else:
                farm.commit(key)
        feed = {'gen_tokens': np.zeros((S, 1), 'int64'),
                'gen_pos': np.zeros((S, 1), 'int64'),
                'gen_btab': np.zeros((S, self._max_blocks), 'int64'),
                'gen_vmask': np.zeros((S, K + 1), 'int64')}
        fetches = [self._drafter_vars['draft_tokens']]
        key, already = farm.track(self.executor, self._drafter_prog,
                                  feed, fetch_list=fetches,
                                  scope=self._draft_scope)
        self._drafter_bound = self.executor.bind(
            self._drafter_prog, feed, fetch_list=fetches,
            scope=self._draft_scope)
        if already:
            reused += 1
        else:
            farm.commit(key)
        feed = {'gen_tokens': np.zeros((S, K + 1), 'int64'),
                'gen_pos': np.zeros((S, K + 1), 'int64'),
                'gen_btab': np.zeros((S, self._max_blocks), 'int64'),
                'gen_vmask': np.zeros((S, K + 1), 'int64')}
        key, already = farm.track(
            self.executor, self._verify_prog, feed,
            fetch_list=[self._verify_vars['verify_tokens']],
            scope=self.scope)
        self._verify_bound = self.executor.bind(
            self._verify_prog, feed,
            fetch_list=[self._verify_vars['verify_tokens']],
            scope=self.scope)
        if already:
            reused += 1
        else:
            farm.commit(key)
        return reused

    # ------------------------------------------------------------------
    # lifecycle
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self.queue.closed:
                raise EngineStoppedError(
                    "a stopped GenerateEngine cannot restart — build a "
                    "fresh engine (the queue already failed its callers)")
            if self._step_bound is None:
                self.warmup()
            else:
                self._ensure_cache()
            self._started = True
            if self._metrics_server is None:
                self._metrics_server = start_metrics_server(
                    self._resolve_metrics_port(), 'GenerateEngine')
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name='paddle-generate',
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout_s=10.0):
        """Close the queue (queued requests fail with EngineStoppedError),
        fail resident generations, join the decode loop."""
        with self._lock:
            self._started = False
        self._stop_evt.set()
        drained = self.queue.close()
        if drained:
            monitor.inc('generate_request_total', drained,
                        labels={'outcome': 'stopped'})
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        if self._prefix is not None:
            # a stopped engine cannot serve another hit; release the
            # cache's block references so accounting reads empty
            self._prefix.drop_all()
            self._set_block_gauges()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _resolve_metrics_port(self):
        return resolve_metrics_port(self.config.metrics_port)

    @property
    def metrics_port(self):
        return self._metrics_server.port if self._metrics_server else None

    # ------------------------------------------------------------------
    # request path
    def submit(self, prompt, max_new_tokens=None, deadline_s=None,
               temperature=None, top_k=None, top_p=None,
               sample_seed=None):
        """Enqueue one prompt (1-D int token ids); returns the
        `GenerateRequest` stream/future. Raises ValueError synchronously
        for prompts the ladder cannot serve and `LoadShedError` when the
        bounded queue is full.

        temperature/top_k/top_p default to the engine-wide
        `GenerateConfig` values; temperature <= 0 is bitwise greedy.
        `sample_seed` pins the request's private sampling stream — the
        same (seed, prompt) replays the same tokens whatever else is
        co-resident; None draws a fresh unpredictable stream."""
        prompt = np.asarray(prompt, dtype='int64').reshape(-1)
        buckets = self.config.prompt_buckets
        if self.config.paged:
            # chunked prefill lifts admission past the bucket ladder:
            # an over-wide prompt prefills in bucket-sized chunks, each
            # attending the cached prefix — only the cache length bounds
            # it (one row must remain for the first decode write)
            limit = self.config.max_len - 1
            limit_why = "max_len - 1 (chunked-prefill admission bound)"
        else:
            limit = buckets[-1]
            limit_why = "largest prompt bucket — trim the prompt, " \
                "widen prompt_buckets, or use paged=True (chunked " \
                "prefill admits up to max_len - 1)"
        if prompt.size < 1 or prompt.size > limit:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'rejected'})
            raise ValueError(
                "prompt length %d outside [1, %d] (%s)"
                % (prompt.size, limit, limit_why))
        if max_new_tokens is None:
            max_new_tokens = self.config.max_new_tokens
        if int(max_new_tokens) < 1:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'rejected'})
            raise ValueError("max_new_tokens must be >= 1")
        c = self.config
        temperature = c.temperature if temperature is None \
            else float(temperature)
        top_k = c.top_k if top_k is None else int(top_k)
        top_p = c.top_p if top_p is None else float(top_p)
        if top_p < 0.0 or top_p > 1.0:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'rejected'})
            raise ValueError("top_p must lie in [0, 1] — 0 (or 1) "
                             "disables nucleus sampling")
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = GenerateRequest(prompt, prompt.size,
                              bucketize(min(prompt.size, buckets[-1]),
                                        buckets), deadline,
                              int(max_new_tokens),
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, sample_seed=sample_seed)
        req.trace = trace_mod.start('generate')
        try:
            self.queue.put(req)
        except (LoadShedError, EngineStoppedError) as e:
            # finishes the trace with the right outcome (keep-errors)
            monitor.inc('generate_request_total', labels={
                'outcome': 'shed' if isinstance(e, LoadShedError)
                else 'stopped'})
            req.fail(e)
            raise
        monitor.set_gauge('generate_queue_depth', self.queue.depth())
        return req

    def generate(self, prompt, max_new_tokens=None, deadline_s=None,
                 timeout=None, temperature=None, top_k=None, top_p=None,
                 sample_seed=None):
        """Blocking convenience: submit + result (the generated tokens)."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline_s=deadline_s, temperature=temperature,
                           top_k=top_k, top_p=top_p,
                           sample_seed=sample_seed).result(timeout)

    def generate_once(self, prompt, max_new_tokens=None, temperature=0.0,
                      top_k=0, top_p=0.0, sample_seed=None):
        """Synchronous single-prompt decode on slot 0, driving the SAME
        compiled prefill/step programs step by step — the sequential
        reference the parity tests compare the continuous batcher
        against, and a zero-thread debug path. Greedy by default;
        sampling args mirror submit() (a pinned `sample_seed` replays
        the exact submit() sampling stream). Only valid while the engine
        is NOT started (it shares the loop's cache slots). Paged engines
        allocate the reference's blocks from the live pool (bypassing
        the prefix cache) and return every block before returning."""
        if self._started:
            raise RuntimeError(
                "generate_once drives the decode programs inline and "
                "must not race the started engine loop — use submit()")
        if self._step_bound is None:
            self.warmup()
        else:
            self._ensure_cache()
        prompt = np.asarray(prompt, dtype='int64').reshape(-1)
        if max_new_tokens is None:
            max_new_tokens = self.config.max_new_tokens
        c = self.config
        temperature = float(temperature)
        rng = [None]

        def draw_u():
            if temperature <= 0.0:
                return 0.0
            if rng[0] is None:
                rng[0] = _sampling_stream(sample_seed)
            return float(rng[0].random())

        sample = (temperature, int(top_k), float(top_p))
        blocks, table = None, None
        if c.paged:
            bs = c.block_size
            blocks = self._alloc_blocks(-(-prompt.size // bs))
            if blocks is None:
                raise RuntimeError(
                    "paged KV pool cannot hold a %d-token prompt right "
                    "now (%d blocks free of %d)"
                    % (prompt.size, self._alloc.available(),
                       self._alloc.capacity))
            table = self._slot_table(blocks)
        try:
            first = self._run_prefill(0, prompt,
                                      sample + (draw_u(),),
                                      table=table, ctx_len=0)
            tokens, last, pos = [first], first, prompt.size
            while (len(tokens) < max_new_tokens and pos < c.max_len and
                   (c.eos_id is None or last != c.eos_id)):
                if c.paged and pos // c.block_size >= len(blocks):
                    grown = self._alloc_blocks(1)
                    if grown is None:     # pool dry: cache_full semantics
                        break
                    table[len(blocks)] = grown[0]
                    blocks.append(grown[0])
                S = c.slots
                toks = np.zeros((S, 1), 'int64')
                posf = np.zeros((S, 1), 'int64')
                toks[0], posf[0] = last, pos
                feed = {'gen_tokens': toks, 'gen_pos': posf}
                if c.paged:
                    btab = np.zeros((S, self._max_blocks), 'int64')
                    btab[0] = table
                    feed['gen_btab'] = btab
                sf = self._sample_feed(S)
                sf['gen_temp'][0], sf['gen_topk'][0] = sample[0], sample[1]
                sf['gen_topp'][0], sf['gen_u'][0] = sample[2], draw_u()
                feed.update(sf)
                out = self._step_bound(feed)
                last = int(np.asarray(out[0]).reshape(-1)[0])
                tokens.append(last)
                pos += 1
            return tokens
        finally:
            if blocks:
                self._deref_blocks(blocks)

    # ------------------------------------------------------------------
    # decode loop
    def _loop(self):
        poll = self.config.idle_poll_s
        while not self._stop_evt.is_set():
            self._evict_expired()
            self._admit()
            if not any(s is not None for s in self._slots):
                if self._pending_admit is not None:
                    # parked for blocks with nothing resident: _admit()
                    # retries it at the top of every loop pass (it can
                    # only be reachable transiently — with no residents
                    # the prefix cache is fully evictable)
                    time.sleep(poll)
                    continue
                # idle: block briefly for new work instead of spinning
                batch, expired = self.queue.take_batch(1, 0.0,
                                                       poll_s=poll)
                self._fail_expired(expired)
                if batch:
                    self._admit_one(batch[0])
                monitor.set_gauge('generate_queue_depth',
                                  self.queue.depth())
                continue
            if self._spec_ready():
                self._spec_round()
                continue
            if self.config.speculative:
                # a sampled resident pins the whole batch on plain
                # steps this round — speculation accelerates greedy
                # traffic (acceptance is an argmax identity)
                monitor.inc('spec_fallback_total')
                self._spec_fallbacks += 1
            pending = self._step_dispatch()
            if pending is not None:
                # overlap: admit queued prompts (queue pops + prefill
                # staging) while the dispatched step computes on device.
                # Eviction stays OUT of this window — releasing a slot
                # the in-flight step's snapshot references would let a
                # new tenant double-book it before completion lands.
                t_adm = time.perf_counter()
                self._admit()
                # admission time is observed as prefill_seconds already;
                # exclude it so decode_step_seconds stays a per-token
                # signal instead of double-counting the overlap window
                self._step_complete(pending,
                                    exclude_s=time.perf_counter() - t_adm)
        # shutdown: a resident generation must not leave its caller
        # blocked forever
        for i, st in enumerate(self._slots):
            if st is not None:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'stopped'})
                st.req.fail(EngineStoppedError(
                    "engine stopped after %d generated tokens"
                    % st.generated))
        if self._pending_admit is not None:
            req, self._pending_admit = self._pending_admit, None
            monitor.inc('generate_request_total',
                        labels={'outcome': 'stopped'})
            req.fail(EngineStoppedError(
                "engine stopped while the request waited for KV blocks"))
        self._set_occupancy()

    def _admit(self):
        while self._free and not self._stop_evt.is_set():
            req = self._pending_admit
            self._pending_admit = None
            if req is None:
                batch, expired = self.queue.take_batch(1, 0.0, poll_s=0.0)
                self._fail_expired(expired)
                if not batch:
                    return
                req = batch[0]
            if not self._admit_one(req):
                return      # parked for blocks: retry next token boundary
            monitor.set_gauge('generate_queue_depth', self.queue.depth())

    def _paged_plan(self, req):
        """Block plan for one admission: (blocks, ctx_len, hashes).
        `blocks` covers the whole prompt in logical order — prefix-cache
        hits mapped to their existing physical blocks (referenced),
        fresh blocks for the rest, and a copy-on-write duplicate of the
        final shared block when the ENTIRE prompt landed on shared
        blocks (its last position must be recomputed, a divergent
        write). Returns None when the pool cannot satisfy the request
        right now (nothing referenced, nothing allocated)."""
        c = self.config
        bs = c.block_size
        L = req.prompt.size
        total = -(-L // bs)
        shared, hashes = [], []
        if self._prefix is not None:
            hashes = chain_hashes(req.prompt, bs)
            shared = self._prefix.match(hashes)
        cow = bool(shared) and len(shared) * bs >= L
        n_keep = len(shared) - (1 if cow else 0)
        ctx_len = min(n_keep * bs + (bs if cow else 0), L - 1)
        # pin every matched block (incl. the COW source) BEFORE touching
        # the allocator: under pool pressure _alloc_blocks evicts
        # refcount-1 prefix entries, and without the pin it could evict
        # a block match() just returned and recycle it as "fresh" —
        # a duplicate id in the plan, i.e. the suffix prefill clobbering
        # its own cached prefix
        pinned = shared[:n_keep] + (shared[-1:] if cow else [])
        for b in pinned:
            self._alloc.ref(b)
        new_ids = self._alloc_blocks(total - n_keep)
        if new_ids is None:
            self._deref_blocks(pinned)
            return None
        if cow:
            self._cow_copy(shared[-1], new_ids[0])
            self._alloc.deref(shared[-1])   # pinned only for the copy
            monitor.inc('kv_block_cow_total')
        if self._prefix is not None:
            monitor.inc('kv_prefix_hit_total', labels={
                'outcome': 'hit' if ctx_len > 0 else 'miss'})
            if ctx_len > 0:
                monitor.inc('kv_prefix_tokens_saved_total', ctx_len)
        return shared[:n_keep] + new_ids, ctx_len, hashes

    def _admit_one(self, req):
        """Admit one popped request. Returns False when a paged engine
        must wait for blocks (the request parks in _pending_admit and is
        retried every token boundary); True when the request was
        consumed — admitted, finished, or failed."""
        c = self.config
        blocks, table, ctx_len, hashes = None, None, 0, []
        if c.paged:
            if -(-req.prompt.size // c.block_size) > self._alloc.capacity:
                # no eviction can ever fit this prompt: structured
                # cache_full, zero tokens, nothing leaked
                monitor.inc('generate_request_total',
                            labels={'outcome': 'ok'})
                req._finish('cache_full')
                return True
            plan = self._paged_plan(req)
            if plan is None:
                self._pending_admit = req
                return False
            blocks, ctx_len, hashes = plan
            table = self._slot_table(blocks)
        slot = self._free.pop()
        qs = max(0.0, time.monotonic() - req.enqueue_t)
        # queue wait as a histogram (the goodput 'queue' loss bucket
        # reads its sum) + the queue-SLO burn sentinel feed
        monitor.observe('generate_queue_seconds', qs)
        goodput.note_queue_wait(qs)
        if req.trace is not None:
            # queue stage closes at admission; the span rides the
            # SUBMITTER's tid so the trace shows the thread hop into
            # this decode loop
            req.trace.add_stage('queue', qs)
            monitor.record_span('request.queue', req.enqueue_wall,
                                qs * 1e6, tid=req._tid, trace=req.trace)
        t0 = time.perf_counter()
        pf_wall = time.time() * 1e6
        dblocks, dtable = None, None
        try:
            first = self._run_prefill(
                slot, req.prompt,
                (req.temperature, req.top_k, req.top_p, req._draw_u()),
                table=table, ctx_len=ctx_len)
            if c.speculative:
                # the draft tracks the request in its OWN pool: full
                # prompt (no prefix cache — draft K/V are model-specific
                # throwaways), chunked exactly like the target's. With
                # draft == target the prompt rows are block-copied from
                # the target pool instead of recomputed.
                dblocks = self._draft_alloc.alloc(
                    -(-req.prompt.size // c.block_size))
                if dblocks is None:     # unreachable by pool sizing
                    raise RuntimeError("draft KV pool exhausted")
                dtable = self._slot_table(dblocks)
                if self._draft_copies_target:
                    self._draft_cache_sync(dblocks, blocks)
                else:
                    self._run_prefill(slot, req.prompt, table=dtable,
                                      ctx_len=0,
                                      bound=self._draft_prefill_bound)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._free.append(slot)
            if blocks:
                self._deref_blocks(blocks)
            if dblocks:
                self._draft_alloc.deref_many(dblocks)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'error'})
            req.fail(e)
            return True
        if c.paged and self._prefix is not None:
            # publish this prompt's FULL blocks (immutable once
            # prefilled: decode writes land strictly past the prompt)
            for i, h in enumerate(hashes):
                self._prefix.register(h, i, blocks[i])
        pf_s = time.perf_counter() - t0
        monitor.observe('prefill_seconds', pf_s)
        if req.trace is not None:
            req.trace.add_stage('prefill', pf_s)
            monitor.record_span('request.prefill', pf_wall, pf_s * 1e6,
                                trace=req.trace)
        monitor.inc('decode_tokens_total')
        self._decode_tokens += 1
        req._emit(first)
        st = _Slot(req, pos=req.prompt.size, last=first,
                   blocks=blocks, table=table,
                   dblocks=dblocks, dtable=dtable)
        reason = self._finish_reason(st)
        if reason:
            if c.paged:
                self._release_blocks(st)
            self._free.append(slot)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'ok'})
            req._finish(reason)
        else:
            self._slots[slot] = st
        self._set_occupancy()
        return True

    def _run_prefill(self, slot, prompt, sample=(0.0, 0, 0.0, 0.0),
                     table=None, ctx_len=0, bound=None):
        c = self.config
        if table is None:
            b = bucketize(prompt.size, c.prompt_buckets)
            padded = np.full((1, b), c.pad_id, 'int64')
            padded[0, :prompt.size] = prompt
            feed = {'gen_prompt': padded,
                    'gen_slot': np.array([[slot]], 'int64'),
                    'gen_len': np.array([[prompt.size]], 'int64')}
            feed.update(self._sample_feed(1, *sample))
            out = self._prefill_bound[b](feed)
            return int(np.asarray(out[0]).reshape(-1)[0])
        # paged: only the UN-CACHED suffix is computed; it buckets by
        # suffix length — the prefill-compute saving of a prefix hit.
        # A suffix wider than the widest bucket runs CHUNKED: each
        # widest-bucket chunk deposits its K/V and attends the cached
        # prefix (kv_prefix_attention), exactly like a shared-prefix
        # suffix — same compiled signatures, any prompt length. Only
        # the FINAL chunk's first-token output is the model's answer.
        bound = bound if bound is not None else self._prefill_bound
        wide = c.prompt_buckets[-1]
        off = int(ctx_len)
        suffix = prompt[off:]
        while suffix.size > wide:
            chunk, suffix = suffix[:wide], suffix[wide:]
            pos = np.clip(off + np.arange(wide), 0, c.max_len - 1)
            feed = {'gen_prompt': chunk[None],
                    'gen_pos': pos[None].astype('int64'),
                    'gen_btab': table[None],
                    'gen_len': np.array([[wide]], 'int64')}
            feed.update(self._sample_feed(1))
            bound[wide](feed)       # K/V deposited; token output unused
            off += wide
        b = bucketize(suffix.size, c.prompt_buckets)
        padded = np.full((1, b), c.pad_id, 'int64')
        padded[0, :suffix.size] = suffix
        pos = np.clip(off + np.arange(b), 0, c.max_len - 1)
        feed = {'gen_prompt': padded,
                'gen_pos': pos[None].astype('int64'),
                'gen_btab': table[None],
                'gen_len': np.array([[suffix.size]], 'int64')}
        feed.update(self._sample_feed(1, *sample))
        out = bound[b](feed)
        return int(np.asarray(out[0]).reshape(-1)[0])

    def _step(self):
        """One decode step, dispatch + completion back to back (the
        inline/debug path; the engine loop splits the two so admission
        overlaps the device time). On a speculative engine with an
        all-greedy resident set this is one SPECULATIVE round."""
        if self._spec_ready():
            self._spec_round()
            return
        if self.config.speculative and \
                any(s is not None for s in self._slots):
            monitor.inc('spec_fallback_total')
            self._spec_fallbacks += 1
        pending = self._step_dispatch()
        if pending is not None:
            self._step_complete(pending)

    def _spec_ready(self):
        """Speculate this round? Requires a speculative engine, at
        least one resident, and every resident greedy (sampled rows
        have no argmax-identity acceptance rule — they fall back to
        plain steps)."""
        if not self.config.speculative:
            return False
        active = [s for s in self._slots if s is not None]
        return bool(active) and \
            all(s.req.temperature <= 0.0 for s in active)

    def _grow_blocks(self):
        """Paged pre-step pass: any resident whose next write position
        crosses into an unallocated block gets one more block; a dry
        pool (even after prefix-cache eviction) finishes the starved
        request with 'cache_full' and returns its blocks — neighbors
        keep decoding."""
        bs = self.config.block_size
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            bi = st.pos // bs
            if bi < len(st.blocks):
                continue
            grown = self._alloc_blocks(1)
            if grown is None:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'ok'})
                st.req._finish('cache_full')
                continue
            st.table[len(st.blocks)] = grown[0]
            st.blocks.append(grown[0])
        self._set_occupancy()

    # ------------------------------------------------------------------
    # speculative decode
    def _spec_grow(self, active):
        """Pre-round block growth for speculation: per active slot,
        extend the TARGET table to cover the verify window's write
        positions (pos .. pos + spec_k, capped at max_len - 1) and the
        DRAFT table to the SAME coverage — the drafter's trailing
        write-only tower deposits position pos + spec_k too, and
        trashing that row would silently drop a target-equal draft's
        accept rate below 1.0.
        Returns {slot_index: n_valid} — how many verify rows are fully
        budgeted (cache coverage, max_len, AND the request's remaining
        max_new_tokens: proposals past what the request may still emit
        are never counted, so accept_rate measures draft QUALITY, not
        budget clipping). Target tail blocks that
        end up holding no accepted position are returned to the pool by
        the post-verify truncation; a pool too dry to extend the tail
        just shortens this round's window (n_valid >= 1 always — the
        plain `_grow_blocks` already guaranteed the next write's
        block), it never starves a request."""
        c = self.config
        bs = c.block_size
        K = c.spec_k
        n_valid = {}
        for i, st in active:
            want_last = min(st.pos + K, c.max_len - 1) // bs
            while len(st.blocks) <= want_last:
                grown = self._alloc_blocks(1)
                if grown is None:
                    break
                st.table[len(st.blocks)] = grown[0]
                st.blocks.append(grown[0])
            covered = len(st.blocks) * bs - 1       # last writable pos
            remaining = st.req.max_new_tokens - st.generated
            n_valid[i] = max(1, min(K + 1, c.max_len - st.pos,
                                    covered - st.pos + 1, remaining))
            # draft coverage mirrors the target's: the trailing
            # write-only draft step deposits position pos + K too
            dwant_last = want_last
            while len(st.dblocks) <= dwant_last:
                grown = self._draft_alloc.alloc(1)
                if grown is None:       # unreachable by pool sizing
                    break
                st.dtable[len(st.dblocks)] = grown[0]
                st.dblocks.append(grown[0])
        self._set_block_gauges()
        return n_valid

    def _spec_truncate(self, st):
        """Roll back the speculative tail: blocks holding NO position
        below the slot's accepted write head — and not needed for the
        NEXT write either — return to their pools and their table
        entries zero out (the trash block). No cache bytes move —
        rejected rows sit past the write head where every attention
        masks them to exact zero. Keeping the next-write block (not
        just ceil(pos/bs)) matches the plain path's invariant that a
        resident never releases the block its next token lands in:
        when an accept ends exactly on a block boundary, freeing that
        block would let a competing slot grab it and turn this
        request's next growth into a premature 'cache_full'."""
        bs = self.config.block_size
        keep = min(self._max_blocks, st.pos // bs + 1)
        while len(st.blocks) > keep:
            b = st.blocks.pop()
            st.table[len(st.blocks)] = 0
            self._alloc.deref(b)
        while len(st.dblocks) > keep:
            b = st.dblocks.pop()
            st.dtable[len(st.dblocks)] = 0
            self._draft_alloc.deref(b)

    def _spec_round(self):
        """One speculative decode round over the resident (all-greedy)
        slots: ONE drafter dispatch proposes spec_k tokens per slot
        from the draft model's paged cache, ONE verify dispatch scores
        all spec_k + 1 positions with the target, and the host accepts
        the longest draft prefix the target agrees with plus the
        target's own next token — every emitted token is the target's
        argmax given the previously emitted tokens, so the output
        stream is bitwise the non-speculative greedy stream. Rejected
        rows roll back via block-table truncation."""
        c = self.config
        self._grow_blocks()     # plain growth (may starve -> cache_full)
        active = [(i, st) for i, st in enumerate(self._slots)
                  if st is not None]
        if not active:
            return
        K, W, S, MB = c.spec_k, c.spec_k + 1, c.slots, self._max_blocks
        n_valid = self._spec_grow(active)
        if max(n_valid.values()) <= 1:
            # every resident is one token from its budget/cache edge —
            # nobody can consume a proposal, so a plain step is
            # strictly cheaper than draft + verify this round
            pending = self._step_dispatch()
            if pending is not None:
                self._step_complete(pending)
            return

        # --- draft-cache staleness: fallback rounds (a sampled rider
        # pinning the batch onto plain steps) advanced positions with
        # K/V deposited into the TARGET cache only. Resuming speculation
        # against those draft-cache holes is CORRECT (acceptance is the
        # target's argmax identity) but accept-degraded — count the
        # resume, and on the draft==target path resync by block-copying
        # the slot's current target blocks across pools (_spec_grow just
        # extended the draft table to the same coverage; the same jitted
        # fixed-width scatter the admission sync uses — zero recompiles).
        # A distinct draft model has no valid copy source (its K/V are
        # model-specific); its stale rows age out only as its own
        # drafter writes past them, which the counter makes visible.
        stale = [(i, st) for i, st in active if st.draft_stale]
        if stale:
            monitor.inc('spec_stale_draft_rounds_total')
            self._spec_stale_rounds += 1
            for i, st in stale:
                if self._draft_copies_target:
                    nsync = min(len(st.dblocks), len(st.blocks))
                    if nsync:
                        self._draft_cache_sync(st.dblocks[:nsync],
                                               st.blocks[:nsync])
                st.draft_stale = False

        # --- draft: K unrolled greedy steps, one dispatch -------------
        # (feed construction vectorized over the slot axis — this runs
        # once per ~K+1 emitted tokens and must stay off the host
        # critical path's per-token budget)
        t0 = time.perf_counter()
        wall0 = time.time() * 1e6
        idx = np.array([i for i, _ in active])
        lastv = np.array([st.last for _, st in active], 'int64')
        posv = np.array([st.pos for _, st in active], 'int64')
        toks = np.zeros((S, 1), 'int64')
        pos = np.zeros((S, 1), 'int64')
        dbtab = np.zeros((S, MB), 'int64')
        vb = np.zeros((S, MB), 'int64')
        toks[idx, 0] = lastv
        pos[idx, 0] = posv
        for i, st in active:
            dbtab[i] = st.dtable
            vb[i] = st.table
        dlim = np.array([min(c.max_len, len(st.dblocks) * c.block_size)
                         for _, st in active], 'int64')
        dvm = np.zeros((S, K + 1), 'int64')
        dvm[idx] = np.arange(K + 1)[None, :] < \
            np.clip(dlim - posv, 0, K + 1)[:, None]
        try:
            douts = self._drafter_bound({
                'gen_tokens': toks, 'gen_pos': pos, 'gen_btab': dbtab,
                'gen_vmask': dvm})
            drafts = np.asarray(douts[0]).reshape(S, K)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return
        draft_s = time.perf_counter() - t0

        # --- verify: one (K+1)-wide target step -----------------------
        t1 = time.perf_counter()
        vt = np.zeros((S, W), 'int64')
        vp = np.zeros((S, W), 'int64')
        vv = np.zeros((S, W), 'int64')
        vt[idx, 0] = lastv
        vt[idx, 1:] = drafts[idx]
        vp[idx] = np.clip(posv[:, None] + np.arange(W)[None, :], 0,
                          c.max_len - 1)
        nvs = np.array([n_valid[i] for i, _ in active], 'int64')
        vv[idx] = np.arange(W)[None, :] < nvs[:, None]
        try:
            out = self._verify_bound({
                'gen_tokens': vt, 'gen_pos': vp, 'gen_btab': vb,
                'gen_vmask': vv}, return_numpy=False)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return
        # overlap: admit queued prompts while the verify computes
        t_adm = time.perf_counter()
        self._admit()
        adm_s = time.perf_counter() - t_adm
        try:
            verdict = np.asarray(out[0]).reshape(S, W)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return
        verify_s = max(0.0, time.perf_counter() - t1 - adm_s)
        monitor.observe('spec_draft_seconds', draft_s)
        monitor.observe('spec_verify_seconds', verify_s)
        monitor.observe('decode_step_seconds', draft_s + verify_s)

        # --- accept + rollback ----------------------------------------
        now = time.perf_counter()
        self._decode_steps += 1
        round_proposed = round_accepted = emitted_total = 0
        # longest draft prefix the target's argmax agrees with, per slot
        agree = drafts[idx] == verdict[idx, :K]              # [n, K]
        first_miss = np.argmax(~agree, axis=1)
        runs = np.where(agree.all(axis=1), K, first_miss)
        run_by_slot = dict(zip(idx.tolist(), runs.tolist()))
        for i, st in active:
            r = st.req
            nv = n_valid[i]
            proposed = nv - 1
            m = 1 + min(run_by_slot[i], nv - 1)
            m = min(m, r.max_new_tokens - st.generated)
            emitted = [int(verdict[i, t]) for t in range(m)]
            if c.eos_id is not None and c.eos_id in emitted:
                emitted = emitted[:emitted.index(c.eos_id) + 1]
                m = len(emitted)
            accepted = max(0, m - 1)
            round_proposed += proposed
            round_accepted += accepted
            r.spec_proposed += proposed
            r.spec_accepted += accepted
            st.pos += m
            st.generated += m
            st.last = emitted[-1]
            self._spec_truncate(st)
            dt = max(0.0, now - st.last_t)
            st.last_t = now
            if r.trace is not None:
                # draft/verify are SUB-stages of the decode wall: the
                # residual host time stays in decode_step so the stage
                # sum still composes the request's end-to-end latency
                r.trace.add_stage('draft', draft_s)
                r.trace.add_stage('verify', verify_s)
                r.trace.add_stage('decode_step',
                                  max(0.0, dt - draft_s - verify_s))
                monitor.record_span('request.draft', wall0,
                                    draft_s * 1e6, trace=r.trace)
                monitor.record_span('request.verify',
                                    wall0 + draft_s * 1e6,
                                    verify_s * 1e6, trace=r.trace)
            per_tok = dt / m
            for tok in emitted:
                r.step_s.append(per_tok)
                r._emit(tok)
            emitted_total += m
            reason = self._finish_reason(st)
            if reason:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'ok'})
                if r.trace is not None and r.trace.sampled and r.step_s:
                    monitor.record_span('request.decode', st.wall0,
                                        sum(r.step_s) * 1e6,
                                        trace=r.trace)
                r._finish(reason)
        self._decode_tokens += emitted_total
        monitor.inc('decode_tokens_total', emitted_total)
        monitor.inc('spec_propose_total', round_proposed)
        monitor.inc('spec_accept_total', round_accepted)
        if round_proposed:
            # accept-collapse sentinel feed (perf_regression_total
            # {kind=accept_collapse} when the EWMA falls off its baseline)
            goodput.note_accept(round_accepted / float(round_proposed),
                                model='generate')
        self._spec_rounds += 1
        self._spec_proposed += round_proposed
        self._spec_accepted += round_accepted
        self._occ_sum += len(active) / float(c.slots)
        self._set_block_gauges()
        self._set_occupancy()

    def _step_dispatch(self):
        """Snapshot the resident slots and dispatch one decode step
        WITHOUT materializing its next-token fetch — JAX's async
        dispatch returns as soon as the step is staged, so the caller
        can do host work (admission) while the device computes."""
        c = self.config
        if c.paged:
            self._grow_blocks()
        S = c.slots
        toks = np.zeros((S, 1), 'int64')
        pos = np.zeros((S, 1), 'int64')
        sample = self._sample_feed(S)
        btab = np.zeros((S, self._max_blocks), 'int64') if c.paged \
            else None
        active = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            toks[i], pos[i] = st.last, st.pos
            r = st.req
            sample['gen_temp'][i] = r.temperature
            sample['gen_topk'][i] = r.top_k
            sample['gen_topp'][i] = r.top_p
            sample['gen_u'][i] = r._draw_u()
            if btab is not None:
                btab[i] = st.table
            active.append((i, st))
        if not active:
            return None
        feed = {'gen_tokens': toks, 'gen_pos': pos}
        if btab is not None:
            feed['gen_btab'] = btab
        feed.update(sample)
        t0 = time.perf_counter()
        try:
            out = self._step_bound(feed, return_numpy=False)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return None
        return (out, active, t0)

    def _fail_step(self, active, e):
        # an exhausted retry (or permanent fault) fails the RESIDENT
        # requests; the loop and the engine live on — the decode
        # analog of the PR 4 "pool never dies" contract
        monitor.inc('generate_step_error_total')
        blackbox.record('generate_step_error', error=e,
                        program=getattr(self._step_bound, '_program', None),
                        residents=len(active))
        for i, st in active:
            self._release(i)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'error'})
            st.req.fail(e)
        self._set_occupancy()

    def _step_complete(self, pending, exclude_s=0.0):
        out, active, t0 = pending
        try:
            # materialization = device completion; an async runtime
            # failure surfaces here and fails the step's residents
            nxt = np.asarray(out[0]).reshape(-1)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return
        monitor.observe('decode_step_seconds',
                        max(0.0, time.perf_counter() - t0 - exclude_s))
        now = time.perf_counter()
        n = len(active)
        self._decode_steps += 1
        self._decode_tokens += n
        self._occ_sum += n / float(self.config.slots)
        monitor.inc('decode_tokens_total', n)
        speculative = self.config.speculative
        for i, st in active:
            st.pos += 1
            st.generated += 1
            st.last = int(nxt[i])
            if speculative:
                # this plain step wrote position pos-1 into the TARGET
                # cache only; the draft cache now has a hole there
                st.draft_stale = True
            # per-request inter-token gap (WALL, overlap included): these
            # compose the request's 'decode_step' stage so queue +
            # prefill + decode sums to its end-to-end latency
            dt = max(0.0, now - st.last_t)
            st.last_t = now
            if st.req.trace is not None:
                st.req.trace.add_stage('decode_step', dt)
                st.req.step_s.append(dt)
            st.req._emit(st.last)
            reason = self._finish_reason(st)
            if reason:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'ok'})
                if st.req.trace is not None and st.req.trace.sampled \
                        and st.req.step_s:
                    monitor.record_span('request.decode', st.wall0,
                                        sum(st.req.step_s) * 1e6,
                                        trace=st.req.trace)
                st.req._finish(reason)
        self._set_occupancy()

    def _finish_reason(self, st):
        c = self.config
        if c.eos_id is not None and st.last == c.eos_id:
            return 'eos'
        if st.generated >= st.req.max_new_tokens:
            return 'length'
        if st.pos >= c.max_len:
            # the cache has no row left for this token's K/V — stepping
            # further would attend past the buffer
            return 'cache_full'
        return None

    def _evict_expired(self):
        now = time.monotonic()
        for i, st in enumerate(self._slots):
            if st is not None and st.req.expired(now):
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'deadline'})
                st.req.fail(DeadlineExceededError(
                    "deadline passed mid-generation after %d tokens"
                    % st.generated))
        if self._pending_admit is not None and \
                self._pending_admit.expired(now):
            req, self._pending_admit = self._pending_admit, None
            monitor.inc('generate_request_total',
                        labels={'outcome': 'deadline'})
            req.fail(DeadlineExceededError(
                "deadline passed waiting for free KV blocks"))
        self._set_occupancy()

    def _fail_expired(self, expired):
        now = time.monotonic()
        for r in expired:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'deadline'})
            r.fail(DeadlineExceededError(
                "deadline passed after %.3fs in queue"
                % (now - r.enqueue_t)))

    def _release(self, i):
        st = self._slots[i]
        if st is not None and self.config.paged:
            self._release_blocks(st)
        self._slots[i] = None
        self._free.append(i)

    def _set_occupancy(self):
        n = sum(1 for s in self._slots if s is not None)
        occ = n / float(len(self._slots))
        self._occ_peak = max(self._occ_peak, occ)
        self._active_peak = max(self._active_peak, n)
        monitor.set_gauge('kv_slot_occupancy', occ)

    # ------------------------------------------------------------------
    def stats(self):
        """Decode-loop statistics since construction. Paged engines add
        the block-level capacity accounting under 'blocks' — physical
        pool state, the peak footprint, and the prefix-cache entry
        count (the monitor mirrors it as kv_blocks_in_use/free)."""
        steps = self._decode_steps
        out = {
            'slots': self.config.slots,
            'active': sum(1 for s in self._slots if s is not None),
            'peak_active': self._active_peak,
            'queue_depth': self.queue.depth(),
            'decode_steps': steps,
            'decode_tokens': self._decode_tokens,
            'peak_slot_occupancy': round(self._occ_peak, 4),
            'mean_slot_occupancy': round(self._occ_sum / steps, 4)
            if steps else 0.0,
        }
        if self.config.paged:
            out['blocks'] = {
                'block_size': self.config.block_size,
                'capacity': self._alloc.capacity,
                'in_use': self._alloc.in_use(),
                'free': self._alloc.available(),
                'peak_in_use': self._blocks_peak,
                'prefix_entries': len(self._prefix)
                if self._prefix is not None else 0,
            }
        if self.config.speculative:
            prop = self._spec_proposed
            out['spec'] = {
                'k': self.config.spec_k,
                'rounds': self._spec_rounds,
                'fallback_rounds': self._spec_fallbacks,
                'stale_draft_rounds': self._spec_stale_rounds,
                'proposed': prop,
                'accepted': self._spec_accepted,
                'accept_rate': round(self._spec_accepted / float(prop), 4)
                if prop else 0.0,
                'draft_blocks_in_use': self._draft_alloc.in_use(),
            }
        out['goodput'] = goodput.stats(fps=self._goodput_fp_set())
        return out

    def _goodput_fp_set(self):
        """Fingerprints of every program this engine dispatches (decode
        step, per-bucket prefills, drafter/verify/draft-prefills) — the
        filter for the engine-scoped stats()['goodput'] block. Memoized:
        the program set is fixed at construction."""
        if self._goodput_fps is None:
            progs = [self._step_prog] + \
                [p for p, _ in self._prefill.values()]
            if self.config.speculative:
                progs += [self._drafter_prog, self._verify_prog]
                progs += [p for p, _ in self._draft_prefill.values()]
            fps = set()
            for p in progs:
                fp = p._fingerprint()
                fps.add(fp)
                goodput.name_model(fp, 'generate')
            self._goodput_fps = fps
        return self._goodput_fps
