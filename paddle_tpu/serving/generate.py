"""Continuous-batching generative decode engine with a device-resident
KV cache.

The PR 4 `ServingEngine` batches fixed-signature SINGLE-CALL predictors:
work is admitted at batch boundaries, so decode throughput of an
autoregressive model is bounded by the slowest sentence in each batch.
This module is the decode-native path:

- **Persistent device-resident KV cache.** One pair of persistable
  ``[slots, layers, heads, max_len, head_dim]`` buffers
  (models/transformer.py ``KV_CACHE_K``/``KV_CACHE_V``) lives in the
  engine's scope like any other executor state: the decode step reads AND
  writes them, so the PR 1 donation path aliases each step's update in
  place — the cache never doubles in HBM and never crosses the host.
- **Two compiled signatures, fixed forever.** A per-prompt-bucket
  ``prefill`` (prompt lengths pad onto ``prompt_buckets``, the
  reader/bucketing ladder idiom) and ONE single-token ``decode step``
  over all slots. ``warmup()`` compiles every cell through the PR 1
  fingerprint cache; steady-state traffic of ANY prompt/output-length mix
  re-executes exactly that set — ``recompiles_after_warmup = 0``.
- **In-flight (continuous) batching.** New requests are admitted into
  free cache slots at TOKEN boundaries — between decode steps — and
  finished / deadline-expired requests are evicted per step, so a long
  generation never holds short ones hostage. Every op in the step program
  is slot-row-independent (ops/kv_cache_ops.py), so co-residents never
  perturb each other's numerics: tests/test_generate.py pins exact parity
  between concurrent and sequential execution.
- **Streaming responses.** Each `GenerateRequest` is a future AND a token
  stream (``for tok in req.stream()``); per-request deadlines ride the
  PR 4 bounded `RequestQueue` (structured `LoadShedError` backpressure)
  and are enforced both in the queue and mid-generation.

Dispatch rides `Executor.bind` (PR 6): the per-token host tax is state
staging + one compiled call, with fault injection and retry at the 'run'
site exactly as `Executor.run` (a transient fault retries inside the
step; an exhausted retry fails the RESIDENT requests and the engine keeps
serving).

Monitor series: ``decode_tokens_total``, ``kv_slot_occupancy``,
``decode_step_seconds``, ``prefill_seconds``,
``generate_request_total{outcome=ok|error|shed|deadline|rejected|stopped}``,
``generate_queue_depth``, ``generate_step_error_total``,
``generate_warmup_total``. Full catalog: docs/observability.md; tuning
guide: docs/serving.md.
"""
import queue as _pyqueue
import threading
import time

import numpy as np

from .. import monitor
from .. import trace as trace_mod
from .. import unique_name
from ..executor import Executor, Scope, scope_guard
from ..framework import Program, TPUPlace, program_guard
from ..models.transformer import (KV_CACHE_K, KV_CACHE_V, LMConfig,
                                  build_lm_decode_step, build_lm_prefill)
from ..reader.bucketing import bucketize
from .batcher import (DeadlineExceededError, EngineStoppedError,
                      LoadShedError, Request, RequestQueue,
                      resolve_metrics_port, start_metrics_server)

__all__ = ['GenerateConfig', 'GenerateEngine', 'GenerateRequest',
           'GenerateResult']

_DONE = object()


class GenerateResult(list):
    """What ``GenerateRequest.result()`` returns: the generated token ids
    (it IS a list — equality/iteration/len behave like the token list)
    plus the structured completion metadata a caller routing on latency
    needs:

    - ``finish_reason``: 'eos' | 'length' | 'cache_full'
    - ``timing``: the request's latency budget — ``queue_s``,
      ``prefill_s``, ``decode_step_s`` (sum over steps), ``total_s``,
      ``tokens``, ``step_s_mean`` / ``step_s_p99`` (per-token decode
      gaps), and the ``trace_id`` joining it to the trace log
      (docs/observability.md).
    """

    def __init__(self, tokens, finish_reason=None, timing=None):
        list.__init__(self, tokens)
        self.finish_reason = finish_reason
        self.timing = timing

    @property
    def tokens(self):
        return list(self)


class GenerateConfig(object):
    """Decode-engine knobs.

    - model: an `LMConfig` (decode programs share parameter names with
      `build_lm`, so a scope trained for the LM serves directly).
    - slots: KV-cache width — the max number of in-flight sequences.
    - max_len: cache length per slot; prompt + generated tokens beyond it
      end the request with finish_reason='cache_full'.
    - prompt_buckets: ascending prompt-length ladder; one prefill program
      compiles per bucket. Default: powers of two from 16 up to max_len/2.
    - eos_id: token ending a sequence (None = length-bounded only).
    - max_new_tokens: per-request generation cap when submit() gives none.
    - queue_cap / default_deadline_s: PR 4 bounded-queue semantics.
    - seed: parameter-init seed (two engines built with equal seeds hold
      identical weights — the parity-test contract).
    - metrics_port: as ServingConfig.metrics_port (None falls back to
      PADDLE_METRICS_PORT; the endpoint rides start()/stop()).
    """

    def __init__(self, model=None, slots=8, max_len=256,
                 prompt_buckets=None, eos_id=None, max_new_tokens=64,
                 pad_id=0, queue_cap=256, default_deadline_s=60.0,
                 seed=0, metrics_port=None, idle_poll_s=0.02):
        self.model = model or LMConfig()
        self.slots = int(slots)
        self.max_len = int(max_len)
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2")
        if prompt_buckets is None:
            prompt_buckets, b = [], 16
            while b <= self.max_len // 2:
                prompt_buckets.append(b)
                b *= 2
            if not prompt_buckets:
                prompt_buckets = [self.max_len // 2 or 1]
        self.prompt_buckets = sorted(set(int(b) for b in prompt_buckets))
        if not self.prompt_buckets:
            raise ValueError("prompt_buckets must not be empty")
        if self.prompt_buckets[0] < 1 or \
                self.prompt_buckets[-1] > self.max_len:
            raise ValueError(
                "prompt_buckets %r must lie in [1, max_len=%d]"
                % (prompt_buckets, self.max_len))
        self.eos_id = eos_id
        self.max_new_tokens = int(max_new_tokens)
        self.pad_id = int(pad_id)
        self.queue_cap = int(queue_cap)
        self.default_deadline_s = default_deadline_s
        self.seed = int(seed)
        self.metrics_port = metrics_port
        self.idle_poll_s = float(idle_poll_s)


class GenerateRequest(Request):
    """One prompt in flight: the PR 4 future contract (`result()`,
    `fail()`, deadline) plus a per-token stream. `result()` returns a
    `GenerateResult` — the generated-token list enriched with
    ``finish_reason`` and the ``timing`` breakdown (queue/prefill/
    per-token decode); ``for tok in req.stream()`` consumes tokens as
    decode steps deliver them. `finish_reason` is
    'eos' | 'length' | 'cache_full' after a normal finish."""

    __slots__ = ('prompt', 'max_new_tokens', 'tokens', 'finish_reason',
                 'step_s', '_stream_q')

    def __init__(self, prompt, seq_len, bucket, deadline, max_new_tokens):
        Request.__init__(self, {'prompt': prompt}, 1, seq_len, bucket,
                         deadline)
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens = []
        self.finish_reason = None
        self.step_s = []        # per-token decode gaps (bounded by
        self._stream_q = _pyqueue.Queue()   # max_new_tokens)

    # engine-side delivery ------------------------------------------------
    def _emit(self, tok):
        self.tokens.append(tok)
        self._stream_q.put(tok)

    def _finish(self, reason):
        self.finish_reason = reason
        tr = self.trace
        if tr is not None and self.timing is None:
            rec = tr.finish('ok', tokens=len(self.tokens))
            t = trace_mod.flat_timing(rec)
            t['tokens'] = len(self.tokens)
            t['finish_reason'] = reason
            if self.step_s:
                srt = sorted(self.step_s)
                t['step_s_mean'] = sum(srt) / len(srt)
                t['step_s_p99'] = srt[monitor._rank_idx(0.99, len(srt))]
            self.timing = t
        Request.done(self, GenerateResult(self.tokens,
                                          finish_reason=reason,
                                          timing=self.timing))
        self._stream_q.put(_DONE)

    def fail(self, error):
        Request.fail(self, error)
        self._stream_q.put(_DONE)

    # consumer side -------------------------------------------------------
    def stream(self, timeout=None):
        """Yield generated tokens as they arrive; on a failed request the
        error raises AFTER the tokens already delivered. `timeout` bounds
        the wait for EACH token; with no explicit timeout the request's
        own deadline (+1s grace) bounds every wait instead — a consumer
        must never hang past its deadline, even on an engine that was
        never started (the result() contract)."""
        while True:
            t = timeout
            if t is None and self.deadline is not None:
                t = max(0.0, self.deadline - time.monotonic()) + 1.0
            try:
                item = self._stream_q.get(timeout=t)
            except _pyqueue.Empty:
                raise DeadlineExceededError(
                    "no token within %.3fs" % (t or 0.0))
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item


class _Slot(object):
    __slots__ = ('req', 'pos', 'generated', 'last', 'last_t', 'wall0')

    def __init__(self, req, pos, last):
        self.req = req
        self.pos = pos          # cache position the NEXT step writes
        self.generated = 1      # prefill already emitted the first token
        self.last = last        # last generated token (next step's input)
        self.last_t = time.perf_counter()   # previous token's completion
        self.wall0 = time.time() * 1e6      # decode-phase start (us)


class GenerateEngine(object):
    """In-process continuous-batching decode engine. ::

        cfg = fluid.serving.GenerateConfig(
            model=LMConfig(...), slots=8, max_len=256, eos_id=1)
        engine = fluid.serving.GenerateEngine(cfg)
        engine.warmup()                      # compiles every signature
        with engine:                         # start()/stop()
            req = engine.submit(prompt_ids, max_new_tokens=32)
            for tok in req.stream():         # streams per decode step
                ...
            full = engine.submit(p2).result()

    Pass ``scope=`` to serve already-trained parameters (names match
    build_lm); otherwise the engine initializes fresh parameters from
    ``config.seed``.
    """

    def __init__(self, config=None, scope=None):
        self.config = config or GenerateConfig()
        self.scope = scope if scope is not None else Scope()
        self.executor = Executor(TPUPlace(0))
        self._build_programs()
        self._init_state()
        self.queue = RequestQueue(self.config.queue_cap)
        self._slots = [None] * self.config.slots
        self._free = list(range(self.config.slots))[::-1]
        self._prefill_bound = {}
        self._step_bound = None
        self._thread = None
        self._started = False
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._metrics_server = None
        self._decode_steps = 0
        self._decode_tokens = 0
        self._occ_sum = 0.0
        self._occ_peak = 0.0
        monitor.set_gauge('kv_slot_occupancy', 0.0)
        monitor.set_gauge('generate_queue_depth', 0.0)

    # ------------------------------------------------------------------
    # build + state
    def _build_programs(self):
        cfg, c = self.config.model, self.config
        self._step_prog, self._startup = Program(), Program()
        self._startup.random_seed = c.seed
        self._step_prog.random_seed = c.seed
        with program_guard(self._step_prog, self._startup):
            with unique_name.guard():
                self._step_vars = build_lm_decode_step(cfg, c.slots,
                                                       c.max_len)
        self._prefill = {}
        for b in c.prompt_buckets:
            main, start = Program(), Program()
            main.random_seed = c.seed
            with program_guard(main, start):
                with unique_name.guard():
                    v = build_lm_prefill(cfg, b, c.slots, c.max_len)
            self._prefill[b] = (main, v)

    def _init_state(self):
        import jax.numpy as jnp
        cfg, c = self.config.model, self.config
        with scope_guard(self.scope):
            if not self.scope.has('tok_emb.w'):
                # fresh engine: init params from config.seed; a provided
                # scope with trained weights skips this entirely
                self.executor.run(self._startup, scope=self.scope)
        if not self.scope.has(KV_CACHE_K):
            dh = cfg.d_model // cfg.n_head
            shape = (c.slots, cfg.n_layer, cfg.n_head, c.max_len, dh)
            self.scope.set(KV_CACHE_K, jnp.zeros(shape, 'float32'))
            self.scope.set(KV_CACHE_V, jnp.zeros(shape, 'float32'))

    # ------------------------------------------------------------------
    # warmup
    def warmup(self):
        """Bind + compile every signature the engine will ever dispatch:
        one prefill per prompt bucket and the decode step. Returns
        {'buckets', 'compiles', 'reused', 'seconds'}; `compiles` is the
        compile_cache_miss delta — 0 when a structurally identical engine
        already warmed the process-wide fingerprint cache. Signatures
        register in the warmup farm (paddle_tpu.warmfarm), so `reused`
        reports how many of this engine's cells were already compiled by
        an earlier process-sharing consumer (bind() still executes each
        program once — it must prime THIS engine's KV-cache state — but
        a reused cell binds at cache-hit speed, compile_seconds ≈ 0)."""
        if self._started:
            # bind() EXECUTES each program once: re-warming a live engine
            # would zero cache rows of resident slots mid-generation
            raise RuntimeError(
                "warmup() executes the decode programs against the live "
                "KV cache and must not race the started engine loop — "
                "warm up before start() (start() warms up automatically)")
        from ..warmfarm import farm
        t0 = time.perf_counter()
        before = monitor.counters()
        S = self.config.slots
        reused = 0
        with monitor.span('generate.warmup'):
            for b, (prog, v) in sorted(self._prefill.items()):
                feed = {'gen_prompt': np.zeros((1, b), 'int64'),
                        'gen_slot': np.zeros((1, 1), 'int64'),
                        'gen_len': np.ones((1, 1), 'int64')}
                key, already = farm.track(self.executor, prog, feed,
                                          fetch_list=[v['first_token']],
                                          scope=self.scope)
                self._prefill_bound[b] = self.executor.bind(
                    prog, feed, fetch_list=[v['first_token']],
                    scope=self.scope)
                if already:
                    reused += 1
                else:
                    farm.commit(key)
            feed = {'gen_tokens': np.zeros((S, 1), 'int64'),
                    'gen_pos': np.zeros((S, 1), 'int64')}
            key, already = farm.track(
                self.executor, self._step_prog, feed,
                fetch_list=[self._step_vars['next_tokens']],
                scope=self.scope)
            self._step_bound = self.executor.bind(
                self._step_prog, feed,
                fetch_list=[self._step_vars['next_tokens']],
                scope=self.scope)
            if already:
                reused += 1
            else:
                farm.commit(key)
        delta = monitor.counter_delta(before)
        compiles = sum(v for k, v in delta.items()
                       if k.startswith('compile_cache_miss'))
        monitor.inc('generate_warmup_total')
        return {'buckets': len(self._prefill_bound),
                'compiles': int(compiles), 'reused': int(reused),
                'seconds': round(time.perf_counter() - t0, 3)}

    # ------------------------------------------------------------------
    # lifecycle
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self.queue.closed:
                raise EngineStoppedError(
                    "a stopped GenerateEngine cannot restart — build a "
                    "fresh engine (the queue already failed its callers)")
            if self._step_bound is None:
                self.warmup()
            self._started = True
            if self._metrics_server is None:
                self._metrics_server = start_metrics_server(
                    self._resolve_metrics_port(), 'GenerateEngine')
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name='paddle-generate',
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout_s=10.0):
        """Close the queue (queued requests fail with EngineStoppedError),
        fail resident generations, join the decode loop."""
        with self._lock:
            self._started = False
        self._stop_evt.set()
        drained = self.queue.close()
        if drained:
            monitor.inc('generate_request_total', drained,
                        labels={'outcome': 'stopped'})
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _resolve_metrics_port(self):
        return resolve_metrics_port(self.config.metrics_port)

    @property
    def metrics_port(self):
        return self._metrics_server.port if self._metrics_server else None

    # ------------------------------------------------------------------
    # request path
    def submit(self, prompt, max_new_tokens=None, deadline_s=None):
        """Enqueue one prompt (1-D int token ids); returns the
        `GenerateRequest` stream/future. Raises ValueError synchronously
        for prompts the ladder cannot serve and `LoadShedError` when the
        bounded queue is full."""
        prompt = np.asarray(prompt, dtype='int64').reshape(-1)
        buckets = self.config.prompt_buckets
        if prompt.size < 1 or prompt.size > buckets[-1]:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'rejected'})
            raise ValueError(
                "prompt length %d outside [1, %d] (largest prompt "
                "bucket) — trim the prompt or widen prompt_buckets"
                % (prompt.size, buckets[-1]))
        if max_new_tokens is None:
            max_new_tokens = self.config.max_new_tokens
        if int(max_new_tokens) < 1:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'rejected'})
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = GenerateRequest(prompt, prompt.size,
                              bucketize(prompt.size, buckets), deadline,
                              int(max_new_tokens))
        req.trace = trace_mod.start('generate')
        try:
            self.queue.put(req)
        except (LoadShedError, EngineStoppedError) as e:
            # finishes the trace with the right outcome (keep-errors)
            monitor.inc('generate_request_total', labels={
                'outcome': 'shed' if isinstance(e, LoadShedError)
                else 'stopped'})
            req.fail(e)
            raise
        monitor.set_gauge('generate_queue_depth', self.queue.depth())
        return req

    def generate(self, prompt, max_new_tokens=None, deadline_s=None,
                 timeout=None):
        """Blocking convenience: submit + result (the generated tokens)."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline_s=deadline_s).result(timeout)

    def generate_once(self, prompt, max_new_tokens=None):
        """Synchronous single-prompt greedy decode on slot 0, driving the
        SAME compiled prefill/step programs step by step — the sequential
        reference the parity tests compare the continuous batcher
        against, and a zero-thread debug path. Only valid while the
        engine is NOT started (it shares the loop's cache slots)."""
        if self._started:
            raise RuntimeError(
                "generate_once drives the decode programs inline and "
                "must not race the started engine loop — use submit()")
        if self._step_bound is None:
            self.warmup()
        prompt = np.asarray(prompt, dtype='int64').reshape(-1)
        if max_new_tokens is None:
            max_new_tokens = self.config.max_new_tokens
        c = self.config
        first = self._run_prefill(0, prompt)
        tokens, last, pos = [first], first, prompt.size
        while (len(tokens) < max_new_tokens and pos < c.max_len and
               (c.eos_id is None or last != c.eos_id)):
            S = c.slots
            toks = np.zeros((S, 1), 'int64')
            posf = np.zeros((S, 1), 'int64')
            toks[0], posf[0] = last, pos
            out = self._step_bound({'gen_tokens': toks, 'gen_pos': posf})
            last = int(np.asarray(out[0]).reshape(-1)[0])
            tokens.append(last)
            pos += 1
        return tokens

    # ------------------------------------------------------------------
    # decode loop
    def _loop(self):
        poll = self.config.idle_poll_s
        while not self._stop_evt.is_set():
            self._evict_expired()
            self._admit()
            if not any(s is not None for s in self._slots):
                # idle: block briefly for new work instead of spinning
                batch, expired = self.queue.take_batch(1, 0.0,
                                                       poll_s=poll)
                self._fail_expired(expired)
                if batch:
                    self._admit_one(batch[0])
                monitor.set_gauge('generate_queue_depth',
                                  self.queue.depth())
                continue
            pending = self._step_dispatch()
            if pending is not None:
                # overlap: admit queued prompts (queue pops + prefill
                # staging) while the dispatched step computes on device.
                # Eviction stays OUT of this window — releasing a slot
                # the in-flight step's snapshot references would let a
                # new tenant double-book it before completion lands.
                t_adm = time.perf_counter()
                self._admit()
                # admission time is observed as prefill_seconds already;
                # exclude it so decode_step_seconds stays a per-token
                # signal instead of double-counting the overlap window
                self._step_complete(pending,
                                    exclude_s=time.perf_counter() - t_adm)
        # shutdown: a resident generation must not leave its caller
        # blocked forever
        for i, st in enumerate(self._slots):
            if st is not None:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'stopped'})
                st.req.fail(EngineStoppedError(
                    "engine stopped after %d generated tokens"
                    % st.generated))
        self._set_occupancy()

    def _admit(self):
        while self._free and not self._stop_evt.is_set():
            batch, expired = self.queue.take_batch(1, 0.0, poll_s=0.0)
            self._fail_expired(expired)
            if not batch:
                return
            self._admit_one(batch[0])
            monitor.set_gauge('generate_queue_depth', self.queue.depth())

    def _admit_one(self, req):
        slot = self._free.pop()
        qs = max(0.0, time.monotonic() - req.enqueue_t)
        if req.trace is not None:
            # queue stage closes at admission; the span rides the
            # SUBMITTER's tid so the trace shows the thread hop into
            # this decode loop
            req.trace.add_stage('queue', qs)
            monitor.record_span('request.queue', req.enqueue_wall,
                                qs * 1e6, tid=req._tid, trace=req.trace)
        t0 = time.perf_counter()
        pf_wall = time.time() * 1e6
        try:
            first = self._run_prefill(slot, req.prompt)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._free.append(slot)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'error'})
            req.fail(e)
            return
        pf_s = time.perf_counter() - t0
        monitor.observe('prefill_seconds', pf_s)
        if req.trace is not None:
            req.trace.add_stage('prefill', pf_s)
            monitor.record_span('request.prefill', pf_wall, pf_s * 1e6,
                                trace=req.trace)
        monitor.inc('decode_tokens_total')
        self._decode_tokens += 1
        req._emit(first)
        st = _Slot(req, pos=req.prompt.size, last=first)
        reason = self._finish_reason(st)
        if reason:
            self._free.append(slot)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'ok'})
            req._finish(reason)
        else:
            self._slots[slot] = st
        self._set_occupancy()

    def _run_prefill(self, slot, prompt):
        b = bucketize(prompt.size, self.config.prompt_buckets)
        padded = np.full((1, b), self.config.pad_id, 'int64')
        padded[0, :prompt.size] = prompt
        out = self._prefill_bound[b]({
            'gen_prompt': padded,
            'gen_slot': np.array([[slot]], 'int64'),
            'gen_len': np.array([[prompt.size]], 'int64')})
        return int(np.asarray(out[0]).reshape(-1)[0])

    def _step(self):
        """One decode step, dispatch + completion back to back (the
        inline/debug path; the engine loop splits the two so admission
        overlaps the device time)."""
        pending = self._step_dispatch()
        if pending is not None:
            self._step_complete(pending)

    def _step_dispatch(self):
        """Snapshot the resident slots and dispatch one decode step
        WITHOUT materializing its next-token fetch — JAX's async
        dispatch returns as soon as the step is staged, so the caller
        can do host work (admission) while the device computes."""
        S = self.config.slots
        toks = np.zeros((S, 1), 'int64')
        pos = np.zeros((S, 1), 'int64')
        active = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            toks[i], pos[i] = st.last, st.pos
            active.append((i, st))
        if not active:
            return None
        t0 = time.perf_counter()
        try:
            out = self._step_bound({'gen_tokens': toks, 'gen_pos': pos},
                                   return_numpy=False)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return None
        return (out, active, t0)

    def _fail_step(self, active, e):
        # an exhausted retry (or permanent fault) fails the RESIDENT
        # requests; the loop and the engine live on — the decode
        # analog of the PR 4 "pool never dies" contract
        monitor.inc('generate_step_error_total')
        for i, st in active:
            self._release(i)
            monitor.inc('generate_request_total',
                        labels={'outcome': 'error'})
            st.req.fail(e)
        self._set_occupancy()

    def _step_complete(self, pending, exclude_s=0.0):
        out, active, t0 = pending
        try:
            # materialization = device completion; an async runtime
            # failure surfaces here and fails the step's residents
            nxt = np.asarray(out[0]).reshape(-1)
        except Exception as e:  # noqa: BLE001 — delivered per-request
            self._fail_step(active, e)
            return
        monitor.observe('decode_step_seconds',
                        max(0.0, time.perf_counter() - t0 - exclude_s))
        now = time.perf_counter()
        n = len(active)
        self._decode_steps += 1
        self._decode_tokens += n
        self._occ_sum += n / float(self.config.slots)
        monitor.inc('decode_tokens_total', n)
        for i, st in active:
            st.pos += 1
            st.generated += 1
            st.last = int(nxt[i])
            # per-request inter-token gap (WALL, overlap included): these
            # compose the request's 'decode_step' stage so queue +
            # prefill + decode sums to its end-to-end latency
            dt = max(0.0, now - st.last_t)
            st.last_t = now
            if st.req.trace is not None:
                st.req.trace.add_stage('decode_step', dt)
                st.req.step_s.append(dt)
            st.req._emit(st.last)
            reason = self._finish_reason(st)
            if reason:
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'ok'})
                if st.req.trace is not None and st.req.trace.sampled \
                        and st.req.step_s:
                    monitor.record_span('request.decode', st.wall0,
                                        sum(st.req.step_s) * 1e6,
                                        trace=st.req.trace)
                st.req._finish(reason)
        self._set_occupancy()

    def _finish_reason(self, st):
        c = self.config
        if c.eos_id is not None and st.last == c.eos_id:
            return 'eos'
        if st.generated >= st.req.max_new_tokens:
            return 'length'
        if st.pos >= c.max_len:
            # the cache has no row left for this token's K/V — stepping
            # further would attend past the buffer
            return 'cache_full'
        return None

    def _evict_expired(self):
        now = time.monotonic()
        for i, st in enumerate(self._slots):
            if st is not None and st.req.expired(now):
                self._release(i)
                monitor.inc('generate_request_total',
                            labels={'outcome': 'deadline'})
                st.req.fail(DeadlineExceededError(
                    "deadline passed mid-generation after %d tokens"
                    % st.generated))
        self._set_occupancy()

    def _fail_expired(self, expired):
        now = time.monotonic()
        for r in expired:
            monitor.inc('generate_request_total',
                        labels={'outcome': 'deadline'})
            r.fail(DeadlineExceededError(
                "deadline passed after %.3fs in queue"
                % (now - r.enqueue_t)))

    def _release(self, i):
        self._slots[i] = None
        self._free.append(i)

    def _set_occupancy(self):
        occ = sum(1 for s in self._slots if s is not None) \
            / float(len(self._slots))
        self._occ_peak = max(self._occ_peak, occ)
        monitor.set_gauge('kv_slot_occupancy', occ)

    # ------------------------------------------------------------------
    def stats(self):
        """Decode-loop statistics since construction."""
        steps = self._decode_steps
        return {
            'slots': self.config.slots,
            'active': sum(1 for s in self._slots if s is not None),
            'queue_depth': self.queue.depth(),
            'decode_steps': steps,
            'decode_tokens': self._decode_tokens,
            'peak_slot_occupancy': round(self._occ_peak, 4),
            'mean_slot_occupancy': round(self._occ_sum / steps, 4)
            if steps else 0.0,
        }
