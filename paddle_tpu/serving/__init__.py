"""In-process serving engine: dynamic batching, shape-bucketed compile
warmup, and a load-shedding predictor pool over `inference.Predictor`.

The throughput-oriented request path the single-request Predictor lacks:
concurrent callers submit, compatible requests coalesce into padded
bucket-shaped batches, a worker pool executes them through the shared
compile cache (pre-warmed by `ServingEngine.warmup`), and a bounded queue
sheds overload with structured errors instead of unbounded latency. See
docs/serving.md for architecture and tuning.

Multi-tenant layer (fleet.py / router.py): a `ModelFleet` hosts many
models resident in one process under shared HBM / paged-block budgets
with zero-downtime hot-swap, and a `Router` schedules admissions by
priority class and deadline using live `goodput.cost_estimate` pricing.
"""
from .bucketing import BucketLadder
from .batcher import (ServingError, LoadShedError, DeadlineExceededError,
                      EngineStoppedError, Request, RequestQueue)
from .engine import ServingConfig, ServingEngine, create_engine
from .fleet import FleetError, ModelFleet
from .generate import (GenerateConfig, GenerateEngine, GenerateRequest,
                       GenerateResult)
from .kv_blocks import BlockAllocator, PrefixCache, QuotaBlockAllocator
from .router import Router, TenantConfig

__all__ = [
    'BucketLadder', 'Request', 'RequestQueue',
    'ServingError', 'LoadShedError', 'DeadlineExceededError',
    'EngineStoppedError',
    'ServingConfig', 'ServingEngine', 'create_engine',
    'GenerateConfig', 'GenerateEngine', 'GenerateRequest',
    'GenerateResult',
    'BlockAllocator', 'PrefixCache', 'QuotaBlockAllocator',
    'FleetError', 'ModelFleet', 'Router', 'TenantConfig',
]
