"""In-process serving engine: dynamic batching, shape-bucketed compile
warmup, and a load-shedding predictor pool over `inference.Predictor`.

The throughput-oriented request path the single-request Predictor lacks:
concurrent callers submit, compatible requests coalesce into padded
bucket-shaped batches, a worker pool executes them through the shared
compile cache (pre-warmed by `ServingEngine.warmup`), and a bounded queue
sheds overload with structured errors instead of unbounded latency. See
docs/serving.md for architecture and tuning.
"""
from .bucketing import BucketLadder
from .batcher import (ServingError, LoadShedError, DeadlineExceededError,
                      EngineStoppedError, Request, RequestQueue)
from .engine import ServingConfig, ServingEngine, create_engine
from .generate import (GenerateConfig, GenerateEngine, GenerateRequest,
                       GenerateResult)

__all__ = [
    'BucketLadder', 'Request', 'RequestQueue',
    'ServingError', 'LoadShedError', 'DeadlineExceededError',
    'EngineStoppedError',
    'ServingConfig', 'ServingEngine', 'create_engine',
    'GenerateConfig', 'GenerateEngine', 'GenerateRequest',
    'GenerateResult',
]
