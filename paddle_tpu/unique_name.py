"""Unique name generator.

Capability parity with reference python/paddle/fluid/unique_name.py:25,57
(UniqueNameGenerator + guard). Build-time only.
"""
import contextlib

__all__ = ['generate', 'switch', 'guard']


class UniqueNameGenerator(object):
    def __init__(self, prefix=None):
        self.ids = {}
        self.prefix = prefix or ''

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    if new_generator is None:
        generator = UniqueNameGenerator()
    else:
        generator = new_generator
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    yield
    switch(old)
