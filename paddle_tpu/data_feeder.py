"""DataFeeder: convert python/numpy minibatch rows into feed dicts.

Reference python/paddle/fluid/data_feeder.py (DataFeeder → LoDTensor batches,
multi-device split). TPU-native: produces numpy feed dicts; multi-device
split is handled by the sharding layer (parallel/), not by the feeder.
"""
import numpy as np

from .framework import Variable, default_main_program

__all__ = ['DataFeeder']


class DataToLoDTensorConverter(object):
    def __init__(self, shape, dtype):
        self.shape = [s if s is not None and s >= 0 else -1 for s in shape]
        self.dtype = dtype
        self.data = []

    def feed(self, data):
        self.data.append(np.asarray(data))

    def done(self):
        tail = self.shape[1:] if self.shape and self.shape[0] == -1 \
            else self.shape
        arrs = []
        for d in self.data:
            a = np.asarray(d, dtype=self.dtype)
            if tail and all(s >= 0 for s in tail) and \
                    a.shape != tuple(tail):
                a = a.reshape(tail)
            arrs.append(a)
        return np.stack(arrs)


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list entries must be Variables/names")
            self.feed_names.append(each_var.name)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(each_var.dtype)
        self.place = place

    def feed(self, iterable):
        converters = [DataToLoDTensorConverter(shape, dtype)
                      for shape, dtype in zip(self.feed_shapes,
                                              self.feed_dtypes)]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), \
                "sample width != number of feed vars"
            for value, conv in zip(each_sample, converters):
                conv.feed(value)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}

    def feed_parallel(self, iterable, num_places=None):
        """Split one batch across devices (reference multi-device feed);
        returns a list of per-device feed dicts."""
        full = self.feed([s for chunk in iterable for s in chunk]) \
            if isinstance(iterable[0], (list, tuple)) else self.feed(iterable)
        if not num_places or num_places <= 1:
            return [full]
        out = []
        for i in range(num_places):
            d = {}
            for k, v in full.items():
                n = v.shape[0] // num_places
                d[k] = v[i * n:(i + 1) * n]
            out.append(d)
        return out
