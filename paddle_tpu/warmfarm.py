"""Warmup farm: pre-compile a signature set once per process and share it.

The compile-time tail is the serving fleet's cold-start tax (bert_base hit
162 s in BENCH_r05, and the persistent on-disk cache is CPU-unsound —
docs/executor_performance.md), so the lever is in-process AOT reuse:
``Executor.precompile`` lowers + compiles an entry keyed by the SAME
fingerprint cache ``run()`` uses, and this module keeps the process-wide
ledger of which (program fingerprint, feed signature, fetch set, donate)
keys are already warm. Every ServingEngine / GenerateEngine ``warmup()``
routes through the farm:

- the FIRST consumer of a signature set pays the compiles and registers
  each key;
- every later consumer in the process (another engine over the same
  model, another worker thread, an A/B replica) sees its cells already
  warm and skips them — ``compile_seconds`` delta ≈ 0 and
  ``compile_cache_miss`` delta 0, the reuse contract
  tests/test_warmfarm.py asserts.

CLI twin: ``tools/warmfarm.py`` pre-compiles a model directory's bucket
grid before traffic and prints the per-signature compile seconds next to
the second-pass (reused) timings.

Counters (docs/observability.md): ``warmfarm_signature_total{outcome}``
(compiled|reused), plus the executor's ``precompile_total`` /
``compile_cache_hit`` / ``compile_cache_miss`` /``compile_seconds``.
"""
import threading
import time

from . import monitor

__all__ = ['WarmFarm', 'farm']


class WarmFarm(object):
    """Process-wide ledger of warmed compile-cache keys. Thread-safe:
    engine warmups and worker threads may race; a key is registered after
    its compile completes, so a racing duplicate pays at worst one extra
    cache hit, never a recompile."""

    def __init__(self):
        self._lock = threading.Lock()
        self._keys = {}                # key -> register wall time

    # ------------------------------------------------------------------
    def signature(self, executor, program, feed, fetch_list=None,
                  scope=None, donate=None):
        """The executor compile-cache key this (program, feed, fetch,
        donate) run would use — computed exactly like run()/bind() so the
        farm's ledger and the cache can never disagree (including the
        NAN_LOCALIZE donation force-off both apply)."""
        from . import analysis
        from .executor import (_donation_enabled, _feed_from_spec,
                               global_scope)
        if scope is None:
            scope = global_scope()
        feed2, fetch_names, static_feed, static_lods = \
            executor._prepare_run_inputs(program, _feed_from_spec(feed),
                                         scope, fetch_list, count=False)
        if donate is None and analysis.nan_localization_enabled():
            from . import flags as _flags
            if _flags.get_flags('check_nan_inf'):
                donate = False
        return (program._fingerprint(),
                executor._feed_signature(feed2, static_lods, static_feed),
                tuple(fetch_names),
                _donation_enabled(override=donate, record=False))

    def is_warm(self, key):
        with self._lock:
            return key in self._keys

    def track(self, executor, program, feed, fetch_list=None, scope=None,
              donate=None):
        """The shared warm-check protocol every engine warmup uses:
        compute the signature key, apply the LRU-eviction guard (a
        ledger entry whose compiled executable was evicted is NOT warm),
        and count the reuse. Returns (key, already_warm); callers that
        go on to compile must follow with :meth:`commit`."""
        key = self.signature(executor, program, feed,
                             fetch_list=fetch_list, scope=scope,
                             donate=donate)
        already = self.is_warm(key) and \
            executor._cache_get(key) is not None
        if already:
            monitor.inc('warmfarm_signature_total',
                        labels={'outcome': 'reused'})
        return key, already

    def commit(self, key):
        """Record a signature the caller just compiled (register + the
        'compiled' outcome — also on a re-stamp after LRU eviction,
        which IS a compile, not a reuse)."""
        self.register(key)
        monitor.inc('warmfarm_signature_total',
                    labels={'outcome': 'compiled'})

    def register(self, key):
        """Stamp (or re-stamp) a key in the ledger; returns whether it
        was new. Pure bookkeeping — outcome counters belong to the
        CALLER, which knows whether it actually compiled or reused (a
        re-stamp after an LRU-eviction recompile is a compile, not a
        reuse)."""
        with self._lock:
            fresh = key not in self._keys
            self._keys[key] = time.time()
        return fresh

    def size(self):
        with self._lock:
            return len(self._keys)

    # ------------------------------------------------------------------
    def warm(self, executor, program, feeds, fetch_list=None, scope=None,
             donate=None):
        """Precompile every feed signature in ``feeds`` (an iterable of
        feed dicts; values may be arrays or (shape, dtype) specs) that is
        not already farm-warm. Returns {'signatures', 'compiled',
        'reused', 'seconds'}."""
        from .executor import _feed_from_spec
        t0 = time.perf_counter()
        compiled = reused = 0
        for feed in feeds:
            feed = _feed_from_spec(feed)
            key, already = self.track(executor, program, feed,
                                      fetch_list=fetch_list, scope=scope,
                                      donate=donate)
            if already:
                reused += 1
                continue
            executor.precompile(program, feed, fetch_list=fetch_list,
                                scope=scope, donate=donate)
            self.commit(key)
            compiled += 1
        return {'signatures': compiled + reused, 'compiled': compiled,
                'reused': reused,
                'seconds': round(time.perf_counter() - t0, 3)}


#: the process singleton every engine warmup routes through
farm = WarmFarm()
