"""Program visualization (reference python/paddle/fluid/debugger.py
draw_block_graphviz + framework/ir/graph_viz_pass.cc).

Emits Graphviz DOT text: ops as boxes, variables as ellipses (parameters
shaded), edges for reads/writes. No graphviz binary needed — the DOT file
renders with any standard tool.
"""
from .framework import Parameter

__all__ = ['draw_block_graphviz', 'program_to_dot']


def _esc(s):
    return str(s).replace('"', '\\"')


def program_to_dot(program, max_vars=500, highlights=None):
    """DOT source for the whole program (block 0 + sub-blocks as
    clusters). At most max_vars variable nodes are emitted (edges to
    elided vars are dropped, with a truncation note); names in
    `highlights` are filled red."""
    highlights = set(highlights or ())
    lines = ['digraph Program {', '  rankdir=TB;',
             '  node [fontsize=10];']
    emitted_vars = set()
    truncated = [False]

    def emit_var(block, name, indent):
        key = 'var_%d_%s' % (block.idx, name)
        if key in emitted_vars:
            return key
        if len(emitted_vars) >= max_vars:
            truncated[0] = True
            return None
        emitted_vars.add(key)
        v = block._find_var_recursive(name)
        if name in highlights:
            style = 'style=filled fillcolor=red shape=ellipse'
        elif isinstance(v, Parameter):
            style = 'style=filled fillcolor=lightblue shape=ellipse'
        elif v is not None and v.persistable:
            style = 'style=filled fillcolor=lightgrey shape=ellipse'
        else:
            style = 'shape=ellipse'
        shape = ' %s' % (v.shape,) if v is not None and v.shape else ''
        lines.append('%s"%s" [label="%s%s" %s];'
                     % (indent, key, _esc(name), _esc(shape), style))
        return key

    def emit_block(block, indent='  '):
        for i, op in enumerate(block.ops):
            op_key = 'op_%d_%d' % (block.idx, i)
            lines.append('%s"%s" [label="%s" shape=box style=filled '
                         'fillcolor=wheat];' % (indent, op_key,
                                                _esc(op.type)))
            for name in op.input_arg_names:
                vk = emit_var(block, name, indent)
                if vk is not None:
                    lines.append('%s"%s" -> "%s";' % (indent, vk, op_key))
            for name in op.output_arg_names:
                vk = emit_var(block, name, indent)
                if vk is not None:
                    lines.append('%s"%s" -> "%s";' % (indent, op_key, vk))
            sb = op.attrs.get('sub_block')
            if isinstance(sb, int):
                lines.append('%ssubgraph cluster_%d {' % (indent, sb))
                lines.append('%s  label="block %d (%s)";'
                             % (indent, sb, _esc(op.type)))
                emit_block(program.block(sb), indent + '  ')
                lines.append('%s}' % indent)

    emit_block(program.global_block())
    if truncated[0]:
        lines.append('  "truncated" [label="... %d-var limit reached" '
                     'shape=note];' % max_vars)
    lines.append('}')
    return '\n'.join(lines)


def draw_block_graphviz(block_or_program, path='program.dot',
                        highlights=None):
    """Write the DOT file (reference debugger.draw_block_graphviz). Accepts
    a Program or a Block (the block's program is drawn)."""
    program = getattr(block_or_program, 'program', block_or_program)
    dot = program_to_dot(program, highlights=highlights)
    with open(path, 'w') as f:
        f.write(dot)
    return path
