"""Eager nn layers: Conv2D, Pool2D, FC, BatchNorm, Embedding.

Reference parity: python/paddle/fluid/imperative/nn.py:28-407 (the five
eager layers of the early dygraph). Each forward runs the SAME registered
op lowerings the compiled Program executor uses (via imperative.ops
.apply_op), so eager and graph mode share one op library — the design the
reference reaches for with its shared OpInfoMap.
"""
import numpy as np

from .base import VarBase, to_variable
from .layers import Layer
from .ops import apply_op

__all__ = ['Conv2D', 'Pool2D', 'FC', 'BatchNorm', 'Embedding']


def _act(out, act):
    if act:
        out, = apply_op(act, {'X': out}, ['Out'], {})
    return out


class Conv2D(Layer):
    """Eager conv2d (+bias, +act): reference imperative/nn.py:28."""

    def __init__(self, name_scope=None, num_channels=1, num_filters=1,
                 filter_size=3, stride=1, padding=0, dilation=1, groups=1,
                 use_cudnn=True, act=None, dtype='float32'):
        super(Conv2D, self).__init__(name_scope, dtype)
        self._act = act
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._groups = groups or 1
        fs = _pair(filter_size)
        self.weight = self.create_parameter(
            (num_filters, num_channels // self._groups, fs[0], fs[1]),
            dtype, name=self._full_name + '.w')
        self.bias = self.create_parameter(
            (num_filters,), dtype, is_bias=True,
            name=self._full_name + '.b')

    def forward(self, input):
        out, = apply_op('conv2d', {'Input': input, 'Filter': self.weight},
                        ['Output'],
                        {'strides': list(self._stride),
                         'paddings': list(self._padding),
                         'dilations': list(self._dilation),
                         'groups': self._groups})
        out, = apply_op('elementwise_add', {'X': out, 'Y': self.bias},
                        ['Out'], {'axis': 1})
        return _act(out, self._act)


class Pool2D(Layer):
    """Eager pool2d: reference imperative/nn.py (Pool2D)."""

    def __init__(self, name_scope=None, pool_size=2, pool_type='max',
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True,
                 dtype='float32'):
        super(Pool2D, self).__init__(name_scope, dtype)
        self._attrs = {
            'ksize': list(_pair(pool_size)),
            'pooling_type': pool_type,
            'strides': list(_pair(pool_stride)),
            'paddings': list(_pair(pool_padding)),
            'global_pooling': global_pooling,
            'ceil_mode': ceil_mode,
            'exclusive': exclusive,
        }

    def forward(self, input):
        out, = apply_op('pool2d', {'X': input}, ['Out'], self._attrs)
        return out


class FC(Layer):
    """Eager fully-connected (lazy weight creation on first forward, since
    the input width is unknown until then): reference imperative/nn.py FC."""

    def __init__(self, name_scope=None, size=1, num_flatten_dims=1,
                 act=None, dtype='float32'):
        super(FC, self).__init__(name_scope, dtype)
        self._size = size
        self._nfd = num_flatten_dims
        self._act = act
        self.weight = None
        self.bias = None

    def forward(self, input):
        if self.weight is None:
            in_dim = int(np.prod(input.shape[self._nfd:]))
            self.weight = self.create_parameter(
                (in_dim, self._size), self._dtype,
                name=self._full_name + '.w')
            self.bias = self.create_parameter(
                (self._size,), self._dtype, is_bias=True,
                name=self._full_name + '.b')
        out, = apply_op('mul', {'X': input, 'Y': self.weight}, ['Out'],
                        {'x_num_col_dims': self._nfd, 'y_num_col_dims': 1})
        out, = apply_op('elementwise_add', {'X': out, 'Y': self.bias},
                        ['Out'], {'axis': len(out.shape) - 1})
        return _act(out, self._act)


class BatchNorm(Layer):
    """Eager batch_norm with running-stat buffers: reference
    imperative/nn.py BatchNorm."""

    def __init__(self, name_scope=None, num_channels=1, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5,
                 dtype='float32', data_layout='NCHW'):
        super(BatchNorm, self).__init__(name_scope, dtype)
        self._act = act
        self._momentum = momentum
        self._epsilon = epsilon
        self._layout = data_layout
        self.weight = self.create_parameter(
            (num_channels,), dtype,
            default_initializer=lambda s, d, r: np.ones(s, d),
            name=self._full_name + '.scale')
        self.bias = self.create_parameter(
            (num_channels,), dtype, is_bias=True,
            name=self._full_name + '.bias')
        # running stats: buffers, not trainable
        self._mean = VarBase(np.zeros((num_channels,), dtype),
                             name=self._full_name + '.mean')
        self._variance = VarBase(np.ones((num_channels,), dtype),
                                 name=self._full_name + '.var')
        if is_test:
            self.training = False

    def forward(self, input):
        y, mean_out, var_out = apply_op(
            'batch_norm',
            {'X': input, 'Scale': self.weight, 'Bias': self.bias,
             'Mean': self._mean, 'Variance': self._variance},
            ['Y', 'MeanOut', 'VarianceOut'],
            {'momentum': self._momentum, 'epsilon': self._epsilon,
             'is_test': not self.training, 'data_layout': self._layout})
        if self.training:
            # running-stat buffers advance outside the autograd tape
            self._mean.set_value(mean_out._value)
            self._variance.set_value(var_out._value)
        return _act(y, self._act)


class Embedding(Layer):
    """Eager lookup_table: reference imperative/nn.py Embedding."""

    def __init__(self, name_scope=None, size=(1, 1), is_sparse=False,
                 padding_idx=None, dtype='float32'):
        super(Embedding, self).__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        scale = 1.0 / np.sqrt(size[1])
        self.weight = self.create_parameter(
            tuple(size), dtype,
            default_initializer=lambda s, d, r:
                r.uniform(-scale, scale, s).astype(d),
            name=self._full_name + '.w')

    def forward(self, input):
        out, = apply_op('lookup_table',
                        {'Ids': input, 'W': self.weight}, ['Out'],
                        {'padding_idx': self._padding_idx})
        return out


def _pair(x, n=2):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,) * n
