"""Eager-mode core: VarBase tensors, the tape-recording Tracer, guard().

Reference parity: python/paddle/fluid/imperative/base.py:28 `guard()`
switches the tracer on, `:46` `to_variable`; the C++ tracer
(imperative/tracer.h:40) records each op as it runs and `Autograd`
(imperative/layer.cc:103) walks the recorded graph backward. Here the tape
stores, per op, a pure replay function plus the input values captured at
execution time; `VarBase.backward()` replays the tape as one functional
program and differentiates it with jax.grad — reverse-mode AD with XLA
semantics instead of per-op grad kernels.
"""
import contextlib
import os

import numpy as np
import jax
import jax.numpy as jnp


_tracer = None


def enabled():
    return _tracer is not None


def current_tracer():
    return _tracer


@contextlib.contextmanager
def guard(place=None):
    """Enable imperative mode (reference imperative/base.py:28)."""
    global _tracer
    prev = _tracer
    _tracer = Tracer()
    try:
        yield
    finally:
        _tracer = prev


class VarBase(object):
    """Eager tensor: a jax array + autograd metadata (reference
    imperative/layer.h VarBase: var_ + grads_ + stop_gradient)."""

    def __init__(self, value, name=None, stop_gradient=True):
        self._value = jnp.asarray(value)
        self.name = name
        self.stop_gradient = stop_gradient
        self._grad = None

    # -- value access ------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return np.dtype(self._value.dtype).name

    def numpy(self):
        return np.asarray(self._value)

    def value(self):
        return self._value

    def set_value(self, value):
        self._value = jnp.asarray(value)
        return self

    def detach(self):
        return VarBase(self._value, name=self.name, stop_gradient=True)

    def astype(self, dtype):
        return VarBase(self._value.astype(dtype),
                       stop_gradient=self.stop_gradient)

    # -- autograd ----------------------------------------------------------
    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def backward(self):
        """Compute d(self)/d(leaf) for every reachable leaf VarBase with
        stop_gradient=False, accumulating into .gradient()."""
        tr = current_tracer()
        if tr is None:
            raise RuntimeError(
                "backward() outside imperative.guard(): no tape recorded")
        tr.run_backward(self)

    def __repr__(self):
        return "VarBase(%s, shape=%s, dtype=%s)" % (
            self.name or '<unnamed>', self.shape, self.dtype)

    # minimal operator sugar (python math on eager tensors)
    def _binary(self, other, op_type, reverse=False):
        from .ops import apply_op
        o = other if isinstance(other, VarBase) else to_variable(
            np.asarray(other, dtype=self.dtype))
        x, y = (o, self) if reverse else (self, o)
        return apply_op(op_type, {'X': x, 'Y': y}, ['Out'], {})[0]

    def __add__(self, o):
        return self._binary(o, 'elementwise_add')

    def __radd__(self, o):
        return self._binary(o, 'elementwise_add', True)

    def __sub__(self, o):
        return self._binary(o, 'elementwise_sub')

    def __mul__(self, o):
        return self._binary(o, 'elementwise_mul')

    def __truediv__(self, o):
        return self._binary(o, 'elementwise_div')


def to_variable(value, name=None, stop_gradient=True):
    """numpy -> eager VarBase (reference imperative/base.py:46)."""
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name, stop_gradient=stop_gradient)


class _TapeEntry(object):
    __slots__ = ('replay', 'in_vars', 'in_vals', 'out_vars')

    def __init__(self, replay, in_vars, in_vals, out_vars):
        self.replay = replay          # pure fn: list[jax values] -> list
        self.in_vars = in_vars        # VarBase refs (strong: id-stable)
        self.in_vals = in_vals        # values captured at execution time
        self.out_vars = out_vars


class Tracer(object):
    """Records eagerly-executed ops for backward replay (reference
    imperative/tracer.h:40 Trace)."""

    def __init__(self):
        self._tape = []
        self._op_counter = 0

    def next_key(self):
        self._op_counter += 1
        return jax.random.PRNGKey(self._op_counter)

    def record(self, replay, in_vars, in_vals, out_vars):
        self._tape.append(_TapeEntry(replay, in_vars, in_vals, out_vars))

    def clear(self):
        """Drop the tape (start a fresh iteration's graph)."""
        self._tape = []

    def run_backward(self, target):
        produced = {}                 # id(VarBase) -> producing entry index
        for i, e in enumerate(self._tape):
            for ov in e.out_vars:
                produced[id(ov)] = i
        if id(target) not in produced:
            raise RuntimeError("backward() target was not produced under "
                               "this imperative guard")

        # leaves: grad-requiring inputs not produced by any tape op
        leaves, leaf_ids = [], set()
        for e in self._tape:
            for iv in e.in_vars:
                if (not iv.stop_gradient and id(iv) not in produced
                        and id(iv) not in leaf_ids):
                    leaf_ids.add(id(iv))
                    leaves.append(iv)
        if not leaves:
            return

        tape = self._tape

        def forward(leaf_vals):
            env = {id(l): v for l, v in zip(leaves, leaf_vals)}
            for e in tape:
                ins = [env.get(id(iv), cap)
                       for iv, cap in zip(e.in_vars, e.in_vals)]
                outs = e.replay(ins)
                for ov, val in zip(e.out_vars, outs):
                    env[id(ov)] = val
            out = env[id(target)]
            # reference Autograd seeds d(target)=ones; for non-scalars this
            # equals differentiating sum(target)
            return jnp.sum(out)

        grads = jax.grad(forward)([l._value for l in leaves])
        for leaf, g in zip(leaves, grads):
            leaf._grad = g if leaf._grad is None else leaf._grad + g


def save_dygraph(state_dict, path):
    """Persist an eager model/optimizer state dict ({name: ndarray}) to
    `path`.npz (the dygraph analog of io.save_persistables; reference adds
    fluid.dygraph.save_persistables in the successor release)."""
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    np.savez(path if path.endswith('.npz') else path + '.npz', **arrays)


def load_dygraph(path):
    """Load a state dict saved by save_dygraph; returns {name: ndarray}
    for Layer.set_dict."""
    p = path if path.endswith('.npz') else path + '.npz'
    with np.load(p) as z:
        return {k: z[k] for k in z.files}
