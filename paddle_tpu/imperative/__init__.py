"""Imperative (dygraph) mode: eager op-by-op execution on jax arrays.

The reference's early dygraph (paddle/fluid/imperative/tracer.h:40,
layer.cc:103 Autograd; python python/paddle/fluid/imperative/base.py:28,46,
layers.py:28,169, nn.py:28-407) interprets ops eagerly on VarBase tensors
while a tracer records them for a backward walk. JAX is eager-native, so the
TPU rebuild runs each op's registered lowering function directly on jax
arrays (the SAME lowerings the compiled Program executor traces — one op
library, two execution modes) and implements `backward()` by replaying the
recorded tape under jax.grad.
"""
from .base import (guard, enabled, to_variable, current_tracer, VarBase,
                   save_dygraph, load_dygraph)
from .layers import Layer, PyLayer
from .nn import Conv2D, Pool2D, FC, BatchNorm, Embedding
from .optimizer import SGDOptimizer, AdamOptimizer
from . import ops

__all__ = ['guard', 'enabled', 'to_variable', 'current_tracer', 'VarBase',
           'save_dygraph', 'load_dygraph',
           'Layer', 'PyLayer', 'Conv2D', 'Pool2D', 'FC', 'BatchNorm',
           'Embedding', 'SGDOptimizer', 'AdamOptimizer', 'ops']
