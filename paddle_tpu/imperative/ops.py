"""apply_op: run one registered op lowering eagerly on VarBase inputs.

This is the imperative interpreter loop of the reference dygraph
(imperative/tracer.cc Trace: build the op, run it on the current place,
record it) collapsed to one function: the op's *compiled-mode* lowering
(core/registry.py) executes directly on jax arrays — the op library is
shared between the Program executor and eager mode — and the active Tracer
records a pure replay closure for backward().
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import get_op
from .base import VarBase, to_variable, current_tracer

__all__ = ['apply_op']


class _FakeOp(object):
    """Just enough of framework.Operator for lowering fns: input/output
    slot name lists + attrs."""
    __slots__ = ('type', '_inputs', '_outputs', '_attrs')

    def __init__(self, type, inputs, outputs, attrs):
        self.type = type
        self._inputs = inputs
        self._outputs = outputs
        self._attrs = attrs

    def input(self, slot):
        return self._inputs.get(slot, [])

    def output(self, slot):
        return self._outputs.get(slot, [])

    def attr(self, name, default=None):
        return self._attrs.get(name, default)


class _EagerCtx(object):
    """Just enough of core.lowering.LowerContext for lowering fns, over a
    plain {name: jax value} env (no Program, no LoD)."""

    def __init__(self, env, key):
        self.env = env
        self._key = key

    def has(self, name):
        return name in self.env

    def get(self, name):
        return self.env[name]

    def in1(self, op, slot, default=None):
        names = op.input(slot)
        return self.env[names[0]] if names else default

    def in_list(self, op, slot):
        return [self.env[n] for n in op.input(slot)]

    def set(self, name, value):
        self.env[name] = value

    def out(self, op, slot, value, idx=0):
        names = op.output(slot)
        if names:
            self.env[names[idx]] = value

    def var(self, name):
        return None

    def rng(self):
        return self._key

    # eager mode is dense-only (LoD/ragged belongs to the Program path)
    def lod_of(self, name):
        return ()

    def in1_lod(self, op, slot):
        return ()

    def set_lod(self, name, lod):
        pass

    # eager mode keeps no NHWC layout twins (every op materializes its
    # public NCHW value immediately); the twin API degrades to transposes
    def has_nhwc(self, op, slot):
        return False

    def in_nhwc(self, op, slot, default=None):
        names = op.input(slot)
        if not names:
            return default
        import jax.numpy as jnp
        return jnp.transpose(self.env[names[0]], (0, 2, 3, 1))

    def out_nhwc(self, op, slot, value_nhwc, idx=0):
        import jax.numpy as jnp
        self.out(op, slot, jnp.transpose(value_nhwc, (0, 3, 1, 2)),
                 idx=idx)

    def in1_static(self, op, slot, default=None):
        names = op.input(slot)
        if not names:
            return default
        return np.asarray(self.env[names[0]])

    def static_value(self, name):
        return np.asarray(self.env[name])

    def set_static(self, name, value):
        pass


def apply_op(op_type, inputs, out_slots, attrs, stop_gradient=False):
    """Execute `op_type` eagerly.

    inputs: {slot: VarBase | [VarBase] | raw array}; out_slots: list of
    output slot names (or (slot, n) for multi-output slots); attrs: dict.
    Returns a list of output VarBases in out_slots order (flattened).
    """
    opdef = get_op(op_type)
    in_slots, in_vars = {}, []
    for slot, val in inputs.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        names = []
        for v in vals:
            if not isinstance(v, VarBase):
                v = to_variable(v)
            names.append('i%d' % len(in_vars))
            in_vars.append(v)
        in_slots[slot] = names

    out_names, out_slot_map = [], {}
    for s in out_slots:
        slot, n = s if isinstance(s, tuple) else (s, 1)
        names = ['o%d' % (len(out_names) + i) for i in range(n)]
        out_names.extend(names)
        out_slot_map[slot] = names

    fake = _FakeOp(op_type, in_slots, out_slot_map, dict(attrs or {}))
    tr = current_tracer()
    key = tr.next_key() if tr is not None else jax.random.PRNGKey(0)
    in_name_list = [n for names in in_slots.values() for n in names]

    def replay(in_vals):
        env = dict(zip(in_name_list, in_vals))
        ctx = _EagerCtx(env, key)
        opdef.lower(ctx, fake)
        return [env.get(n) for n in out_names]

    in_vals = [v._value for v in in_vars]
    out_vals = replay(in_vals)
    out_vars = [VarBase(val, stop_gradient=stop_gradient)
                if val is not None else None for val in out_vals]
    if tr is not None and not stop_gradient:
        tr.record(replay, in_vars, in_vals,
                  [ov for ov in out_vars if ov is not None])
    return out_vars
