"""Layer / PyLayer: the eager module system.

Reference parity: python/paddle/fluid/imperative/layers.py:28 `Layer`
(parameter dict + sublayers + __call__->forward) and `:169` `PyLayer`
(user-supplied numpy forward/backward as a differentiable node). PyLayer's
host computation enters the jax graph via jax.pure_callback, so it stays
differentiable on replay (the TPU analog of the reference's
PyLayer::Apply C++ trampoline, imperative/layer.cc).
"""
import numpy as np
import jax
import jax.numpy as jnp

from .base import VarBase, to_variable, current_tracer
from .. import unique_name

__all__ = ['Layer', 'PyLayer']


class Parameter(VarBase):
    """Trainable leaf (stop_gradient=False by default)."""

    def __init__(self, value, name=None, trainable=True):
        super(Parameter, self).__init__(value, name=name,
                                        stop_gradient=not trainable)


class Layer(object):
    """Base class for eager layers (reference imperative/layers.py:28)."""

    def __init__(self, name_scope=None, dtype='float32'):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self._parameters = {}
        self._sub_layers = {}
        self.training = True

    def full_name(self):
        return self._full_name

    # -- parameter / sublayer registry ------------------------------------
    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         is_bias=False, name=None):
        import zlib
        dtype = dtype or self._dtype
        # deterministic digest (NOT hash(): string hashing is randomized
        # per process, which would make eager init irreproducible and
        # divergent across hosts)
        seed_src = '%s|%s|%d' % (self._full_name, name,
                                 len(self._parameters))
        rng = np.random.RandomState(
            zlib.crc32(seed_src.encode()) % (2 ** 31))
        if default_initializer is not None:
            value = default_initializer(shape, dtype, rng)
        elif is_bias:
            value = np.zeros(shape, dtype)
        else:                      # Xavier-uniform default
            fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
            fan_out = shape[0]
            limit = float(np.sqrt(6.0 / max(fan_in + fan_out, 1)))
            value = rng.uniform(-limit, limit, shape).astype(dtype)
        p = Parameter(value, name=name or unique_name.generate(
            self._full_name + '.w'))
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    def state_dict(self):
        out = {}
        for k, p in self._parameters.items():
            out[self._full_name + '.' + k] = p.numpy()
        for l in self._sub_layers.values():
            out.update(l.state_dict())
        return out

    def set_dict(self, state):
        for k, p in self._parameters.items():
            full = self._full_name + '.' + k
            if full in state:
                p.set_value(state[full])
        for l in self._sub_layers.values():
            l.set_dict(state)

    # -- attribute sugar: self.conv = Conv2D(...) auto-registers ----------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self.__dict__.setdefault('_parameters', {})[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault('_sub_layers', {})[name] = value
        object.__setattr__(self, name, value)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class PyLayer(object):
    """User-defined numpy forward/backward as a differentiable eager node
    (reference imperative/layers.py:169; backward receives the output
    cotangents and returns input cotangents)."""

    @staticmethod
    def forward(*inputs):
        raise NotImplementedError

    @staticmethod
    def backward(*douts):
        raise NotImplementedError

    @classmethod
    def __call__(cls, *inputs):
        return cls.apply(*inputs)

    @classmethod
    def apply(cls, *inputs):
        in_vars = [v if isinstance(v, VarBase) else to_variable(v)
                   for v in inputs]
        in_vals = [v._value for v in in_vars]
        np_ins = [np.asarray(v) for v in in_vals]
        np_outs = cls.forward(*np_ins)
        if not isinstance(np_outs, (list, tuple)):
            np_outs = (np_outs,)
        out_struct = tuple(jax.ShapeDtypeStruct(np.asarray(o).shape,
                                                np.asarray(o).dtype)
                           for o in np_outs)
        in_struct = tuple(jax.ShapeDtypeStruct(np.asarray(i).shape,
                                               np.asarray(i).dtype)
                          for i in np_ins)

        @jax.custom_vjp
        def f(*vals):
            return jax.pure_callback(
                lambda *a: tuple(np.asarray(o) for o in _as_tuple(
                    cls.forward(*[np.asarray(x) for x in a]))),
                out_struct, *vals)

        def f_fwd(*vals):
            return f(*vals), None

        def f_bwd(_, cts):
            return jax.pure_callback(
                lambda *a: tuple(np.asarray(g) for g in _as_tuple(
                    cls.backward(*[np.asarray(x) for x in a]))),
                in_struct, *cts)

        f.defvjp(f_fwd, f_bwd)

        def replay(vals):
            return list(f(*vals))

        out_vars = [VarBase(jnp.asarray(o), stop_gradient=False)
                    for o in np_outs]
        tr = current_tracer()
        if tr is not None:
            tr.record(replay, in_vars, in_vals, out_vars)
        return out_vars if len(out_vars) > 1 else out_vars[0]


def _as_tuple(x):
    return x if isinstance(x, (list, tuple)) else (x,)
