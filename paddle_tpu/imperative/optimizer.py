"""Eager-mode optimizers operating directly on Parameter VarBases.

The reference reuses its graph optimizers under the tracer; here eager
updates are plain jax array math on the parameter leaves (`minimize` =
backward() + apply + clear tape), mirroring the
backward->apply_gradients contract of python/paddle/fluid/optimizer.py:357.
"""
import numpy as np
import jax.numpy as jnp

__all__ = ['SGDOptimizer', 'AdamOptimizer']


class _EagerOptimizer(object):
    def __init__(self, learning_rate):
        self._lr = learning_rate

    def minimize(self, loss, parameter_list=None):
        from .base import current_tracer
        loss.backward()
        params = parameter_list
        if params is None:
            raise ValueError("eager minimize needs parameter_list "
                             "(e.g. model.parameters())")
        for p in params:
            if p._grad is not None:
                self._apply_one(p)
                p.clear_gradient()
        tr = current_tracer()
        if tr is not None:
            tr.clear()

    def _apply_one(self, p):
        raise NotImplementedError


class SGDOptimizer(_EagerOptimizer):
    def _apply_one(self, p):
        p._value = p._value - self._lr * p._grad


class AdamOptimizer(_EagerOptimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super(AdamOptimizer, self).__init__(learning_rate)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._state = {}

    def _apply_one(self, p):
        m, v, t = self._state.get(id(p), (0.0, 0.0, 0))
        t += 1
        g = p._grad
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * g * g
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        p._value = p._value - self._lr * mhat / (jnp.sqrt(vhat) + self._eps)
        self._state[id(p)] = (m, v, t)
