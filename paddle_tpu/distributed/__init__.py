"""Distributed launch tooling (reference python/paddle/distributed/)."""
from . import launch  # noqa: F401
from .launch import launch_procs, init_from_env  # noqa: F401
