"""Multi-process trainer launcher.

Reference parity: python/paddle/distributed/launch.py:40 spawns one trainer
process per device with the PADDLE_* env contract (PADDLE_TRAINER_ID,
PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS).
The TPU-native launcher keeps that env contract and adds the
jax.distributed coordinator address (PADDLE_COORDINATOR) so workers
bootstrap the multi-host runtime with `init_from_env()` — the analog of
the reference's gen_nccl_id gRPC unique-id exchange
(operators/distributed_ops/gen_nccl_id_op.cc:31).

CLI:  python -m paddle_tpu.distributed.launch \
          --nproc_per_node 4 [--node_ip 127.0.0.1] [--log_dir logs] \
          train_script.py [script args...]

Each worker sees:
  PADDLE_TRAINER_ID        global rank
  PADDLE_TRAINERS_NUM      world size
  PADDLE_CURRENT_ENDPOINT  this worker's ip:port
  PADDLE_TRAINER_ENDPOINTS comma list of all endpoints
  PADDLE_COORDINATOR       jax.distributed coordinator 'ip:port'

Failure detection (docs/resilience.md): ``wait_procs`` replaces the bare
wait loop — a worker dying mid-run kills the survivors and raises a
WorkerFailedError NAMING the dead rank within seconds, instead of the
classic "7 of 8 workers hang in the next collective until the job
timeout". Worker-side, ``init_from_env`` bounds the jax.distributed
rendezvous with ``PADDLE_RENDEZVOUS_DEADLINE_S`` (default 300) and raises
an actionable error naming this rank, the coordinator, and the expected
endpoint list when peers never show up.

Elastic mode (docs/resilience.md "Elastic checkpointing"): with
``wait_procs(procs, elastic=True)`` a dead worker does NOT take the
survivors down — the call **returns** the failure (a WorkerFailedError
value naming the dead rank and the ranks still alive) so the driver can
drain the survivors and respawn at a smaller world size instead of
kill-and-restart. ``run_elastic`` is that driver: it relaunches at
``len(survivors)`` workers (down to ``min_nproc``), stamping each
incarnation with ``PADDLE_ELASTIC_RESTART=<n>`` /
``PADDLE_ELASTIC_RESUME=1`` so workers know to
``checkpoint.load_latest_valid(..., reshard=True)``. CLI: ``--elastic``.
"""
import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time

__all__ = ['launch_procs', 'init_from_env', 'wait_procs', 'run_elastic',
           'WorkerFailedError', 'main']


def _free_ports(n, ip='127.0.0.1'):
    """Allocate n distinct free ports: every probe socket stays bound until
    all n are claimed, so two callers in one launch can't be handed the
    same port (the close-then-reprobe race)."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((ip, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _free_port(ip='127.0.0.1'):
    return _free_ports(1, ip)[0]


def launch_procs(entrypoint, entrypoint_args=(), nproc_per_node=1,
                 node_ip='127.0.0.1', node_ips=None, node_id=0,
                 log_dir=None, env_extra=None, devices_per_proc=None):
    """Spawn `nproc_per_node` worker processes with the PADDLE_* env
    contract; returns the list of Popen objects (caller waits).

    Multi-node: pass node_ips (list of node IPs, same launcher run on each
    node with its node_id); endpoints are enumerated for all nodes, but
    only this node's workers are spawned here — exactly the reference
    start_procs contract (launch.py:40).
    """
    node_ips = list(node_ips or [node_ip])
    nnodes = len(node_ips)
    world = nnodes * nproc_per_node
    # Multi-node: every node must compute the SAME endpoint/coordinator
    # addresses, so the fixed port scheme (coordinator 6269, workers
    # 6170+i) is used on all nodes including node 0 — free-port probing is
    # only safe single-node, where no other launcher needs to agree.
    endpoints = []
    if nnodes == 1:
        # all ports drawn from one held-socket batch (probe race: closing
        # a probe then reprobing can hand two workers the same port)
        ports = _free_ports(nproc_per_node + 1, node_ips[0])
        endpoints = ['%s:%d' % (node_ips[0], p) for p in ports[:-1]]
        coordinator = '%s:%d' % (node_ips[0], ports[-1])
    else:
        for ip in node_ips:
            for i in range(nproc_per_node):
                endpoints.append('%s:%d' % (ip, 6170 + i))
        coordinator = '%s:%d' % (node_ips[0], 6269)

    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    # causal tracing across the spawn boundary: when the launcher runs
    # under a trace (run_elastic's incarnation trace, or any caller's),
    # workers inherit its id via env — their trace records carry it as
    # 'parent', so tracereport joins a whole incarnation from rank logs
    from .. import trace as _trace
    _cur = _trace.current()
    procs, logs = [], []
    for i in range(nproc_per_node):
        rank = node_id * nproc_per_node + i
        env = dict(os.environ)
        if _cur is not None:
            env['PADDLE_TRACE_PARENT'] = _cur.trace_id
        env.update(env_extra or {})
        env.update({
            'PADDLE_TRAINER_ID': str(rank),
            'PADDLE_TRAINERS_NUM': str(world),
            'PADDLE_CURRENT_ENDPOINT': endpoints[rank],
            'PADDLE_TRAINER_ENDPOINTS': ','.join(endpoints),
            'PADDLE_COORDINATOR': coordinator,
        })
        # fleet telemetry (docs/observability.md): every worker writes its
        # own rank-suffixed FLAGS_monitor_log file (snapshot lines carry
        # 'rank' too) so `tools/obsreport.py --merge <log>.rank*` can
        # aggregate the fleet; N workers appending one JSON-lines file
        # would interleave torn lines
        mlog = env.get('FLAGS_monitor_log')
        if mlog:
            env['FLAGS_monitor_log'] = '%s.rank%d' % (mlog, rank)
        # ... and serves /metrics on PADDLE_METRICS_PORT+rank (port 0 =
        # every worker picks an ephemeral port; init_from_env starts the
        # endpoint after rendezvous)
        mport = env.get('PADDLE_METRICS_PORT')
        if mport and mport.strip().isdigit() and int(mport) != 0:
            env['PADDLE_METRICS_PORT'] = str(int(mport) + rank)
        # ... and publishes its incident bundles under a rank-suffixed
        # dir, for the same torn-interleaving reason as the monitor log
        # (two ranks sharing one rotation window would evict each other)
        if env.get('PADDLE_BLACKBOX'):
            bdir = env.get('PADDLE_BLACKBOX_DIR', '') or 'blackbox'
            env['PADDLE_BLACKBOX_DIR'] = os.path.join(
                bdir, 'rank%d' % rank)
        if devices_per_proc:
            # virtual-device CPU runs (tests / laptops): give each worker
            # its own device slice
            env['JAX_PLATFORMS'] = 'cpu'
            env['XLA_FLAGS'] = (
                env.get('XLA_FLAGS', '').replace(
                    '--xla_force_host_platform_device_count=8', '').strip()
                + ' --xla_force_host_platform_device_count=%d'
                % devices_per_proc).strip()
        out = None
        if log_dir:
            f = open(os.path.join(log_dir, 'workerlog.%d' % rank), 'w')
            logs.append(f)
            out = f
        cmd = [sys.executable, '-u', entrypoint] + list(entrypoint_args)
        p = subprocess.Popen(cmd, env=env, stdout=out,
                             stderr=subprocess.STDOUT if out else None)
        p.paddle_rank = rank            # wait_procs names ranks from this
        procs.append(p)
    return procs


class WorkerFailedError(RuntimeError):
    """One worker of a multi-process launch died (or the launch deadline
    expired). .rank / .returncode identify the first failure; .running
    lists ranks that were still alive (and were killed) at raise time."""

    def __init__(self, message, rank=None, returncode=None, running=()):
        RuntimeError.__init__(self, message)
        self.rank = rank
        self.returncode = returncode
        self.running = list(running)


def _rank_of(p, i):
    return getattr(p, 'paddle_rank', i)


class CapacityReturned(object):
    """Sentinel ``wait_procs(elastic=True, capacity_fn=)`` returns when
    the capacity probe reports more worker slots than the current world
    size — the ``run_elastic`` cue to drain the (healthy, shrunken)
    fleet and respawn LARGER (grow-back). ``.capacity`` is the probed
    slot count; ``.running`` the ranks alive at probe time."""

    def __init__(self, capacity, running):
        self.capacity = int(capacity)
        self.running = list(running)


def wait_procs(procs, deadline_s=None, poll_s=0.2, kill_survivors=True,
               elastic=False, capacity_fn=None):
    """Wait for every launched worker; FAIL FAST with a rank-naming error.

    - a worker exits nonzero -> the survivors are killed (they would hang
      in their next collective waiting for the dead rank) and
      WorkerFailedError names the dead rank and exit code;
    - `deadline_s` (default env PADDLE_LAUNCH_DEADLINE_S, unset = no
      deadline) elapses -> everything is killed and the error names the
      ranks that were still running.

    Returns the list of exit codes (all zero) on success.

    elastic=True: a dead worker neither kills the survivors nor raises —
    the WorkerFailedError is **returned** (``.rank`` = the dead rank,
    ``.running`` = ranks still alive) so an elastic driver (run_elastic)
    can drain the survivors and respawn at a smaller world size. Only
    the deadline still kills everything and raises: a hung fleet has
    nothing left to shrink around.

    capacity_fn (elastic only): the returned-rank rendezvous — a
    callable polled once per sweep returning the number of worker slots
    currently schedulable (freed machines rejoining, a scheduler quota
    restored). When it exceeds ``len(procs)``, a ``CapacityReturned``
    sentinel is **returned** (the workers stay running — the caller
    decides when to drain and re-expand)."""
    if deadline_s is None:
        env = os.environ.get('PADDLE_LAUNCH_DEADLINE_S', '')
        deadline_s = float(env) if env else None

    def _kill_and_reap(pending, do_kill):
        """Name still-running ranks, then (optionally) kill + reap them —
        a rank that exited within this poll sweep is dead, not 'still
        running', and long-lived callers must not accumulate zombies."""
        running = sorted(_rank_of(q, procs.index(q))
                         for q in pending if q.poll() is None)
        if do_kill:
            for q in pending:
                if q.poll() is None:
                    q.kill()
            for q in pending:
                try:
                    q.wait(timeout=10)
                except Exception:
                    pass
        return running

    t0 = time.monotonic()
    pending = list(procs)
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            pending.remove(p)
            if rc != 0:
                running = _kill_and_reap(
                    pending, kill_survivors and not elastic)
                from .. import blackbox, monitor
                monitor.inc('worker_failure_total')
                blackbox.record(
                    'worker_failed', rank=_rank_of(p, procs.index(p)),
                    returncode=rc, running=running, elastic=elastic)
                if elastic:
                    detail = ("ranks %s left RUNNING for elastic respawn"
                              % running)
                elif not running:
                    detail = "no other workers were running"
                elif kill_survivors:
                    detail = ("killed still-running ranks %s (they would "
                              "have hung at the next collective)" % running)
                else:
                    detail = ("ranks %s are STILL RUNNING "
                              "(kill_survivors=False)" % running)
                err = WorkerFailedError(
                    "worker rank %d exited with code %s; %s"
                    % (_rank_of(p, procs.index(p)), rc, detail),
                    rank=_rank_of(p, procs.index(p)), returncode=rc,
                    running=running)
                if elastic:
                    return err
                raise err
        if pending and elastic and capacity_fn is not None:
            cap = int(capacity_fn())
            if cap > len(procs):
                running = sorted(_rank_of(q, procs.index(q))
                                 for q in pending if q.poll() is None)
                return CapacityReturned(cap, running)
        if pending and deadline_s is not None and \
                time.monotonic() - t0 > deadline_s:
            running = _kill_and_reap(pending, True)
            from .. import monitor
            # its own series: a deadline kill of HEALTHY-but-slow workers
            # is not a worker crash — alerts keyed on worker_failure_total
            # must not fire for it
            monitor.inc('launch_deadline_total')
            raise WorkerFailedError(
                "launch deadline (%.1fs) expired with ranks %s still "
                "running — killed them; inspect their logs for the hang"
                % (deadline_s, running), running=running)
        if pending:
            time.sleep(poll_s)
    return [p.returncode for p in procs]


def _drain(procs, grace_s=10.0):
    """Terminate still-running workers gently (SIGTERM -> grace -> kill)
    and reap them — the elastic driver's pre-respawn drain. A SIGTERM'd
    trainer gets the chance to flush its last checkpoint; a kill-only
    drain would routinely throw away the newest step."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    t0 = time.monotonic()
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, grace_s -
                                   (time.monotonic() - t0)))
            except Exception:
                p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:
            pass


def run_elastic(entrypoint, entrypoint_args=(), nproc_per_node=1,
                min_nproc=1, max_restarts=None, deadline_s=None,
                log_dir=None, env_extra=None, devices_per_proc=None,
                capacity_fn=None, **launch_kw):
    """Elastic launch driver: spawn `nproc_per_node` workers, and when one
    dies, SHRINK instead of dying — drain the survivors (SIGTERM, so they
    can flush a final checkpoint), then respawn the job at
    ``len(survivors)`` workers, repeating down to `min_nproc`. Every
    incarnation after the first sees ``PADDLE_ELASTIC_RESTART=<n>`` (the
    restart ordinal) and ``PADDLE_ELASTIC_RESUME=1`` in its env — the
    worker-side cue to restore the latest valid checkpoint with
    ``reshard=True`` before training (docs/resilience.md).

    GROW-BACK: with ``capacity_fn`` (a callable returning the number of
    schedulable worker slots), a SHRUNKEN fleet is re-expanded when
    capacity returns: the probe is polled while world size is below the
    original ``nproc_per_node``, and when it reports more slots the
    healthy workers are drained (SIGTERM — they publish their final
    checkpoint) and the job respawns at
    ``min(nproc_per_node, capacity)`` with the same resume cue. Grow
    respawns count ``elastic_grow_total`` and do NOT consume
    `max_restarts` — returned capacity is good news, not a failure.

    Returns ``(exit_codes, restarts)`` on success. Raises the final
    WorkerFailedError when the world would shrink below `min_nproc` or
    `max_restarts` (default env PADDLE_ELASTIC_MAX_RESTARTS, else 8) is
    exhausted."""
    if max_restarts is None:
        env = os.environ.get('PADDLE_ELASTIC_MAX_RESTARTS', '')
        max_restarts = int(env) if env else 8
    from .. import monitor
    from .. import trace as trace_mod
    nproc = int(nproc_per_node)
    restarts = 0            # incarnation ordinal (log/bundle subdirs)
    fail_restarts = 0       # only FAILURE respawns consume max_restarts
    # the incarnation trace: one id across every respawn of this job,
    # stamped into each worker's env (PADDLE_TRACE_PARENT) by
    # launch_procs — a post-mortem joins the driver's respawn events
    # with every incarnation's worker-side traces on this one id
    tr = trace_mod.start('incarnation',
                         name=os.path.basename(str(entrypoint)),
                         sampled=True)
    with trace_mod.activate(tr):
        while True:
            extra = dict(env_extra or {})
            if restarts:
                extra['PADDLE_ELASTIC_RESTART'] = str(restarts)
                extra['PADDLE_ELASTIC_RESUME'] = '1'
                # incident bundles survive respawns the same way worker
                # logs do: each incarnation publishes under its own
                # restart_<n>/ subtree, so the FAILED incarnation's
                # bundles (the crash evidence) are never evicted by the
                # new incarnation's keep-last-N rotation
                if extra.get('PADDLE_BLACKBOX',
                             os.environ.get('PADDLE_BLACKBOX')):
                    bdir = extra.get(
                        'PADDLE_BLACKBOX_DIR',
                        os.environ.get('PADDLE_BLACKBOX_DIR', '')) \
                        or 'blackbox'
                    extra['PADDLE_BLACKBOX_DIR'] = os.path.join(
                        bdir, 'restart_%d' % restarts)
            # each incarnation logs into its own subdir: launch_procs opens
            # workerlog.<rank> with mode 'w', and truncating the FAILED
            # incarnation's logs would destroy exactly the crash evidence
            # an operator needs when ranks keep dying
            ld = log_dir if not (log_dir and restarts) else \
                os.path.join(log_dir, 'restart_%d' % restarts)
            procs = launch_procs(
                entrypoint, entrypoint_args, nproc_per_node=nproc,
                log_dir=ld, env_extra=extra,
                devices_per_proc=devices_per_proc, **launch_kw)
            try:
                # probe for returned capacity only while SHRUNKEN — at
                # full size there is nothing to grow back to
                res = wait_procs(
                    procs, deadline_s=deadline_s, elastic=True,
                    capacity_fn=capacity_fn
                    if nproc < int(nproc_per_node) else None)
            except BaseException as e:
                _drain(procs)
                tr.finish('error', error=e, restarts=restarts)
                raise
            if isinstance(res, CapacityReturned):
                # grow-back: drain the healthy shrunken fleet (SIGTERM,
                # so each worker publishes its final checkpoint) and
                # respawn at the returned capacity with the same
                # restore-with-reshard resume cue — the grow direction
                # of the same elastic machinery
                _drain(procs)
                new_n = min(int(nproc_per_node), res.capacity)
                restarts += 1       # a new incarnation (log/bundle dirs)
                monitor.inc('elastic_grow_total')
                monitor.inc('elastic_resume_total')
                tr.event('elastic_grow', restart=restarts,
                         world_size=new_n, capacity=res.capacity,
                         old_world_size=nproc)
                from .. import blackbox
                blackbox.record('elastic_grow', restart=restarts,
                                world_size=new_n, capacity=res.capacity,
                                old_world_size=nproc)
                sys.stderr.write(
                    'paddle_tpu.distributed.launch: capacity returned '
                    '(%d slots); elastic grow-back #%d to world size %d\n'
                    % (res.capacity, restarts, new_n))
                nproc = new_n
                continue
            if not isinstance(res, WorkerFailedError):
                tr.finish('ok', restarts=restarts, world_size=nproc)
                return res, restarts
            _drain(procs)
            survivors = len(res.running)
            restarts += 1
            fail_restarts += 1
            if survivors < int(min_nproc) or \
                    fail_restarts > int(max_restarts):
                monitor.inc('elastic_giveup_total')
                tr.event('elastic_giveup', restarts=restarts,
                         dead_rank=res.rank, world_size=survivors,
                         min_nproc=int(min_nproc))
                err = WorkerFailedError(
                    "elastic launch giving up after %d restart(s): %s "
                    "(next world size %d < min_nproc %d or max_restarts "
                    "%d exhausted)" % (restarts, res, survivors,
                                       min_nproc, max_restarts),
                    rank=res.rank, returncode=res.returncode,
                    running=res.running)
                tr.finish('error', error=err, restarts=restarts)
                raise err
            monitor.inc('elastic_resume_total')
            tr.event('elastic_respawn', restart=restarts,
                     dead_rank=res.rank, returncode=res.returncode,
                     world_size=survivors)
            sys.stderr.write(
                'paddle_tpu.distributed.launch: rank %s died; elastic '
                'respawn #%d at world size %d\n'
                % (res.rank, restarts, survivors))
            nproc = survivors


def init_from_env(rendezvous_deadline_s=None):
    """Worker-side bootstrap: read the launcher's env contract and
    initialize jax.distributed; returns (rank, world_size). No-op (0, 1)
    when not launched by the launcher.

    The rendezvous is bounded by `rendezvous_deadline_s` (default env
    PADDLE_RENDEZVOUS_DEADLINE_S, 300 s): when peers never connect —
    a worker crashed before rendezvous, a typo'd coordinator — this
    raises an error naming this rank, the coordinator, and the expected
    endpoints instead of hanging until the cluster scheduler's timeout.
    Transient connect errors retry under the 'collective' site policy."""
    world = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    coordinator = os.environ.get('PADDLE_COORDINATOR')
    if world > 1 and coordinator:
        if rendezvous_deadline_s is None:
            env = os.environ.get('PADDLE_RENDEZVOUS_DEADLINE_S', '')
            if env:
                rendezvous_deadline_s = float(env)
            else:
                from .. import flags as _flags
                rendezvous_deadline_s = _flags.get_flags(
                    'rendezvous_deadline_secs') or 300.0
        from ..parallel import collective
        from .. import resilience

        done = threading.Event()
        cancelled = threading.Event()
        errs = []
        outcome = []                    # ['ok'] | ['cancelled']

        def _connect():
            try:
                resilience.retry_call(
                    lambda: collective.init_distributed(
                        coordinator_address=coordinator,
                        num_processes=world, process_id=rank),
                    site='collective')
                if cancelled.is_set():
                    # the caller already raised the deadline error: a
                    # late success must not leave live jax.distributed
                    # global state behind (a re-init attempt would die on
                    # 'initialize should only be called once')
                    import jax
                    try:
                        jax.distributed.shutdown()
                    except Exception:
                        pass
                    outcome.append('cancelled')
                else:
                    outcome.append('ok')
            except Exception as e:      # noqa: BLE001 — re-raised below
                errs.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_connect, daemon=True)
        t.start()
        if not done.wait(rendezvous_deadline_s):
            cancelled.set()
            # close the success/timeout race: the thread may have
            # finished init between our wait timing out and cancelled
            # being set (in which case it skipped the shutdown) — give
            # it a beat and honor a clean 'ok' as success
            if done.wait(1.0):
                if outcome == ['ok'] and not errs:
                    return rank, world
                if errs:
                    # the thread failed for a REAL reason in the grace
                    # window — surface it, not a misleading generic
                    # "peer never connected"
                    raise errs[0]
            from .. import monitor
            monitor.inc('rendezvous_timeout_total')
            raise RuntimeError(
                "rank %d: jax.distributed rendezvous at %s did not "
                "complete within %.1fs — of the %d expected workers "
                "(endpoints %s) at least one never connected. Check the "
                "launcher logs for a dead rank (wait_procs names it), "
                "then restart the job."
                % (rank, coordinator, rendezvous_deadline_s, world,
                   os.environ.get('PADDLE_TRAINER_ENDPOINTS', '?')))
        if errs:
            raise errs[0]
    _maybe_serve_metrics()
    return rank, world


_metrics_server = [None]


def _maybe_serve_metrics():
    """Start this worker's /metrics endpoint when the launcher's env
    contract asks for one (PADDLE_METRICS_PORT, already offset per rank
    by launch_procs). Idempotent across repeated init_from_env calls; a
    bind failure warns instead of killing the worker — telemetry must
    never take the job down."""
    if _metrics_server[0] is not None:
        return _metrics_server[0]
    port = os.environ.get('PADDLE_METRICS_PORT', '')
    if port == '':
        return None
    from .. import monitor
    try:
        _metrics_server[0] = monitor.serve_metrics(int(port))
    except Exception as e:              # noqa: BLE001 — telemetry only
        import warnings
        warnings.warn(
            "rank %s: could not serve /metrics on PADDLE_METRICS_PORT=%s "
            "(%s); continuing without the endpoint"
            % (os.environ.get('PADDLE_TRAINER_ID', '?'), port, e),
            stacklevel=2)
    return _metrics_server[0]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='paddle_tpu multi-process launcher')
    ap.add_argument('--nproc_per_node', type=int, default=1)
    ap.add_argument('--node_ip', default='127.0.0.1')
    ap.add_argument('--node_ips', default='',
                    help='comma list of all node IPs (multi-node)')
    ap.add_argument('--node_id', type=int, default=0)
    ap.add_argument('--log_dir', default=None)
    ap.add_argument('--devices_per_proc', type=int, default=0,
                    help='virtual CPU devices per worker (testing)')
    ap.add_argument('--elastic', action='store_true',
                    help='on worker death, respawn at a smaller world '
                         'size instead of failing (run_elastic)')
    ap.add_argument('--min_nproc', type=int, default=1,
                    help='elastic mode: smallest world size to shrink to')
    ap.add_argument('entrypoint')
    ap.add_argument('entrypoint_args', nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.elastic:
        # elastic respawn relaunches the whole node group at the new
        # world size — single-node only (multi-node membership needs an
        # external coordinator to agree on the surviving node set). One
        # --node_ips entry IS single-node: treat it as the node_ip.
        nips = [s for s in args.node_ips.split(',') if s]
        if len(nips) > 1:
            ap.error('--elastic supports single-node launches only')
        try:
            _, restarts = run_elastic(
                args.entrypoint, args.entrypoint_args,
                nproc_per_node=args.nproc_per_node,
                min_nproc=args.min_nproc, log_dir=args.log_dir,
                node_ip=nips[0] if nips else args.node_ip,
                devices_per_proc=args.devices_per_proc or None)
        except WorkerFailedError as e:
            sys.stderr.write('paddle_tpu.distributed.launch: %s\n' % e)
            sys.exit(1)
        if restarts:
            sys.stderr.write('paddle_tpu.distributed.launch: finished '
                             'after %d elastic respawn(s)\n' % restarts)
        sys.exit(0)
    procs = launch_procs(
        args.entrypoint, args.entrypoint_args,
        nproc_per_node=args.nproc_per_node, node_ip=args.node_ip,
        node_ips=[s for s in args.node_ips.split(',') if s] or None,
        node_id=args.node_id, log_dir=args.log_dir,
        devices_per_proc=args.devices_per_proc or None)
    try:
        wait_procs(procs)
    except WorkerFailedError as e:
        sys.stderr.write('paddle_tpu.distributed.launch: %s\n' % e)
        sys.exit(1)
    sys.exit(0)


if __name__ == '__main__':
    main()
