"""Multi-process trainer launcher.

Reference parity: python/paddle/distributed/launch.py:40 spawns one trainer
process per device with the PADDLE_* env contract (PADDLE_TRAINER_ID,
PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS).
The TPU-native launcher keeps that env contract and adds the
jax.distributed coordinator address (PADDLE_COORDINATOR) so workers
bootstrap the multi-host runtime with `init_from_env()` — the analog of
the reference's gen_nccl_id gRPC unique-id exchange
(operators/distributed_ops/gen_nccl_id_op.cc:31).

CLI:  python -m paddle_tpu.distributed.launch \
          --nproc_per_node 4 [--node_ip 127.0.0.1] [--log_dir logs] \
          train_script.py [script args...]

Each worker sees:
  PADDLE_TRAINER_ID        global rank
  PADDLE_TRAINERS_NUM      world size
  PADDLE_CURRENT_ENDPOINT  this worker's ip:port
  PADDLE_TRAINER_ENDPOINTS comma list of all endpoints
  PADDLE_COORDINATOR       jax.distributed coordinator 'ip:port'
"""
import argparse
import os
import socket
import subprocess
import sys

__all__ = ['launch_procs', 'init_from_env', 'main']


def _free_ports(n, ip='127.0.0.1'):
    """Allocate n distinct free ports: every probe socket stays bound until
    all n are claimed, so two callers in one launch can't be handed the
    same port (the close-then-reprobe race)."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((ip, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _free_port(ip='127.0.0.1'):
    return _free_ports(1, ip)[0]


def launch_procs(entrypoint, entrypoint_args=(), nproc_per_node=1,
                 node_ip='127.0.0.1', node_ips=None, node_id=0,
                 log_dir=None, env_extra=None, devices_per_proc=None):
    """Spawn `nproc_per_node` worker processes with the PADDLE_* env
    contract; returns the list of Popen objects (caller waits).

    Multi-node: pass node_ips (list of node IPs, same launcher run on each
    node with its node_id); endpoints are enumerated for all nodes, but
    only this node's workers are spawned here — exactly the reference
    start_procs contract (launch.py:40).
    """
    node_ips = list(node_ips or [node_ip])
    nnodes = len(node_ips)
    world = nnodes * nproc_per_node
    # Multi-node: every node must compute the SAME endpoint/coordinator
    # addresses, so the fixed port scheme (coordinator 6269, workers
    # 6170+i) is used on all nodes including node 0 — free-port probing is
    # only safe single-node, where no other launcher needs to agree.
    endpoints = []
    if nnodes == 1:
        # all ports drawn from one held-socket batch (probe race: closing
        # a probe then reprobing can hand two workers the same port)
        ports = _free_ports(nproc_per_node + 1, node_ips[0])
        endpoints = ['%s:%d' % (node_ips[0], p) for p in ports[:-1]]
        coordinator = '%s:%d' % (node_ips[0], ports[-1])
    else:
        for ip in node_ips:
            for i in range(nproc_per_node):
                endpoints.append('%s:%d' % (ip, 6170 + i))
        coordinator = '%s:%d' % (node_ips[0], 6269)

    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    procs, logs = [], []
    for i in range(nproc_per_node):
        rank = node_id * nproc_per_node + i
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            'PADDLE_TRAINER_ID': str(rank),
            'PADDLE_TRAINERS_NUM': str(world),
            'PADDLE_CURRENT_ENDPOINT': endpoints[rank],
            'PADDLE_TRAINER_ENDPOINTS': ','.join(endpoints),
            'PADDLE_COORDINATOR': coordinator,
        })
        if devices_per_proc:
            # virtual-device CPU runs (tests / laptops): give each worker
            # its own device slice
            env['JAX_PLATFORMS'] = 'cpu'
            env['XLA_FLAGS'] = (
                env.get('XLA_FLAGS', '').replace(
                    '--xla_force_host_platform_device_count=8', '').strip()
                + ' --xla_force_host_platform_device_count=%d'
                % devices_per_proc).strip()
        out = None
        if log_dir:
            f = open(os.path.join(log_dir, 'workerlog.%d' % rank), 'w')
            logs.append(f)
            out = f
        cmd = [sys.executable, '-u', entrypoint] + list(entrypoint_args)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT
                                      if out else None))
    return procs


def init_from_env():
    """Worker-side bootstrap: read the launcher's env contract and
    initialize jax.distributed; returns (rank, world_size). No-op (0, 1)
    when not launched by the launcher."""
    world = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    coordinator = os.environ.get('PADDLE_COORDINATOR')
    if world > 1 and coordinator:
        from ..parallel import collective
        collective.init_distributed(coordinator_address=coordinator,
                                    num_processes=world, process_id=rank)
    return rank, world


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='paddle_tpu multi-process launcher')
    ap.add_argument('--nproc_per_node', type=int, default=1)
    ap.add_argument('--node_ip', default='127.0.0.1')
    ap.add_argument('--node_ips', default='',
                    help='comma list of all node IPs (multi-node)')
    ap.add_argument('--node_id', type=int, default=0)
    ap.add_argument('--log_dir', default=None)
    ap.add_argument('--devices_per_proc', type=int, default=0,
                    help='virtual CPU devices per worker (testing)')
    ap.add_argument('entrypoint')
    ap.add_argument('entrypoint_args', nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    procs = launch_procs(
        args.entrypoint, args.entrypoint_args,
        nproc_per_node=args.nproc_per_node, node_ip=args.node_ip,
        node_ips=[s for s in args.node_ips.split(',') if s] or None,
        node_id=args.node_id, log_dir=args.log_dir,
        devices_per_proc=args.devices_per_proc or None)
    rc = 0
    for p in procs:
        rc |= p.wait()
    sys.exit(rc)


if __name__ == '__main__':
    main()
