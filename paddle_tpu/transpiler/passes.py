"""Program-rewrite pass framework.

Reference parity: framework/ir/pass.h:32,144 (Pass + PassRegistry) and
graph_pattern_detector.h — scoped down to what a trace-to-XLA design
actually needs (SURVEY §2.2: XLA owns fusion; program-level rewrites cover
semantic cleanups). Passes mutate the Program in place and bump its
version so compile caches invalidate.

Built-ins match the reference inference-analysis cleanups the round-2
review called out (framework/ir/is_test_pass.cc,
identity_scale_op_clean_pass.cc) plus the conv+BN fold, and the
PatternMatcher gives transpilers a declarative way to find op chains
(single-consumer var links), replacing ad-hoc index walking.
"""
import numpy as np

__all__ = ['Pass', 'PassRegistry', 'PatternMatcher', 'register_pass',
           'get_pass', 'apply_passes']


class Pass(object):
    """Base pass: subclass and implement apply_impl (reference
    ir/pass.h:32)."""
    name = None

    def apply(self, program, scope=None):
        self.apply_impl(program, scope)
        program._bump_version()
        return program

    def apply_impl(self, program, scope):
        raise NotImplementedError


class PassRegistry(object):
    _passes = {}

    @classmethod
    def register(cls, name, pass_cls):
        if name in cls._passes:
            raise KeyError("pass %r already registered" % name)
        cls._passes[name] = pass_cls

    @classmethod
    def get(cls, name):
        if name not in cls._passes:
            raise KeyError("no pass named %r (have: %s)"
                           % (name, sorted(cls._passes)))
        return cls._passes[name]()

    @classmethod
    def names(cls):
        return sorted(cls._passes)


def register_pass(name):
    def deco(pass_cls):
        pass_cls.name = name
        PassRegistry.register(name, pass_cls)
        return pass_cls
    return deco


def get_pass(name):
    return PassRegistry.get(name)


def apply_passes(program, names, scope=None):
    for n in names:
        get_pass(n).apply(program, scope)
    return program


class PatternMatcher(object):
    """Match chains of op types linked by single-consumer vars (the
    program-level core of reference graph_pattern_detector.h).

    match(block, ['conv2d', 'batch_norm']) yields lists of op objects
    [conv, bn] where conv's first output is consumed ONLY by bn.
    """

    def __init__(self, block):
        self.block = block

    def _consumers(self, var_name):
        return [o for o in self.block.ops if var_name in o.input_arg_names]

    def match(self, types):
        out = []
        for op in list(self.block.ops):
            if op.type != types[0]:
                continue
            chain = [op]
            ok = True
            for want in types[1:]:
                outs = chain[-1].output_arg_names
                if len(outs) < 1:
                    ok = False
                    break
                # follow the op's primary output
                nxt = None
                for name in outs:
                    cons = self._consumers(name)
                    if len(cons) == 1 and cons[0].type == want:
                        nxt = cons[0]
                        break
                if nxt is None:
                    ok = False
                    break
                chain.append(nxt)
            if ok:
                out.append(chain)
        return out


@register_pass('is_test_pass')
class IsTestPass(Pass):
    """reference framework/ir/is_test_pass.cc: flip every op carrying an
    is_test attr to inference mode."""

    def apply_impl(self, program, scope):
        for block in program.blocks:
            for op in block.ops:
                if op.attr('is_test', None) is not None or op.type in (
                        'dropout', 'batch_norm', 'lrn', 'pool2d',
                        'fake_quantize_range_abs_max'):
                    op.set_attr('is_test', True)


@register_pass('identity_scale_op_clean_pass')
class IdentityScaleCleanPass(Pass):
    """reference framework/ir/identity_scale_op_clean_pass.cc: remove
    scale(x, scale=1, bias=0) ops, rewiring consumers to the input."""

    def apply_impl(self, program, scope):
        # The reference pass rewires the PRODUCER of X to emit the scale's
        # Out name, so Out (the name users fetch after transpile) survives;
        # a scale whose input has no in-block producer (feed/parameter) is
        # left alone because there is nothing to rewire.
        for block in program.blocks:
            changed = True
            while changed:
                changed = False
                for i, op in enumerate(block.ops):
                    is_identity = (
                        op.type == 'scale'
                        and float(op.attr('scale', 1.0)) == 1.0
                        and float(op.attr('bias', 0.0)) == 0.0
                        and op.input('X') and op.output('Out'))
                    if not is_identity:
                        continue
                    src = op.input('X')[0]
                    dst = op.output('Out')[0]
                    # rewiring is only sound when src has exactly ONE writer
                    # in the whole program (non-SSA programs may overwrite
                    # src later; renaming every reader would then alias
                    # readers of the later write onto the stale dst value)
                    # — and when dst has no OTHER writer either (rewiring
                    # the producer to emit dst must not clobber or be
                    # clobbered by an unrelated write of dst)
                    writers = [o for blk in program.blocks for o in blk.ops
                               if o is not op and src in o.output_arg_names]
                    if len(writers) != 1 or writers[0] not in block.ops[:i]:
                        continue
                    dst_writers = [o for blk in program.blocks
                                   for o in blk.ops
                                   if o is not op
                                   and dst in o.output_arg_names]
                    if dst_writers:
                        continue
                    producer = writers[0]
                    producer._rename_output(src, dst)
                    # src no longer exists after the rewire: rename readers
                    # in EVERY block (sub-blocks of while/cond read parent
                    # vars by name)
                    for blk in program.blocks:
                        for other in blk.ops:
                            if other is not op:
                                other._rename_input(src, dst)
                    block.ops = block.ops[:i] + block.ops[i + 1:]
                    changed = True
                    break


@register_pass('conv_bn_fuse_pass')
class ConvBNFusePass(Pass):
    """Constant-fold inference batch_norm into the preceding conv2d's
    weights (reference framework/ir/conv_bn_fuse_pass.cc semantics via the
    InferenceTranspiler implementation)."""

    def apply_impl(self, program, scope):
        from .inference_transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(program, scope=scope)
