"""DistributeTranspiler: distributed-training program planning.

Reference python/paddle/fluid/transpiler/distribute_transpiler.py:161,280 —
there, the transpiler rewrites the program into trainer/pserver halves with
send/recv/barrier ops over gRPC. On TPU there are no parameter servers: the
two reference modes map to SPMD plans (SURVEY §2.7):

- pserver mode  -> sharded-parameter SPMD: each "pserver shard" becomes a
  slice of the parameter along mesh axis 'model' (round-robin/size-balanced,
  mirroring slice_var_up/min_block_size), updated in place by the same
  compiled step; the gather/scatter the pserver RPC performed becomes XLA
  all_gather/reduce_scatter over ICI.
- nccl2 mode    -> plain data-parallel SPMD over all trainers
  (jax.distributed handles the multi-host bootstrap that gen_nccl_id did).

The transpile() API is kept; the result is a ShardingPlan (mesh axes + rules)
consumable by parallel.MeshRunner, plus trainer/pserver program getters that
return the SAME program (SPMD is single-program) with the plan attached.
"""
import numpy as np
from jax.sharding import PartitionSpec as P

from ..framework import default_main_program, Parameter
from ..parallel.api import ShardingRules

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig',
           'PSServerState']


class DistributeTranspilerConfig(object):
    """Reference distribute_transpiler.py:130: slice_var_up, split_method,
    min_block_size (+ mode)."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    mode = "pserver"
    print_log = False


class ShardingPlan(object):
    def __init__(self, rules, feed_axis='data', num_shards=1):
        self.rules = rules
        self.feed_axis = feed_axis
        self.num_shards = num_shards

    def mesh_axes(self, num_devices):
        if self.num_shards <= 1:
            return [('data', num_devices)]
        model = int(np.gcd(self.num_shards, num_devices))
        return [('data', num_devices // model), ('model', model)]


class PSServerState(object):
    """One pserver endpoint's runnable startup state (mode='pserver'):
    the shard's tables plus a `serve()` that binds the transport."""

    def __init__(self, endpoint, shard_id, num_shards, tables):
        self.endpoint = endpoint
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.tables = tables

    def serve(self, host=None, port=None):
        """Start a ps.PSServer on this state's endpoint (or an explicit
        host/port — port=0 picks an ephemeral one)."""
        from ..ps.transport import PSServer
        if host is None or port is None:
            h, _, p = self.endpoint.rpartition(':')
            host = host if host is not None else (h or '127.0.0.1')
            port = port if port is not None else int(p)
        return PSServer(self.tables, host=host, port=port)

    def __repr__(self):
        return "PSServerState(%s, shard %d/%d, tables=%s)" % (
            self.endpoint, self.shard_id, self.num_shards,
            sorted(self.tables))


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._plan = None
        self._program = None
        self._ps_info = None

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174", mode=None):
        """mode=None (default): the in-process SPMD planning below —
        byte-for-byte the pre-PS behavior. mode='pserver': the HOST
        parameter-server subsystem (paddle_tpu/ps) — the program is
        rewritten so is_distributed embedding tables are PS-remote
        (ps_lookup_table + rows feeds + server-side optimizer), one
        pserver shard per endpoint; get_pserver_programs(endpoint) then
        returns that endpoint's runnable startup state."""
        if program is None:
            program = default_main_program()
        self._program = program
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.sync_mode = sync_mode
        if isinstance(pservers, str):
            eplist = [e for e in pservers.split(",") if e]
        else:
            eplist = list(pservers)
        self.pserver_endpoints = eplist
        self._ps_info = None

        if mode == 'pserver':
            from ..ps.program import convert_to_ps_program
            if not eplist:
                raise ValueError(
                    "transpile(mode='pserver') needs at least one pserver "
                    "endpoint (pservers='host:port,...')")
            self._ps_info = convert_to_ps_program(
                program, startup_program=startup_program)
            self._startup = startup_program
            self._plan = ShardingPlan(ShardingRules([]), num_shards=1)
            return
        if mode not in (None, 'mesh'):
            raise ValueError("transpile: unknown mode %r "
                             "(None/'mesh' = SPMD plan, 'pserver' = host "
                             "parameter server)" % (mode,))

        if self.config.mode == "nccl2" or not eplist:
            # pure data parallel; params replicated
            self._plan = ShardingPlan(ShardingRules([]), num_shards=1)
            return

        # pserver mode: shard large parameters along their largest dim over
        # the 'model' axis — one rule per parameter above min_block_size.
        # lookup_table(is_distributed=True) tables ALWAYS shard on dim 0
        # (vocab), whatever their size: that is the distributed-lookup-table
        # path (reference distribute_transpiler.py:161 special-cases these
        # into a prefetch pipeline; here the rule + the lowering's sharding
        # constraint make XLA emit the id-exchange collectives).
        dist_tables = set()
        for block in program.blocks:
            for dop in block.ops:
                if dop.type in ('lookup_table', 'lookup_sparse_table') and \
                        dop.attr('is_distributed', False):
                    dist_tables.add(dop.input('W')[0])
        rules = []

        def _shard_with_accumulators(p, axis):
            """One rule for the parameter plus one per optimizer
            accumulator (named '<param>_<slot>...', optimizer.py:92) whose
            shape matches the parameter's — moments must shard WITH their
            parameter or every device re-materializes the full [V, d]
            state the sharding exists to avoid. Shape-matched only:
            beta-pow style scalar accumulators stay replicated."""
            spec = [None] * len(p.shape)
            spec[axis] = 'model'
            rules.append((r'^%s$' % _re_escape(p.name), P(*spec)))
            for v in program.list_vars():
                if v.name.startswith(p.name + '_') and v.shape is not None \
                        and tuple(v.shape) == tuple(p.shape) \
                        and not isinstance(v, Parameter):
                    rules.append((r'^%s$' % _re_escape(v.name), P(*spec)))

        for p in program.all_parameters():
            if not isinstance(p, Parameter) or p.shape is None:
                continue
            if p.name in dist_tables:
                _shard_with_accumulators(p, 0)
                continue
            size = int(np.prod(p.shape))
            if self.config.slice_var_up and \
                    size >= self.config.min_block_size and len(eplist) > 1:
                _shard_with_accumulators(p, int(np.argmax(p.shape)))
        self._plan = ShardingPlan(ShardingRules(rules),
                                  num_shards=len(eplist))

    # ------------------------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        """SPMD: the trainer program IS the original program; the plan rides
        along for MeshRunner (reference returned a rewritten program with
        send/recv ops)."""
        self._program._sharding_plan = self._plan
        return self._program

    def get_pserver_program(self, endpoint):
        """mode='pserver': this endpoint's runnable startup state — a
        `PSServerState` whose `.tables` are the endpoint's shard of every
        PS table and whose `.serve()` binds a live `ps.PSServer`.
        Default (mesh) mode keeps the API-parity error: no pserver
        process exists in SPMD training."""
        if self._ps_info is not None:
            if endpoint not in self.pserver_endpoints:
                raise ValueError(
                    "get_pserver_program: %r is not one of the transpiled "
                    "endpoints %s" % (endpoint, self.pserver_endpoints))
            from ..ps.program import build_pserver_tables
            shard_id = self.pserver_endpoints.index(endpoint)
            return PSServerState(
                endpoint, shard_id, len(self.pserver_endpoints),
                build_pserver_tables(self._ps_info,
                                     len(self.pserver_endpoints),
                                     shard_id))
        raise NotImplementedError(
            "TPU-native training has no parameter-server role: parameters "
            "are sharded over the mesh ('model' axis) inside one SPMD "
            "program. Run get_trainer_program() on every host; "
            "jax.distributed.initialize() replaces the pserver bootstrap. "
            "For a HOST parameter server (tables beyond device memory), "
            "transpile(..., mode='pserver').")

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    @property
    def ps_info(self):
        """The PSProgramInfo of a mode='pserver' transpile (None in the
        default mesh mode)."""
        return self._ps_info

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from ..framework import default_startup_program
        if startup_program is not None:
            return startup_program
        if self._ps_info is not None and \
                getattr(self, '_startup', None) is not None:
            # mode='pserver': the startup that transpile stripped the
            # table/accumulator inits from
            return self._startup
        return default_startup_program()

    @property
    def sharding_plan(self):
        return self._plan


def _re_escape(s):
    import re
    return re.escape(s)
